# Developer/CI gates. `make check` is the PR gate: the JAX-pitfall lint
# must be clean over the package source, then the tier-1 test command
# (ROADMAP.md) must pass.

PY ?= python
TIER1 = set -o pipefail; rm -f /tmp/_t1.log; \
	timeout -k 10 870 env JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q \
	-m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
	-p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; \
	rc=$${PIPESTATUS[0]}; \
	echo DOTS_PASSED=$$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$$' /tmp/_t1.log | tr -cd . | wc -c); \
	exit $$rc

.PHONY: lint serve-smoke test check

lint:
	$(PY) -m transmogrifai_tpu.lint transmogrifai_tpu/

# end-to-end serving smoke: train tiny -> save -> boot HTTP server on a
# random port -> POST /score -> scrape /metrics (+ /healthz, /reload
# no-op) -> clean shutdown. See transmogrifai_tpu/serving/smoke.py.
serve-smoke:
	env JAX_PLATFORMS=cpu $(PY) -m transmogrifai_tpu.serving.smoke

test:
	bash -c "$(TIER1)"

check: lint serve-smoke test
