# Developer/CI gates. `make check` is the PR gate: the JAX-pitfall lint
# must be clean over the package source, then the tier-1 test command
# (ROADMAP.md) must pass.

PY ?= python
# bash, not /bin/sh: TIER1 uses PIPESTATUS, and with a dash /bin/sh the
# old `bash -c "$(TIER1)"` indirection broke — the OUTER shell expanded
# ${PIPESTATUS[0]} inside the double quotes ("Bad substitution")
SHELL := /bin/bash
TIER1 = set -o pipefail; rm -f /tmp/_t1.log; \
	timeout -k 10 870 env JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q \
	-m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
	-p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; \
	rc=$${PIPESTATUS[0]}; \
	echo DOTS_PASSED=$$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$$' /tmp/_t1.log | tr -cd . | wc -c); \
	exit $$rc

.PHONY: lint conc-check serve-smoke fleet-smoke chaos-smoke \
	ingest-smoke faults-smoke trace-smoke cache-smoke multichip-smoke \
	continual-smoke costmodel-smoke roofline-smoke slo-smoke \
	parse-smoke router-smoke pod-smoke autopilot-smoke fleetobs-smoke \
	test check

lint:
	$(PY) -m transmogrifai_tpu.lint transmogrifai_tpu/

# whole-program concurrency audit (C001-C004): lock discipline,
# lock-order cycles, blocking-under-lock, generation-fence re-checks.
# Fails on any finding not in the reviewed baseline; prints the
# lock-order graph so ordering regressions are visible in CI logs.
conc-check:
	$(PY) -m transmogrifai_tpu.analysis.concurrency transmogrifai_tpu/ \
		--baseline conc_baseline.json --graph

# fault-tolerance smoke: kill a ModelSelector sweep mid-grid with an
# injected fault, resume it from the block journal, and assert the best
# config + every fold metric are bit-identical to an uninterrupted run;
# also kills a save_model mid-write and asserts the resident artifact
# survives intact. See transmogrifai_tpu/runtime/smoke.py.
faults-smoke:
	env JAX_PLATFORMS=cpu $(PY) -m transmogrifai_tpu.runtime.smoke

# feature-cache smoke: cold dual build writes the content-addressed
# wire artifact, a rebuild HITS it (zero store reads, bit-identical
# buffers), a corrupted artifact is rejected and falls back to a
# rebuild, and the int8 quantized wire stays within tolerance at 2x
# compression. See transmogrifai_tpu/data/feature_cache.py.
cache-smoke:
	env JAX_PLATFORMS=cpu $(PY) -m transmogrifai_tpu.data.feature_cache

# out-of-core ingest smoke: small synthetic ColumnarStore through the
# pipelined one-pass dual-representation build (data/pipeline.py) —
# asserts serial-parity results and that overlap metrics are emitted.
ingest-smoke:
	env JAX_PLATFORMS=cpu $(PY) -c "from transmogrifai_tpu.data.pipeline \
	import _smoke; raise SystemExit(_smoke())"

# end-to-end serving smoke: train tiny -> save -> boot HTTP server on a
# random port -> POST /score -> scrape /metrics (+ /healthz, /reload
# no-op) -> clean shutdown. See transmogrifai_tpu/serving/smoke.py.
serve-smoke:
	env JAX_PLATFORMS=cpu $(PY) -m transmogrifai_tpu.serving.smoke

# fleet-serving smoke: three models (two same-shaped, one different)
# across two tenants in ONE process — the same-shaped pair shares
# compiled bucket programs (zero new traces, RetraceMonitor-asserted),
# the over-quota tenant collects the only 429s under mixed HTTP load,
# a rolling swap of one model drops zero in-flight requests for the
# others, and cold-start-to-first-score is measured without and with
# the persistent compile cache + warmup manifest. See
# transmogrifai_tpu/serving/fleet_smoke.py.
fleet-smoke:
	env JAX_PLATFORMS=cpu $(PY) -m transmogrifai_tpu.serving.fleet_smoke

# roofline-scoring smoke: a warm service executes exactly ONE device
# dispatch per bucket per score call (whole-pipeline fusion,
# DISPATCHES-asserted), int8 scoring agrees with f32 within the stated
# wire tolerance and never adopts the f32 programs, two same-shaped
# linear tenants share one compiled program set (zero traces on the
# second, bit-identical vs solo), and scoring_hbm_frac is present and
# nonzero. See transmogrifai_tpu/serving/roofline_smoke.py.
roofline-smoke:
	env JAX_PLATFORMS=cpu $(PY) -m transmogrifai_tpu.serving.roofline_smoke

# serving-resilience chaos smoke: a seeded device-error storm trips one
# fleet member's breaker (HEALTHY->QUARANTINED->HEALTHY with measured
# MTTR) while degraded fallback serves from the resident previous
# version and the untouched members' traffic sees zero errors with
# bounded p99; a killed scoring thread and a stalled dispatch are both
# watchdog-recovered with every in-flight request answered (never a
# hang); a corrupt reload is rejected under concurrent traffic. See
# transmogrifai_tpu/serving/chaos.py.
chaos-smoke:
	env JAX_PLATFORMS=cpu $(PY) -m transmogrifai_tpu.serving.chaos

# serving-autopilot smoke: the same seeded overload storm (delayed
# member + low-priority flood, gold deadline tighter than the degraded
# queue drain) is driven at a static-config fleet and an autopilot
# fleet; the static arm's gold availability collapses while the
# controller climbs the actuation ladder (rebucket re-arm -> fidelity
# flip to the resident int8 member -> predictive admission -> warm
# spare), damps gold p99 below the static arm, makes ZERO actuations
# in the healthy phase, releases every actuation after the storm, and
# every actuation event embeds the burn window that justified it. See
# transmogrifai_tpu/serving/chaos.py (run_storm / storm_main).
autopilot-smoke:
	env JAX_PLATFORMS=cpu $(PY) -m transmogrifai_tpu.serving.chaos --storm

# distributed-sweep smoke: on 8 forced host devices, a 2-family grid
# sweep scheduled across the mesh must return the bit-identical winner
# to the single-device sweep; an injected kill of one worker preempts
# the schedule and the resume re-runs ONLY that worker's in-flight
# block (journal-shard-asserted; with blocks <= lanes every other block
# was dispatched and drains to its journal); a worker-level error is
# survived by work stealing. See transmogrifai_tpu/parallel/smoke.py.
multichip-smoke:
	$(PY) -m transmogrifai_tpu.parallel.smoke

# pod-scale sweep smoke: 2 real host scheduler PROCESSES (fresh
# interpreters, forced host meshes) claim-race one sweep's blocks
# through the shared store/ lease table; every host must report the
# bit-identical winner vs a single-host run (rows merged from the
# host-qualified journal shards); a host killed holding a block lease
# is TTL-reclaimed by a survivor process that finishes with exactly
# the dead host's unjournaled blocks re-run (journal-shard- and
# lease-attempt-asserted); measured speedup + the fleet-wide
# mesh-utilization rollup are emitted. The parent never initializes
# JAX (children force their own host meshes).
# See transmogrifai_tpu/parallel/pod_smoke.py.
pod-smoke:
	$(PY) -m transmogrifai_tpu.parallel.pod_smoke

# continuous-training smoke: drifted records appended to a live store
# fire the drift monitor, a warm-start refit runs while serving stays
# live (zero dropped requests, p99 measured during refit), the promoted
# model answers /score with a new version, and an injected holdout
# regression (runtime/faults site continual.holdout_eval) auto-rolls
# the swap back. See transmogrifai_tpu/continual/smoke.py.
continual-smoke:
	env JAX_PLATFORMS=cpu $(PY) -m transmogrifai_tpu.continual.smoke

# observability smoke: tiny train+score through the runner with
# --trace-out; validates the Perfetto JSON (well-formed events,
# monotonic ts, parented spans), the GoodputReport buckets summing to
# ~wall time, and the correlation-id-stamped JSONL event log. See
# transmogrifai_tpu/obs/smoke.py.
trace-smoke:
	env JAX_PLATFORMS=cpu $(PY) -m transmogrifai_tpu.obs.smoke

# observability-plane smoke: scripted traffic + one injected
# device-error storm against a served model — asserts the traceparent
# roundtrip (caller trace id echoed; queue-wait/assemble+parse/pad/
# dispatch spans under the request root), tail sampling keeping every
# error trace while head-sampling successes, the breaker-open flight
# dump validating as a Chrome trace with the failing dispatch spans,
# and the availability SLO burn-rate alert firing during the storm and
# clearing after recovery. See transmogrifai_tpu/obs/slo_smoke.py.
slo-smoke:
	env JAX_PLATFORMS=cpu $(PY) -m transmogrifai_tpu.obs.slo_smoke

# learned-cost-model smoke: a synthetic corpus fits to holdout MAPE
# under the gate per target; then a real multi-block sweep on 8 forced
# host devices schedules count-LPT (cold model, recording its block
# rows) and predicted-LPT (refit from that corpus) — winners and fold
# metrics bit-identical, residuals recorded, packing pair reported.
# See transmogrifai_tpu/perf/smoke.py.
costmodel-smoke:
	$(PY) -m transmogrifai_tpu.perf.smoke

# host-data-plane smoke: the compiled row codec is bit-identical to the
# reference Dataset.from_rows on a hostile NaN/None/missing-key/big-int
# /object schema, a warm service assembles batches by WRITING into the
# resident staging buffers (zero fresh batch allocations across
# sustained traffic, generation-fenced across swaps), and calibrated
# int8 quantization scores the same rows bit-identically inside two
# different batch compositions. See
# transmogrifai_tpu/serving/parse_smoke.py.
parse-smoke:
	env JAX_PLATFORMS=cpu $(PY) -m transmogrifai_tpu.serving.parse_smoke

# fleet-router smoke: two replicas over ONE shared artifact store —
# replica-2's cold start is artifact replay (store-keyed warmup
# manifest + shared compile cache, <= 1.5x a warm restart), the
# over-quota tenant 429s from EITHER replica (CAS-guarded shared
# balance), and concurrent binary-framed requests through the frontend
# score bit-identically to the JSON columnar wire. See
# transmogrifai_tpu/serving/router_smoke.py.
router-smoke:
	env JAX_PLATFORMS=cpu $(PY) -m transmogrifai_tpu.serving.router_smoke

# fleet-observability smoke: two replica PROCESSES + a routing frontend
# over one shared store — a sampled request's W3C traceparent crosses
# the HTTP hop and the fleet merge stitches frontend + replica shards
# into ONE validate-clean Perfetto trace (100% of sampled requests);
# /metrics/fleet folds every replica's published registry snapshot; a
# seeded storm split across both replicas fires the fleet SLO alert
# EXACTLY once (CAS latch) and clears without re-firing; the firing
# replica's flight dump opens a fleet incident that every peer joins
# within the capture window, merged into one cross-host Chrome trace.
# See transmogrifai_tpu/serving/fleetobs_smoke.py.
fleetobs-smoke:
	env JAX_PLATFORMS=cpu $(PY) -m transmogrifai_tpu.serving.fleetobs_smoke

test:
	@$(TIER1)

check: lint conc-check serve-smoke parse-smoke fleet-smoke chaos-smoke \
	autopilot-smoke roofline-smoke ingest-smoke cache-smoke faults-smoke \
	trace-smoke slo-smoke multichip-smoke pod-smoke continual-smoke \
	costmodel-smoke router-smoke fleetobs-smoke test
