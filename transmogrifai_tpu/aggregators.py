"""Monoid aggregators for event-time feature aggregation.

Reference parity: `features/src/main/scala/com/salesforce/op/aggregators/`
(17 files) — `Event.scala`, `CutOffTime.scala`/`CutOffTimeTypes.scala`,
`MonoidAggregatorDefaults.scala:41-120` (the per-type dispatch),
`TimeBasedAggregator.scala` (first/last), `Geolocation.scala` (midpoint),
`Numerics.scala`/`Text.scala`/`Lists.scala`/`Sets.scala`/`Maps.scala`.

Redesign: instead of ~200 Algebird case objects (SumReal, UnionConcatTextMap,
…), aggregation behaviors are small parameterized factories (`sum_agg`,
`concat_agg`, `union_map_agg(inner)`, …) plus one `default_aggregator(ftype)`
dispatch that reproduces the reference's defaults table. Aggregation is a
host-side (numpy/python) concern: it runs in the readers before any data
reaches the device, collapsing unbounded per-key event streams to constant
row width (SURVEY.md §5.7).

An aggregator is (prepare, combine, present):
    prepare(Event) -> state        # lift one event into the monoid
    combine(state, state) -> state # associative merge; None is identity
    present(state|None) -> value   # final typed value (None = empty)
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence

from transmogrifai_tpu import types as T


# --------------------------------------------------------------------- #
# events & cutoffs                                                      #
# --------------------------------------------------------------------- #

@dataclass(frozen=True)
class Event:
    """A timestamped raw value (aggregators/Event.scala): `time` is epoch
    milliseconds, matching the reference's Long date fields."""

    time: int
    value: Any

    @staticmethod
    def of(value: Any, time: int) -> "Event":
        return Event(int(time), value)


MS_PER_DAY = 24 * 60 * 60 * 1000


class CutOffTime:
    """Cutoff separating predictor events (strictly before) from response
    events (at/after) — `aggregators/CutOffTime.scala`, kinds in
    `CutOffTimeTypes.scala` (UnixEpoch / DaysAgo / WeeksAgo / DDMMYYYY /
    NoCutoff)."""

    def __init__(self, kind: str, timestamp: Optional[int]):
        self.kind = kind
        self.timestamp = timestamp  # epoch ms; None = no cutoff

    @staticmethod
    def no_cutoff() -> "CutOffTime":
        return CutOffTime("NoCutoff", None)

    @staticmethod
    def infinite_future() -> "CutOffTime":
        """Every event is a predictor event; responses stay empty (used for
        unmatched conditional-reader keys)."""
        return CutOffTime("InfiniteFuture", math.inf)

    @staticmethod
    def unix_epoch(ms: int) -> "CutOffTime":
        return CutOffTime("UnixEpoch", int(ms))

    @staticmethod
    def days_ago(days: int, now_ms: int) -> "CutOffTime":
        return CutOffTime("DaysAgo", int(now_ms) - days * MS_PER_DAY)

    @staticmethod
    def weeks_ago(weeks: int, now_ms: int) -> "CutOffTime":
        return CutOffTime("WeeksAgo", int(now_ms) - weeks * 7 * MS_PER_DAY)

    @staticmethod
    def ddmmyyyy(date: str) -> "CutOffTime":
        """'ddMMyyyy' string, as the reference's DDMMYYYY cutoff."""
        import datetime
        d = datetime.datetime.strptime(date, "%d%m%Y")
        d = d.replace(tzinfo=datetime.timezone.utc)
        return CutOffTime("DDMMYYYY", int(d.timestamp() * 1000))

    def __repr__(self) -> str:
        return f"CutOffTime({self.kind}, {self.timestamp})"


# --------------------------------------------------------------------- #
# aggregator core                                                       #
# --------------------------------------------------------------------- #

class MonoidAggregator:
    """(prepare, combine, present) triple over Events. `name` keeps the
    reference's case-object vocabulary for serialization/debug."""

    def __init__(self, name: str,
                 prepare: Callable[[Event], Any],
                 combine: Callable[[Any, Any], Any],
                 present: Callable[[Optional[Any]], Any],
                 zero: Any = None):
        self.name = name
        self._prepare = prepare
        self._combine = combine
        self._present = present
        # monoid zero: the fold's START state, so an EMPTY fold presents
        # the zero instead of missing — the reference distinguishes e.g.
        # SumReal (zero=None → empty folds to null) from SumRealNN
        # (zero=Some(0.0) → empty folds to 0.0), Numerics.scala:18-21
        self._zero = zero

    def __call__(self, events: Sequence[Event]) -> Any:
        """Fold events → final value (None-states are identity)."""
        acc = self._zero
        for e in events:
            s = self._prepare(e)
            if s is None:
                continue
            acc = s if acc is None else self._combine(acc, s)
        return self._present(acc)

    def __repr__(self) -> str:
        return f"MonoidAggregator({self.name})"


def _value_prepare(e: Event) -> Any:
    return e.value if e.value is not None else None


# -- numeric ----------------------------------------------------------- #

def sum_agg(name: str = "Sum", integral: bool = False,
            zero: Any = None) -> MonoidAggregator:
    """SumReal/SumIntegral/SumCurrency (zero=None → empty folds missing);
    SumRealNN passes zero=0.0 (aggregators/Numerics.scala:18-21)."""
    def present(s):
        if s is None:
            return None
        return int(s) if integral else float(s)
    return MonoidAggregator(name, _value_prepare, lambda a, b: a + b, present,
                            zero=zero)


def mean_agg(name: str = "Mean", zero: Any = None) -> MonoidAggregator:
    """MeanReal/MeanPercent/MeanCurrency: intermediate (sum, count).
    MeanRealNN passes zero=(0.0, 0), presenting 0.0 on an empty fold
    (Numerics.scala MeanDouble present: count==0 → 0.0)."""
    return MonoidAggregator(
        name,
        lambda e: None if e.value is None else (float(e.value), 1),
        lambda a, b: (a[0] + b[0], a[1] + b[1]),
        lambda s: None if s is None else (s[0] / s[1] if s[1] else 0.0),
        zero=zero)


def min_agg(name: str = "Min", integral: bool = False,
            zero: Any = None) -> MonoidAggregator:
    """MinReal/... (MinRealNN passes zero=+inf, Numerics.scala:41)."""
    def present(s):
        if s is None:
            return None
        return int(s) if integral else float(s)
    return MonoidAggregator(name, _value_prepare, min, present, zero=zero)


def max_agg(name: str = "Max", integral: bool = False,
            zero: Any = None) -> MonoidAggregator:
    """MaxReal/... (MaxRealNN passes zero=-inf, Numerics.scala:34)."""
    def present(s):
        if s is None:
            return None
        return int(s) if integral else float(s)
    return MonoidAggregator(name, _value_prepare, max, present, zero=zero)


def logical_or_agg() -> MonoidAggregator:
    """LogicalOr — the Binary default."""
    return MonoidAggregator(
        "LogicalOr", _value_prepare, lambda a, b: bool(a or b),
        lambda s: None if s is None else bool(s))


def logical_and_agg() -> MonoidAggregator:
    return MonoidAggregator(
        "LogicalAnd", _value_prepare, lambda a, b: bool(a and b),
        lambda s: None if s is None else bool(s))


def logical_xor_agg() -> MonoidAggregator:
    return MonoidAggregator(
        "LogicalXor", _value_prepare, lambda a, b: bool(a) ^ bool(b),
        lambda s: None if s is None else bool(s))


# -- text -------------------------------------------------------------- #

def concat_agg(separator: str = " ", name: str = "ConcatText") -> MonoidAggregator:
    """ConcatText* (aggregators/Text.scala): join non-empty texts."""
    return MonoidAggregator(
        name,
        lambda e: None if e.value in (None, "") else str(e.value),
        lambda a, b: a + separator + b,
        lambda s: s)


def mode_agg(name: str = "ModePickList") -> MonoidAggregator:
    """ModePickList (aggregators/Text.scala, ExtendedMultiset): most frequent
    value; ties broken by lexicographic min, matching the multiset fold."""
    def present(s: Optional[Dict[str, int]]):
        if not s:
            return None
        best = max(s.items(), key=lambda kv: (kv[1], ), default=None)
        top = best[1]
        return min(k for k, v in s.items() if v == top)
    return MonoidAggregator(
        name,
        lambda e: None if e.value is None else {str(e.value): 1},
        lambda a, b: {k: a.get(k, 0) + b.get(k, 0) for k in {*a, *b}},
        present)


# -- collections ------------------------------------------------------- #

def concat_list_agg(name: str = "ConcatList") -> MonoidAggregator:
    """ConcatTextList/ConcatDateList/ConcatDateTimeList (Lists.scala)."""
    return MonoidAggregator(
        name,
        lambda e: None if e.value is None else list(e.value),
        lambda a, b: a + b,
        lambda s: s)


def union_set_agg(name: str = "UnionMultiPickList") -> MonoidAggregator:
    """UnionMultiPickList (Sets.scala)."""
    return MonoidAggregator(
        name,
        lambda e: None if e.value is None else set(e.value),
        lambda a, b: a | b,
        lambda s: s)


def combine_vector_agg(name: str = "CombineVector") -> MonoidAggregator:
    """CombineVector (OPVector.scala): concatenate vectors."""
    return MonoidAggregator(
        name,
        lambda e: None if e.value is None else list(e.value),
        lambda a, b: a + b,
        lambda s: s)


def sum_vector_agg(name: str = "SumVector") -> MonoidAggregator:
    """SumVector (OPVector.scala): elementwise sum."""
    return MonoidAggregator(
        name,
        lambda e: None if e.value is None else list(e.value),
        lambda a, b: [x + y for x, y in zip(a, b)],
        lambda s: s)


def geolocation_midpoint_agg(name: str = "GeolocationMidpoint") -> MonoidAggregator:
    """GeolocationMidpoint (aggregators/Geolocation.scala): average the
    lat/lon points in 3-D Cartesian space, convert back, keep max accuracy
    (the reference's documented midpoint algorithm)."""
    def prepare(e: Event):
        v = e.value
        if v is None or len(v) < 2:
            return None
        lat, lon = math.radians(v[0]), math.radians(v[1])
        acc = v[2] if len(v) > 2 else 0.0
        return (math.cos(lat) * math.cos(lon), math.cos(lat) * math.sin(lon),
                math.sin(lat), acc, 1)

    def combine(a, b):
        # cartesian components + count sum; accuracy keeps the max
        return (a[0] + b[0], a[1] + b[1], a[2] + b[2],
                max(a[3], b[3]), a[4] + b[4])

    def present(s):
        if s is None:
            return None
        x, y, z, acc, n = s
        x, y, z = x / n, y / n, z / n
        lon = math.atan2(y, x)
        lat = math.atan2(z, math.sqrt(x * x + y * y))
        return [math.degrees(lat), math.degrees(lon), acc]

    return MonoidAggregator(name, prepare, combine, present)


# -- maps -------------------------------------------------------------- #

def union_map_agg(inner: MonoidAggregator, name: str = "UnionMap") -> MonoidAggregator:
    """Union*Map (aggregators/Maps.scala): per-key combine with an inner
    aggregator (UnionRealMap = union_map(sum), UnionConcatTextMap =
    union_map(concat), UnionMeanPercentMap = union_map(mean), …).

    State: {key: inner_state}."""
    def prepare(e: Event):
        if e.value is None:
            return None
        out = {}
        for k, v in dict(e.value).items():
            s = inner._prepare(Event(e.time, v))
            if s is not None:
                out[k] = s
        return out or None

    def combine(a, b):
        out = dict(a)
        for k, s in b.items():
            out[k] = inner._combine(out[k], s) if k in out else s
        return out

    def present(s):
        if s is None:
            return None
        return {k: inner._present(v) for k, v in s.items()}

    return MonoidAggregator(name, prepare, combine, present)


# -- time-based -------------------------------------------------------- #

def first_agg(name: str = "First") -> MonoidAggregator:
    """First* (TimeBasedAggregator.scala): value of the earliest event."""
    return MonoidAggregator(
        name,
        lambda e: None if e.value is None else (e.time, e.value),
        lambda a, b: a if a[0] <= b[0] else b,
        lambda s: None if s is None else s[1])


def last_agg(name: str = "Last") -> MonoidAggregator:
    """Last*: value of the latest event."""
    return MonoidAggregator(
        name,
        lambda e: None if e.value is None else (e.time, e.value),
        lambda a, b: a if a[0] > b[0] else b,
        lambda s: None if s is None else s[1])


def custom_agg(fn: Callable[[Any, Any], Any], name: str = "Custom",
               prepare: Optional[Callable[[Any], Any]] = None) -> MonoidAggregator:
    """CustomMonoidAggregator.scala: user-supplied associative combine."""
    return MonoidAggregator(
        name,
        (lambda e: None if e.value is None else prepare(e.value)) if prepare
        else _value_prepare,
        fn, lambda s: s)


# --------------------------------------------------------------------- #
# defaults dispatch (MonoidAggregatorDefaults.aggregatorOf)             #
# --------------------------------------------------------------------- #

def default_aggregator(ftype: type) -> MonoidAggregator:
    """Per-type default, reproducing the dispatch table at
    `MonoidAggregatorDefaults.scala:52-120`: vectors combine; lists concat;
    geolocation midpoint; maps union with a type-appropriate inner combine;
    Binary OR; Currency/Integral/Real/RealNN sum; Percent mean;
    Date/DateTime max; sets union; PickList mode; other texts concat."""
    t = ftype
    # maps first (they subclass OPMap); inner combine mirrors the scalar rule
    if issubclass(t, T.GeolocationMap):
        return union_map_agg(geolocation_midpoint_agg(), "UnionGeolocationMidpointMap")
    if issubclass(t, T.BinaryMap):
        return union_map_agg(logical_or_agg(), "UnionBinaryMap")
    if issubclass(t, T.PercentMap):
        return union_map_agg(mean_agg(), "UnionMeanPercentMap")
    if issubclass(t, (T.DateMap, T.DateTimeMap)):
        return union_map_agg(max_agg(integral=True), "UnionMaxDateMap")
    if issubclass(t, T.IntegralMap):
        return union_map_agg(sum_agg(integral=True), "UnionIntegralMap")
    if issubclass(t, T.Prediction):
        return union_map_agg(mean_agg(), "UnionMeanPrediction")
    if issubclass(t, (T.CurrencyMap, T.RealMap)):
        return union_map_agg(sum_agg(), "UnionRealMap")
    if issubclass(t, T.MultiPickListMap):
        return union_map_agg(union_set_agg(), "UnionMultiPickListMap")
    if issubclass(t, (T.NameStats,)) or issubclass(t, T.OPMap):
        return union_map_agg(concat_agg(), "UnionConcatTextMap")
    # collections
    if issubclass(t, T.OPVector):
        return combine_vector_agg()
    if issubclass(t, T.Geolocation):
        return geolocation_midpoint_agg()
    if issubclass(t, (T.TextList, T.DateList, T.DateTimeList)):
        return concat_list_agg()
    if issubclass(t, T.MultiPickList):
        return union_set_agg()
    # numerics
    if issubclass(t, T.Binary):
        return logical_or_agg()
    if issubclass(t, T.Percent):
        return mean_agg("MeanPercent")
    if issubclass(t, (T.Date, T.DateTime)):
        return max_agg("MaxDate", integral=True)
    if issubclass(t, (T.Integral,)):
        return sum_agg("SumIntegral", integral=True)
    if issubclass(t, T.RealNN):
        # RealNN is non-nullable: its sum carries a real monoid zero, so
        # an empty fold is 0.0, not missing (SumRealNN, Numerics.scala:21)
        return sum_agg("SumRealNN", zero=0.0)
    if issubclass(t, (T.Currency, T.Real)):
        return sum_agg("SumReal")
    # text
    if issubclass(t, T.PickList):
        return mode_agg()
    if issubclass(t, T.Text):
        return concat_agg()
    raise T.FeatureTypeError(f"No default aggregator for {ftype.__name__}")


def aggregate_events(events: List[Event], ftype: type,
                     aggregator: Optional[MonoidAggregator] = None,
                     cutoff: Optional[CutOffTime] = None,
                     is_response: bool = False,
                     window_ms: Optional[int] = None,
                     response_window_ms: Optional[int] = None,
                     predictor_window_ms: Optional[int] = None) -> Any:
    """FeatureAggregator.extract (aggregators/FeatureAggregator.scala):
    split events around the cutoff — predictors fold events strictly
    *before* it (optionally only within the window back from it),
    responses fold events *at/after* it (optionally only the window
    forward) — then apply the monoid.

    `window_ms` is the feature's own aggregate window and wins over the
    reader-level `response_window_ms`/`predictor_window_ms`
    (`specialTimeWindow.orElse(timeWindow)`, FeatureAggregator.scala)."""
    agg = aggregator or default_aggregator(ftype)
    if window_ms is None:
        window_ms = response_window_ms if is_response else predictor_window_ms
    ts = None if cutoff is None else cutoff.timestamp
    if ts is None:
        kept = events
    elif is_response:
        # inclusive upper bound: the reference keeps events exactly at
        # cutoff + window (FeatureAggregator.scala filterByDateWithCutoff)
        hi = None if window_ms is None else ts + window_ms
        kept = [e for e in events if e.time >= ts and (hi is None or e.time <= hi)]
    else:
        # an infinite-future cutoff means "everything is a predictor" — a
        # window anchored at infinity must not filter anything out
        lo = None if (window_ms is None or math.isinf(ts)) else ts - window_ms
        kept = [e for e in events if e.time < ts and (lo is None or e.time >= lo)]
    out = agg(kept)
    if out is None and issubclass(ftype, T.NonNullable):
        # non-nullable types present the monoid zero, not an empty value
        # (SumRealNN's zero is 0.0 — aggregators/Numerics.scala)
        return 0.0 if issubclass(ftype, T.OPNumeric) else ftype.empty_value
    return out
