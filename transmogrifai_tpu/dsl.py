"""The feature DSL: every op as a method on `Feature`.

Reference parity: `core/src/main/scala/com/salesforce/op/dsl/` — the
implicit enrichment classes `RichNumericFeature` (arith at :70-228,
`bucketize:263`, `autoBucketize:288`, `vectorize:315`, `zNormalize:377`,
`sanityCheck:469`), `RichTextFeature` (tokenize/pivot/smartVectorize),
`RichDateFeature`, `RichListFeature`, `RichSetFeature`, `RichMapFeature`,
`RichVectorFeature`, generic `RichFeature` (map/alias/filter/exists/
replaceWith/occurs), and `RichFeaturesCollection.transmogrify`
(`RichFeaturesCollection.scala:69`).

Python has no implicits: importing this module (done by the package
`__init__`) attaches the methods directly onto `Feature`. Each method wires
a stage lazily and returns its output feature — nothing executes.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

from transmogrifai_tpu import types as T
from transmogrifai_tpu.features.feature import Feature


def _stage(cls, *inputs, **kw) -> Feature:
    return cls(**kw).set_input(*inputs).get_output()


# ----------------------------------------------------------------- #
# arithmetic (RichNumericFeature:70-228)                            #
# ----------------------------------------------------------------- #

def _binary_or_scalar(op: str):
    def method(self: Feature, other):
        from transmogrifai_tpu.ops.mathops import (
            BinaryMathTransformer, ScalarMathTransformer)
        if isinstance(other, Feature):
            return _stage(BinaryMathTransformer, self, other, op=op)
        return _stage(ScalarMathTransformer, self, op=op, scalar=float(other))
    return method


def _reflected_scalar(op: str):
    """scalar ⊕ feature for non-commutative ops (__rsub__/__rtruediv__)."""
    def method(self: Feature, other):
        from transmogrifai_tpu.ops.mathops import ScalarMathTransformer
        return _stage(ScalarMathTransformer, self, op=op, scalar=float(other))
    return method


def _unary(op: str, needs_arg: bool = False):
    if needs_arg:
        def method(self: Feature, arg: float):
            from transmogrifai_tpu.ops.mathops import UnaryMathTransformer
            return _stage(UnaryMathTransformer, self, op=op, arg=arg)
    else:
        def method(self: Feature):
            from transmogrifai_tpu.ops.mathops import UnaryMathTransformer
            return _stage(UnaryMathTransformer, self, op=op)
    return method


def log(self: Feature, base: float = 0.0) -> Feature:
    from transmogrifai_tpu.ops.mathops import UnaryMathTransformer
    return _stage(UnaryMathTransformer, self, op="log", arg=base)


# ----------------------------------------------------------------- #
# numeric feature engineering                                       #
# ----------------------------------------------------------------- #

def vectorize(self: Feature, track_nulls: bool = True, fill_value="mean") -> Feature:
    """Per-type default encoding of a single feature (RichNumericFeature.vectorize
    etc.) — delegates to transmogrify on the singleton list."""
    from transmogrifai_tpu.automl.transmogrify import (
        TransmogrifierDefaults, transmogrify)
    d = TransmogrifierDefaults(track_nulls=track_nulls, fill_numeric=fill_value)
    return transmogrify([self], defaults=d)


def z_normalize(self: Feature, with_mean: bool = True, with_std: bool = True) -> Feature:
    from transmogrifai_tpu.ops.scalers import OpScalarStandardScaler
    return _stage(OpScalarStandardScaler, self, with_mean=with_mean, with_std=with_std)


def fill_missing_with_mean(self: Feature, default: float = 0.0) -> Feature:
    from transmogrifai_tpu.ops.scalers import FillMissingWithMean
    return _stage(FillMissingWithMean, self, default=default)


def bucketize(self: Feature, splits: Sequence[float], track_nulls: bool = True,
              track_invalid: bool = False) -> Feature:
    from transmogrifai_tpu.ops.bucketizers import NumericBucketizer
    return _stage(NumericBucketizer, self, splits=splits,
                  track_nulls=track_nulls, track_invalid=track_invalid)


def auto_bucketize(self: Feature, label: Feature, max_depth: int = 2,
                   track_nulls: bool = True) -> Feature:
    from transmogrifai_tpu.ops.bucketizers import DecisionTreeNumericBucketizer
    return _stage(DecisionTreeNumericBucketizer, label, self,
                  max_depth=max_depth, track_nulls=track_nulls)


def to_percentile(self: Feature, buckets: int = 100) -> Feature:
    from transmogrifai_tpu.ops.scalers import PercentileCalibrator
    return _stage(PercentileCalibrator, self, buckets=buckets)


def scale(self: Feature, scaling_type: str = "linear", slope: float = 1.0,
          intercept: float = 0.0) -> Feature:
    from transmogrifai_tpu.ops.scalers import ScalerTransformer
    return _stage(ScalerTransformer, self, scaling_type=scaling_type,
                  slope=slope, intercept=intercept)


def descale(self: Feature, scaled: Feature) -> Feature:
    from transmogrifai_tpu.ops.scalers import DescalerTransformer
    return _stage(DescalerTransformer, self, scaled)


# ----------------------------------------------------------------- #
# label / sanity / selection entry points                           #
# ----------------------------------------------------------------- #

def sanity_check(self: Feature, feature_vector: Feature, **kw) -> Feature:
    """label.sanity_check(vector) — RichNumericFeature.sanityCheck:469."""
    from transmogrifai_tpu.automl.sanity_checker import SanityChecker
    return _stage(SanityChecker, self, feature_vector, **kw)


# ----------------------------------------------------------------- #
# text (RichTextFeature)                                            #
# ----------------------------------------------------------------- #

def tokenize(self: Feature, **kw) -> Feature:
    from transmogrifai_tpu.ops.text import TextTokenizer
    return _stage(TextTokenizer, self, **kw)


def pivot(self: Feature, top_k: int = 20, min_support: int = 10,
          track_nulls: bool = True) -> Feature:
    from transmogrifai_tpu.ops.categorical import OneHotVectorizer
    return _stage(OneHotVectorizer, self, top_k=top_k, min_support=min_support,
                  track_nulls=track_nulls)


def smart_vectorize(self: Feature, **kw) -> Feature:
    from transmogrifai_tpu.ops.text import SmartTextVectorizer
    return _stage(SmartTextVectorizer, self, **kw)


def indexed(self: Feature, handle_invalid: str = "error") -> Feature:
    from transmogrifai_tpu.ops.indexers import OpStringIndexer
    return _stage(OpStringIndexer, self, handle_invalid=handle_invalid)


def deindexed(self: Feature, labels: Optional[Sequence[str]] = None) -> Feature:
    from transmogrifai_tpu.ops.indexers import OpIndexToString
    return _stage(OpIndexToString, self, labels=labels)


def text_len(self: Feature) -> Feature:
    from transmogrifai_tpu.ops.rowops import TextLenTransformer
    return _stage(TextLenTransformer, self)


# ----------------------------------------------------------------- #
# enrichment (RichTextFeature email/url/phone/base64 sections)      #
# ----------------------------------------------------------------- #

def is_valid_email(self: Feature) -> Feature:
    from transmogrifai_tpu.ops.enrich import ValidEmailTransformer
    return _stage(ValidEmailTransformer, self)


def to_email_domain(self: Feature) -> Feature:
    from transmogrifai_tpu.ops.enrich import EmailDomainTransformer
    return _stage(EmailDomainTransformer, self)


def to_email_parts(self: Feature) -> Feature:
    from transmogrifai_tpu.ops.enrich import EmailToPickListMapTransformer
    return _stage(EmailToPickListMapTransformer, self)


def is_valid_url(self: Feature) -> Feature:
    from transmogrifai_tpu.ops.enrich import UrlIsValidTransformer
    return _stage(UrlIsValidTransformer, self)


def to_domain(self: Feature) -> Feature:
    from transmogrifai_tpu.ops.enrich import UrlDomainTransformer
    return _stage(UrlDomainTransformer, self)


def to_protocol(self: Feature) -> Feature:
    from transmogrifai_tpu.ops.enrich import UrlProtocolTransformer
    return _stage(UrlProtocolTransformer, self)


def is_valid_phone(self: Feature, *, region: Optional[Feature] = None,
                   default_region: str = "US") -> Feature:
    """RichTextFeature.isValidPhoneDefaultCountry / isValidPhoneNumber
    (RichTextFeature.scala:493-545): pass a region-code/country-name Text
    feature to resolve the validation region per row."""
    from transmogrifai_tpu.ops.enrich import (
        PhoneIsValidTransformer, PhoneIsValidWithRegionTransformer)
    if region is not None:
        return _stage(PhoneIsValidWithRegionTransformer, self, region,
                      default_region=default_region)
    return _stage(PhoneIsValidTransformer, self, default_region=default_region)


def parse_phone(self: Feature, *, region: Optional[Feature] = None,
                default_region: str = "US") -> Feature:
    """RichTextFeature.parsePhone / parsePhoneDefaultCountry
    (RichTextFeature.scala:466-493): normalized "+cc…" Phone, None when
    invalid."""
    from transmogrifai_tpu.ops.enrich import (
        PhoneParseTransformer, PhoneParseWithRegionTransformer)
    if region is not None:
        return _stage(PhoneParseWithRegionTransformer, self, region,
                      default_region=default_region)
    return _stage(PhoneParseTransformer, self, default_region=default_region)


def is_valid_phone_map(self: Feature, default_region: str = "US") -> Feature:
    """RichMapFeature phone-map validity (IsValidPhoneMapDefaultCountry)."""
    from transmogrifai_tpu.ops.enrich import PhoneMapIsValidTransformer
    return _stage(PhoneMapIsValidTransformer, self,
                  default_region=default_region)


def detect_mime_types(self: Feature, type_hint=None) -> Feature:
    from transmogrifai_tpu.ops.enrich import MimeTypeDetector
    return _stage(MimeTypeDetector, self, type_hint=type_hint)


def detect_languages(self: Feature) -> Feature:
    from transmogrifai_tpu.ops.enrich import LangDetector
    return _stage(LangDetector, self)


def detect_name(self: Feature) -> Feature:
    from transmogrifai_tpu.ops.enrich import HumanNameDetector
    return _stage(HumanNameDetector, self)


def recognize_entities(self: Feature) -> Feature:
    from transmogrifai_tpu.ops.enrich import NameEntityRecognizer
    return _stage(NameEntityRecognizer, self)


def remove_stop_words(self: Feature, stop_words=None,
                      case_sensitive: bool = False) -> Feature:
    from transmogrifai_tpu.ops.text_advanced import OpStopWordsRemover
    return _stage(OpStopWordsRemover, self, stop_words=stop_words,
                  case_sensitive=case_sensitive)


def ngram(self: Feature, n: int = 2) -> Feature:
    from transmogrifai_tpu.ops.text_advanced import OpNGram
    return _stage(OpNGram, self, n=n)


def count_vectorize(self: Feature, vocab_size: int = 1 << 18,
                    min_df: float = 1.0, binary: bool = False) -> Feature:
    from transmogrifai_tpu.ops.text_advanced import OpCountVectorizer
    return _stage(OpCountVectorizer, self, vocab_size=vocab_size,
                  min_df=min_df, binary=binary)


def word2vec(self: Feature, vector_size: int = 100, window: int = 5,
             min_count: int = 5, num_iter: int = 1) -> Feature:
    from transmogrifai_tpu.ops.text_advanced import OpWord2Vec
    return _stage(OpWord2Vec, self, vector_size=vector_size, window=window,
                  min_count=min_count, num_iter=num_iter)


def lda(self: Feature, k: int = 10, max_iter: int = 20) -> Feature:
    from transmogrifai_tpu.ops.text_advanced import OpLDA
    return _stage(OpLDA, self, k=k, max_iter=max_iter)


# ----------------------------------------------------------------- #
# dates (RichDateFeature)                                           #
# ----------------------------------------------------------------- #

def to_unit_circle(self: Feature, periods: Optional[Sequence[str]] = None) -> Feature:
    from transmogrifai_tpu.ops.dates import DEFAULT_PERIODS, DateToUnitCircleVectorizer
    return _stage(DateToUnitCircleVectorizer, self,
                  periods=list(periods or DEFAULT_PERIODS))


def to_time_period(self: Feature, period: str = "DayOfWeek") -> Feature:
    from transmogrifai_tpu.ops.dates import TimePeriodTransformer
    return _stage(TimePeriodTransformer, self, period=period)


# ----------------------------------------------------------------- #
# generic (RichFeature)                                             #
# ----------------------------------------------------------------- #

def alias(self: Feature, name: str) -> Feature:
    from transmogrifai_tpu.ops.rowops import AliasTransformer
    return _stage(AliasTransformer, self, name=name)


def map_values(self: Feature, fn: Callable[[Any], Any], out_type: type) -> Feature:
    from transmogrifai_tpu.ops.rowops import LambdaMap
    return _stage(LambdaMap, self, fn=fn, out_type=out_type)


def filter_values(self: Feature, predicate: Callable[[Any], bool]) -> Feature:
    from transmogrifai_tpu.ops.rowops import FilterTransformer
    return _stage(FilterTransformer, self, predicate=predicate)


def exists(self: Feature, predicate: Callable[[Any], bool]) -> Feature:
    from transmogrifai_tpu.ops.rowops import ExistsTransformer
    return _stage(ExistsTransformer, self, predicate=predicate)


def replace_with(self: Feature, old: Any, new: Any) -> Feature:
    from transmogrifai_tpu.ops.rowops import ReplaceTransformer
    return _stage(ReplaceTransformer, self, old=old, new=new)


def occurs(self: Feature, match_fn: Optional[Callable[[Any], bool]] = None) -> Feature:
    from transmogrifai_tpu.ops.rowops import ToOccurTransformer
    return _stage(ToOccurTransformer, self, match_fn=match_fn)


def jaccard_similarity(self: Feature, other: Feature) -> Feature:
    from transmogrifai_tpu.ops.rowops import JaccardSimilarity
    return _stage(JaccardSimilarity, self, other)


def ngram_similarity(self: Feature, other: Feature, n: int = 3) -> Feature:
    from transmogrifai_tpu.ops.rowops import NGramSimilarity
    return _stage(NGramSimilarity, self, other, n=n)


def contained_in(self: Feature, other: Feature, ignore_case: bool = True) -> Feature:
    from transmogrifai_tpu.ops.rowops import SubstringTransformer
    return _stage(SubstringTransformer, self, other, ignore_case=ignore_case)


# ----------------------------------------------------------------- #
# vector (RichVectorFeature)                                        #
# ----------------------------------------------------------------- #

def combine(self: Feature, *others: Feature) -> Feature:
    from transmogrifai_tpu.ops.combiner import VectorsCombiner
    return _stage(VectorsCombiner, self, *others)


_METHODS = {
    "__add__": _binary_or_scalar("plus"),
    "__radd__": _binary_or_scalar("plus"),
    "__sub__": _binary_or_scalar("minus"),
    "__rsub__": _reflected_scalar("rminus"),
    "__mul__": _binary_or_scalar("multiply"),
    "__rmul__": _binary_or_scalar("multiply"),
    "__truediv__": _binary_or_scalar("divide"),
    "__rtruediv__": _reflected_scalar("rdivide"),
    "abs": _unary("abs"), "ceil": _unary("ceil"), "floor": _unary("floor"),
    "round": _unary("round"), "exp": _unary("exp"), "sqrt": _unary("sqrt"),
    "negate": _unary("negate"), "power": _unary("power", needs_arg=True),
    "log": log,
    "vectorize": vectorize, "z_normalize": z_normalize,
    "fill_missing_with_mean": fill_missing_with_mean,
    "bucketize": bucketize, "auto_bucketize": auto_bucketize,
    "to_percentile": to_percentile, "scale": scale, "descale": descale,
    "sanity_check": sanity_check,
    "tokenize": tokenize, "pivot": pivot, "smart_vectorize": smart_vectorize,
    "indexed": indexed, "deindexed": deindexed, "text_len": text_len,
    "is_valid_email": is_valid_email, "to_email_domain": to_email_domain,
    "to_email_parts": to_email_parts, "is_valid_url": is_valid_url,
    "to_domain": to_domain, "to_protocol": to_protocol,
    "is_valid_phone": is_valid_phone, "parse_phone": parse_phone,
    "is_valid_phone_map": is_valid_phone_map,
    "detect_mime_types": detect_mime_types,
    "detect_languages": detect_languages, "detect_name": detect_name,
    "recognize_entities": recognize_entities,
    "remove_stop_words": remove_stop_words, "ngram": ngram,
    "count_vectorize": count_vectorize, "word2vec": word2vec, "lda": lda,
    "to_unit_circle": to_unit_circle, "to_time_period": to_time_period,
    "alias": alias, "map_values": map_values, "filter_values": filter_values,
    "exists": exists, "replace_with": replace_with, "occurs": occurs,
    "jaccard_similarity": jaccard_similarity,
    "ngram_similarity": ngram_similarity, "contained_in": contained_in,
    "combine": combine,
}

for _name, _fn in _METHODS.items():
    setattr(Feature, _name, _fn)
