from transmogrifai_tpu.stages.base import (
    FitContext, Stage, Transformer, HostTransformer, Estimator,
    FeatureGeneratorStage, StageRegistry,
)

__all__ = [
    "FitContext", "Stage", "Transformer", "HostTransformer", "Estimator",
    "FeatureGeneratorStage", "StageRegistry",
]
