"""Stage abstraction: typed, lazily-wired transformers and estimators.

Reference parity: `features/.../stages/OpPipelineStages.scala:55-553` (arity
traits, `OpTransformer` row contract) and `features/.../stages/base/*`
(Unary/Binary/.../Sequence Transformer+Estimator pairs).

TPU-first redesign: a stage is a pair of pure functions instead of a Spark
pipeline node —

- `Estimator.fit(columns, ctx) -> Transformer`  (host-driven; may run jitted
  stats reductions over sharded batches)
- `Transformer` splits into `host_prepare(columns) -> enc` (string/object
  work, numpy) and `device_apply(enc, device_inputs) -> arrays` (pure jnp,
  jittable). The fitted DAG's device_apply chain fuses into ONE XLA program
  at scoring time (replacing both `FitStagesUtil.applyOpTransformations`
  row-fusion and the MLeap local path).

Contract for `host_prepare`: it may only read host-kind input columns
(text/list/map); device-kind inputs (scalar/vector/prediction) may be None
when running inside the compiled scorer.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Type

import numpy as np

from transmogrifai_tpu import types as T
from transmogrifai_tpu.data.columns import Column, kind_of, SCALAR, VECTOR, PREDICTION
from transmogrifai_tpu.data.metadata import VectorMetadata
from transmogrifai_tpu.utils.uid import UID


@dataclass
class FitContext:
    """Per-fit environment: row count, rng seed, optional device mesh.

    `cv_refit` is set by the workflow ONLY on the ModelSelector's context
    when workflow-level CV is enabled (`Workflow.with_workflow_cv()`): a
    callable `fold_rows -> (n_total, d) feature matrix` that re-fits the
    pre-selector feature-engineering DAG on the given rows (the cutDAG
    equivalent, FitStagesUtil.scala:302-367)."""

    n_rows: int
    seed: int = 42
    mesh: Any = None  # jax.sharding.Mesh when running sharded
    data_axis: str = "data"
    cv_refit: Any = None

    def child(self, salt: int) -> "FitContext":
        return FitContext(self.n_rows, self.seed * 1000003 + salt, self.mesh, self.data_axis)


class StageRegistry:
    """Class registry for stage (de)serialization
    (OpPipelineStageReaderWriter analogue)."""

    _classes: Dict[str, type] = {}

    @classmethod
    def register(cls, stage_cls: type) -> None:
        cls._classes[stage_cls.__name__] = stage_cls

    @classmethod
    def get(cls, name: str) -> type:
        try:
            return cls._classes[name]
        except KeyError:
            raise KeyError(f"Stage class {name!r} is not registered") from None


class Stage:
    """Base: typed inputs, one output feature, serializable params.

    Subclasses declare `in_types`: a tuple of FeatureType classes for fixed
    arity, or (`elem_type`, Ellipsis) for variadic same-type inputs
    (SequenceEstimator analogue). `None` disables checking.
    """

    in_types: Optional[Tuple] = None
    out_type: type = T.OPVector  # default output feature type

    # Stages that may legitimately combine the response with predictors
    # (models, SanityChecker, supervised bucketizers) set this True; by
    # convention their slot 0 is the label slot. `analysis.opcheck` treats
    # any other stage mixing response-derived features with predictors as
    # response leakage, and outputs of response-aware stages (e.g. a
    # Prediction) as sanctioned rather than tainted.
    response_aware: bool = False

    def __init__(self, uid: Optional[str] = None, **params):
        self.uid = uid or UID(type(self))
        self.params: Dict[str, Any] = params
        self.input_features: Tuple = ()
        self._output = None

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        StageRegistry.register(cls)

    # -- wiring --------------------------------------------------------- #

    @property
    def operation_name(self) -> str:
        return type(self).__name__

    def set_input(self, *features) -> "Stage":
        self._check_inputs(features)
        self.input_features = tuple(features)
        self._output = None
        return self

    def _check_inputs(self, features: Sequence) -> None:
        spec = self.in_types
        if spec is None:
            return
        if len(spec) == 2 and spec[1] is Ellipsis:
            elem = spec[0]
            if elem is not None:
                for f in features:
                    if not issubclass(f.ftype, elem):
                        raise TypeError(
                            f"{self.operation_name} requires inputs of type "
                            f"{elem.__name__}; got {f.ftype.__name__} ({f.name})")
            return
        if len(features) != len(spec):
            raise TypeError(
                f"{self.operation_name} requires {len(spec)} inputs, got {len(features)}")
        for f, t in zip(features, spec):
            if t is not None and not issubclass(f.ftype, t):
                raise TypeError(
                    f"{self.operation_name} input {f.name!r}: expected "
                    f"{t.__name__}, got {f.ftype.__name__}")

    def output_ftype(self) -> type:
        return self.out_type

    def output_name(self) -> str:
        base = "-".join(f.name for f in self.input_features) or "raw"
        return f"{base}_{self.operation_name}_{self.uid}"

    def get_output(self):
        from transmogrifai_tpu.features import Feature
        if self._output is None:
            if not self.input_features and not isinstance(self, FeatureGeneratorStage):
                raise RuntimeError(f"{self.operation_name}: set_input before get_output")
            is_resp = bool(self.input_features) and all(
                f.is_response for f in self.input_features)
            self._output = Feature(
                name=self.output_name(), ftype=self.output_ftype(),
                origin_stage=self, parents=self.input_features,
                is_response=is_resp)
        return self._output

    # -- persistence ----------------------------------------------------- #

    def get_params(self) -> Dict[str, Any]:
        """JSON-serializable constructor params (override to extend)."""
        return dict(self.params)

    def __repr__(self) -> str:
        return f"{type(self).__name__}(uid={self.uid!r})"


class Transformer(Stage):
    """A fitted/stateless row-parallel operation (OpTransformer analogue)."""

    jittable = True  # device_apply is pure jnp and may be traced under jit

    def host_prepare(self, cols: Sequence[Optional[Column]]) -> Any:
        """Host-side encode of object-kind inputs → pytree of np arrays."""
        return None

    def device_apply(self, enc: Any, dev: Sequence[Any]) -> Any:
        """Pure-jnp compute over encoded + parent device values."""
        raise NotImplementedError(type(self).__name__)

    def device_constants(self) -> Any:
        """Large fitted arrays the compiled scorer should pass as jit
        ARGUMENTS instead of letting device_apply close over them:
        closure-captured arrays are re-staged host→device on every
        execution through the serving tunnel (~100ms per 20MB), so
        megabyte-scale model parameters (tree tables) must flow as
        arguments. None (default) = nothing big; device_apply reads self.
        """
        return None

    def device_apply_with(self, consts: Any, enc: Any,
                          dev: Sequence[Any]) -> Any:
        """device_apply with `device_constants()` threaded back in as a
        traced argument. Default ignores consts."""
        return self.device_apply(enc, dev)

    def signature_params(self) -> Dict[str, Any]:
        """Fitted params that shape the TRACED program — the facts
        `serving/fleet.scoring_signature` folds into the compile-group
        key. Defaults to `get_params()` (every fitted value is a closure
        constant baked into the XLA program). Stages that lift their
        fitted arrays through `device_constants()` override this to
        exclude the lifted VALUES — they flow as jit arguments, so only
        their shapes/dtypes key the program (via the consts digest) and
        same-shaped tenants share one compiled program — while keeping
        any hyperparams that still steer the trace (static control flow,
        baked scalars like a GBT learning rate)."""
        return self.get_params()

    def narrow_device_constants(self, consts: Any) -> Any:
        """Quantized-inference view of `device_constants()`: the same
        pytree with HBM-heavy tables re-typed to narrower dtypes, used
        by the compiled scorer's int8/int4 scoring mode. The narrowing
        rule must depend only on STATIC shape facts (never array
        values), so every model sharing a scoring signature narrows to
        identical traced dtypes and program adoption stays zero-trace.
        Default: unchanged (nothing to narrow)."""
        return consts

    def output_meta(self) -> Optional[VectorMetadata]:
        """Static vector metadata (set at fit time for fitted models)."""
        return None

    def transform(self, cols: Sequence[Column], ctx: Optional[FitContext] = None) -> Column:
        enc = self.host_prepare(cols)
        dev = self.device_apply(enc, [c.device_value() for c in cols])
        return self._wrap(dev)

    def _wrap(self, dev: Any) -> Column:
        out_t = self.output_ftype()
        k = kind_of(out_t)
        if k == VECTOR:
            return Column.vector(dev, self.output_meta())
        if k == SCALAR:
            # normalize back to the host columnar contract (f64 value, bool mask)
            return Column(out_t, {
                "value": np.asarray(dev["value"], dtype=np.float64),
                "mask": np.asarray(dev["mask"]).astype(bool)})
        if k == PREDICTION:
            return Column(out_t, {key: np.asarray(a) for key, a in dev.items()})
        raise TypeError(
            f"{self.operation_name}: device output cannot have host kind {k}; "
            "override transform() as a HostTransformer")


class HostTransformer(Transformer):
    """Transformer producing host-kind output (text/list/map) — runs eagerly
    on host in both fit and compiled-scoring paths."""

    jittable = False

    def transform(self, cols: Sequence[Column], ctx: Optional[FitContext] = None) -> Column:
        raise NotImplementedError(type(self).__name__)


# Column kinds that never cross to device (see data/columns.py kind table).
HOST_KINDS = ("text", "list", "map")


def is_host_stage(stage) -> bool:
    """THE host/device segmentation rule — single source of truth shared by
    the compiled scorer's planner (workflow/compiled.py) and the static
    validator (analysis/opcheck.py): a Transformer runs on host when it
    subclasses HostTransformer OR sets jittable=False (plain Transformers
    like DateListVectorizer override transform() and must never be traced
    into a device segment)."""
    return isinstance(stage, Transformer) and (
        isinstance(stage, HostTransformer) or not stage.jittable)


class Estimator(Stage):
    """Unfitted stage: `fit` learns params and returns the fitted
    Transformer (which keeps this estimator's uid, mirroring the reference's
    estimator→model swap in `Feature.copyWithNewStages`)."""

    def fit(self, cols: Sequence[Column], ctx: FitContext) -> Transformer:
        model = self.fit_model(cols, ctx)
        model.uid = self.uid
        model.input_features = self.input_features
        # The fitted model takes over the estimator's output feature node AND
        # becomes its origin stage, so post-fit DAG traversal sees fitted
        # transformers — the reference's `copyWithNewStages` estimator→model
        # swap. `_estimator` is kept so a re-train can find the unfitted stage.
        out = self.get_output()
        out.origin_stage = model
        model._output = out
        model._estimator = self
        return model

    def fit_model(self, cols: Sequence[Column], ctx: FitContext) -> Transformer:
        raise NotImplementedError(type(self).__name__)


class FeatureGeneratorStage(Stage):
    """Arity-0 origin of every raw feature
    (`features/.../stages/FeatureGeneratorStage.scala:67-125`).

    Extracts one typed column from a Dataset: either a named column (fast
    vectorized path) or a per-record python extract function (the reference's
    macro-captured extractFn)."""

    def __init__(self, name: str, ftype: type,
                 extract: Optional[Callable[[Dict[str, Any]], Any]] = None,
                 column: Optional[str] = None, is_response: bool = False,
                 null_fill: Any = None, uid: Optional[str] = None):
        super().__init__(uid=uid)
        from transmogrifai_tpu.utils.fnser import decode_fn
        self.feature_name = name
        self.ftype = ftype
        self.extract = decode_fn(extract)
        self.column = column if column is not None else (name if extract is None else None)
        self.is_response = is_response
        self.null_fill = null_fill  # vectorized null replacement (fast path)

    def output_ftype(self) -> type:
        return self.ftype

    def output_name(self) -> str:
        return self.feature_name

    def get_output(self):
        from transmogrifai_tpu.features import Feature
        if self._output is None:
            self._output = Feature(
                name=self.feature_name, ftype=self.ftype, origin_stage=self,
                parents=(), is_response=self.is_response)
        return self._output

    def materialize(self, dataset, allow_missing_response: bool = False) -> Column:
        if self.feature_name in getattr(dataset, "pre_extracted", ()) and \
                self.feature_name in dataset.columns:
            # aggregating readers already folded events to final typed values
            # keyed by feature name — bypass extract fns (readers/readers.py)
            return Column.from_values(
                self.ftype, dataset.column(self.feature_name))
        if self.extract is not None:
            values = [self.extract(row) for row in dataset.to_rows()]
            return Column.from_values(self.ftype, values)
        if self.column not in dataset.columns:
            if self.is_response and allow_missing_response:
                # scoring data without the label column: a type-appropriate
                # placeholder (zeros for numerics, empties otherwise).
                # Training always raises (allow_missing_response=False).
                fill = 0.0 if issubclass(self.ftype, T.OPNumeric) else None
                return Column.from_values(self.ftype, [fill] * len(dataset))
            raise KeyError(
                f"Raw feature {self.feature_name!r}: column {self.column!r} "
                f"not in dataset {dataset.names()}")
        values = dataset.column(self.column)
        if self.null_fill is not None:
            if values.dtype != object:  # typed numeric storage: NaN = missing
                values = np.where(np.isnan(values.astype(np.float64)),
                                  float(self.null_fill), values)
            else:
                values = np.array(
                    [self.null_fill if v is None else v for v in values],
                    dtype=object)
        return Column.from_values(self.ftype, values)

    def get_params(self) -> Dict[str, Any]:
        from transmogrifai_tpu.utils.fnser import encode_fn
        return {
            "name": self.feature_name, "ftype": self.ftype.__name__,
            "extract": encode_fn(self.extract),
            "column": self.column, "is_response": self.is_response,
            "null_fill": self.null_fill,
        }
