"""Warm-start refit: continue a fitted predictor from its own weights.

One dispatch point per model family, so the continual loop treats every
predictor uniformly:

- linear families (logistic / linear / GLM): the estimator's
  ``init_params`` warm-start (models/*.py) — the optimizer continues
  from the resident weights, reusing the SAME compiled fit program at
  fixed shapes (the warm pytree form compiles once; subsequent refits
  are pure cache hits, retrace-asserted in tests);
- forests: replacement trees grown on the appended delta swap in for
  the oldest resident trees (`models/trees.warm_refit_forest`);
- GBT: boosting continues from the resident ensemble's margin and the
  new rounds append (`models/trees.warm_refit_gbt`).

The refit itself runs through ``Workflow.train`` with every
feature-engineering stage reused warm (``with_model_stages(exclude=
predictor)``), so vectorizer vocabularies / scaler statistics stay
EXACTLY what the serving model scores with — only the predictor moves.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, Optional

import numpy as np

log = logging.getLogger(__name__)


def extract_warm_params(fitted_model) -> Optional[Dict[str, Any]]:
    """The warm-start payload for a fitted prediction model, in the
    shape its estimator's warm path expects; None for families with no
    warm form (naive bayes, isotonic, MLP — those refit cold)."""
    from transmogrifai_tpu.models.glm import GLMModel
    from transmogrifai_tpu.models.linear import LinearRegressionModel
    from transmogrifai_tpu.models.logistic import LogisticRegressionModel
    from transmogrifai_tpu.models.trees import _TreeModelBase

    if isinstance(fitted_model, LogisticRegressionModel):
        return {"W": np.asarray(fitted_model.W),
                "b": np.asarray(fitted_model.b)}
    if isinstance(fitted_model, GLMModel):
        return {"beta": np.asarray(fitted_model.beta),
                "b": float(fitted_model.b)}
    if isinstance(fitted_model, LinearRegressionModel):
        return {"beta": np.asarray(fitted_model.beta)}
    if isinstance(fitted_model, _TreeModelBase):
        # edges + trees (+ learning_rate for GBT): the tree estimators'
        # warm path consumes a fitted model's params dict directly
        return {k: v for k, v in fitted_model.get_params().items()}
    return None


def prepare_warm_estimator(estimator, fitted_model,
                           delta_rows: Optional[int] = None,
                           refit_max_iter: Optional[int] = None) -> bool:
    """Arm `estimator` to warm-start its next fit from `fitted_model`.
    Returns False (estimator untouched — the fit will be cold) when the
    family has no warm form. `delta_rows` tells the tree families how
    many trailing rows are new; `refit_max_iter` caps the warm
    optimizer budget for iterative families."""
    warm = extract_warm_params(fitted_model)
    if warm is None:
        estimator.init_params = None
        return False
    if delta_rows is not None and "trees" in warm:
        warm["delta_rows"] = int(delta_rows)
    estimator.init_params = warm
    if refit_max_iter is not None and hasattr(estimator, "max_iter"):
        estimator.max_iter = int(refit_max_iter)
    return True
