"""Drift detection: training fingerprint vs a sliding window of
appended records.

The fingerprint is captured at fit time from the SAME matrix the
predictor trained on — per-feature quantile-bin histograms plus the
streaming moments the SanityChecker already computes in one fused
device pass (`automl/sanity_checker._column_reductions`) — and is
persisted into ModelInsights beside the model artifact, so the monitor
of a freshly restarted process compares against what the serving model
actually saw, not against whatever rows happen to be on disk.

Shift is scored per feature as PSI (population stability index) over
the fingerprint's own bin edges:

    PSI = Σ_b (q_b − p_b) · ln(q_b / p_b)

with p the training fraction and q the window fraction per bin
(ε-clamped — an empty bin must read as strong evidence, not a NaN).
PSI ≥ 0.2 is the standard "significant shift" trigger. The label side
is a plain rate shift: |mean(y_window) − mean(y_train)| — cheap, and a
flipped label relationship shows up there long before feature
marginals move.
"""

from __future__ import annotations

import json
import logging
import os
import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Deque, Dict, List, Optional, Tuple

import numpy as np

from transmogrifai_tpu.continual.params import ContinualParams

log = logging.getLogger(__name__)

_EPS = 1e-4          # PSI bin-fraction clamp
_FP_SAMPLE = 100_000  # fingerprint row-sample cap (quantiles stabilize long before)


def psi(expected: np.ndarray, actual: np.ndarray,
        eps: float = _EPS) -> float:
    """Population stability index between two bin-fraction vectors."""
    p = np.clip(np.asarray(expected, np.float64), eps, None)
    q = np.clip(np.asarray(actual, np.float64), eps, None)
    p = p / p.sum()
    q = q / q.sum()
    return float(((q - p) * np.log(q / p)).sum())


def _histogram_fractions(X: np.ndarray, edges: np.ndarray) -> np.ndarray:
    """(d, n_bins) per-feature bin fractions of X over `edges`
    ((d, n_bins-1) interior edges): one vectorized searchsorted per
    feature, NaNs dropped from the count."""
    n, d = X.shape
    n_bins = edges.shape[1] + 1
    out = np.zeros((d, n_bins), np.float64)
    for j in range(d):
        col = X[:, j]
        col = col[np.isfinite(col)]
        if col.size == 0:
            out[j] = 1.0 / n_bins
            continue
        b = np.searchsorted(edges[j], col, side="right")
        out[j] = np.bincount(b, minlength=n_bins)[:n_bins] / col.size
    return out


@dataclass
class TrainingFingerprint:
    """What the training data looked like, compressed to what drift
    scoring needs: per-feature quantile edges + bin fractions + moments,
    and the label rate. JSON round-trips into ModelInsights."""

    n_rows: int
    edges: np.ndarray        # (d, n_bins-1) interior quantile edges
    fractions: np.ndarray    # (d, n_bins) training bin fractions
    means: np.ndarray        # (d,)
    variances: np.ndarray    # (d,)
    label_rate: float
    feature_names: List[str] = field(default_factory=list)

    @property
    def n_features(self) -> int:
        return int(self.edges.shape[0])

    @property
    def n_bins(self) -> int:
        return int(self.edges.shape[1] + 1)

    @staticmethod
    def from_arrays(X, y, n_bins: int = 10, sample: int = _FP_SAMPLE,
                    seed: int = 0,
                    feature_names: Optional[List[str]] = None,
                    total_rows: Optional[int] = None
                    ) -> "TrainingFingerprint":
        """Fingerprint the training matrix. Rows beyond `sample` are
        seeded-subsampled (quantile error is O(1/sample) of a bin);
        moments come from the SanityChecker's fused device reduction so
        the fingerprint pass adds no second stats implementation.
        `total_rows` records the true training size when the caller
        already subsampled X (e.g. device-side, to avoid a full host
        transfer)."""
        from transmogrifai_tpu.automl.sanity_checker import _column_reductions
        X = np.asarray(X, np.float32)
        y = np.asarray(y, np.float64).reshape(-1)
        n = X.shape[0]
        if n > sample:
            rng = np.random.default_rng(seed)
            idx = np.sort(rng.choice(n, size=sample, replace=False))
            Xs = X[idx]
        else:
            Xs = X
        qs = np.linspace(0.0, 1.0, n_bins + 1)[1:-1]
        edges = np.nanquantile(Xs.astype(np.float64), qs, axis=0).T
        edges = np.ascontiguousarray(edges)
        red = {k: np.asarray(v) for k, v in _column_reductions(Xs).items()}
        ns = max(Xs.shape[0], 1)
        means = red["sx"] / ns
        variances = np.maximum(
            (red["sxx"] - ns * means ** 2) / max(ns - 1, 1), 0.0)
        return TrainingFingerprint(
            n_rows=int(total_rows if total_rows is not None else n),
            edges=edges,
            fractions=_histogram_fractions(Xs, edges),
            means=np.asarray(means, np.float64),
            variances=np.asarray(variances, np.float64),
            label_rate=float(np.nanmean(y)) if y.size else 0.0,
            feature_names=list(feature_names or []))

    def to_json(self) -> Dict[str, Any]:
        return {
            "n_rows": self.n_rows,
            "edges": np.asarray(self.edges, np.float64).tolist(),
            "fractions": np.asarray(self.fractions, np.float64).tolist(),
            "means": np.asarray(self.means, np.float64).tolist(),
            "variances": np.asarray(self.variances, np.float64).tolist(),
            "label_rate": self.label_rate,
            "feature_names": list(self.feature_names),
        }

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "TrainingFingerprint":
        return TrainingFingerprint(
            n_rows=int(d["n_rows"]),
            edges=np.asarray(d["edges"], np.float64),
            fractions=np.asarray(d["fractions"], np.float64),
            means=np.asarray(d["means"], np.float64),
            variances=np.asarray(d["variances"], np.float64),
            label_rate=float(d["label_rate"]),
            feature_names=list(d.get("feature_names") or []))


def load_fingerprint(model_dir: str) -> Optional[TrainingFingerprint]:
    """The fingerprint persisted beside a saved model (the
    `insights.json` the continual loop writes via `save_model`'s
    extra-files hook). None when the artifact predates fingerprinting."""
    path = os.path.join(model_dir, "insights.json")
    if not os.path.exists(path):
        return None
    try:
        with open(path) as fh:
            doc = json.load(fh)
        fp = doc.get("trainingFingerprint")
        return TrainingFingerprint.from_json(fp) if fp else None
    except (ValueError, KeyError, OSError):
        log.warning("unreadable training fingerprint in %s", model_dir,
                    exc_info=True)
        return None


@dataclass
class DriftReport:
    """One drift check: per-feature PSI against the training histogram
    plus the label-rate shift, with the thresholds that judged them."""

    drifted: bool
    window_rows: int
    max_psi: float
    label_shift: float
    psi_by_feature: Dict[str, float] = field(default_factory=dict)
    triggers: List[str] = field(default_factory=list)

    def to_json(self) -> Dict[str, Any]:
        return {
            "drifted": self.drifted, "window_rows": self.window_rows,
            "max_psi": round(self.max_psi, 6),
            "label_shift": round(self.label_shift, 6),
            "psi_by_feature": {k: round(v, 6)
                               for k, v in self.psi_by_feature.items()},
            "triggers": list(self.triggers),
        }


class DriftMonitor:
    """Sliding-window drift scoring against a TrainingFingerprint.

    `observe(X, y)` feeds appended records; the window keeps the most
    recent `params.window_rows`. `check()` is cheap (histogram counts +
    one PSI per feature) and never judges fewer than
    `params.min_window_rows` rows. Thread-safe: `observe` runs on the
    appending application thread while the loop's supervisor thread
    calls `check`/`window`, so the deque is snapshotted under a lock —
    a check concurrent with an append sees a consistent (X, y) pairing,
    never a half-updated window."""

    def __init__(self, fingerprint: TrainingFingerprint,
                 params: Optional[ContinualParams] = None):
        self.fingerprint = fingerprint
        self.params = params or ContinualParams()
        self._chunks: Deque[Tuple[np.ndarray, np.ndarray]] = deque()
        self._rows = 0
        self._lock = threading.Lock()

    @property
    def window_rows(self) -> int:
        return self._rows

    def observe(self, X, y) -> None:
        X = np.asarray(X, np.float32)
        y = np.asarray(y, np.float64).reshape(-1)
        if X.ndim != 2 or X.shape[1] != self.fingerprint.n_features:
            raise ValueError(
                f"drift monitor: observed width {X.shape} does not match "
                f"the fingerprint's {self.fingerprint.n_features} features")
        with self._lock:
            self._chunks.append((X, y))
            self._rows += len(X)
            while self._chunks and self._rows - len(self._chunks[0][0]) \
                    >= self.params.window_rows:
                old = self._chunks.popleft()
                self._rows -= len(old[0])

    def _snapshot(self) -> Tuple[List[Tuple[np.ndarray, np.ndarray]], int]:
        with self._lock:
            return list(self._chunks), self._rows

    def window(self) -> Tuple[np.ndarray, np.ndarray]:
        """The materialized sliding window (most recent rows last)."""
        chunks, _ = self._snapshot()
        if not chunks:
            d = self.fingerprint.n_features
            return np.zeros((0, d), np.float32), np.zeros((0,), np.float64)
        return (np.concatenate([c for c, _ in chunks]),
                np.concatenate([yc for _, yc in chunks]))

    def check(self) -> DriftReport:
        fp, p = self.fingerprint, self.params
        chunks, rows = self._snapshot()
        if rows < p.min_window_rows:
            return DriftReport(drifted=False, window_rows=rows,
                               max_psi=0.0, label_shift=0.0)
        Xw = np.concatenate([c for c, _ in chunks])
        yw = np.concatenate([yc for _, yc in chunks])
        frac = _histogram_fractions(Xw, np.asarray(fp.edges))
        names = fp.feature_names or [f"f{i}" for i in range(fp.n_features)]
        scores = {names[j]: psi(fp.fractions[j], frac[j])
                  for j in range(fp.n_features)}
        label_shift = abs((float(np.nanmean(yw)) if yw.size else 0.0)
                          - fp.label_rate)
        triggers = [nm for nm, s in scores.items() if s > p.psi_threshold]
        if label_shift > p.label_shift_threshold:
            triggers.append("__label__")
        return DriftReport(
            drifted=bool(triggers), window_rows=rows,
            max_psi=max(scores.values()) if scores else 0.0,
            label_shift=label_shift, psi_by_feature=scores,
            triggers=triggers)
