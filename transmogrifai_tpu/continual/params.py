"""ContinualParams: JSON-loadable knobs for the continuous-training loop.

One dataclass holds every threshold the loop reads — drift detection
(window geometry, PSI/label-shift triggers), warm-refit budget, the
promotion gate's metric tolerance, and the post-swap live-eval/rollback
policy — mirroring how ServingParams/MeshParams/SweepCheckpointParams
configure their subsystems from the same OpParams JSON document.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional


@dataclass
class ContinualParams:
    """Knobs for `continual.loop.ContinualLoop`.

    Drift: the monitor holds a sliding window of the most recent
    `window_rows` appended records and refuses to judge fewer than
    `min_window_rows` (PSI over a near-empty histogram is noise). Drift
    fires when any feature's PSI against the training fingerprint
    exceeds `psi_threshold` (0.2 is the standard "significant shift"
    line; 0.1-0.2 is "monitor") or the label rate moved more than
    `label_shift_threshold` absolute.

    Refit: `refit_max_iter` caps the warm-started optimizer budget
    (None = the estimator's own default — warm starts usually converge
    well inside it); `refit_max_rows` caps how many trailing store rows
    the refit trains on (None = all rows — set it for multi-GB stores,
    whose full materialization would otherwise dominate host RAM every
    cycle); `holdout_fraction` of the window is excluded from the refit
    and scores the candidate.

    Promotion: the candidate must not regress the holdout metric more
    than `metric_tolerance` below the resident model's. After the swap,
    `live_eval_rows` of held-out records are scored THROUGH the serving
    path; with `auto_rollback` a live regression (or an eval failure)
    restores the previous resident version.
    """

    window_rows: int = 4096
    min_window_rows: int = 256
    n_bins: int = 10                   # PSI histogram resolution
    psi_threshold: float = 0.2
    label_shift_threshold: float = 0.1
    holdout_fraction: float = 0.2
    refit_max_iter: Optional[int] = None
    refit_max_rows: Optional[int] = None  # cap on trailing store rows a
    #                                       refit trains on (bounds the
    #                                       host materialization of
    #                                       multi-GB stores; None = all)
    metric_tolerance: float = 0.02
    live_eval_rows: int = 512
    auto_rollback: bool = True
    check_interval_s: float = 1.0      # supervisor poll period
    versions_dir: Optional[str] = None  # promoted artifacts (default:
    #                                     "<model_dir>-versions")
    journal_dir: Optional[str] = None  # cycle journal for crash resume

    _FIELDS = ("window_rows", "min_window_rows", "n_bins", "psi_threshold",
               "label_shift_threshold", "holdout_fraction",
               "refit_max_iter", "refit_max_rows", "metric_tolerance",
               "live_eval_rows", "auto_rollback", "check_interval_s",
               "versions_dir", "journal_dir")

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "ContinualParams":
        return ContinualParams(**{k: d[k] for k in ContinualParams._FIELDS
                                  if k in d})

    def to_json(self) -> Dict[str, Any]:
        return {k: getattr(self, k) for k in self._FIELDS}

    def __post_init__(self):
        if not (0.0 < self.holdout_fraction < 1.0):
            raise ValueError("holdout_fraction must be in (0, 1)")
        if self.min_window_rows > self.window_rows:
            raise ValueError("min_window_rows cannot exceed window_rows")
        if self.n_bins < 2:
            raise ValueError("n_bins must be >= 2")
        if self.refit_max_rows is not None and self.refit_max_rows < 1:
            raise ValueError("refit_max_rows must be >= 1 (or None)")
