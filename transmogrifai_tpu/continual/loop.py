"""ContinualLoop: streaming append → drift → warm refit → gated hot-swap.

The closed loop the rest of the codebase built in halves: records
append to a live `ColumnarStore` (crash-consistent segments), a
`DriftMonitor` watches them against the training fingerprint persisted
beside the serving model, and when drift fires a WARM-START refit runs
OFF the serving path — the feature-engineering stages are reused
as-fitted, the predictor continues from the resident weights — under a
`RetryPolicy`, with every completed step journaled so a killed process
resumes at the saved candidate instead of refitting again. Promotion is
gated twice: the candidate must hold the holdout metric BEFORE the
swap, and after the integrity-verified `/reload` a live holdout scored
THROUGH the serving path must not regress, or the swap auto-rolls back
to the resident version.

Observability: each pass is one `continual:cycle` span (drift / refit /
eval / promote children), with `drift_detected` / `refit` / `promoted`
/ `rolled_back` events in the shared event log and
`continual_*` counters in the process metrics registry — the same
surface serving `/metrics` scrapes. A `continual_cycle` summary event
carries staleness (append → fresh-model-serving seconds) into the
GoodputReport's `continual` section.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from transmogrifai_tpu import types as T
from transmogrifai_tpu.continual.drift import (
    DriftMonitor, DriftReport, TrainingFingerprint, load_fingerprint)
from transmogrifai_tpu.continual.params import ContinualParams
from transmogrifai_tpu.continual.refit import prepare_warm_estimator
from transmogrifai_tpu.data.columnar_store import ColumnarStore
from transmogrifai_tpu.data.dataset import Dataset
from transmogrifai_tpu.obs.export import record_event
from transmogrifai_tpu.obs.metrics import get_registry
from transmogrifai_tpu.obs.trace import TRACER
from transmogrifai_tpu.runtime.faults import SITE_HOLDOUT_EVAL, fault_point
from transmogrifai_tpu.runtime.journal import SweepJournal
from transmogrifai_tpu.runtime.retry import RetryPolicy

log = logging.getLogger(__name__)

LABEL_COLUMN = "label"


def _gate_metric(pred: np.ndarray, y: np.ndarray,
                 classification: bool) -> float:
    """THE gate's larger-is-better score — accuracy for classifiers,
    negative MSE for regressors. One implementation on purpose: the
    pre-swap baseline (holdout_eval) and the post-swap live gate
    (live_holdout_metric) must judge the identical quantity, or a
    candidate that holds the holdout could be rolled back (or a
    regressed one promoted) by metric skew alone."""
    pred = np.asarray(pred, np.float64).reshape(-1)
    y = np.asarray(y, np.float64).reshape(-1)
    if classification:
        return float((pred == np.round(y)).mean())
    return -float(((pred - y) ** 2).mean())


def holdout_eval(model, ds: Dataset, y: np.ndarray) -> Tuple[float, bool]:
    """(metric, classification) holdout score of a WorkflowModel:
    accuracy for classifiers, negative MSE for regressors — one number
    on purpose, the gate needs an ordering, not a report. Whether the
    model IS a classifier is judged from its own output (a non-empty
    probability head), so the pre-swap baseline and the post-swap live
    gate share one detector — integer-valued regression labels must not
    flip the live gate onto accuracy."""
    out = model.score(ds)
    tree = next((c.data for c in out.values()
                 if isinstance(c.data, dict) and "prediction" in c.data),
                None)
    if tree is None:
        raise ValueError("model produced no prediction feature")
    pred = np.asarray(tree["prediction"], np.float64).reshape(-1)
    prob = np.asarray(tree.get("probability"))
    classification = bool(prob.ndim == 2 and prob.shape[1] > 0)
    return _gate_metric(pred, y, classification), classification


def holdout_metric(model, ds: Dataset, y: np.ndarray) -> float:
    """Larger-is-better holdout score (see `holdout_eval`)."""
    return holdout_eval(model, ds, y)[0]


def live_holdout_metric(service, rows: List[Dict[str, Any]],
                        y: np.ndarray, classification: bool) -> float:
    """The same metric scored THROUGH the serving path (the live model,
    the live batcher, real requests) — what the post-swap gate judges.
    Requests are cut to the service's own bucket ladder, so the eval
    coexists with live traffic instead of monopolizing the top bucket.
    The `continual.holdout_eval` fault site fires first, so chaos tests
    can force this eval to fail deterministically.

    The CYCLE's trace context rides on every eval request: each one is
    a `serving:request` span parented under the open continual span
    (promote/cycle), force-kept past the tail sampler — so "why did
    the gate decide that" reads as one trace: the cycle, its eval
    requests, and each request's parse/queue/dispatch phases."""
    fault_point(SITE_HOLDOUT_EVAL)
    from transmogrifai_tpu.obs.trace import TraceContext, current_span
    ctx = TraceContext.from_span(current_span())
    step = int(service.ladder[-1])
    preds: List[np.ndarray] = []
    for i in range(0, len(rows), step):
        result = service.score(rows[i:i + step], trace=ctx)
        tree = next((v for v in result.outputs.values()
                     if isinstance(v, dict) and "prediction" in v), None)
        if tree is None:
            raise ValueError("serving returned no prediction feature")
        preds.append(np.asarray(tree["prediction"], np.float64).reshape(-1))
    pred = np.concatenate(preds) if preds else np.zeros(0)
    return _gate_metric(pred, y, classification)


def gated_swap(service, candidate_dir: str, rows: List[Dict[str, Any]],
               y: np.ndarray, baseline: float, tolerance: float,
               classification: bool = True,
               registry=None, auto_rollback: bool = True) -> Dict[str, Any]:
    """Reload `candidate_dir` into `service`, then judge it on a LIVE
    holdout: if the served metric regresses more than `tolerance` below
    `baseline` — or the eval itself fails (an unknowable metric must be
    assumed regressed) — the swap rolls back to the resident version.
    With `auto_rollback=False` a regressed candidate STAYS live (the
    regression is reported, not reverted — an operator policy choice).
    In-flight traffic is never touched: reload warms off the serving
    path and rollback re-activates an already-warm version.

    Returns {"status": "promoted" | "rolled_back", "metric": ...}."""
    reg = registry or get_registry()
    info = service.reload(candidate_dir)
    if info.get("status") == "unchanged":
        # content-identical candidate (a warm refit at an optimum that
        # still fits the new data converges in zero steps): nothing was
        # swapped, so there is nothing to gate — and nothing to roll
        # back. Running the live eval here would judge the RESIDENT
        # model, and a transient eval failure would then rollback() a
        # version that was never displaced, silently downgrading
        # serving to the previous (stale) artifact.
        record_event("promotion_unchanged", version=info.get("version"))
        log.info("continual: candidate %s is content-identical to the "
                 "live version; promotion is a no-op", info.get("version"))
        return {"status": "promoted", "metric": None, "unchanged": True,
                "version": info.get("version")}
    try:
        live = live_holdout_metric(service, rows, y, classification)
        ok = live >= baseline - tolerance
        reason = (None if ok else
                  f"live metric {live:.4f} < baseline {baseline:.4f} "
                  f"- tol {tolerance}")
    except Exception as e:
        live, ok = None, False
        reason = f"live holdout eval failed: {type(e).__name__}: {e}"
    if ok:
        return {"status": "promoted", "metric": live,
                "version": info.get("version")}
    if not auto_rollback:
        record_event("live_regression", reason=reason, metric=live,
                     baseline=round(baseline, 6))
        log.warning("continual: live regression but auto_rollback is "
                    "off; candidate %s stays live (%s)",
                    info.get("version"), reason)
        return {"status": "promoted", "metric": live, "regressed": reason,
                "version": info.get("version")}
    rb = service.rollback()
    reg.counter("continual_rollbacks_total",
                "post-swap live regressions auto-rolled back").inc()
    record_event("rolled_back", reason=reason,
                 metric=live, baseline=round(baseline, 6),
                 restored=rb.get("version"))
    log.warning("continual: rolled back %s -> %s (%s)",
                info.get("version"), rb.get("version"), reason)
    return {"status": "rolled_back", "metric": live, "reason": reason,
            "restored": rb.get("version")}


class ContinualLoop:
    """Supervises one store + one serving model as an always-on system.

    Usage::

        loop = ContinualLoop(store_path, model_dir, params)
        loop.train_initial()                      # cold fit + save
        svc = ScoringService.from_path(model_dir).start()
        loop.attach(svc)
        loop.start()                              # background supervisor
        ...
        loop.append(X_new, y_new)                 # streaming records
        # drift -> warm refit -> gated swap happen off the serving path

    Single supervisor thread: `run_cycle` (drift check, refit, gate) is
    only ever called from it (or synchronously in tests/smoke) — the
    serving scoring thread is never blocked by a refit.
    """

    def __init__(self, store, model_dir: str,
                 params: Optional[ContinualParams] = None,
                 estimator=None, seed: int = 42,
                 registry=None):
        self.store = (ColumnarStore(store) if isinstance(store, str)
                      else store)
        self.model_dir = os.path.normpath(model_dir)
        self.params = params or ContinualParams()
        self.seed = seed
        self.registry = registry or get_registry()
        if estimator is None:
            from transmogrifai_tpu.models.logistic import OpLogisticRegression
            estimator = OpLogisticRegression(max_iter=100)
        self._estimator = estimator
        self._result_features = None
        self._label_feature = None
        self.model = None                 # resident WorkflowModel
        self.monitor: Optional[DriftMonitor] = None
        self.service = None
        self._trace_parent = None
        self._thread: Optional[threading.Thread] = None
        self._running = False
        self._wake = threading.Event()
        self._cycle = 0
        self._pending_since: Optional[float] = None  # oldest unserved append
        # store size at the last rejected/rolled-back cycle: until new
        # rows arrive, re-running the refit would reproduce the same
        # gated-out candidate once per poll interval (a full train per
        # second) — drift alone is not new evidence
        self._gate_cooldown_rows: Optional[int] = None
        self._journal = None
        jd = self.params.journal_dir
        if jd:
            os.makedirs(jd, exist_ok=True)
            self._journal = SweepJournal(
                os.path.join(jd, "continual.jsonl"),
                meta={"kind": "continual", "model_dir": self.model_dir})
            self._cycle = self._restore_cycle()
        self._retry = RetryPolicy(max_attempts=3, seed=seed)

    # -- construction ---------------------------------------------------- #

    def _versions_dir(self) -> str:
        return self.params.versions_dir or f"{self.model_dir}-versions"

    def _build_graph(self, ds: Dataset) -> None:
        from transmogrifai_tpu.features.feature import FeatureBuilder
        from transmogrifai_tpu.ops.numeric import RealVectorizer
        preds, label = FeatureBuilder.from_dataset(ds, response=LABEL_COLUMN)
        vec = RealVectorizer(track_nulls=False).set_input(*preds).get_output()
        pred = self._estimator.set_input(label, vec).get_output()
        self._result_features = (pred, label)
        self._label_feature = label

    def _dataset(self, r0: int, r1: int) -> Dataset:
        X = np.asarray(self.store.chunk(r0, r1), np.float32)
        y = np.asarray(self.store.y[r0:r1], np.float64)
        cols: Dict[str, Any] = {
            name: X[:, j].astype(np.float64)
            for j, name in enumerate(self.store.feature_names)}
        cols[LABEL_COLUMN] = y
        schema = {name: T.Real for name in self.store.feature_names}
        schema[LABEL_COLUMN] = T.Integral if self._classification \
            else T.Real
        return Dataset(cols, schema)

    @property
    def _classification(self) -> bool:
        y = self.store.y
        if y is None:
            return True
        head = np.asarray(y[:1024], np.float64)
        return bool(np.allclose(head, np.round(head)))

    def _insights_json(self, model) -> Dict[str, Any]:
        from transmogrifai_tpu.insights import ModelInsights
        return ModelInsights.extract(model).to_json()

    def _save(self, model, path: str) -> None:
        model.save(path, extra_json={
            "insights.json": self._insights_json(model)})

    def train_initial(self):
        """Cold fit on every current store row; persists the model (and
        its training fingerprint, inside insights.json) to model_dir."""
        from transmogrifai_tpu.workflow.workflow import Workflow
        ds = self._dataset(0, self.store.n_rows)
        if self._result_features is None:
            self._build_graph(ds)
        wf = Workflow().set_result_features(*self._result_features) \
            .set_input_dataset(ds) \
            .set_parameters({"continual": self.params.to_json()})
        model = wf.train(seed=self.seed)
        self._save(model, self.model_dir)
        self.model = model
        self._install_monitor(model.training_fingerprint)
        return model

    def load_resident(self):
        """Adopt an existing serialized model + fingerprint (process
        restart path): the monitor compares against what the ARTIFACT
        trained on, not whatever is currently on disk."""
        from transmogrifai_tpu.workflow.serialization import load_model
        self.model = load_model(self.model_dir)
        fp = load_fingerprint(self.model_dir)
        if fp is None:
            raise ValueError(
                f"{self.model_dir} has no training fingerprint — retrain "
                "with train_initial() (or any Workflow.train) to capture "
                "one")
        self._adopt_graph(self.model)
        self._install_monitor(fp)
        # rehydrate the drift window from the store's appended segments:
        # a process restart must not forget rows that already landed on
        # disk — without this, a refit candidate journaled before a
        # crash is unreachable (run_cycle bails at 'no_drift' on the
        # empty window) until ANOTHER min_window_rows of drifted
        # appends arrive, and the stale resident serves indefinitely
        appended = self.store.n_rows - self.store.base_rows
        if appended > 0:
            take = min(appended, self.params.window_rows)
            r0 = self.store.n_rows - take
            self.monitor.observe(
                np.asarray(self.store.chunk(r0, self.store.n_rows),
                           np.float32),
                np.asarray(self.store.y[r0:self.store.n_rows],
                           np.float64))
            if self._pending_since is None:
                self._pending_since = time.perf_counter()
        return self.model

    def _adopt_graph(self, model) -> None:
        """Refit graph for a restarted process, built ON the loaded
        artifact's own feature stages (original uids, already fitted):
        feature engineering is reused verbatim — a fresh graph's
        process-local uids would never match `model.fitted`, so
        `with_model_stages` would silently REFIT every vectorizer the
        serving model scores with — and only the predictor is swapped
        for a fresh estimator wired into the same inputs."""
        pred_f = next((f for f in model.result_features
                       if issubclass(f.ftype, T.Prediction)
                       and f.origin_stage is not None), None)
        label_f = next((p for p in (pred_f.parents if pred_f else ())
                        if p.is_response), None)
        vec_f = next((p for p in (pred_f.parents if pred_f else ())
                      if issubclass(p.ftype, T.OPVector)), None)
        if label_f is None or vec_f is None:
            # artifact without a (label, vector) predictor: fall back to
            # a fresh graph (feature stages will refit cold)
            ds = self._dataset(0, min(self.store.n_rows, 16))
            if self._result_features is None:
                self._build_graph(ds)
            return
        new_pred = self._estimator.set_input(label_f, vec_f).get_output()
        self._result_features = (new_pred, label_f)
        self._label_feature = label_f

    def _install_monitor(self, fingerprint) -> None:
        if fingerprint is None:
            raise ValueError("training produced no fingerprint (no "
                             "(label, vector) predictor in the graph?)")
        if isinstance(fingerprint, dict):
            fingerprint = TrainingFingerprint.from_json(fingerprint)
        self.monitor = DriftMonitor(fingerprint, self.params)

    def attach(self, service) -> "ContinualLoop":
        """Bind the serving service promotions hot-swap into."""
        self.service = service
        return self

    # -- streaming append ------------------------------------------------- #

    def append(self, X, y) -> ColumnarStore:
        """Extend the live store with new records (crash-consistent
        segment append) and feed the drift window. The store object is
        swapped for the post-append view; readers holding the old one
        keep a consistent pre-append snapshot."""
        X = np.asarray(X)
        y = np.asarray(y, np.float32)
        w = ColumnarStore.append(self.store.path, len(X))
        w.write_chunk(0, X.astype(self.store.dtype), y)
        self.store = w.close()
        if self.monitor is not None:
            self.monitor.observe(X, y)
        if self._pending_since is None:
            self._pending_since = time.perf_counter()
        self.note_staleness()
        self.registry.counter(
            "continual_rows_appended_total",
            "records appended to the live store").inc(len(X))
        record_event("continual_append", rows=len(X),
                     store_rows=self.store.n_rows)
        self._wake.set()
        return self.store

    # -- the cycle --------------------------------------------------------- #

    def _restore_cycle(self) -> int:
        """Journal-derived resume point: normally one past the last
        cycle, but a cycle whose refit landed with NO terminal step
        (promoted / rejected / rolled_back — the process died between
        candidate save and swap) is resumed IN PLACE so the saved
        candidate gets its gate instead of a duplicate refit."""
        by_cycle: Dict[int, set] = {}
        for g, _ in self._journal.rows():
            by_cycle.setdefault(int(g.get("cycle", 0)), set()).add(
                g.get("step"))
        if not by_cycle:
            return 0
        last = max(by_cycle)
        terminal = {"promoted", "rejected", "rolled_back"}
        if "refit" in by_cycle[last] and not (by_cycle[last] & terminal):
            return last
        return last + 1

    def _journal_step(self, step: str, metric: float = 0.0,
                      **extra: Any) -> None:
        if self._journal is not None:
            self._journal.append({"cycle": self._cycle, "step": step,
                                  **extra}, [float(metric)])

    def _pending_candidate(self) -> Optional[Dict[str, Any]]:
        """A refit journaled for this cycle whose promotion never
        landed (crash between save and swap): resume at the gate
        instead of refitting again."""
        if self._journal is None:
            return None
        steps: Dict[str, Dict[str, Any]] = {}
        for grid, metrics in self._journal.rows():
            if int(grid.get("cycle", -1)) == self._cycle:
                steps[grid.get("step")] = {**grid, "metric": metrics[0]
                                           if metrics else 0.0}
        if "refit" in steps and "promoted" not in steps \
                and "rolled_back" not in steps:
            cand = steps["refit"]
            path = cand.get("model_dir")
            if path and os.path.isdir(path):
                from transmogrifai_tpu.workflow.serialization import (
                    ModelIntegrityError, verify_model_dir)
                try:
                    verify_model_dir(path)
                    return cand
                except (ModelIntegrityError, OSError):
                    log.warning("continual: journaled candidate %s is "
                                "torn; refitting", path)
        return None

    def _split_holdout(self):
        """The trailing `holdout_fraction` of the drift window: the
        newest records, held out of the refit, score the candidate."""
        Xw, yw = self.monitor.window()
        n_hold = max(1, int(len(Xw) * self.params.holdout_fraction))
        return Xw[-n_hold:], yw[-n_hold:]

    def _resident_predictor(self):
        """The resident model's fitted prediction stage — matched by
        TYPE, not uid, so a process restart (fresh graph uids over a
        loaded artifact) still finds its warm-start source."""
        from transmogrifai_tpu.models.base import PredictionModel
        fitted = self.model.fitted.get(self._estimator.uid)
        if isinstance(fitted, PredictionModel):
            return fitted
        for m in self.model.fitted.values():
            if isinstance(m, PredictionModel):
                return m
        raise ValueError("resident model has no fitted prediction stage")

    def _rows_of(self, X: np.ndarray) -> List[Dict[str, Any]]:
        names = self.store.feature_names
        return [{nm: float(x[j]) for j, nm in enumerate(names)}
                for x in np.asarray(X, np.float64)]

    def _warm_refit(self, holdout_rows: int, store_rows: int):
        """The refit itself: every feature-engineering stage reused
        as-fitted, the predictor re-trained warm on all store rows
        except the trailing holdout. `store_rows` is the row count
        captured WHEN the holdout was split — an append landing
        mid-cycle must not shift the holdout boundary, or the refit
        would train on the very rows the gate scores it on. A warm
        start whose shapes no longer match the data (e.g. appended
        records introduced a new class) falls back to a cold fit
        instead of wedging the loop."""
        from transmogrifai_tpu.workflow.workflow import Workflow
        fit_hi = max(1, store_rows - holdout_rows)
        delta = min(self.monitor.window_rows, fit_hi) \
            if self.monitor is not None else None
        cold_max_iter = getattr(self._estimator, "max_iter", None)
        prepare_warm_estimator(
            self._estimator, self._resident_predictor(),
            delta_rows=delta,
            refit_max_iter=self.params.refit_max_iter)
        try:
            # refit_max_rows bounds the host materialization: with a
            # warm start, the trailing rows carry the new signal — a
            # multi-GB store need not round-trip through host RAM
            fit_lo = 0
            if self.params.refit_max_rows is not None:
                fit_lo = max(0, fit_hi - int(self.params.refit_max_rows))
            ds = self._dataset(fit_lo, fit_hi)

            def _train():
                wf = Workflow() \
                    .set_result_features(*self._result_features) \
                    .set_input_dataset(ds) \
                    .set_parameters({"continual": self.params.to_json()}) \
                    .with_model_stages(self.model,
                                       exclude=(self._estimator.uid,))
                return wf.train(seed=self.seed + self._cycle + 1)

            try:
                model = _train()
            except ValueError as e:
                if "init_params" not in str(e):
                    raise
                log.warning("continual: warm start invalid (%s); "
                            "refitting cold", e)
                record_event("warm_start_fallback", reason=str(e)[:200])
                self._estimator.init_params = None
                if cold_max_iter is not None:
                    self._estimator.max_iter = cold_max_iter
                model = _train()
        finally:
            # the warm arming is scoped to THIS fit: a later cold fit of
            # the same estimator must see its own iteration budget again
            self._estimator.init_params = None
            if cold_max_iter is not None:
                self._estimator.max_iter = cold_max_iter
        self.registry.counter(
            "continual_refits_total", "warm-start refits executed").inc()
        return model

    def run_cycle(self) -> Dict[str, Any]:
        """One supervised pass: drift check; on drift a warm refit,
        pre-swap holdout gate, integrity-verified promotion, post-swap
        live gate with auto-rollback. Returns a status dict; never
        raises for gate failures (those are outcomes, not errors)."""
        p = self.params
        t0 = time.perf_counter()
        with TRACER.span("continual:cycle", category="continual",
                         parent=self._trace_parent,
                         cycle=self._cycle) as cycle_span:
            self.registry.counter(
                "continual_cycles_total", "continual cycles run").inc()
            with TRACER.span("continual:drift", category="continual"):
                report = self.monitor.check() if self.monitor else \
                    DriftReport(False, 0, 0.0, 0.0)
            if not report.drifted:
                cycle_span.set(status="no_drift")
                return {"status": "no_drift", "report": report.to_json()}
            if self._gate_cooldown_rows == self.store.n_rows:
                # the last candidate from exactly this data was gated
                # out (rejected or rolled back); wait for new appends
                # instead of re-training the same rejection every poll
                cycle_span.set(status="cooldown")
                return {"status": "cooldown",
                        "report": report.to_json()}
            record_event("drift_detected",
                         max_psi=round(report.max_psi, 4),
                         label_shift=round(report.label_shift, 4),
                         triggers=report.triggers[:8],
                         window_rows=report.window_rows)
            self.registry.counter(
                "continual_drift_detected_total",
                "drift checks that fired").inc()

            # snapshot BEFORE splitting: an append() landing after this
            # line can only shrink the training range relative to the
            # holdout (never put holdout rows inside it) — the reverse
            # order would let a mid-cycle append push fit_hi past the
            # holdout rows and train on them
            store_rows = self.store.n_rows
            Xh, yh = self._split_holdout()
            hold_ds = self._window_dataset(Xh, yh)
            baseline, classification = holdout_eval(self.model, hold_ds,
                                                    yh)

            resumed = self._pending_candidate()
            if resumed is not None:
                candidate_dir = resumed["model_dir"]
                metric_new = float(resumed["metric"])
                from transmogrifai_tpu.workflow.serialization import (
                    load_model)
                model2 = load_model(candidate_dir)
                record_event("refit", resumed=True,
                             candidate=candidate_dir)
            else:
                with TRACER.span("continual:refit", category="continual",
                                 rows=store_rows - len(Xh)):
                    model2 = self._retry.call(
                        self._warm_refit, len(Xh), store_rows,
                        label="continual.refit")
                with TRACER.span("continual:eval", category="continual"):
                    metric_new = holdout_metric(model2, hold_ds, yh)
                record_event("refit", metric=round(metric_new, 6),
                             baseline=round(baseline, 6))
                if metric_new < baseline - p.metric_tolerance:
                    record_event("refit_rejected",
                                 metric=round(metric_new, 6),
                                 baseline=round(baseline, 6))
                    self._journal_step("rejected", metric_new)
                    self._gate_cooldown_rows = store_rows
                    self._finish_cycle(cycle_span, "rejected", t0, report)
                    return {"status": "rejected", "metric": metric_new,
                            "baseline": baseline}
                candidate_dir = os.path.join(
                    self._versions_dir(), f"v{self._cycle:05d}")
                self._save(model2, candidate_dir)
                self._journal_step("refit", metric_new,
                                   model_dir=candidate_dir)

            swap: Dict[str, Any] = {"status": "promoted", "metric": None}
            if self.service is not None:
                with TRACER.span("continual:promote", category="continual",
                                 candidate=candidate_dir):
                    live_n = min(len(Xh), p.live_eval_rows)
                    # the live gate judges candidate-vs-resident on the
                    # SAME rows: a full-holdout baseline against a
                    # live_n-row candidate metric would let sampling
                    # noise alone cross the tolerance
                    live_baseline = baseline if live_n == len(Xh) else \
                        holdout_metric(
                            self.model,
                            self._window_dataset(Xh[-live_n:],
                                                 yh[-live_n:]),
                            yh[-live_n:])
                    swap = gated_swap(
                        self.service, candidate_dir,
                        self._rows_of(Xh[-live_n:]), yh[-live_n:],
                        baseline=live_baseline,
                        tolerance=p.metric_tolerance,
                        classification=classification,
                        registry=self.registry,
                        auto_rollback=p.auto_rollback)
                if swap["status"] == "rolled_back":
                    self._journal_step("rolled_back")
                    self._gate_cooldown_rows = store_rows
                    self._finish_cycle(cycle_span, "rolled_back", t0,
                                       report)
                    return {**swap, "candidate": candidate_dir}
            # promotion landed: the candidate is the resident model now
            self._gate_cooldown_rows = None
            self.model = model2
            new_fp = (model2.training_fingerprint
                      or load_fingerprint(candidate_dir))
            if new_fp is not None:
                self._install_monitor(new_fp)
            else:
                # fingerprint capture is best-effort in Workflow.train;
                # raising HERE (after the swap landed) would skip the
                # 'promoted' journal step and wedge the supervisor in a
                # resume loop on this candidate. Keep drifting against
                # the previous baseline instead — stale but functional —
                # with a fresh window (the promoted model absorbed it).
                log.warning("continual: promoted model has no training "
                            "fingerprint; keeping the previous drift "
                            "baseline")
                self._install_monitor(self.monitor.fingerprint)
            staleness = (time.perf_counter() - self._pending_since
                         if self._pending_since is not None else 0.0)
            self._pending_since = None
            self.registry.histogram(
                "continual_staleness_seconds",
                "append-to-fresh-model-serving latency").observe(staleness)
            self.registry.counter(
                "continual_promotions_total",
                "refit models promoted to serving").inc()
            record_event("promoted", candidate=candidate_dir,
                         metric=swap.get("metric"),
                         staleness_s=round(staleness, 3))
            self._journal_step("promoted", metric_new,
                               model_dir=candidate_dir)
            self._finish_cycle(cycle_span, "promoted", t0, report,
                               staleness)
            return {"status": "promoted", "candidate": candidate_dir,
                    "metric": metric_new, "baseline": baseline,
                    "staleness_s": staleness}

    def _window_dataset(self, Xh: np.ndarray, yh: np.ndarray) -> Dataset:
        cols: Dict[str, Any] = {
            nm: np.asarray(Xh[:, j], np.float64)
            for j, nm in enumerate(self.store.feature_names)}
        cols[LABEL_COLUMN] = np.asarray(yh, np.float64)
        schema = {nm: T.Real for nm in self.store.feature_names}
        schema[LABEL_COLUMN] = T.Integral if self._classification else T.Real
        return Dataset(cols, schema)

    def _finish_cycle(self, span, status: str, t0: float,
                      report: DriftReport,
                      staleness: Optional[float] = None) -> None:
        wall = time.perf_counter() - t0
        span.set(status=status, wall_s=round(wall, 4))
        record_event("continual_cycle", status=status,
                     cycle=self._cycle, wall_s=round(wall, 6),
                     max_psi=round(report.max_psi, 4),
                     staleness_s=(round(staleness, 6)
                                  if staleness is not None else None))
        self._cycle += 1
        self.note_staleness()

    def staleness_s(self) -> float:
        """CURRENT freshness debt: seconds since the oldest append not
        yet absorbed by a promoted model (0 when fully fresh) — what
        the staleness SLO judges each tick."""
        if self._pending_since is None:
            return 0.0
        return max(0.0, time.perf_counter() - self._pending_since)

    def note_staleness(self) -> None:
        """Publish the live freshness gauge the SLO engine's staleness
        source reads (`continual_staleness_current_seconds` on this
        loop's registry — the process registry by default, so serving
        `/metrics` and a fleet SLO both see it)."""
        self.registry.gauge(
            "continual_staleness_current_seconds",
            "seconds since the oldest store append not yet served by a "
            "promoted model (0 = fully fresh)").set(self.staleness_s())

    # -- supervisor thread -------------------------------------------------- #

    def start(self) -> "ContinualLoop":
        """Run cycles on a background thread, polling every
        `check_interval_s` (or immediately on append) — the serving
        scoring thread never blocks on a refit."""
        if self._running:
            return self
        self._trace_parent = TRACER.current()
        self._running = True
        self._thread = threading.Thread(
            target=self._supervise, name="continual-loop", daemon=True)
        self._thread.start()
        return self

    def _supervise(self) -> None:
        """Supervisor shell: the poll loop must survive ANYTHING short
        of process death. A per-cycle Exception is logged and the next
        poll retries from journaled state (inner handler); anything
        that ESCAPES that — an `InjectedKill`, a real fatal error in
        the fault-injected holdout path, a MemoryError — used to kill
        the thread permanently and silently stall continual training
        forever. Now it restarts the loop under the RetryPolicy's
        backoff schedule, with a `continual_supervisor_restarts_total`
        tick and a ``supervisor_restart`` event per restart."""
        import random as _random
        rng = _random.Random(f"{self.seed}:supervisor")
        restarts = 0
        while self._running:
            try:
                self._poll_loop()
                return  # stop() requested: clean exit
            except BaseException as e:
                if not self._running or isinstance(
                        e, (KeyboardInterrupt, SystemExit)):
                    raise
                restarts += 1
                delay = self._retry.delay_for(min(restarts, 8), rng)
                self.registry.counter(
                    "continual_supervisor_restarts_total",
                    "supervisor poll loops restarted after an escaped "
                    "failure").inc()
                record_event("supervisor_restart",
                             error=f"{type(e).__name__}: {e}"[:200],
                             restarts=restarts,
                             delay_s=round(delay, 6))
                log.error("continual: supervisor loop died (%s: %s); "
                          "restarting in %.3fs (restart %d)",
                          type(e).__name__, e, delay, restarts)
                time.sleep(delay)

    def _poll_loop(self) -> None:
        while self._running:
            self._wake.wait(timeout=self.params.check_interval_s)
            self._wake.clear()
            if not self._running:
                return
            self.note_staleness()  # freshness gauge ticks every poll
            try:
                self.run_cycle()
            except Exception:
                # the supervisor must survive a failed cycle: the next
                # append/poll retries from journaled state
                log.exception("continual: cycle failed; supervisor "
                              "continues")

    def stop(self, timeout: float = 30.0) -> None:
        self._running = False
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None
