"""Continuous training: streaming append → drift detection → warm-start
refit → gated hot-swap under traffic (see continual/loop.py)."""

from transmogrifai_tpu.continual.drift import (
    DriftMonitor, DriftReport, TrainingFingerprint, load_fingerprint, psi)
from transmogrifai_tpu.continual.loop import (
    ContinualLoop, gated_swap, holdout_eval, holdout_metric,
    live_holdout_metric)
from transmogrifai_tpu.continual.params import ContinualParams
from transmogrifai_tpu.continual.refit import (
    extract_warm_params, prepare_warm_estimator)

__all__ = [
    "ContinualLoop", "ContinualParams", "DriftMonitor", "DriftReport",
    "TrainingFingerprint", "load_fingerprint", "psi", "gated_swap",
    "holdout_eval", "holdout_metric", "live_holdout_metric",
    "extract_warm_params",
    "prepare_warm_estimator",
]
