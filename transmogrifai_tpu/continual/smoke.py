"""Continual-training smoke: the closed loop end-to-end in one process.

`make continual-smoke` runs this module. Under a minute on CPU it must
prove the ISSUE's acceptance scenario:

1. a store-backed model trains, saves (fingerprint included), and
   serves over HTTP;
2. drifted records APPEND to the live store (crash-consistent segment
   + manifest checksum update) and the DriftMonitor fires;
3. a warm-start refit runs OFF the serving path while a client thread
   keeps scoring — zero dropped requests, serving p99 measured during
   the refit;
4. the promoted model is integrity-verified, hot-swapped, and answers
   /score with a NEW version;
5. a second cycle with an injected `continual.holdout_eval` fault
   auto-rolls the swap back to the resident version
   (`serving_rollbacks_total` ticks, traffic unaffected);
6. the whole run sits under one trace whose GoodputReport carries the
   continual cycle accounting.

Run: ``JAX_PLATFORMS=cpu python -m transmogrifai_tpu.continual.smoke``
"""

from __future__ import annotations

import json
import sys
import tempfile
import threading
import time
import urllib.request

import numpy as np

N, D = 1500, 6
APPEND = 500


def _post(url: str, payload: dict) -> dict:
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=30) as resp:
        return json.loads(resp.read())


class _Client(threading.Thread):
    """Steady scoring traffic against /score; collects latencies and
    errors so the smoke can assert 'no dropped requests' and report the
    p99 observed DURING the refit."""

    def __init__(self, base: str, row: dict):
        super().__init__(daemon=True)
        self.base = base
        self.row = row
        self.latencies: list = []
        self.errors: list = []
        self._halt = threading.Event()

    def run(self) -> None:
        while not self._halt.is_set():
            t0 = time.perf_counter()
            try:
                _post(f"{self.base}/score", {"rows": [self.row]})
                self.latencies.append(time.perf_counter() - t0)
            except Exception as e:  # any failure under swap = a drop
                self.errors.append(f"{type(e).__name__}: {e}")
            time.sleep(0.01)

    def stop(self) -> None:
        self._halt.set()

    def p99_ms(self) -> float:
        if not self.latencies:
            return 0.0
        return float(np.percentile(np.array(self.latencies), 99) * 1e3)


def main() -> int:
    from transmogrifai_tpu.continual import ContinualLoop, ContinualParams
    from transmogrifai_tpu.data.columnar_store import ColumnarStore
    from transmogrifai_tpu.obs.goodput import build_report
    from transmogrifai_tpu.obs.trace import TRACER
    from transmogrifai_tpu.runtime.faults import (
        SITE_HOLDOUT_EVAL, FaultPlan, FaultSpec)
    from transmogrifai_tpu.serving.http import serve
    from transmogrifai_tpu.serving.service import (
        ScoringService, ServingConfig)

    rng = np.random.default_rng(11)
    beta = rng.normal(size=D)

    with tempfile.TemporaryDirectory(prefix="continual-smoke-") as tmp, \
            TRACER.span("run:continual-smoke", category="run",
                        new_trace=True) as root:
        X = rng.standard_normal((N, D)).astype(np.float32)
        y = (X @ beta > 0).astype(np.float32)
        w = ColumnarStore.create(f"{tmp}/store", N, D, dtype="float32")
        w.write_chunk(0, X, y)
        store = w.close()

        params = ContinualParams(window_rows=1024, min_window_rows=200,
                                 journal_dir=f"{tmp}/journal")
        loop = ContinualLoop(store, f"{tmp}/model", params=params, seed=11)
        loop.train_initial()

        service = ScoringService.from_path(
            f"{tmp}/model", config=ServingConfig(max_batch=16))
        service.start()
        loop.attach(service)
        server, _ = serve(service, port=0, block=False)
        base = f"http://127.0.0.1:{server.port}"
        client = _Client(base, {f"f{j}": 0.1 * j for j in range(D)})
        try:
            v0 = service.health()["model_version"]
            assert loop.run_cycle()["status"] == "no_drift", \
                "undrifted store must not refit"

            # 2. drifted append: shifted marginals, same relationship
            Xn = (rng.standard_normal((APPEND, D)) + 2.0).astype(np.float32)
            yn = (Xn @ beta > 0).astype(np.float32)
            loop.append(Xn, yn)
            report = loop.monitor.check()
            assert report.drifted and report.max_psi > 0.2, report.to_json()

            # 3+4. warm refit under live traffic -> gated promotion
            client.start()
            result = loop.run_cycle()
            assert result["status"] == "promoted", result
            v1 = service.health()["model_version"]
            assert v1 != v0, "promotion must hot-swap the version"
            scored = _post(f"{base}/score",
                           {"rows": [{f"f{j}": 1.0 for j in range(D)}]})
            assert scored["model_version"] == v1, scored
            refit_p99_ms = client.p99_ms()
            assert not client.errors, \
                f"requests dropped during refit: {client.errors[:3]}"

            # 5. injected holdout regression -> automatic rollback
            Xr = (rng.standard_normal((APPEND, D)) - 2.0).astype(np.float32)
            yr = (Xr @ beta > 0).astype(np.float32)
            loop.append(Xr, yr)
            plan = FaultPlan([FaultSpec(site=SITE_HOLDOUT_EVAL, at=1,
                                        kind="error")])
            with plan.active():
                result = loop.run_cycle()
            assert result["status"] == "rolled_back", result
            assert service.health()["model_version"] == v1, \
                "rollback must restore the resident version"
            prom = urllib.request.urlopen(
                f"{base}/metrics", timeout=30).read().decode()
            assert "serving_rollbacks_total 1" in prom, \
                [ln for ln in prom.splitlines() if "rollback" in ln]
            assert not client.errors, \
                f"requests dropped during rollback: {client.errors[:3]}"
            client.stop()
            client.join(timeout=5)

            # 6. one trace accounts the cycles
            gp = build_report(root, TRACER.trace_spans(root.trace_id))
            cont = gp.to_json().get("continual") or {}
            assert cont.get("cycles", 0) >= 2, gp.to_json()
            assert cont.get("promoted", 0) >= 1, cont
            assert cont.get("rolled_back", 0) >= 1, cont
            staleness = cont.get("last_staleness_s")
        except AssertionError as e:
            print(f"continual-smoke FAILED: {e}", file=sys.stderr)
            return 1
        finally:
            client.stop()
            server.shutdown()
            server.server_close()
            service.stop()
    print(f"continual-smoke OK: drift fired, warm refit promoted under "
          f"traffic (client p99 {refit_p99_ms:.1f} ms, 0 drops), "
          f"injected holdout regression rolled back, goodput cycles="
          f"{cont.get('cycles')} staleness={staleness}s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
