"""costmodel-smoke: the learned cost model's CI gate (`make costmodel-smoke`)
and the measured half of ``python bench.py costmodel``.

1. **synthetic corpus → fit → holdout MAPE.** A corpus generated from a
   known multiplicative law (with seeded lognormal noise) must fit to a
   holdout MAPE under the gate threshold per target — the log-linear
   ridge can actually learn the structure it claims to.
2. **predicted-LPT vs count-LPT on the forced 8-device host mesh.** A
   real multi-block sweep schedules twice: once with an explicitly COLD
   model (count-LPT — today's heuristic, and its block rows feed the
   corpus), once after refitting on the corpus those runs just wrote
   (predicted-LPT). Winners and every fold metric must be BIT-IDENTICAL
   either way — the model reorders and resizes work, never changes it —
   and both packings are measured via the goodput mesh rollup so the
   bench reports the improvement honestly.

Run: ``python -m transmogrifai_tpu.perf.smoke`` (fresh process: the
forced host-device count must precede JAX backend init).
"""

from __future__ import annotations

import json
import math
import os
import tempfile
import time
from typing import Any, Dict

MAPE_GATE = 0.35


def synth_corpus(corpus, seed: int = 11) -> None:
    """Deterministic synthetic training rows from known cost laws, one
    per target, with seeded multiplicative noise — the fit must recover
    structure, not memorize points."""
    import numpy as np
    rng = np.random.default_rng(seed)

    def noise(sigma=0.12):
        return float(np.exp(rng.normal(0.0, sigma)))

    for n_configs in (1, 2, 4, 8):
        for iters in (4, 8, 16, 32, 64):
            for n_rows in (10_000, 50_000, 200_000):
                secs = 3e-8 * n_configs * iters * n_rows * noise()
                corpus.append("block_runtime", {
                    "n_configs": n_configs, "n_rows": n_rows,
                    "n_cols": 50, "n_folds": 3, "dtype_bytes": 4,
                    "fam_logistic": 1.0, "iters": iters}, secs)
    for workers in (1, 2, 4):
        for depth in (1, 2, 4, 8):
            for gb in (0.5, 2.0, 8.0):
                bytes_wire = gb * 1e9
                wall = (bytes_wire / (40e6 * math.sqrt(workers))
                        + 64 * 0.05 / math.sqrt(depth)) * noise()
                corpus.append("ingest", {
                    "bytes_wire": bytes_wire, "workers": workers,
                    "depth": depth, "chunks": 64, "cache_hit": 0.0}, wall)
    for bucket in (1, 2, 4, 8, 16, 32, 64, 128):
        for _ in range(4):
            lat = (0.002 + 2e-5 * bucket) * noise(0.08)
            corpus.append("serving_bucket", {"bucket": bucket}, lat)
    for n_configs in (1, 2, 4, 8):
        for n_rows in (10_000, 50_000, 200_000):
            hbm = n_configs * 3.0 * n_rows * (50 * 32 + 64) * 2.0
            corpus.append("hbm", {
                "n_configs": n_configs, "n_rows": n_rows, "n_cols": 50,
                "n_folds": 3, "dtype_bytes": 4, "fam_forest": 1.0,
                "learners": 20, "bins": 32, "depth": 6, "nodes": 64},
                hbm * noise(0.05))


def _measured_schedule(selector_fn, cols, n_rows, mesh, label: str
                       ) -> Dict[str, Any]:
    from transmogrifai_tpu.obs import goodput as obs_goodput
    from transmogrifai_tpu.obs.trace import TRACER
    from transmogrifai_tpu.parallel.smoke import _fit, _rows
    with TRACER.span(f"run:costmodel-{label}", category="run",
                     new_trace=True) as root:
        t0 = time.perf_counter()
        rows = _rows(_fit(selector_fn(), cols, n_rows, mesh=mesh))
        wall = time.perf_counter() - t0
    report = obs_goodput.build_report(root, TRACER.trace_spans(root.trace_id))
    return {"rows": rows, "wall_s": round(wall, 3),
            "util": float(report.mesh.get("utilization_frac", 0.0)),
            "perf": report.perf}


def run_costmodel_bench(n_devices: int = 8,
                        n_rows: int = 240) -> Dict[str, Any]:
    """Shared by the smoke gate and ``bench.py costmodel``: synthetic-
    corpus MAPE per target + the measured count-LPT vs predicted-LPT
    schedule pair on the forced host mesh."""
    from transmogrifai_tpu.parallel.smoke import (
        _cols, _selector, ensure_host_devices)
    ensure_host_devices(n_devices)
    from transmogrifai_tpu import perf
    from transmogrifai_tpu.parallel.mesh import make_mesh
    from transmogrifai_tpu.parallel.smoke import _fit

    payload: Dict[str, Any] = {}

    # 1 — synthetic corpus: fit must beat the MAPE gate per target
    with tempfile.TemporaryDirectory(prefix="costmodel-synth-") as tmp:
        synth = perf.CostCorpus(tmp)
        synth_corpus(synth)
        for target in ("block_runtime", "ingest", "serving_bucket", "hbm"):
            mape = perf.holdout_mape(synth, target)
            payload[f"holdout_mape_{target}"] = (
                round(mape, 4) if mape is not None else None)

    # 2 — measured packing: count-LPT (cold) vs predicted-LPT (warm)
    # on one multi-block sweep. The count run's tie-break orders the
    # LR groups ascending by max_iter — the longest blocks START LAST,
    # the pessimal packing predicted-LPT exists to fix.
    import shutil
    corpus_dir = tempfile.mkdtemp(prefix="costmodel-corpus-")
    os.environ.pop("TRANSMOGRIFAI_PERF_MODEL", None)
    perf.set_params(perf.PerfModelParams(corpus_dir=corpus_dir, min_rows=4))
    max_iters = (96, 80, 64, 48, 40, 32, 24, 16, 8, 4)
    mesh = make_mesh(n_devices, sweep=n_devices)
    cols = _cols(n_rows)

    def sel():
        return _selector(max_iters=max_iters)

    try:
        # warm compiles off the measurement (blocks record corpus rows)
        from transmogrifai_tpu.obs.trace import TRACER
        perf.set_model(perf.CostModel())  # explicitly cold decisions
        with TRACER.span("run:costmodel-warmup", category="run",
                         new_trace=True):
            _fit(sel(), cols, n_rows)
            _fit(sel(), cols, n_rows, mesh=mesh)

        count = _measured_schedule(sel, cols, n_rows, mesh, "count")

        # refit from the corpus those runs just wrote → predicted-LPT
        model = perf.refresh()
        warm = (model is not None
                and model.predict("block_runtime", perf.block_features(
                    "logistic", (96, False), 2, n_rows, 6, 2)) is not None)
        payload["model_warm"] = bool(warm)
        predicted = _measured_schedule(sel, cols, n_rows, mesh, "predicted")
        real_mape = perf.holdout_mape(perf.get_corpus(), "block_runtime")
        payload["holdout_mape_block_runtime_measured"] = (
            round(real_mape, 4) if real_mape is not None else None)
    finally:
        perf.set_model(None)
        perf.set_params(None)
        shutil.rmtree(corpus_dir, ignore_errors=True)

    exact = (count["rows"]["best_grid"] == predicted["rows"]["best_grid"]
             and set(count["rows"]["rows"]) == set(predicted["rows"]["rows"])
             and all(json.dumps(count["rows"]["rows"][k])
                     == json.dumps(predicted["rows"]["rows"][k])
                     for k in count["rows"]["rows"]))
    payload.update({
        "winner_exact": exact,
        "mesh_utilization_frac_count_lpt": round(count["util"], 4),
        "mesh_utilization_frac_predicted_lpt": round(predicted["util"], 4),
        "packing_improvement": round(
            predicted["util"] - count["util"], 4),
        "wall_s_count_lpt": count["wall_s"],
        "wall_s_predicted_lpt": predicted["wall_s"],
        "perf_residuals": predicted["perf"],
        "n_devices": n_devices,
    })
    return payload


def _smoke() -> int:
    payload = run_costmodel_bench()
    mape = payload.get("holdout_mape_block_runtime")
    assert mape is not None and mape < MAPE_GATE, (
        f"block-runtime holdout MAPE {mape} over the {MAPE_GATE} gate")
    for target in ("ingest", "serving_bucket", "hbm"):
        m = payload.get(f"holdout_mape_{target}")
        assert m is not None and m < MAPE_GATE, (
            f"{target} holdout MAPE {m} over the {MAPE_GATE} gate")
    assert payload["winner_exact"], (
        "predicted-LPT schedule is not bit-identical to count-LPT")
    assert payload["model_warm"], (
        "measured schedule runs did not warm the model from the corpus")
    # predicted residuals were recorded (the honesty layer is live)
    assert payload["perf_residuals"].get("predictions", 0) > 0, (
        f"no perf_residual events recorded: {payload['perf_residuals']}")
    # packing: predicted-LPT must not be meaningfully WORSE than
    # count-LPT (host-CPU timing noise gets a small tolerance; bench.py
    # costmodel reports the raw pair as the headline)
    assert (payload["mesh_utilization_frac_predicted_lpt"]
            >= payload["mesh_utilization_frac_count_lpt"] - 0.1), payload
    print(json.dumps({"costmodel_smoke": "ok", **payload}))
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(_smoke())
