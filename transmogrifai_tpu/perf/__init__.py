"""perf/: a learned cost model driving the repo's tuning knobs.

The repo emits a timed profile corpus on every run (journal
`duration_s` stamps, `IngestStats`, serving latency histograms); this
package fits a small per-target predictor on it (`perf/model.py`) and
closes the loop into four consumers:

- `parallel/scheduler.py` orders grid blocks by PREDICTED seconds (true
  LPT) and sizes block widths toward a seconds-per-block target;
- `parallel/sweep.py` pre-shrinks blocks whose predicted HBM footprint
  exceeds the budget instead of paying an OOM-redo first;
- `parallel/bigdata.py` picks upload workers/depth from the predicted
  read-vs-upload balance;
- `serving/batcher.py` derives the bucket ladder from the observed
  request-size distribution + predicted per-bucket latency.

Cold start (empty corpus, or ``TRANSMOGRIFAI_PERF_MODEL=0``): every
consumer reproduces today's heuristics bit-for-bit. Every decision
records its predicted-vs-measured residual (``perf_model_abs_rel_err``
histogram + ``perf_residual`` events), so the model is continuously
scored in production; ``python bench.py costmodel`` reports holdout
MAPE per target and the measured packing improvement.
"""

from transmogrifai_tpu.perf.corpus import (
    CostCorpus, device_generation, get_corpus, harvest_journal, note,
    note_parse, note_serving)
from transmogrifai_tpu.perf.features import (
    block_features, hbm_proxy_bytes, ingest_features, parse_features,
    serving_features)
from transmogrifai_tpu.perf.model import (
    CostModel, Prediction, choose_upload_plan, fit_corpus, get_model,
    holdout_mape, observe, predict_block_seconds, predict_bucket_seconds,
    predict_drain_seconds, predict_sweep_seconds, refresh, set_model)
from transmogrifai_tpu.perf.params import (
    PerfModelParams, enabled, get_params, hbm_budget_bytes, params_scope,
    resolved_corpus_dir, set_params, target_block_s)

__all__ = [
    "CostCorpus", "CostModel", "PerfModelParams", "Prediction",
    "block_features", "choose_upload_plan", "device_generation",
    "enabled", "fit_corpus",
    "get_corpus", "get_model", "get_params", "harvest_journal",
    "hbm_budget_bytes", "hbm_proxy_bytes", "holdout_mape",
    "ingest_features", "note", "note_parse", "note_serving", "observe",
    "params_scope", "parse_features", "predict_block_seconds",
    "predict_bucket_seconds", "predict_drain_seconds",
    "predict_sweep_seconds", "resolved_corpus_dir", "refresh",
    "serving_features", "set_model", "set_params", "target_block_s",
]
