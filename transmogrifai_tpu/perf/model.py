"""CostModel: a deliberately small learned performance model.

"A Learned Performance Model for TPUs" (arxiv 2008.01040) learns
runtime from program features with a GNN; this repo's programs are a
closed family (sweep blocks, chunk uploads, serving buckets), so a
per-target **log-linear ridge** over engineered features
(`perf/features.py`) captures the same multiplicative structure —
runtime ≈ c · Πᵢ fᵢ^wᵢ — at a few hundred bytes per target, fit with
the repo's own JAX `lstsq` (no new deps) in milliseconds:

    z = log(value),  φ(x) = [1, log1p(f₁), log1p(f₂), ...]
    w = argmin ‖Φw − z‖² + λ‖w‖²      (ridge via row augmentation)

Per-prediction uncertainty comes from the RESIDUAL QUANTILES of the fit
(no distributional assumption): ``Prediction.lo``/``hi`` are the
10th/90th-percentile multiplicative error bands around the median-
calibrated point estimate — exactly the error bars bench attaches to
its (formerly bare) extrapolations.

Cold-start contract: a target with fewer than `min_rows` training rows
predicts **None**, and every consumer falls back to today's heuristics
bit-for-bit (regression-tested per call site). A fitted model
save/loads as JSON so a saved workflow ships with its predictor.

Fleet behaviour (pod-scale sweeps): every training row is stamped with
its **device generation** (`corpus.device_generation`) and fits filter
to the local generation — a shared corpus on pod storage can mix v4 and
v5 hosts without cross-training. The lazily fitted process model is
updated **online, per decision**: the ridge fit is exactly Bayesian
linear regression's posterior mean under a Gaussian prior, so each
`corpus.note` appends one row to the running sufficient statistics
(A ← A + φφᵀ, b ← b + φ·z) and re-solves w = A⁻¹b in O(k²) — no
periodic ~512-row refit cadence; batch refits remain only for FOREIGN
shard growth (another host writing the shared corpus).
"""

from __future__ import annotations

import json
import logging
import math
import os
import threading
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from transmogrifai_tpu.perf import params as perf_params
from transmogrifai_tpu.perf.corpus import (
    CostCorpus, device_generation, get_corpus)
from transmogrifai_tpu.perf.features import (
    block_features, ingest_features, serving_features)

__all__ = ["Prediction", "CostModel", "fit_corpus", "get_model",
           "set_model", "refresh", "observe", "choose_upload_plan",
           "predict_block_seconds", "predict_bucket_seconds",
           "predict_drain_seconds", "predict_sweep_seconds",
           "holdout_mape"]

log = logging.getLogger(__name__)

_EPS = 1e-6
_RIDGE = 1e-3
# residual window for the online error bands: the newest prediction
# errors define lo/hi, so the bands track hardware drift instead of
# averaging over the corpus's whole history
_RESID_WINDOW = 256


@dataclass
class Prediction:
    """One cost prediction with its uncertainty band (residual-quantile
    multiplicative error bars) and the training support behind it."""

    value: float
    lo: float
    hi: float
    n: int  # training rows behind this target

    def to_json(self) -> Dict[str, Any]:
        return {"value": round(self.value, 6), "lo": round(self.lo, 6),
                "hi": round(self.hi, 6), "n": self.n}


class _TargetFit:
    """One target's fitted log-linear ridge, optionally carrying the
    running sufficient statistics (A = ΦᵀΦ + λI, b = Φᵀz) that make it
    an online Bayesian posterior: `observe` folds one decision's
    measurement in and re-solves the posterior mean. JSON-loaded fits
    have no statistics and stay frozen."""

    def __init__(self, names: List[str], w: Sequence[float],
                 resid_q: Sequence[float], n: int,
                 A: Optional[np.ndarray] = None,
                 b: Optional[np.ndarray] = None,
                 resid: Optional[Sequence[float]] = None):
        self.names = list(names)
        self.w = np.asarray(w, np.float64)
        self.resid_q = [float(q) for q in resid_q]  # [q10, q50, q90]
        self.n = int(n)
        self.A = None if A is None else np.asarray(A, np.float64)
        self.b = None if b is None else np.asarray(b, np.float64)
        self._resid: deque = deque((float(r) for r in (resid or [])),
                                   maxlen=_RESID_WINDOW)

    def phi(self, feats: Dict[str, float]) -> np.ndarray:
        row = [1.0] + [math.log1p(max(float(feats.get(nm, 0.0)), 0.0))
                       for nm in self.names]
        return np.asarray(row, np.float64)

    def predict(self, feats: Dict[str, float]) -> Prediction:
        z = float(self.phi(feats) @ self.w)
        q10, q50, q90 = self.resid_q
        return Prediction(value=math.exp(z + q50), lo=math.exp(z + q10),
                          hi=math.exp(z + q90), n=self.n)

    def observe(self, feats: Dict[str, float], value: float) -> None:
        """One per-decision Bayesian update: record this prediction's
        residual (computed BEFORE the update — an honest error sample),
        add φφᵀ/φz to the running statistics, re-solve the posterior
        mean, and refresh the residual-quantile bands. O(k²) in the
        feature count — microseconds for these targets."""
        if self.A is None or value <= 0.0:
            return
        new = sorted(set(feats) - set(self.names))
        if new:
            # a feature this fit never saw (new family one-hot): expand
            # the statistics with the ridge prior on the new dimensions
            k_old = len(self.w)
            self.names.extend(new)
            k = 1 + len(self.names)
            A = np.eye(k, dtype=np.float64) * _RIDGE
            A[:k_old, :k_old] = self.A
            b = np.zeros(k, dtype=np.float64)
            b[:k_old] = self.b
            w = np.zeros(k, dtype=np.float64)
            w[:k_old] = self.w
            self.A, self.b, self.w = A, b, w
        phi = self.phi(feats)
        z = math.log(max(float(value), _EPS))
        if self.n > 0:
            self._resid.append(z - float(phi @ self.w))
        self.A = self.A + np.outer(phi, phi)
        self.b = self.b + phi * z
        try:
            self.w = np.linalg.solve(self.A, self.b)
        except np.linalg.LinAlgError:
            self.w = np.linalg.lstsq(self.A, self.b, rcond=None)[0]
        self.n += 1
        if len(self._resid) > 1:
            q10, q50, q90 = np.quantile(
                np.asarray(self._resid), (0.1, 0.5, 0.9))
            self.resid_q = [float(q10), float(q50), float(q90)]

    def to_json(self) -> Dict[str, Any]:
        return {"names": self.names, "w": [float(x) for x in self.w],
                "resid_q": self.resid_q, "n": self.n}

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "_TargetFit":
        return _TargetFit(d["names"], d["w"], d["resid_q"], int(d["n"]))


class CostModel:
    """Per-target predictors + the cold-start floor. `devgen` names the
    device-generation namespace the fits were trained in (None =
    unspecified, e.g. a hand-built test model)."""

    def __init__(self, min_rows: Optional[int] = None,
                 devgen: Optional[str] = None):
        self.targets: Dict[str, _TargetFit] = {}
        self.min_rows = int(min_rows if min_rows is not None
                            else perf_params.get_params().min_rows)
        self.devgen = devgen
        # online observes land from every consumer thread (scheduler
        # lanes, serving threads); the fit objects mutate in place, so
        # reads and updates share one lock — both are microseconds
        self._lock = threading.Lock()

    def predict(self, target: str,
                feats: Dict[str, float]) -> Optional[Prediction]:
        """Point estimate + error band, or None when this target is
        cold (unfitted, or fitted on fewer than `min_rows` rows) — the
        caller then uses today's heuristic unchanged."""
        with self._lock:
            fit = self.targets.get(target)
            if fit is None or fit.n < self.min_rows:
                return None
            try:
                return fit.predict(feats)
            except Exception:
                log.debug("cost model predict failed for %s", target,
                          exc_info=True)
                return None

    def observe(self, target: str, feats: Dict[str, float],
                value: float) -> None:
        """Fold one measured decision into `target`'s posterior. An
        unseen target starts from the bare ridge prior and stays cold
        (predict → None) until `min_rows` observations accumulate."""
        with self._lock:
            fit = self.targets.get(target)
            if fit is None:
                names = sorted(feats)
                k = 1 + len(names)
                fit = _TargetFit(names, np.zeros(k), [0.0, 0.0, 0.0], 0,
                                 A=np.eye(k, dtype=np.float64) * _RIDGE,
                                 b=np.zeros(k, dtype=np.float64))
                self.targets[target] = fit
            fit.observe(feats, value)

    def fit_target(self, target: str,
                   rows: List[Dict[str, Any]], ridge: float = _RIDGE) -> None:
        """Fit one target from corpus rows ({"features", "value"}).
        Non-positive values are dropped (log space); OOM rows keep their
        inflated value — they pull the HBM fit UP near the boundary,
        which is the conservative direction for a pre-dispatch gate."""
        rows = [r for r in rows if float(r.get("value", 0.0)) > 0.0]
        if not rows:
            return
        names = sorted({k for r in rows for k in r["features"]})
        import jax.numpy as jnp
        phi = np.asarray(
            [[1.0] + [math.log1p(max(float(r["features"].get(nm, 0.0)), 0.0))
                      for nm in names] for r in rows], np.float64)
        z = np.log(np.maximum(
            np.asarray([float(r["value"]) for r in rows]), _EPS))
        k = phi.shape[1]
        lam = math.sqrt(ridge)
        A = np.vstack([phi, lam * np.eye(k)])
        b = np.concatenate([z, np.zeros(k)])
        w = np.asarray(jnp.linalg.lstsq(
            jnp.asarray(A), jnp.asarray(b))[0], np.float64)
        resid = z - phi @ w
        q10, q50, q90 = (np.quantile(resid, (0.1, 0.5, 0.9))
                         if len(resid) > 1 else (0.0, 0.0, 0.0))
        fit = _TargetFit(
            names, w, [q10, q50, q90], len(rows),
            # seed the online posterior with the batch's sufficient
            # statistics so subsequent observes CONTINUE this fit
            A=phi.T @ phi + ridge * np.eye(k, dtype=np.float64),
            b=phi.T @ z, resid=resid[-_RESID_WINDOW:].tolist())
        with self._lock:
            self.targets[target] = fit

    # -- persistence ------------------------------------------------------- #

    def to_json(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "cost_model": 1, "min_rows": self.min_rows,
            "targets": {t: f.to_json() for t, f in self.targets.items()}}
        if self.devgen is not None:
            out["devgen"] = self.devgen
        return out

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "CostModel":
        m = CostModel(min_rows=d.get("min_rows"), devgen=d.get("devgen"))
        for t, fd in (d.get("targets") or {}).items():
            m.targets[t] = _TargetFit.from_json(fd)
        return m

    def save(self, path: str) -> None:
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(self.to_json(), fh)
        os.replace(tmp, path)

    @staticmethod
    def load(path: str) -> "CostModel":
        with open(path, encoding="utf-8") as fh:
            return CostModel.from_json(json.load(fh))


def fit_corpus(corpus: CostCorpus,
               min_rows: Optional[int] = None) -> CostModel:
    """Fit every known target from the corpus, restricted to this
    host's device-generation namespace (rows another generation's host
    wrote into a shared fleet corpus are someone else's physics). An
    empty corpus yields a model with no fitted targets — every
    predict() is None, every consumer cold."""
    from transmogrifai_tpu.perf.corpus import TARGETS
    gen = device_generation()
    model = CostModel(min_rows=min_rows, devgen=gen)
    for target in TARGETS:
        rows = corpus.rows(target, devgen=gen)
        if rows:
            try:
                model.fit_target(target, rows)
            except Exception:
                log.warning("cost model fit failed for target %s",
                            target, exc_info=True)
    return model


# -- process-default model -------------------------------------------------- #

_MODEL_LOCK = threading.Lock()
_MODEL: Optional[CostModel] = None
_MODEL_KEY: Optional[tuple] = None
_MODEL_VERSION: Optional[tuple] = None  # corpus.version() at fit time
# foreign-writer invalidation: another process growing the shared
# corpus file by this much since our fit triggers a refit even though
# OUR _appended counter never moved
_FOREIGN_BYTES = 1 << 20


def get_model() -> Optional[CostModel]:
    """The process's active cost model, or None when disabled. Lazily
    fitted from the active corpus and refitted when the corpus version
    moves enough (≥1 MB
    written by another), or loaded once from
    `PerfModelParams.model_path` when a fitted model ships with the
    workflow. A load FAILURE is cached too: an unreadable model_path
    falls back to the corpus fit once and must not re-open the bad
    file (with a warning) on every subsequent decision."""
    global _MODEL, _MODEL_KEY, _MODEL_VERSION
    if not perf_params.enabled():
        return None
    with _MODEL_LOCK:
        if _MODEL_KEY == ("explicit",):
            return _MODEL  # set_model() pins it against lazy refits
    p = perf_params.get_params()
    path_failed = False
    if p.model_path:
        key = ("path", p.model_path)
        fail_key = ("path-failed", p.model_path)
        with _MODEL_LOCK:
            if _MODEL_KEY == key:
                return _MODEL
            path_failed = _MODEL_KEY == fail_key
        if not path_failed:
            try:
                loaded = CostModel.load(p.model_path)
            except (OSError, ValueError, KeyError, TypeError):
                log.warning("cost model at %s unreadable; falling back "
                            "to corpus fit", p.model_path, exc_info=True)
                path_failed = True
            else:
                with _MODEL_LOCK:
                    _MODEL = loaded
                    _MODEL_KEY = key
                    return _MODEL
    corpus = get_corpus()
    if corpus is None:
        return None
    key = (("path-failed", p.model_path) if path_failed
           else ("corpus", corpus.path))
    with _MODEL_LOCK:
        version = corpus.version()
        stale = (_MODEL is None or _MODEL_KEY != key
                 or _MODEL_VERSION is None)
        if not stale:
            size_delta = abs(version[1] - _MODEL_VERSION[1])
            own_bytes = (version[3] - _MODEL_VERSION[3]
                         if len(version) > 3 and len(_MODEL_VERSION) > 3
                         else 0)
            # our OWN appends are absorbed online, per decision
            # (observe() below) — only FOREIGN shard growth (another
            # host/replica writing the shared fleet corpus) warrants a
            # batch refit; the old ~512-row own-append refit cadence is
            # gone
            stale = (size_delta - max(own_bytes, 0)) >= _FOREIGN_BYTES
        if stale:
            _MODEL = fit_corpus(corpus)
            _MODEL_KEY = key
            _MODEL_VERSION = version
        return _MODEL


def set_model(model: Optional[CostModel]) -> None:
    """Install an explicit model as the process default (tests, smoke;
    None reverts to lazy corpus fitting)."""
    global _MODEL, _MODEL_KEY, _MODEL_VERSION
    with _MODEL_LOCK:
        _MODEL = model
        _MODEL_KEY = ("explicit",) if model is not None else None
        _MODEL_VERSION = None


def refresh() -> Optional[CostModel]:
    """Drop the cached model and refit from the current corpus."""
    set_model(None)
    return get_model()


def observe(target: str, features: Dict[str, float], value: float) -> None:
    """Per-decision online update of the lazily fitted process model
    (`corpus.note` calls this after appending the training row).
    Explicit (`set_model`) and `model_path`-loaded models are pinned —
    they stay exactly what was installed/shipped. A not-yet-fitted
    model is left alone too: the next `get_model()` batch fit reads
    this row from the corpus anyway. Never raises."""
    if not perf_params.enabled():
        return
    with _MODEL_LOCK:
        model, key = _MODEL, _MODEL_KEY
    if model is None or not key or key[0] != "corpus":
        return
    try:
        model.observe(target, features, float(value))
    except Exception:
        log.debug("online cost-model update failed for %s", target,
                  exc_info=True)


# -- consumer helpers -------------------------------------------------------- #

def predict_block_seconds(family: str, static: Tuple, n_configs: int,
                          n_rows: int, n_cols: int, n_folds: int,
                          dtype_bytes: int = 4,
                          model: Optional[CostModel] = None
                          ) -> Optional[Prediction]:
    m = model if model is not None else get_model()
    if m is None:
        return None
    return m.predict("block_runtime",
                     block_features(family, static, n_configs, n_rows,
                                    n_cols, n_folds, dtype_bytes))


def predict_bucket_seconds(bucket: int,
                           model: Optional[CostModel] = None
                           ) -> Optional[Prediction]:
    """Predicted device+dispatch seconds for ONE serving batch at a
    ladder rung (`serving_bucket` target, fed by `corpus.note_serving`).
    None while the model is cold — callers must fall back to their
    observed-signal path."""
    m = model if model is not None else get_model()
    if m is None:
        return None
    return m.predict("serving_bucket", serving_features(int(bucket)))


def predict_drain_seconds(queue_rows: int, bucket: int,
                          model: Optional[CostModel] = None
                          ) -> Optional[Prediction]:
    """Predicted wall seconds to drain `queue_rows` backlogged rows
    through `bucket`-sized batches: ceil(rows/bucket) sequential batch
    executions at the predicted per-batch latency. The serving layer
    turns this into a proportional 429/503 Retry-After; the autopilot
    compares it against the deadline budget for predictive admission.
    None when the model is cold (constant Retry-After fallback)."""
    per_batch = predict_bucket_seconds(bucket, model=model)
    if per_batch is None or bucket <= 0:
        return None
    n_batches = max(1, math.ceil(max(0, int(queue_rows)) / int(bucket)))
    return Prediction(value=per_batch.value * n_batches,
                      lo=per_batch.lo * n_batches,
                      hi=per_batch.hi * n_batches,
                      n=per_batch.n)


_PLAN_WORKERS = (1, 2, 4, 8)
_PLAN_DEPTHS = (1, 2, 4, 8)


def choose_upload_plan(bytes_wire: float, chunks: int,
                       default_workers: int, default_depth: int,
                       fixed_workers: Optional[int] = None,
                       fixed_depth: Optional[int] = None,
                       model: Optional[CostModel] = None
                       ) -> Tuple[int, int, Optional[Prediction]]:
    """Pick upload (workers, depth) from the predicted read-vs-upload
    balance: predict the pipeline wall for each candidate plan and take
    the fastest (ties prefer the default — compiled-shape stability).
    Cold model → exactly today's defaults with no prediction. Explicit
    `fixed_*` values are honored (only the free axis is searched)."""
    m = model if model is not None else get_model()
    best = (fixed_workers if fixed_workers is not None else default_workers,
            fixed_depth if fixed_depth is not None else default_depth)
    if m is None:
        return best[0], best[1], None
    ws = (fixed_workers,) if fixed_workers is not None else _PLAN_WORKERS
    ds = (fixed_depth,) if fixed_depth is not None else _PLAN_DEPTHS
    best_pred = m.predict("ingest", ingest_features(
        bytes_wire, best[0], best[1], chunks))
    if best_pred is None:
        return best[0], best[1], None
    for w in ws:
        for d in ds:
            p = m.predict("ingest",
                          ingest_features(bytes_wire, w, d, chunks))
            if p is not None and p.value < best_pred.value:
                best, best_pred = (w, d), p
    return best[0], best[1], best_pred


def predict_sweep_seconds(models, n_rows: int, n_cols: int, n_folds: int,
                          dtype_bytes: int = 4,
                          model: Optional[CostModel] = None
                          ) -> Optional[Dict[str, Any]]:
    """Predicted wall seconds for a whole selector sweep — the learned
    replacement for bench's hand-rolled ``scale()`` extrapolation.
    `models` is the selector shape: [(estimator, grids), ...]. Blocks
    are cut along the REAL compile-group boundaries
    (`sweep.static_signature`), predicted independently, and summed;
    the lo/hi band sums the per-block bands (blocks run sequentially
    per chip, so the sum is the right composition). Returns None when
    ANY block is cold — a half-predicted extrapolation would be the
    dishonesty this replaces."""
    m = model if model is not None else get_model()
    if m is None:
        return None
    from transmogrifai_tpu.parallel.sweep import static_signature
    total = lo = hi = 0.0
    per_family: Dict[str, float] = {}
    n_min = None
    for est, grids in models:
        groups: Dict[Tuple, int] = {}
        for g in grids:
            key = static_signature(est, g)
            groups[key] = groups.get(key, 0) + 1
        for (family, static), n_cfg in groups.items():
            p = m.predict("block_runtime",
                          block_features(family, static, n_cfg, n_rows,
                                         n_cols, n_folds, dtype_bytes))
            if p is None:
                return None
            total += p.value
            lo += p.lo
            hi += p.hi
            per_family[family] = per_family.get(family, 0.0) + p.value
            n_min = p.n if n_min is None else min(n_min, p.n)
    return {"value": round(total, 3), "lo": round(lo, 3),
            "hi": round(hi, 3), "n_min": n_min,
            "per_family": {k: round(v, 3) for k, v in per_family.items()}}


def holdout_mape(corpus: CostCorpus, target: str,
                 holdout_frac: float = 0.3, seed: int = 7,
                 min_rows: Optional[int] = None) -> Optional[float]:
    """Mean absolute percentage error on a random holdout split of one
    target's corpus rows — the continuous scorecard `bench.py costmodel`
    reports. None when the target has too few rows to split."""
    rows = corpus.rows(target, devgen=device_generation())
    if len(rows) < 10:
        return None
    rng = np.random.default_rng(seed)
    idx = rng.permutation(len(rows))
    n_hold = max(1, int(len(rows) * holdout_frac))
    hold = [rows[i] for i in idx[:n_hold]]
    train = [rows[i] for i in idx[n_hold:]]
    model = CostModel(min_rows=min_rows if min_rows is not None else 1)
    model.fit_target(target, train)
    fit = model.targets.get(target)
    if fit is None:
        return None
    errs = []
    for r in hold:
        v = float(r["value"])
        if v <= 0:
            continue
        p = fit.predict(r["features"])
        errs.append(abs(p.value - v) / v)
    return float(np.mean(errs)) if errs else None


def main(argv=None) -> int:
    """``python -m transmogrifai_tpu.perf.model fit [--out model.json]``
    fits from the active corpus and reports per-target row counts +
    holdout MAPE; ``predict <target> k=v ...`` prints one prediction."""
    import argparse
    parser = argparse.ArgumentParser(
        prog="python -m transmogrifai_tpu.perf.model")
    sub = parser.add_subparsers(dest="cmd", required=True)
    fit_p = sub.add_parser("fit")
    fit_p.add_argument("--out", help="save the fitted model JSON here")
    pred_p = sub.add_parser("predict")
    pred_p.add_argument("target")
    pred_p.add_argument("kv", nargs="+", help="feature=value pairs")
    args = parser.parse_args(argv)
    corpus = get_corpus()
    if corpus is None:
        print(json.dumps({"error": "perf model disabled"}))
        return 1
    if args.cmd == "fit":
        model = fit_corpus(corpus)
        out: Dict[str, Any] = {"corpus": corpus.path, "targets": {}}
        for t, f in model.targets.items():
            out["targets"][t] = {
                "rows": f.n,
                "holdout_mape": holdout_mape(corpus, t)}
        if args.out:
            model.save(args.out)
            out["saved"] = args.out
        print(json.dumps(out))
        return 0
    model = get_model()
    feats = {}
    for kv in args.kv:
        k, _, v = kv.partition("=")
        feats[k] = float(v)
    p = model.predict(args.target, feats) if model is not None else None
    print(json.dumps({"target": args.target, "features": feats,
                      "prediction": p.to_json() if p else None}))
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
