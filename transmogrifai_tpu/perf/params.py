"""PerfModelParams: configuration for the learned cost model.

Lives in `perf/` (not `workflow/params.py`) for the same reason
`FeatureCacheParams` lives in `data/feature_cache.py`: the subsystem
owns its config shape, and `workflow/params.py` imports it for the
JSON-loadable `OpParams.perf_model` block. No heavy imports here —
`workflow.params` must stay importable without touching jax.

Process-default installation mirrors the feature cache: `set_params`
replaces the process default, `params_scope` installs one for a `with`
extent (used by `Workflow.train`), and every perf consumer resolves the
active params through `get_params()` at decision time. Environment
knobs override nothing structurally — they fill the DEFAULTS, so a
params file or CLI flag always wins:

- ``TRANSMOGRIFAI_PERF_MODEL=0``       kill switch (all consumers cold)
- ``TRANSMOGRIFAI_PERF_CORPUS_DIR``    corpus directory
- ``TRANSMOGRIFAI_PERF_TARGET_BLOCK_S``scheduler seconds-per-block target
- ``TRANSMOGRIFAI_PERF_HBM_BUDGET_GB`` pre-dispatch HBM gate budget
"""

from __future__ import annotations

import os
import threading
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Any, Dict, Optional

__all__ = ["PerfModelParams", "get_params", "set_params", "params_scope",
           "enabled", "resolved_corpus_dir", "target_block_s",
           "hbm_budget_bytes"]

# today's pre-dispatch budget heuristic is "none" — the HBM gate only
# fires when a budget is configured OR the model is warm enough to
# predict a footprint; the default budget matches the sweep's dispatch
# memory plan (_PAIR_MEM_BYTES in parallel/sweep.py)
_DEFAULT_HBM_BUDGET_GB = 4.0
_DEFAULT_TARGET_BLOCK_S = 30.0


@dataclass
class PerfModelParams:
    """JSON-loadable cost-model config (`OpParams.perf_model`).

    `model_path` points at a fitted model JSON (`CostModel.save`) so a
    saved workflow ships with the predictor that tuned it; when unset,
    the model is fitted lazily from the corpus. `min_rows` is the
    cold-start floor: a target with fewer training rows predicts None
    and every consumer falls back to today's heuristics exactly."""

    enabled: bool = True
    corpus_dir: Optional[str] = None      # default: env / ~/.cache/...
    model_path: Optional[str] = None      # fitted model JSON to load
    target_block_s: Optional[float] = None  # scheduler width sizing
    hbm_budget_gb: Optional[float] = None   # pre-dispatch OOM gate
    min_rows: int = 8                     # per-target cold-start floor

    _FIELDS = ("enabled", "corpus_dir", "model_path", "target_block_s",
               "hbm_budget_gb", "min_rows")

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "PerfModelParams":
        return PerfModelParams(**{k: d[k] for k in PerfModelParams._FIELDS
                                  if k in d})

    def to_json(self) -> Dict[str, Any]:
        return {k: getattr(self, k) for k in self._FIELDS}


_LOCK = threading.Lock()
_PARAMS = PerfModelParams()


def get_params() -> PerfModelParams:
    return _PARAMS


def set_params(params: Optional[PerfModelParams]) -> None:
    """Replace the process-default perf params (None → factory
    defaults)."""
    global _PARAMS
    with _LOCK:
        _PARAMS = params if params is not None else PerfModelParams()


@contextmanager
def params_scope(params):
    """Install `params` (a PerfModelParams, a JSON dict, or None) as the
    process default for the scope's extent. None is a no-op — the
    ambient params stay active, so a train without a perf_model block
    inherits the process/env configuration. Restore only when our
    install is still the active one (overlapping scopes must not wipe a
    live policy — same contract as feature_cache.cache_scope)."""
    if params is None:
        yield
        return
    if isinstance(params, dict):
        params = PerfModelParams.from_json(params)
    global _PARAMS
    with _LOCK:
        prev = _PARAMS
        _PARAMS = params
    try:
        yield
    finally:
        with _LOCK:
            if _PARAMS is params:
                _PARAMS = prev


def enabled() -> bool:
    """The master switch: env kill switch beats everything, then the
    active params."""
    if os.environ.get("TRANSMOGRIFAI_PERF_MODEL", "1") == "0":
        return False
    return bool(_PARAMS.enabled)


def resolved_corpus_dir() -> str:
    # one resolution point with the artifact store: params arg wins,
    # then the subsystem env, then <store root>/perf — so pointing
    # TRANSMOGRIFAI_STORE_DIR at shared storage moves the corpus too
    from transmogrifai_tpu.store.config import resolve_dir
    return resolve_dir("perf", env="TRANSMOGRIFAI_PERF_CORPUS_DIR",
                       explicit=_PARAMS.corpus_dir)


def target_block_s() -> float:
    if _PARAMS.target_block_s is not None:
        return float(_PARAMS.target_block_s)
    try:
        return float(os.environ.get("TRANSMOGRIFAI_PERF_TARGET_BLOCK_S",
                                    _DEFAULT_TARGET_BLOCK_S))
    except ValueError:
        return _DEFAULT_TARGET_BLOCK_S


def hbm_budget_bytes() -> float:
    if _PARAMS.hbm_budget_gb is not None:
        return float(_PARAMS.hbm_budget_gb) * 2.0 ** 30
    try:
        gb = float(os.environ.get("TRANSMOGRIFAI_PERF_HBM_BUDGET_GB",
                                  _DEFAULT_HBM_BUDGET_GB))
    except ValueError:
        gb = _DEFAULT_HBM_BUDGET_GB
    return gb * 2.0 ** 30
