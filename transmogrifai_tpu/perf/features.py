"""Feature engineering for the cost model: one dict of numeric features
per decision, shared by the RECORDING side (parallel/sweep.py journaling
measured block wall times) and the PREDICTION side (the scheduler, the
HBM gate, bench extrapolations) — the two must agree on names or the
model silently predicts garbage for half its consumers.

The static-signature layouts mirrored here are the module-level
`_static_<family>` functions in `parallel/sweep.py` (the compile-group
keys the scheduler already cuts blocks along); this module is kept
import-light (numpy only) so `perf.params`/`workflow.params` never drag
jax in.
"""

from __future__ import annotations

from typing import Dict, Tuple

__all__ = ["block_features", "hbm_proxy_bytes", "ingest_features",
           "parse_features", "serving_features"]


def block_features(family: str, static: Tuple, n_configs: int,
                   n_rows: int, n_cols: int, n_folds: int,
                   dtype_bytes: int = 4) -> Dict[str, float]:
    """Features of one sweep grid block (a compile group, or a scheduler
    sub-block of one): the family one-hot plus the static-signature
    facts that drive its runtime — iteration counts for linear-likes,
    learners × nodes × bins for trees — and the training-matrix shape.
    Unknown families degrade to the shape facts alone."""
    f: Dict[str, float] = {
        "n_configs": float(n_configs),
        "n_rows": float(n_rows),
        "n_cols": float(n_cols),
        "n_folds": float(n_folds),
        "dtype_bytes": float(dtype_bytes),
        f"fam_{family}": 1.0,
    }
    try:
        if family == "logistic":
            f["iters"] = float(static[0])
            f["enet"] = 1.0 if static[1] else 0.0
        elif family == "linreg":
            f["enet"] = 1.0 if static[0] else 0.0
        elif family == "svc":
            f["iters"] = float(static[0])
        elif family == "glm":
            f["iters"] = float(static[1])
        elif family == "mlp":
            hidden, iters = static[0], static[1]
            f["units"] = float(sum(int(h) for h in hidden))
            f["iters"] = float(iters)
        elif family in ("forest", "gbt"):
            learners, bins = int(static[0]), int(static[1])
            depth = int(static[3])
            f["learners"] = float(learners)
            f["bins"] = float(bins)
            f["depth"] = float(depth)
            f["nodes"] = float(2 ** min(depth, 14))
    except (IndexError, TypeError, ValueError):
        pass  # foreign static layout: shape facts still predict coarsely
    return f


def hbm_proxy_bytes(feats: Dict[str, float]) -> float:
    """Analytic peak-HBM proxy for a block, in bytes — the 'observed
    peak-HBM proxy' training target. Tree families: per-pair bin
    one-hots (n·d·bins bf16) plus deepest-level routing one-hots
    (n·nodes bf16), times the grid×fold pairs simultaneously live
    (mirrors `_tree_pair_width`'s memory bound in parallel/sweep.py).
    Linear-likes: the per-config parameter/logit working set on top of
    the shared X."""
    n = feats.get("n_rows", 0.0)
    d = feats.get("n_cols", 0.0)
    pairs = feats.get("n_configs", 1.0) * max(feats.get("n_folds", 1.0), 1.0)
    if feats.get("nodes"):
        per_pair = n * (d * max(feats.get("bins", 1.0), 1.0)
                        + feats["nodes"]) * 2.0
        return pairs * per_pair
    # linear-likes: X (shared) + per-pair logits/params f32
    return n * d * feats.get("dtype_bytes", 4.0) + pairs * n * 4.0


def ingest_features(bytes_wire: float, workers: int, depth: int,
                    chunks: int, cache_hit: bool = False
                    ) -> Dict[str, float]:
    """Features of one pipelined upload (data/pipeline.py): wire bytes,
    pipeline shape, and whether the bytes came from a cache artifact
    (artifact replay has different read characteristics than a store
    sweep, so the model must be able to tell them apart)."""
    return {"bytes_wire": float(bytes_wire), "workers": float(workers),
            "depth": float(depth), "chunks": float(chunks),
            "cache_hit": 1.0 if cache_hit else 0.0}


def serving_features(bucket: int) -> Dict[str, float]:
    """Features of one serving device batch: the padded bucket size is
    the compiled shape, which is what drives the latency."""
    return {"bucket": float(bucket)}


def parse_features(n_rows: int, n_cols: int) -> Dict[str, float]:
    """Features of one host-side request parse (row codec / columnar
    convert): cost is ~affine in rows with a per-column fixed term, so
    rows, cols, and their product carry the fit."""
    return {"rows": float(n_rows), "cols": float(n_cols),
            "cells": float(n_rows * n_cols)}
