"""CostCorpus: the append-only JSONL profile corpus the cost model fits on.

Every run already times its work — sweep blocks journal `duration_s`,
ingest pipelines fill `IngestStats`, serving batches observe latency
histograms. This module persists those measurements as training rows:

    {"target": "block_runtime", "features": {...}, "value": 12.3,
     "predicted": 11.8, "ts": 1690000000}

one JSON object per line, appended with flush (no fsync — the corpus is
an optimization; losing the tail costs training rows, not correctness)
and read torn-tail-tolerantly. Rows accumulate across runs in one
directory (`perf.params.resolved_corpus_dir`), so the model a process
fits reflects every run before it — the tf.data-autotuning-style
closed loop (arxiv 2101.12127) over the repo's own history.

`note()` is the single recording entry point every consumer calls: it
appends the training row AND, when a prediction was made, scores it —
the absolute relative error lands in the process-wide
``perf_model_abs_rel_err`` histogram (exposed on serving /metrics) and
as a ``perf_residual`` event in the run's trace/event log (rolled into
the goodput payload), so the model is continuously scored in
production. Recording NEVER raises: a full disk degrades the model,
not the sweep.

`harvest_journal` lifts block rows out of `SweepJournal` files whose
records carry the static-signature ``facts`` stamp (runtime/journal.py)
— resumed runs contribute training rows even when this process never
executed their blocks.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Dict, Iterable, List, Optional

from transmogrifai_tpu.perf import params as perf_params

__all__ = ["CostCorpus", "get_corpus", "note", "note_serving",
           "note_parse", "harvest_journal", "device_generation",
           "CORPUS_FILE"]

log = logging.getLogger(__name__)

CORPUS_FILE = "corpus.jsonl"

# a replica name (FleetConfig.replica, exported by the operator) moves
# this process's appends into its own shard — corpus-<replica>.jsonl —
# so K replicas on one shared corpus dir never interleave writes into
# one file; readers merge every shard
ENV_REPLICA = "TRANSMOGRIFAI_PERF_REPLICA"

# device-generation namespace override (tests / heterogeneous-pod ops);
# default is derived from the local accelerator's device_kind
ENV_DEVGEN = "TRANSMOGRIFAI_PERF_DEVGEN"

# targets the model learns; anything else is ignored at fit time
TARGETS = ("block_runtime", "hbm", "ingest", "serving_bucket",
           "serving_parse")

_DEVGEN_LOCK = threading.Lock()
_DEVGEN: Optional[str] = None  # guarded-by: _DEVGEN_LOCK


def device_generation() -> str:
    """The accelerator generation this process measures on, as a slug
    (``cpu``, ``tpu_v4``, ...). A fleet corpus on shared storage mixes
    hosts of different generations; rows are stamped with this so each
    host fits only the timings its own hardware produced — a v4 block
    time is training noise to a v5 scheduler. Env-overridable; falls
    back to ``unknown`` before the backend is importable."""
    global _DEVGEN
    with _DEVGEN_LOCK:
        if _DEVGEN is not None:
            return _DEVGEN
    env = os.environ.get(ENV_DEVGEN)
    if env:
        gen = env
    else:
        try:
            import jax
            import re as _re
            kind = jax.devices()[0].device_kind
            gen = _re.sub(r"[^a-z0-9]+", "_", str(kind).lower()).strip("_") \
                or "unknown"
        except Exception:
            return "unknown"  # backend not up yet: do NOT cache
    with _DEVGEN_LOCK:
        _DEVGEN = gen
    return gen


class CostCorpus:
    """Append-only JSONL training corpus: this process writes ONE shard
    (`corpus.jsonl`, or `corpus-<replica>.jsonl` when a replica name is
    set), readers merge every shard in the directory."""

    def __init__(self, dir_path: str, replica: Optional[str] = None):
        self.dir = dir_path
        if replica is None:
            replica = os.environ.get(ENV_REPLICA) or None
        self.replica = replica
        name = f"corpus-{replica}.jsonl" if replica else CORPUS_FILE
        self.path = os.path.join(dir_path, name)
        self._lock = threading.Lock()
        self._appended = 0  # rows this process added (fit invalidation)
        self._appended_bytes = 0  # bytes of those rows (foreign-delta calc)
        self._seq = 0  # per-process append sequence (merge tie-break)

    def _shard_paths(self) -> List[str]:
        """Every corpus shard in the directory, own shard included —
        the unsharded `corpus.jsonl` plus each `corpus-<replica>.jsonl`."""
        try:
            names = os.listdir(self.dir)
        except OSError:
            return [self.path]
        shards = sorted(
            os.path.join(self.dir, n) for n in names
            if n == CORPUS_FILE
            or (n.startswith("corpus-") and n.endswith(".jsonl")))
        return shards or [self.path]

    def append(self, target: str, features: Dict[str, float], value: float,
               predicted: Optional[float] = None, **extra: Any) -> bool:
        """Append one training row; returns False (and logs at debug) on
        any failure instead of raising."""
        rec: Dict[str, Any] = {
            "target": target,
            "features": {k: float(v) for k, v in features.items()},
            "value": float(value),
            "ts": int(time.time()),
            # merge identity: (ts, replica, seq) totally orders the
            # fleet-merged view — ts alone ties constantly at int-second
            # resolution across K replica shards
            "replica": self.replica or "",
            # device-generation namespace: fits filter on this
            "devgen": device_generation(),
        }
        if predicted is not None:
            rec["predicted"] = float(predicted)
        if extra:
            rec.update(extra)
        try:
            with self._lock:
                rec["seq"] = self._seq
                self._seq += 1
                line = json.dumps(rec)
                # the corpus IS an append-only log: the lock exists to
                # serialize the disk appends (torn-tail repair + write
                # must be atomic per row), so I/O under it is the design
                # conc-ok: C003 (append-log serializer)
                os.makedirs(self.dir, exist_ok=True)
                # conc-ok: C003 (append-log serializer)
                with open(self.path, "a+b") as fh:
                    # a torn tail from a killed writer has no newline:
                    # appending straight onto it would corrupt THIS row
                    # too — terminate the torn line first (the reader
                    # skips it, this row survives)
                    fh.seek(0, os.SEEK_END)
                    if fh.tell() > 0:
                        fh.seek(-1, os.SEEK_END)
                        if fh.read(1) != b"\n":
                            fh.write(b"\n")
                    fh.write(line.encode("utf-8") + b"\n")
                    fh.flush()
                self._appended += 1
                self._appended_bytes += len(line) + 1
            return True
        except (OSError, ValueError, TypeError):
            log.debug("perf corpus append failed", exc_info=True)
            return False

    def rows(self, target: Optional[str] = None,
             max_rows: int = 200_000,
             devgen: Optional[str] = None) -> List[Dict[str, Any]]:
        """Parsed corpus rows (newest-last), skipping torn/garbage lines.
        `max_rows` keeps a years-old corpus from ballooning fit time —
        the NEWEST rows are kept (they reflect the current hardware).
        `devgen` filters to one device-generation namespace (rows
        without a stamp — pre-namespacing corpora — are kept, they came
        from the same machine as today's unsharded readers).

        The merge is totally ordered by (ts, replica, seq): replica
        shards on a fleet store carry identical int-second `ts` values
        constantly, and a ts-only sort leaves same-second interleaving
        to incidental shard listing order — the max_rows trim would
        then drop one replica's rows wholesale and dedupe keys (e.g.
        harvest block_keys) could vanish from the kept window. Rows
        predating the stamps tie-break on (shard name, line number),
        which is the same order the old stable sort preserved."""
        keyed: List[tuple] = []
        for path in self._shard_paths():
            shard_name = os.path.basename(path)
            try:
                with open(path, encoding="utf-8", errors="replace") as fh:
                    for lineno, line in enumerate(fh):
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            rec = json.loads(line)
                        except ValueError:
                            continue  # torn tail / garbage line
                        if not isinstance(rec, dict):
                            continue
                        if target is not None and \
                                rec.get("target") != target:
                            continue
                        if devgen is not None and \
                                rec.get("devgen") not in (None, devgen):
                            continue
                        if isinstance(rec.get("features"), dict) and \
                                isinstance(rec.get("value"), (int, float)):
                            ts = rec.get("ts", 0)
                            if not isinstance(ts, (int, float)):
                                ts = 0
                            replica = rec.get("replica")
                            if not isinstance(replica, str):
                                replica = shard_name
                            seq = rec.get("seq")
                            if not isinstance(seq, int):
                                seq = lineno
                            keyed.append((ts, replica, seq, len(keyed),
                                          rec))
            except OSError:
                continue
        keyed.sort(key=lambda t: t[:4])
        return [t[4] for t in keyed[-max_rows:]]

    def version(self) -> tuple:
        """Cheap change token for fit caching: (total shard bytes, rows
        appended by this process, bytes this process appended — the
        foreign-growth delta is total minus own)."""
        size = 0
        for path in self._shard_paths():
            try:
                size += os.path.getsize(path)
            except OSError:
                pass
        return (self.path, size, self._appended, self._appended_bytes)

    def __len__(self) -> int:
        return len(self.rows())


_CORPUS_LOCK = threading.Lock()
_CORPUS: Dict[str, CostCorpus] = {}


def get_corpus() -> Optional[CostCorpus]:
    """The active corpus (per resolved directory), or None when the
    perf model is disabled."""
    if not perf_params.enabled():
        return None
    d = perf_params.resolved_corpus_dir()
    key = f"{d}\x00{os.environ.get(ENV_REPLICA, '')}"
    with _CORPUS_LOCK:
        c = _CORPUS.get(key)
        if c is None:
            c = CostCorpus(d)
            _CORPUS[key] = c
        return c


def note(target: str, features: Dict[str, float], predicted,
         measured: float, example: bool = True, **extra: Any) -> None:
    """Record one consumer decision: the measured value as a training
    row (when `example`), and — when a prediction was made — the
    predicted-vs-measured residual into the process metrics registry
    (``perf_model_abs_rel_err`` histogram) and the run's event log
    (``perf_residual``). `predicted` is a `model.Prediction`, a float,
    or None (cold). Never raises."""
    try:
        pred_v: Optional[float] = None
        if predicted is not None:
            pred_v = float(getattr(predicted, "value", predicted))
        if example:
            corpus = get_corpus()
            if corpus is not None:
                corpus.append(target, features, measured,
                              predicted=pred_v, **extra)
                # online per-decision Bayesian update: the process
                # model absorbs this measurement NOW (sufficient-
                # statistics update, perf/model.py) instead of waiting
                # for a periodic batch refit
                from transmogrifai_tpu.perf.model import observe
                observe(target, features, measured)
        if pred_v is not None and measured > 0:
            err = abs(pred_v - measured) / max(abs(measured), 1e-9)
            from transmogrifai_tpu.obs.metrics import get_registry
            get_registry().histogram(
                "perf_model_abs_rel_err",
                "cost-model |predicted-measured|/measured per decision",
                target=target).observe(err)
            from transmogrifai_tpu.obs.export import record_event
            record_event("perf_residual", target=target,
                         abs_rel_err=round(err, 4),
                         predicted=round(pred_v, 6),
                         measured=round(measured, 6))
    except Exception:
        log.debug("perf residual recording failed", exc_info=True)


# serving batches arrive at request rate: record the first few per
# bucket densely (cold corpus needs rows fast), then sample — the
# corpus must not grow one line per scored batch forever
_SERVING_COUNTS: Dict[int, int] = {}
_SERVING_LOCK = threading.Lock()
_SERVING_DENSE = 64
_SERVING_SAMPLE = 16


def note_serving(bucket: int, latency_s: float, predicted=None) -> None:
    """Sampled recording of one serving device batch (bucket, latency).
    When no prediction is passed, the active model's own per-bucket
    estimate is scored — the honesty layer must see serving residuals
    whenever the ladder decision was model-driven (the predict is a
    dot product, and only on sampled batches)."""
    with _SERVING_LOCK:
        n = _SERVING_COUNTS.get(bucket, 0)
        _SERVING_COUNTS[bucket] = n + 1
    if n >= _SERVING_DENSE and n % _SERVING_SAMPLE != 0:
        return
    from transmogrifai_tpu.perf.features import serving_features
    feats = serving_features(bucket)
    if predicted is None:
        try:
            from transmogrifai_tpu.perf.model import get_model
            model = get_model()
            if model is not None:
                predicted = model.predict("serving_bucket", feats)
        except Exception:
            predicted = None
    note("serving_bucket", feats, predicted, latency_s)


# host-parse recordings arrive once per REQUEST — denser than batches;
# same dense-then-sampled cadence as serving batches, keyed by rows
_PARSE_COUNTS: Dict[int, int] = {}
_PARSE_DENSE = 64
_PARSE_SAMPLE = 64


def note_parse(n_rows: int, n_cols: int, seconds: float) -> None:
    """Sampled recording of one host-side request parse (the row codec
    / columnar convert): rows+cols → measured seconds becomes a
    ``serving_parse`` training row, so ladder derivation and other
    host-cost consumers can PREDICT what a b-row request costs on host
    instead of treating parse as free. Residuals are not scored here —
    parse predictions are consumed inside derive_ladder, which has no
    per-decision measurement to compare against."""
    with _SERVING_LOCK:
        n = _PARSE_COUNTS.get(n_rows, 0)
        _PARSE_COUNTS[n_rows] = n + 1
    if n >= _PARSE_DENSE and n % _PARSE_SAMPLE != 0:
        return
    from transmogrifai_tpu.perf.features import parse_features
    note("serving_parse", parse_features(n_rows, n_cols), None, seconds)


def harvest_journal(paths: Iterable[str],
                    corpus: Optional[CostCorpus] = None) -> int:
    """Lift block-runtime training rows out of sweep-journal files whose
    records carry the ``facts`` stamp (one row per unique block, not per
    config — the block ran as ONE program). Appends into `corpus` (or
    the active one) and returns how many rows were added. Unreadable
    files and fact-less records (pre-PR-9 journals) are skipped.

    Idempotent against the corpus: blocks whose ``block_key`` is
    already recorded — by a previous harvest, or LIVE by the run that
    wrote the journal (the sweep stamps its corpus rows with the same
    key) — are skipped, so re-running the harvest CLI never duplicates
    training rows. (A block with identical grids re-measured in a
    LATER run records live under the same key; its journal harvest is
    skipped as redundant — harvesting is a backfill for runs whose
    live rows were lost, not a second measurement channel.)"""
    corpus = corpus if corpus is not None else get_corpus()
    if corpus is None:
        return 0
    added = 0
    seen: set = {r.get("block_key")
                 for r in corpus.rows("block_runtime")} - {None}
    for path in paths:
        try:
            with open(path, encoding="utf-8", errors="replace") as fh:
                lines = fh.readlines()
        except OSError:
            continue
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            facts = rec.get("facts") if isinstance(rec, dict) else None
            if not isinstance(facts, dict):
                continue
            block_key = facts.get("block_key")
            block_s = facts.get("block_s")
            if block_key in seen or not isinstance(block_s, (int, float)):
                continue
            seen.add(block_key)
            feats = {k: float(v) for k, v in facts.items()
                     if k not in ("block_key", "block_s")
                     and isinstance(v, (int, float))}
            if corpus.append("block_runtime", feats, float(block_s),
                             source="journal", block_key=block_key):
                added += 1
    return added


def main(argv=None) -> int:
    """``python -m transmogrifai_tpu.perf.corpus <journal files/dirs>`` —
    harvest journal records into the active corpus and print a summary."""
    import argparse
    import glob as _glob
    parser = argparse.ArgumentParser(
        prog="python -m transmogrifai_tpu.perf.corpus",
        description="harvest sweep-journal records into the perf corpus")
    parser.add_argument("paths", nargs="+",
                        help="journal files, or directories to scan for "
                             "*.journal* files")
    args = parser.parse_args(argv)
    files: List[str] = []
    for p in args.paths:
        if os.path.isdir(p):
            files.extend(sorted(_glob.glob(os.path.join(
                _glob.escape(p), "*.journal*"))))
        else:
            files.append(p)
    corpus = get_corpus()
    if corpus is None:
        print(json.dumps({"error": "perf model disabled "
                                   "(TRANSMOGRIFAI_PERF_MODEL=0)"}))
        return 1
    added = harvest_journal(files, corpus)
    print(json.dumps({"harvested_rows": added, "corpus": corpus.path,
                      "total_rows": len(corpus)}))
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
