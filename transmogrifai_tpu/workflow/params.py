"""OpParams: JSON-loadable run configuration.

Reference parity: `features/src/main/scala/com/salesforce/op/OpParams.scala:81-97`
(stageParams, readerParams, model/write/metrics locations, streaming batch
duration, custom tags, metric flags, customParams; JSON load at :300-308).
Applied to stages reflectively at `Workflow.set_parameters`
(OpWorkflow.scala:179-201 analogue — here: matched by stage class name or
uid, set via params dict + attribute).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from transmogrifai_tpu.continual.params import ContinualParams
from transmogrifai_tpu.data.feature_cache import FeatureCacheParams
from transmogrifai_tpu.perf.params import PerfModelParams


@dataclass
class ReaderParams:
    """Per-reader runtime params (ReaderParams analogue): data path +
    format + anything reader-specific."""

    path: Optional[str] = None
    format: str = "csv"          # csv | parquet | stream
    key_column: Optional[str] = None
    batch_size: int = 1024
    custom: Dict[str, Any] = field(default_factory=dict)

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "ReaderParams":
        known = {k: d[k] for k in ("path", "format", "key_column",
                                   "batch_size") if k in d}
        custom = {k: v for k, v in d.items()
                  if k not in ("path", "format", "key_column", "batch_size")}
        return ReaderParams(custom=custom, **known)

    def to_json(self) -> Dict[str, Any]:
        return {"path": self.path, "format": self.format,
                "key_column": self.key_column, "batch_size": self.batch_size,
                **self.custom}


@dataclass
class ServingParams:
    """Online-serving runtime params (the `serving/` subsystem's
    JSON-loadable config: the `serve` run type / CLI subcommand builds a
    `serving.ServingConfig` + HTTP frontend from this)."""

    host: str = "127.0.0.1"
    port: int = 8080               # 0 = OS-assigned free port
    max_batch: int = 64
    min_bucket: int = 1
    buckets: Optional[list] = None  # explicit ladder; overrides max_batch
    max_queue: int = 256
    batch_wait_ms: float = 2.0
    default_deadline_ms: float = 2000.0
    warm_on_load: bool = True
    keep_versions: int = 2
    # derive the bucket ladder from observed request sizes + the cost
    # model's predicted per-bucket latency (serving/batcher.derive_ladder)
    auto_ladder: bool = False
    # FeatureCacheParams JSON dict: installed as the serving process's
    # device-matrix cache policy (resident matrices survive hot-swaps)
    feature_cache: Optional[Dict[str, Any]] = None
    # persistent XLA compilation cache at serving startup
    # (utils/compile_cache.py, 0s persistence threshold): a replica or
    # same-shaped swap warms on cache hits instead of recompiling the
    # bucket ladder; None = TRANSMOGRIFAI_SERVING_COMPILE_CACHE env
    # (cli `serve` defaults it on)
    compile_cache: Optional[bool] = None
    compile_cache_dir: Optional[str] = None
    # write/read the AOT warmup manifest beside each model artifact so
    # warm starts report `serving_compile_cache_saved_s`
    warmup_manifest: bool = True
    # FleetConfig JSON block (serving/fleet.py): when set, `cli serve`
    # boots a multi-model FleetService (named models, per-tenant
    # quotas/priorities, shared bucket programs) instead of the
    # single-model service
    fleet: Optional[Dict[str, Any]] = None
    # serving/resilience.ResilienceParams JSON: health state machine,
    # circuit breaker + degraded fallback, hang watchdog (None =
    # defaults, enabled; {"enabled": false} turns the layer off)
    resilience: Optional[Dict[str, Any]] = None
    # quantized inference mode ("int8"/"int4", or "int8-calibrated"/
    # "int4-calibrated" for fit-time fleet-wide ranges with bit-stable
    # repeat scores): request matrix on an affine narrow wire +
    # narrowed fitted-table dtypes inside the fused bucket programs
    # (workflow/compiled.ScoringQuant; None = exact f32 scoring)
    quantize: Optional[str] = None
    # request-scoped tracing + tail sampling (obs/trace.TracingParams
    # JSON; None = defaults, ON; {"enabled": false} disables)
    tracing: Optional[Dict[str, Any]] = None
    # SLO burn-rate engine (obs/slo.SLOParams JSON; None = off)
    slo: Optional[Dict[str, Any]] = None
    # crash flight recorder config ({"enabled", "dir", "capacity",
    # "min_interval_s"}; None = enabled with defaults)
    flight: Optional[Dict[str, Any]] = None
    # SLO-burn serving autopilot (serving/autopilot.AutopilotParams
    # JSON; fleet runs only — needs the fleet block + an slo block to
    # close the loop on; None = no controller)
    autopilot: Optional[Dict[str, Any]] = None

    _FIELDS = ("host", "port", "max_batch", "min_bucket", "buckets",
               "max_queue", "batch_wait_ms", "default_deadline_ms",
               "warm_on_load", "keep_versions", "auto_ladder",
               "feature_cache", "compile_cache", "compile_cache_dir",
               "warmup_manifest", "fleet", "resilience", "quantize",
               "tracing", "slo", "flight", "autopilot")

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "ServingParams":
        return ServingParams(**{k: d[k] for k in ServingParams._FIELDS
                                if k in d})

    def to_json(self) -> Dict[str, Any]:
        return {k: getattr(self, k) for k in self._FIELDS}

    def to_config(self):
        """The serving.ServingConfig view (service knobs only — host/port
        belong to the HTTP frontend)."""
        from transmogrifai_tpu.serving.service import ServingConfig
        return ServingConfig(
            max_batch=self.max_batch, min_bucket=self.min_bucket,
            buckets=self.buckets, max_queue=self.max_queue,
            batch_wait_ms=self.batch_wait_ms,
            default_deadline_ms=self.default_deadline_ms,
            warm_on_load=self.warm_on_load,
            keep_versions=self.keep_versions,
            auto_ladder=self.auto_ladder,
            feature_cache=self.feature_cache,
            compile_cache=self.compile_cache,
            compile_cache_dir=self.compile_cache_dir,
            warmup_manifest=self.warmup_manifest,
            resilience=self.resilience,
            quantize=self.quantize,
            tracing=self.tracing,
            slo=self.slo,
            flight=self.flight)

    def to_fleet_config(self):
        """The serving.fleet.FleetConfig view of the `fleet` block, with
        the service-level serving knobs as the members' shared defaults
        (each model spec may still override per-member)."""
        from transmogrifai_tpu.serving.fleet import FleetConfig
        if not self.fleet:
            raise ValueError("serving params carry no `fleet` block")
        block = dict(self.fleet)
        serving = {
            "max_batch": self.max_batch, "min_bucket": self.min_bucket,
            "buckets": self.buckets, "max_queue": self.max_queue,
            "batch_wait_ms": self.batch_wait_ms,
            "default_deadline_ms": self.default_deadline_ms,
            "warm_on_load": self.warm_on_load,
            "keep_versions": self.keep_versions,
            "auto_ladder": self.auto_ladder,
            "feature_cache": self.feature_cache,
            "warmup_manifest": self.warmup_manifest,
            **(block.pop("serving", None) or {})}
        if self.tracing is not None:
            serving.setdefault("tracing", self.tracing)
        if self.flight is not None:
            serving.setdefault("flight", self.flight)
        block.setdefault("compile_cache", self.compile_cache)
        block.setdefault("compile_cache_dir", self.compile_cache_dir)
        if self.resilience is not None:
            block.setdefault("resilience", self.resilience)
        if self.slo is not None:
            block.setdefault("slo", self.slo)
        if self.autopilot is not None:
            block.setdefault("autopilot", self.autopilot)
        return FleetConfig.from_json({**block, "serving": serving})


@dataclass
class MeshParams:
    """Device-mesh configuration for distributed runs.

    `Workflow.train()` accepts a `jax.sharding.Mesh` directly; this is
    the JSON-loadable form the runner/CLI build one from. A >1-wide
    sweep axis makes every `ModelSelector` in the run schedule its grid
    blocks across the mesh through the work-stealing scheduler
    (`parallel/scheduler.py`); devices left on the data axis shard each
    worker's row data (`parallel/mesh.py`). `n_slices` lays the mesh
    out for a multi-slice pod via `make_multislice_mesh` (slice
    boundaries on the sweep axis, DCN-friendly)."""

    n_devices: Optional[int] = None   # default: every visible device
    sweep: Optional[int] = None       # sweep-axis width (default: all)
    n_slices: Optional[int] = None    # multislice layout when set
    data_per_slice: Optional[int] = None

    _FIELDS = ("n_devices", "sweep", "n_slices", "data_per_slice")

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "MeshParams":
        return MeshParams(**{k: d[k] for k in MeshParams._FIELDS if k in d})

    def to_json(self) -> Dict[str, Any]:
        return {k: getattr(self, k) for k in self._FIELDS}

    def build(self):
        """The configured jax.sharding.Mesh (validates divisibility —
        a config asking for devices it cannot use must fail loudly, not
        silently train on a subset)."""
        from transmogrifai_tpu.parallel.mesh import (
            make_mesh, make_multislice_mesh)
        if self.n_slices:
            if self.sweep is not None:
                # the multislice sweep width is n_slices × per/data_per_slice
                # — a `sweep` request would be silently ignored
                raise ValueError(
                    "mesh params: `sweep` cannot be combined with "
                    "`n_slices`; control the lane count via "
                    "`data_per_slice` (sweep = n_slices × "
                    "devices_per_slice / data_per_slice)")
            per = None
            if self.n_devices is not None:
                if self.n_devices % self.n_slices != 0:
                    raise ValueError(
                        f"mesh params: n_devices={self.n_devices} does "
                        f"not divide into n_slices={self.n_slices}")
                per = self.n_devices // self.n_slices
            return make_multislice_mesh(
                self.n_slices, devices_per_slice=per,
                data_per_slice=self.data_per_slice)
        if self.data_per_slice is not None:
            # only the multislice layout reads it — on the flat mesh the
            # requested per-worker data sharding would be silently dropped
            raise ValueError(
                "mesh params: `data_per_slice` requires `n_slices`; on a "
                "flat mesh set `sweep` (data width = n_devices / sweep)")
        return make_mesh(self.n_devices, sweep=self.sweep)


@dataclass
class SweepCheckpointParams:
    """Resumable-sweep configuration: where `ModelSelector` persists its
    per-family checkpoints and per-block `SweepJournal` files
    (runtime/journal.py). With `checkpoint_dir` set, `Workflow.train()`
    threads it onto every selector in the DAG that has none of its own,
    so a preempted training run re-invoked with the same params resumes
    at the first un-journaled grid block."""

    checkpoint_dir: Optional[str] = None
    fsync: bool = True        # journal durability (relax for throwaway runs)

    _FIELDS = ("checkpoint_dir", "fsync")

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "SweepCheckpointParams":
        return SweepCheckpointParams(
            **{k: d[k] for k in SweepCheckpointParams._FIELDS if k in d})

    def to_json(self) -> Dict[str, Any]:
        return {k: getattr(self, k) for k in self._FIELDS}


@dataclass
class OpParams:
    """Runtime workflow configuration (OpParams.scala:81-97)."""

    stage_params: Dict[str, Dict[str, Any]] = field(default_factory=dict)
    reader_params: Dict[str, ReaderParams] = field(default_factory=dict)
    model_location: Optional[str] = None
    write_location: Optional[str] = None
    metrics_location: Optional[str] = None
    # Perfetto/Chrome-trace output path for the run's span timeline
    # (the CLI's --trace-out); a sibling .events.jsonl gets the
    # structured event log with the run correlation id
    trace_location: Optional[str] = None
    batch_duration_secs: Optional[int] = None
    custom_tag_name: Optional[str] = None
    custom_tag_value: Optional[str] = None
    log_stage_metrics: bool = False
    collect_stage_metrics: bool = True
    custom_params: Dict[str, Any] = field(default_factory=dict)
    serving: Optional[ServingParams] = None
    sweep_checkpoint: Optional[SweepCheckpointParams] = None
    # device-mesh config: train runs build the mesh and pass it to
    # Workflow.train(mesh=...), turning the selector sweep into a
    # distributed schedule (parallel/scheduler.py)
    mesh: Optional[MeshParams] = None
    # persistent device-matrix cache (data/feature_cache.py):
    # `Workflow.train()` installs this as the process default for the
    # run's extent, so every big-data matrix build under the train
    # resolves the run's cache policy
    feature_cache: Optional[FeatureCacheParams] = None
    # continuous-training loop thresholds (continual/params.py): drift
    # triggers, warm-refit budget, promotion gate, rollback policy
    continual: Optional[ContinualParams] = None
    # learned cost model (perf/): corpus/model locations and the knobs
    # it drives (scheduler block sizing, HBM gate); installed for the
    # train's extent by `Workflow.train()` like the feature cache
    perf_model: Optional[PerfModelParams] = None

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "OpParams":
        readers = {k: ReaderParams.from_json(v)
                   for k, v in (d.get("reader_params") or {}).items()}
        serving = (ServingParams.from_json(d["serving"])
                   if d.get("serving") else None)
        sweep_ckpt = (SweepCheckpointParams.from_json(d["sweep_checkpoint"])
                      if d.get("sweep_checkpoint") else None)
        feature_cache = (FeatureCacheParams.from_json(d["feature_cache"])
                         if d.get("feature_cache") else None)
        mesh = MeshParams.from_json(d["mesh"]) if d.get("mesh") else None
        continual = (ContinualParams.from_json(d["continual"])
                     if d.get("continual") else None)
        perf_model = (PerfModelParams.from_json(d["perf_model"])
                      if d.get("perf_model") else None)
        return OpParams(
            stage_params=dict(d.get("stage_params") or {}),
            reader_params=readers,
            model_location=d.get("model_location"),
            write_location=d.get("write_location"),
            metrics_location=d.get("metrics_location"),
            trace_location=d.get("trace_location"),
            batch_duration_secs=d.get("batch_duration_secs"),
            custom_tag_name=d.get("custom_tag_name"),
            custom_tag_value=d.get("custom_tag_value"),
            log_stage_metrics=bool(d.get("log_stage_metrics", False)),
            collect_stage_metrics=bool(d.get("collect_stage_metrics", True)),
            custom_params=dict(d.get("custom_params") or {}),
            serving=serving,
            sweep_checkpoint=sweep_ckpt,
            mesh=mesh,
            feature_cache=feature_cache,
            continual=continual,
            perf_model=perf_model)

    @staticmethod
    def load(path: str) -> "OpParams":
        with open(path) as f:
            return OpParams.from_json(json.load(f))

    def to_json(self) -> Dict[str, Any]:
        return {
            "stage_params": self.stage_params,
            "reader_params": {k: v.to_json()
                              for k, v in self.reader_params.items()},
            "model_location": self.model_location,
            "write_location": self.write_location,
            "metrics_location": self.metrics_location,
            "trace_location": self.trace_location,
            "batch_duration_secs": self.batch_duration_secs,
            "custom_tag_name": self.custom_tag_name,
            "custom_tag_value": self.custom_tag_value,
            "log_stage_metrics": self.log_stage_metrics,
            "collect_stage_metrics": self.collect_stage_metrics,
            "custom_params": self.custom_params,
            "serving": self.serving.to_json() if self.serving else None,
            "sweep_checkpoint": (self.sweep_checkpoint.to_json()
                                 if self.sweep_checkpoint else None),
            "mesh": self.mesh.to_json() if self.mesh else None,
            "feature_cache": (self.feature_cache.to_json()
                              if self.feature_cache else None),
            "continual": (self.continual.to_json()
                          if self.continual else None),
            "perf_model": (self.perf_model.to_json()
                           if self.perf_model else None),
        }


def apply_stage_params(stages, stage_params: Dict[str, Dict[str, Any]],
                       log=None) -> int:
    """Set per-stage param overrides, matched by stage class name, operation
    name, or uid (OpWorkflow.setParameters → ReflectionUtils setter path).
    Returns the number of stages touched."""
    touched = 0
    for stage in stages:
        for key in (type(stage).__name__, stage.operation_name, stage.uid):
            overrides = stage_params.get(key)
            if overrides:
                # REBIND params (defense in depth): clones now own their
                # params dict (dag._clone_stage), but rebinding instead of
                # mutating also keeps overrides out of any dict a caller
                # obtained via get_params()/aliasing before this ran
                stage.params = {**stage.params, **overrides}
                for name, value in overrides.items():
                    if hasattr(stage, name):
                        setattr(stage, name, value)
                touched += 1
                if log is not None:
                    log.info("Applied %s overrides to %s", key, stage.uid)
                break
    return touched
