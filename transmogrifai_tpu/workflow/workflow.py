"""Workflow engine: fit the feature DAG layer-by-layer, score, save/load.

Reference parity: `core/.../OpWorkflow.scala:61-588` (train),
`OpWorkflowModel.scala:60-455` (score/evaluate/save),
`FitStagesUtil.scala:51-369` (layered DAG fit + fused layer transforms).

TPU-first: fitting walks the layered DAG on host, dispatching estimator fits
(which internally run jitted reductions/optimizers); transforms execute
eagerly during fit so estimators see materialized inputs. Scoring uses the
same walk (`_execute`) or the fused `CompiledScorer` (workflow/compiled.py)
that runs every jittable stage in ONE XLA program.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

_log = logging.getLogger(__name__)

from transmogrifai_tpu.data.columns import Column
from transmogrifai_tpu.data.dataset import Dataset
from transmogrifai_tpu.features.dag import clone_graph, topological_layers
from transmogrifai_tpu.obs.metrics import get_registry
from transmogrifai_tpu.obs.trace import TRACER
from transmogrifai_tpu.stages.base import (
    Estimator, FeatureGeneratorStage, FitContext, Stage, Transformer)


def _validate_or_raise(result_features, strict: bool, where: str) -> None:
    """Run the static opcheck pass; raise on errors under strict, else log.
    Warnings are always logged (they never block)."""
    import logging

    from transmogrifai_tpu.analysis.opcheck import validate_graph

    log = logging.getLogger(__name__)
    report = validate_graph(result_features)
    if report.errors and strict:
        report.raise_if_errors()
    for issue in report.errors:
        log.warning("opcheck (%s, strict=False): %s", where, issue)
    for issue in report.warnings:
        log.info("opcheck (%s): %s", where, issue)


class Workflow:
    """Declarative workflow: wire result features, then `train()`."""

    def __init__(self):
        self.result_features: Tuple = ()
        self._dataset: Optional[Dataset] = None
        self._reader = None
        self.parameters: Dict[str, Any] = {}
        self._rff = None
        self._rff_score_source = None
        self.blocklist: List[str] = []
        self._workflow_cv = False
        self._warm_models: Dict[str, Transformer] = {}

    def set_result_features(self, *features) -> "Workflow":
        self.result_features = tuple(features)
        return self

    def set_input_dataset(self, dataset: Dataset) -> "Workflow":
        self._dataset = dataset
        return self

    def set_reader(self, reader) -> "Workflow":
        self._reader = reader
        return self

    def set_parameters(self, params) -> "Workflow":
        """Accepts an OpParams or a plain dict; stage_params overrides are
        applied to the DAG's stages at train time
        (OpWorkflow.setParameters, OpWorkflow.scala:179-201)."""
        from transmogrifai_tpu.workflow.params import OpParams
        if isinstance(params, OpParams):
            self.parameters = params.to_json()
        else:
            self.parameters = dict(params)
        return self

    def with_model_stages(self, model: "WorkflowModel",
                          exclude: Sequence[str] = ()) -> "Workflow":
        """Warm start (OpWorkflow.withModelStages, OpWorkflow.scala:468-472):
        estimators whose uid matches a fitted stage in `model` reuse that
        fitted transformer instead of refitting — only new estimators
        train. `exclude` uids REFIT even when a fitted stage exists —
        the continual-refit path reuses every feature-engineering fit
        but re-trains the predictor (warm-started from its weights)."""
        skip = set(exclude)
        self._warm_models.update({uid: m for uid, m in model.fitted.items()
                                  if uid not in skip})
        return self

    def with_workflow_cv(self) -> "Workflow":
        """Move the pre-ModelSelector feature-engineering DAG inside the CV
        folds (OpWorkflowCore.withWorkflowCV, OpWorkflowCore.scala:105 →
        FitStagesUtil.cutDAG:302-367): estimators feeding the selector are
        re-fit on each fold's training rows, so fold-global statistics
        (target encodings, supervised buckets, sanity-check selections)
        cannot leak into validation metrics."""
        self._workflow_cv = True
        return self

    def with_raw_feature_filter(self, score_dataset=None, score_reader=None,
                                **rff_params) -> "Workflow":
        """Enable RawFeatureFilter before training
        (OpWorkflow.withRawFeatureFilter, OpWorkflow.scala:544-586):
        train/score distribution comparison drops unhealthy raw features and
        rewires the DAG around them."""
        from transmogrifai_tpu.automl.raw_feature_filter import RawFeatureFilter
        self._rff = RawFeatureFilter(**rff_params)
        self._rff_score_source = (score_dataset, score_reader)
        return self

    # ------------------------------------------------------------------ #

    def _raw_features(self) -> List:
        seen: Dict[str, Any] = {}
        for f in self.result_features:
            for r in f.raw_features():
                seen.setdefault(r.uid, r)
        return list(seen.values())

    def _resolve_dataset(self, dataset: Optional[Dataset]) -> Dataset:
        ds = dataset if dataset is not None else self._dataset
        if ds is None and self._reader is not None:
            # aggregating readers fold per-key event streams through each raw
            # feature's monoid (readers/readers.py; DataReader.scala:216-330)
            ds = self._reader.read(self._raw_features())
        if ds is None:
            raise RuntimeError(
                "No input data: call set_input_dataset / set_reader or pass "
                "a dataset to train()/score()")
        return ds

    def train(self, dataset: Optional[Dataset] = None, seed: int = 42,
              mesh=None, strict: bool = True) -> "WorkflowModel":
        """Materialize raw features, then fit the DAG layer by layer
        (OpWorkflow.train → fitStages → fitAndTransformLayer).

        `mesh`: optional jax.sharding.Mesh — estimator fits that support it
        (the ModelSelector sweep) shard their work across it.

        A static opcheck pass (`analysis.opcheck.validate_graph`) runs
        FIRST — before any data materialization, fit, or XLA compile — and
        raises `GraphValidationError` on a miswired DAG (type mismatches,
        response leakage, cycles, host/device contract violations).
        `strict=False` downgrades validation errors to logged warnings.

        An OpParams ``feature_cache`` config is installed as the
        process-default device-matrix cache policy for the train's
        extent (`data/feature_cache.py`), so any big-data matrix built
        under this train — selector sweeps, out-of-core fits — resolves
        the run's cache policy without per-call plumbing. An OpParams
        ``perf_model`` config installs the same way (`perf/params.py`):
        the learned cost model's corpus location and tuning knobs apply
        to every scheduler/sweep/ingest decision under this train."""
        from transmogrifai_tpu.data.feature_cache import cache_scope
        from transmogrifai_tpu.perf.params import params_scope
        with cache_scope(self.parameters.get("feature_cache")), \
                params_scope(self.parameters.get("perf_model")):
            return self._train_impl(dataset, seed, mesh, strict)

    def _train_impl(self, dataset: Optional[Dataset], seed: int,
                    mesh, strict: bool) -> "WorkflowModel":
        if not self.result_features:
            raise RuntimeError("set_result_features before train()")
        _validate_or_raise(self.result_features, strict, where="train")
        ds = self._resolve_dataset(dataset)
        rff_results = None
        source_features = self.result_features
        if self._rff is not None:
            ds, source_features, rff_results = self._apply_rff(ds)
        # fit a private clone: the estimator→model swap must not mutate the
        # user's graph or previously returned models (see dag.clone_graph)
        result_features = clone_graph(source_features)
        layers = topological_layers(result_features)
        stage_params = self.parameters.get("stage_params") or {}
        if stage_params:
            from transmogrifai_tpu.workflow.params import apply_stage_params
            import logging
            apply_stage_params(
                [s for layer in layers[1:] for s in layer], stage_params,
                log=logging.getLogger(__name__))
        # resumable sweeps: thread the sweep-checkpoint config onto every
        # ModelSelector in the (cloned) DAG that has no checkpoint_dir of
        # its own — a re-invoked train() with the same params then skips
        # journaled grid blocks (runtime/journal.py)
        sweep_ckpt = self.parameters.get("sweep_checkpoint") or {}
        if sweep_ckpt.get("checkpoint_dir"):
            for layer in layers[1:]:
                for stage in layer:
                    est = getattr(stage, "_estimator", None) or stage
                    if self._is_selector(est) and est.checkpoint_dir is None:
                        est.checkpoint_dir = sweep_ckpt["checkpoint_dir"]
                        est.checkpoint_fsync = bool(
                            sweep_ckpt.get("fsync", True))
        ctx = FitContext(n_rows=len(ds), seed=seed, mesh=mesh)
        columns: Dict[str, Column] = {}
        fitted: Dict[str, Transformer] = {}

        for gen in layers[0] if layers else []:
            if not isinstance(gen, FeatureGeneratorStage):
                raise TypeError(f"Layer-0 stage {gen!r} is not a feature generator")
            columns[gen.get_output().uid] = gen.materialize(ds)

        n_fits = 0
        for li, layer in enumerate(layers[1:], start=1):
            for stage in layer:
                inputs = [columns[f.uid] for f in stage.input_features]
                # a re-train sees fitted models in the DAG; refit via their
                # original estimator (copyWithNewStages swap, stages/base.py)
                est = getattr(stage, "_estimator", None) or stage
                if isinstance(est, Estimator):
                    warm = self._warm_models.get(est.uid)
                    if warm is not None and not isinstance(warm, Estimator):
                        # warm start: reuse the previously fitted model
                        fitted[est.uid] = warm
                        columns[stage.get_output().uid] = warm.transform(
                            inputs, ctx)
                        continue
                    stage_ctx = ctx.child(li)
                    if self._workflow_cv and self._is_selector(est):
                        stage_ctx.cv_refit = self._make_cv_refit(
                            stage, layers, columns, ctx)
                    # per-stage spans: every fit and transform lands in
                    # the run's unified timeline keyed by stage uid, so
                    # a slow estimator is attributable from the trace
                    # alone (the OpSparkListener per-stage analogue)
                    with TRACER.span(
                            f"stage:fit:{stage.operation_name}",
                            category="stage", uid=est.uid, layer=li):
                        model = est.fit(inputs, stage_ctx)
                    n_fits += 1
                    fitted[est.uid] = model
                    with TRACER.span(
                            f"stage:transform:{stage.operation_name}",
                            category="stage", uid=est.uid, layer=li):
                        out = model.transform(inputs, ctx)
                elif isinstance(stage, Transformer):
                    fitted[stage.uid] = stage
                    with TRACER.span(
                            f"stage:transform:{stage.operation_name}",
                            category="stage", uid=stage.uid, layer=li):
                        out = stage.transform(inputs, ctx)
                else:
                    raise TypeError(f"Cannot execute stage {stage!r}")
                columns[stage.get_output().uid] = out

        reg = get_registry()
        reg.counter("train_runs_total",
                    "Workflow.train invocations").inc()
        reg.counter("train_stages_fitted_total",
                    "estimators fitted during train").inc(n_fits)
        model = WorkflowModel(
            result_features=result_features, fitted=fitted,
            train_columns=columns)
        model.rff_results = rff_results
        model.blocklist = list(self.blocklist)
        # fingerprint capture is opt-in via a "continual" parameters
        # block (even an empty one): the sampled device gather + per-
        # column quantile pass is real work on wide matrices, and batch
        # workflows that never attach a DriftMonitor shouldn't pay it
        if "continual" in self.parameters:
            cont_params = self.parameters.get("continual") or {}
            model.training_fingerprint = self._capture_fingerprint(
                result_features, columns, seed,
                n_bins=int(cont_params.get("n_bins", 10)))
        # per-column quantization calibration is captured on EVERY
        # train (a strided min/max over the host-origin columns — far
        # cheaper than the opt-in histogram fingerprint): quantized
        # serving with "-calibrated" mode then ships fleet-wide
        # fit-time ranges and repeat scores are bit-stable across batch
        # compositions (workflow/compiled.ScoringQuant)
        model.quant_calibration = self._capture_quant_calibration(
            result_features, fitted, columns)
        return model

    @staticmethod
    def _capture_quant_calibration(result_features, fitted, columns):
        """Fit-time per-column [lo, hi] ranges for the quantized
        serving wire: captured for every HOST-ORIGIN device-input
        column (raw generator outputs + host-stage outputs — exactly
        the leaves `quantize_wire` sees as numpy arrays at serving
        time). Scalar ranges are extended to include 0.0 because
        masked slots ride the wire as exact 0.0 fills. Rows are
        strided-sampled past 256k (a quant range needs coverage, not
        exactness). Best-effort: failure means no calibration, never a
        failed train."""
        from transmogrifai_tpu.data.columns import SCALAR, VECTOR
        from transmogrifai_tpu.stages.base import is_host_stage
        try:
            host_uids = {f.uid for rf in result_features
                         for f in rf.raw_features()}
            for s in fitted.values():
                if is_host_stage(s):
                    host_uids.add(s.get_output().uid)
            cal = {}
            for uid in host_uids:
                col = columns.get(uid)
                if col is None:
                    continue
                kind = col.kind
                if kind == SCALAR:
                    v = np.asarray(col.data["value"], np.float64)
                    m = np.asarray(col.data["mask"]).astype(bool)
                    v = v[m]
                    if v.size > 262_144:
                        v = v[::v.size // 262_144]
                    if v.size == 0:
                        continue
                    with np.errstate(invalid="ignore"):
                        fin = v[np.isfinite(v)]
                    if fin.size == 0:
                        continue
                    lo = min(float(fin.min()), 0.0)
                    hi = max(float(fin.max()), 0.0)
                    cal[uid] = {"lo": [lo], "hi": [hi]}
                elif kind == VECTOR:
                    a = np.asarray(col.data)
                    if a.ndim != 2 or a.size == 0:
                        continue
                    if a.shape[0] > 65_536:
                        a = a[::a.shape[0] // 65_536]
                    import warnings
                    with np.errstate(invalid="ignore"), \
                            warnings.catch_warnings():
                        warnings.simplefilter("ignore", RuntimeWarning)
                        fin = np.where(np.isfinite(a), a, np.nan)
                        lo = np.nanmin(fin, axis=0)
                        hi = np.nanmax(fin, axis=0)
                    lo = np.where(np.isfinite(lo), lo, 0.0)
                    hi = np.where(np.isfinite(hi), hi, lo)
                    cal[uid] = {"lo": [float(x) for x in lo],
                                "hi": [float(x) for x in hi]}
            return cal or None
        except Exception as e:
            _log.warning("quant calibration capture failed (%s: %s) — "
                         "quantized serving will use batch-relative "
                         "ranges", type(e).__name__, e)
            return None

    @staticmethod
    def _capture_fingerprint(result_features, columns, seed: int,
                             n_bins: int = 10):
        """Training-data fingerprint for drift detection (continual/):
        per-feature histograms + moments of the PREDICTOR'S input matrix
        plus the label rate, taken from the already-materialized train
        columns (no second data pass). The row sample is gathered ON
        DEVICE, so only sample-many rows ever transfer to host — a
        multi-GB big-data matrix must not round-trip through host RAM
        for a 100k-row histogram. Persisted into ModelInsights, so a
        later DriftMonitor compares appended records against what this
        model actually trained on. Best-effort: workflows without a
        (label, vector) predictor simply have no fingerprint."""
        from transmogrifai_tpu import types as T
        from transmogrifai_tpu.continual.drift import (
            _FP_SAMPLE, TrainingFingerprint)
        try:
            pred = next((f for f in result_features
                         if issubclass(f.ftype, T.Prediction)), None)
            if pred is None or pred.origin_stage is None:
                return None
            label_f = next((p for p in pred.parents if p.is_response), None)
            vec_f = next((p for p in pred.parents
                          if issubclass(p.ftype, T.OPVector)), None)
            if label_f is None or vec_f is None:
                return None
            vec_col = columns.get(vec_f.uid)
            label_col = columns.get(label_f.uid)
            if vec_col is None or label_col is None:
                return None
            dv = vec_col.device_value()
            total = int(dv.shape[0])
            if total > _FP_SAMPLE:
                rng = np.random.default_rng(seed)
                idx = np.sort(rng.choice(total, size=_FP_SAMPLE,
                                         replace=False))
                X = np.asarray(dv[idx])  # device gather, sample-sized copy
            else:
                X = np.asarray(dv)
            y = np.asarray(label_col.data["value"], dtype=np.float64)
            meta = vec_col.meta
            names = meta.column_names() if meta is not None else None
            return TrainingFingerprint.from_arrays(
                X, y, n_bins=n_bins, seed=seed, feature_names=names,
                total_rows=total)
        except Exception as e:
            _log.warning("training fingerprint capture failed (%s: %s) — "
                         "model will have no drift fingerprint",
                         type(e).__name__, e)
            _log.debug("fingerprint capture traceback", exc_info=True)
            return None

    @staticmethod
    def _is_selector(est) -> bool:
        from transmogrifai_tpu.selector.model_selector import ModelSelector
        return isinstance(est, ModelSelector)

    def _make_cv_refit(self, selector_stage, layers, columns, ctx):
        """The cutDAG "during" partition (FitStagesUtil.scala:302-367) as a
        closure: re-fit every estimator feeding the selector's feature
        vector on `fold_rows` only, re-run the transformers, and return the
        fold-specific feature matrix for ALL rows. The label subtree is
        excluded (reused from the global pass) so fold masks stay aligned.
        """
        label_f, vec_f = selector_stage.input_features
        label_uids = {f.uid for f in label_f.traverse()}
        during_stage_uids = {
            f.origin_stage.uid for f in vec_f.traverse()
            if not f.is_raw and f.uid not in label_uids}
        base = dict(columns)  # global columns materialized so far

        def refit(fold_rows: np.ndarray) -> np.ndarray:
            cols = dict(base)
            salt = 0
            for layer in layers[1:]:
                for stage in layer:
                    if (stage is selector_stage
                            or stage.uid not in during_stage_uids):
                        continue
                    salt += 1
                    ins_full = [cols[f.uid] for f in stage.input_features]
                    est = getattr(stage, "_estimator", None) or stage
                    if isinstance(est, Estimator):
                        fold_ctx = FitContext(
                            n_rows=len(fold_rows),
                            seed=ctx.seed * 1000003 + salt, mesh=ctx.mesh)
                        # fit_model (NOT fit): fold models are throwaway and
                        # must not graph-swap origin_stage away from the
                        # globally fitted model
                        m = est.fit_model(
                            [c.take(fold_rows) for c in ins_full], fold_ctx)
                        m.uid = est.uid
                        m.input_features = est.input_features
                        out = m.transform(ins_full)
                    else:
                        out = stage.transform(ins_full)
                    cols[stage.get_output().uid] = out
            return np.asarray(cols[vec_f.uid].data)

        return refit

    def _apply_rff(self, ds: Dataset):
        """Run RawFeatureFilter and rewire the DAG around dropped raw
        features (OpWorkflow.scala:235-258 generateRawData with RFF +
        setBlocklist). Result features that become unproducible raise —
        the reference's default retention policy."""
        from transmogrifai_tpu.features.dag import rewire_without

        raws = self._raw_features()
        label = next((f for f in raws if f.is_response), None)
        score_ds = None
        if self._rff_score_source is not None:
            score_ds, score_reader = self._rff_score_source
            if score_ds is None and score_reader is not None:
                score_ds = score_reader.read(raws)
        filtered = self._rff.generate_filtered_raw(
            ds, raws, score_dataset=score_ds, label_feature=label)
        self.blocklist = list(filtered.features_to_drop)
        if not filtered.features_to_drop:
            return filtered.clean_dataset, self.result_features, filtered.results
        survived, dropped = rewire_without(
            self.result_features, filtered.features_to_drop)
        if dropped:
            raise RuntimeError(
                f"RawFeatureFilter removed raw features "
                f"{filtered.features_to_drop} making result features "
                f"{dropped} unproducible; protect them via "
                f"protected_features or relax thresholds")
        return filtered.clean_dataset, tuple(survived), filtered.results


class WorkflowModel:
    """A fitted workflow (OpWorkflowModel): scoring, evaluation, persistence."""

    def __init__(self, result_features: Sequence, fitted: Dict[str, Transformer],
                 train_columns: Optional[Dict[str, Column]] = None):
        self.result_features = tuple(result_features)
        self.fitted = dict(fitted)
        self.train_columns = train_columns or {}
        self._compiled = None
        self.rff_results = None   # RawFeatureFilterResults when RFF ran
        self.blocklist: List[str] = []
        self._check_finite = False
        self.loaded_from: Optional[str] = None  # set by load_model
        # drift-detection fingerprint of the predictor's training matrix
        # (continual/drift.TrainingFingerprint), set by Workflow.train()
        self.training_fingerprint = None
        # fit-time per-column [lo, hi] ranges for calibrated quantized
        # serving (uid -> {"lo": [...], "hi": [...]}); set by
        # Workflow.train(), persisted in the model manifest
        self.quant_calibration = None

    def with_finite_checks(self, enabled: bool = True) -> "WorkflowModel":
        """Numeric-sanitizer discipline (SURVEY §5.2 — the build's
        analogue of the reference's serializability validation): when
        enabled, every fitted transform's numeric output is checked for
        NaN/Inf on PRESENT values during eager scoring, raising with the
        producing stage's name instead of letting a poisoned column
        propagate into a silent bad model score."""
        self._check_finite = enabled
        return self

    @staticmethod
    def _assert_finite(stage, col: Column) -> None:
        data = col.data
        leaves = (data.values() if isinstance(data, dict) else [data])
        for leaf in leaves:
            arr = np.asarray(leaf)
            if arr.dtype.kind != "f":
                continue
            if isinstance(data, dict) and "mask" in data:
                mask = np.asarray(data["mask"]).astype(bool)
                if arr.shape[:1] == mask.shape[:1]:
                    arr = arr[mask]
            if arr.size and not np.isfinite(arr).all():
                raise FloatingPointError(
                    f"Stage {stage.operation_name} ({stage.uid}) produced "
                    f"non-finite values (NaN/Inf) in its output — enable "
                    f"upstream imputation or inspect the fitted params")

    # ------------------------------------------------------------------ #
    # execution                                                          #
    # ------------------------------------------------------------------ #

    def _execute(self, ds: Dataset) -> Dict[str, Column]:
        """Eager layer-by-layer transform walk (estimators must be fitted)."""
        layers = topological_layers(self.result_features)
        columns: Dict[str, Column] = {}
        for gen in layers[0] if layers else []:
            columns[gen.get_output().uid] = gen.materialize(
                ds, allow_missing_response=True)
        for layer in layers[1:]:
            for stage in layer:
                model = self.fitted.get(stage.uid)
                if model is None:
                    raise RuntimeError(
                        f"Stage {stage.operation_name} ({stage.uid}) has no "
                        "fitted model — did train() run?")
                inputs = [columns[f.uid] for f in stage.input_features]
                out_col = model.transform(inputs)
                if self._check_finite:
                    self._assert_finite(stage, out_col)
                columns[stage.get_output().uid] = out_col
        return columns

    def score(self, dataset: Dataset,
              keep_intermediate: bool = False) -> Dict[str, Column]:
        """Batch scoring: returns {feature_name: Column} for result features
        (OpWorkflowModel.score; drops raw/intermediate like saveScores)."""
        columns = self._execute(dataset)
        if keep_intermediate:
            return columns
        return {f.name: columns[f.uid] for f in self.result_features}

    def _ensure_compiled(self, sharding=None, strict: bool = True,
                         quant=None):
        """Shared gate for EVERY compiled entry point (score_compiled,
        score_stream, score_function): opcheck-validate the fitted graph
        before building a new CompiledScorer. Post-train the graph's
        origin stages ARE the fitted transformers (the estimator→model
        swap in stages/base.py mutates the feature nodes in place), so
        the device-contract checks see exactly what the planner traces.

        `quant` ("int8"/"int4"/ScoringQuant/None) selects the quantized
        inference mode — a different compiled program set, so the cached
        scorer is rebuilt when it changes."""
        from transmogrifai_tpu.workflow.compiled import (
            CompiledScorer, ScoringQuant)
        q = ScoringQuant.resolve(quant)
        if self._compiled is None or \
                getattr(self._compiled, "sharding", None) != sharding or \
                getattr(self._compiled, "quant", None) != q:
            _validate_or_raise(self.result_features, strict,
                               where="compile")
            self._compiled = CompiledScorer(self, sharding=sharding,
                                            quant=q)
        return self._compiled

    def score_compiled(self, dataset: Dataset, sharding=None,
                       strict: bool = True) -> Dict[str, Any]:
        """Fused-XLA scoring path (the `local/` + MLeap equivalent).

        `sharding`: optional row-axis NamedSharding (e.g.
        `parallel.data_sharding(mesh)`) — batch inputs are placed with it
        so the fused program's work spreads across the mesh.

        The fitted graph is opcheck-validated before the first compile
        (`strict=False` downgrades errors to logged warnings)."""
        return self._ensure_compiled(sharding, strict)(dataset)

    def score_stream(self, batches, prefetch: int = 2, sharding=None,
                     host_workers: int = 2, device_depth: int = 2,
                     fetch_group: int = 1, coalesce_rows: int = 0,
                     strict: bool = True, pad_tail: bool = True):
        """Streaming micro-batch scoring as a TWO-stage pipeline
        (OpWorkflowRunner streaming loop, OpWorkflowRunner.scala:233-262):

        - stage 1 (thread pool, `host_workers`): host encode of upcoming
          batches — string→id tables, raw column extraction (numpy/C
          murmur3, mostly GIL-releasing);
        - stage 2 (`device_depth` in flight): the fused device program is
          DISPATCHED for batch i+1..i+depth before batch i's results are
          yielded — JAX's async dispatch means the tunnel RPC and device
          execution of later batches overlap the consumer's reads of
          earlier ones. A depth-1 loop (r2) serialized
          host→dispatch→fetch per batch and capped streaming at ~42k
          rows/s even though host encode was 28 ms/batch.

        `fetch_group` > 1 amortizes the device→host RESULT fetch: through
        the serving tunnel a host materialization costs ~0.7 s of RPC
        latency regardless of size (r4 measured: 22 MB transfers at
        1.2 GB/s, tiny fetches 0.7 s), so per-batch fetches cap streaming
        at ~140k rows/s. Grouped mode packs `fetch_group` batches' result
        arrays into ONE flat device buffer (one concat dispatch) and
        fetches it with a single RPC, then yields the batches as
        host-materialized numpy results.

        `coalesce_rows` > 0 merges incoming batches into super-batches of
        at least that many rows before dispatch, then splits each result
        back to the ORIGINAL batch boundaries — the output contract (one
        result per input batch, in order) is unchanged. Through an
        RPC-bound link every dispatch pays a fixed round-trip tax on top
        of the device compute, so bigger dispatches raise throughput
        roughly until compute dominates; stable input batch sizes keep
        the coalesced shape stable (one compiled program).

        `pad_tail` (default on) pads a RAGGED FINAL micro-batch up to the
        largest batch shape already seen instead of tracing a fresh XLA
        program for it: a 10M-row stream at batch 1024 ends with one
        partial batch, and before this fix that one batch paid a full
        recompile (seconds) to score a sliver of rows. Pad rows repeat
        the last real row and are sliced back off before the yield, so
        the output contract is unchanged (`analysis/retrace` counters
        assert the no-churn property in tests).

        `batches`: iterable of Datasets (e.g. `StreamingReader.stream()`).
        Yields {feature_name: result} per batch like `score_compiled`.
        """
        from collections import deque
        from concurrent.futures import ThreadPoolExecutor

        from transmogrifai_tpu.workflow.compiled import (
            pad_dataset, slice_result_tree)

        if coalesce_rows and coalesce_rows > 0:
            split_sizes: deque = deque()

            def _coalesced():
                buf, rows = [], 0
                for ds in batches:
                    buf.append(ds)
                    rows += ds.n_rows
                    if rows >= coalesce_rows:
                        split_sizes.append([b.n_rows for b in buf])
                        yield Dataset.concat(buf)
                        buf, rows = [], 0
                if buf:
                    split_sizes.append([b.n_rows for b in buf])
                    yield Dataset.concat(buf)

            # results come back in dispatch order, so the FIFO of split
            # sizes stays aligned with the inner generator's yields
            for host in self.score_stream(
                    _coalesced(), prefetch=prefetch, sharding=sharding,
                    host_workers=host_workers, device_depth=device_depth,
                    fetch_group=fetch_group, strict=strict,
                    pad_tail=pad_tail):
                off = 0
                for s in split_sizes.popleft():
                    yield {f: slice_result_tree(v, off, off + s)
                           for f, v in host.items()}
                    off += s
            return
        if pad_tail:
            # ragged-tail fix: the FINAL partial batch re-pads to the
            # largest shape already compiled (then slices the pad rows
            # back off) instead of tracing a fresh program for one batch.
            # Only the final batch: a mid-stream smaller batch is a real
            # workload shape (variable-size sources), and padding every
            # one of them to the max would silently multiply device work.
            # One-item lookahead tells us which batch is last; an empty
            # final batch passes through unpadded (nothing to repeat).
            tail_sizes: deque = deque()

            def _pad_tails():
                it = iter(batches)
                try:
                    cur = next(it)
                except StopIteration:
                    return
                prev = 0
                for nxt in it:
                    tail_sizes.append((cur.n_rows, cur.n_rows))
                    yield cur
                    prev = max(prev, cur.n_rows)
                    cur = nxt
                n = cur.n_rows
                target = prev if (prev and 0 < n < prev) else n
                tail_sizes.append((n, target))
                yield pad_dataset(cur, target) if target > n else cur

            for host in self.score_stream(
                    _pad_tails(), prefetch=prefetch, sharding=sharding,
                    host_workers=host_workers, device_depth=device_depth,
                    fetch_group=fetch_group, strict=strict,
                    pad_tail=False):
                n, target = tail_sizes.popleft()
                if target > n:
                    yield {f: slice_result_tree(v, 0, n)
                           for f, v in host.items()}
                else:
                    yield host
            return
        scorer = self._ensure_compiled(sharding, strict)
        try:
            device_fn = scorer.fused_jitted()  # shared compile cache
        except RuntimeError:
            # multi-segment plan (host stage consumes device output):
            # sequential per-batch scoring, no host/device overlap
            for ds in batches:
                yield scorer(ds)
            return

        group_n = max(1, int(fetch_group))

        def dispatch(host_out):
            encs, raw_dev, columns = host_out
            out = device_fn(scorer._consts, encs, raw_dev)  # async dispatch
            result: Dict[str, Any] = {}
            for f in self.result_features:
                result[f.name] = (out[f.uid] if f.uid in out
                                  else columns[f.uid].data)
            # per-batch-fetch mode: start the device→host result copy NOW
            # (it queues behind the execution), so the consumer's
            # np.asarray finds the bytes already on host instead of
            # paying a blocking RPC per batch. Grouped mode fetches one
            # packed buffer instead — per-leaf async copies would just
            # burn tunnel round-trips.
            if group_n == 1:
                try:
                    for leaf in _jax.tree_util.tree_leaves(result):
                        if hasattr(leaf, "copy_to_host_async"):
                            leaf.copy_to_host_async()
                except Exception:
                    _log.debug("async host copy unavailable; consumer "
                               "will fetch synchronously", exc_info=True)
            return result

        import jax as _jax

        def encode(ds):
            encs, raw_dev, columns = scorer.host_phase(ds)
            # pre-stage the bulk input transfer from the WORKER thread so
            # uploads of batch i+1 overlap the device execution of batch
            # i (the transfer otherwise serializes inside dispatch)
            try:
                raw_dev = _jax.device_put(raw_dev)
            except Exception:
                # non-array leaves: let dispatch transfer lazily
                _log.debug("worker-side device_put skipped", exc_info=True)
            return encs, raw_dev, columns

        # ONE jitted pack fn: jax.jit itself caches per input pytree
        # structure/shape, so distinct group shapes retrace automatically
        _pack = _jax.jit(lambda ls: _jax.numpy.concatenate(
            [x.reshape(-1) for x in ls]))

        def _packable(v) -> bool:
            # float32 only: the flat buffer is f32, and round-tripping
            # wider/integer dtypes through it would silently lose bits.
            # Non-f32 device leaves (none exist today) fall back to a
            # per-leaf fetch below.
            return (isinstance(v, _jax.Array)
                    and v.dtype == _jax.numpy.float32)

        def materialize_group(group):
            """One flat-buffer fetch for a whole group of results.
            Packs every f32 device leaf — inside result dicts AND bare
            array result features — into one buffer; anything else is
            materialized per leaf."""
            if not group:
                return []
            flats = []   # per-result f32 leaves in deterministic order
            metas = []   # (fname, key-or-None, shape) per leaf
            for result in group:
                leaves = []
                meta = []
                for fname in sorted(result):
                    val = result[fname]
                    if isinstance(val, dict):
                        for k in sorted(val):
                            if _packable(val[k]):
                                meta.append((fname, k, val[k].shape))
                                leaves.append(val[k])
                    elif _packable(val):
                        meta.append((fname, None, val.shape))
                        leaves.append(val)
                flats.append(leaves)
                metas.append(meta)
            if sum(len(ls) for ls in flats) == 0:
                return list(group)
            flat_all = [x for ls in flats for x in ls]
            buf = np.asarray(_pack(flat_all))  # ONE fetch RPC
            out = []
            off = 0
            for result, meta in zip(group, metas):
                host: Dict[str, Any] = {}
                for f, v in result.items():
                    if isinstance(v, dict):
                        host[f] = {k: (np.asarray(x)
                                       if isinstance(x, _jax.Array)
                                       and not _packable(x) else x)
                                   for k, x in v.items()}
                    elif isinstance(v, _jax.Array) and not _packable(v):
                        host[f] = np.asarray(v)
                    else:
                        host[f] = v
                for fname, k, shape in meta:
                    size = int(np.prod(shape))
                    # copy: a view would pin the WHOLE group buffer for
                    # as long as any one batch's array is retained
                    piece = buf[off:off + size].reshape(shape).copy()
                    if k is None:
                        host[fname] = piece
                    else:
                        host[fname][k] = piece
                    off += size
                out.append(host)
            return out

        with ThreadPoolExecutor(max_workers=max(1, host_workers)) as pool:
            encoded = deque()    # host-encode futures
            in_flight = deque()  # dispatched (async) device results

            def pump():  # encode-done or backlog → dispatch to device
                while encoded and (encoded[0].done()
                                   or len(encoded) > max(1, prefetch)):
                    in_flight.append(dispatch(encoded.popleft().result()))

            if group_n == 1:
                for ds in batches:
                    encoded.append(pool.submit(encode, ds))
                    pump()
                    while len(in_flight) > max(1, device_depth):
                        yield in_flight.popleft()
                while encoded:
                    in_flight.append(dispatch(encoded.popleft().result()))
                while in_flight:
                    yield in_flight.popleft()
                return
            # grouped-fetch mode: hold up to group_n dispatched batches,
            # then pack + materialize them with one RPC. The fetch runs
            # on its OWN single worker so the RPC (0.7s on a healthy
            # tunnel, several seconds on a degraded one) overlaps
            # continued encode+dispatch instead of idling the device —
            # r5 measured the consumer-blocking fetch capping streaming
            # at ~1/8 of the device ceiling when the tunnel degraded.
            # Exactly ONE worker: a same-session A/B with 2-3 parallel
            # fetch RPCs measured ~20% SLOWER (server-side contention).
            depth = max(group_n, device_depth)
            with ThreadPoolExecutor(max_workers=1) as fetch_pool:
                fetched = deque()  # materialize futures, arrival order

                def drain_ready(max_pending: int):
                    while fetched and (fetched[0].done()
                                       or len(fetched) > max_pending):
                        yield from fetched.popleft().result()

                for ds in batches:
                    encoded.append(pool.submit(encode, ds))
                    pump()
                    while len(in_flight) >= depth + group_n:
                        grp = [in_flight.popleft()
                               for _ in range(group_n)]
                        fetched.append(
                            fetch_pool.submit(materialize_group, grp))
                    yield from drain_ready(2)
                while encoded:
                    in_flight.append(dispatch(encoded.popleft().result()))
                while in_flight:
                    grp = [in_flight.popleft()
                           for _ in range(min(group_n, len(in_flight)))]
                    fetched.append(
                        fetch_pool.submit(materialize_group, grp))
                while fetched:
                    yield from fetched.popleft().result()

    def score_function(self, strict: bool = True):
        """Row-level scoring closure: Map[str, Any] → Map[str, Any]
        (local/.../OpWorkflowModelLocal.scala:79-122). Shares the cached
        validated scorer with score_compiled/score_stream."""
        scorer = self._ensure_compiled(strict=strict)

        def score_row(row: Dict[str, Any]) -> Dict[str, Any]:
            ds = Dataset.from_rows([row])
            out = scorer(ds)
            result: Dict[str, Any] = {}
            for f in self.result_features:
                v = out.get(f.name)
                if isinstance(v, dict) and "prediction" in v:  # Prediction pytree
                    m: Dict[str, float] = {
                        "prediction": float(np.asarray(v["prediction"])[0])}
                    prob = np.asarray(v["probability"])[0]
                    raw = np.asarray(v["rawPrediction"])[0]
                    for i, x in enumerate(prob):
                        m[f"probability_{i}"] = float(x)
                    for i, x in enumerate(raw):
                        m[f"rawPrediction_{i}"] = float(x)
                    result[f.name] = m
                elif isinstance(v, dict):  # scalar {value, mask} pytree
                    present = bool(np.asarray(v["mask"])[0])
                    result[f.name] = (
                        float(np.asarray(v["value"])[0]) if present else None)
                else:
                    arr = np.asarray(v)
                    first = arr[0]
                    if arr.dtype == object:  # host kinds: str/list/dict
                        result[f.name] = first
                    else:
                        result[f.name] = (first.tolist() if arr.ndim > 1
                                          else first.item())
            return result

        return score_row

    def evaluate(self, dataset: Dataset, evaluator, label_feature,
                 prediction_feature):
        cols = self._execute(dataset)
        return evaluator.evaluate(
            cols[label_feature.uid], cols[prediction_feature.uid])

    # ------------------------------------------------------------------ #
    # persistence                                                        #
    # ------------------------------------------------------------------ #

    def save(self, path: str, overwrite: bool = True,
             strict_fns: bool = False, extra_json=None) -> None:
        """`strict_fns=True` refuses to persist cloudpickled closures —
        callable params must be `@extract_fn`-registered or module-level
        (see `workflow/serialization.py`). `extra_json` stages sidecar
        JSON files (e.g. insights with the training fingerprint) under
        the same integrity manifest."""
        from transmogrifai_tpu.workflow.serialization import save_model
        save_model(self, path, overwrite=overwrite, strict_fns=strict_fns,
                   extra_json=extra_json)

    @staticmethod
    def load(path: str, verify: bool = True) -> "WorkflowModel":
        """`verify=False` skips the integrity-manifest check — the
        escape hatch for artifacts saved before integrity.json existed
        (see workflow/serialization.py)."""
        from transmogrifai_tpu.workflow.serialization import load_model
        return load_model(path, verify=verify)

    def model_insights(self):
        """Merged explanation artifact (ModelInsights.scala:74)."""
        from transmogrifai_tpu.insights import ModelInsights
        return ModelInsights.extract(self)

    def summary(self) -> Dict[str, Any]:
        """Stage inventory + params (OpWorkflowModel.summary analogue)."""
        return {
            "result_features": [f.name for f in self.result_features],
            "stages": [
                {"uid": uid, "class": type(s).__name__}
                for uid, s in sorted(self.fitted.items())
            ],
        }
