"""CompiledScorer: the fitted DAG as ONE fused XLA program.

This is the TPU replacement for both the reference's fused row transform
(`FitStagesUtil.applyOpTransformations`, FitStagesUtil.scala:96-119) and its
Spark-free MLeap scoring path (`local/.../OpWorkflowModelLocal.scala:79-122`):

- host phase (per batch): materialize raw columns, run HostTransformers
  eagerly, call each jittable stage's `host_prepare` (string → ids etc.)
- device phase: a single `jax.jit` function threads every stage's
  `device_apply` — XLA fuses imputation, one-hot, concat, and the model
  matmul into one program; with a mesh, the batch axis shards over devices.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import numpy as np

from transmogrifai_tpu.data.columns import Column
from transmogrifai_tpu.data.dataset import Dataset
from transmogrifai_tpu.features.dag import topological_layers
from transmogrifai_tpu.stages.base import (
    FeatureGeneratorStage, HostTransformer, Transformer)

_HOST_KINDS = ("text", "list", "map")


class CompiledScorer:
    def __init__(self, model, sharding: Optional[Any] = None):
        self.model = model
        self.sharding = sharding  # optional jax.sharding.NamedSharding for batch
        layers = topological_layers(model.result_features)
        self.generators: List[FeatureGeneratorStage] = list(layers[0]) if layers else []
        self.host_stages: List[Transformer] = []
        self.device_stages: List[Transformer] = []
        for layer in layers[1:]:
            for stage in layer:
                fitted = model.fitted.get(stage.uid)
                if fitted is None:
                    raise RuntimeError(f"Unfitted stage {stage.uid}")
                if isinstance(fitted, HostTransformer):
                    self.host_stages.append(fitted)
                else:
                    self.device_stages.append(fitted)
        self._stage_out_uid = {
            s.uid: s.get_output().uid
            for s in self.host_stages + self.device_stages}
        self._jitted = jax.jit(self._device_fn)

    # ------------------------------------------------------------------ #

    def _device_fn(self, encs: Dict[str, Any], raw_dev: Dict[str, Any]):
        vals: Dict[str, Any] = dict(raw_dev)
        for stage in self.device_stages:
            dev_inputs = [vals.get(f.uid) for f in stage.input_features]
            out = stage.device_apply(encs.get(stage.uid), dev_inputs)
            vals[self._stage_out_uid[stage.uid]] = out
        return {
            f.uid: vals[f.uid]
            for f in self.model.result_features if f.uid in vals
        }

    def host_phase(self, dataset: Dataset):
        """Per-batch host work: materialize raw columns, run host stages,
        call each device stage's host_prepare. Returns (encs, raw_dev,
        columns) — the jitted device program's inputs."""
        columns: Dict[str, Column] = {}
        for gen in self.generators:
            columns[gen.get_output().uid] = gen.materialize(
                dataset, allow_missing_response=True)
        for stage in self.host_stages:
            inputs = []
            for f in stage.input_features:
                c = columns.get(f.uid)
                if c is None:
                    raise RuntimeError(
                        f"Host stage {stage.operation_name} needs device-"
                        f"produced input {f.name}; unsupported topology")
                inputs.append(c)
            columns[self._stage_out_uid[stage.uid]] = stage.transform(inputs)

        encs: Dict[str, Any] = {}
        for stage in self.device_stages:
            cols = [columns.get(f.uid) for f in stage.input_features]
            enc = stage.host_prepare(cols)
            if enc is not None:
                encs[stage.uid] = enc

        raw_dev: Dict[str, Any] = {}
        for gen in self.generators:
            f = gen.get_output()
            c = columns[f.uid]
            if c.kind not in _HOST_KINDS:
                raw_dev[f.uid] = c.device_value()
        return encs, raw_dev, columns

    def __call__(self, dataset: Dataset) -> Dict[str, Any]:
        encs, raw_dev, columns = self.host_phase(dataset)
        # -- device phase (one XLA program) ----------------------------- #
        out = self._jitted(encs, raw_dev)

        result: Dict[str, Any] = {}
        for f in self.model.result_features:
            if f.uid in out:
                result[f.name] = out[f.uid]
            else:  # host-kind result feature
                result[f.name] = columns[f.uid].data
        return result
