"""CompiledScorer: the fitted DAG as fused XLA program segments.

This is the TPU replacement for both the reference's fused row transform
(`FitStagesUtil.applyOpTransformations`, FitStagesUtil.scala:96-119) and its
Spark-free MLeap scoring path (`local/.../OpWorkflowModelLocal.scala:79-122`):

- host phase (per batch): materialize raw columns, call each jittable
  stage's `host_prepare` (string → ids etc.)
- device phase: consecutive jittable stages compile into ONE `jax.jit`
  program — XLA fuses imputation, one-hot, concat, and the model matmul;
  with a mesh, the batch axis shards over devices.

Topologies where a HostTransformer consumes a device-produced feature
(e.g. `(sibSp + parCh).alias(...)`) split the plan into alternating
host/device SEGMENTS: each device segment is still one fused XLA program,
and device outputs materialize to host columns only when a host stage
actually reads them. A pipeline with no such crossing keeps the single
fused program.

Roofline scoring (PR 13): tabular scoring is memory-bound, so the hot
path is engineered against the HBM roofline rather than MFU:

- each device segment's program returns ONLY the outputs something
  downstream actually reads (a later host segment or a result feature)
  — intermediates stay fusion-eligible instead of being forced into
  HBM as program outputs;
- plans with a single trailing device segment (the overwhelmingly
  common shape — host string work happens in `host_prepare`, not in
  host stages) score through `score_padded`'s fused fast path: ONE
  device dispatch per call, accounted per segment in
  `analysis.retrace.DISPATCHES` and as `device_dispatch` trace events
  (bytes in/out per dispatch — the numerator of the achieved-bandwidth
  roofline `bench.py` reports as `scoring_hbm_frac`);
- `quant=ScoringQuant("int8"|"int4")` turns on end-to-end quantized
  inference: the request matrix ships on a per-batch affine uint8 wire
  (int4 packs two features per byte — same nibble layout as
  `data/feature_cache.QuantPlan`/`parallel/bigdata._unpack_dequant`)
  with dequant fused into the scoring program, and fitted tables
  compute from narrowed dtypes (`Transformer.narrow_device_constants`:
  f16 tree thresholds, uint8 bin ids, bf16 linear weights). Stated
  tolerance per feature: scale/2 = (hi − lo)/(2·(2^bits − 1)) on the
  batch's own [lo, hi] range; masks ride the wire as exact uint8 0/1.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from transmogrifai_tpu import types as T
from transmogrifai_tpu.analysis.retrace import DISPATCHES
from transmogrifai_tpu.data.columns import Column
from transmogrifai_tpu.data.dataset import Dataset
from transmogrifai_tpu.features.dag import topological_layers
from transmogrifai_tpu.obs.trace import TRACER, add_event
from transmogrifai_tpu.stages.base import (
    HOST_KINDS as _HOST_KINDS, FeatureGeneratorStage, HostTransformer,
    Transformer, is_host_stage)


@dataclass(frozen=True)
class ScoringQuant:
    """Quantized-inference mode for the compiled scorer: ``"int8"``
    ships 1 byte/element on the wire, ``"int4"`` half that (two
    features per byte). Per-feature max abs error is scale/2 with
    scale = (hi − lo)/(2^bits − 1).

    ``calibrated=False`` (batch-relative, the PR-13 wire): [lo, hi] is
    each BATCH's own value range — a request quantizes against its
    batchmates, so repeat scoring of one row in different batches
    agrees within the stated tolerance, not bitwise.

    ``calibrated=True`` (``"int8-calibrated"``/``"int4-calibrated"``):
    [lo, hi] comes from the per-feature ranges captured at FIT time and
    persisted with the model (``WorkflowModel.quant_calibration``, the
    fingerprint pass's range sidecar) — scale/lo are constants of the
    model, quantization is a single vectorized pass with no per-batch
    range scan, and repeat scores of one row are BIT-STABLE across
    batch compositions. Serving values outside the training range clip
    to it (the fleet-wide contract: the model never saw them either).
    A model with no captured calibration falls back batch-relative."""

    mode: str = "int8"
    calibrated: bool = False

    def __post_init__(self) -> None:
        if self.mode not in ("int8", "int4"):
            raise ValueError(
                f"quantized scoring mode must be 'int8' or 'int4', "
                f"got {self.mode!r}")

    @property
    def bits(self) -> int:
        return 4 if self.mode == "int4" else 8

    @staticmethod
    def resolve(q: Any) -> Optional["ScoringQuant"]:
        """None | "int8[-calibrated]" | "int4[-calibrated]" |
        ScoringQuant -> Optional[ScoringQuant]."""
        if q is None or isinstance(q, ScoringQuant):
            return q
        s = str(q)
        if s.endswith("-calibrated"):
            return ScoringQuant(s[:-len("-calibrated")], calibrated=True)
        return ScoringQuant(s)


# -- quantized request wire -------------------------------------------------- #

def _pack4_np(q: np.ndarray) -> np.ndarray:
    """(n, d) uint8 in [0,15] -> (n, ceil(d/2)) uint8; feature 2j in the
    low nibble, 2j+1 high — the `data/feature_cache._pack4` layout, so
    the device unpack below and `parallel/bigdata._unpack_dequant` agree
    on the wire format."""
    n, d = q.shape
    if d % 2:
        q = np.concatenate([q, np.zeros((n, 1), np.uint8)], axis=1)
    return (q[:, 0::2] | (q[:, 1::2] << 4)).astype(np.uint8)


def quantize_leaf(arr: np.ndarray, bits: int,
                  lo: Optional[np.ndarray] = None,
                  hi: Optional[np.ndarray] = None
                  ) -> Dict[str, np.ndarray]:
    """Host half of the quantized wire: per-feature affine uint8 of one
    (n,) or (n, d) float leaf. NaN quantizes to lo (uint8 casts of NaN
    are platform-undefined), values outside [lo, hi] clip to the range
    bounds. The "q1" key marks a 1-D leaf so the device side restores
    the original rank.

    With ``lo``/``hi`` given (CALIBRATED ranges captured at fit time),
    the batch's own min/max pass is skipped entirely and the affine
    constants are batch-independent — repeat scores are bit-stable
    across batch compositions. Without them, [lo, hi] is the batch's
    own finite range (a single ±inf must not degenerate the fit and
    corrupt its finite batchmates)."""
    a = np.asarray(arr, np.float32)
    one_d = a.ndim == 1
    if one_d:
        a = a[:, None]
    if lo is None or hi is None:
        import warnings
        with np.errstate(invalid="ignore"), warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            fin = np.where(np.isfinite(a), a, np.nan)
            lo = np.nanmin(fin, axis=0) if a.shape[0] \
                else np.zeros(a.shape[1])
            hi = np.nanmax(fin, axis=0) if a.shape[0] \
                else np.zeros(a.shape[1])
        lo = np.where(np.isfinite(lo), lo, 0.0).astype(np.float32)
        hi = np.where(np.isfinite(hi), hi, lo).astype(np.float32)
    else:
        lo = np.asarray(lo, np.float32).reshape(-1)
        hi = np.asarray(hi, np.float32).reshape(-1)
    qmax = float((1 << bits) - 1)
    scale = np.where(hi > lo, (hi - lo) / qmax, 1.0).astype(np.float32)
    q = np.rint((a - lo) / scale)
    q = np.where(np.isnan(q), 0.0, q)
    q = np.clip(q, 0.0, qmax).astype(np.uint8)
    if bits == 4:
        q = _pack4_np(q)
    return {("q1" if one_d else "q"): q, "scale": scale, "lo": lo}


def dequantize_leaf(wire: Dict[str, Any], bits: int):
    """Device half (pure jnp, traced INSIDE the scoring program so the
    dequant fuses with the first consumer): affine x = q·scale + lo,
    unpacking int4 nibbles first. Mirrors `bigdata._unpack_dequant`."""
    one_d = "q1" in wire
    q = wire["q1"] if one_d else wire["q"]
    scale, lo = wire["scale"], wire["lo"]
    d = scale.shape[0]
    if bits == 4:
        lo_nib = q & jnp.uint8(0x0F)
        hi_nib = (q >> 4).astype(jnp.uint8)
        q = jnp.stack([lo_nib, hi_nib], axis=-1) \
            .reshape(q.shape[0], -1)[:, :d]
    x = q.astype(jnp.float32) * scale + lo
    return x[:, 0] if one_d else x


_WIRE_KEYS = ({"q", "scale", "lo"}, {"q1", "scale", "lo"})


def quantize_wire(tree: Any, bits: int,
                  ranges: Optional[Dict[str, Any]] = None) -> Any:
    """Structure-preserving wire form of a host device-input pytree:
    float numpy leaves become affine uint8 wire dicts, "mask" leaves
    (exact 0/1 floats by the Column contract) become exact uint8, and
    anything already on device (jax arrays from an earlier segment)
    passes through untouched.

    ``ranges`` maps column uid (the tree's top-level keys) to
    ``{"lo": [...], "hi": [...]}`` calibrated fit-time ranges: a leaf
    whose uid has a matching-width entry quantizes against the FIXED
    range (bit-stable across batches); others fall back to the
    batch-relative pass."""
    def leaf_ranges(rng, width: int):
        if rng is None:
            return None, None
        lo = np.asarray(rng.get("lo"), np.float32).reshape(-1)
        hi = np.asarray(rng.get("hi"), np.float32).reshape(-1)
        if lo.shape[0] != width or hi.shape[0] != width:
            return None, None  # stale calibration: batch-relative leaf
        return lo, hi

    def walk(node, key=None, rng=None):
        if isinstance(node, dict):
            return {k: walk(v, k,
                            (ranges.get(k) if ranges is not None
                             and k in ranges else rng))
                    for k, v in node.items()}
        if isinstance(node, np.ndarray) and node.dtype.kind == "f":
            if key == "mask":
                return node.astype(np.uint8)
            if node.ndim in (1, 2):
                width = 1 if node.ndim == 1 else node.shape[1]
                lo, hi = leaf_ranges(rng, width)
                return quantize_leaf(node, bits, lo=lo, hi=hi)
        return node
    return walk(tree)


def dequantize_wire(tree: Any, bits: int) -> Any:
    """Inverse walk, traced inside the jitted program: wire dicts
    dequantize, uint8 mask leaves cast back to the f32 0/1 contract,
    device-resident leaves pass through."""
    def walk(node):
        if isinstance(node, dict):
            if set(node) in _WIRE_KEYS:
                return dequantize_leaf(node, bits)
            return {k: walk(v) for k, v in node.items()}
        if getattr(node, "dtype", None) == np.uint8:
            return node.astype(jnp.float32)
        return node
    return walk(tree)


def _tree_nbytes(tree: Any) -> int:
    """Total array bytes in a pytree (the wire/HBM traffic a dispatch
    ships and returns — the roofline numerator per call)."""
    return int(sum(getattr(leaf, "nbytes", 0)
                   for leaf in jax.tree_util.tree_leaves(tree)))


def pad_dataset(dataset: Dataset, target_rows: int) -> Dataset:
    """Pad a Dataset to `target_rows` by repeating its last row.

    Shape-bucket discipline: the serving batcher and the streaming
    ragged-tail path never hand the compiled scorer a novel batch shape —
    they pad up to an already-compiled bucket and slice the result back.
    Repeating a REAL row (instead of synthesizing nulls) guarantees the
    pad rows take the exact host-encode path the valid rows take, so
    padding can never introduce a new code path or dtype."""
    n = len(dataset)
    if target_rows < n:
        raise ValueError(f"cannot pad {n} rows down to {target_rows}")
    if target_rows == n:
        return dataset
    if n == 0:
        raise ValueError("cannot pad an empty dataset (no row to repeat)")
    pad_idx = np.full(target_rows - n, n - 1, dtype=np.int64)
    return Dataset.concat([dataset, dataset.take(pad_idx)])


def slice_result_tree(value: Any, start: int, stop: int) -> Any:
    """Slice every batch-leading array leaf of a scoring result pytree
    (dicts of arrays, Prediction dicts, bare arrays) to rows
    [start, stop) — the inverse of batch coalescing/padding."""
    if isinstance(value, dict):
        return {k: slice_result_tree(v, start, stop)
                for k, v in value.items()}
    if getattr(value, "ndim", 0) >= 1:
        return value[start:stop]
    return value


def _column_from_device(ftype: type, dev) -> Column:
    """Wrap a device pytree back into a host Column (segment boundary)."""
    if isinstance(dev, dict) and "prediction" in dev:
        return Column(T.Prediction,
                      {k: np.asarray(v) for k, v in dev.items()})
    if isinstance(dev, dict) and "value" in dev:
        return Column(ftype, {
            "value": np.asarray(dev["value"], dtype=np.float64),
            "mask": np.asarray(dev["mask"]) > 0.5})
    return Column(T.OPVector, np.asarray(dev))


class CompiledScorer:
    def __init__(self, model, sharding: Optional[Any] = None,
                 quant: Any = None):
        self.model = model
        # optional jax.sharding.NamedSharding for the batch (row) axis:
        # raw device inputs are placed with it, so the fused program's
        # elementwise/encode work shards across the mesh and XLA inserts
        # any cross-shard collectives (batch scoring is embarrassingly
        # row-parallel, so there are none in practice)
        self.sharding = sharding
        # quantized inference mode (module docstring): request matrix on
        # the narrow wire, fitted tables in narrowed dtypes
        self.quant = ScoringQuant.resolve(quant)
        # calibrated quant ranges: fit-time per-column [lo, hi] persisted
        # with the model (uid -> {"lo": [...], "hi": [...]}). Scale/lo
        # ride as traced ARGUMENTS, so calibrated and batch-relative
        # builds share the same compiled programs (and the fleet's
        # program-sharing signature) — only the wire constants differ.
        self._cal_ranges: Optional[Dict[str, Any]] = None
        if self.quant is not None and self.quant.calibrated:
            cal = getattr(model, "quant_calibration", None)
            if cal:
                self._cal_ranges = dict(cal)
            else:
                import logging
                logging.getLogger(__name__).warning(
                    "calibrated quantization requested but the model "
                    "carries no quant_calibration (artifact predates "
                    "fit-time range capture); falling back to "
                    "batch-relative ranges")
        layers = topological_layers(model.result_features)
        self.generators: List[FeatureGeneratorStage] = list(layers[0]) if layers else []
        ordered: List[Transformer] = []
        for layer in layers[1:]:
            for stage in layer:
                fitted = model.fitted.get(stage.uid)
                if fitted is None:
                    raise RuntimeError(f"Unfitted stage {stage.uid}")
                ordered.append(fitted)
        self._stage_out_uid = {
            s.uid: s.get_output().uid for s in ordered}
        # alternating host/device segments in topo order, split by the
        # shared `is_host_stage` rule (stages/base.py) — the same rule the
        # static validator checks against
        self.segments: List[Tuple[str, List[Transformer]]] = []
        for s in ordered:
            kind = "host" if is_host_stage(s) else "device"
            if not self.segments or self.segments[-1][0] != kind:
                self.segments.append((kind, []))
            self.segments[-1][1].append(s)
        # per-segment needed outputs: a device segment returns ONLY what
        # a later segment's stage or a result feature reads. Everything
        # else stays an XLA-internal value — fusion-eligible instead of
        # a forced HBM materialization (the roofline win: the old
        # every-stage-output contract made each intermediate a program
        # output the device had to write back per call).
        result_uids = {f.uid for f in model.result_features}
        self._seg_out_uids: List[List[str]] = []
        for i, (kind, stages) in enumerate(self.segments):
            produced = {self._stage_out_uid[s.uid] for s in stages}
            needed = set(result_uids)
            for _, later in self.segments[i + 1:]:
                for s2 in later:
                    needed.update(f.uid for f in s2.input_features)
            self._seg_out_uids.append(sorted(produced & needed))
        # instrumented jit: the retrace monitor counts traces per segment
        # (label = stage ops), so per-batch shape drift shows up as churn
        # on a NAMED program instead of silent recompiles
        from transmogrifai_tpu.analysis.retrace import instrumented_jit
        self._seg_labels = [
            "compiled:seg%d[%s]%s" % (
                i, ",".join(s.operation_name for s in stages),
                f"@{self.quant.mode}" if self.quant else "")
            if kind == "device" else None
            for i, (kind, stages) in enumerate(self.segments)]
        self._seg_fns = [
            (instrumented_jit(
                self._make_segment_fn(stages, self._seg_out_uids[i]),
                label=self._seg_labels[i])
             if kind == "device" else None)
            for i, (kind, stages) in enumerate(self.segments)]
        self.device_stages: List[Transformer] = [
            s for kind, stages in self.segments if kind == "device"
            for s in stages]
        # megabyte-scale fitted arrays (tree tables, lifted linear/GLM
        # weights) flow into the jitted segments as ARGUMENTS: closure
        # constants are re-staged host→device on every execution through
        # the serving tunnel, and value-baked weights would force every
        # tenant onto its own compiled program (serving/fleet.py). In
        # quantized mode the stage may narrow its tables (shape-gated
        # dtype rules only, so same-signature tenants narrow alike).
        self._consts: Dict[str, Any] = {}
        for s in self.device_stages:
            c = s.device_constants()
            if c is not None:
                self._consts[s.uid] = (
                    s.narrow_device_constants(c) if self.quant else c)

    # ------------------------------------------------------------------ #

    def _make_segment_fn(self, stages: List[Transformer],
                         out_uids: Optional[List[str]] = None):
        out_uid = self._stage_out_uid
        quant = self.quant

        def seg_fn(consts: Dict[str, Any], encs: Dict[str, Any],
                   dev_vals: Dict[str, Any]):
            if quant is not None:
                # dequant INSIDE the program: XLA fuses the affine
                # x = q·scale + lo into each leaf's first consumer, so
                # the f32 request matrix never lands in HBM at full width
                dev_vals = dequantize_wire(dev_vals, quant.bits)
            vals = dict(dev_vals)
            for stage in stages:
                dev_inputs = [vals.get(f.uid) for f in stage.input_features]
                if stage.uid in consts:
                    out = stage.device_apply_with(
                        consts[stage.uid], encs.get(stage.uid), dev_inputs)
                else:
                    out = stage.device_apply(encs.get(stage.uid), dev_inputs)
                vals[out_uid[stage.uid]] = out
            keep = out_uids if out_uids is not None else \
                [out_uid[s.uid] for s in stages]
            return {u: vals[u] for u in keep}

        return seg_fn

    def _dispatch(self, seg_idx: int, encs: Dict[str, Any],
                  dev_vals: Dict[str, Any]) -> Dict[str, Any]:
        """The ONE device-dispatch site: per-segment dispatch counts land
        in `analysis.retrace.DISPATCHES` (the roofline smoke asserts one
        dispatch per score call on fused plans) and a `device_dispatch`
        event carries the bytes shipped/returned for the current obs
        span (serving batch spans, bench runs) — fusion and wire wins
        are visible per call, not just in aggregate."""
        label = self._seg_labels[seg_idx]
        t0 = time.perf_counter()
        out = self._seg_fns[seg_idx](self._consts, encs, dev_vals)
        DISPATCHES.record(label)
        if TRACER.current() is not None:
            # byte accounting only when a span will actually keep the
            # event — two pytree walks are waste on an untraced hot path
            add_event("device_dispatch", segment=label,
                      bytes_in=_tree_nbytes((encs, dev_vals)),
                      bytes_out=_tree_nbytes(out),
                      # async dispatch: this is time-to-enqueue, not
                      # device execution — the honest per-call host cost
                      dispatch_s=round(time.perf_counter() - t0, 6),
                      quant=self.quant.mode if self.quant else None)
        return out

    def _fused_index(self) -> int:
        """Index of the single trailing device segment, or raise."""
        dev_segs = [i for i, (k, _) in enumerate(self.segments)
                    if k == "device"]
        if len(dev_segs) != 1 or dev_segs[0] != len(self.segments) - 1:
            raise RuntimeError(
                "pipeline does not compile to a single trailing device "
                "segment; use __call__")
        return dev_segs[0]

    @property
    def fusable(self) -> bool:
        """True when the whole pipeline collapses to ONE device program
        per batch shape (host prefix + a single trailing device segment
        — `score_padded` then takes the one-dispatch fast path; plans
        with a host stage BETWEEN device segments fall back to the
        general segmented `__call__`)."""
        cached = getattr(self, "_fusable", None)
        if cached is None:
            try:
                self._fused_index()
                cached = True
            except RuntimeError:
                cached = False
            self._fusable = cached
        return cached

    # the driver's single-chip compile check (__graft_entry__) jits this
    @property
    def _device_fn(self):
        return self._make_segment_fn(self.segments[self._fused_index()][1])

    def fused_jitted(self):
        """The ALREADY-jitted trailing device segment (streaming path —
        shares the compile cache with __call__)."""
        return self._seg_fns[self._fused_index()]

    def host_phase(self, dataset: Dataset):
        """Raw materialization + host-prefix stages + host_prepare for the
        single-trailing-device-segment fast path (driver entry + streaming
        overlap; __call__ handles the general segmented case)."""
        columns: Dict[str, Column] = {}
        for gen in self.generators:
            columns[gen.get_output().uid] = gen.materialize(
                dataset, allow_missing_response=True)
        for kind, stages in self.segments[:-1]:  # host prefix
            if kind != "host":
                raise RuntimeError("host_phase requires a host-prefix plan")
            for stage in stages:
                inputs = [columns[f.uid] for f in stage.input_features]
                columns[self._stage_out_uid[stage.uid]] = \
                    stage.transform(inputs)
        encs: Dict[str, Any] = {}
        for stage in self.device_stages:
            cols = [columns.get(f.uid) for f in stage.input_features]
            enc = stage.host_prepare(cols)
            if enc is not None:
                encs[stage.uid] = enc
        raw_dev: Dict[str, Any] = {}
        for uid, c in columns.items():
            if c.kind not in _HOST_KINDS:
                dv = c.device_value()
                if dv is not None:
                    raw_dev[uid] = dv
        if self.quant is not None:
            # quantize HERE, before placement: streaming workers
            # device_put this pytree, so the narrow wire is what crosses
            # the host→device link (1 byte/elem int8, 0.5 int4)
            raw_dev = quantize_wire(raw_dev, self.quant.bits,
                                    ranges=self._cal_ranges)
        n_rows = len(dataset)
        return (self._place(encs, n_rows), self._place(raw_dev, n_rows),
                columns)

    def _place(self, pytree, n_rows: int):
        """Shard arrays whose leading dim IS the batch axis over the row
        sharding. Matching on `n_rows` (not mere divisibility) keeps
        non-batch arrays — e.g. a (d,) encoding vector whose length
        happens to divide by the shard count — replicated instead of
        feature-axis-sharded (which would be value-correct but insert
        pointless resharding collectives)."""
        if self.sharding is None:
            return pytree
        import jax.tree_util as jtu

        # only dim 0 of the spec shards the row axis; its entry may be an
        # axis name or a tuple of axis names
        spec = self.sharding.spec
        dim0 = spec[0] if len(spec) else None
        axes = (dim0 if isinstance(dim0, tuple)
                else (dim0,) if dim0 is not None else ())
        shards = int(np.prod([self.sharding.mesh.shape[a]
                              for a in axes])) if axes else 1

        def put(a):
            arr = np.asarray(a) if not hasattr(a, "sharding") else a
            if (getattr(arr, "ndim", 0) >= 1 and arr.shape[0] == n_rows
                    and n_rows % shards == 0):
                return jax.device_put(arr, self.sharding)
            return a
        return jtu.tree_map(put, pytree)

    # ------------------------------------------------------------------ #

    def run(self, dataset: Dataset):
        """Execute all segments; returns (dev_vals, columns)."""
        n_rows = len(dataset)
        columns: Dict[str, Column] = {}
        dev_vals: Dict[str, Any] = {}
        for gen in self.generators:
            f = gen.get_output()
            c = gen.materialize(dataset, allow_missing_response=True)
            columns[f.uid] = c
            if c.kind not in _HOST_KINDS:
                dev_vals[f.uid] = c.device_value()
        if self.quant is None:
            # quantized mode defers placement to the dispatch site so
            # the NARROW wire (not the f32 original) crosses the link
            dev_vals = self._place(dev_vals, n_rows)

        for seg_idx, (kind, stages) in enumerate(self.segments):
            if kind == "host":
                for stage in stages:
                    inputs = []
                    for f in stage.input_features:
                        c = columns.get(f.uid)
                        if c is None:  # device-produced → materialize once
                            c = _column_from_device(f.ftype, dev_vals[f.uid])
                            columns[f.uid] = c
                        inputs.append(c)
                    out_col = stage.transform(inputs)
                    uid = self._stage_out_uid[stage.uid]
                    columns[uid] = out_col
                    dv = out_col.device_value()
                    if dv is not None:
                        # quantized mode keeps host outputs HOST-side
                        # until the dispatch site quantizes+places them:
                        # placing here would ship full-width f32 and make
                        # numerics depend on whether sharding is set
                        dev_vals[uid] = dv if self.quant is not None \
                            else self._place(dv, n_rows)
            else:
                encs: Dict[str, Any] = {}
                for stage in stages:
                    cols = [columns.get(f.uid) for f in stage.input_features]
                    enc = stage.host_prepare(cols)
                    if enc is not None:
                        encs[stage.uid] = enc
                args = dev_vals
                if self.quant is not None:
                    # wire form of the still-host-resident leaves only;
                    # device arrays from earlier segments pass through
                    # (quantizing them would round-trip HBM→host)
                    args = self._place(
                        quantize_wire(dev_vals, self.quant.bits,
                                      ranges=self._cal_ranges), n_rows)
                dev_vals.update(
                    self._dispatch(seg_idx, self._place(encs, n_rows), args))
        return dev_vals, columns

    def __call__(self, dataset: Dataset) -> Dict[str, Any]:
        dev_vals, columns = self.run(dataset)
        result: Dict[str, Any] = {}
        for f in self.model.result_features:
            if f.uid in dev_vals:
                result[f.name] = dev_vals[f.uid]
            else:  # host-kind result feature
                result[f.name] = columns[f.uid].data
        return result

    def score_fused(self, dataset: Dataset) -> Dict[str, Any]:
        """One-dispatch scoring for single-trailing-device-segment plans:
        host phase (generators + host prefix + host_prepare + wire
        quantization) then EXACTLY ONE device dispatch of the fused
        program, which returns only the result features. Raises
        RuntimeError on multi-device-segment plans — `__call__` is the
        general fallback."""
        fi = self._fused_index()
        encs, raw_dev, columns = self.host_phase(dataset)
        out = self._dispatch(fi, encs, raw_dev)
        result: Dict[str, Any] = {}
        for f in self.model.result_features:
            if f.uid in out:
                result[f.name] = out[f.uid]
            else:
                c = columns[f.uid]
                dv = c.device_value()
                # raw/host-prefix result features never ride the wire:
                # their original (unquantized) host values are returned
                # exactly, matching __call__'s dev_vals
                result[f.name] = dv if dv is not None else c.data
        return result

    def score_padded(self, dataset: Dataset,
                     pad_to: int) -> Dict[str, Any]:
        """Score `dataset` padded up to `pad_to` rows (a shape bucket),
        returning results for ONLY the valid rows.

        The validity mask is positional — pad rows are appended, so rows
        [0, n_valid) of every result leaf are the real ones and the tail
        is sliced off before anything leaves this call. Each distinct
        `pad_to` value compiles once; every batch size <= `pad_to` then
        reuses that program (the serving batcher's bucket ladder).

        Fusable plans (the serving hot path) route through `score_fused`:
        one device dispatch per call, result features only. Pad rows
        repeat a REAL row, so they never widen the quantized wire's
        per-batch [lo, hi] range — valid-row results are invariant to
        the bucket they were padded to."""
        n_valid = len(dataset)
        padded = pad_dataset(dataset, pad_to)
        out = self.score_fused(padded) if self.fusable else self(padded)
        if pad_to == n_valid:
            return out
        return {name: slice_result_tree(v, 0, n_valid)
                for name, v in out.items()}
