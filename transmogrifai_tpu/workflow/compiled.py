"""CompiledScorer: the fitted DAG as fused XLA program segments.

This is the TPU replacement for both the reference's fused row transform
(`FitStagesUtil.applyOpTransformations`, FitStagesUtil.scala:96-119) and its
Spark-free MLeap scoring path (`local/.../OpWorkflowModelLocal.scala:79-122`):

- host phase (per batch): materialize raw columns, call each jittable
  stage's `host_prepare` (string → ids etc.)
- device phase: consecutive jittable stages compile into ONE `jax.jit`
  program — XLA fuses imputation, one-hot, concat, and the model matmul;
  with a mesh, the batch axis shards over devices.

Topologies where a HostTransformer consumes a device-produced feature
(e.g. `(sibSp + parCh).alias(...)`) split the plan into alternating
host/device SEGMENTS: each device segment is still one fused XLA program,
and device outputs materialize to host columns only when a host stage
actually reads them. A pipeline with no such crossing keeps the single
fused program.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from transmogrifai_tpu import types as T
from transmogrifai_tpu.data.columns import Column
from transmogrifai_tpu.data.dataset import Dataset
from transmogrifai_tpu.features.dag import topological_layers
from transmogrifai_tpu.stages.base import (
    HOST_KINDS as _HOST_KINDS, FeatureGeneratorStage, HostTransformer,
    Transformer, is_host_stage)


def pad_dataset(dataset: Dataset, target_rows: int) -> Dataset:
    """Pad a Dataset to `target_rows` by repeating its last row.

    Shape-bucket discipline: the serving batcher and the streaming
    ragged-tail path never hand the compiled scorer a novel batch shape —
    they pad up to an already-compiled bucket and slice the result back.
    Repeating a REAL row (instead of synthesizing nulls) guarantees the
    pad rows take the exact host-encode path the valid rows take, so
    padding can never introduce a new code path or dtype."""
    n = len(dataset)
    if target_rows < n:
        raise ValueError(f"cannot pad {n} rows down to {target_rows}")
    if target_rows == n:
        return dataset
    if n == 0:
        raise ValueError("cannot pad an empty dataset (no row to repeat)")
    pad_idx = np.full(target_rows - n, n - 1, dtype=np.int64)
    return Dataset.concat([dataset, dataset.take(pad_idx)])


def slice_result_tree(value: Any, start: int, stop: int) -> Any:
    """Slice every batch-leading array leaf of a scoring result pytree
    (dicts of arrays, Prediction dicts, bare arrays) to rows
    [start, stop) — the inverse of batch coalescing/padding."""
    if isinstance(value, dict):
        return {k: slice_result_tree(v, start, stop)
                for k, v in value.items()}
    if getattr(value, "ndim", 0) >= 1:
        return value[start:stop]
    return value


def _column_from_device(ftype: type, dev) -> Column:
    """Wrap a device pytree back into a host Column (segment boundary)."""
    if isinstance(dev, dict) and "prediction" in dev:
        return Column(T.Prediction,
                      {k: np.asarray(v) for k, v in dev.items()})
    if isinstance(dev, dict) and "value" in dev:
        return Column(ftype, {
            "value": np.asarray(dev["value"], dtype=np.float64),
            "mask": np.asarray(dev["mask"]) > 0.5})
    return Column(T.OPVector, np.asarray(dev))


class CompiledScorer:
    def __init__(self, model, sharding: Optional[Any] = None):
        self.model = model
        # optional jax.sharding.NamedSharding for the batch (row) axis:
        # raw device inputs are placed with it, so the fused program's
        # elementwise/encode work shards across the mesh and XLA inserts
        # any cross-shard collectives (batch scoring is embarrassingly
        # row-parallel, so there are none in practice)
        self.sharding = sharding
        layers = topological_layers(model.result_features)
        self.generators: List[FeatureGeneratorStage] = list(layers[0]) if layers else []
        ordered: List[Transformer] = []
        for layer in layers[1:]:
            for stage in layer:
                fitted = model.fitted.get(stage.uid)
                if fitted is None:
                    raise RuntimeError(f"Unfitted stage {stage.uid}")
                ordered.append(fitted)
        self._stage_out_uid = {
            s.uid: s.get_output().uid for s in ordered}
        # alternating host/device segments in topo order, split by the
        # shared `is_host_stage` rule (stages/base.py) — the same rule the
        # static validator checks against
        self.segments: List[Tuple[str, List[Transformer]]] = []
        for s in ordered:
            kind = "host" if is_host_stage(s) else "device"
            if not self.segments or self.segments[-1][0] != kind:
                self.segments.append((kind, []))
            self.segments[-1][1].append(s)
        # instrumented jit: the retrace monitor counts traces per segment
        # (label = stage ops), so per-batch shape drift shows up as churn
        # on a NAMED program instead of silent recompiles
        from transmogrifai_tpu.analysis.retrace import instrumented_jit
        self._seg_fns = [
            (instrumented_jit(
                self._make_segment_fn(stages),
                label="compiled:seg%d[%s]" % (
                    i, ",".join(s.operation_name for s in stages)))
             if kind == "device" else None)
            for i, (kind, stages) in enumerate(self.segments)]
        self.device_stages: List[Transformer] = [
            s for kind, stages in self.segments if kind == "device"
            for s in stages]
        # megabyte-scale fitted arrays (tree tables) flow into the jitted
        # segments as ARGUMENTS: closure constants are re-staged
        # host→device on every execution through the serving tunnel
        self._consts: Dict[str, Any] = {}
        for s in self.device_stages:
            c = s.device_constants()
            if c is not None:
                self._consts[s.uid] = c

    # ------------------------------------------------------------------ #

    def _make_segment_fn(self, stages: List[Transformer]):
        out_uid = self._stage_out_uid

        def seg_fn(consts: Dict[str, Any], encs: Dict[str, Any],
                   dev_vals: Dict[str, Any]):
            vals = dict(dev_vals)
            outs: Dict[str, Any] = {}
            for stage in stages:
                dev_inputs = [vals.get(f.uid) for f in stage.input_features]
                if stage.uid in consts:
                    out = stage.device_apply_with(
                        consts[stage.uid], encs.get(stage.uid), dev_inputs)
                else:
                    out = stage.device_apply(encs.get(stage.uid), dev_inputs)
                vals[out_uid[stage.uid]] = out
                outs[out_uid[stage.uid]] = out
            return outs

        return seg_fn

    def _fused_index(self) -> int:
        """Index of the single trailing device segment, or raise."""
        dev_segs = [i for i, (k, _) in enumerate(self.segments)
                    if k == "device"]
        if len(dev_segs) != 1 or dev_segs[0] != len(self.segments) - 1:
            raise RuntimeError(
                "pipeline does not compile to a single trailing device "
                "segment; use __call__")
        return dev_segs[0]

    # the driver's single-chip compile check (__graft_entry__) jits this
    @property
    def _device_fn(self):
        return self._make_segment_fn(self.segments[self._fused_index()][1])

    def fused_jitted(self):
        """The ALREADY-jitted trailing device segment (streaming path —
        shares the compile cache with __call__)."""
        return self._seg_fns[self._fused_index()]

    def host_phase(self, dataset: Dataset):
        """Raw materialization + host-prefix stages + host_prepare for the
        single-trailing-device-segment fast path (driver entry + streaming
        overlap; __call__ handles the general segmented case)."""
        columns: Dict[str, Column] = {}
        for gen in self.generators:
            columns[gen.get_output().uid] = gen.materialize(
                dataset, allow_missing_response=True)
        for kind, stages in self.segments[:-1]:  # host prefix
            if kind != "host":
                raise RuntimeError("host_phase requires a host-prefix plan")
            for stage in stages:
                inputs = [columns[f.uid] for f in stage.input_features]
                columns[self._stage_out_uid[stage.uid]] = \
                    stage.transform(inputs)
        encs: Dict[str, Any] = {}
        for stage in self.device_stages:
            cols = [columns.get(f.uid) for f in stage.input_features]
            enc = stage.host_prepare(cols)
            if enc is not None:
                encs[stage.uid] = enc
        raw_dev: Dict[str, Any] = {}
        for uid, c in columns.items():
            if c.kind not in _HOST_KINDS:
                dv = c.device_value()
                if dv is not None:
                    raw_dev[uid] = dv
        n_rows = len(dataset)
        return (self._place(encs, n_rows), self._place(raw_dev, n_rows),
                columns)

    def _place(self, pytree, n_rows: int):
        """Shard arrays whose leading dim IS the batch axis over the row
        sharding. Matching on `n_rows` (not mere divisibility) keeps
        non-batch arrays — e.g. a (d,) encoding vector whose length
        happens to divide by the shard count — replicated instead of
        feature-axis-sharded (which would be value-correct but insert
        pointless resharding collectives)."""
        if self.sharding is None:
            return pytree
        import jax.tree_util as jtu

        # only dim 0 of the spec shards the row axis; its entry may be an
        # axis name or a tuple of axis names
        spec = self.sharding.spec
        dim0 = spec[0] if len(spec) else None
        axes = (dim0 if isinstance(dim0, tuple)
                else (dim0,) if dim0 is not None else ())
        shards = int(np.prod([self.sharding.mesh.shape[a]
                              for a in axes])) if axes else 1

        def put(a):
            arr = np.asarray(a) if not hasattr(a, "sharding") else a
            if (getattr(arr, "ndim", 0) >= 1 and arr.shape[0] == n_rows
                    and n_rows % shards == 0):
                return jax.device_put(arr, self.sharding)
            return a
        return jtu.tree_map(put, pytree)

    # ------------------------------------------------------------------ #

    def run(self, dataset: Dataset):
        """Execute all segments; returns (dev_vals, columns)."""
        n_rows = len(dataset)
        columns: Dict[str, Column] = {}
        dev_vals: Dict[str, Any] = {}
        for gen in self.generators:
            f = gen.get_output()
            c = gen.materialize(dataset, allow_missing_response=True)
            columns[f.uid] = c
            if c.kind not in _HOST_KINDS:
                dev_vals[f.uid] = c.device_value()
        dev_vals = self._place(dev_vals, n_rows)

        for (kind, stages), jfn in zip(self.segments, self._seg_fns):
            if kind == "host":
                for stage in stages:
                    inputs = []
                    for f in stage.input_features:
                        c = columns.get(f.uid)
                        if c is None:  # device-produced → materialize once
                            c = _column_from_device(f.ftype, dev_vals[f.uid])
                            columns[f.uid] = c
                        inputs.append(c)
                    out_col = stage.transform(inputs)
                    uid = self._stage_out_uid[stage.uid]
                    columns[uid] = out_col
                    dv = out_col.device_value()
                    if dv is not None:
                        dev_vals[uid] = self._place(dv, n_rows)
            else:
                encs: Dict[str, Any] = {}
                for stage in stages:
                    cols = [columns.get(f.uid) for f in stage.input_features]
                    enc = stage.host_prepare(cols)
                    if enc is not None:
                        encs[stage.uid] = enc
                dev_vals.update(jfn(self._consts, self._place(encs, n_rows),
                                    dev_vals))
        return dev_vals, columns

    def __call__(self, dataset: Dataset) -> Dict[str, Any]:
        dev_vals, columns = self.run(dataset)
        result: Dict[str, Any] = {}
        for f in self.model.result_features:
            if f.uid in dev_vals:
                result[f.name] = dev_vals[f.uid]
            else:  # host-kind result feature
                result[f.name] = columns[f.uid].data
        return result

    def score_padded(self, dataset: Dataset,
                     pad_to: int) -> Dict[str, Any]:
        """Score `dataset` padded up to `pad_to` rows (a shape bucket),
        returning results for ONLY the valid rows.

        The validity mask is positional — pad rows are appended, so rows
        [0, n_valid) of every result leaf are the real ones and the tail
        is sliced off before anything leaves this call. Each distinct
        `pad_to` value compiles once; every batch size <= `pad_to` then
        reuses that program (the serving batcher's bucket ladder)."""
        n_valid = len(dataset)
        out = self(pad_dataset(dataset, pad_to))
        if pad_to == n_valid:
            return out
        return {name: slice_result_tree(v, 0, n_valid)
                for name, v in out.items()}
