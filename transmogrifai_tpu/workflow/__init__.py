from transmogrifai_tpu.workflow.workflow import Workflow, WorkflowModel

__all__ = ["Workflow", "WorkflowModel"]
