from transmogrifai_tpu.workflow.workflow import Workflow, WorkflowModel
from transmogrifai_tpu.workflow.params import OpParams, ReaderParams
from transmogrifai_tpu.workflow.runner import RunResult, WorkflowRunner

__all__ = ["Workflow", "WorkflowModel", "OpParams", "ReaderParams",
           "RunResult", "WorkflowRunner"]
