"""WorkflowRunner: train / score / streaming-score / features / evaluate.

Reference parity: `core/.../OpWorkflowRunner.scala:296-440` (run-type
dispatch driven by OpParams, streaming loop :233-262, result types) and
`OpApp.scala:49,191` (the application shell the CLI invokes).

TPU-first: scoring writes parquet (columnar) instead of Avro; the
streaming loop drives `WorkflowModel.score_stream` so host encode of the
next micro-batch overlaps device compute; per-phase timings are collected
by `RunProfile` (the OpSparkListener analogue) and written beside the
metrics.
"""

from __future__ import annotations

import json
import logging
import os
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional

import numpy as np

from transmogrifai_tpu.data.dataset import Dataset
from transmogrifai_tpu.obs import export as obs_export
from transmogrifai_tpu.obs import goodput as obs_goodput
from transmogrifai_tpu.obs.trace import TRACER, new_run_id
from transmogrifai_tpu.utils import profiling
from transmogrifai_tpu.utils.profiling import RunProfile
from transmogrifai_tpu.workflow.params import OpParams, ReaderParams
from transmogrifai_tpu.workflow.workflow import Workflow, WorkflowModel

log = logging.getLogger(__name__)

RUN_TYPES = ("train", "score", "streaming-score", "features", "evaluate",
             "serve")


@dataclass
class RunResult:
    """OpWorkflowRunnerResult analogue."""

    run_type: str
    metrics: Dict[str, Any] = field(default_factory=dict)
    model_location: Optional[str] = None
    write_location: Optional[str] = None
    profile: Optional[Dict[str, Any]] = None
    batches: int = 0

    def to_json(self) -> Dict[str, Any]:
        return {"run_type": self.run_type, "metrics": self.metrics,
                "model_location": self.model_location,
                "write_location": self.write_location,
                "profile": self.profile, "batches": self.batches}


def _reader_from_params(rp: ReaderParams):
    from transmogrifai_tpu.readers import DataReaders
    if rp.format == "csv":
        return DataReaders.csv(rp.path, key_column=rp.key_column)
    if rp.format == "parquet":
        return DataReaders.parquet(rp.path, key_column=rp.key_column)
    if rp.format == "stream":
        if rp.path and rp.path.endswith(".parquet"):
            return DataReaders.stream(parquet_path=rp.path,
                                      batch_size=rp.batch_size)
        return DataReaders.stream(csv_path=rp.path, batch_size=rp.batch_size)
    raise ValueError(f"Unknown reader format {rp.format!r}")


class WorkflowRunner:
    """Dispatch a workflow run (OpWorkflowRunner.scala:70-131 ctor shape:
    workflow + train/score/evaluation readers + evaluator + the features
    needed to wire scoring outputs)."""

    def __init__(self, workflow: Workflow, train_reader=None,
                 score_reader=None, evaluation_reader=None, evaluator=None,
                 label_feature=None, prediction_feature=None):
        self.workflow = workflow
        self.train_reader = train_reader
        self.score_reader = score_reader
        self.evaluation_reader = evaluation_reader
        self.evaluator = evaluator
        self.label_feature = label_feature
        self.prediction_feature = prediction_feature
        self._end_handlers: List = []

    def add_application_end_handler(self, fn) -> "WorkflowRunner":
        """Callback receiving the RunProfile when a run finishes
        (OpWorkflowRunner.addApplicationEndHandler)."""
        self._end_handlers.append(fn)
        return self

    # ------------------------------------------------------------------ #

    def run(self, run_type: str, params: OpParams) -> RunResult:
        if run_type not in RUN_TYPES:
            raise ValueError(
                f"run_type must be one of {RUN_TYPES}, got {run_type!r}")
        log.info("Assuming OP params: %s", json.dumps(params.to_json()))
        run_id = new_run_id()
        profile = RunProfile(run_type=run_type,
                             custom_tag_name=params.custom_tag_name,
                             custom_tag_value=params.custom_tag_value,
                             run_id=run_id)
        self.workflow.set_parameters(params)
        dispatch = {
            "train": self._train, "score": self._score,
            "streaming-score": self._streaming_score,
            "features": self._features, "evaluate": self._evaluate,
            "serve": self._serve,
        }
        # the run ROOT span: every phase, stage fit, ingest worker, sweep
        # block, retry backoff, and serving batch below nests under one
        # correlation id — exported as a single Perfetto timeline and
        # rolled into the goodput report
        event_log = None
        if params.trace_location:
            event_log = obs_export.EventLog(
                params.trace_location + ".events.jsonl", run_id=run_id)
            obs_export.install_event_log(event_log)
            obs_export.emit_event("run_start", run_type=run_type)
        try:
            # trace_id=run_id: the Perfetto trace, the RunProfile, and
            # the JSONL event log share ONE correlation id
            with TRACER.span(f"run:{run_type}", category="run",
                             new_trace=True, trace_id=run_id,
                             run_id=run_id, run_type=run_type) as root:
                result = dispatch[run_type](params, profile)
        finally:
            if event_log is not None:
                obs_export.emit_event("run_end")
                obs_export.uninstall_event_log(event_log)
                event_log.close()
        spans = TRACER.trace_spans(root.trace_id)
        profile.goodput = obs_goodput.build_report(root, spans).to_json()
        result.profile = profile.to_json()
        if params.trace_location:
            obs_export.write_chrome_trace(params.trace_location, spans)
            log.info("trace written to %s (%d spans, run %s)",
                     params.trace_location, len(spans), run_id)
        if params.metrics_location:
            os.makedirs(params.metrics_location, exist_ok=True)
            with open(os.path.join(params.metrics_location,
                                   f"{run_type}-metrics.json"), "w") as f:
                json.dump(result.to_json(), f, indent=2, default=str)
        if params.log_stage_metrics:
            log.info("%s", profile.pretty())
        for fn in self._end_handlers:
            fn(profile)
        return result

    # ------------------------------------------------------------------ #

    def _resolve_reader(self, default, params: OpParams, name: str,
                        model: Optional[WorkflowModel] = None):
        rp = params.reader_params.get(name)
        if rp is not None and rp.path:
            reader = _reader_from_params(rp)
        elif default is None:
            raise ValueError(
                f"Run requires a {name!r} reader: construct the runner with "
                f"one or put reader_params[{name!r}].path in the params")
        else:
            reader = default
        if model is not None:
            _ensure_schema(reader, model)
        return reader

    def _train(self, params: OpParams, profile: RunProfile) -> RunResult:
        reader = self._resolve_reader(self.train_reader, params, "train")
        with profile.phase(profiling.DATA_READING):
            ds = reader.read(self.workflow._raw_features())
        # mesh params: build the (sweep, data) device mesh so selector
        # sweeps run the distributed work-stealing scheduler
        mesh = params.mesh.build() if params.mesh is not None else None
        with profile.phase(profiling.TRAINING, n_rows=len(ds)):
            model = self.workflow.set_input_dataset(ds).train(mesh=mesh)
        metrics: Dict[str, Any] = {}
        if self.prediction_feature is not None:
            fitted = model.fitted.get(self.prediction_feature.origin_stage.uid)
            summary = getattr(fitted, "summary", None)
            if summary is not None:
                metrics = {"train": summary.train_metrics,
                           "holdout": summary.holdout_metrics,
                           "best_model": summary.best_model,
                           "best_grid": summary.best_grid}
        loc = params.model_location
        if loc:
            model.save(loc)
        return RunResult("train", metrics=metrics, model_location=loc)

    def _load_model(self, params: OpParams) -> WorkflowModel:
        if not params.model_location:
            raise ValueError("model_location required")
        # custom_params["verify_model"]: false is the params-JSON escape
        # hatch for artifacts saved before integrity manifests existed
        verify = bool(params.custom_params.get("verify_model", True))
        return WorkflowModel.load(params.model_location, verify=verify)

    def _score(self, params: OpParams, profile: RunProfile) -> RunResult:
        model = self._load_model(params)
        reader = self._resolve_reader(self.score_reader, params, "score",
                                      model=model)
        with profile.phase(profiling.DATA_READING):
            ds = reader.read([f for f in model.result_features])
        with profile.phase(profiling.SCORING, n_rows=len(ds)):
            scores = model.score_compiled(ds)
        loc = params.write_location
        if loc:
            os.makedirs(loc, exist_ok=True)
            _write_scores(scores, model, os.path.join(loc, "scores.parquet"))
        metrics: Dict[str, Any] = {"n_rows": len(ds)}
        if self.evaluator is not None and self.label_feature is not None \
                and self.prediction_feature is not None:
            with profile.phase(profiling.EVALUATION):
                try:
                    label_col = self.label_feature.origin_stage.materialize(ds)
                except KeyError:
                    # scoring data legitimately has no label column —
                    # scores are still written, evaluation just skips
                    log.info("score: label column absent, skipping "
                             "evaluation")
                else:
                    metrics["evaluation"] = self._eval_scores(
                        model, ds, scores, label_col)
        return RunResult("score", metrics=metrics, write_location=loc)

    def _streaming_score(self, params: OpParams,
                         profile: RunProfile) -> RunResult:
        model = self._load_model(params)
        reader = self._resolve_reader(self.score_reader, params, "score",
                                      model=model)
        if not hasattr(reader, "stream"):
            raise ValueError("streaming-score requires a StreamingReader")
        loc = params.write_location
        if loc:
            os.makedirs(loc, exist_ok=True)
        n_batches = 0
        n_rows = 0
        # per-batch consume-to-consume latency through the pipelined
        # scorer, into the serving metrics histogram type — p50 tracks
        # steady-state, p99 exposes stalls/recompiles (ML Goodput:
        # untracked stalls, not FLOPs, dominate fleet efficiency)
        from transmogrifai_tpu.obs.metrics import Histogram
        batch_latency = Histogram()
        with profile.phase(profiling.SCORING):
            t_prev = time.perf_counter()
            for out in model.score_stream(reader.stream()):
                if loc:
                    _write_scores(out, model, os.path.join(
                        loc, f"scores-{n_batches:05d}.parquet"))
                first = next(iter(out.values()))
                n_rows += _batch_len(first)
                n_batches += 1
                now = time.perf_counter()
                batch_latency.observe(now - t_prev)
                t_prev = now
        profile.record_histogram("streaming_batch_latency_s", batch_latency)
        return RunResult("streaming-score",
                         metrics={"n_rows": n_rows, "batches": n_batches,
                                  "batch_latency": batch_latency.summary()},
                         write_location=loc, batches=n_batches)

    def _serve(self, params: OpParams, profile: RunProfile) -> RunResult:
        """Online scoring run type: load the model, AOT-warm the shape
        buckets, and serve `/score` `/healthz` `/metrics` `/reload` until
        interrupted (or for `custom_params["serve_duration_s"]` seconds —
        the testable bounded mode). The serving metrics registry is
        written into the run result, so a bounded serve doubles as a
        micro-benchmark record."""
        from transmogrifai_tpu.serving.http import serve as http_serve
        from transmogrifai_tpu.serving.service import ScoringService
        from transmogrifai_tpu.workflow.params import ServingParams

        if not params.model_location:
            raise ValueError("model_location required")
        sp = params.serving or ServingParams()
        with profile.phase(profiling.SCORING):
            service = ScoringService.from_path(
                params.model_location, config=sp.to_config())
            service.start()
        server, thread = http_serve(service, host=sp.host, port=sp.port,
                                    block=False)
        log.info("serving %s on http://%s:%d (buckets %s)",
                 params.model_location, sp.host, server.port,
                 list(service.ladder))
        duration = params.custom_params.get("serve_duration_s")
        try:
            if duration is not None:
                time.sleep(float(duration))
            else:
                while thread.is_alive():  # until KeyboardInterrupt
                    thread.join(1.0)
        except KeyboardInterrupt:
            log.info("serve: interrupted, shutting down")
        finally:
            server.shutdown()
            server.server_close()
            service.stop()
        profile.record_histogram(
            "request_latency_s",
            service.registry.histogram("serving_request_latency_seconds"))
        return RunResult(
            "serve",
            metrics={"port": server.port,
                     "model_version": service.health()["model_version"],
                     "serving": service.registry.to_json()},
            model_location=params.model_location)

    def _features(self, params: OpParams, profile: RunProfile) -> RunResult:
        """Materialize + write the transformed feature columns
        (computeFeatures run type)."""
        model = self._load_model(params)
        reader = self._resolve_reader(self.score_reader, params, "score",
                                      model=model)
        with profile.phase(profiling.DATA_READING):
            ds = reader.read([f for f in model.result_features])
        with profile.phase(profiling.FEATURE_ENG, n_rows=len(ds)):
            columns = model.score(ds, keep_intermediate=True)
        loc = params.write_location
        if loc:
            os.makedirs(loc, exist_ok=True)
            arrays: Dict[str, np.ndarray] = {}
            for f in model.result_features:
                col = columns[f.uid]
                if col.kind == "vector":
                    arr = np.asarray(col.data)
                    for j in range(arr.shape[1]):
                        arrays[f"{f.name}_{j}"] = arr[:, j].astype(np.float64)
            Dataset(arrays, {k: __import__(
                "transmogrifai_tpu.types", fromlist=["Real"]).Real
                for k in arrays}).to_parquet(
                os.path.join(loc, "features.parquet"))
        return RunResult("features", metrics={"n_rows": len(ds)},
                         write_location=loc)

    def _evaluate(self, params: OpParams, profile: RunProfile) -> RunResult:
        if self.evaluator is None or self.label_feature is None or \
                self.prediction_feature is None:
            raise ValueError(
                "evaluate requires evaluator + label_feature + "
                "prediction_feature on the runner")
        model = self._load_model(params)
        reader = self._resolve_reader(
            self.evaluation_reader or self.score_reader, params,
            "evaluation", model=model)
        with profile.phase(profiling.DATA_READING):
            ds = reader.read([f for f in model.result_features])
        with profile.phase(profiling.EVALUATION, n_rows=len(ds)):
            scores = model.score_compiled(ds)
            metrics = self._eval_scores(model, ds, scores)
        loc = params.write_location
        if loc:
            os.makedirs(loc, exist_ok=True)
            _write_scores(scores, model, os.path.join(loc, "scores.parquet"))
        return RunResult("evaluate", metrics=metrics, write_location=loc)

    # ------------------------------------------------------------------ #

    def _eval_scores(self, model: WorkflowModel, ds: Dataset,
                     scores: Dict[str, Any], label_col=None) -> Dict[str, Any]:
        from transmogrifai_tpu import types as T
        from transmogrifai_tpu.data.columns import Column
        if label_col is None:
            label_col = self.label_feature.origin_stage.materialize(ds)
        # look the prediction up on the LOADED model's graph: derived
        # feature names embed process-local uid counters, so the rebuilt
        # app graph's name need not match the saved one
        pred_name = next(
            (f.name for f in model.result_features
             if issubclass(f.ftype, T.Prediction)),
            self.prediction_feature.name)
        pred = scores[pred_name]
        pcol = Column(T.Prediction,
                      {k: np.asarray(v) for k, v in pred.items()})
        m = self.evaluator.evaluate(label_col, pcol).to_json()
        return {k: v for k, v in m.items() if not isinstance(v, list)}


def _ensure_schema(reader, model: WorkflowModel) -> None:
    """Schema-less file readers infer types that can clash with the model's
    raw feature types (e.g. integer-looking PickLists); inject the model's
    own raw schema (the reference derives reader schema from the features,
    DataReader.scala:221-259)."""
    schema = {}
    for rf in model.result_features:
        for f in rf.raw_features():
            schema[f.name] = f.ftype
    for attr in ("_schema", "schema"):
        if hasattr(reader, attr) and getattr(reader, attr) is None:
            setattr(reader, attr, schema)
            break


def _batch_len(v) -> int:
    if isinstance(v, dict):
        return int(np.asarray(next(iter(v.values()))).shape[0])
    return int(np.asarray(v).shape[0])


def _write_scores(scores: Dict[str, Any], model: WorkflowModel,
                  path: str) -> None:
    """Flatten result features into a columnar parquet file
    (saveScores analogue; parquet instead of Avro)."""
    import transmogrifai_tpu.types as T
    arrays: Dict[str, np.ndarray] = {}
    schema: Dict[str, type] = {}
    for name, v in scores.items():
        if isinstance(v, dict) and "prediction" in v:
            arrays[f"{name}_prediction"] = np.asarray(
                v["prediction"], dtype=np.float64)
            schema[f"{name}_prediction"] = T.Real
            prob = np.asarray(v["probability"])
            if prob.ndim == 2:
                for j in range(prob.shape[1]):
                    arrays[f"{name}_probability_{j}"] = prob[:, j].astype(
                        np.float64)
                    schema[f"{name}_probability_{j}"] = T.Real
        elif isinstance(v, dict) and "value" in v:
            val = np.asarray(v["value"], dtype=np.float64).copy()
            mask = np.asarray(v["mask"]).astype(bool)
            val[~mask] = np.nan
            arrays[name] = val
            schema[name] = T.Real
        else:
            arr = np.asarray(v)
            if arr.dtype == object:
                arrays[name] = np.array(
                    [None if x is None else str(x) for x in arr],
                    dtype=object)
                schema[name] = T.Text
            elif arr.ndim == 1:
                arrays[name] = arr.astype(np.float64)
                schema[name] = T.Real
            else:
                for j in range(arr.shape[1]):
                    arrays[f"{name}_{j}"] = arr[:, j].astype(np.float64)
                    schema[f"{name}_{j}"] = T.Real
    Dataset(arrays, schema).to_parquet(path)
