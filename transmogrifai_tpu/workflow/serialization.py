"""Model persistence: JSON manifest + per-stage params, crash-consistent.

Reference parity: `core/.../OpWorkflowModelWriter.scala:56-207` (single
`op-model.json` manifest: uids, features, stages, params, version) and
`OpWorkflowModelReader.scala:63-300` (rebuild stages via registry, re-link
features by uid — `resolveFeatures:182`).

Layout: `<path>/op-model.json` + `<path>/arrays.npz` + the integrity
manifest `<path>/integrity.json`. Small stage params inline as JSON;
numeric payloads of >= NPZ_MIN_SIZE elements offload to the npz
(`_offload_arrays`) so megabyte-scale tree tables and weight matrices
round-trip as binary arrays, not PyObject lists. Extract-fn raw
features round-trip only through the `@extract_fn` registry
(`utils/fnser.py`); saving an unregistered closure raises at save time.

Crash consistency (`save_model`): every file is written into a TEMP
SIBLING directory and fsynced; the integrity manifest (per-file sha256 +
size) is written LAST; only then is the directory renamed into place —
with any previous model renamed ASIDE first and deleted only after the
new one is live, so a crash at any point leaves either the old model,
the new model, or both recoverable, never a torn mix. `load_model`
verifies the integrity manifest before deserializing anything: a
truncated, bit-flipped, or mid-save-killed directory raises a structured
`ModelIntegrityError` instead of loading garbage (the serving layer
turns that into a rejected `/reload` while the resident version keeps
serving).
"""

from __future__ import annotations

import json
import logging
import os
import shutil
from typing import Any, Dict, List, Optional

import numpy as np

from transmogrifai_tpu import types as T
from transmogrifai_tpu.features.feature import Feature
from transmogrifai_tpu.runtime.faults import SITE_WRITE_FILE, fault_point
from transmogrifai_tpu.runtime.integrity import (
    commit_staged_dir as _commit_staged_dir, fsync_dir as _fsync_dir,
    fsync_file as _fsync_file, sha256_file as _sha256_file)
from transmogrifai_tpu.stages.base import (
    FeatureGeneratorStage, StageRegistry, Transformer)

log = logging.getLogger(__name__)

MANIFEST = "op-model.json"
ARRAYS = "arrays.npz"
INTEGRITY = "integrity.json"
WARMUP = "warmup.json"
VERSION = 1
WARMUP_VERSION = 1
INTEGRITY_VERSION = 1
NPZ_MIN_SIZE = 64  # numeric payloads at/above this many elements offload


class ModelIntegrityError(RuntimeError):
    """A serialized model directory failed integrity verification
    (missing/truncated/bit-flipped file, or a save that died before the
    integrity manifest landed). Structured: carries the dir and reason."""

    def __init__(self, path: str, reason: str):
        self.path = path
        self.reason = reason
        super().__init__(
            f"model artifact {path!r} failed integrity check: {reason}")


def _offload_arrays(value: Any, store: Dict[str, np.ndarray],
                    prefix: str) -> Any:
    """Replace large numeric lists/arrays inside stage params with
    `{"__npz__": key}` references; the arrays land in one arrays.npz
    beside the manifest (OpWorkflowModelWriter's per-stage payload dirs,
    sized for real models — a 20-tree forest no longer round-trips
    through JSON text)."""
    if isinstance(value, dict):
        return {k: _offload_arrays(v, store, f"{prefix}.{k}")
                for k, v in value.items()}
    if isinstance(value, (np.ndarray, list)):
        try:
            arr = np.asarray(value)
        except Exception:
            arr = None
        if arr is not None and arr.dtype != object \
                and arr.dtype.kind in "biuf" and arr.size >= NPZ_MIN_SIZE:
            key = f"{prefix}#{len(store)}"
            store[key] = arr
            return {"__npz__": key}
        if isinstance(value, np.ndarray):
            return value.tolist()
        return [_offload_arrays(v, store, f"{prefix}[{i}]")
                for i, v in enumerate(value)]
    if isinstance(value, (np.integer,)):
        return int(value)
    if isinstance(value, (np.floating,)):
        return float(value)
    return value


def _restore_arrays(value: Any, npz) -> Any:
    if isinstance(value, dict):
        if set(value.keys()) == {"__npz__"}:
            if npz is None:
                raise ValueError(
                    "manifest references arrays.npz but the file is missing")
            return npz[value["__npz__"]]
        return {k: _restore_arrays(v, npz) for k, v in value.items()}
    if isinstance(value, list):
        return [_restore_arrays(v, npz) for v in value]
    return value


def _feature_entry(f: Feature) -> Dict[str, Any]:
    return {
        "uid": f.uid, "name": f.name, "ftype": f.ftype.__name__,
        "is_response": f.is_response,
        "origin_stage": f.origin_stage.uid if f.origin_stage else None,
        "parents": [p.uid for p in f.parents],
    }


def save_model(model, path: str, overwrite: bool = True,
               strict_fns: bool = False,
               extra_json: Optional[Dict[str, Any]] = None) -> None:
    """Crash-consistent save: serialize into a temp sibling dir, fsync,
    write the integrity manifest LAST, then rename into place. With
    `overwrite=True` an existing model is renamed ASIDE (never deleted
    before the replacement is live) — a crash at any instruction leaves
    a loadable old model, a loadable new model, or both; never a torn
    directory that `load_model` would accept.

    `strict_fns=True` forbids cloudpickle payloads: every callable
    param (extract fns, row-op lambdas) must be `@extract_fn`-registered
    or module-level, or the save RAISES — nothing bytecode-pinned ships
    silently (VERDICT r2 #6; reference analogue: macro-captured class
    names, `FeatureBuilderMacros.scala:40-95`).

    `extra_json` maps extra file names (e.g. "insights.json" with the
    continual loop's training fingerprint) to JSON-serializable payloads
    staged WITH the model: they ride the same temp-sibling commit and
    are listed in the integrity manifest, so sidecar metadata can never
    be torn relative to the model it describes."""
    from transmogrifai_tpu.utils import fnser
    if strict_fns:
        token = fnser.push_strict()
        try:
            return save_model(model, path, overwrite, strict_fns=False,
                              extra_json=extra_json)
        finally:
            fnser.pop_strict(token)
    for name in extra_json or ():
        if name in (MANIFEST, ARRAYS, INTEGRITY) or os.sep in name:
            raise ValueError(f"extra_json name {name!r} collides with a "
                             "reserved model file")
    path = os.path.normpath(path)
    if os.path.exists(os.path.join(path, MANIFEST)) and not overwrite:
        raise FileExistsError(os.path.join(path, MANIFEST))

    features: Dict[str, Feature] = {}
    order: List[str] = []
    for rf in model.result_features:
        for f in rf.traverse():
            if f.uid not in features:
                features[f.uid] = f
                order.append(f.uid)

    stage_entries = []
    seen = set()
    arrays: Dict[str, np.ndarray] = {}
    for f in features.values():
        stage = f.origin_stage
        if stage is None or stage.uid in seen:
            continue
        seen.add(stage.uid)
        fitted = model.fitted.get(stage.uid, stage)
        entry = {
            "uid": stage.uid,
            "class": type(fitted).__name__,
            "estimator_class": type(getattr(stage, "_estimator", stage)).__name__,
            "params": _offload_arrays(fitted.get_params(), arrays, stage.uid),
            "inputs": [p.uid for p in stage.input_features],
        }
        stage_entries.append(entry)

    manifest = {
        "version": VERSION,
        "result_features": [f.uid for f in model.result_features],
        "features": [_feature_entry(features[uid]) for uid in order],
        "stages": stage_entries,
    }
    # fit-time quantization calibration rides the sealed manifest (it
    # is small JSON keyed by the same uids): a reloaded model serves
    # bit-stable calibrated quant without re-deriving anything
    cal = getattr(model, "quant_calibration", None)
    if cal:
        manifest["quant_calibration"] = cal

    # -- stage everything in a temp sibling (same filesystem => same-dir
    #    rename is atomic); a kill in here never touches `path` ---------- #
    tmp = f"{path}.tmp-{os.getpid()}"
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    try:
        names = []
        if arrays:
            fault_point(SITE_WRITE_FILE)
            np.savez_compressed(os.path.join(tmp, ARRAYS), **arrays)
            _fsync_file(os.path.join(tmp, ARRAYS))
            names.append(ARRAYS)
        fault_point(SITE_WRITE_FILE)
        with open(os.path.join(tmp, MANIFEST), "w") as fh:
            json.dump(manifest, fh)
            fh.flush()
            os.fsync(fh.fileno())
        names.append(MANIFEST)
        for name, payload in (extra_json or {}).items():
            fault_point(SITE_WRITE_FILE)
            with open(os.path.join(tmp, name), "w") as fh:
                json.dump(payload, fh, default=str)
                fh.flush()
                os.fsync(fh.fileno())
            names.append(name)
        # integrity manifest LAST: its presence asserts every other file
        # is complete, its checksums pin their bytes
        fault_point(SITE_WRITE_FILE)
        integrity = {
            "integrity_version": INTEGRITY_VERSION,
            "files": {name: {
                "sha256": _sha256_file(os.path.join(tmp, name)),
                "bytes": os.path.getsize(os.path.join(tmp, name)),
            } for name in names},
        }
        with open(os.path.join(tmp, INTEGRITY), "w") as fh:
            json.dump(integrity, fh)
            fh.flush()
            os.fsync(fh.fileno())
        _fsync_dir(tmp)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise

    # -- swap into place: the old model is renamed aside, not deleted,
    #    until the new one is live (shared staged-dir protocol) ---------- #
    _commit_staged_dir(tmp, path)


def model_fingerprint(path: str) -> str:
    """Stable short id for a serialized model dir: sha256 over the
    manifest bytes + the arrays.npz bytes. Deterministic per dir (the
    bytes ARE the identity) and any retrain/param change moves it; two
    separate save() calls need not match (npz zip metadata differs).
    The serving layer uses this as the hot-swap version id, so '/reload'
    of an unchanged dir is detectable as a no-op and a rollback target
    is identified by content, not by path."""
    import hashlib
    h = hashlib.sha256()
    with open(os.path.join(path, MANIFEST), "rb") as fh:
        h.update(fh.read())
    npz_path = os.path.join(path, ARRAYS)
    if os.path.exists(npz_path):
        with open(npz_path, "rb") as fh:
            for chunk in iter(lambda: fh.read(1 << 20), b""):
                h.update(chunk)
    return h.hexdigest()[:12]


def save_warmup_manifest(model_dir: str, payload: Dict[str, Any]) -> bool:
    """Persist an AOT warmup manifest BESIDE a serialized model (the
    serving layer's cold-start record: bucket ladder, scoring-signature,
    cold warmup wall seconds, compile counts). Written as
    `<model_dir>/warmup.json` via tmp-file + atomic rename.

    Deliberately OUTSIDE the integrity manifest: the model artifact is
    sealed at save time, while this file is operational metadata the
    serving layer rewrites after each cold warmup (`verify_model_dir`
    checks only the files the integrity manifest lists, so the sidecar
    never trips verification). Best-effort: a read-only artifact dir
    must not break model load/serve — returns False instead of raising."""
    record = {"warmup_version": WARMUP_VERSION, **payload}
    path = os.path.join(model_dir, WARMUP)
    tmp = f"{path}.tmp-{os.getpid()}"
    ok = True
    try:
        with open(tmp, "w") as fh:
            json.dump(record, fh)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
    except OSError:
        log.debug("warmup manifest write to %s failed", path, exc_info=True)
        try:
            os.unlink(tmp)
        except OSError:
            pass
        ok = False
    # replica-portable copy: when a shared store root is configured,
    # also publish the record keyed by model fingerprint so a SECOND
    # replica's cold start replays this replica's warmup plan (bucket
    # ladder, compile counts) instead of rediscovering it. Best-effort:
    # warmup must never fail because the store is unreachable.
    try:
        store = _warmup_store()
        if store is not None:
            key = f"warmup-{model_fingerprint(model_dir)}"

            def stage(tmp_dir: str) -> None:
                with open(os.path.join(tmp_dir, WARMUP), "w") as fh:
                    json.dump(record, fh)

            store.put(key, stage, meta={"kind": "warmup_manifest"})
    except Exception:
        log.debug("warmup manifest store publish failed", exc_info=True)
    return ok


def _warmup_store():
    """Shared-store handle for replica-portable warmup manifests, or
    None when no store root is configured (local sidecar only)."""
    from transmogrifai_tpu.store.artifact import (
        ArtifactStore, LocalDirBackend)
    from transmogrifai_tpu.store.config import (
        resolve_dir, store_configured)
    if not store_configured():
        return None
    return ArtifactStore(LocalDirBackend(resolve_dir("warmup")))


def load_warmup_manifest(model_dir: str) -> Optional[Dict[str, Any]]:
    """Read the warmup manifest beside a model dir — falling back to
    the shared artifact store (keyed by model fingerprint) when the
    sidecar is absent, so a fresh replica inherits the fleet's warmup
    plan. None when absent everywhere, unreadable, or from a different
    manifest version (a torn/garbage sidecar means 'cold start', never
    an error)."""
    path = os.path.join(model_dir, WARMUP)
    record: Any = None
    try:
        with open(path) as fh:
            record = json.load(fh)
    except (OSError, ValueError):
        record = None
    if record is None:
        try:
            store = _warmup_store()
            if store is not None:
                key = f"warmup-{model_fingerprint(model_dir)}"
                apath = store.get(key)
                if apath is not None:
                    with open(os.path.join(apath, WARMUP)) as fh:
                        record = json.load(fh)
        except Exception:
            record = None
    if not isinstance(record, dict) or \
            record.get("warmup_version") != WARMUP_VERSION:
        return None
    return record


def _ensure_stage_library() -> None:
    """Import the standard stage library so StageRegistry resolves every
    built-in class. Training paths import these modules implicitly via
    the app graph; a model-only process (e.g. `cli serve`, or a bare
    `WorkflowModel.load`) has no app imports, so load must pull in the
    registry population itself."""
    import importlib
    for mod in ("transmogrifai_tpu.ops", "transmogrifai_tpu.models",
                "transmogrifai_tpu.automl", "transmogrifai_tpu.selector",
                "transmogrifai_tpu.insights"):
        try:
            importlib.import_module(mod)
        except Exception:
            # a broken optional module must not block load; a truly
            # missing class still raises at registry resolution below
            log.debug("stage library module %s failed to import", mod,
                      exc_info=True)


def verify_model_dir(path: str) -> Dict[str, Any]:
    """Verify a serialized model dir against its integrity manifest;
    returns the parsed manifest. Raises `ModelIntegrityError` for a
    missing/unreadable integrity manifest (a save killed before the
    final write — or a pre-integrity artifact: re-save, or load with
    `verify=False`), a missing or truncated file, or a checksum
    mismatch (torn write / bit corruption)."""
    if not os.path.isdir(path):
        raise ModelIntegrityError(path, "not a directory")
    if not os.path.exists(os.path.join(path, MANIFEST)):
        raise ModelIntegrityError(path, f"missing {MANIFEST}")
    ipath = os.path.join(path, INTEGRITY)
    if not os.path.exists(ipath):
        raise ModelIntegrityError(
            path, f"missing {INTEGRITY} — the save died before the "
                  "integrity manifest landed (torn artifact), or this is "
                  "a pre-integrity save (load with verify=False)")
    try:
        with open(ipath) as fh:
            integrity = json.load(fh)
    except ValueError as e:
        raise ModelIntegrityError(path, f"unreadable {INTEGRITY}: {e}")
    files = integrity.get("files")
    if not isinstance(files, dict) or MANIFEST not in files:
        raise ModelIntegrityError(path, f"malformed {INTEGRITY}")
    for name, rec in files.items():
        fpath = os.path.join(path, name)
        if not os.path.exists(fpath):
            raise ModelIntegrityError(path, f"{name} is missing")
        size = os.path.getsize(fpath)
        if size != rec.get("bytes"):
            raise ModelIntegrityError(
                path, f"{name} truncated or resized: {size} bytes on "
                      f"disk, {rec.get('bytes')} recorded")
        if _sha256_file(fpath) != rec.get("sha256"):
            raise ModelIntegrityError(
                path, f"{name} checksum mismatch (torn write or bit "
                      "corruption)")
    return integrity


def load_model(path: str, verify: bool = True):
    """Deserialize a model dir. `verify=True` (default) checks the
    integrity manifest FIRST — a torn or corrupt dir raises
    `ModelIntegrityError` and never reaches deserialization. Use
    `verify=False` only for artifacts written before the integrity
    manifest existed."""
    from transmogrifai_tpu.workflow.workflow import WorkflowModel

    if verify:
        verify_model_dir(path)
    _ensure_stage_library()
    with open(os.path.join(path, MANIFEST)) as fh:
        manifest = json.load(fh)
    if manifest["version"] != VERSION:
        raise ValueError(f"Unsupported model version {manifest['version']}")

    npz_path = os.path.join(path, ARRAYS)
    npz = np.load(npz_path) if os.path.exists(npz_path) else None
    stage_specs = {s["uid"]: s for s in manifest["stages"]}
    stages: Dict[str, Any] = {}
    for uid, spec in stage_specs.items():
        cls = StageRegistry.get(spec["class"])
        params = _restore_arrays(dict(spec["params"]), npz)
        if cls is FeatureGeneratorStage:
            params["ftype"] = T.feature_type_by_name(params.pop("ftype"))
        stages[uid] = cls(uid=uid, **params)

    features: Dict[str, Feature] = {}
    for fe in manifest["features"]:
        stage = stages.get(fe["origin_stage"])
        parents = tuple(features[p] for p in fe["parents"])
        if stage is not None and parents:
            stage.input_features = parents
        f = Feature(
            name=fe["name"], ftype=T.feature_type_by_name(fe["ftype"]),
            origin_stage=stage, parents=parents,
            is_response=fe["is_response"], uid=fe["uid"])
        if stage is not None:
            stage._output = f
        features[fe["uid"]] = f

    fitted = {
        uid: stage for uid, stage in stages.items()
        if isinstance(stage, Transformer)}
    result = [features[uid] for uid in manifest["result_features"]]
    model = WorkflowModel(result_features=result, fitted=fitted)
    model.loaded_from = path  # provenance for serving hot-swap/reload
    model.quant_calibration = manifest.get("quant_calibration")
    return model
