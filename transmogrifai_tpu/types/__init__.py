"""The feature type lattice.

Reference parity: `features/src/main/scala/com/salesforce/op/features/types/`
(`FeatureType.scala:44-176`, `Numerics.scala:40-150`, `Text.scala:50-303`,
`Lists.scala`, `Sets.scala`, `Maps.scala:40-394`, `Geolocation.scala`,
`OPVector.scala`, `FeatureTypeDefaults.scala`).

Every feature type wraps an optional value: "missing" is represented in-band
(`None` / empty collection), so stages can reason about nulls uniformly.
The lattice is *semantic*, not physical — it drives automatic encoder choice
in `transmogrify` and type-checking of stage wiring. On device the physical
representation is columnar (see `transmogrifai_tpu.data.columns`); these
classes are the row-level / scalar view used by extract functions, local
scoring, and the test kit.
"""

from __future__ import annotations

import math
import numbers
from typing import Any, Dict, Iterable, List, Optional, Set, Tuple

import numpy as np

__all__ = [
    # base + traits
    "FeatureType", "NonNullable", "SingleResponse", "MultiResponse",
    "Categorical", "Location", "FeatureTypeError",
    # numerics
    "OPNumeric", "Real", "RealNN", "Binary", "Integral", "Percent",
    "Currency", "Date", "DateTime",
    # text
    "Text", "Email", "Base64", "Phone", "ID", "URL", "TextArea",
    "PickList", "ComboBox", "Country", "State", "City", "PostalCode", "Street",
    # collections
    "OPCollection", "OPList", "OPSet", "OPVector", "TextList", "DateList",
    "DateTimeList", "MultiPickList", "Geolocation",
    # maps
    "OPMap", "TextMap", "EmailMap", "Base64Map", "PhoneMap", "IDMap",
    "URLMap", "TextAreaMap", "PickListMap", "ComboBoxMap", "CountryMap",
    "StateMap", "CityMap", "PostalCodeMap", "StreetMap", "GeolocationMap",
    "BinaryMap", "IntegralMap", "RealMap", "PercentMap", "CurrencyMap",
    "DateMap", "DateTimeMap", "MultiPickListMap", "NameStats", "Prediction",
    # registry / factory
    "feature_type_by_name", "all_feature_types", "from_value",
]


class FeatureTypeError(TypeError):
    """Raised when a value cannot be represented by the requested feature type."""


# ---------------------------------------------------------------------------
# Base + traits (FeatureType.scala:44-176)
# ---------------------------------------------------------------------------

_REGISTRY: Dict[str, type] = {}


class FeatureType:
    """Root of the lattice. Wraps a (possibly missing) value.

    Subclasses define `_convert(raw) -> canonical value` and `empty_value`.
    Equality is type + value; hashability allows use in sets/dict keys.
    """

    __slots__ = ("_value",)
    empty_value: Any = None

    def __init__(self, value: Any = None):
        self._value = self._convert(value)

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        _REGISTRY[cls.__name__] = cls

    # -- conversion ---------------------------------------------------------
    @classmethod
    def _convert(cls, value: Any) -> Any:
        raise NotImplementedError

    # -- accessors ----------------------------------------------------------
    @property
    def value(self) -> Any:
        return self._value

    @property
    def v(self) -> Any:
        return self._value

    @property
    def is_empty(self) -> bool:
        return self._value == self.empty_value or self._value is None

    @property
    def is_nullable(self) -> bool:
        return not isinstance(self, NonNullable)

    @classmethod
    def empty(cls) -> "FeatureType":
        return cls(cls.empty_value)

    # -- dunder -------------------------------------------------------------
    def __eq__(self, other: Any) -> bool:
        return type(self) is type(other) and self._equals(other)

    def _equals(self, other: "FeatureType") -> bool:
        return self._value == other._value

    def __hash__(self) -> int:
        v = self._value
        if isinstance(v, (list, np.ndarray)):
            v = tuple(np.asarray(v).ravel().tolist())
        elif isinstance(v, set):
            v = frozenset(v)
        elif isinstance(v, dict):
            v = tuple(sorted(v.items()))
        return hash((type(self).__name__, v))

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self._value!r})"


class NonNullable:
    """Trait: the value may never be empty (FeatureType.scala:122)."""


class SingleResponse:
    """Trait marker (FeatureType.scala:145)."""


class MultiResponse:
    """Trait marker (FeatureType.scala:150)."""


class Categorical:
    """Trait: finite unordered domain (FeatureType.scala:155)."""


class Location:
    """Trait: geographic semantic (FeatureType.scala:140)."""


# ---------------------------------------------------------------------------
# Numerics (Numerics.scala:40-150)
# ---------------------------------------------------------------------------

class OPNumeric(FeatureType):
    """Abstract numeric; value is Optional[number]."""

    @classmethod
    def _convert(cls, value):
        if value is None:
            return None
        if isinstance(value, FeatureType):
            value = value.value
            if value is None:
                return None
        if isinstance(value, bool):
            return cls._coerce(int(value))
        if isinstance(value, numbers.Number):
            if isinstance(value, float) and math.isnan(value):
                return None
            return cls._coerce(value)
        raise FeatureTypeError(f"{cls.__name__} cannot hold {value!r}")

    @classmethod
    def _coerce(cls, n):
        return float(n)

    def to_double(self) -> Optional[float]:
        return None if self._value is None else float(self._value)


class Real(OPNumeric):
    """Optional double."""


class RealNN(Real, NonNullable):
    """Non-nullable double (Numerics.scala — RealNN)."""

    @classmethod
    def _convert(cls, value):
        v = super()._convert(value)
        if v is None:
            raise FeatureTypeError("RealNN cannot be empty")
        return v


class Binary(OPNumeric, SingleResponse, Categorical):
    """Optional boolean."""

    @classmethod
    def _coerce(cls, n):
        return bool(n)

    def to_double(self) -> Optional[float]:
        return None if self._value is None else float(self._value)


class Integral(OPNumeric):
    """Optional int64."""

    @classmethod
    def _coerce(cls, n):
        return int(n)


class Percent(Real):
    """Real constrained to percentage semantics."""


class Currency(Real):
    """Real with currency semantics."""


class Date(Integral):
    """Epoch milliseconds (day semantics). Accepts ISO-8601 strings
    ('2020-05-01', '2020-05-01 12:30[:45]', 'T' separator too) — the
    reference's converter likewise parses temporal strings into epoch ms
    (`FeatureTypeSparkConverter.scala` date handling)."""

    @classmethod
    def _convert(cls, value):
        if isinstance(value, str):
            import datetime as _dt
            s = value.strip()
            if not s:
                return None
            for fmt in ("%Y-%m-%d", "%Y-%m-%d %H:%M", "%Y-%m-%d %H:%M:%S",
                        "%Y-%m-%dT%H:%M", "%Y-%m-%dT%H:%M:%S"):
                try:
                    d = _dt.datetime.strptime(s, fmt)
                    d = d.replace(tzinfo=_dt.timezone.utc)
                    return int(d.timestamp() * 1000)
                except ValueError:
                    continue
            raise FeatureTypeError(f"{cls.__name__} cannot hold {value!r}")
        return super()._convert(value)


class DateTime(Date):
    """Epoch milliseconds (instant semantics)."""


# ---------------------------------------------------------------------------
# Text family (Text.scala:50-303)
# ---------------------------------------------------------------------------

class Text(FeatureType):
    """Optional string."""

    @classmethod
    def _convert(cls, value):
        if value is None:
            return None
        if isinstance(value, FeatureType):
            value = value.value
            if value is None:
                return None
        if isinstance(value, str):
            return value
        if isinstance(value, (bytes, bytearray)):
            return value.decode("utf-8", "replace")
        raise FeatureTypeError(f"{cls.__name__} cannot hold {value!r}")


class Email(Text):
    """Email address; `prefix`/`domain` accessors mirror RichTextFeature."""

    def _split(self) -> Optional[Tuple[str, str]]:
        if self.is_empty or "@" not in self._value:
            return None
        prefix, _, domain = self._value.rpartition("@")
        if not prefix or not domain:
            return None
        return prefix, domain

    @property
    def prefix(self) -> Optional[str]:
        s = self._split()
        return s[0] if s else None

    @property
    def domain(self) -> Optional[str]:
        s = self._split()
        return s[1] if s else None


class Base64(Text):
    """Base64-encoded binary blob."""


class Phone(Text):
    """Phone number string."""


class ID(Text):
    """Opaque identifier."""


class URL(Text):
    """URL; domain/protocol accessors (Text.scala:169)."""

    @property
    def domain(self) -> Optional[str]:
        if self.is_empty:
            return None
        v = self._value
        rest = v.split("://", 1)[1] if "://" in v else v
        host = rest.split("/", 1)[0].split("?", 1)[0]
        return host or None

    @property
    def protocol(self) -> Optional[str]:
        if self.is_empty or "://" not in self._value:
            return None
        return self._value.split("://", 1)[0] or None

    @property
    def is_valid(self) -> bool:
        p = self.protocol
        return p in ("http", "https", "ftp") and bool(self.domain)


class TextArea(Text):
    """Long-form text."""


class PickList(Text, SingleResponse, Categorical):
    """Single-select categorical string."""


class ComboBox(Text):
    """Semi-open categorical string."""


class Country(Text, Location):
    pass


class State(Text, Location):
    pass


class City(Text, Location):
    pass


class PostalCode(Text, Location):
    pass


class Street(Text, Location):
    pass


# ---------------------------------------------------------------------------
# Collections (Lists.scala, Sets.scala, OPVector.scala, Geolocation.scala)
# ---------------------------------------------------------------------------

class OPCollection(FeatureType):
    """Abstract collection; empty collection == missing."""


class OPList(OPCollection):
    empty_value: List = []

    @classmethod
    def _convert(cls, value):
        if value is None:
            return []
        if isinstance(value, FeatureType):
            value = value.value
        if isinstance(value, (list, tuple, np.ndarray)):
            return [cls._elem(x) for x in value]
        raise FeatureTypeError(f"{cls.__name__} cannot hold {value!r}")

    @classmethod
    def _elem(cls, x):
        return x

    @property
    def is_empty(self) -> bool:
        return len(self._value) == 0

    def __len__(self):
        return len(self._value)

    def __iter__(self):
        return iter(self._value)


class TextList(OPList):
    @classmethod
    def _elem(cls, x):
        if not isinstance(x, str):
            raise FeatureTypeError(f"TextList element {x!r} is not a string")
        return x


class DateList(OPList):
    @classmethod
    def _elem(cls, x):
        if isinstance(x, bool) or not isinstance(x, numbers.Number):
            raise FeatureTypeError(f"DateList element {x!r} is not numeric")
        return int(x)


class DateTimeList(DateList):
    pass


class Geolocation(OPList, Location):
    """(lat, lon, accuracy) triple (Geolocation.scala)."""

    @classmethod
    def _convert(cls, value):
        v = super()._convert(value)
        if v and len(v) != 3:
            raise FeatureTypeError(f"Geolocation requires [lat, lon, accuracy], got {v!r}")
        if v:
            lat, lon, acc = float(v[0]), float(v[1]), float(v[2])
            if not (-90.0 <= lat <= 90.0):
                raise FeatureTypeError(f"Latitude {lat} out of range")
            if not (-180.0 <= lon <= 180.0):
                raise FeatureTypeError(f"Longitude {lon} out of range")
            return [lat, lon, acc]
        return v

    @property
    def lat(self) -> Optional[float]:
        return self._value[0] if self._value else None

    @property
    def lon(self) -> Optional[float]:
        return self._value[1] if self._value else None

    @property
    def accuracy(self) -> Optional[float]:
        return self._value[2] if self._value else None


class OPSet(OPCollection):
    empty_value: frozenset = frozenset()

    @classmethod
    def _convert(cls, value):
        if value is None:
            return frozenset()
        if isinstance(value, FeatureType):
            value = value.value
        if isinstance(value, str):
            raise FeatureTypeError(f"{cls.__name__} cannot hold a bare string {value!r}")
        if isinstance(value, Iterable):
            return frozenset(value)
        raise FeatureTypeError(f"{cls.__name__} cannot hold {value!r}")

    @property
    def is_empty(self) -> bool:
        return len(self._value) == 0

    def __len__(self):
        return len(self._value)

    def __iter__(self):
        return iter(self._value)


class MultiPickList(OPSet, MultiResponse, Categorical):
    """Multi-select categorical set of strings."""


class OPVector(OPCollection):
    """Dense numeric vector — the physical feature-engineering currency.

    Wraps a 1-D float array (reference wraps `ml.linalg.Vector`,
    OPVector.scala). Columnar equivalent is an (n, d) jnp array + metadata.
    """

    empty_value = None

    @classmethod
    def _convert(cls, value):
        if value is None:
            return np.zeros((0,), dtype=np.float32)
        if isinstance(value, FeatureType):
            value = value.value
        arr = np.asarray(value, dtype=np.float32)
        if arr.ndim != 1:
            raise FeatureTypeError(f"OPVector requires 1-D data, got shape {arr.shape}")
        return arr

    @property
    def is_empty(self) -> bool:
        return self._value.size == 0

    def _equals(self, other) -> bool:
        return self._value.shape == other._value.shape and bool(
            np.array_equal(self._value, other._value))

    def __len__(self):
        return int(self._value.size)


# ---------------------------------------------------------------------------
# Maps (Maps.scala:40-394) — record-of-named-values per scalar type
# ---------------------------------------------------------------------------

class OPMap(FeatureType):
    """Abstract map String -> element; empty map == missing."""

    empty_value: Dict = {}
    _elem_type: Optional[type] = None  # FeatureType used to validate elements

    @classmethod
    def _convert(cls, value):
        if value is None:
            return {}
        if isinstance(value, FeatureType):
            value = value.value
        if not isinstance(value, dict):
            raise FeatureTypeError(f"{cls.__name__} cannot hold {value!r}")
        out = {}
        for k, v in value.items():
            if not isinstance(k, str):
                raise FeatureTypeError(f"{cls.__name__} key {k!r} is not a string")
            out[k] = cls._elem(v)
        return out

    @classmethod
    def _elem(cls, v):
        if cls._elem_type is None:
            return v
        return cls._elem_type._convert(v)

    @property
    def is_empty(self) -> bool:
        return len(self._value) == 0

    def __len__(self):
        return len(self._value)

    def __getitem__(self, k):
        return self._value[k]

    def get(self, k, default=None):
        return self._value.get(k, default)

    def keys(self):
        return self._value.keys()

    def items(self):
        return self._value.items()


class TextMap(OPMap):
    _elem_type = Text


class EmailMap(TextMap):
    _elem_type = Email


class Base64Map(TextMap):
    _elem_type = Base64


class PhoneMap(TextMap):
    _elem_type = Phone


class IDMap(TextMap):
    _elem_type = ID


class URLMap(TextMap):
    _elem_type = URL


class TextAreaMap(TextMap):
    _elem_type = TextArea


class PickListMap(TextMap, Categorical):
    _elem_type = PickList


class ComboBoxMap(TextMap):
    _elem_type = ComboBox


class CountryMap(TextMap, Location):
    _elem_type = Country


class StateMap(TextMap, Location):
    _elem_type = State


class CityMap(TextMap, Location):
    _elem_type = City


class PostalCodeMap(TextMap, Location):
    _elem_type = PostalCode


class StreetMap(TextMap, Location):
    _elem_type = Street


class BinaryMap(OPMap, Categorical):
    _elem_type = Binary


class IntegralMap(OPMap):
    _elem_type = Integral


class RealMap(OPMap):
    _elem_type = Real


class PercentMap(RealMap):
    _elem_type = Percent


class CurrencyMap(RealMap):
    _elem_type = Currency


class DateMap(IntegralMap):
    _elem_type = Date


class DateTimeMap(DateMap):
    _elem_type = DateTime


class MultiPickListMap(OPMap, MultiResponse, Categorical):
    _elem_type = MultiPickList

    @classmethod
    def _elem(cls, v):
        return MultiPickList._convert(v)  # rejects bare strings like OPSet does


class GeolocationMap(OPMap, Location):
    @classmethod
    def _elem(cls, v):
        return Geolocation._convert(v)


class NameStats(TextMap):
    """Name-detection result map (Maps.scala — NameStats keys)."""

    IS_NAME = "isName"
    ORIGINAL = "originalValue"
    GENDER = "gender"


class Prediction(RealMap, NonNullable):
    """Model output map with reserved keys (Maps.scala:339-394).

    Keys: `prediction` (required), `probability_{i}`, `rawPrediction_{i}`.
    """

    PREDICTION = "prediction"
    RAW = "rawPrediction"
    PROB = "probability"

    _KEY_RE = None  # compiled lazily below

    @classmethod
    def _convert(cls, value):
        import re
        v = super()._convert(value)
        if cls.PREDICTION not in v:
            raise FeatureTypeError("Prediction map must contain key 'prediction'")
        if Prediction._KEY_RE is None:
            Prediction._KEY_RE = re.compile(
                f"^({re.escape(cls.RAW)}|{re.escape(cls.PROB)})_\\d+$")
        for k in v:
            if k != cls.PREDICTION and not Prediction._KEY_RE.match(k):
                raise FeatureTypeError(f"Prediction map key {k!r} not allowed")
        return v

    @property
    def prediction(self) -> float:
        return float(self._value[self.PREDICTION])

    def _keyed(self, prefix: str) -> List[float]:
        ks = sorted(
            (k for k in self._value if k.startswith(prefix + "_")),
            key=lambda k: int(k.rsplit("_", 1)[1]))
        return [float(self._value[k]) for k in ks]

    @property
    def probability(self) -> List[float]:
        return self._keyed(self.PROB)

    @property
    def raw_prediction(self) -> List[float]:
        return self._keyed(self.RAW)

    @classmethod
    def build(cls, prediction: float, raw_prediction: Iterable[float] = (),
              probability: Iterable[float] = ()) -> "Prediction":
        m: Dict[str, float] = {cls.PREDICTION: float(prediction)}
        for i, x in enumerate(raw_prediction):
            m[f"{cls.RAW}_{i}"] = float(x)
        for i, x in enumerate(probability):
            m[f"{cls.PROB}_{i}"] = float(x)
        return cls(m)


# ---------------------------------------------------------------------------
# Registry / factory (FeatureType.scala:176, FeatureTypeFactory.scala)
# ---------------------------------------------------------------------------

def feature_type_by_name(name: str) -> type:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise FeatureTypeError(f"Unknown feature type {name!r}") from None


def all_feature_types() -> Dict[str, type]:
    return dict(_REGISTRY)


def from_value(ftype: type, value: Any) -> FeatureType:
    """Runtime construction of `ftype` from a raw python value."""
    if isinstance(value, ftype):
        return value
    return ftype(value)
