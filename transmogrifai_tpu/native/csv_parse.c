/* Native CSV numeric-column parser (host ingestion hot path).
 *
 * Reference parity: the reference's row extraction runs on the JVM inside
 * Spark (readers/.../DataReader.scala:174-259, CSVReaders.scala); this is
 * the TPU build's native equivalent for the dominant case — filling the
 * float64+NaN columnar storage for numeric columns in one pass over the
 * file buffer, so million-row ingestion does not serialize through
 * python's csv module. Quoted fields (RFC 4180, incl. embedded delimiters
 * and doubled quotes) are handled; embedded newlines inside quotes are
 * treated as row text, not row breaks.
 *
 * csv_numeric_fill:
 *   buf, len        — file contents AFTER the header line
 *   n_cols          — total columns per row
 *   sel, n_sel      — indices of the numeric columns to extract
 *   out             — (max_rows, n_sel) doubles, row-major
 *   missing         — per-cell flag: 0 = integer-lexical value,
 *                     4 = float-lexical value (decimal point/exponent —
 *                     callers use this to widen sample-inferred Integral
 *                     columns to Real), 1 = missing token
 *                     (""/na/n/a/null/none/nan), 2 = NOT PARSEABLE as a
 *                     double, an integer too long for exact float64
 *                     (>15 digits), or a malformed quoted field — the
 *                     caller must fall back to the python path on any 2
 *                     so text sentinels and big IDs are never silently
 *                     NaN'd/rounded
 *   returns number of rows parsed (≤ max_rows), or -1 on malformed input
 */

#include <stdint.h>
#include <stdlib.h>
#include <string.h>
#include <math.h>

/* Fast double parse for the common form [+-]ddd[.ddd][eE[+-]dd]: digits
 * accumulate exactly in uint64 (≤15 → < 2^53) and the scale is an EXACT
 * power of ten, so the single division/multiplication rounds correctly —
 * identical to strtod (this is the fast path real strtod implementations
 * use). Returns 0 and falls back for anything unusual (hex, inf/nan
 * spellings, >15 sig digits, |net exponent| > 22). ~10x faster than
 * glibc strtod, which dominated the kernel profile. */
static const double POW10[23] = {
    1e0, 1e1, 1e2, 1e3, 1e4, 1e5, 1e6, 1e7, 1e8, 1e9, 1e10, 1e11,
    1e12, 1e13, 1e14, 1e15, 1e16, 1e17, 1e18, 1e19, 1e20, 1e21, 1e22};

static int fast_parse_double(const char *s, int64_t n, double *out,
                             int *floaty) {
    int64_t i = 0;
    int neg = 0, exp_neg = 0, seen_exp = 0;
    uint64_t mant = 0;
    int digits = 0, any = 0, frac = 0, seen_point = 0, exp10 = 0;
    if (i < n && (s[i] == '+' || s[i] == '-')) neg = s[i++] == '-';
    if (i >= n) return 0;
    for (; i < n; i++) {
        char c = s[i];
        if (c >= '0' && c <= '9') {
            any = 1;
            if (digits >= 15) return 0;
            mant = mant * 10u + (uint64_t)(c - '0');
            if (mant) digits++;
            if (seen_point) frac++;
        } else if (c == '.') {
            if (seen_point) return 0;
            seen_point = 1;
        } else if (c == 'e' || c == 'E') {
            if (!any) return 0;
            seen_exp = 1;
            i++;
            if (i < n && (s[i] == '+' || s[i] == '-'))
                exp_neg = s[i++] == '-';
            if (i >= n) return 0;
            for (; i < n; i++) {
                if (s[i] < '0' || s[i] > '9') return 0;
                exp10 = exp10 * 10 + (s[i] - '0');
                if (exp10 > 400) return 0;
            }
            break;
        } else return 0;
    }
    if (!any) return 0;
    {
        int net = (exp_neg ? -exp10 : exp10) - frac;
        double v = (double)mant;
        if (net >= 0) {
            if (net > 22) return 0;
            v *= POW10[net];
        } else {
            if (net < -22) return 0;
            v /= POW10[-net];
        }
        *out = neg ? -v : v;
        /* any exponent marker is floaty: python int("1e0") raises, so an
         * "1e0" cell must widen an Integral column to Real */
        *floaty = seen_point || seen_exp;
        return 1;
    }
}

static int is_missing_token(const char *s, int64_t n) {
    /* "", na, n/a, null, none, nan — case-insensitive (Dataset._MISSING) */
    char low[8];
    int64_t i;
    if (n == 0) return 1;
    if (n > 4) return 0;
    for (i = 0; i < n; i++) {
        char c = s[i];
        low[i] = (c >= 'A' && c <= 'Z') ? (char)(c + 32) : c;
    }
    low[n] = 0;
    return strcmp(low, "na") == 0 || strcmp(low, "n/a") == 0 ||
           strcmp(low, "null") == 0 || strcmp(low, "none") == 0 ||
           strcmp(low, "nan") == 0;
}

int64_t csv_numeric_fill(const char *buf, int64_t len, int32_t n_cols,
                         const int32_t *sel, int32_t n_sel, char delim,
                         double *out, uint8_t *missing, int64_t max_rows) {
    /* sel must be ascending; map col index -> slot (or -1) */
    int32_t *slot = (int32_t *)malloc((size_t)n_cols * sizeof(int32_t));
    int64_t pos = 0, row = 0;
    int32_t c;
    if (!slot) return -1;
    for (c = 0; c < n_cols; c++) slot[c] = -1;
    for (c = 0; c < n_sel; c++) {
        if (sel[c] < 0 || sel[c] >= n_cols) { free(slot); return -1; }
        slot[sel[c]] = c;
    }

    while (pos < len && row < max_rows) {
        int32_t col = 0;
        while (col < n_cols && pos <= len) {
            int64_t start, end;
            int bad = 0;
            if (pos < len && buf[pos] == '"') {
                pos++;
                start = pos;
                while (pos < len) {
                    if (buf[pos] == '"') {
                        if (pos + 1 < len && buf[pos + 1] == '"') pos += 2;
                        else break;
                    } else pos++;
                }
                end = pos;
                if (pos < len) pos++; /* closing quote */
                /* junk between closing quote and delimiter: the python
                 * csv module concatenates ('"1.5"x' -> '1.5x') — defer */
                if (pos < len && buf[pos] != delim && buf[pos] != '\n'
                    && buf[pos] != '\r')
                    bad = 1;
            } else {
                start = pos;
                while (pos < len && buf[pos] != delim && buf[pos] != '\n'
                       && buf[pos] != '\r')
                    pos++;
                end = pos;
            }
            if (slot[col] >= 0) {
                int64_t n = end - start;
                double *cell = out + row * n_sel + slot[col];
                uint8_t *miss = missing + row * n_sel + slot[col];
                /* trim spaces */
                while (n > 0 && (buf[start] == ' ' || buf[start] == '\t')) {
                    start++; n--;
                }
                while (n > 0 && (buf[start + n - 1] == ' ' ||
                                 buf[start + n - 1] == '\t'))
                    n--;
                int floaty = 0;
                if (bad) {
                    *cell = 0.0; *miss = 2;
                } else if (is_missing_token(buf + start, n)) {
                    *cell = 0.0; *miss = 1;
                } else if (fast_parse_double(buf + start, n, cell, &floaty)) {
                    *miss = floaty ? 4 : 0;
                } else if (n < 64) {
                    char tmp[64];
                    char *endp;
                    double v;
                    int64_t digits = 0, k;
                    int intlike = 1, hex = 0;
                    memcpy(tmp, buf + start, (size_t)n);
                    tmp[n] = 0;
                    /* glibc strtod accepts hex literals ("0x1A" -> 26.0)
                     * but python float("0x1A") raises — such cells must
                     * take the text path, not silently parse numeric */
                    for (k = 0; k < n; k++)
                        if (tmp[k] == 'x' || tmp[k] == 'X') { hex = 1; break; }
                    v = hex ? 0.0 : strtod(tmp, &endp);
                    if (hex || endp != tmp + n) { *cell = 0.0; *miss = 2; }
                    else {
                        for (k = 0; k < n; k++) {
                            char ch = tmp[k];
                            if (ch >= '0' && ch <= '9') digits++;
                            else if (!(ch == '+' || ch == '-')) intlike = 0;
                        }
                        if (intlike && digits > 15) {
                            /* exact int may exceed 2^53 — python keeps
                             * object storage for these */
                            *cell = 0.0; *miss = 2;
                        } else { *cell = v; *miss = intlike ? 0 : 4; }
                    }
                } else { *cell = 0.0; *miss = 2; }
            }
            col++;
            if (pos < len && buf[pos] == delim && col < n_cols) {
                pos++;
                continue;
            }
            break;
        }
        /* fill unseen selected columns of a short row as missing */
        for (; col < n_cols; col++) {
            if (slot[col] >= 0) {
                out[row * n_sel + slot[col]] = 0.0;
                missing[row * n_sel + slot[col]] = 1;
            }
        }
        /* advance to next line; bare '\r' is a row break too (python's
         * csv module splits on it), and a trailing blank line parses as
         * an all-missing row exactly like the python csv path */
        while (pos < len && buf[pos] != '\n' && buf[pos] != '\r') pos++;
        if (pos < len) {
            if (buf[pos] == '\r') {
                pos++;
                if (pos < len && buf[pos] == '\n') pos++;
            } else pos++;
        }
        row++;
    }
    free(slot);
    return row;
}
