"""Lazy cc build + ctypes binding for the native host-encode kernels."""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional

log = logging.getLogger(__name__)

_lock = threading.Lock()
_libs: dict = {}

_DIR = os.path.dirname(__file__)


def _build(src: str, so_path: str) -> bool:
    for cc in ("cc", "gcc", "clang"):
        try:
            res = subprocess.run(
                [cc, "-O3", "-shared", "-fPIC", src, "-o", so_path],
                capture_output=True, timeout=120)
            if res.returncode == 0:
                return True
            log.debug("%s failed: %s", cc, res.stderr.decode()[:500])
        except (OSError, subprocess.TimeoutExpired):
            continue
    return False


def _load(stem: str, signatures) -> Optional[ctypes.CDLL]:
    """Compile-on-first-use + bind; None → callers use the python path."""
    with _lock:
        if stem in _libs:
            return _libs[stem]
        _libs[stem] = None
        src = os.path.join(_DIR, stem + ".c")
        so_path = os.path.join(_DIR, f"_{stem}.so")
        try:
            if not os.path.exists(so_path) or \
                    os.path.getmtime(so_path) < os.path.getmtime(src):
                if not _build(src, so_path):
                    return None
            lib = ctypes.CDLL(so_path)
            for name, argtypes, restype in signatures:
                fn = getattr(lib, name)
                fn.argtypes = argtypes
                fn.restype = restype
            _libs[stem] = lib
        except Exception:
            log.exception("native %s unavailable; using python path", stem)
        return _libs[stem]


def get_murmur3() -> Optional[ctypes.CDLL]:
    p = ctypes.c_void_p
    return _load("murmur3", [
        ("murmur3_buckets_i32",
         [p, p, ctypes.c_int64, ctypes.c_uint32, ctypes.c_uint32, p], None),
        ("murmur3_buckets_i64",
         [p, p, ctypes.c_int64, ctypes.c_uint32, ctypes.c_uint32, p], None),
        ("murmur3_hash_counts_i32",
         [p, p, p, ctypes.c_int64, ctypes.c_uint32, ctypes.c_uint32, p],
         None),
    ])


def get_csv_parser() -> Optional[ctypes.CDLL]:
    p = ctypes.c_void_p
    return _load("csv_parse", [
        ("csv_numeric_fill",
         [p, ctypes.c_int64, ctypes.c_int32, p, ctypes.c_int32,
          ctypes.c_char, p, p, ctypes.c_int64], ctypes.c_int64),
    ])
