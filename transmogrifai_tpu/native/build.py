"""Lazy cc build + ctypes binding for the native host-encode kernels."""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional

log = logging.getLogger(__name__)

_lock = threading.Lock()
_lib = None
_tried = False

_SRC = os.path.join(os.path.dirname(__file__), "murmur3.c")


def _build(so_path: str) -> bool:
    for cc in ("cc", "gcc", "clang"):
        try:
            res = subprocess.run(
                [cc, "-O3", "-shared", "-fPIC", _SRC, "-o", so_path],
                capture_output=True, timeout=120)
            if res.returncode == 0:
                return True
            log.debug("%s failed: %s", cc, res.stderr.decode()[:500])
        except (OSError, subprocess.TimeoutExpired):
            continue
    return False


def get_murmur3() -> Optional[ctypes.CDLL]:
    """The compiled kernel library, or None (callers fall back to python)."""
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        so_path = os.path.join(os.path.dirname(__file__), "_murmur3.so")
        try:
            if not os.path.exists(so_path) or \
                    os.path.getmtime(so_path) < os.path.getmtime(_SRC):
                if not _build(so_path):
                    return None
            lib = ctypes.CDLL(so_path)
            for name, argtypes in (
                ("murmur3_buckets_i32",
                 [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
                  ctypes.c_uint32, ctypes.c_uint32, ctypes.c_void_p]),
                ("murmur3_buckets_i64",
                 [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
                  ctypes.c_uint32, ctypes.c_uint32, ctypes.c_void_p]),
                ("murmur3_hash_counts_i32",
                 [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                  ctypes.c_int64, ctypes.c_uint32, ctypes.c_uint32,
                  ctypes.c_void_p]),
            ):
                fn = getattr(lib, name)
                fn.argtypes = argtypes
                fn.restype = None
            _lib = lib
        except Exception:
            log.exception("native murmur3 unavailable; using python path")
            _lib = None
        return _lib
