"""Native (C) host-runtime components.

The reference's runtime leans on JVM-native paths (Spark shuffle, Rabit
allreduce, Lucene); here the TPU compute path is XLA and the host runtime's
hot loops are C: murmur3 feature hashing directly over Arrow string
buffers and one-pass CSV numeric-column parsing into float64+NaN storage (SURVEY §2.9 — components whose equivalents cannot be Python
stand-ins). Compiled lazily with the in-image gcc; every caller falls back
to the pure-python implementation when the toolchain is unavailable.
"""

from transmogrifai_tpu.native.build import get_csv_parser, get_murmur3

__all__ = ["get_csv_parser", "get_murmur3"]
