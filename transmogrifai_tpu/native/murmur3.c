/* Batched murmur3-32 over Arrow string-array buffers.
 *
 * The host-encode hot path (hashed text features) calls this straight on
 * pyarrow's (data, offsets) layout -- no per-token Python objects, no
 * per-token interpreter dispatch. Semantics match the pure-python
 * murmur3_32 in ops/text.py exactly (tested bucket-for-bucket).
 *
 * Build: gcc -O3 -shared -fPIC murmur3.c -o _murmur3.so (native/build.py)
 */

#include <stdint.h>
#include <stddef.h>

static inline uint32_t rotl32(uint32_t x, int8_t r) {
    return (x << r) | (x >> (32 - r));
}

static uint32_t murmur3_32(const uint8_t *data, size_t len, uint32_t seed) {
    const uint32_t c1 = 0xcc9e2d51u, c2 = 0x1b873593u;
    uint32_t h = seed;
    const size_t nblocks = len / 4;
    size_t i;
    for (i = 0; i < nblocks; i++) {
        uint32_t k = (uint32_t)data[i * 4]
                   | ((uint32_t)data[i * 4 + 1] << 8)
                   | ((uint32_t)data[i * 4 + 2] << 16)
                   | ((uint32_t)data[i * 4 + 3] << 24);
        k *= c1; k = rotl32(k, 15); k *= c2;
        h ^= k; h = rotl32(h, 13); h = h * 5 + 0xe6546b64u;
    }
    const uint8_t *tail = data + nblocks * 4;
    uint32_t k1 = 0;
    switch (len & 3) {
        case 3: k1 ^= (uint32_t)tail[2] << 16; /* fallthrough */
        case 2: k1 ^= (uint32_t)tail[1] << 8;  /* fallthrough */
        case 1: k1 ^= (uint32_t)tail[0];
                k1 *= c1; k1 = rotl32(k1, 15); k1 *= c2; h ^= k1;
    }
    h ^= (uint32_t)len;
    h ^= h >> 16; h *= 0x85ebca6bu;
    h ^= h >> 13; h *= 0xc2b2ae35u;
    h ^= h >> 16;
    return h;
}

/* Hash n strings laid out arrow-style: string i is
 * data[offsets[i] .. offsets[i+1]).  out[i] = hash % num_features. */
void murmur3_buckets_i32(const uint8_t *data, const int32_t *offsets,
                         int64_t n, uint32_t seed, uint32_t num_features,
                         int64_t *out) {
    for (int64_t i = 0; i < n; i++) {
        int32_t lo = offsets[i], hi = offsets[i + 1];
        out[i] = (int64_t)(murmur3_32(data + lo, (size_t)(hi - lo), seed)
                           % num_features);
    }
}

void murmur3_buckets_i64(const uint8_t *data, const int64_t *offsets,
                         int64_t n, uint32_t seed, uint32_t num_features,
                         int64_t *out) {
    for (int64_t i = 0; i < n; i++) {
        int64_t lo = offsets[i], hi = offsets[i + 1];
        out[i] = (int64_t)(murmur3_32(data + lo, (size_t)(hi - lo), seed)
                           % num_features);
    }
}

/* Fused scatter-add: counts[row_ids[i], bucket(token_i)] += 1 */
void murmur3_hash_counts_i32(const uint8_t *data, const int32_t *offsets,
                             const int64_t *row_ids, int64_t n,
                             uint32_t seed, uint32_t num_features,
                             float *counts /* (n_rows, num_features) */) {
    for (int64_t i = 0; i < n; i++) {
        int32_t lo = offsets[i], hi = offsets[i + 1];
        uint32_t b = murmur3_32(data + lo, (size_t)(hi - lo), seed)
                     % num_features;
        counts[row_ids[i] * (int64_t)num_features + (int64_t)b] += 1.0f;
    }
}
