"""Type-specific enrichment: email/URL/phone/MIME/language/name detection.

Reference parity:
- `core/.../feature/PhoneNumberParser.scala` (libphonenumber validity) →
  region-aware digit rules here (pure python; no JVM libphonenumber)
- `ValidEmailTransformer` (core/.../feature/ValidEmailTransformer.scala)
- Email/URL domain pivots (`core/.../dsl/RichTextFeature.scala:603-688`,
  `EmailToPickListMapTransformer.scala`)
- `MimeTypeDetector` (core/.../feature/MimeTypeDetector.scala — Tika) →
  magic-byte table here
- `LangDetector` (core/.../feature/LangDetector.scala +
  `OptimaizeLanguageDetector.scala:45`) → script ranges + stopword-profile
  scoring (pure python)
- `HumanNameDetector`/gender (`features/.../impl/feature/
  GenderDetectStrategy.scala`, OpenNLPNameEntityTagger.scala:42) →
  dictionary heuristic (the reference's OpenNLP binaries are data files,
  substituted per SURVEY §2.9)

All are host-side stages: their outputs (Binary/PickList/Text/maps) feed
the standard vectorizers, so the device program sees only dense encodings.
"""

from __future__ import annotations

import base64
import binascii
import re
from typing import Dict, List, Optional, Sequence

import numpy as np

from transmogrifai_tpu import types as T
from transmogrifai_tpu.data.columns import Column
from transmogrifai_tpu.data.metadata import (
    NULL_INDICATOR, VectorColumnMetadata, VectorMetadata)
from transmogrifai_tpu.stages.base import HostTransformer, Transformer

# --------------------------------------------------------------------------- #
# email                                                                       #
# --------------------------------------------------------------------------- #

_EMAIL_RE = re.compile(
    r"^[A-Za-z0-9!#$%&'*+/=?^_`{|}~.-]+@([A-Za-z0-9-]+\.)+[A-Za-z]{2,}$")


def email_parts(s: Optional[str]):
    """(prefix, domain) or (None, None) when invalid."""
    if not s or not _EMAIL_RE.match(s):
        return None, None
    prefix, domain = s.rsplit("@", 1)
    return prefix, domain


class ValidEmailTransformer(HostTransformer):
    """Email → Binary validity (ValidEmailTransformer.scala)."""

    in_types = (T.Email,)
    out_type = T.Binary

    def transform(self, cols: Sequence[Column], ctx=None) -> Column:
        src = cols[0].data
        return Column.from_values(T.Binary, [
            None if v is None else (email_parts(v)[1] is not None)
            for v in src])


class EmailDomainTransformer(HostTransformer):
    """Email → PickList of the domain (EmailDomainToPickList,
    RichTextFeature.scala:630); invalid/empty → None."""

    in_types = (T.Email,)
    out_type = T.PickList

    def transform(self, cols: Sequence[Column], ctx=None) -> Column:
        out = np.empty(len(cols[0].data), dtype=object)
        for i, v in enumerate(cols[0].data):
            out[i] = email_parts(v)[1]
        return Column(T.PickList, out)


class EmailToPickListMapTransformer(HostTransformer):
    """Email → PickListMap {Prefix, Domain}
    (EmailToPickListMapTransformer.scala)."""

    in_types = (T.Email,)
    out_type = T.PickListMap

    def transform(self, cols: Sequence[Column], ctx=None) -> Column:
        out = np.empty(len(cols[0].data), dtype=object)
        for i, v in enumerate(cols[0].data):
            prefix, domain = email_parts(v)
            out[i] = ({"Prefix": prefix, "Domain": domain}
                      if domain is not None else None)
        return Column(T.PickListMap, out)


# --------------------------------------------------------------------------- #
# URL                                                                         #
# --------------------------------------------------------------------------- #

_URL_RE = re.compile(
    r"^(?P<proto>https?|ftp)://(?P<host>[A-Za-z0-9.-]+\.[A-Za-z]{2,})"
    r"(?::\d+)?(?:/[^\s]*)?$", re.IGNORECASE)


def url_parts(s: Optional[str]):
    """(protocol, domain) of a valid http/https/ftp url, else (None, None)
    (URLIsValid / URLDomainToText, RichTextFeature.scala:642-654)."""
    if not s:
        return None, None
    m = _URL_RE.match(s.strip())
    if not m:
        return None, None
    return m.group("proto").lower(), m.group("host").lower()


class UrlIsValidTransformer(HostTransformer):
    in_types = (T.URL,)
    out_type = T.Binary

    def transform(self, cols: Sequence[Column], ctx=None) -> Column:
        return Column.from_values(T.Binary, [
            None if v is None else (url_parts(v)[1] is not None)
            for v in cols[0].data])


class UrlDomainTransformer(HostTransformer):
    """URL → PickList domain of VALID urls (URLDomainToPickList,
    RichTextFeature.scala:843)."""

    in_types = (T.URL,)
    out_type = T.PickList

    def transform(self, cols: Sequence[Column], ctx=None) -> Column:
        out = np.empty(len(cols[0].data), dtype=object)
        for i, v in enumerate(cols[0].data):
            out[i] = url_parts(v)[1]
        return Column(T.PickList, out)


class UrlProtocolTransformer(HostTransformer):
    in_types = (T.URL,)
    out_type = T.Text

    def transform(self, cols: Sequence[Column], ctx=None) -> Column:
        out = np.empty(len(cols[0].data), dtype=object)
        for i, v in enumerate(cols[0].data):
            out[i] = url_parts(v)[0]
        return Column(T.Text, out)


# --------------------------------------------------------------------------- #
# phone                                                                       #
# --------------------------------------------------------------------------- #

# national number length rules per region (libphonenumber-lite):
# region → (country_code, min_len, max_len)
_PHONE_REGIONS: Dict[str, tuple] = {
    "US": ("1", 10, 10), "CA": ("1", 10, 10), "GB": ("44", 9, 10),
    "DE": ("49", 6, 11), "FR": ("33", 9, 9), "IN": ("91", 10, 10),
    "AU": ("61", 9, 9), "JP": ("81", 9, 10), "BR": ("55", 10, 11),
    "MX": ("52", 10, 10), "CN": ("86", 10, 11), "ES": ("34", 9, 9),
    "IT": ("39", 8, 11), "NL": ("31", 9, 9),
}


def is_valid_phone(s: Optional[str], default_region: str = "US",
                   strict: bool = False) -> Optional[bool]:
    """Region-aware validity (PhoneNumberParser.scala: validity against a
    default region; non-strict mode tolerates missing country code)."""
    if s is None:
        return None
    digits = re.sub(r"[^\d+]", "", s.strip())
    if not digits:
        return False
    cc, lo, hi = _PHONE_REGIONS.get(default_region.upper(), ("1", 7, 15))
    if digits.startswith("+"):
        body = digits[1:]
        if not body.isdigit():
            return False
        if body.startswith(cc):
            national = body[len(cc):]
            return lo <= len(national) <= hi
        # other country code: generic E.164 bound
        return 7 <= len(body) <= 15
    if not digits.isdigit():
        return False
    if digits.startswith(cc) and lo <= len(digits) - len(cc) <= hi:
        return not strict or default_region.upper() in ("US", "CA")
    return lo <= len(digits) <= hi


def phone_valid_block(values, default_region: str,
                      track_nulls: bool) -> np.ndarray:
    """[isValid(, isNull)] block shared by PhoneVectorizer and
    PhoneMapVectorizer so scalar and map phone encodings cannot drift."""
    n = len(values)
    block = np.zeros((n, 2 if track_nulls else 1), dtype=np.float32)
    for i, v in enumerate(values):
        valid = is_valid_phone(v, default_region)
        if valid is None:
            if track_nulls:
                block[i, 1] = 1.0
        elif valid:
            block[i, 0] = 1.0
    return block


class PhoneIsValidTransformer(HostTransformer):
    """Phone → Binary validity (RichTextFeature.isValidPhoneDefaultCountry,
    RichTextFeature.scala:545)."""

    in_types = (T.Phone,)
    out_type = T.Binary

    def __init__(self, default_region: str = "US", strict: bool = False,
                 uid: Optional[str] = None):
        super().__init__(uid=uid, default_region=default_region, strict=strict)
        self.default_region = default_region
        self.strict = strict

    def transform(self, cols: Sequence[Column], ctx=None) -> Column:
        return Column.from_values(T.Binary, [
            is_valid_phone(v, self.default_region, self.strict)
            for v in cols[0].data])


class PhoneVectorizer(Transformer):
    """N Phone features → [isValid, isNull] per feature — the transmogrify
    default for Phone (RichTextFeature.vectorize, :569-582)."""

    in_types = (T.Phone, Ellipsis)
    out_type = T.OPVector

    def __init__(self, default_region: str = "US", track_nulls: bool = True,
                 uid: Optional[str] = None):
        super().__init__(uid=uid, default_region=default_region,
                         track_nulls=track_nulls)
        self.default_region = default_region
        self.track_nulls = track_nulls

    def host_prepare(self, cols: Sequence[Optional[Column]]):
        return [phone_valid_block(c.data, self.default_region,
                                  self.track_nulls) for c in cols]

    def device_apply(self, enc, dev):
        import jax.numpy as jnp
        return jnp.concatenate([jnp.asarray(b) for b in enc], axis=1)

    def output_meta(self) -> VectorMetadata:
        cols: List[VectorColumnMetadata] = []
        for f in self.input_features:
            cols.append(VectorColumnMetadata(
                parent_name=f.name, parent_type=f.ftype.__name__,
                grouping=f.name, indicator_value="IsValid"))
            if self.track_nulls:
                cols.append(VectorColumnMetadata(
                    parent_name=f.name, parent_type=f.ftype.__name__,
                    grouping=f.name, indicator_value=NULL_INDICATOR))
        return VectorMetadata(self.output_name(), tuple(cols)).with_indices()


# --------------------------------------------------------------------------- #
# MIME type (Base64 payloads)                                                 #
# --------------------------------------------------------------------------- #

_MAGIC = [
    (b"%PDF", "application/pdf"),
    (b"\x89PNG\r\n\x1a\n", "image/png"),
    (b"\xff\xd8\xff", "image/jpeg"),
    (b"GIF8", "image/gif"),
    (b"BM", "image/bmp"),
    (b"PK\x03\x04", "application/zip"),
    (b"\x1f\x8b", "application/gzip"),
    (b"Rar!", "application/x-rar-compressed"),
    (b"\x7fELF", "application/x-executable"),
    (b"MZ", "application/x-msdownload"),
    (b"ID3", "audio/mpeg"),
    (b"RIFF", "audio/x-wav"),
    (b"OggS", "audio/ogg"),
    (b"\xd0\xcf\x11\xe0", "application/x-ole-storage"),
]


def detect_mime(b64: Optional[str], type_hint: Optional[str] = None) -> Optional[str]:
    """Magic-byte MIME sniffing of base64 payloads (MimeTypeDetector.scala —
    Tika's detector behind the same Base64 → Text contract)."""
    if b64 is None:
        return None
    if not b64:
        return ""
    try:
        raw = base64.b64decode(b64, validate=True)
    except (binascii.Error, ValueError):
        return None
    if not raw:
        return ""
    for magic, mime in _MAGIC:
        if raw.startswith(magic):
            return mime
    head = raw[:512]
    try:
        text = head.decode("utf-8")
    except UnicodeDecodeError:
        return type_hint or "application/octet-stream"
    stripped = text.lstrip().lower()
    if stripped.startswith(("<html", "<!doctype html")):
        return "text/html"
    if stripped.startswith("<?xml"):
        return "application/xml"
    if stripped.startswith(("{", "[")):
        return "application/json"
    if stripped.startswith("<svg"):
        return "image/svg+xml"
    return type_hint or "text/plain"


class MimeTypeDetector(HostTransformer):
    """Base64 → Text MIME type (MimeTypeDetector.scala)."""

    in_types = (T.Base64,)
    out_type = T.Text

    def __init__(self, type_hint: Optional[str] = None,
                 uid: Optional[str] = None):
        super().__init__(uid=uid, type_hint=type_hint)
        self.type_hint = type_hint

    def transform(self, cols: Sequence[Column], ctx=None) -> Column:
        out = np.empty(len(cols[0].data), dtype=object)
        for i, v in enumerate(cols[0].data):
            out[i] = detect_mime(v, self.type_hint)
        return Column(T.Text, out)


# --------------------------------------------------------------------------- #
# language detection                                                          #
# --------------------------------------------------------------------------- #

# script ranges decide non-latin languages outright
_SCRIPTS = [
    ((0x0400, 0x04FF), "ru"), ((0x3040, 0x30FF), "ja"),
    ((0xAC00, 0xD7AF), "ko"), ((0x4E00, 0x9FFF), "zh"),
    ((0x0600, 0x06FF), "ar"), ((0x0900, 0x097F), "hi"),
    ((0x0370, 0x03FF), "el"), ((0x0590, 0x05FF), "he"),
    ((0x0E00, 0x0E7F), "th"),
]

# latin languages: high-frequency function words (profile scoring)
_PROFILES: Dict[str, frozenset] = {
    "en": frozenset("the of and to in is was for that it with as his on be "
                    "at by had this are but from they which not have".split()),
    "de": frozenset("der die und das in den von zu mit sich des auf für ist "
                    "im dem nicht ein eine als auch es an werden".split()),
    "fr": frozenset("de la le et les des en un du une est que dans qui par "
                    "pour au sur pas plus ne se sont avec il".split()),
    "es": frozenset("de la que el en y a los se del las un por con una su "
                    "para es al lo como más pero sus le".split()),
    "it": frozenset("di e il la che in un a per è una sono con non del si "
                    "da come le dei nel alla più anche".split()),
    "pt": frozenset("de a o que e do da em um para é com não uma os no se "
                    "na por mais as dos como mas foi ao".split()),
    "nl": frozenset("de van het een en in is dat op te zijn met voor niet "
                    "aan er om ook als dan maar bij uit".split()),
}


def detect_language(text: Optional[str]) -> Dict[str, float]:
    """{language: confidence} (LanguageDetector contract,
    OptimaizeLanguageDetector.scala:45). Scripts decide CJK/Cyrillic/...;
    latin text scores stopword-profile hits."""
    if not text:
        return {}
    counts: Dict[str, int] = {}
    letters = 0
    for ch in text:
        cp = ord(ch)
        if cp < 0x80:
            if ch.isalpha():
                letters += 1
            continue
        for (lo, hi), lang in _SCRIPTS:
            if lo <= cp <= hi:
                counts[lang] = counts.get(lang, 0) + 1
                break
    if counts:
        total = sum(counts.values())
        if total >= max(1, letters // 4):
            return {lang: c / total for lang, c in
                    sorted(counts.items(), key=lambda kv: -kv[1])}
    words = re.findall(r"[a-zà-ÿäöüß]+", text.lower())
    if not words:
        return {}
    scores = {}
    for lang, profile in _PROFILES.items():
        hits = sum(1 for w in words if w in profile)
        if hits:
            scores[lang] = hits / len(words)
    total = sum(scores.values())
    if not total:
        return {}
    return {lang: s / total for lang, s in
            sorted(scores.items(), key=lambda kv: -kv[1])}


class LangDetector(HostTransformer):
    """Text → RealMap of language → confidence (LangDetector.scala)."""

    in_types = (T.Text,)
    out_type = T.RealMap

    def transform(self, cols: Sequence[Column], ctx=None) -> Column:
        out = np.empty(len(cols[0].data), dtype=object)
        for i, v in enumerate(cols[0].data):
            d = detect_language(v)
            out[i] = d if d else None
        return Column(T.RealMap, out)


# --------------------------------------------------------------------------- #
# human names                                                                 #
# --------------------------------------------------------------------------- #

_FEMALE = frozenset("""
mary patricia jennifer linda elizabeth barbara susan jessica sarah karen
nancy lisa margaret betty sandra ashley dorothy kimberly emily donna
michelle carol amanda melissa deborah stephanie rebecca laura sharon
cynthia kathleen amy shirley angela helen anna brenda pamela nicole emma
samantha katherine christine debra rachel catherine carolyn janet ruth
maria heather diane virginia julie joyce victoria olivia kelly christina
lauren joan evelyn judith megan cheryl andrea hannah martha jacqueline
frances gloria ann teresa kathryn sara janice jean alice madison doris
abigail julia judy grace denise amber marilyn beverly danielle theresa
sophia marie diana brittany natalie isabella charlotte rose alexis kayla
""".split())

_MALE = frozenset("""
james robert john michael david william richard joseph thomas charles
christopher daniel matthew anthony mark donald steven paul andrew joshua
kenneth kevin brian george timothy ronald edward jason jeffrey ryan jacob
gary nicholas eric jonathan stephen larry justin scott brandon benjamin
samuel gregory frank alexander raymond patrick jack dennis jerry tyler
aaron jose adam nathan henry douglas zachary peter kyle ethan walter noah
jeremy christian keith roger terry austin sean gerald carl harold dylan
arthur lawrence jordan jesse bryan billy bruce gabriel joe logan alan
juan albert willie elijah wayne randy vincent mason roy ralph bobby
russell bradley philip eugene
""".split())


def name_stats(text: Optional[str]) -> Optional[Dict[str, str]]:
    """NameStats map {isName, gender[, firstName]} — HumanNameDetector /
    GenderDetectStrategy.ByFirstName analogue over a name dictionary."""
    if not text:
        return None
    tokens = [t.lower() for t in re.findall(r"[A-Za-zà-ÿ'-]+", text)]
    if not 1 <= len(tokens) <= 4:
        return {"isName": "false", "gender": "unknown"}
    first = tokens[0]
    if first in _FEMALE:
        return {"isName": "true", "gender": "female", "firstName": first}
    if first in _MALE:
        return {"isName": "true", "gender": "male", "firstName": first}
    # any dictionary hit in later tokens (e.g. "dr maria lopez")
    for t in tokens[1:]:
        if t in _FEMALE:
            return {"isName": "true", "gender": "female", "firstName": t}
        if t in _MALE:
            return {"isName": "true", "gender": "male", "firstName": t}
    return {"isName": "false", "gender": "unknown"}


class HumanNameDetector(HostTransformer):
    """Text → NameStats (HumanNameDetector.scala / GenderDetectStrategy)."""

    in_types = (T.Text,)
    out_type = T.NameStats

    def transform(self, cols: Sequence[Column], ctx=None) -> Column:
        out = np.empty(len(cols[0].data), dtype=object)
        for i, v in enumerate(cols[0].data):
            out[i] = name_stats(v)
        return Column(T.NameStats, out)


class NameEntityRecognizer(HostTransformer):
    """Text → MultiPickListMap of entity type → tokens
    (OpenNLPNameEntityTagger.scala:42 contract; capitalization + dictionary
    heuristics standing in for the OpenNLP binary models)."""

    in_types = (T.Text,)
    out_type = T.MultiPickListMap

    def transform(self, cols: Sequence[Column], ctx=None) -> Column:
        out = np.empty(len(cols[0].data), dtype=object)
        for i, v in enumerate(cols[0].data):
            out[i] = self._entities(v)
        return Column(T.MultiPickListMap, out)

    @staticmethod
    def _entities(text: Optional[str]) -> Optional[Dict[str, frozenset]]:
        if not text:
            return None
        persons = set()
        for m in re.finditer(r"\b([A-Z][a-zà-ÿ'-]+)(?:\s+[A-Z][a-zà-ÿ'-]+)*",
                             text):
            first = m.group(1).lower()
            if first in _FEMALE or first in _MALE:
                persons.add(m.group(0).lower())
        return {"Person": frozenset(persons)} if persons else None
