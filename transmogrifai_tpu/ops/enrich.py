"""Type-specific enrichment: email/URL/phone/MIME/language/name detection.

Reference parity:
- `core/.../feature/PhoneNumberParser.scala` (libphonenumber validity) →
  region-aware digit rules here (pure python; no JVM libphonenumber)
- `ValidEmailTransformer` (core/.../feature/ValidEmailTransformer.scala)
- Email/URL domain pivots (`core/.../dsl/RichTextFeature.scala:603-688`,
  `EmailToPickListMapTransformer.scala`)
- `MimeTypeDetector` (core/.../feature/MimeTypeDetector.scala — Tika) →
  magic-byte table here
- `LangDetector` (core/.../feature/LangDetector.scala +
  `OptimaizeLanguageDetector.scala:45`) → script ranges + stopword-profile
  scoring (pure python)
- `HumanNameDetector`/gender (`features/.../impl/feature/
  GenderDetectStrategy.scala`, OpenNLPNameEntityTagger.scala:42) →
  dictionary heuristic (the reference's OpenNLP binaries are data files,
  substituted per SURVEY §2.9)

All are host-side stages: their outputs (Binary/PickList/Text/maps) feed
the standard vectorizers, so the device program sees only dense encodings.
"""

from __future__ import annotations

import base64
import binascii
import logging
import re
from typing import Dict, List, Optional, Sequence

import numpy as np

from transmogrifai_tpu import types as T
from transmogrifai_tpu.data.columns import Column
from transmogrifai_tpu.data.metadata import (
    NULL_INDICATOR, VectorColumnMetadata, VectorMetadata)
from transmogrifai_tpu.stages.base import HostTransformer, Transformer

log = logging.getLogger(__name__)

# --------------------------------------------------------------------------- #
# email                                                                       #
# --------------------------------------------------------------------------- #

_EMAIL_RE = re.compile(
    r"^[A-Za-z0-9!#$%&'*+/=?^_`{|}~.-]+@([A-Za-z0-9-]+\.)+[A-Za-z]{2,}$")


def email_parts(s: Optional[str]):
    """(prefix, domain) or (None, None) when invalid."""
    if not s or not _EMAIL_RE.match(s):
        return None, None
    prefix, domain = s.rsplit("@", 1)
    return prefix, domain


class ValidEmailTransformer(HostTransformer):
    """Email → Binary validity (ValidEmailTransformer.scala)."""

    in_types = (T.Email,)
    out_type = T.Binary

    def transform(self, cols: Sequence[Column], ctx=None) -> Column:
        src = cols[0].data
        return Column.from_values(T.Binary, [
            None if v is None else (email_parts(v)[1] is not None)
            for v in src])


class EmailDomainTransformer(HostTransformer):
    """Email → PickList of the domain (EmailDomainToPickList,
    RichTextFeature.scala:630); invalid/empty → None."""

    in_types = (T.Email,)
    out_type = T.PickList

    def transform(self, cols: Sequence[Column], ctx=None) -> Column:
        out = np.empty(len(cols[0].data), dtype=object)
        for i, v in enumerate(cols[0].data):
            out[i] = email_parts(v)[1]
        return Column(T.PickList, out)


class EmailToPickListMapTransformer(HostTransformer):
    """Email → PickListMap {Prefix, Domain}
    (EmailToPickListMapTransformer.scala)."""

    in_types = (T.Email,)
    out_type = T.PickListMap

    def transform(self, cols: Sequence[Column], ctx=None) -> Column:
        out = np.empty(len(cols[0].data), dtype=object)
        for i, v in enumerate(cols[0].data):
            prefix, domain = email_parts(v)
            out[i] = ({"Prefix": prefix, "Domain": domain}
                      if domain is not None else None)
        return Column(T.PickListMap, out)


# --------------------------------------------------------------------------- #
# URL                                                                         #
# --------------------------------------------------------------------------- #

_URL_RE = re.compile(
    r"^(?P<proto>https?|ftp)://(?P<host>[A-Za-z0-9.-]+\.[A-Za-z]{2,})"
    r"(?::\d+)?(?:/[^\s]*)?$", re.IGNORECASE)


def url_parts(s: Optional[str]):
    """(protocol, domain) of a valid http/https/ftp url, else (None, None)
    (URLIsValid / URLDomainToText, RichTextFeature.scala:642-654)."""
    if not s:
        return None, None
    m = _URL_RE.match(s.strip())
    if not m:
        return None, None
    return m.group("proto").lower(), m.group("host").lower()


class UrlIsValidTransformer(HostTransformer):
    in_types = (T.URL,)
    out_type = T.Binary

    def transform(self, cols: Sequence[Column], ctx=None) -> Column:
        return Column.from_values(T.Binary, [
            None if v is None else (url_parts(v)[1] is not None)
            for v in cols[0].data])


class UrlDomainTransformer(HostTransformer):
    """URL → PickList domain of VALID urls (URLDomainToPickList,
    RichTextFeature.scala:843)."""

    in_types = (T.URL,)
    out_type = T.PickList

    def transform(self, cols: Sequence[Column], ctx=None) -> Column:
        out = np.empty(len(cols[0].data), dtype=object)
        for i, v in enumerate(cols[0].data):
            out[i] = url_parts(v)[1]
        return Column(T.PickList, out)


class UrlProtocolTransformer(HostTransformer):
    in_types = (T.URL,)
    out_type = T.Text

    def transform(self, cols: Sequence[Column], ctx=None) -> Column:
        out = np.empty(len(cols[0].data), dtype=object)
        for i, v in enumerate(cols[0].data):
            out[i] = url_parts(v)[0]
        return Column(T.Text, out)


# --------------------------------------------------------------------------- #
# phone                                                                       #
# --------------------------------------------------------------------------- #

# national number length rules per region (libphonenumber-lite):
# region → (country_code, min_len, max_len)
_PHONE_REGIONS: Dict[str, tuple] = {
    "US": ("1", 10, 10), "CA": ("1", 10, 10), "GB": ("44", 9, 10),
    "DE": ("49", 6, 11), "FR": ("33", 9, 9), "IN": ("91", 10, 10),
    "AU": ("61", 9, 9), "JP": ("81", 9, 10), "BR": ("55", 10, 11),
    "MX": ("52", 10, 10), "CN": ("86", 10, 11), "ES": ("34", 9, 9),
    "IT": ("39", 8, 11), "NL": ("31", 9, 9),
}


def is_valid_phone(s: Optional[str], default_region: str = "US",
                   strict: bool = False) -> Optional[bool]:
    """Region-aware validity (PhoneNumberParser.scala: validity against a
    default region; non-strict mode tolerates missing country code)."""
    if s is None:
        return None
    digits = re.sub(r"[^\d+]", "", s.strip())
    if not digits:
        return False
    cc, lo, hi = _PHONE_REGIONS.get(default_region.upper(), ("1", 7, 15))
    if digits.startswith("+"):
        body = digits[1:]
        if not body.isdigit():
            return False
        if body.startswith(cc):
            national = body[len(cc):]
            return lo <= len(national) <= hi
        # other country code: generic E.164 bound
        return 7 <= len(body) <= 15
    if not digits.isdigit():
        return False
    if digits.startswith(cc) and lo <= len(digits) - len(cc) <= hi:
        return not strict or default_region.upper() in ("US", "CA")
    return lo <= len(digits) <= hi


def phone_valid_block(values, default_region: str,
                      track_nulls: bool) -> np.ndarray:
    """[isValid(, isNull)] block shared by PhoneVectorizer and
    PhoneMapVectorizer so scalar and map phone encodings cannot drift."""
    n = len(values)
    block = np.zeros((n, 2 if track_nulls else 1), dtype=np.float32)
    for i, v in enumerate(values):
        valid = is_valid_phone(v, default_region)
        if valid is None:
            if track_nulls:
                block[i, 1] = 1.0
        elif valid:
            block[i, 0] = 1.0
    return block


class PhoneIsValidTransformer(HostTransformer):
    """Phone → Binary validity (RichTextFeature.isValidPhoneDefaultCountry,
    RichTextFeature.scala:545)."""

    in_types = (T.Phone,)
    out_type = T.Binary

    def __init__(self, default_region: str = "US", strict: bool = False,
                 uid: Optional[str] = None):
        super().__init__(uid=uid, default_region=default_region, strict=strict)
        self.default_region = default_region
        self.strict = strict

    def transform(self, cols: Sequence[Column], ctx=None) -> Column:
        return Column.from_values(T.Binary, [
            is_valid_phone(v, self.default_region, self.strict)
            for v in cols[0].data])


class PhoneVectorizer(Transformer):
    """N Phone features → [isValid, isNull] per feature — the transmogrify
    default for Phone (RichTextFeature.vectorize, :569-582)."""

    in_types = (T.Phone, Ellipsis)
    out_type = T.OPVector

    def __init__(self, default_region: str = "US", track_nulls: bool = True,
                 uid: Optional[str] = None):
        super().__init__(uid=uid, default_region=default_region,
                         track_nulls=track_nulls)
        self.default_region = default_region
        self.track_nulls = track_nulls

    def host_prepare(self, cols: Sequence[Optional[Column]]):
        return [phone_valid_block(c.data, self.default_region,
                                  self.track_nulls) for c in cols]

    def device_apply(self, enc, dev):
        import jax.numpy as jnp
        return jnp.concatenate([jnp.asarray(b) for b in enc], axis=1)

    def output_meta(self) -> VectorMetadata:
        cols: List[VectorColumnMetadata] = []
        for f in self.input_features:
            cols.append(VectorColumnMetadata(
                parent_name=f.name, parent_type=f.ftype.__name__,
                grouping=f.name, indicator_value="IsValid"))
            if self.track_nulls:
                cols.append(VectorColumnMetadata(
                    parent_name=f.name, parent_type=f.ftype.__name__,
                    grouping=f.name, indicator_value=NULL_INDICATOR))
        return VectorMetadata(self.output_name(), tuple(cols)).with_indices()


# --------------------------------------------------------------------------- #
# MIME type (Base64 payloads)                                                 #
# --------------------------------------------------------------------------- #

_MAGIC = [
    (b"%PDF", "application/pdf"),
    (b"\x89PNG\r\n\x1a\n", "image/png"),
    (b"\xff\xd8\xff", "image/jpeg"),
    (b"GIF8", "image/gif"),
    (b"BM", "image/bmp"),
    (b"PK\x03\x04", "application/zip"),
    (b"\x1f\x8b", "application/gzip"),
    (b"Rar!", "application/x-rar-compressed"),
    (b"\x7fELF", "application/x-executable"),
    (b"MZ", "application/x-msdownload"),
    (b"ID3", "audio/mpeg"),
    (b"RIFF", "audio/x-wav"),
    (b"OggS", "audio/ogg"),
    (b"\xd0\xcf\x11\xe0", "application/x-ole-storage"),
]


def detect_mime(b64: Optional[str], type_hint: Optional[str] = None) -> Optional[str]:
    """Magic-byte MIME sniffing of base64 payloads (MimeTypeDetector.scala —
    Tika's detector behind the same Base64 → Text contract)."""
    if b64 is None:
        return None
    if not b64:
        return ""
    try:
        raw = base64.b64decode(b64, validate=True)
    except (binascii.Error, ValueError):
        return None
    if not raw:
        return ""
    for magic, mime in _MAGIC:
        if raw.startswith(magic):
            return mime
    head = raw[:512]
    try:
        text = head.decode("utf-8")
    except UnicodeDecodeError:
        return type_hint or "application/octet-stream"
    stripped = text.lstrip().lower()
    if stripped.startswith(("<html", "<!doctype html")):
        return "text/html"
    if stripped.startswith("<?xml"):
        return "application/xml"
    if stripped.startswith(("{", "[")):
        return "application/json"
    if stripped.startswith("<svg"):
        return "image/svg+xml"
    return type_hint or "text/plain"


class MimeTypeDetector(HostTransformer):
    """Base64 → Text MIME type (MimeTypeDetector.scala)."""

    in_types = (T.Base64,)
    out_type = T.Text

    def __init__(self, type_hint: Optional[str] = None,
                 uid: Optional[str] = None):
        super().__init__(uid=uid, type_hint=type_hint)
        self.type_hint = type_hint

    def transform(self, cols: Sequence[Column], ctx=None) -> Column:
        out = np.empty(len(cols[0].data), dtype=object)
        for i, v in enumerate(cols[0].data):
            out[i] = detect_mime(v, self.type_hint)
        return Column(T.Text, out)


# --------------------------------------------------------------------------- #
# language detection                                                          #
# --------------------------------------------------------------------------- #

# n-gram profile detector over ~45 languages (VERDICT r3 #4): script
# histograms + Cavnar-Trenkle trigram rank profiles + distinctive-char
# evidence, reimplementing the Optimaize technique from scratch
from transmogrifai_tpu.utils.language import detect_language  # noqa: F401


class LangDetector(HostTransformer):
    """Text → RealMap of language → confidence (LangDetector.scala)."""

    in_types = (T.Text,)
    out_type = T.RealMap

    def transform(self, cols: Sequence[Column], ctx=None) -> Column:
        out = np.empty(len(cols[0].data), dtype=object)
        for i, v in enumerate(cols[0].data):
            d = detect_language(v)
            out[i] = d if d else None
        return Column(T.RealMap, out)


# --------------------------------------------------------------------------- #
# human names                                                                 #
# --------------------------------------------------------------------------- #

_FEMALE = frozenset("""
mary patricia jennifer linda elizabeth barbara susan jessica sarah karen
nancy lisa margaret betty sandra ashley dorothy kimberly emily donna
michelle carol amanda melissa deborah stephanie rebecca laura sharon
cynthia kathleen amy shirley angela helen anna brenda pamela nicole emma
samantha katherine christine debra rachel catherine carolyn janet ruth
maria heather diane virginia julie joyce victoria olivia kelly christina
lauren joan evelyn judith megan cheryl andrea hannah martha jacqueline
frances gloria ann teresa kathryn sara janice jean alice madison doris
abigail julia judy grace denise amber marilyn beverly danielle theresa
sophia marie diana brittany natalie isabella charlotte rose alexis kayla
""".split())

_MALE = frozenset("""
james robert john michael david william richard joseph thomas charles
christopher daniel matthew anthony mark donald steven paul andrew joshua
kenneth kevin brian george timothy ronald edward jason jeffrey ryan jacob
gary nicholas eric jonathan stephen larry justin scott brandon benjamin
samuel gregory frank alexander raymond patrick jack dennis jerry tyler
aaron jose adam nathan henry douglas zachary peter kyle ethan walter noah
jeremy christian keith roger terry austin sean gerald carl harold dylan
arthur lawrence jordan jesse bryan billy bruce gabriel joe logan alan
juan albert willie elijah wayne randy vincent mason roy ralph bobby
russell bradley philip eugene
""".split())


def name_stats(text: Optional[str]) -> Optional[Dict[str, str]]:
    """NameStats map {isName, gender[, firstName]} — HumanNameDetector /
    GenderDetectStrategy.ByFirstName analogue over a name dictionary."""
    if not text:
        return None
    tokens = [t.lower() for t in re.findall(r"[A-Za-zà-ÿ'-]+", text)]
    if not 1 <= len(tokens) <= 4:
        return {"isName": "false", "gender": "unknown"}
    first = tokens[0]
    if first in _FEMALE:
        return {"isName": "true", "gender": "female", "firstName": first}
    if first in _MALE:
        return {"isName": "true", "gender": "male", "firstName": first}
    # any dictionary hit in later tokens (e.g. "dr maria lopez")
    for t in tokens[1:]:
        if t in _FEMALE:
            return {"isName": "true", "gender": "female", "firstName": t}
        if t in _MALE:
            return {"isName": "true", "gender": "male", "firstName": t}
    return {"isName": "false", "gender": "unknown"}


class HumanNameDetector(HostTransformer):
    """Text → NameStats (HumanNameDetector.scala / GenderDetectStrategy)."""

    in_types = (T.Text,)
    out_type = T.NameStats

    def transform(self, cols: Sequence[Column], ctx=None) -> Column:
        out = np.empty(len(cols[0].data), dtype=object)
        for i, v in enumerate(cols[0].data):
            out[i] = name_stats(v)
        return Column(T.NameStats, out)


class NameEntityRecognizer(HostTransformer):
    """Text → MultiPickListMap of entity type → tokens
    (OpenNLPNameEntityTagger.scala:42 contract).

    When a directory of OpenNLP 1.5-format models is configured
    (`TRANSMOGRIFAI_OPENNLP_DIR` or `model_dir=`), the REAL trained
    maxent models run through the native loader (`utils/opennlp.py`):
    text → SentenceDetector → TokenizerME → per-entity NameFinder beam
    search, exactly the reference's tagger pipeline. With no models
    available it falls back to the capitalization + name-dictionary
    heuristic."""

    in_types = (T.Text,)
    out_type = T.MultiPickListMap

    def __init__(self, language: str = "es", model_dir: Optional[str] = None,
                 uid: Optional[str] = None):
        super().__init__(uid=uid, language=language, model_dir=model_dir)
        self.language = language
        self._model_dir = model_dir
        self._pipeline = None  # lazy (sentence, tokenizer, {entity: finder})

    def _load_pipeline(self):
        if self._pipeline is not None:
            return self._pipeline
        self._pipeline = False
        try:
            from transmogrifai_tpu.utils import opennlp as onlp
            mods = onlp.available_models(self._model_dir)
            finders = {}
            for key, path in mods.items():
                pre = f"{self.language}-ner-"
                if key.startswith(pre):
                    finders[key[len(pre):]] = onlp.NameFinder(
                        onlp.load_model(path))
            if finders:
                def _maybe(key):
                    return (onlp.load_model(mods[key]) if key in mods
                            else None)
                sent = _maybe(f"{self.language}-sent") or _maybe("en-sent")
                tok = _maybe(f"{self.language}-token") or _maybe("en-token")
                self._pipeline = (
                    onlp.SentenceDetector(sent) if sent else None,
                    onlp.TokenizerME(tok) if tok else None,
                    finders)
        except Exception:
            log.exception("OpenNLP models unavailable; heuristic NER")
        return self._pipeline

    def transform(self, cols: Sequence[Column], ctx=None) -> Column:
        pipe = self._load_pipeline()
        out = np.empty(len(cols[0].data), dtype=object)
        for i, v in enumerate(cols[0].data):
            out[i] = (self._entities_model(v, pipe) if pipe
                      else self._entities(v))
        return Column(T.MultiPickListMap, out)

    @staticmethod
    def _entities_model(text: Optional[str], pipe
                        ) -> Optional[Dict[str, frozenset]]:
        if not text:
            return None
        sent_d, tok_d, finders = pipe
        sentences = sent_d.split(text) if sent_d else [text]
        found: Dict[str, set] = {}
        for s in sentences:
            tokens = tok_d.tokenize(s) if tok_d else s.split()
            for entity, finder in finders.items():
                for a, b, _ in finder.spans(tokens):
                    found.setdefault(entity.capitalize(), set()).add(
                        " ".join(tokens[a:b]).lower())
        if not found:
            return None
        return {k: frozenset(v) for k, v in found.items()}

    @staticmethod
    def _entities(text: Optional[str]) -> Optional[Dict[str, frozenset]]:
        if not text:
            return None
        persons = set()
        for m in re.finditer(r"\b([A-Z][a-zà-ÿ'-]+)(?:\s+[A-Z][a-zà-ÿ'-]+)*",
                             text):
            first = m.group(1).lower()
            if first in _FEMALE or first in _MALE:
                persons.add(m.group(0).lower())
        return {"Person": frozenset(persons)} if persons else None
