"""Type-specific enrichment: email/URL/phone/MIME/language/name detection.

Reference parity:
- `core/.../feature/PhoneNumberParser.scala` (libphonenumber validity) →
  region-aware digit rules here (pure python; no JVM libphonenumber)
- `ValidEmailTransformer` (core/.../feature/ValidEmailTransformer.scala)
- Email/URL domain pivots (`core/.../dsl/RichTextFeature.scala:603-688`,
  `EmailToPickListMapTransformer.scala`)
- `MimeTypeDetector` (core/.../feature/MimeTypeDetector.scala — Tika) →
  magic-byte table here
- `LangDetector` (core/.../feature/LangDetector.scala +
  `OptimaizeLanguageDetector.scala:45`) → script ranges + stopword-profile
  scoring (pure python)
- `HumanNameDetector`/gender (`features/.../impl/feature/
  GenderDetectStrategy.scala`, OpenNLPNameEntityTagger.scala:42) →
  dictionary heuristic (the reference's OpenNLP binaries are data files,
  substituted per SURVEY §2.9)

All are host-side stages: their outputs (Binary/PickList/Text/maps) feed
the standard vectorizers, so the device program sees only dense encodings.
"""

from __future__ import annotations

import base64
import binascii
import logging
import re
from typing import Dict, List, Optional, Sequence

import numpy as np

from transmogrifai_tpu import types as T
from transmogrifai_tpu.data.columns import Column
from transmogrifai_tpu.data.metadata import (
    NULL_INDICATOR, VectorColumnMetadata, VectorMetadata)
from transmogrifai_tpu.stages.base import HostTransformer, Transformer

log = logging.getLogger(__name__)

# --------------------------------------------------------------------------- #
# email                                                                       #
# --------------------------------------------------------------------------- #

_EMAIL_RE = re.compile(
    r"^[A-Za-z0-9!#$%&'*+/=?^_`{|}~.-]+@([A-Za-z0-9-]+\.)+[A-Za-z]{2,}$")


def email_parts(s: Optional[str]):
    """(prefix, domain) or (None, None) when invalid."""
    if not s or not _EMAIL_RE.match(s):
        return None, None
    prefix, domain = s.rsplit("@", 1)
    return prefix, domain


class ValidEmailTransformer(HostTransformer):
    """Email → Binary validity (ValidEmailTransformer.scala)."""

    in_types = (T.Email,)
    out_type = T.Binary

    def transform(self, cols: Sequence[Column], ctx=None) -> Column:
        src = cols[0].data
        return Column.from_values(T.Binary, [
            None if v is None else (email_parts(v)[1] is not None)
            for v in src])


class EmailDomainTransformer(HostTransformer):
    """Email → PickList of the domain (EmailDomainToPickList,
    RichTextFeature.scala:630); invalid/empty → None."""

    in_types = (T.Email,)
    out_type = T.PickList

    def transform(self, cols: Sequence[Column], ctx=None) -> Column:
        out = np.empty(len(cols[0].data), dtype=object)
        for i, v in enumerate(cols[0].data):
            out[i] = email_parts(v)[1]
        return Column(T.PickList, out)


class EmailToPickListMapTransformer(HostTransformer):
    """Email → PickListMap {Prefix, Domain}
    (EmailToPickListMapTransformer.scala)."""

    in_types = (T.Email,)
    out_type = T.PickListMap

    def transform(self, cols: Sequence[Column], ctx=None) -> Column:
        out = np.empty(len(cols[0].data), dtype=object)
        for i, v in enumerate(cols[0].data):
            prefix, domain = email_parts(v)
            out[i] = ({"Prefix": prefix, "Domain": domain}
                      if domain is not None else None)
        return Column(T.PickListMap, out)


# --------------------------------------------------------------------------- #
# URL                                                                         #
# --------------------------------------------------------------------------- #

_URL_RE = re.compile(
    r"^(?P<proto>https?|ftp)://(?P<host>[A-Za-z0-9.-]+\.[A-Za-z]{2,})"
    r"(?::\d+)?(?:/[^\s]*)?$", re.IGNORECASE)


def url_parts(s: Optional[str]):
    """(protocol, domain) of a valid http/https/ftp url, else (None, None)
    (URLIsValid / URLDomainToText, RichTextFeature.scala:642-654)."""
    if not s:
        return None, None
    m = _URL_RE.match(s.strip())
    if not m:
        return None, None
    return m.group("proto").lower(), m.group("host").lower()


class UrlIsValidTransformer(HostTransformer):
    in_types = (T.URL,)
    out_type = T.Binary

    def transform(self, cols: Sequence[Column], ctx=None) -> Column:
        return Column.from_values(T.Binary, [
            None if v is None else (url_parts(v)[1] is not None)
            for v in cols[0].data])


class UrlDomainTransformer(HostTransformer):
    """URL → PickList domain of VALID urls (URLDomainToPickList,
    RichTextFeature.scala:843)."""

    in_types = (T.URL,)
    out_type = T.PickList

    def transform(self, cols: Sequence[Column], ctx=None) -> Column:
        out = np.empty(len(cols[0].data), dtype=object)
        for i, v in enumerate(cols[0].data):
            out[i] = url_parts(v)[1]
        return Column(T.PickList, out)


class UrlProtocolTransformer(HostTransformer):
    in_types = (T.URL,)
    out_type = T.Text

    def transform(self, cols: Sequence[Column], ctx=None) -> Column:
        out = np.empty(len(cols[0].data), dtype=object)
        for i, v in enumerate(cols[0].data):
            out[i] = url_parts(v)[0]
        return Column(T.Text, out)


# --------------------------------------------------------------------------- #
# phone                                                                       #
# --------------------------------------------------------------------------- #

# national number length rules per region (libphonenumber-lite: the
# reference wraps full libphonenumber metadata; this table carries the
# country code + national-number length window for the ~40 most common
# calling regions, plus NANP structural rules below):
# region → (country_code, min_len, max_len)
_PHONE_REGIONS: Dict[str, tuple] = {
    "US": ("1", 10, 10), "CA": ("1", 10, 10), "GB": ("44", 9, 10),
    "DE": ("49", 6, 11), "FR": ("33", 9, 9), "IN": ("91", 10, 10),
    "AU": ("61", 9, 9), "JP": ("81", 9, 10), "BR": ("55", 10, 11),
    "MX": ("52", 10, 10), "CN": ("86", 10, 11), "ES": ("34", 9, 9),
    "IT": ("39", 8, 11), "NL": ("31", 9, 9), "SE": ("46", 7, 9),
    "NO": ("47", 8, 8), "DK": ("45", 8, 8), "FI": ("358", 5, 10),
    "PL": ("48", 9, 9), "CZ": ("420", 9, 9), "SK": ("421", 9, 9),
    "AT": ("43", 7, 11), "CH": ("41", 9, 9), "BE": ("32", 8, 9),
    "PT": ("351", 9, 9), "GR": ("30", 10, 10), "IE": ("353", 7, 9),
    "RU": ("7", 10, 10), "UA": ("380", 9, 9), "TR": ("90", 10, 10),
    "IL": ("972", 8, 9), "SA": ("966", 8, 9), "AE": ("971", 8, 9),
    "EG": ("20", 8, 10), "ZA": ("27", 9, 9), "NG": ("234", 7, 10),
    "KE": ("254", 9, 9), "KR": ("82", 8, 10), "SG": ("65", 8, 8),
    "HK": ("852", 8, 8), "TW": ("886", 8, 9), "TH": ("66", 8, 9),
    "VN": ("84", 9, 10), "ID": ("62", 8, 12), "MY": ("60", 9, 10),
    "PH": ("63", 8, 10), "PK": ("92", 9, 10), "BD": ("880", 8, 10),
    "AR": ("54", 10, 10), "CL": ("56", 9, 9), "CO": ("57", 10, 10),
    "PE": ("51", 9, 9), "NZ": ("64", 8, 10),
}

# country code → (min_len, max_len) for resolving "+cc..." numbers from
# OTHER regions against their own length window (longest-prefix match)
_CC_LENGTHS: Dict[str, tuple] = {}
for _region, (_cc, _lo, _hi) in _PHONE_REGIONS.items():
    prev = _CC_LENGTHS.get(_cc)
    _CC_LENGTHS[_cc] = ((min(prev[0], _lo), max(prev[1], _hi))
                        if prev else (_lo, _hi))


def _nanp_valid(national: str) -> bool:
    """NANP structure (US/CA): NXX-NXX-XXXX with N in 2-9 for the area
    and exchange codes (libphonenumber's generalDesc pattern)."""
    return (len(national) == 10 and national[0] not in "01"
            and national[3] not in "01")


# libphonenumber's region sentinel for "+"-prefixed numbers whose region
# is carried by the number itself (PhoneNumberParser.scala:256)
INTERNATIONAL_REGION = "ZZ"

# region → comma-separated country names, the resolution table behind
# country-name region matching (PhoneNumberParser.DefaultCountryCodes,
# PhoneNumberParser.scala:327-…; ours covers every region in
# _PHONE_REGIONS rather than only the NANP islands)
_COUNTRY_NAMES: Dict[str, str] = {
    "US": "USA, United States of America, United States",
    "CA": "Canada", "GB": "United Kingdom, Great Britain, England",
    "DE": "Germany, Deutschland", "FR": "France", "IN": "India",
    "AU": "Australia", "JP": "Japan", "BR": "Brazil, Brasil",
    "MX": "Mexico", "CN": "China", "ES": "Spain, Espana",
    "IT": "Italy, Italia", "NL": "Netherlands, Holland", "SE": "Sweden",
    "NO": "Norway", "DK": "Denmark", "FI": "Finland", "PL": "Poland",
    "CZ": "Czech Republic, Czechia", "SK": "Slovakia", "AT": "Austria",
    "CH": "Switzerland", "BE": "Belgium", "PT": "Portugal",
    "GR": "Greece", "IE": "Ireland", "RU": "Russia, Russian Federation",
    "UA": "Ukraine", "TR": "Turkey, Turkiye", "IL": "Israel",
    "SA": "Saudi Arabia", "AE": "United Arab Emirates, UAE",
    "EG": "Egypt", "ZA": "South Africa", "NG": "Nigeria", "KE": "Kenya",
    "KR": "South Korea, Korea, Republic of Korea", "SG": "Singapore",
    "HK": "Hong Kong", "TW": "Taiwan", "TH": "Thailand",
    "VN": "Vietnam, Viet Nam", "ID": "Indonesia", "MY": "Malaysia",
    "PH": "Philippines", "PK": "Pakistan", "BD": "Bangladesh",
    "AR": "Argentina", "CL": "Chile", "CO": "Colombia", "PE": "Peru",
    "NZ": "New Zealand",
}


def _parse_parts(s: str, default_region: str = "US", strict: bool = False):
    """(valid, country_code, national_number) for a non-None input.

    The shared core behind `is_valid_phone` and `parse_phone`
    (PhoneNumberParser.scala parsePhoneNumber/validate/parse:270-322).
    `country_code` is "" when the calling code cannot be resolved
    (unknown "+cc" prefix) and None when invalid."""
    digits = re.sub(r"[^\d+]", "", s.strip())
    if not digits:
        return False, None, None
    region = default_region.upper()
    known = region in _PHONE_REGIONS
    cc, lo, hi = _PHONE_REGIONS.get(region, ("", 7, 15))

    def _check(cc_used: str, national: str, lo_: int, hi_: int) -> bool:
        if cc_used == "1":
            return _nanp_valid(national)
        return lo_ <= len(national) <= hi_

    if digits.startswith("+"):
        body = digits[1:]
        if not body.isdigit():
            return False, None, None
        if known and body.startswith(cc):
            nat = body[len(cc):]
            return _check(cc, nat, lo, hi), cc, nat
        # another country's code: longest-prefix match into the table
        for plen in (3, 2, 1):
            pref = body[:plen]
            if pref in _CC_LENGTHS:
                flo, fhi = _CC_LENGTHS[pref]
                nat = body[plen:]
                return _check(pref, nat, flo, fhi), pref, nat
        # unknown code: generic E.164 bound; calling code unresolvable
        return 7 <= len(body) <= 15, "", body
    if not digits.isdigit():
        return False, None, None
    if region == INTERNATIONAL_REGION:
        # "ZZ" carries no national metadata — only "+" numbers resolve
        # (libphonenumber parse throws for ZZ without "+")
        return False, None, None
    if known and digits.startswith(cc) and \
            _check(cc, digits[len(cc):], lo, hi):
        return ((not strict or region in ("US", "CA")),
                cc, digits[len(cc):])
    # bare national number: NANP structure only for NANP default regions;
    # unknown regions keep the generic (7, 15) window
    if known and cc == "1":
        return _nanp_valid(digits), cc, digits
    if lo <= len(digits) <= hi:
        # normalization strips the national trunk 0 where the remainder
        # still fits the window — Italy keeps its leading zero as part of
        # the significant number (libphonenumber nationalPrefix metadata)
        if (digits.startswith("0") and region != "IT"
                and lo <= len(digits) - 1 <= hi):
            return True, cc, digits[1:]
        return True, cc, digits
    # national trunk prefix: most non-NANP regions write national numbers
    # with a leading 0 that is not part of the significant number
    # (libphonenumber's nationalPrefix strip); Italy's zero is significant,
    # so IT numbers must fit the window zero included (branch above)
    if (digits.startswith("0") and region != "IT"
            and lo <= len(digits) - 1 <= hi):
        return True, cc, digits[1:]
    return False, None, None


def is_valid_phone(s: Optional[str], default_region: str = "US",
                   strict: bool = False) -> Optional[bool]:
    """Region-aware validity (PhoneNumberParser.scala: validity against a
    default region; non-strict mode tolerates missing country code).
    "+cc" numbers from a different region validate against THAT region's
    length window via longest-code match; NANP numbers additionally check
    the N[2-9]XX area/exchange structure."""
    if s is None:
        return None
    return _parse_parts(s, default_region, strict)[0]


def parse_phone(s: Optional[str], default_region: str = "US",
                strict: bool = False) -> Optional[str]:
    """Normalize to "+{countryCode}{nationalNumber}" when valid, else None
    (PhoneNumberParser.parse, PhoneNumberParser.scala:314-322). Numbers
    whose calling code cannot be resolved (unknown "+cc") return None even
    when length-valid, matching the reference's isValidNumber gate."""
    if s is None:
        return None
    valid, cc, nat = _parse_parts(s, default_region, strict)
    if not valid or not cc:
        return None
    return f"+{cc}{nat}"


def _char_bigrams(s: str):
    return {s[i:i + 2] for i in range(len(s) - 1)}


def _name_bigrams(codes: Dict[str, str]):
    return [(reg, _char_bigrams(name.strip().upper()))
            for reg, names in codes.items()
            for name in str(names).split(",")]


_DEFAULT_NAME_BIGRAMS = _name_bigrams(_COUNTRY_NAMES)
_REGION_CACHE: Dict[str, str] = {}


def resolve_region(phone: Optional[str], region_text: Optional[str] = None,
                   default_region: str = "US",
                   country_codes: Optional[Dict[str, str]] = None) -> str:
    """Resolve the validation region for a (phone, region-text) pair
    (PhoneNumberParser.validCountryCode, PhoneNumberParser.scala:285-305):
    "+" numbers resolve to the international sentinel; a recognized region
    code wins; otherwise the nearest country NAME by character-bigram
    Jaccard similarity over `country_codes` (region → comma-separated
    names; defaults to the built-in table); else the default region."""
    if phone and phone.strip().startswith("+"):
        return INTERNATIONAL_REGION
    if region_text and region_text.strip():
        rc = region_text.strip().upper()
        codes = country_codes if country_codes else _COUNTRY_NAMES
        if rc in codes or rc in _PHONE_REGIONS:
            return rc
        if country_codes:
            entries = _name_bigrams(country_codes)
        else:
            # region texts are low-cardinality in practice — cache the
            # name-match result so per-row calls don't rescan the table
            if rc in _REGION_CACHE:
                return _REGION_CACHE[rc]
            entries = _DEFAULT_NAME_BIGRAMS
        q = _char_bigrams(rc)
        best, best_sim = None, 0.0
        for reg, b in entries:
            union = len(q | b)
            sim = len(q & b) / union if union else 0.0
            if sim > best_sim:
                best, best_sim = reg, sim
        if best is not None:
            if not country_codes and len(_REGION_CACHE) < 4096:
                _REGION_CACHE[rc] = best
            return best
    return default_region.upper()


def phone_valid_block(values, default_region: str,
                      track_nulls: bool) -> np.ndarray:
    """[isValid(, isNull)] block shared by PhoneVectorizer and
    PhoneMapVectorizer so scalar and map phone encodings cannot drift."""
    n = len(values)
    block = np.zeros((n, 2 if track_nulls else 1), dtype=np.float32)
    for i, v in enumerate(values):
        valid = is_valid_phone(v, default_region)
        if valid is None:
            if track_nulls:
                block[i, 1] = 1.0
        elif valid:
            block[i, 0] = 1.0
    return block


class PhoneIsValidTransformer(HostTransformer):
    """Phone → Binary validity (RichTextFeature.isValidPhoneDefaultCountry,
    RichTextFeature.scala:545)."""

    in_types = (T.Phone,)
    out_type = T.Binary

    def __init__(self, default_region: str = "US", strict: bool = False,
                 uid: Optional[str] = None):
        super().__init__(uid=uid, default_region=default_region, strict=strict)
        self.default_region = default_region
        self.strict = strict

    def transform(self, cols: Sequence[Column], ctx=None) -> Column:
        return Column.from_values(T.Binary, [
            is_valid_phone(v, self.default_region, self.strict)
            for v in cols[0].data])


class PhoneIsValidWithRegionTransformer(HostTransformer):
    """(Phone, Text region) → Binary validity with per-row region
    resolution incl. country-name matching (IsValidPhoneNumber,
    PhoneNumberParser.scala:198-215)."""

    in_types = (T.Phone, T.Text)
    out_type = T.Binary

    def __init__(self, default_region: str = "US", strict: bool = False,
                 country_codes: Optional[Dict[str, str]] = None,
                 uid: Optional[str] = None):
        super().__init__(uid=uid, default_region=default_region,
                         strict=strict, country_codes=country_codes)
        self.default_region = default_region
        self.strict = strict
        self.country_codes = country_codes

    def transform(self, cols: Sequence[Column], ctx=None) -> Column:
        return Column.from_values(T.Binary, [
            is_valid_phone(p, resolve_region(p, r, self.default_region,
                                             self.country_codes),
                           self.strict)
            for p, r in zip(cols[0].data, cols[1].data)])


class PhoneParseTransformer(HostTransformer):
    """Phone → normalized "+cc…" Phone against the default region, None
    when invalid (ParsePhoneDefaultCountry, PhoneNumberParser.scala:170-179)."""

    in_types = (T.Phone,)
    out_type = T.Phone

    def __init__(self, default_region: str = "US", strict: bool = False,
                 uid: Optional[str] = None):
        super().__init__(uid=uid, default_region=default_region, strict=strict)
        self.default_region = default_region
        self.strict = strict

    def transform(self, cols: Sequence[Column], ctx=None) -> Column:
        return Column.from_values(T.Phone, [
            parse_phone(v, self.default_region, self.strict)
            for v in cols[0].data])


class PhoneParseWithRegionTransformer(HostTransformer):
    """(Phone, Text region) → normalized Phone with per-row region
    resolution (ParsePhoneNumber, PhoneNumberParser.scala:143-159)."""

    in_types = (T.Phone, T.Text)
    out_type = T.Phone

    def __init__(self, default_region: str = "US", strict: bool = False,
                 country_codes: Optional[Dict[str, str]] = None,
                 uid: Optional[str] = None):
        super().__init__(uid=uid, default_region=default_region,
                         strict=strict, country_codes=country_codes)
        self.default_region = default_region
        self.strict = strict
        self.country_codes = country_codes

    def transform(self, cols: Sequence[Column], ctx=None) -> Column:
        return Column.from_values(T.Phone, [
            parse_phone(p, resolve_region(p, r, self.default_region,
                                          self.country_codes),
                        self.strict)
            for p, r in zip(cols[0].data, cols[1].data)])


class PhoneMapIsValidTransformer(HostTransformer):
    """PhoneMap → BinaryMap per-key validity; keys whose value is None are
    dropped, matching the reference's SomeValue collect
    (IsValidPhoneMapDefaultCountry, PhoneNumberParser.scala:241-251)."""

    in_types = (T.PhoneMap,)
    out_type = T.BinaryMap

    def __init__(self, default_region: str = "US", strict: bool = False,
                 uid: Optional[str] = None):
        super().__init__(uid=uid, default_region=default_region, strict=strict)
        self.default_region = default_region
        self.strict = strict

    def transform(self, cols: Sequence[Column], ctx=None) -> Column:
        out: List[Optional[Dict[str, bool]]] = []
        for m in cols[0].data:
            if m is None:
                out.append(None)
                continue
            d = {}
            for k, v in m.items():
                valid = is_valid_phone(v, self.default_region, self.strict)
                if valid is not None:
                    d[k] = valid
            out.append(d)
        return Column.from_values(T.BinaryMap, out)


class PhoneVectorizer(Transformer):
    """N Phone features → [isValid, isNull] per feature — the transmogrify
    default for Phone (RichTextFeature.vectorize, :569-582)."""

    in_types = (T.Phone, Ellipsis)
    out_type = T.OPVector

    def __init__(self, default_region: str = "US", track_nulls: bool = True,
                 uid: Optional[str] = None):
        super().__init__(uid=uid, default_region=default_region,
                         track_nulls=track_nulls)
        self.default_region = default_region
        self.track_nulls = track_nulls

    def host_prepare(self, cols: Sequence[Optional[Column]]):
        return [phone_valid_block(c.data, self.default_region,
                                  self.track_nulls) for c in cols]

    def device_apply(self, enc, dev):
        import jax.numpy as jnp
        return jnp.concatenate([jnp.asarray(b) for b in enc], axis=1)

    def output_meta(self) -> VectorMetadata:
        cols: List[VectorColumnMetadata] = []
        for f in self.input_features:
            cols.append(VectorColumnMetadata(
                parent_name=f.name, parent_type=f.ftype.__name__,
                grouping=f.name, indicator_value="IsValid"))
            if self.track_nulls:
                cols.append(VectorColumnMetadata(
                    parent_name=f.name, parent_type=f.ftype.__name__,
                    grouping=f.name, indicator_value=NULL_INDICATOR))
        return VectorMetadata(self.output_name(), tuple(cols)).with_indices()


# --------------------------------------------------------------------------- #
# MIME type (Base64 payloads)                                                 #
# --------------------------------------------------------------------------- #

_MAGIC = [
    (b"%PDF", "application/pdf"),
    (b"\x89PNG\r\n\x1a\n", "image/png"),
    (b"\xff\xd8\xff", "image/jpeg"),
    (b"GIF8", "image/gif"),
    (b"BM", "image/bmp"),
    (b"PK\x03\x04", "application/zip"),
    (b"\x1f\x8b", "application/gzip"),
    (b"Rar!", "application/x-rar-compressed"),
    (b"\x7fELF", "application/x-executable"),
    (b"MZ", "application/x-msdownload"),
    (b"ID3", "audio/mpeg"),
    (b"RIFF", "audio/x-wav"),
    (b"OggS", "audio/ogg"),
    (b"\xd0\xcf\x11\xe0", "application/x-ole-storage"),
]


def detect_mime(b64: Optional[str], type_hint: Optional[str] = None) -> Optional[str]:
    """Magic-byte MIME sniffing of base64 payloads (MimeTypeDetector.scala —
    Tika's detector behind the same Base64 → Text contract)."""
    if b64 is None:
        return None
    if not b64:
        return ""
    try:
        raw = base64.b64decode(b64, validate=True)
    except (binascii.Error, ValueError):
        return None
    if not raw:
        return ""
    for magic, mime in _MAGIC:
        if raw.startswith(magic):
            return mime
    head = raw[:512]
    try:
        text = head.decode("utf-8")
    except UnicodeDecodeError:
        return type_hint or "application/octet-stream"
    stripped = text.lstrip().lower()
    if stripped.startswith(("<html", "<!doctype html")):
        return "text/html"
    if stripped.startswith("<?xml"):
        return "application/xml"
    if stripped.startswith(("{", "[")):
        return "application/json"
    if stripped.startswith("<svg"):
        return "image/svg+xml"
    return type_hint or "text/plain"


class MimeTypeDetector(HostTransformer):
    """Base64 → Text MIME type (MimeTypeDetector.scala)."""

    in_types = (T.Base64,)
    out_type = T.Text

    def __init__(self, type_hint: Optional[str] = None,
                 uid: Optional[str] = None):
        super().__init__(uid=uid, type_hint=type_hint)
        self.type_hint = type_hint

    def transform(self, cols: Sequence[Column], ctx=None) -> Column:
        out = np.empty(len(cols[0].data), dtype=object)
        for i, v in enumerate(cols[0].data):
            out[i] = detect_mime(v, self.type_hint)
        return Column(T.Text, out)


# --------------------------------------------------------------------------- #
# language detection                                                          #
# --------------------------------------------------------------------------- #

# n-gram profile detector over ~45 languages (VERDICT r3 #4): script
# histograms + Cavnar-Trenkle trigram rank profiles + distinctive-char
# evidence, reimplementing the Optimaize technique from scratch
from transmogrifai_tpu.utils.language import detect_language  # noqa: F401


class LangDetector(HostTransformer):
    """Text → RealMap of language → confidence (LangDetector.scala)."""

    in_types = (T.Text,)
    out_type = T.RealMap

    def transform(self, cols: Sequence[Column], ctx=None) -> Column:
        out = np.empty(len(cols[0].data), dtype=object)
        for i, v in enumerate(cols[0].data):
            d = detect_language(v)
            out[i] = d if d else None
        return Column(T.RealMap, out)


# --------------------------------------------------------------------------- #
# human names                                                                 #
# --------------------------------------------------------------------------- #

_FEMALE = frozenset("""
mary patricia jennifer linda elizabeth barbara susan jessica sarah karen
nancy lisa margaret betty sandra ashley dorothy kimberly emily donna
michelle carol amanda melissa deborah stephanie rebecca laura sharon
cynthia kathleen amy shirley angela helen anna brenda pamela nicole emma
samantha katherine christine debra rachel catherine carolyn janet ruth
maria heather diane virginia julie joyce victoria olivia kelly christina
lauren joan evelyn judith megan cheryl andrea hannah martha jacqueline
frances gloria ann teresa kathryn sara janice jean alice madison doris
abigail julia judy grace denise amber marilyn beverly danielle theresa
sophia marie diana brittany natalie isabella charlotte rose alexis kayla
""".split())

_MALE = frozenset("""
james robert john michael david william richard joseph thomas charles
christopher daniel matthew anthony mark donald steven paul andrew joshua
kenneth kevin brian george timothy ronald edward jason jeffrey ryan jacob
gary nicholas eric jonathan stephen larry justin scott brandon benjamin
samuel gregory frank alexander raymond patrick jack dennis jerry tyler
aaron jose adam nathan henry douglas zachary peter kyle ethan walter noah
jeremy christian keith roger terry austin sean gerald carl harold dylan
arthur lawrence jordan jesse bryan billy bruce gabriel joe logan alan
juan albert willie elijah wayne randy vincent mason roy ralph bobby
russell bradley philip eugene
""".split())


def name_stats(text: Optional[str]) -> Optional[Dict[str, str]]:
    """NameStats map {isName, gender[, firstName]} — HumanNameDetector /
    GenderDetectStrategy.ByFirstName analogue over a name dictionary."""
    if not text:
        return None
    tokens = [t.lower() for t in re.findall(r"[A-Za-zà-ÿ'-]+", text)]
    if not 1 <= len(tokens) <= 4:
        return {"isName": "false", "gender": "unknown"}
    first = tokens[0]
    if first in _FEMALE:
        return {"isName": "true", "gender": "female", "firstName": first}
    if first in _MALE:
        return {"isName": "true", "gender": "male", "firstName": first}
    # any dictionary hit in later tokens (e.g. "dr maria lopez")
    for t in tokens[1:]:
        if t in _FEMALE:
            return {"isName": "true", "gender": "female", "firstName": t}
        if t in _MALE:
            return {"isName": "true", "gender": "male", "firstName": t}
    return {"isName": "false", "gender": "unknown"}


class HumanNameDetector(HostTransformer):
    """Text → NameStats (HumanNameDetector.scala / GenderDetectStrategy)."""

    in_types = (T.Text,)
    out_type = T.NameStats

    def transform(self, cols: Sequence[Column], ctx=None) -> Column:
        out = np.empty(len(cols[0].data), dtype=object)
        for i, v in enumerate(cols[0].data):
            out[i] = name_stats(v)
        return Column(T.NameStats, out)


class NameEntityRecognizer(HostTransformer):
    """Text → MultiPickListMap of entity type → tokens
    (OpenNLPNameEntityTagger.scala:42 contract).

    When a directory of OpenNLP 1.5-format models is configured
    (`TRANSMOGRIFAI_OPENNLP_DIR` or `model_dir=`), the REAL trained
    maxent models run through the native loader (`utils/opennlp.py`):
    text → SentenceDetector → TokenizerME → per-entity NameFinder beam
    search, exactly the reference's tagger pipeline. With no models
    available it falls back to the capitalization + name-dictionary
    heuristic."""

    in_types = (T.Text,)
    out_type = T.MultiPickListMap

    def __init__(self, language: str = "es", model_dir: Optional[str] = None,
                 uid: Optional[str] = None):
        super().__init__(uid=uid, language=language, model_dir=model_dir)
        self.language = language
        self._model_dir = model_dir
        self._pipeline = None  # lazy (sentence, tokenizer, {entity: finder})

    def _load_pipeline(self):
        if self._pipeline is not None:
            return self._pipeline
        self._pipeline = False
        try:
            from transmogrifai_tpu.utils import opennlp as onlp
            mods = onlp.available_models(self._model_dir)
            finders = {}
            for key, path in mods.items():
                pre = f"{self.language}-ner-"
                if key.startswith(pre):
                    finders[key[len(pre):]] = onlp.NameFinder(
                        onlp.load_model(path))
            if finders:
                def _maybe(key):
                    return (onlp.load_model(mods[key]) if key in mods
                            else None)
                sent = _maybe(f"{self.language}-sent") or _maybe("en-sent")
                tok = _maybe(f"{self.language}-token") or _maybe("en-token")
                self._pipeline = (
                    onlp.SentenceDetector(sent) if sent else None,
                    onlp.TokenizerME(tok) if tok else None,
                    finders)
        except Exception:
            log.exception("OpenNLP models unavailable; heuristic NER")
        return self._pipeline

    def transform(self, cols: Sequence[Column], ctx=None) -> Column:
        pipe = self._load_pipeline()
        out = np.empty(len(cols[0].data), dtype=object)
        for i, v in enumerate(cols[0].data):
            out[i] = (self._entities_model(v, pipe) if pipe
                      else self._entities(v))
        return Column(T.MultiPickListMap, out)

    @staticmethod
    def _entities_model(text: Optional[str], pipe
                        ) -> Optional[Dict[str, frozenset]]:
        if not text:
            return None
        sent_d, tok_d, finders = pipe
        sentences = sent_d.split(text) if sent_d else [text]
        found: Dict[str, set] = {}
        for s in sentences:
            tokens = tok_d.tokenize(s) if tok_d else s.split()
            for entity, finder in finders.items():
                for a, b, _ in finder.spans(tokens):
                    found.setdefault(entity.capitalize(), set()).add(
                        " ".join(tokens[a:b]).lower())
        if not found:
            return None
        return {k: frozenset(v) for k, v in found.items()}

    @staticmethod
    def _entities(text: Optional[str]) -> Optional[Dict[str, frozenset]]:
        if not text:
            return None
        persons = set()
        for m in re.finditer(r"\b([A-Z][a-zà-ÿ'-]+)(?:\s+[A-Z][a-zà-ÿ'-]+)*",
                             text):
            first = m.group(1).lower()
            if first in _FEMALE or first in _MALE:
                persons.add(m.group(0).lower())
        return {"Person": frozenset(persons)} if persons else None
