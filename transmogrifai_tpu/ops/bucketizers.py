"""Bucketization stages: unsupervised splits and supervised (decision-tree)
split discovery.

Reference parity:
- `core/.../feature/NumericBucketizer.scala` — one-hot of user-provided
  monotonic splits, with trackNulls / trackInvalid columns.
- `core/.../feature/DecisionTreeNumericBucketizer.scala` — label-aware
  bucketization: fit a single-feature decision tree against the label and
  use its thresholds as splits; produces no bucket columns when the tree
  finds no useful split.
- `core/.../feature/DecisionTreeNumericMapBucketizer.scala` — same per map
  key.

TPU-first: the reference delegates to Spark's DecisionTreeClassifier; here
split search is a vectorized prefix-sum scan over sorted (value, label)
pairs — O(n log n) on host numpy at fit time (fit-time host work mirrors
the two-phase fit→static-transform design), while the fitted transform is a
pure jnp one-hot that fuses into the scoring program.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from transmogrifai_tpu import types as T
from transmogrifai_tpu.data.columns import Column
from transmogrifai_tpu.data.metadata import (
    NULL_INDICATOR, VectorColumnMetadata, VectorMetadata)
from transmogrifai_tpu.stages.base import Estimator, FitContext, Transformer


def _bucket_labels(splits: Sequence[float]) -> List[str]:
    s = ["-Inf" if not np.isfinite(a) else f"{a:g}" for a in splits]
    s[-1] = "Inf" if not np.isfinite(splits[-1]) else s[-1]
    return [f"[{a}-{b})" for a, b in zip(s[:-1], s[1:])]


class NumericBucketizerModel(Transformer):
    """One-hot of bucket membership given monotonic `splits` (left-inclusive)."""

    in_types = (T.OPNumeric,)
    out_type = T.OPVector

    def __init__(self, splits: Sequence[float], track_nulls: bool = True,
                 track_invalid: bool = False,
                 labels: Optional[Sequence[str]] = None,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.splits = np.asarray(splits, dtype=np.float64)
        if len(self.splits) < 2 or np.any(np.diff(self.splits) <= 0):
            raise ValueError("splits must be ≥2 strictly increasing values")
        self.track_nulls = track_nulls
        self.track_invalid = track_invalid
        self.labels = list(labels) if labels else _bucket_labels(self.splits)

    @property
    def n_buckets(self) -> int:
        return len(self.splits) - 1

    def device_apply(self, enc, dev):
        x, m = dev[0]["value"], dev[0]["mask"].astype(bool)
        inner = jnp.asarray(self.splits[1:-1])
        idx = jnp.searchsorted(inner, x, side="right")
        valid = m & (x >= self.splits[0]) & (x < self.splits[-1])
        onehot = (jnp.arange(self.n_buckets)[None, :] == idx[:, None]) & valid[:, None]
        cols = [onehot.astype(jnp.float32)]
        if self.track_invalid:
            cols.append((m & ~valid)[:, None].astype(jnp.float32))
        if self.track_nulls:
            cols.append((~m)[:, None].astype(jnp.float32))
        return jnp.concatenate(cols, axis=1)

    def output_meta(self) -> VectorMetadata:
        f = self.input_features[0]
        cols = [VectorColumnMetadata(parent_name=f.name,
                                     parent_type=f.ftype.__name__,
                                     indicator_value=lbl)
                for lbl in self.labels]
        if self.track_invalid:
            cols.append(VectorColumnMetadata(
                parent_name=f.name, parent_type=f.ftype.__name__,
                indicator_value="OutOfBounds"))
        if self.track_nulls:
            cols.append(VectorColumnMetadata(
                parent_name=f.name, parent_type=f.ftype.__name__,
                indicator_value=NULL_INDICATOR))
        return VectorMetadata(self.output_name(), tuple(cols)).with_indices()

    def get_params(self):
        return {"splits": self.splits.tolist(), "track_nulls": self.track_nulls,
                "track_invalid": self.track_invalid, "labels": self.labels}


class NumericBucketizer(NumericBucketizerModel):
    """Public unsupervised bucketizer (it is already a pure transformer)."""


# ------------------------------------------------------------------ #
# supervised split search                                            #
# ------------------------------------------------------------------ #

def _best_split(x: np.ndarray, y: np.ndarray, classification: bool,
                min_leaf: int) -> Tuple[Optional[float], float]:
    """Best threshold by impurity decrease via one sorted prefix-sum scan.

    Returns (threshold, gain); threshold None when no valid split.
    Candidate thresholds are midpoints between distinct consecutive sorted
    values (Spark's tree uses binned candidates; exact scan is affordable
    for the single-feature case and removes binning error).
    """
    n = x.shape[0]
    if n < 2 * min_leaf:
        return None, 0.0
    order = np.argsort(x, kind="stable")
    xs, ys = x[order], y[order]
    # positions where a split is allowed: value changes AND both sides ≥ min_leaf
    change = xs[1:] != xs[:-1]
    pos = np.arange(1, n)
    ok = change & (pos >= min_leaf) & (n - pos >= min_leaf)
    if not ok.any():
        return None, 0.0
    if classification:
        classes, yi = np.unique(ys, return_inverse=True)
        k = len(classes)
        onehot = np.zeros((n, k), dtype=np.float64)
        onehot[np.arange(n), yi] = 1.0
        left = np.cumsum(onehot, axis=0)[:-1]         # (n-1, k) counts left of split i
        total = onehot.sum(axis=0)
        right = total[None, :] - left
        nl = pos.astype(np.float64)
        nr = (n - pos).astype(np.float64)
        gini_l = 1.0 - ((left / nl[:, None]) ** 2).sum(axis=1)
        gini_r = 1.0 - ((right / nr[:, None]) ** 2).sum(axis=1)
        p = onehot.sum(axis=0) / n
        parent = 1.0 - (p ** 2).sum()
        gain = parent - (nl / n) * gini_l - (nr / n) * gini_r
    else:
        s = np.cumsum(ys)[:-1]
        s2 = np.cumsum(ys ** 2)[:-1]
        st, s2t = ys.sum(), (ys ** 2).sum()
        nl = pos.astype(np.float64)
        nr = (n - pos).astype(np.float64)
        var_l = s2 / nl - (s / nl) ** 2
        var_r = (s2t - s2) / nr - ((st - s) / nr) ** 2
        parent = s2t / n - (st / n) ** 2
        gain = parent - (nl / n) * var_l - (nr / n) * var_r
    gain = np.where(ok, gain, -np.inf)
    i = int(np.argmax(gain))
    if not np.isfinite(gain[i]) or gain[i] <= 0:
        return None, 0.0
    # split index i puts xs[0..i] left and xs[i+1..] right
    return float((xs[i] + xs[i + 1]) / 2.0), float(gain[i])


def decision_tree_splits(x: np.ndarray, y: np.ndarray, classification: bool,
                         max_depth: int = 2, min_leaf: int = 1,
                         min_info_gain: float = 1e-6) -> List[float]:
    """Thresholds of a greedy depth-`max_depth` single-feature tree."""
    thresholds: List[float] = []

    def grow(idx: np.ndarray, depth: int) -> None:
        if depth >= max_depth or idx.size < 2 * min_leaf:
            return
        thr, gain = _best_split(x[idx], y[idx], classification, min_leaf)
        if thr is None or gain < min_info_gain:
            return
        thresholds.append(thr)
        grow(idx[x[idx] < thr], depth + 1)
        grow(idx[x[idx] >= thr], depth + 1)

    grow(np.arange(x.shape[0]), 0)
    return sorted(thresholds)


def _is_classification(y: np.ndarray, max_classes: int = 32) -> bool:
    u = np.unique(y)
    return u.size <= max_classes and np.allclose(u, np.round(u))


class DecisionTreeNumericBucketizer(Estimator):
    """(label, numeric) → one-hot of label-aware buckets; empty buckets (only
    the null indicator, if tracked) when no useful split exists."""

    in_types = (T.OPNumeric, T.OPNumeric)  # (response, numeric predictor)
    out_type = T.OPVector
    response_aware = True  # supervised: slot 0 is the label

    def __init__(self, max_depth: int = 2, min_info_gain: float = 1e-6,
                 min_instances_per_node: int = 1, track_nulls: bool = True,
                 uid: Optional[str] = None):
        super().__init__(uid=uid, max_depth=max_depth,
                         min_info_gain=min_info_gain,
                         min_instances_per_node=min_instances_per_node,
                         track_nulls=track_nulls)
        self.max_depth = max_depth
        self.min_info_gain = min_info_gain
        self.min_instances_per_node = min_instances_per_node
        self.track_nulls = track_nulls

    def _fit_splits(self, label: Column, num: Column) -> List[float]:
        y = np.asarray(label.data["value"], dtype=np.float64)
        x = np.asarray(num.data["value"], dtype=np.float64)
        m = (np.asarray(num.data["mask"]).astype(bool)
             & np.asarray(label.data["mask"]).astype(bool))
        if not m.any():
            return []
        x, y = x[m], y[m]
        return decision_tree_splits(
            x, y, _is_classification(y), self.max_depth,
            self.min_instances_per_node, self.min_info_gain)

    def fit_model(self, cols: Sequence[Column], ctx: FitContext) -> Transformer:
        thr = self._fit_splits(cols[0], cols[1])
        return DecisionTreeBucketizerModel(thr, track_nulls=self.track_nulls)


class DecisionTreeBucketizerModel(Transformer):
    """Fitted supervised bucketizer. Input wiring keeps (label, numeric); the
    label is ignored at transform time (may be absent when scoring)."""

    in_types = (T.OPNumeric, T.OPNumeric)
    out_type = T.OPVector
    response_aware = True  # wiring keeps (label, numeric) post-fit

    def __init__(self, thresholds: Sequence[float], track_nulls: bool = True,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.thresholds = list(thresholds)
        self.track_nulls = track_nulls
        if self.thresholds:
            splits = [-np.inf] + self.thresholds + [np.inf]
            # splits span ±inf so every present value is in-bounds;
            # track_invalid therefore adds no column here
            self._inner = NumericBucketizerModel(
                splits, track_nulls=False, track_invalid=False)
        else:
            self._inner = None

    @property
    def did_split(self) -> bool:
        return self._inner is not None

    def device_apply(self, enc, dev):
        d = dev[1]
        m = d["mask"].astype(bool)
        cols = []
        if self._inner is not None:
            cols.append(self._inner.device_apply(None, [d]))
        if self.track_nulls:
            cols.append((~m)[:, None].astype(jnp.float32))
        if not cols:
            return jnp.zeros((d["value"].shape[0], 0), jnp.float32)
        return jnp.concatenate(cols, axis=1)

    def output_meta(self) -> VectorMetadata:
        f = self.input_features[1]
        cols: List[VectorColumnMetadata] = []
        if self._inner is not None:
            for lbl in self._inner.labels:
                cols.append(VectorColumnMetadata(
                    parent_name=f.name, parent_type=f.ftype.__name__,
                    indicator_value=lbl))
        if self.track_nulls:
            cols.append(VectorColumnMetadata(
                parent_name=f.name, parent_type=f.ftype.__name__,
                indicator_value=NULL_INDICATOR))
        return VectorMetadata(self.output_name(), tuple(cols)).with_indices()

    def get_params(self):
        return {"thresholds": self.thresholds, "track_nulls": self.track_nulls}


class DecisionTreeNumericMapBucketizer(Estimator):
    """(label, numeric map) → concatenated label-aware buckets per map key
    (`DecisionTreeNumericMapBucketizer.scala`)."""

    in_types = (T.OPNumeric, T.OPMap)
    out_type = T.OPVector
    response_aware = True  # supervised: slot 0 is the label

    def __init__(self, max_depth: int = 2, min_info_gain: float = 1e-6,
                 track_nulls: bool = True, uid: Optional[str] = None):
        super().__init__(uid=uid, max_depth=max_depth,
                         min_info_gain=min_info_gain, track_nulls=track_nulls)
        self.max_depth = max_depth
        self.min_info_gain = min_info_gain
        self.track_nulls = track_nulls

    def fit_model(self, cols: Sequence[Column], ctx: FitContext) -> Transformer:
        label, mapped = cols
        y_all = np.asarray(label.data["value"], dtype=np.float64)
        ym = np.asarray(label.data["mask"]).astype(bool)
        keys = sorted({k for row in mapped.data for k in (row or {})})
        per_key = {}
        for k in keys:
            x = np.array([float(row[k]) if row and k in row and row[k] is not None
                          else np.nan for row in mapped.data])
            m = ~np.isnan(x) & ym
            thr: List[float] = []
            if m.any():
                thr = decision_tree_splits(
                    x[m], y_all[m], _is_classification(y_all[ym]),
                    self.max_depth, 1, self.min_info_gain)
            per_key[k] = thr
        return DecisionTreeMapBucketizerModel(per_key, self.track_nulls)


class DecisionTreeMapBucketizerModel(Transformer):
    in_types = (T.OPNumeric, T.OPMap)
    out_type = T.OPVector
    response_aware = True  # wiring keeps (label, map) post-fit
    jittable = False  # map input needs host-side key extraction

    def __init__(self, splits_by_key, track_nulls: bool = True,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.splits_by_key = dict(splits_by_key)
        self.track_nulls = track_nulls

    def host_prepare(self, cols):
        mapped = cols[1]
        out = {}
        for k in self.splits_by_key:
            x = np.array([float(row[k]) if row and k in row and row[k] is not None
                          else np.nan for row in mapped.data])
            out[k] = {"value": np.nan_to_num(x), "mask": ~np.isnan(x)}
        return out

    def device_apply(self, enc, dev):
        groups = []
        for k, thr in self.splits_by_key.items():
            d = enc[k]
            m = jnp.asarray(d["mask"])
            if thr:
                inner = NumericBucketizerModel(
                    [-np.inf] + list(thr) + [np.inf],
                    track_nulls=False, track_invalid=False)
                groups.append(inner.device_apply(None, [d]))
            if self.track_nulls:
                groups.append((~m)[:, None].astype(jnp.float32))
        if not groups:
            n = len(next(iter(enc.values()))["value"]) if enc else 0
            return jnp.zeros((n, 0), jnp.float32)
        return jnp.concatenate(groups, axis=1)

    def output_meta(self) -> VectorMetadata:
        f = self.input_features[1]
        cols: List[VectorColumnMetadata] = []
        for k, thr in self.splits_by_key.items():
            if thr:
                for lbl in _bucket_labels([-np.inf] + list(thr) + [np.inf]):
                    cols.append(VectorColumnMetadata(
                        parent_name=f.name, parent_type=f.ftype.__name__,
                        grouping=k, indicator_value=lbl))
            if self.track_nulls:
                cols.append(VectorColumnMetadata(
                    parent_name=f.name, parent_type=f.ftype.__name__,
                    grouping=k, indicator_value=NULL_INDICATOR))
        return VectorMetadata(self.output_name(), tuple(cols)).with_indices()

    def get_params(self):
        return {"splits_by_key": {k: list(v) for k, v in self.splits_by_key.items()},
                "track_nulls": self.track_nulls}
