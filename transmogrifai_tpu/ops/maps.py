"""Map-type vectorizers: each map key behaves like its scalar counterpart.

Reference parity: `core/.../feature/OPMapVectorizer.scala`,
`TextMapPivotVectorizer.scala`, `MultiPickListMapVectorizer.scala`,
`GeolocationMapVectorizer.scala`, `DateMapToUnitCircleVectorizer.scala`.

Fit discovers the key set (data-dependent → resolved on host at fit time,
sorted for determinism); transform is static-shape per-key encoding. A map
column explodes into `len(keys)` pseudo-columns whose metadata carries the
key in `grouping`.
"""

from __future__ import annotations

from collections import Counter
from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from transmogrifai_tpu import types as T
from transmogrifai_tpu.data.columns import Column
from transmogrifai_tpu.data.metadata import (
    NULL_INDICATOR, OTHER_INDICATOR, VectorColumnMetadata, VectorMetadata)
from transmogrifai_tpu.ops.categorical import top_k_levels
from transmogrifai_tpu.ops.dates import DEFAULT_PERIODS, _phase_fraction
from transmogrifai_tpu.stages.base import Estimator, FitContext, Transformer


def _discover_keys(col: Column, allow: Sequence[str] = (),
                   block: Sequence[str] = ()) -> List[str]:
    keys = set()
    for m in col.data:
        if m is not None:
            keys.update(m.keys())
    if allow:
        keys &= set(allow)
    keys -= set(block)
    return sorted(keys)


def _key_scalar(col: Column, key: str) -> Tuple[np.ndarray, np.ndarray]:
    """Extract one key of a numeric map → (value f64, mask f32)."""
    n = len(col.data)
    val = np.zeros(n, dtype=np.float64)
    mask = np.zeros(n, dtype=np.float32)
    for i, m in enumerate(col.data):
        if m is not None:
            v = m.get(key)
            if v is not None:
                val[i] = float(v)
                mask[i] = 1.0
    return val, mask


class NumericMapModel(Transformer):
    out_type = T.OPVector

    def __init__(self, keys_per_feature: Sequence[Sequence[str]],
                 fills: Sequence[Sequence[float]], track_nulls: bool = True,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.keys_per_feature = [list(k) for k in keys_per_feature]
        self.fills = [np.asarray(f, dtype=np.float32) for f in fills]
        self.track_nulls = track_nulls

    def host_prepare(self, cols):
        out = []
        for i, c in enumerate(cols):
            vals, masks = [], []
            for key in self.keys_per_feature[i]:
                v, m = _key_scalar(c, key)
                vals.append(v.astype(np.float32))
                masks.append(m)
            out.append({
                "value": np.stack(vals, 1) if vals else np.zeros((len(c.data), 0), np.float32),
                "mask": np.stack(masks, 1) if masks else np.zeros((len(c.data), 0), np.float32)})
        return out

    def device_apply(self, enc, dev):
        parts = []
        for i, e in enumerate(enc):
            v, m = jnp.asarray(e["value"]), jnp.asarray(e["mask"])
            filled = v * m + self.fills[i][None, :] * (1.0 - m)
            if self.track_nulls:
                # explicit width: reshape(n, -1) breaks on 0-row batches
                both = jnp.stack([filled, 1.0 - m], axis=2).reshape(
                    v.shape[0], 2 * v.shape[1])
                parts.append(both)
            else:
                parts.append(filled)
        return jnp.concatenate(parts, axis=1)

    def output_meta(self) -> VectorMetadata:
        cols: List[VectorColumnMetadata] = []
        for f, keys in zip(self.input_features, self.keys_per_feature):
            for k in keys:
                cols.append(VectorColumnMetadata(
                    parent_name=f.name, parent_type=f.ftype.__name__, grouping=k))
                if self.track_nulls:
                    cols.append(VectorColumnMetadata(
                        parent_name=f.name, parent_type=f.ftype.__name__,
                        grouping=k, indicator_value=NULL_INDICATOR))
        return VectorMetadata(self.output_name(), tuple(cols)).with_indices()

    def get_params(self):
        return {"keys_per_feature": self.keys_per_feature,
                "fills": [f.tolist() for f in self.fills],
                "track_nulls": self.track_nulls}


class NumericMapVectorizer(Estimator):
    """RealMap/IntegralMap/BinaryMap… → per-key impute + null indicator
    (OPMapVectorizer)."""

    in_types = (T.OPMap, Ellipsis)
    out_type = T.OPVector

    def __init__(self, fill_value: str = "mean", track_nulls: bool = True,
                 allow_keys: Sequence[str] = (), block_keys: Sequence[str] = (),
                 uid: Optional[str] = None):
        super().__init__(uid=uid, fill_value=fill_value, track_nulls=track_nulls,
                         allow_keys=list(allow_keys), block_keys=list(block_keys))
        self.fill_value = fill_value
        self.track_nulls = track_nulls
        self.allow_keys = tuple(allow_keys)
        self.block_keys = tuple(block_keys)

    def fit_model(self, cols: Sequence[Column], ctx: FitContext) -> Transformer:
        keys_pf, fills_pf = [], []
        for c in cols:
            keys = _discover_keys(c, self.allow_keys, self.block_keys)
            fills = []
            for k in keys:
                v, m = _key_scalar(c, k)
                if self.fill_value == "mean" and m.sum() > 0:
                    fills.append(float((v * m).sum() / m.sum()))
                else:
                    fills.append(0.0)
            keys_pf.append(keys)
            fills_pf.append(fills)
        return NumericMapModel(keys_pf, fills_pf, self.track_nulls)


class TextMapPivotModel(Transformer):
    out_type = T.OPVector

    def __init__(self, keys_per_feature: Sequence[Sequence[str]],
                 vocabs: Sequence[Dict[str, List[str]]], track_nulls: bool = True,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.keys_per_feature = [list(k) for k in keys_per_feature]
        self.vocabs = list(vocabs)
        self.track_nulls = track_nulls

    def host_prepare(self, cols):
        blocks = []
        for i, c in enumerate(cols):
            n = len(c.data)
            feat_blocks = []
            for key in self.keys_per_feature[i]:
                vocab = self.vocabs[i][key]
                lut = {s: j for j, s in enumerate(vocab)}
                k = len(vocab)
                width = k + 1 + (1 if self.track_nulls else 0)
                block = np.zeros((n, width), dtype=np.float32)
                for r, m in enumerate(c.data):
                    v = None if m is None else m.get(key)
                    if v is None:
                        if self.track_nulls:
                            block[r, k + 1] = 1.0
                    elif isinstance(v, (set, frozenset)):  # MultiPickListMap
                        for s in v:
                            block[r, lut.get(s, k)] = 1.0
                    else:
                        block[r, lut.get(v, k)] = 1.0
                feat_blocks.append(block)
            blocks.append(np.concatenate(feat_blocks, 1) if feat_blocks
                          else np.zeros((n, 0), np.float32))
        return blocks

    def device_apply(self, enc, dev):
        return jnp.concatenate([jnp.asarray(b) for b in enc], axis=1)

    def output_meta(self) -> VectorMetadata:
        cols: List[VectorColumnMetadata] = []
        for i, f in enumerate(self.input_features):
            for key in self.keys_per_feature[i]:
                for lvl in self.vocabs[i][key]:
                    cols.append(VectorColumnMetadata(
                        parent_name=f.name, parent_type=f.ftype.__name__,
                        grouping=key, indicator_value=lvl))
                cols.append(VectorColumnMetadata(
                    parent_name=f.name, parent_type=f.ftype.__name__,
                    grouping=key, indicator_value=OTHER_INDICATOR))
                if self.track_nulls:
                    cols.append(VectorColumnMetadata(
                        parent_name=f.name, parent_type=f.ftype.__name__,
                        grouping=key, indicator_value=NULL_INDICATOR))
        return VectorMetadata(self.output_name(), tuple(cols)).with_indices()

    def get_params(self):
        return {"keys_per_feature": self.keys_per_feature, "vocabs": self.vocabs,
                "track_nulls": self.track_nulls}


class TextMapPivotVectorizer(Estimator):
    """TextMap/PickListMap… → per-key top-K pivot
    (TextMapPivotVectorizer.scala)."""

    in_types = (T.OPMap, Ellipsis)
    out_type = T.OPVector

    def __init__(self, top_k: int = 20, min_support: int = 10,
                 track_nulls: bool = True, uid: Optional[str] = None):
        super().__init__(uid=uid, top_k=top_k, min_support=min_support,
                         track_nulls=track_nulls)
        self.top_k = top_k
        self.min_support = min_support
        self.track_nulls = track_nulls

    def fit_model(self, cols: Sequence[Column], ctx: FitContext) -> Transformer:
        keys_pf, vocabs_pf = [], []
        for c in cols:
            keys = _discover_keys(c)
            vocabs: Dict[str, List[str]] = {}
            for k in keys:
                counter: Counter = Counter()
                for m in c.data:
                    if m is not None:
                        v = m.get(k)
                        if v is None:
                            continue
                        if isinstance(v, (set, frozenset)):  # MultiPickListMap
                            counter.update(v)
                        else:
                            counter[v] += 1
                vocabs[k] = top_k_levels(counter, self.top_k, self.min_support)
            keys_pf.append(keys)
            vocabs_pf.append(vocabs)
        return TextMapPivotModel(keys_pf, vocabs_pf, self.track_nulls)


class GeolocationMapModel(Transformer):
    out_type = T.OPVector

    def __init__(self, keys_per_feature, fills, track_nulls: bool = True,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.keys_per_feature = [list(k) for k in keys_per_feature]
        self.fills = [np.asarray(f, dtype=np.float32) for f in fills]  # (K,3)
        self.track_nulls = track_nulls

    def host_prepare(self, cols):
        out = []
        for i, c in enumerate(cols):
            n = len(c.data)
            keys = self.keys_per_feature[i]
            vals = np.zeros((n, len(keys), 3), dtype=np.float32)
            mask = np.zeros((n, len(keys)), dtype=np.float32)
            for r, m in enumerate(c.data):
                if m is None:
                    continue
                for j, key in enumerate(keys):
                    v = m.get(key)
                    if v is not None:
                        vals[r, j] = v
                        mask[r, j] = 1.0
            out.append({"value": vals, "mask": mask})
        return out

    def device_apply(self, enc, dev):
        parts = []
        for i, e in enumerate(enc):
            v = jnp.asarray(e["value"])           # (n, K, 3)
            m = jnp.asarray(e["mask"])[:, :, None]  # (n, K, 1)
            filled = v * m + self.fills[i][None, :, :] * (1.0 - m)
            if self.track_nulls:
                block = jnp.concatenate([filled, 1.0 - m], axis=2)
            else:
                block = filled
            parts.append(block.reshape(v.shape[0], -1))
        return jnp.concatenate(parts, axis=1)

    def output_meta(self) -> VectorMetadata:
        cols: List[VectorColumnMetadata] = []
        for i, f in enumerate(self.input_features):
            for key in self.keys_per_feature[i]:
                for d in ("lat", "lon", "accuracy"):
                    cols.append(VectorColumnMetadata(
                        parent_name=f.name, parent_type=f.ftype.__name__,
                        grouping=key, descriptor_value=d))
                if self.track_nulls:
                    cols.append(VectorColumnMetadata(
                        parent_name=f.name, parent_type=f.ftype.__name__,
                        grouping=key, indicator_value=NULL_INDICATOR))
        return VectorMetadata(self.output_name(), tuple(cols)).with_indices()

    def get_params(self):
        return {"keys_per_feature": self.keys_per_feature,
                "fills": [f.tolist() for f in self.fills],
                "track_nulls": self.track_nulls}


class GeolocationMapVectorizer(Estimator):
    in_types = (T.GeolocationMap, Ellipsis)
    out_type = T.OPVector

    def __init__(self, track_nulls: bool = True, uid: Optional[str] = None):
        super().__init__(uid=uid, track_nulls=track_nulls)
        self.track_nulls = track_nulls

    def fit_model(self, cols: Sequence[Column], ctx: FitContext) -> Transformer:
        keys_pf, fills_pf = [], []
        for c in cols:
            keys = _discover_keys(c)
            sums = np.zeros((len(keys), 3), dtype=np.float64)
            counts = np.zeros(len(keys), dtype=np.float64)
            for m in c.data:
                if m is None:
                    continue
                for j, key in enumerate(keys):
                    v = m.get(key)
                    if v is not None:
                        sums[j] += v
                        counts[j] += 1
            fills = sums / np.maximum(counts, 1.0)[:, None]
            keys_pf.append(keys)
            fills_pf.append(fills)
        return GeolocationMapModel(keys_pf, fills_pf, self.track_nulls)


class DateMapVectorizer(Estimator):
    """DateMap → per-key unit-circle encodings
    (DateMapToUnitCircleVectorizer.scala)."""

    in_types = (T.DateMap, Ellipsis)
    out_type = T.OPVector

    def __init__(self, periods: Sequence[str] = DEFAULT_PERIODS,
                 uid: Optional[str] = None):
        super().__init__(uid=uid, periods=list(periods))
        self.periods = tuple(periods)

    def fit_model(self, cols: Sequence[Column], ctx: FitContext) -> Transformer:
        keys_pf = [_discover_keys(c) for c in cols]
        return DateMapModel(keys_pf, self.periods)


class DateMapModel(Transformer):
    out_type = T.OPVector

    def __init__(self, keys_per_feature, periods: Sequence[str] = DEFAULT_PERIODS,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.keys_per_feature = [list(k) for k in keys_per_feature]
        self.periods = tuple(periods)

    def host_prepare(self, cols):
        per_key = []
        n = len(cols[0].data) if cols else 0
        for i, c in enumerate(cols):
            for key in self.keys_per_feature[i]:
                val, mask = _key_scalar(c, key)
                ms = val.astype(np.int64)
                phases = np.stack(
                    [np.asarray(_phase_fraction(ms, p), dtype=np.float32)
                     for p in self.periods], axis=1)
                per_key.append({"phases": phases, "mask": mask})
        return {"n": np.zeros((n, 0), np.float32), "keys": per_key}

    def device_apply(self, enc, dev):
        parts = []
        for e in enc["keys"]:
            theta = 2.0 * jnp.pi * jnp.asarray(e["phases"])
            m = jnp.asarray(e["mask"])[:, None]
            sc = jnp.stack([jnp.sin(theta) * m, jnp.cos(theta) * m], axis=2)
            parts.append(sc.reshape(theta.shape[0], -1))
        if not parts:  # all keys filtered / all-null training data
            return jnp.asarray(enc["n"])
        return jnp.concatenate(parts, axis=1)

    def output_meta(self) -> VectorMetadata:
        cols: List[VectorColumnMetadata] = []
        for i, f in enumerate(self.input_features):
            for key in self.keys_per_feature[i]:
                for p in self.periods:
                    for fn in ("sin", "cos"):
                        cols.append(VectorColumnMetadata(
                            parent_name=f.name, parent_type=f.ftype.__name__,
                            grouping=key, descriptor_value=f"{p}_{fn}"))
        return VectorMetadata(self.output_name(), tuple(cols)).with_indices()

    def get_params(self):
        return {"keys_per_feature": self.keys_per_feature,
                "periods": list(self.periods)}


class SmartTextMapModel(Transformer):
    """Fitted per-(feature, key) strategy: pivot / hashed tokens / ignore."""

    out_type = T.OPVector

    def __init__(self, keys_per_feature: Sequence[Sequence[str]],
                 strategies: Sequence[Dict[str, str]],
                 vocabs: Sequence[Dict[str, List[str]]],
                 num_features: int = 512, track_nulls: bool = True,
                 seed: int = 42, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.keys_per_feature = [list(k) for k in keys_per_feature]
        self.strategies = list(strategies)
        self.vocabs = list(vocabs)
        self.num_features = num_features
        self.track_nulls = track_nulls
        self.seed = seed

    def _key_values(self, c: Column, key: str) -> np.ndarray:
        out = np.empty(len(c.data), dtype=object)
        for i, m in enumerate(c.data):
            out[i] = None if m is None else m.get(key)
        return out

    def host_prepare(self, cols):
        from transmogrifai_tpu.ops.categorical import one_hot_np, pivot_encode_ids
        from transmogrifai_tpu.ops.text import TokenHasher, _hash_counts
        blocks = []
        for i, c in enumerate(cols):
            n = len(c.data)
            feat_blocks = []
            for ki, key in enumerate(self.keys_per_feature[i]):
                values = self._key_values(c, key)
                strat = self.strategies[i][key]
                if strat == "pivot":
                    vocab = self.vocabs[i][key]
                    lut = {s: j for j, s in enumerate(vocab)}
                    block = one_hot_np(
                        pivot_encode_ids(values, lut, len(vocab)),
                        len(vocab), self.track_nulls)
                elif strat == "hash":
                    hasher = TokenHasher(self.num_features,
                                         self.seed + 31 * i + ki)
                    block = _hash_counts(values, hasher, False, False)
                    if self.track_nulls:
                        nulls = np.fromiter(
                            (1.0 if v is None else 0.0 for v in values),
                            dtype=np.float32, count=n)
                        block = np.concatenate([block, nulls[:, None]], 1)
                else:  # ignore: null indicator only
                    nulls = np.fromiter(
                        (1.0 if v is None else 0.0 for v in values),
                        dtype=np.float32, count=n)
                    block = nulls[:, None]
                feat_blocks.append(block)
            blocks.append(np.concatenate(feat_blocks, 1) if feat_blocks
                          else np.zeros((n, 0), np.float32))
        return blocks

    def device_apply(self, enc, dev):
        return jnp.concatenate([jnp.asarray(b) for b in enc], axis=1)

    def output_meta(self) -> VectorMetadata:
        cols: List[VectorColumnMetadata] = []
        for i, f in enumerate(self.input_features):
            for key in self.keys_per_feature[i]:
                strat = self.strategies[i][key]
                if strat == "pivot":
                    for lvl in self.vocabs[i][key]:
                        cols.append(VectorColumnMetadata(
                            parent_name=f.name, parent_type=f.ftype.__name__,
                            grouping=key, indicator_value=lvl))
                    cols.append(VectorColumnMetadata(
                        parent_name=f.name, parent_type=f.ftype.__name__,
                        grouping=key, indicator_value=OTHER_INDICATOR))
                    if self.track_nulls:
                        cols.append(VectorColumnMetadata(
                            parent_name=f.name, parent_type=f.ftype.__name__,
                            grouping=key, indicator_value=NULL_INDICATOR))
                elif strat == "hash":
                    for j in range(self.num_features):
                        cols.append(VectorColumnMetadata(
                            parent_name=f.name, parent_type=f.ftype.__name__,
                            grouping=key, descriptor_value=f"hash_{j}"))
                    if self.track_nulls:
                        cols.append(VectorColumnMetadata(
                            parent_name=f.name, parent_type=f.ftype.__name__,
                            grouping=key, indicator_value=NULL_INDICATOR))
                else:
                    cols.append(VectorColumnMetadata(
                        parent_name=f.name, parent_type=f.ftype.__name__,
                        grouping=key, indicator_value=NULL_INDICATOR))
        return VectorMetadata(self.output_name(), tuple(cols)).with_indices()

    def get_params(self):
        return {"keys_per_feature": self.keys_per_feature,
                "strategies": self.strategies, "vocabs": self.vocabs,
                "num_features": self.num_features,
                "track_nulls": self.track_nulls, "seed": self.seed}


class SmartTextMapVectorizer(Estimator):
    """TextMap/TextAreaMap → per-KEY cardinality stats choose pivot vs
    hashed tokens vs ignore (SmartTextMapVectorizer.scala — the map
    variant of SmartTextVectorizer; the transmogrify default for
    TextMap/TextAreaMap, Transmogrifier.scala:196-209)."""

    in_types = (T.OPMap, Ellipsis)
    out_type = T.OPVector

    def __init__(self, max_cardinality: int = 100, top_k: int = 20,
                 min_support: int = 10, num_features: int = 512,
                 id_detect_ratio: float = 0.99, track_nulls: bool = True,
                 seed: int = 42, uid: Optional[str] = None):
        super().__init__(
            uid=uid, max_cardinality=max_cardinality, top_k=top_k,
            min_support=min_support, num_features=num_features,
            id_detect_ratio=id_detect_ratio, track_nulls=track_nulls,
            seed=seed)
        self.max_cardinality = max_cardinality
        self.top_k = top_k
        self.min_support = min_support
        self.num_features = num_features
        self.id_detect_ratio = id_detect_ratio
        self.track_nulls = track_nulls
        self.seed = seed

    def fit_model(self, cols: Sequence[Column], ctx: FitContext) -> Transformer:
        keys_pf, strats_pf, vocabs_pf = [], [], []
        for c in cols:
            keys = _discover_keys(c)
            strategies: Dict[str, str] = {}
            vocabs: Dict[str, List[str]] = {}
            for k in keys:
                counter: Counter = Counter()
                for m in c.data:
                    v = None if m is None else m.get(k)
                    if v is not None:
                        counter[v] += 1
                n_values = sum(counter.values())
                n_distinct = len(counter)
                if n_distinct == 0:
                    strategies[k] = "ignore"
                    vocabs[k] = []
                elif n_distinct <= self.max_cardinality:
                    strategies[k] = "pivot"
                    vocabs[k] = top_k_levels(counter, self.top_k,
                                             self.min_support)
                elif n_values > 0 and \
                        n_distinct / n_values >= self.id_detect_ratio:
                    strategies[k] = "ignore"
                    vocabs[k] = []
                else:
                    strategies[k] = "hash"
                    vocabs[k] = []
            keys_pf.append(keys)
            strats_pf.append(strategies)
            vocabs_pf.append(vocabs)
        return SmartTextMapModel(keys_pf, strats_pf, vocabs_pf,
                                 self.num_features, self.track_nulls,
                                 self.seed)


class MultiPickListMapVectorizer(TextMapPivotVectorizer):
    """MultiPickListMap → per-key top-K multi-hot
    (MultiPickListMapVectorizer.scala). The pivot model already multi-hots
    set values; this named class carries the reference's stage identity and
    restricts input typing."""

    in_types = (T.MultiPickListMap, Ellipsis)


class PhoneMapModel(Transformer):
    out_type = T.OPVector

    def __init__(self, keys_per_feature: Sequence[Sequence[str]],
                 default_region: str = "US", track_nulls: bool = True,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.keys_per_feature = [list(k) for k in keys_per_feature]
        self.default_region = default_region
        self.track_nulls = track_nulls

    def host_prepare(self, cols):
        from transmogrifai_tpu.ops.enrich import phone_valid_block
        blocks = []
        for i, c in enumerate(cols):
            n = len(c.data)
            key_blocks = []
            for key in self.keys_per_feature[i]:
                values = [None if m is None else m.get(key) for m in c.data]
                key_blocks.append(phone_valid_block(
                    values, self.default_region, self.track_nulls))
            blocks.append(np.concatenate(key_blocks, 1) if key_blocks
                          else np.zeros((n, 0), np.float32))
        return blocks

    def device_apply(self, enc, dev):
        return jnp.concatenate([jnp.asarray(b) for b in enc], axis=1)

    def output_meta(self) -> VectorMetadata:
        cols: List[VectorColumnMetadata] = []
        for i, f in enumerate(self.input_features):
            for key in self.keys_per_feature[i]:
                cols.append(VectorColumnMetadata(
                    parent_name=f.name, parent_type=f.ftype.__name__,
                    grouping=key, indicator_value="IsValid"))
                if self.track_nulls:
                    cols.append(VectorColumnMetadata(
                        parent_name=f.name, parent_type=f.ftype.__name__,
                        grouping=key, indicator_value=NULL_INDICATOR))
        return VectorMetadata(self.output_name(), tuple(cols)).with_indices()

    def get_params(self):
        return {"keys_per_feature": self.keys_per_feature,
                "default_region": self.default_region,
                "track_nulls": self.track_nulls}


class PhoneMapVectorizer(Estimator):
    """PhoneMap → per-key validity vector (the transmogrify default for
    PhoneMap, Transmogrifier.scala:185-187)."""

    in_types = (T.PhoneMap, Ellipsis)
    out_type = T.OPVector

    def __init__(self, default_region: str = "US", track_nulls: bool = True,
                 uid: Optional[str] = None):
        super().__init__(uid=uid, default_region=default_region,
                         track_nulls=track_nulls)
        self.default_region = default_region
        self.track_nulls = track_nulls

    def fit_model(self, cols: Sequence[Column], ctx: FitContext) -> Transformer:
        return PhoneMapModel([_discover_keys(c) for c in cols],
                             self.default_region, self.track_nulls)


def map_vectorizers(features: Sequence, defaults) -> List:
    """Dispatch map-typed features to their vectorizers (transmogrify
    helper; per-type cases Transmogrifier.scala:140-273)."""
    numeric, pivot, smart, multi, phone, geo, date = ([], [], [], [], [],
                                                      [], [])
    for f in features:
        ft = f.ftype
        if issubclass(ft, T.Prediction):
            raise TypeError(
                f"transmogrify: refusing to vectorize Prediction feature "
                f"{f.name!r} — feeding model scores back in is usually "
                f"label leakage; extract explicit columns if intended")
        if issubclass(ft, T.GeolocationMap):
            geo.append(f)
        elif issubclass(ft, (T.DateMap,)):
            date.append(f)
        elif issubclass(ft, (T.RealMap, T.IntegralMap, T.BinaryMap)):
            numeric.append(f)
        elif issubclass(ft, T.PhoneMap):
            phone.append(f)
        elif issubclass(ft, T.MultiPickListMap):
            multi.append(f)
        elif issubclass(ft, (T.TextAreaMap,)) or ft in (T.TextMap,):
            # free-text maps → per-key smart strategies
            smart.append(f)
        elif issubclass(ft, T.TextMap):
            # Email/ID/URL/PickList/ComboBox/Base64/location maps → pivot
            pivot.append(f)
        else:
            raise TypeError(f"No map vectorizer for {ft.__name__} ({f.name})")
    out = []
    if numeric:
        out.append(NumericMapVectorizer(
            track_nulls=defaults.track_nulls).set_input(*numeric).get_output())
    if pivot:
        out.append(TextMapPivotVectorizer(
            top_k=defaults.top_k, min_support=defaults.min_support,
            track_nulls=defaults.track_nulls).set_input(*pivot).get_output())
    if smart:
        out.append(SmartTextMapVectorizer(
            max_cardinality=defaults.max_cardinality, top_k=defaults.top_k,
            min_support=defaults.min_support,
            num_features=defaults.num_hash_features,
            track_nulls=defaults.track_nulls).set_input(*smart).get_output())
    if multi:
        out.append(MultiPickListMapVectorizer(
            top_k=defaults.top_k, min_support=defaults.min_support,
            track_nulls=defaults.track_nulls).set_input(*multi).get_output())
    if phone:
        out.append(PhoneMapVectorizer(
            track_nulls=defaults.track_nulls).set_input(*phone).get_output())
    if geo:
        out.append(GeolocationMapVectorizer(
            track_nulls=defaults.track_nulls).set_input(*geo).get_output())
    if date:
        out.append(DateMapVectorizer(
            periods=defaults.circular_date_periods).set_input(*date).get_output())
    return out
