"""Scaling / imputation / calibration stages for scalar features.

Reference parity:
- `core/.../feature/OpScalarStandardScaler.scala` — z-normalization of a
  single numeric feature (Spark StandardScaler on a 1-d vector there; a
  masked mean/std reduction here).
- `core/.../feature/ScalerTransformer.scala` / `DescalerTransformer.scala`
  + `features/.../impl/feature/ScalingArgs.scala` — invertible scaling whose
  args travel with the stage so a descaler can undo it (the reference stores
  them in column metadata).
- `core/.../feature/FillMissingWithMean.scala` — Real → RealNN mean impute.
- `core/.../feature/PercentileCalibrator.scala` — maps a score to its
  percentile bucket [0, 99] via fitted quantiles (Spark QuantileDiscretizer
  there; a device-side searchsorted here).

TPU-first: fits are masked reductions over the sharded batch; transforms are
pure jnp maps that fuse into the downstream scoring program.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax.numpy as jnp
import numpy as np

from transmogrifai_tpu import types as T
from transmogrifai_tpu.data.columns import Column
from transmogrifai_tpu.stages.base import Estimator, FitContext, Transformer


def _masked_mean_std(value: np.ndarray, mask: np.ndarray):
    m = mask.astype(bool)
    n = max(int(m.sum()), 1)
    mean = float(np.where(m, value, 0.0).sum() / n)
    var = float((np.where(m, value - mean, 0.0) ** 2).sum() / n)
    return mean, float(np.sqrt(var))


class StandardScalerModel(Transformer):
    in_types = (T.OPNumeric,)
    out_type = T.RealNN

    def __init__(self, mean: float, std: float, with_mean: bool = True,
                 with_std: bool = True, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.mean, self.std = float(mean), float(std)
        self.with_mean, self.with_std = with_mean, with_std

    def device_apply(self, enc, dev):
        x, m = dev[0]["value"], dev[0]["mask"].astype(bool)
        v = jnp.where(m, x, self.mean)
        if self.with_mean:
            v = v - self.mean
        if self.with_std:
            v = v / (self.std if self.std > 0 else 1.0)
        return {"value": v, "mask": jnp.ones_like(m, dtype=bool)}

    def get_params(self):
        return {"mean": self.mean, "std": self.std,
                "with_mean": self.with_mean, "with_std": self.with_std}


class OpScalarStandardScaler(Estimator):
    """z-normalize one numeric feature (missing imputed with the mean)."""

    in_types = (T.OPNumeric,)
    out_type = T.RealNN

    def __init__(self, with_mean: bool = True, with_std: bool = True,
                 uid: Optional[str] = None):
        super().__init__(uid=uid, with_mean=with_mean, with_std=with_std)
        self.with_mean, self.with_std = with_mean, with_std

    def fit_model(self, cols: Sequence[Column], ctx: FitContext) -> Transformer:
        mean, std = _masked_mean_std(
            np.asarray(cols[0].data["value"], dtype=np.float64),
            np.asarray(cols[0].data["mask"]))
        return StandardScalerModel(mean, std, self.with_mean, self.with_std)


class FillMissingWithMeanModel(Transformer):
    in_types = (T.OPNumeric,)
    out_type = T.RealNN

    def __init__(self, fill: float, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.fill = float(fill)

    def device_apply(self, enc, dev):
        x, m = dev[0]["value"], dev[0]["mask"].astype(bool)
        return {"value": jnp.where(m, x, self.fill),
                "mask": jnp.ones_like(m, dtype=bool)}

    def get_params(self):
        return {"fill": self.fill}


class FillMissingWithMean(Estimator):
    """Real → RealNN: impute missing with the training mean (or `default`
    when the whole column is missing)."""

    in_types = (T.OPNumeric,)
    out_type = T.RealNN

    def __init__(self, default: float = 0.0, uid: Optional[str] = None):
        super().__init__(uid=uid, default=default)
        self.default = float(default)

    def fit_model(self, cols: Sequence[Column], ctx: FitContext) -> Transformer:
        v = np.asarray(cols[0].data["value"], dtype=np.float64)
        m = np.asarray(cols[0].data["mask"]).astype(bool)
        fill = float(v[m].mean()) if m.any() else self.default
        return FillMissingWithMeanModel(fill)


class ScalerTransformer(Transformer):
    """Invertible scaling of a Real feature: 'linear' (slope, intercept) or
    'log'. The args are stage params, so `DescalerTransformer` can invert by
    walking the parent feature's origin stage."""

    in_types = (T.Real,)
    out_type = T.Real

    def __init__(self, scaling_type: str = "linear", slope: float = 1.0,
                 intercept: float = 0.0, uid: Optional[str] = None):
        if scaling_type not in ("linear", "log"):
            raise ValueError(f"unknown scaling_type {scaling_type!r}")
        super().__init__(uid=uid, scaling_type=scaling_type, slope=slope,
                         intercept=intercept)
        self.scaling_type = scaling_type
        self.slope, self.intercept = float(slope), float(intercept)

    def device_apply(self, enc, dev):
        x, m = dev[0]["value"], dev[0]["mask"].astype(bool)
        if self.scaling_type == "linear":
            v = self.slope * x + self.intercept
        else:
            v = jnp.log(jnp.where(x > 0, x, jnp.nan))
            m = m & jnp.isfinite(v)
            v = jnp.where(m, v, 0.0)
        return {"value": v, "mask": m}

    def invert(self, value, mask):
        if self.scaling_type == "linear":
            slope = self.slope if self.slope != 0 else 1.0
            return (value - self.intercept) / slope, mask
        return jnp.exp(value), mask


class DescalerTransformer(Transformer):
    """(scaled value, scaled feature) → Real: applies the inverse of the
    ScalerTransformer that produced input 2 to input 1 (the reference reads
    the scaler args from metadata — `DescalerTransformer.scala`)."""

    in_types = (T.Real, T.Real)
    out_type = T.Real

    def _scaler(self) -> ScalerTransformer:
        origin = self.input_features[1].origin_stage
        if not isinstance(origin, ScalerTransformer):
            raise TypeError(
                "DescalerTransformer input 2 must be produced by a "
                f"ScalerTransformer; got {type(origin).__name__}")
        return origin

    def device_apply(self, enc, dev):
        x, m = dev[0]["value"], dev[0]["mask"].astype(bool)
        v, m = self._scaler().invert(x, m)
        return {"value": v, "mask": m}


class PercentileCalibratorModel(Transformer):
    in_types = (T.OPNumeric,)
    out_type = T.RealNN

    def __init__(self, quantiles: Sequence[float], uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.quantiles = np.asarray(quantiles, dtype=np.float64)

    def device_apply(self, enc, dev):
        x, m = dev[0]["value"], dev[0]["mask"].astype(bool)
        q = jnp.asarray(self.quantiles)
        buckets = jnp.searchsorted(q, x, side="right").astype(jnp.float32)
        hi = float(len(self.quantiles))
        v = jnp.clip(buckets * (99.0 / max(hi, 1.0)), 0.0, 99.0)
        return {"value": jnp.where(m, jnp.round(v), 0.0), "mask": m}

    def get_params(self):
        return {"quantiles": self.quantiles.tolist()}


class PercentileCalibrator(Estimator):
    """RealNN score → percentile bucket in [0, 99] via fitted quantiles."""

    in_types = (T.OPNumeric,)
    out_type = T.RealNN

    def __init__(self, buckets: int = 100, uid: Optional[str] = None):
        super().__init__(uid=uid, buckets=buckets)
        self.buckets = int(buckets)

    def fit_model(self, cols: Sequence[Column], ctx: FitContext) -> Transformer:
        v = np.asarray(cols[0].data["value"], dtype=np.float64)
        m = np.asarray(cols[0].data["mask"]).astype(bool)
        vals = v[m]
        if vals.size == 0:
            return PercentileCalibratorModel([0.0])
        qs = np.quantile(vals, np.linspace(0, 1, self.buckets + 1)[1:-1])
        return PercentileCalibratorModel(np.unique(qs))
