"""Categorical pivot (one-hot) vectorizers.

Reference parity: `core/.../feature/OpOneHotVectorizer.scala` /
`OpSetVectorizer` — top-K pivot with OTHER and null-indicator columns,
defaults TopK=20, MinSupport=10 (`Transmogrifier.scala:52-90`).

TPU-first: the vocabulary (data-dependent) is resolved at fit time on host;
the transform is a static-shape `one_hot` over integer ids — host_prepare
maps strings → ids with a dict lookup, device_apply builds the dense pivot
so XLA fuses it with the downstream combine/model matmul.
"""

from __future__ import annotations

from collections import Counter
from typing import Dict, List, Optional, Sequence, Tuple

import jax.nn
import jax.numpy as jnp
import numpy as np

from transmogrifai_tpu import types as T
from transmogrifai_tpu.data.columns import Column
from transmogrifai_tpu.data.metadata import (
    NULL_INDICATOR, OTHER_INDICATOR, VectorColumnMetadata, VectorMetadata)
from transmogrifai_tpu.stages.base import Estimator, FitContext, Transformer


def top_k_levels(counter: Counter, top_k: int, min_support: int) -> List[str]:
    """Most frequent levels, count-desc then lexicographic for determinism."""
    eligible = [(c, lvl) for lvl, c in counter.items() if c >= min_support]
    eligible.sort(key=lambda t: (-t[0], t[1]))
    return [lvl for _, lvl in eligible[:top_k]]


def pivot_encode_ids(values, lut: Dict[str, int], k: int) -> np.ndarray:
    """Map level strings → ids with OTHER=k, NULL=k+1 (shared by OneHotModel
    and SmartTextModel so the two pivot encodings cannot drift).

    Vectorized: id-map each UNIQUE level once, then gather — categorical
    columns are overwhelmingly duplicated, so this replaces n dict lookups
    with |levels| lookups + one unique/take (VERDICT r1 weak#5)."""
    n = len(values)
    arr = np.asarray(values, dtype=object)
    # None and float NaN are both missing → NULL id (pd.factorize would
    # otherwise code NaN as -1, which fancy-indexes the LAST level)
    mask = np.fromiter((v is not None and v == v for v in arr),
                       dtype=bool, count=n)
    out = np.full(n, k + 1, dtype=np.int32)  # NULL id
    present = arr[mask]
    if present.size:
        try:
            # hash-based factorize: no sort, no stringification — levels
            # keep their python identity for the lut lookup
            import pandas as pd
            inv, uniq = pd.factorize(present)
            ids = np.fromiter((lut.get(u, k) for u in uniq), np.int32,
                              len(uniq))
            out[mask] = ids[inv]
        except Exception:  # unhashable levels etc: direct per-row path
            out[mask] = np.fromiter((lut.get(v, k) for v in present),
                                    np.int32, present.size)
    return out


def one_hot_np(ids: np.ndarray, k: int, track_nulls: bool) -> np.ndarray:
    """Host-side dense pivot block: k levels + OTHER (+ NULL if tracked)."""
    block = np.zeros((len(ids), k + 2), dtype=np.float32)
    block[np.arange(len(ids)), ids] = 1.0
    return block if track_nulls else block[:, : k + 1]


class OneHotModel(Transformer):
    """Fitted pivot: per feature K level columns + OTHER + null indicator."""

    out_type = T.OPVector

    def __init__(self, vocabs: Sequence[Sequence[str]], track_nulls: bool = True,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.vocabs = [list(v) for v in vocabs]
        self.track_nulls = track_nulls
        self._lookups = [
            {lvl: i for i, lvl in enumerate(v)} for v in self.vocabs]

    def _widths(self) -> List[int]:
        return [len(v) + 1 + (1 if self.track_nulls else 0) for v in self.vocabs]

    def host_prepare(self, cols: Sequence[Optional[Column]]):
        return [
            pivot_encode_ids(c.data, self._lookups[i], len(self.vocabs[i]))
            for i, c in enumerate(cols)
        ]

    def device_apply(self, enc, dev):
        outs = []
        for i, ids in enumerate(enc):
            k = len(self.vocabs[i])
            n_classes = k + 2  # levels + OTHER + NULL
            oh = jax.nn.one_hot(ids, n_classes, dtype=jnp.float32)
            if not self.track_nulls:
                oh = oh[:, : k + 1]
            outs.append(oh)
        return jnp.concatenate(outs, axis=1) if outs else jnp.zeros((0, 0))

    def output_meta(self) -> VectorMetadata:
        cols: List[VectorColumnMetadata] = []
        for f, vocab in zip(self.input_features, self.vocabs):
            for lvl in vocab:
                cols.append(VectorColumnMetadata(
                    parent_name=f.name, parent_type=f.ftype.__name__,
                    grouping=f.name, indicator_value=lvl))
            cols.append(VectorColumnMetadata(
                parent_name=f.name, parent_type=f.ftype.__name__,
                grouping=f.name, indicator_value=OTHER_INDICATOR))
            if self.track_nulls:
                cols.append(VectorColumnMetadata(
                    parent_name=f.name, parent_type=f.ftype.__name__,
                    grouping=f.name, indicator_value=NULL_INDICATOR))
        return VectorMetadata(self.output_name(), tuple(cols)).with_indices()

    def get_params(self):
        return {"vocabs": self.vocabs, "track_nulls": self.track_nulls}


class OneHotVectorizer(Estimator):
    """N categorical text features → top-K pivot each (OpSetVectorizer)."""

    in_types = (T.Text, Ellipsis)
    out_type = T.OPVector

    def __init__(self, top_k: int = 20, min_support: int = 10,
                 track_nulls: bool = True, uid: Optional[str] = None):
        super().__init__(uid=uid, top_k=top_k, min_support=min_support,
                         track_nulls=track_nulls)
        self.top_k = top_k
        self.min_support = min_support
        self.track_nulls = track_nulls

    def fit_model(self, cols: Sequence[Column], ctx: FitContext) -> Transformer:
        vocabs = []
        for c in cols:
            counter = Counter(s for s in c.data if s is not None)
            vocabs.append(top_k_levels(counter, self.top_k, self.min_support))
        return OneHotModel(vocabs, self.track_nulls)


class MultiPickListModel(Transformer):
    """Fitted multi-hot pivot for set-valued categoricals."""

    out_type = T.OPVector

    def __init__(self, vocabs: Sequence[Sequence[str]], track_nulls: bool = True,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.vocabs = [list(v) for v in vocabs]
        self.track_nulls = track_nulls
        self._lookups = [{lvl: i for i, lvl in enumerate(v)} for v in self.vocabs]

    def host_prepare(self, cols: Sequence[Optional[Column]]):
        outs = []
        for i, c in enumerate(cols):
            lut, k = self._lookups[i], len(self.vocabs[i])
            width = k + 1 + (1 if self.track_nulls else 0)
            arr = np.zeros((len(c.data), width), dtype=np.float32)
            for r, val in enumerate(c.data):
                if val is None:
                    if self.track_nulls:
                        arr[r, k + 1] = 1.0
                    continue
                for s in val:
                    j = lut.get(s)
                    if j is None:
                        arr[r, k] = 1.0  # OTHER
                    else:
                        arr[r, j] = 1.0
            outs.append(arr)
        return outs

    def device_apply(self, enc, dev):
        return jnp.concatenate([jnp.asarray(a) for a in enc], axis=1)

    def output_meta(self) -> VectorMetadata:
        cols: List[VectorColumnMetadata] = []
        for f, vocab in zip(self.input_features, self.vocabs):
            for lvl in vocab:
                cols.append(VectorColumnMetadata(
                    parent_name=f.name, parent_type=f.ftype.__name__,
                    grouping=f.name, indicator_value=lvl))
            cols.append(VectorColumnMetadata(
                parent_name=f.name, parent_type=f.ftype.__name__,
                grouping=f.name, indicator_value=OTHER_INDICATOR))
            if self.track_nulls:
                cols.append(VectorColumnMetadata(
                    parent_name=f.name, parent_type=f.ftype.__name__,
                    grouping=f.name, indicator_value=NULL_INDICATOR))
        return VectorMetadata(self.output_name(), tuple(cols)).with_indices()

    def get_params(self):
        return {"vocabs": self.vocabs, "track_nulls": self.track_nulls}


class MultiPickListVectorizer(Estimator):
    """N MultiPickList features → top-K multi-hot each."""

    in_types = (T.MultiPickList, Ellipsis)
    out_type = T.OPVector

    def __init__(self, top_k: int = 20, min_support: int = 10,
                 track_nulls: bool = True, uid: Optional[str] = None):
        super().__init__(uid=uid, top_k=top_k, min_support=min_support,
                         track_nulls=track_nulls)
        self.top_k = top_k
        self.min_support = min_support
        self.track_nulls = track_nulls

    def fit_model(self, cols: Sequence[Column], ctx: FitContext) -> Transformer:
        vocabs = []
        for c in cols:
            counter: Counter = Counter()
            for val in c.data:
                if val is not None:
                    counter.update(val)
            vocabs.append(top_k_levels(counter, self.top_k, self.min_support))
        return MultiPickListModel(vocabs, self.track_nulls)
