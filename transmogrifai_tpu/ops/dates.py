"""Date/time vectorization: circular (unit-circle) encodings.

Reference parity: `core/.../feature/DateToUnitCircleTransformer.scala` and
the transmogrify defaults `CircularDateRepresentations = HourOfDay,
DayOfWeek, DayOfMonth, DayOfYear` (`Transmogrifier.scala:81`).

TPU-first: calendar math runs on host over int64 epoch-millis (float32
cannot hold epoch-ms precision), producing small phase fractions; the
device side is just sin/cos — fully fusable. Missing dates map to the
origin (0, 0), which no valid point on the unit circle can hit.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from transmogrifai_tpu import types as T
from transmogrifai_tpu.data.columns import Column
from transmogrifai_tpu.data.metadata import VectorColumnMetadata, VectorMetadata
from transmogrifai_tpu.stages.base import Transformer

DEFAULT_PERIODS = ("HourOfDay", "DayOfWeek", "DayOfMonth", "DayOfYear")

_MS_PER_DAY = 86_400_000
_MS_PER_HOUR = 3_600_000


def _phase_fraction(ms: np.ndarray, period: str) -> np.ndarray:
    """Fraction in [0, 1) of the given calendar period (host, int64-exact)."""
    if period == "HourOfDay":
        return (ms % _MS_PER_DAY) / _MS_PER_DAY
    if period == "DayOfWeek":
        day = ms // _MS_PER_DAY
        # 1970-01-01 was a Thursday; ISO Monday=0 → offset 3
        dow = (day + 3) % 7
        return dow / 7.0
    days = (ms // _MS_PER_DAY).astype("datetime64[D]")
    if period == "DayOfMonth":
        month_start = days.astype("datetime64[M]")
        dom = (days - month_start).astype(np.int64)  # 0-based day of month
        return dom / 31.0
    if period == "DayOfYear":
        year_start = days.astype("datetime64[Y]")
        doy = (days - year_start).astype(np.int64)
        return doy / 366.0
    if period == "MonthOfYear":
        months = days.astype("datetime64[M]").astype(np.int64)
        return (months % 12) / 12.0
    if period == "WeekOfYear":
        year_start = days.astype("datetime64[Y]")
        doy = (days - year_start).astype(np.int64)
        return (doy // 7) / 53.0
    raise ValueError(f"Unknown time period {period!r}")


class DateToUnitCircleVectorizer(Transformer):
    """N Date features → [sin, cos] per period per feature (stateless)."""

    in_types = (T.Date, Ellipsis)
    out_type = T.OPVector

    def __init__(self, periods: Sequence[str] = DEFAULT_PERIODS,
                 uid: Optional[str] = None):
        super().__init__(uid=uid, periods=list(periods))
        self.periods = tuple(periods)

    def host_prepare(self, cols: Sequence[Optional[Column]]):
        out = []
        for c in cols:
            ms = np.asarray(c.data["value"], dtype=np.int64)
            mask = np.asarray(c.data["mask"], dtype=np.float32)
            phases = np.stack(
                [np.asarray(_phase_fraction(ms, p), dtype=np.float32)
                 for p in self.periods], axis=1)
            out.append({"phases": phases, "mask": mask})
        return out

    def device_apply(self, enc, dev):
        parts = []
        for e in enc:
            theta = 2.0 * jnp.pi * jnp.asarray(e["phases"])
            m = jnp.asarray(e["mask"])[:, None]
            parts.append(jnp.sin(theta) * m)
            parts.append(jnp.cos(theta) * m)
        # interleave sin/cos per feature: [sin_p0, cos_p0, sin_p1, ...]
        stacked = []
        for i in range(0, len(parts), 2):
            s, c = parts[i], parts[i + 1]
            inter = jnp.stack([s, c], axis=2).reshape(s.shape[0], -1)
            stacked.append(inter)
        return jnp.concatenate(stacked, axis=1)

    def output_meta(self) -> VectorMetadata:
        cols: List[VectorColumnMetadata] = []
        for f in self.input_features:
            for p in self.periods:
                for fn in ("sin", "cos"):
                    cols.append(VectorColumnMetadata(
                        parent_name=f.name, parent_type=f.ftype.__name__,
                        descriptor_value=f"{p}_{fn}"))
        return VectorMetadata(self.output_name(), tuple(cols)).with_indices()
