"""Date/time vectorization: circular (unit-circle) encodings.

Reference parity: `core/.../feature/DateToUnitCircleTransformer.scala` and
the transmogrify defaults `CircularDateRepresentations = HourOfDay,
DayOfWeek, DayOfMonth, DayOfYear` (`Transmogrifier.scala:81`).

TPU-first: calendar math runs on host over int64 epoch-millis (float32
cannot hold epoch-ms precision), producing small phase fractions; the
device side is just sin/cos — fully fusable. Missing dates map to the
origin (0, 0), which no valid point on the unit circle can hit.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from transmogrifai_tpu import types as T
from transmogrifai_tpu.data.columns import Column
from transmogrifai_tpu.data.metadata import VectorColumnMetadata, VectorMetadata
from transmogrifai_tpu.stages.base import Transformer

DEFAULT_PERIODS = ("HourOfDay", "DayOfWeek", "DayOfMonth", "DayOfYear")

_MS_PER_DAY = 86_400_000
_MS_PER_HOUR = 3_600_000


def _phase_fraction(ms: np.ndarray, period: str) -> np.ndarray:
    """Fraction in [0, 1) of the given calendar period (host, int64-exact)."""
    if period == "HourOfDay":
        return (ms % _MS_PER_DAY) / _MS_PER_DAY
    if period == "DayOfWeek":
        day = ms // _MS_PER_DAY
        # 1970-01-01 was a Thursday; ISO Monday=0 → offset 3
        dow = (day + 3) % 7
        return dow / 7.0
    days = (ms // _MS_PER_DAY).astype("datetime64[D]")
    if period == "DayOfMonth":
        month_start = days.astype("datetime64[M]")
        dom = (days - month_start).astype(np.int64)  # 0-based day of month
        return dom / 31.0
    if period == "DayOfYear":
        year_start = days.astype("datetime64[Y]")
        doy = (days - year_start).astype(np.int64)
        return doy / 366.0
    if period == "MonthOfYear":
        months = days.astype("datetime64[M]").astype(np.int64)
        return (months % 12) / 12.0
    if period == "WeekOfYear":
        year_start = days.astype("datetime64[Y]")
        doy = (days - year_start).astype(np.int64)
        return (doy // 7) / 53.0
    raise ValueError(f"Unknown time period {period!r}")


class DateToUnitCircleVectorizer(Transformer):
    """N Date features → [sin, cos] per period per feature (stateless)."""

    in_types = (T.Date, Ellipsis)
    out_type = T.OPVector

    def __init__(self, periods: Sequence[str] = DEFAULT_PERIODS,
                 uid: Optional[str] = None):
        super().__init__(uid=uid, periods=list(periods))
        self.periods = tuple(periods)

    def host_prepare(self, cols: Sequence[Optional[Column]]):
        out = []
        for c in cols:
            ms = np.asarray(c.data["value"], dtype=np.int64)
            mask = np.asarray(c.data["mask"], dtype=np.float32)
            phases = np.stack(
                [np.asarray(_phase_fraction(ms, p), dtype=np.float32)
                 for p in self.periods], axis=1)
            out.append({"phases": phases, "mask": mask})
        return out

    def device_apply(self, enc, dev):
        parts = []
        for e in enc:
            theta = 2.0 * jnp.pi * jnp.asarray(e["phases"])
            m = jnp.asarray(e["mask"])[:, None]
            parts.append(jnp.sin(theta) * m)
            parts.append(jnp.cos(theta) * m)
        # interleave sin/cos per feature: [sin_p0, cos_p0, sin_p1, ...]
        stacked = []
        for i in range(0, len(parts), 2):
            s, c = parts[i], parts[i + 1]
            # explicit width: reshape(n, -1) breaks on 0-row batches
            inter = jnp.stack([s, c], axis=2).reshape(
                s.shape[0], 2 * s.shape[1])
            stacked.append(inter)
        return jnp.concatenate(stacked, axis=1)

    def output_meta(self) -> VectorMetadata:
        cols: List[VectorColumnMetadata] = []
        for f in self.input_features:
            for p in self.periods:
                for fn in ("sin", "cos"):
                    cols.append(VectorColumnMetadata(
                        parent_name=f.name, parent_type=f.ftype.__name__,
                        descriptor_value=f"{p}_{fn}"))
        return VectorMetadata(self.output_name(), tuple(cols)).with_indices()


# --------------------------------------------------------------------- #
# calendar-unit extraction + date-list pivots                           #
# --------------------------------------------------------------------- #

TIME_PERIODS = ("DayOfMonth", "DayOfWeek", "DayOfYear", "HourOfDay",
                "MonthOfYear", "WeekOfMonth", "WeekOfYear")


def time_period_value(ms: np.ndarray, period: str) -> np.ndarray:
    """Integral calendar unit per reference `TimePeriod.scala` (1-based
    days/months, 0-based hours/weeks)."""
    day = ms // _MS_PER_DAY
    days = day.astype("datetime64[D]")
    if period == "HourOfDay":
        return (ms % _MS_PER_DAY) // _MS_PER_HOUR
    if period == "DayOfWeek":
        return (day + 3) % 7 + 1  # Monday=1..Sunday=7 (ISO)
    if period == "DayOfMonth":
        return (days - days.astype("datetime64[M]")).astype(np.int64) + 1
    if period == "DayOfYear":
        return (days - days.astype("datetime64[Y]")).astype(np.int64) + 1
    if period == "MonthOfYear":
        return days.astype("datetime64[M]").astype(np.int64) % 12 + 1
    if period == "WeekOfMonth":
        dom = (days - days.astype("datetime64[M]")).astype(np.int64)
        return dom // 7
    if period == "WeekOfYear":
        doy = (days - days.astype("datetime64[Y]")).astype(np.int64)
        return doy // 7
    raise ValueError(f"Unknown time period {period!r}")


class TimePeriodTransformer(Transformer):
    """Date → Integral calendar unit (`TimePeriodTransformer.scala`).

    Host-path stage: ALL the work is datetime64 calendar math in
    host_prepare (device_apply just forwards the encoding), and reading a
    device-kind (Date/scalar) input from host_prepare violates the
    compiled scorer's contract for jittable stages — inside a fused plan
    the column may be None. jittable=False keeps it in host segments
    where inputs are always materialized."""

    in_types = (T.Date,)
    out_type = T.Integral
    jittable = False

    def __init__(self, period: str = "DayOfWeek", uid: Optional[str] = None):
        if period not in TIME_PERIODS:
            raise ValueError(f"period must be one of {TIME_PERIODS}")
        super().__init__(uid=uid, period=period)
        self.period = period

    def host_prepare(self, cols):
        ms = np.asarray(cols[0].data["value"], dtype=np.int64)
        mask = np.asarray(cols[0].data["mask"]).astype(bool)
        vals = time_period_value(ms, self.period).astype(np.float64)
        return {"value": np.where(mask, vals, 0.0), "mask": mask}

    def device_apply(self, enc, dev):
        return enc


class TimePeriodListTransformer(Transformer):
    """DateList → TextList-like integral list is host-only in the reference;
    here we map each date list to its calendar units (host kind output)."""

    in_types = (T.DateList,)
    out_type = T.TextList
    jittable = False

    def __init__(self, period: str = "DayOfWeek", uid: Optional[str] = None):
        super().__init__(uid=uid, period=period)
        self.period = period

    def transform(self, cols, ctx=None):
        out = np.empty(len(cols[0].data), dtype=object)
        for i, lst in enumerate(cols[0].data):
            if not lst:
                out[i] = []
            else:
                ms = np.asarray(list(lst), dtype=np.int64)
                out[i] = [str(int(v)) for v in time_period_value(ms, self.period)]
        return Column(T.TextList, out)


DATE_LIST_PIVOTS = ("SinceFirst", "SinceLast", "ModeDay", "ModeMonth", "ModeHour")


class DateListVectorizer(Transformer):
    """N DateList features → OPVector per the reference's DateListPivot modes
    (`core/.../feature/DateListVectorizer.scala`):

    - SinceFirst/SinceLast: days between reference date and first/last event
      (+ null indicator).
    - ModeDay/ModeMonth/ModeHour: one-hot of the modal day-of-week / month /
      hour across the list.
    """

    in_types = (T.DateList, Ellipsis)
    out_type = T.OPVector
    jittable = False  # list input needs host extraction

    def __init__(self, pivot: str = "SinceLast",
                 reference_ms: Optional[int] = None,
                 track_nulls: bool = True, uid: Optional[str] = None):
        if pivot not in DATE_LIST_PIVOTS:
            raise ValueError(f"pivot must be one of {DATE_LIST_PIVOTS}")
        super().__init__(uid=uid, pivot=pivot, reference_ms=reference_ms,
                         track_nulls=track_nulls)
        self.pivot = pivot
        self.reference_ms = reference_ms
        self.track_nulls = track_nulls

    def _pivot_widths(self):
        return {"ModeDay": 7, "ModeMonth": 12, "ModeHour": 24}.get(self.pivot)

    def host_prepare(self, cols):
        out = []
        period = {"ModeDay": "DayOfWeek", "ModeMonth": "MonthOfYear",
                  "ModeHour": "HourOfDay"}.get(self.pivot)
        for c in cols:
            n = len(c.data)
            if period is None:  # SinceFirst / SinceLast
                val = np.zeros(n, dtype=np.float32)
                mask = np.zeros(n, dtype=np.float32)
                ref = self.reference_ms
                if ref is None:
                    # default reference = latest event in the batch (the
                    # reference uses "now"; a data-derived instant keeps the
                    # transform deterministic)
                    batch_max = max((max(lst) for lst in c.data if lst),
                                    default=0)
                    ref = batch_max
                for i, lst in enumerate(c.data):
                    if lst:
                        pick = min(lst) if self.pivot == "SinceFirst" else max(lst)
                        val[i] = (ref - pick) / _MS_PER_DAY
                        mask[i] = 1.0
                out.append({"value": val, "mask": mask})
            else:
                w = self._pivot_widths()
                oh = np.zeros((n, w), dtype=np.float32)
                mask = np.zeros(n, dtype=np.float32)
                base = 1 if period != "HourOfDay" else 0
                for i, lst in enumerate(c.data):
                    if lst:
                        units = time_period_value(
                            np.asarray(list(lst), dtype=np.int64), period) - base
                        counts = np.bincount(units.astype(np.int64), minlength=w)[:w]
                        oh[i, int(np.argmax(counts))] = 1.0
                        mask[i] = 1.0
                out.append({"onehot": oh, "mask": mask})
        return out

    def device_apply(self, enc, dev):
        parts = []
        for e in enc:
            if "onehot" in e:
                parts.append(jnp.asarray(e["onehot"]))
            else:
                parts.append(jnp.asarray(e["value"])[:, None])
            if self.track_nulls:
                parts.append(1.0 - jnp.asarray(e["mask"])[:, None])
        return jnp.concatenate(parts, axis=1)

    def transform(self, cols, ctx=None):
        enc = self.host_prepare(cols)
        return self._wrap(self.device_apply(enc, None))

    def output_meta(self) -> VectorMetadata:
        from transmogrifai_tpu.data.metadata import NULL_INDICATOR
        cols: List[VectorColumnMetadata] = []
        w = self._pivot_widths()
        for f in self.input_features:
            if w is None:
                cols.append(VectorColumnMetadata(
                    parent_name=f.name, parent_type=f.ftype.__name__,
                    descriptor_value=self.pivot))
            else:
                for j in range(w):
                    cols.append(VectorColumnMetadata(
                        parent_name=f.name, parent_type=f.ftype.__name__,
                        indicator_value=f"{self.pivot}_{j}"))
            if self.track_nulls:
                cols.append(VectorColumnMetadata(
                    parent_name=f.name, parent_type=f.ftype.__name__,
                    indicator_value=NULL_INDICATOR))
        return VectorMetadata(self.output_name(), tuple(cols)).with_indices()
