"""DropIndicesByTransformer: drop vector columns by metadata predicate.

Reference parity: `core/.../feature/DropIndicesByTransformer.scala` —
`vector.dropIndicesBy(_.isNullIndicator)` style pruning driven by
`OpVectorColumnMetadata`. The predicate receives each column's
VectorColumnMetadata; matched columns are removed. Fitted form is a static
column gather (same device shape as SanityCheckerModel)."""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from transmogrifai_tpu import types as T
from transmogrifai_tpu.data.columns import Column
from transmogrifai_tpu.data.metadata import VectorMetadata
from transmogrifai_tpu.stages.base import FitContext, Transformer
from transmogrifai_tpu.utils.fnser import decode_fn, encode_fn


class DropIndicesByTransformer(Transformer):
    """OPVector → OPVector minus the columns whose metadata matches
    `predicate`. Indices resolve lazily from the input metadata on first
    use (the metadata is static per fitted DAG, so the gather is static)."""

    in_types = (T.OPVector,)
    out_type = T.OPVector

    def __init__(self, predicate: Callable, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.predicate = decode_fn(predicate)
        self._indices = None
        self._meta = None

    def _resolve(self, meta: Optional[VectorMetadata], d: int):
        if self._indices is not None:
            return
        if meta is None or meta.size != d:
            raise ValueError(
                "DropIndicesByTransformer requires vector column metadata")
        keep = [i for i, c in enumerate(meta.columns)
                if not self.predicate(c)]
        if not keep:
            raise ValueError("predicate matched every column")
        self._indices = keep
        self._meta = meta.select(keep)

    def host_prepare(self, cols: Sequence[Optional[Column]]):
        c = cols[0]
        if c is not None:
            self._resolve(c.meta, int(np.asarray(c.data).shape[1]))
        return None

    def device_apply(self, enc, dev):
        X = jnp.asarray(dev[-1])
        if self._indices is None:
            # metadata travels on the feature, not the device pytree
            meta = getattr(self.input_features[0].origin_stage,
                           "output_meta", lambda: None)()
            self._resolve(meta, int(X.shape[1]))
        return X[:, jnp.asarray(self._indices, dtype=jnp.int32)]

    def output_meta(self) -> Optional[VectorMetadata]:
        return self._meta

    def get_params(self):
        return {"predicate": encode_fn(self.predicate)}
