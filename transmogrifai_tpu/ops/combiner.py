"""VectorsCombiner: N OPVector features → one, with metadata union.

Reference parity: `core/.../feature/VectorsCombiner.scala`. On device this
is a single concatenate that XLA folds into downstream consumers — the
combined matrix never materializes separately in HBM unless a stage needs
it whole.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax.numpy as jnp

from transmogrifai_tpu import types as T
from transmogrifai_tpu.data.metadata import VectorMetadata
from transmogrifai_tpu.stages.base import Transformer


class VectorsCombiner(Transformer):
    in_types = (T.OPVector, Ellipsis)
    out_type = T.OPVector

    def device_apply(self, enc, dev):
        return jnp.concatenate([jnp.asarray(d) for d in dev], axis=1)

    def output_meta(self) -> Optional[VectorMetadata]:
        metas = []
        for f in self.input_features:
            stage = f.origin_stage
            m = stage.output_meta() if isinstance(stage, Transformer) else None
            if m is None:
                return None  # an input with unknown lineage poisons the union
            metas.append(m)
        return VectorMetadata.union(self.output_name(), metas)
