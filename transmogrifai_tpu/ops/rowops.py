"""Generic per-row feature ops: alias/map/filter/exists/replace/occurs and
small text measures.

Reference parity: `core/.../feature/AliasTransformer.scala`,
`ToOccurTransformer.scala`, `FilterTransformer/FilterMap/ExistsTransformer/
ReplaceTransformer/SubstringTransformer` (surfaced by the generic DSL in
`core/.../dsl/RichFeature.scala`), `TextLenTransformer.scala`,
`JaccardSimilarity.scala`, `NGramSimilarity.scala`.

These are host-value row maps (arbitrary python predicates over typed
values, like the reference's arbitrary Scala lambdas); numeric outputs land
in device scalar columns so downstream stages stay jittable. `LambdaMap`'s
function is serialized by qualified name, mirroring the reference's
extract-fn class-name persistence (`FeatureGeneratorStage.scala:129`).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import numpy as np

from transmogrifai_tpu import types as T
from transmogrifai_tpu.data.columns import Column, kind_of, SCALAR, TEXT
from transmogrifai_tpu.stages.base import HostTransformer, Transformer
from transmogrifai_tpu.utils.fnser import decode_fn, encode_fn


def _values_of(col: Column):
    """Host python values (None = missing) for any column kind."""
    k = col.kind
    if k == SCALAR:
        v = np.asarray(col.data["value"])
        m = np.asarray(col.data["mask"]).astype(bool)
        return [float(v[i]) if m[i] else None for i in range(len(v))]
    return list(col.data)


class AliasTransformer(HostTransformer):
    """Rename a feature without changing values (`AliasTransformer.scala`)."""

    in_types = None

    def __init__(self, name: str, uid: Optional[str] = None):
        super().__init__(uid=uid, name=name)
        self.name = name

    def output_name(self) -> str:
        return self.name

    def output_ftype(self) -> type:
        return self.input_features[0].ftype

    def transform(self, cols: Sequence[Column], ctx=None) -> Column:
        c = cols[0]
        return Column(c.ftype, c.data, c.meta)


class LambdaMap(HostTransformer):
    """feature.map(fn): arbitrary row transform to `out_type`. Lambdas and
    closures persist via cloudpickle (utils/fnser.py); named functions as
    module:name references."""

    in_types = None

    def __init__(self, fn: Callable[[Any], Any], out_type: type,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.fn = decode_fn(fn)
        self._out = (out_type if isinstance(out_type, type)
                     else T.feature_type_by_name(out_type))

    def output_ftype(self) -> type:
        return self._out

    def transform(self, cols: Sequence[Column], ctx=None) -> Column:
        vals = _values_of(cols[0])
        return Column.from_values(self._out, [self.fn(v) for v in vals])

    def get_params(self):
        return {"fn": encode_fn(self.fn), "out_type": self._out.__name__}


class FilterTransformer(HostTransformer):
    """Keep the value when `predicate(value)` else missing
    (`FilterTransformer.scala`; default-on-missing like the reference)."""

    in_types = None

    def __init__(self, predicate: Callable[[Any], bool],
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.predicate = decode_fn(predicate)

    def output_ftype(self) -> type:
        return self.input_features[0].ftype

    def transform(self, cols: Sequence[Column], ctx=None) -> Column:
        ft = self.input_features[0].ftype
        vals = _values_of(cols[0])
        kept = [v if (v is not None and self.predicate(v)) else None for v in vals]
        return Column.from_values(ft, kept)

    def get_params(self):
        return {"predicate": encode_fn(self.predicate)}


class ExistsTransformer(HostTransformer):
    """feature.exists(pred) → Binary (`RichFeature.exists`)."""

    in_types = None
    out_type = T.Binary

    def __init__(self, predicate: Callable[[Any], bool],
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.predicate = decode_fn(predicate)

    def transform(self, cols: Sequence[Column], ctx=None) -> Column:
        vals = _values_of(cols[0])
        out = [bool(v is not None and self.predicate(v)) for v in vals]
        return Column.from_values(T.Binary, out)

    def get_params(self):
        return {"predicate": encode_fn(self.predicate)}


class ReplaceTransformer(HostTransformer):
    """Replace values equal to `old` with `new` (`RichFeature.replaceWith`)."""

    in_types = None

    def __init__(self, old: Any, new: Any, uid: Optional[str] = None):
        super().__init__(uid=uid, old=old, new=new)
        self.old, self.new = old, new

    def output_ftype(self) -> type:
        return self.input_features[0].ftype

    def transform(self, cols: Sequence[Column], ctx=None) -> Column:
        ft = self.input_features[0].ftype
        vals = _values_of(cols[0])
        return Column.from_values(
            ft, [self.new if v == self.old else v for v in vals])


class ToOccurTransformer(HostTransformer):
    """Non-empty (by `matchFn`) → 1.0 else 0.0 (`ToOccurTransformer.scala`)."""

    in_types = None
    out_type = T.RealNN

    def __init__(self, match_fn: Optional[Callable[[Any], bool]] = None,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.match_fn = decode_fn(match_fn)

    def get_params(self):
        return {"match_fn": encode_fn(self.match_fn)}

    def transform(self, cols: Sequence[Column], ctx=None) -> Column:
        vals = _values_of(cols[0])

        def occurs(v):
            if v is None:
                return False
            if self.match_fn is not None:
                return bool(self.match_fn(v))
            if isinstance(v, (list, tuple, set, frozenset, dict, str)):
                return len(v) > 0
            return True

        return Column.from_values(
            T.RealNN, [1.0 if occurs(v) else 0.0 for v in vals])


class SubstringTransformer(HostTransformer):
    """(text, text) → Binary: does input 2 contain input 1?
    (`SubstringTransformer.scala`)."""

    in_types = (T.Text, T.Text)
    out_type = T.Binary

    def __init__(self, ignore_case: bool = True, uid: Optional[str] = None):
        super().__init__(uid=uid, ignore_case=ignore_case)
        self.ignore_case = ignore_case

    def transform(self, cols: Sequence[Column], ctx=None) -> Column:
        out = []
        for needle, hay in zip(cols[0].data, cols[1].data):
            if needle is None or hay is None:
                out.append(None)
            elif self.ignore_case:
                out.append(needle.lower() in hay.lower())
            else:
                out.append(needle in hay)
        return Column.from_values(T.Binary, out)


class TextLenTransformer(HostTransformer):
    """Text(/TextList) → Integral total length (`TextLenTransformer.scala`)."""

    in_types = None
    out_type = T.Integral

    def transform(self, cols: Sequence[Column], ctx=None) -> Column:
        vals = _values_of(cols[0])
        out = []
        for v in vals:
            if v is None:
                out.append(0)
            elif isinstance(v, str):
                out.append(len(v))
            else:
                out.append(sum(len(s) for s in v))
        return Column.from_values(T.Integral, out)


class JaccardSimilarity(HostTransformer):
    """(set, set) → RealNN |∩|/|∪| (`JaccardSimilarity.scala`; both empty → 1)."""

    in_types = (T.OPSet, T.OPSet)
    out_type = T.RealNN

    def transform(self, cols: Sequence[Column], ctx=None) -> Column:
        out = []
        for a, b in zip(cols[0].data, cols[1].data):
            sa = set(a) if a else set()
            sb = set(b) if b else set()
            union = sa | sb
            out.append(1.0 if not union else len(sa & sb) / len(union))
        return Column.from_values(T.RealNN, out)


def _ngrams(s: str, n: int) -> set:
    s = f" {s} "
    if len(s) < n:
        return {s}
    return {s[i:i + n] for i in range(len(s) - n + 1)}


class NGramSimilarity(HostTransformer):
    """(text, text) → RealNN character n-gram Jaccard similarity, the
    behavioral analogue of Lucene's NGramDistance used by
    `NGramSimilarity.scala` (0 when either side is empty)."""

    in_types = (T.Text, T.Text)
    out_type = T.RealNN

    def __init__(self, n: int = 3, uid: Optional[str] = None):
        super().__init__(uid=uid, n=n)
        self.n = int(n)

    def transform(self, cols: Sequence[Column], ctx=None) -> Column:
        out = []
        for a, b in zip(cols[0].data, cols[1].data):
            if not a or not b:
                out.append(0.0)
                continue
            ga, gb = _ngrams(a.lower(), self.n), _ngrams(b.lower(), self.n)
            union = ga | gb
            out.append(len(ga & gb) / len(union) if union else 0.0)
        return Column.from_values(T.RealNN, out)
