"""Text ops: tokenizer, feature hashing, smart cardinality-driven vectorizer.

Reference parity: `core/.../feature/TextTokenizer.scala` (Lucene analyzer →
simple analyzer here), `OPCollectionHashingVectorizer.scala` + murmur3
(`HashAlgorithm.scala`), `SmartTextVectorizer.scala:62-267` (per-field
TextStats choose pivot vs hash vs ignore; shared/separate hash space).

TPU-first: all string work is host-side vectorized prep producing dense
(n, d) count arrays; the device side is pure concat/scale so the hashed
space feeds straight into the combined matmul. Hashing is murmur3-32 for
cross-process determinism (python's hash() is salted), memoized per token.
"""

from __future__ import annotations

import re
from collections import Counter
from typing import Dict, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from transmogrifai_tpu import types as T
from transmogrifai_tpu.data.columns import Column
from transmogrifai_tpu.data.metadata import (
    NULL_INDICATOR, VectorColumnMetadata, VectorMetadata)
from transmogrifai_tpu.ops.categorical import (
    one_hot_np, pivot_encode_ids, top_k_levels)
from transmogrifai_tpu.stages.base import (
    Estimator, FitContext, HostTransformer, Transformer)

# ---------------------------------------------------------------------------
# murmur3-32 (pure python, memoized) — HashAlgorithm.MurMur3 parity
# ---------------------------------------------------------------------------

_M32 = 0xFFFFFFFF


def murmur3_32(data: bytes, seed: int = 0) -> int:
    c1, c2 = 0xCC9E2D51, 0x1B873593
    h = seed & _M32
    length = len(data)
    rounded = length & ~0x3
    for i in range(0, rounded, 4):
        k = int.from_bytes(data[i:i + 4], "little")
        k = (k * c1) & _M32
        k = ((k << 15) | (k >> 17)) & _M32
        k = (k * c2) & _M32
        h ^= k
        h = ((h << 13) | (h >> 19)) & _M32
        h = (h * 5 + 0xE6546B64) & _M32
    k = 0
    tail = data[rounded:]
    if len(tail) >= 3:
        k ^= tail[2] << 16
    if len(tail) >= 2:
        k ^= tail[1] << 8
    if len(tail) >= 1:
        k ^= tail[0]
        k = (k * c1) & _M32
        k = ((k << 15) | (k >> 17)) & _M32
        k = (k * c2) & _M32
        h ^= k
    h ^= length
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & _M32
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & _M32
    h ^= h >> 16
    return h


class TokenHasher:
    """Memoized token → bucket mapper."""

    def __init__(self, num_features: int, seed: int = 42):
        self.num_features = num_features
        self.seed = seed
        self._memo: Dict[str, int] = {}

    def __call__(self, token: str) -> int:
        b = self._memo.get(token)
        if b is None:
            b = murmur3_32(token.encode("utf-8"), self.seed) % self.num_features
            self._memo[token] = b
        return b


# ---------------------------------------------------------------------------
# Tokenizer (TextTokenizer.scala → LuceneTextAnalyzer.scala:87 parity:
# Unicode-script-aware analysis instead of one regex; VERDICT r3 #4)
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(r"[^\W_]+", re.UNICODE)

# script runs that need non-whitespace segmentation. Lucene's CJKAnalyzer
# emits overlapping character bigrams for Han/kana runs; Thai/Lao/Khmer/
# Myanmar (no inter-word spaces) get the same bigram treatment here as a
# dictionary-segmentation stand-in (Lucene uses ICU break iterators).
_BIGRAM_CLASS = (
    "\u4e00-\u9fff\u3400-\u4dbf"   # Han
    "\u3040-\u309f\u30a0-\u30ff"   # hiragana / katakana
    "\u0e00-\u0e7f\u0e80-\u0eff"   # Thai / Lao
    "\u1780-\u17ff\u1000-\u109f")  # Khmer / Myanmar
_BIGRAM_RUN_RE = re.compile(f"([{_BIGRAM_CLASS}]+)")
_ARABIC_RE = re.compile("[\u0600-\u06ff\u0750-\u077f]")
# cheap probe: does the text contain ANY char needing the analyzer path?
_NONSIMPLE_RE = re.compile(
    f"[{_BIGRAM_CLASS}\u0600-\u06ff\u0750-\u077f]")

# Arabic normalization (Lucene ArabicNormalizer): strip tatweel (0640) +
# harakat diacritics (064B-065F, 0670), fold alef/yaa/ta-marbuta variants
_AR_DIACRITICS = re.compile("[\u0640\u064b-\u065f\u0670]")
_AR_FOLD = str.maketrans({"\u0622": "\u0627", "\u0623": "\u0627",
                          "\u0625": "\u0627", "\u0649": "\u064a",
                          "\u0629": "\u0647"})


def _bigram_tokens(run: str) -> List[str]:
    if len(run) == 1:
        return [run]
    return [run[i:i + 2] for i in range(len(run) - 1)]


def _analyze(text: str, min_token_length: int) -> List[str]:
    """Script-aware token stream: bigram CJK/SEA runs, normalized Arabic,
    regex words elsewhere. CJK/SEA bigrams bypass min_token_length (a
    2-char bigram IS the token unit for those scripts)."""
    out: List[str] = []
    for part in _BIGRAM_RUN_RE.split(text):
        if not part:
            continue
        if _BIGRAM_RUN_RE.fullmatch(part):
            out.extend(_bigram_tokens(part))
            continue
        if _ARABIC_RE.search(part):
            part = _AR_DIACRITICS.sub("", part).translate(_AR_FOLD)
        out.extend(t for t in _TOKEN_RE.findall(part)
                   if len(t) >= min_token_length)
    return out


def tokenize(text: Optional[str], min_token_length: int = 1,
             to_lowercase: bool = True,
             language: Optional[str] = None) -> List[str]:
    """Analyzer tokens. `language` is accepted for the TextTokenizer
    API (reserved for per-language stopword/stemming rules); the script-
    aware segmentation itself is language-independent."""
    if not text:
        return []
    if to_lowercase:
        text = text.lower()
    if _NONSIMPLE_RE.search(text) is None:  # fast path: simple scripts
        return [t for t in _TOKEN_RE.findall(text)
                if len(t) >= min_token_length]
    return _analyze(text, min_token_length)


def _flat_tokens_arrow(values, min_token_length: int = 1,
                       to_lowercase: bool = True):
    """Whole-column tokenization via Arrow's C++ utf8 kernels — the same
    tokens as row-wise `tokenize`, at columnar speed. Returns
    (row_ids: int64 ndarray, flat_tokens: pyarrow StringArray)."""
    import pyarrow as pa
    import pyarrow.compute as pc

    arr = pa.array(values, type=pa.string(), from_pandas=True)
    if to_lowercase:
        arr = pc.utf8_lower(arr)
    # RE2's \W is ASCII-only; unicode letter/number classes keep parity
    # with the row-wise tokenizer's re.UNICODE [^\W_]+ on non-English text
    toks = pc.split_pattern_regex(arr, pattern=r"[^\p{L}\p{N}]+")
    flat = pc.list_flatten(toks)
    keep = pc.greater_equal(pc.utf8_length(flat), max(1, min_token_length))
    # row id per flattened token from the list offsets
    lens = pc.list_value_length(toks).to_numpy(zero_copy_only=False)
    lens = np.nan_to_num(lens, nan=0.0).astype(np.int64)
    rows = np.repeat(np.arange(len(values), dtype=np.int64), lens)
    keep_np = keep.to_numpy(zero_copy_only=False)
    rows, flat = rows[keep_np], flat.filter(keep)
    # rows containing CJK/SEA/Arabic codepoints need the script-aware
    # analyzer (bigrams + normalization): find them columnar via RE2,
    # re-tokenize row-wise, splice back in row order so every consumer
    # (hash kernel, batch tokenizer) sees identical tokens to `tokenize`
    sp = pc.fill_null(
        pc.match_substring_regex(arr, _NONSIMPLE_RE.pattern), False)
    sp_np = sp.to_numpy(zero_copy_only=False).astype(bool)
    if sp_np.any():
        if isinstance(flat, pa.ChunkedArray):
            flat = flat.combine_chunks()
        keep_rows = ~sp_np[rows]
        rows_simple = rows[keep_rows]
        flat_simple = flat.filter(pa.array(keep_rows))
        add_rows: list = []
        add_toks: list = []
        for i in np.flatnonzero(sp_np):
            ts = tokenize(values[i], min_token_length, to_lowercase)
            add_rows.extend([i] * len(ts))
            add_toks.extend(ts)
        rows = np.concatenate(
            [rows_simple, np.asarray(add_rows, np.int64)])
        flat = pa.concat_arrays(
            [flat_simple, pa.array(add_toks, pa.string())])
        order = np.argsort(rows, kind="stable")
        rows = rows[order]
        flat = flat.take(pa.array(order))
    return rows, flat


def tokenize_batch(values, min_token_length: int = 1,
                   to_lowercase: bool = True) -> np.ndarray:
    """Whole-column tokenization: object array of per-row token lists
    (None where the row has no tokens), matching row-wise `tokenize`.
    Arrow-backed with a row-loop fallback; rows containing CJK/SEA/Arabic
    codepoints are re-analyzed row-wise (script-aware bigrams +
    normalization) after the columnar pass."""
    n = len(values)
    out = np.empty(n, dtype=object)
    try:
        rows, flat = _flat_tokens_arrow(values, min_token_length, to_lowercase)
    except Exception:
        for i, v in enumerate(values):
            toks = tokenize(v, min_token_length, to_lowercase)
            out[i] = toks or None
        return out
    out[:] = None
    toks = flat.to_pylist()
    if len(rows):
        starts = np.searchsorted(rows, np.arange(n, dtype=np.int64), "left")
        ends = np.searchsorted(rows, np.arange(n, dtype=np.int64), "right")
        for i in range(n):
            if ends[i] > starts[i]:
                out[i] = toks[starts[i]:ends[i]]
    return out


class TextTokenizer(HostTransformer):
    """Text → TextList of analyzer tokens (host-only stage).

    Parameter surface mirrors `TextTokenizer.scala` (languageDetector /
    analyzer / autoDetectLanguage / defaultLanguage / minTokenLength /
    toLowercase): `auto_detect_language` runs the n-gram detector
    (`utils/language.py`) and only accepts its verdict above
    `auto_detect_threshold`, else `default_language` — the reference's
    LanguageDetector confidence-threshold branch. A resolved language
    (explicit `language=` or auto-detect) activates that language's
    stopword filter AND light Snowball-style stemmer
    (`utils/stemmers.py`, r4 VERDICT #6) — the analogue of Lucene's
    per-language analyzers, which stem by default; `stem=False` opts
    out. With neither language mode set (the default) tokens pass
    through unfiltered and unstemmed. CJK/Thai bigram tokens are never
    stemmed (the stemmers cover Latin + Russian only)."""

    in_types = (T.Text,)
    out_type = T.TextList

    def __init__(self, min_token_length: int = 1, to_lowercase: bool = True,
                 language: Optional[str] = None,
                 auto_detect_language: bool = False,
                 auto_detect_threshold: float = 0.99,
                 default_language: str = "en",
                 stem: bool = True,
                 uid: Optional[str] = None):
        super().__init__(uid=uid, min_token_length=min_token_length,
                         to_lowercase=to_lowercase, language=language,
                         auto_detect_language=auto_detect_language,
                         auto_detect_threshold=auto_detect_threshold,
                         default_language=default_language, stem=stem)
        self.min_token_length = min_token_length
        self.to_lowercase = to_lowercase
        self.language = language
        self.auto_detect_language = auto_detect_language
        self.auto_detect_threshold = auto_detect_threshold
        self.default_language = default_language
        self.stem = stem

    def language_of(self, text: Optional[str]) -> str:
        """Effective language for a row (explicit > auto-detect > default)."""
        if self.language:
            return self.language
        if self.auto_detect_language and text:
            from transmogrifai_tpu.utils.language import detect_language
            d = detect_language(text)
            if d:
                lang, conf = next(iter(d.items()))
                if conf >= self.auto_detect_threshold:
                    return lang
        return self.default_language

    def transform(self, cols: Sequence[Column], ctx=None) -> Column:
        data = cols[0].data
        out = tokenize_batch(data, self.min_token_length, self.to_lowercase)
        if self.language or self.auto_detect_language:
            from transmogrifai_tpu.utils.language import stopwords_for
            from transmogrifai_tpu.utils.stemmers import stem_tokens
            lang_fixed = self.language
            for i in range(len(out)):
                if out[i] is None:
                    continue
                lang = lang_fixed or self.language_of(data[i])
                stops = stopwords_for(lang)
                kept = out[i]
                if stops:
                    kept = [t for t in kept if t.lower() not in stops]
                # stemmers operate on lowercased tokens; with
                # to_lowercase=False, stemming would be case-inconsistent
                # (Dog/dog stem apart) — preserve the case contract and
                # skip it instead
                if self.stem and self.to_lowercase and kept:
                    kept = stem_tokens(kept, lang)
                out[i] = kept or None
        return Column(self.output_ftype(), out)


# ---------------------------------------------------------------------------
# Hashing vectorizer (OPCollectionHashingVectorizer)
# ---------------------------------------------------------------------------

def _native_hash_counts(flat, rows_np: np.ndarray, hasher: TokenHasher,
                        out: np.ndarray) -> bool:
    """Fused C kernel over the arrow StringArray's (offsets, data) buffers
    (native/murmur3.c) — zero per-token Python objects. Returns False when
    the native library or a flat buffer layout is unavailable."""
    import ctypes

    from transmogrifai_tpu.native import get_murmur3
    lib = get_murmur3()
    if lib is None:
        return False
    if flat.null_count or flat.offset != 0:
        flat = flat.combine_chunks() if hasattr(flat, "combine_chunks") else flat
        if flat.null_count or flat.offset != 0:
            return False
    bufs = flat.buffers()
    if len(bufs) < 3 or bufs[2] is None:
        return False
    import pyarrow as pa
    offsets_buf, data_buf = bufs[1], bufs[2]
    rows = np.ascontiguousarray(rows_np, dtype=np.int64)
    fn = (lib.murmur3_hash_counts_i32
          if pa.types.is_string(flat.type) else None)
    if fn is None:
        return False
    fn(ctypes.c_void_p(data_buf.address),
       ctypes.c_void_p(offsets_buf.address),
       rows.ctypes.data_as(ctypes.c_void_p),
       ctypes.c_int64(len(flat)),
       ctypes.c_uint32(hasher.seed & 0xFFFFFFFF),
       ctypes.c_uint32(hasher.num_features),
       out.ctypes.data_as(ctypes.c_void_p))
    return True


def _hash_counts(values, hasher: TokenHasher, binary: bool,
                 pre_tokenized: bool) -> np.ndarray:
    """Vectorized hashed token counts (VERDICT r1 weak#5): Arrow C++ utf8
    kernels tokenize the whole column, dictionary-encode finds the distinct
    tokens, murmur3 runs once per DISTINCT token (it is pure-python — the
    unique set is the whole cost), and np.add.at scatter-adds the counts.
    Falls back to the row loop for pre-tokenized lists / non-string input.
    """
    n = len(values)
    out = np.zeros((n, hasher.num_features), dtype=np.float32)
    if not pre_tokenized:
        try:
            rows_np, flat = _flat_tokens_arrow(values)
            if len(rows_np) == 0:
                return out
            if _native_hash_counts(flat, rows_np, hasher, out):
                pass  # fused C kernel: hash + scatter straight off arrow
            else:
                d = flat.dictionary_encode()
                uniq = d.dictionary.to_pylist()
                idx = np.asarray(d.indices.to_numpy(zero_copy_only=False),
                                 dtype=np.int64)
                buckets_u = np.fromiter((hasher(t) for t in uniq), np.int64,
                                        len(uniq))
                np.add.at(out, (rows_np, buckets_u[idx]), 1.0)
            if binary:
                np.minimum(out, 1.0, out=out)
            return out
        except Exception:
            out[:] = 0.0  # arrow unavailable/odd input: row-loop fallback
    rows: List[int] = []
    toks: List[str] = []
    for i, v in enumerate(values):
        if v is None:
            continue
        t = v if pre_tokenized else tokenize(v)
        toks.extend(t)
        rows.extend([i] * len(t))
    if not toks:
        return out
    buckets = np.fromiter((hasher(t) for t in toks), np.int64, len(toks))
    np.add.at(out, (np.asarray(rows, dtype=np.int64), buckets), 1.0)
    if binary:
        np.minimum(out, 1.0, out=out)
    return out


class HashingVectorizer(Transformer):
    """N Text/TextList features → murmur3 hashed token counts.

    shared_hash_space=True packs all inputs into one `num_features` space
    (HashSpaceStrategy.Shared); otherwise each input gets its own block.
    """

    in_types = None  # Text or TextList, checked below
    out_type = T.OPVector

    def __init__(self, num_features: int = 512, binary: bool = False,
                 shared_hash_space: bool = False, track_nulls: bool = True,
                 seed: int = 42, uid: Optional[str] = None):
        super().__init__(uid=uid, num_features=num_features, binary=binary,
                         shared_hash_space=shared_hash_space,
                         track_nulls=track_nulls, seed=seed)
        self.num_features = num_features
        self.binary = binary
        self.shared_hash_space = shared_hash_space
        self.track_nulls = track_nulls
        self.seed = seed

    def _check_inputs(self, features):
        for f in features:
            if not (issubclass(f.ftype, T.Text) or issubclass(f.ftype, T.TextList)):
                raise TypeError(
                    f"HashingVectorizer input {f.name!r} must be Text or "
                    f"TextList, got {f.ftype.__name__}")

    def host_prepare(self, cols: Sequence[Optional[Column]]):
        blocks, nulls = [], []
        shared = (TokenHasher(self.num_features, self.seed)
                  if self.shared_hash_space else None)
        for i, c in enumerate(cols):
            pre_tok = c.kind == "list"
            hasher = shared or TokenHasher(self.num_features, self.seed + i)
            blocks.append(_hash_counts(c.data, hasher, self.binary, pre_tok))
            nulls.append(np.fromiter(
                (1.0 if v is None else 0.0 for v in c.data),
                dtype=np.float32, count=len(c.data)))
        if self.shared_hash_space:
            merged = np.sum(blocks, axis=0)
            if self.binary:
                merged = np.minimum(merged, 1.0)  # keep 0/1 presence contract
            blocks = [merged]
        return {"blocks": blocks, "nulls": nulls}

    def device_apply(self, enc, dev):
        parts = [jnp.asarray(b) for b in enc["blocks"]]
        if self.track_nulls:
            parts.extend(jnp.asarray(z)[:, None] for z in enc["nulls"])
        return jnp.concatenate(parts, axis=1)

    def output_meta(self) -> VectorMetadata:
        cols: List[VectorColumnMetadata] = []
        if self.shared_hash_space:
            group = ",".join(f.name for f in self.input_features)
            for j in range(self.num_features):
                cols.append(VectorColumnMetadata(
                    parent_name=group, parent_type="Text",
                    descriptor_value=f"hash_{j}"))
        else:
            for f in self.input_features:
                for j in range(self.num_features):
                    cols.append(VectorColumnMetadata(
                        parent_name=f.name, parent_type=f.ftype.__name__,
                        descriptor_value=f"hash_{j}"))
        if self.track_nulls:
            for f in self.input_features:
                cols.append(VectorColumnMetadata(
                    parent_name=f.name, parent_type=f.ftype.__name__,
                    indicator_value=NULL_INDICATOR))
        return VectorMetadata(self.output_name(), tuple(cols)).with_indices()


# ---------------------------------------------------------------------------
# SmartTextVectorizer (SmartTextVectorizer.scala:62-267)
# ---------------------------------------------------------------------------

PIVOT, HASH, IGNORE = "pivot", "hash", "ignore"


class SmartTextModel(Transformer):
    """Fitted per-field strategy: categorical pivot, hashed tokens, or
    null-indicator-only for ID-like fields."""

    out_type = T.OPVector

    def __init__(self, strategies: Sequence[str], vocabs: Sequence[Sequence[str]],
                 num_features: int, track_nulls: bool = True, seed: int = 42,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.strategies = list(strategies)
        self.vocabs = [list(v) for v in vocabs]
        self.num_features = num_features
        self.track_nulls = track_nulls
        self.seed = seed
        self._lookups = [{s: i for i, s in enumerate(v)} for v in self.vocabs]

    def host_prepare(self, cols: Sequence[Optional[Column]]):
        blocks = []
        for i, c in enumerate(cols):
            strat = self.strategies[i]
            n = len(c.data)
            if strat == PIVOT:
                lut, k = self._lookups[i], len(self.vocabs[i])
                block = one_hot_np(pivot_encode_ids(c.data, lut, k), k,
                                   self.track_nulls)
            elif strat == HASH:
                hasher = TokenHasher(self.num_features, self.seed + i)
                block = _hash_counts(c.data, hasher, False, False)
                if self.track_nulls:
                    nulls = np.fromiter(
                        (1.0 if v is None else 0.0 for v in c.data),
                        dtype=np.float32, count=n)
                    block = np.concatenate([block, nulls[:, None]], axis=1)
            else:  # IGNORE: null indicator only
                nulls = np.fromiter(
                    (1.0 if v is None else 0.0 for v in c.data),
                    dtype=np.float32, count=n)
                block = nulls[:, None]
            blocks.append(block)
        return blocks

    def device_apply(self, enc, dev):
        return jnp.concatenate([jnp.asarray(b) for b in enc], axis=1)

    def output_meta(self) -> VectorMetadata:
        cols: List[VectorColumnMetadata] = []
        for i, f in enumerate(self.input_features):
            strat = self.strategies[i]
            if strat == PIVOT:
                for lvl in self.vocabs[i]:
                    cols.append(VectorColumnMetadata(
                        parent_name=f.name, parent_type=f.ftype.__name__,
                        grouping=f.name, indicator_value=lvl))
                cols.append(VectorColumnMetadata(
                    parent_name=f.name, parent_type=f.ftype.__name__,
                    grouping=f.name, indicator_value="OTHER"))
                if self.track_nulls:
                    cols.append(VectorColumnMetadata(
                        parent_name=f.name, parent_type=f.ftype.__name__,
                        grouping=f.name, indicator_value=NULL_INDICATOR))
            elif strat == HASH:
                for j in range(self.num_features):
                    cols.append(VectorColumnMetadata(
                        parent_name=f.name, parent_type=f.ftype.__name__,
                        descriptor_value=f"hash_{j}"))
                if self.track_nulls:
                    cols.append(VectorColumnMetadata(
                        parent_name=f.name, parent_type=f.ftype.__name__,
                        indicator_value=NULL_INDICATOR))
            else:
                cols.append(VectorColumnMetadata(
                    parent_name=f.name, parent_type=f.ftype.__name__,
                    indicator_value=NULL_INDICATOR))
        return VectorMetadata(self.output_name(), tuple(cols)).with_indices()

    def get_params(self):
        return {"strategies": self.strategies, "vocabs": self.vocabs,
                "num_features": self.num_features,
                "track_nulls": self.track_nulls, "seed": self.seed}


class SmartTextVectorizer(Estimator):
    """Per-field cardinality stats choose the encoding
    (SmartTextVectorizer.scala):

    - distinct <= max_cardinality          → top-K categorical pivot
    - ID-like (distinct ≈ count)           → ignore (null indicator only)
    - otherwise                            → hashed token counts
    """

    in_types = (T.Text, Ellipsis)
    out_type = T.OPVector

    def __init__(self, max_cardinality: int = 100, top_k: int = 20,
                 min_support: int = 10, num_features: int = 512,
                 id_detect_ratio: float = 0.99, track_nulls: bool = True,
                 seed: int = 42, uid: Optional[str] = None):
        super().__init__(
            uid=uid, max_cardinality=max_cardinality, top_k=top_k,
            min_support=min_support, num_features=num_features,
            id_detect_ratio=id_detect_ratio, track_nulls=track_nulls, seed=seed)
        self.max_cardinality = max_cardinality
        self.top_k = top_k
        self.min_support = min_support
        self.num_features = num_features
        self.id_detect_ratio = id_detect_ratio
        self.track_nulls = track_nulls
        self.seed = seed

    def fit_model(self, cols: Sequence[Column], ctx: FitContext) -> Transformer:
        strategies, vocabs = [], []
        for c in cols:
            counter = Counter(s for s in c.data if s is not None)
            n_values = sum(counter.values())
            n_distinct = len(counter)
            if n_distinct == 0:
                strategies.append(IGNORE)
                vocabs.append([])
            elif n_distinct <= self.max_cardinality:
                strategies.append(PIVOT)
                vocabs.append(top_k_levels(counter, self.top_k, self.min_support))
            elif n_values > 0 and n_distinct / n_values >= self.id_detect_ratio:
                strategies.append(IGNORE)  # ID-like: every value unique
                vocabs.append([])
            else:
                strategies.append(HASH)
                vocabs.append([])
        return SmartTextModel(strategies, vocabs, self.num_features,
                              self.track_nulls, self.seed)
