"""String/label indexing round-trip.

Reference parity: `core/.../feature/OpStringIndexer.scala` (Text → RealNN
indices ordered by descending frequency), `OpIndexToString.scala` (+
NoFilter variants: unseen labels map to an extra index instead of erroring),
`core/.../preparators/PredictionDeIndexer.scala` (map a Prediction's class
index back to the original string label using the indexer that encoded the
response).

Host/device split: building and applying a vocabulary over strings is host
work (numpy object arrays); the produced index column is a device scalar so
everything downstream stays jittable.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from transmogrifai_tpu import types as T
from transmogrifai_tpu.data.columns import Column
from transmogrifai_tpu.stages.base import (
    Estimator, FitContext, HostTransformer, Transformer)

ERROR, SKIP, KEEP = "error", "skip", "keep"


class StringIndexerModel(Transformer):
    """Fitted vocabulary: label → index (desc-frequency order)."""

    in_types = (T.Text,)
    out_type = T.RealNN
    jittable = False  # input is a host text column

    def __init__(self, labels: Sequence[str], handle_invalid: str = ERROR,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.labels = list(labels)
        self.handle_invalid = handle_invalid
        self._index: Dict[str, int] = {l: i for i, l in enumerate(self.labels)}

    def host_prepare(self, cols):
        vals = cols[0].data
        n = len(vals)
        idx = np.zeros(n, dtype=np.float64)
        mask = np.ones(n, dtype=bool)
        unseen = float(len(self.labels))
        for i, v in enumerate(vals):
            if v is None:
                mask[i] = False
                continue
            j = self._index.get(v)
            if j is None:
                if self.handle_invalid == ERROR:
                    raise ValueError(f"Unseen label {v!r} in {self.operation_name}")
                if self.handle_invalid == SKIP:
                    mask[i] = False
                else:  # KEEP
                    idx[i] = unseen
            else:
                idx[i] = float(j)
        return {"value": idx, "mask": mask}

    def device_apply(self, enc, dev):
        return enc

    def get_params(self):
        return {"labels": self.labels, "handle_invalid": self.handle_invalid}


class OpStringIndexer(Estimator):
    """Text → RealNN index; labels ordered by descending frequency (ties by
    label for determinism)."""

    in_types = (T.Text,)
    out_type = T.RealNN

    def __init__(self, handle_invalid: str = ERROR, uid: Optional[str] = None):
        if handle_invalid not in (ERROR, SKIP, KEEP):
            raise ValueError(f"handle_invalid must be one of error/skip/keep")
        super().__init__(uid=uid, handle_invalid=handle_invalid)
        self.handle_invalid = handle_invalid

    def fit_model(self, cols: Sequence[Column], ctx: FitContext) -> Transformer:
        counts: Dict[str, int] = {}
        for v in cols[0].data:
            if v is not None:
                counts[v] = counts.get(v, 0) + 1
        labels = sorted(counts, key=lambda l: (-counts[l], l))
        return StringIndexerModel(labels, self.handle_invalid)


class OpStringIndexerNoFilter(OpStringIndexer):
    """Unseen labels keep an extra index (`OpStringIndexerNoFilter.scala`)."""

    def __init__(self, uid: Optional[str] = None):
        super().__init__(handle_invalid=KEEP, uid=uid)


class OpIndexToString(HostTransformer):
    """RealNN index → Text using an explicit label list, or the labels of the
    StringIndexerModel that produced the input."""

    in_types = (T.OPNumeric,)
    out_type = T.Text

    def __init__(self, labels: Optional[Sequence[str]] = None,
                 unseen_name: str = "UnseenLabel", uid: Optional[str] = None):
        super().__init__(uid=uid, labels=list(labels) if labels else None,
                         unseen_name=unseen_name)
        self.labels = list(labels) if labels else None
        self.unseen_name = unseen_name

    def _labels(self) -> List[str]:
        if self.labels is not None:
            return self.labels
        origin = self.input_features[0].origin_stage
        if isinstance(origin, StringIndexerModel):
            return origin.labels
        raise ValueError(
            "OpIndexToString needs labels= or a StringIndexerModel parent")

    def transform(self, cols: Sequence[Column], ctx=None) -> Column:
        labels = self._labels()
        v = np.asarray(cols[0].data["value"], dtype=np.float64)
        m = np.asarray(cols[0].data["mask"]).astype(bool)
        out = np.empty(len(v), dtype=object)
        for i in range(len(v)):
            if not m[i]:
                out[i] = None
            else:
                j = int(v[i])
                out[i] = labels[j] if 0 <= j < len(labels) else self.unseen_name
        return Column(T.Text, out)


class PredictionDeIndexer(HostTransformer):
    """(indexed response, Prediction) → Text: the predicted class as its
    original string label (`PredictionDeIndexer.scala`)."""

    in_types = (T.OPNumeric, T.Prediction)
    out_type = T.Text
    response_aware = True  # slot 0 is the (indexed) label

    def __init__(self, labels: Optional[Sequence[str]] = None,
                 uid: Optional[str] = None):
        super().__init__(uid=uid, labels=list(labels) if labels else None)
        self.labels = list(labels) if labels else None

    def _labels(self) -> List[str]:
        if self.labels is not None:
            return self.labels
        origin = self.input_features[0].origin_stage
        if isinstance(origin, StringIndexerModel):
            return origin.labels
        raise ValueError(
            "PredictionDeIndexer: response must come from a StringIndexerModel "
            "(or pass labels=)")

    def transform(self, cols: Sequence[Column], ctx=None) -> Column:
        labels = self._labels()
        pred = np.asarray(cols[1].data["prediction"], dtype=np.float64)
        out = np.empty(len(pred), dtype=object)
        for i, p in enumerate(pred):
            j = int(p)
            out[i] = labels[j] if 0 <= j < len(labels) else None
        return Column(T.Text, out)
