"""Arithmetic transformers over numeric features.

Reference parity: `core/.../feature/MathTransformers.scala` (Add/Subtract/
Multiply/Divide + scalar variants, AbsoluteValue, Ceil, Floor, Round, Exp,
Sqrt, Log, Power) surfaced through the DSL
(`core/.../dsl/RichNumericFeature.scala:70-228`).

Missing-value semantics match the reference:
- plus/minus: present if EITHER side is present (one-sided gives that side,
  minus gives the negation) — `MathTransformers.scala:57,97-102`.
- multiply/divide: require BOTH sides; non-finite results (divide by zero,
  overflow) become missing — `MathTransformers.scala:145-151,192-198`.
- unary ops propagate the input mask and drop non-finite outputs
  (log of non-positive, sqrt of negative).

TPU-first: each op is a masked jnp expression; chains of arithmetic fuse
into one XLA kernel with no intermediate materialization.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax.numpy as jnp

from transmogrifai_tpu import types as T
from transmogrifai_tpu.data.columns import Column
from transmogrifai_tpu.stages.base import Transformer

_BINARY_OPS = ("plus", "minus", "multiply", "divide")
_UNARY_OPS = ("abs", "ceil", "floor", "round", "exp", "sqrt", "log", "power",
              "negate")


def _finite_mask(value, mask):
    ok = jnp.isfinite(value)
    return jnp.where(ok, value, 0.0), mask & ok


class BinaryMathTransformer(Transformer):
    """feature ⊕ feature → Real (op in plus/minus/multiply/divide)."""

    in_types = (T.OPNumeric, T.OPNumeric)
    out_type = T.Real

    def __init__(self, op: str, uid: Optional[str] = None):
        if op not in _BINARY_OPS:
            raise ValueError(f"unknown binary math op {op!r}")
        super().__init__(uid=uid, op=op)
        self.op = op

    @property
    def operation_name(self) -> str:
        return self.op

    def device_apply(self, enc, dev):
        (x, mx), (y, my) = ((d["value"], d["mask"]) for d in dev)
        mx = mx.astype(bool)
        my = my.astype(bool)
        if self.op == "plus":
            return {"value": jnp.where(mx, x, 0.0) + jnp.where(my, y, 0.0),
                    "mask": mx | my}
        if self.op == "minus":
            return {"value": jnp.where(mx, x, 0.0) - jnp.where(my, y, 0.0),
                    "mask": mx | my}
        if self.op == "multiply":
            v, m = _finite_mask(x * y, mx & my)
            return {"value": v, "mask": m}
        v = x / jnp.where(y == 0.0, jnp.nan, y)
        v, m = _finite_mask(v, mx & my)
        return {"value": v, "mask": m}


class ScalarMathTransformer(Transformer):
    """feature ⊕ scalar → Real (ScalarAdd/Subtract/Multiply/Divide; the
    r-variants put the scalar on the left for non-commutative ops)."""

    _OPS = _BINARY_OPS + ("rminus", "rdivide")

    in_types = (T.OPNumeric,)
    out_type = T.Real

    def __init__(self, op: str, scalar: float, uid: Optional[str] = None):
        if op not in self._OPS:
            raise ValueError(f"unknown scalar math op {op!r}")
        super().__init__(uid=uid, op=op, scalar=float(scalar))
        self.op = op
        self.scalar = float(scalar)

    @property
    def operation_name(self) -> str:
        return f"{self.op}S"

    def device_apply(self, enc, dev):
        x, m = dev[0]["value"], dev[0]["mask"].astype(bool)
        s = self.scalar
        if self.op == "plus":
            v = x + s
        elif self.op == "minus":
            v = x - s
        elif self.op == "rminus":
            v = s - x
        elif self.op == "multiply":
            v = x * s
        elif self.op == "rdivide":
            v = s / jnp.where(x == 0.0, jnp.nan, x)
        else:
            v = x / s if s != 0.0 else jnp.full_like(x, jnp.nan)
        v, m = _finite_mask(v, m)
        return {"value": v, "mask": m}


class UnaryMathTransformer(Transformer):
    """Elementwise unary op → Real: abs/ceil/floor/round/exp/sqrt/log/power."""

    in_types = (T.OPNumeric,)
    out_type = T.Real

    def __init__(self, op: str, arg: float = 0.0, uid: Optional[str] = None):
        if op not in _UNARY_OPS:
            raise ValueError(f"unknown unary math op {op!r}")
        super().__init__(uid=uid, op=op, arg=float(arg))
        self.op = op
        self.arg = float(arg)  # log base / power exponent

    @property
    def operation_name(self) -> str:
        return self.op

    def device_apply(self, enc, dev):
        x, m = dev[0]["value"], dev[0]["mask"].astype(bool)
        op = self.op
        if op == "abs":
            v = jnp.abs(x)
        elif op == "ceil":
            v = jnp.ceil(x)
        elif op == "floor":
            v = jnp.floor(x)
        elif op == "round":
            v = jnp.round(x)
        elif op == "exp":
            v = jnp.exp(x)
        elif op == "sqrt":
            v = jnp.sqrt(x)
        elif op == "negate":
            v = -x
        elif op == "log":
            base = self.arg if self.arg > 0 else jnp.e
            v = jnp.log(jnp.where(x > 0, x, jnp.nan)) / jnp.log(base)
        else:  # power
            v = jnp.power(x, self.arg)
        v, m = _finite_mask(v, m)
        return {"value": v, "mask": m}
