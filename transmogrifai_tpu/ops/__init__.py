from transmogrifai_tpu.ops.numeric import (
    RealVectorizer, IntegralVectorizer, BinaryVectorizer, RealNNVectorizer)
from transmogrifai_tpu.ops.categorical import OneHotVectorizer, MultiPickListVectorizer
from transmogrifai_tpu.ops.combiner import VectorsCombiner
from transmogrifai_tpu.ops.text import TextTokenizer, HashingVectorizer, SmartTextVectorizer
from transmogrifai_tpu.ops.dates import DateToUnitCircleVectorizer
from transmogrifai_tpu.ops.geo import GeolocationVectorizer

__all__ = [
    "RealVectorizer", "IntegralVectorizer", "BinaryVectorizer",
    "RealNNVectorizer", "OneHotVectorizer", "MultiPickListVectorizer",
    "VectorsCombiner", "TextTokenizer", "HashingVectorizer",
    "SmartTextVectorizer", "DateToUnitCircleVectorizer", "GeolocationVectorizer",
]
