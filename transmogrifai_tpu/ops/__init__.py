from transmogrifai_tpu.ops.numeric import (
    RealVectorizer, IntegralVectorizer, BinaryVectorizer, RealNNVectorizer)
from transmogrifai_tpu.ops.categorical import OneHotVectorizer, MultiPickListVectorizer
from transmogrifai_tpu.ops.combiner import VectorsCombiner
from transmogrifai_tpu.ops.text import TextTokenizer, HashingVectorizer, SmartTextVectorizer
from transmogrifai_tpu.ops.dates import (
    DateToUnitCircleVectorizer, TimePeriodTransformer, TimePeriodListTransformer,
    DateListVectorizer)
from transmogrifai_tpu.ops.geo import GeolocationVectorizer
from transmogrifai_tpu.ops.mathops import (
    BinaryMathTransformer, ScalarMathTransformer, UnaryMathTransformer)
from transmogrifai_tpu.ops.scalers import (
    OpScalarStandardScaler, FillMissingWithMean, ScalerTransformer,
    DescalerTransformer, PercentileCalibrator)
from transmogrifai_tpu.ops.bucketizers import (
    NumericBucketizer, DecisionTreeNumericBucketizer,
    DecisionTreeNumericMapBucketizer)
from transmogrifai_tpu.ops.indexers import (
    OpStringIndexer, OpStringIndexerNoFilter, OpIndexToString,
    PredictionDeIndexer)
from transmogrifai_tpu.ops.rowops import (
    AliasTransformer, LambdaMap, FilterTransformer, ExistsTransformer,
    ReplaceTransformer, ToOccurTransformer, SubstringTransformer,
    TextLenTransformer, JaccardSimilarity, NGramSimilarity)
from transmogrifai_tpu.ops.enrich import (
    ValidEmailTransformer, EmailDomainTransformer,
    EmailToPickListMapTransformer, UrlIsValidTransformer,
    UrlDomainTransformer, UrlProtocolTransformer, PhoneIsValidTransformer,
    PhoneIsValidWithRegionTransformer, PhoneParseTransformer,
    PhoneParseWithRegionTransformer, PhoneMapIsValidTransformer,
    PhoneVectorizer, MimeTypeDetector, LangDetector, HumanNameDetector,
    NameEntityRecognizer)
from transmogrifai_tpu.ops.text_advanced import (
    OpStopWordsRemover, OpNGram, OpCountVectorizer, OpWord2Vec, OpLDA)
from transmogrifai_tpu.ops.drop_indices import DropIndicesByTransformer
from transmogrifai_tpu.ops.maps import (
    NumericMapVectorizer, TextMapPivotVectorizer, SmartTextMapVectorizer,
    MultiPickListMapVectorizer, PhoneMapVectorizer, GeolocationMapVectorizer,
    DateMapVectorizer)

__all__ = [
    "RealVectorizer", "IntegralVectorizer", "BinaryVectorizer",
    "RealNNVectorizer", "OneHotVectorizer", "MultiPickListVectorizer",
    "VectorsCombiner", "TextTokenizer", "HashingVectorizer",
    "SmartTextVectorizer", "DateToUnitCircleVectorizer",
    "TimePeriodTransformer", "TimePeriodListTransformer", "DateListVectorizer",
    "GeolocationVectorizer",
    "BinaryMathTransformer", "ScalarMathTransformer", "UnaryMathTransformer",
    "OpScalarStandardScaler", "FillMissingWithMean", "ScalerTransformer",
    "DescalerTransformer", "PercentileCalibrator",
    "NumericBucketizer", "DecisionTreeNumericBucketizer",
    "DecisionTreeNumericMapBucketizer",
    "OpStringIndexer", "OpStringIndexerNoFilter", "OpIndexToString",
    "PredictionDeIndexer",
    "AliasTransformer", "LambdaMap", "FilterTransformer", "ExistsTransformer",
    "ReplaceTransformer", "ToOccurTransformer", "SubstringTransformer",
    "TextLenTransformer", "JaccardSimilarity", "NGramSimilarity",
    "ValidEmailTransformer", "EmailDomainTransformer",
    "EmailToPickListMapTransformer", "UrlIsValidTransformer",
    "UrlDomainTransformer", "UrlProtocolTransformer",
    "PhoneIsValidTransformer", "PhoneIsValidWithRegionTransformer",
    "PhoneParseTransformer", "PhoneParseWithRegionTransformer",
    "PhoneMapIsValidTransformer", "PhoneVectorizer", "MimeTypeDetector",
    "LangDetector", "HumanNameDetector", "NameEntityRecognizer",
    "OpStopWordsRemover", "OpNGram", "OpCountVectorizer", "OpWord2Vec",
    "OpLDA", "DropIndicesByTransformer",
    "NumericMapVectorizer", "TextMapPivotVectorizer",
    "SmartTextMapVectorizer", "MultiPickListMapVectorizer",
    "PhoneMapVectorizer", "GeolocationMapVectorizer", "DateMapVectorizer",
]
