"""Advanced text ops: count vectorizer, n-grams, stopwords, Word2Vec, LDA.

Reference parity: `core/.../feature/OpCountVectorizer.scala`,
`OpNGram.scala`, `OpStopWordsRemover.scala`, `OpWord2Vec.scala:41`,
`OpLDA.scala:41` — the reference wraps Spark MLlib; these are native
implementations (numpy fit / jnp-friendly dense transforms) with the same
stage contracts (TextList → OPVector / TextList).
"""

from __future__ import annotations

from collections import Counter
from typing import Dict, List, Optional, Sequence

import numpy as np

from transmogrifai_tpu import types as T
from transmogrifai_tpu.data.columns import Column
from transmogrifai_tpu.data.metadata import (
    VectorColumnMetadata, VectorMetadata)
from transmogrifai_tpu.stages.base import (
    Estimator, FitContext, HostTransformer, Transformer)

# --------------------------------------------------------------------------- #
# OpStopWordsRemover                                                          #
# --------------------------------------------------------------------------- #

ENGLISH_STOP_WORDS = frozenset("""
a about above after again against all am an and any are aren't as at be
because been before being below between both but by can't cannot could
couldn't did didn't do does doesn't doing don't down during each few for
from further had hadn't has hasn't have haven't having he he'd he'll he's
her here here's hers herself him himself his how how's i i'd i'll i'm i've
if in into is isn't it it's its itself let's me more most mustn't my myself
no nor not of off on once only or other ought our ours ourselves out over
own same shan't she she'd she'll she's should shouldn't so some such than
that that's the their theirs them themselves then there there's these they
they'd they'll they're they've this those through to too under until up
very was wasn't we we'd we'll we're we've were weren't what what's when
when's where where's which while who who's whom why why's with won't would
wouldn't you you'd you'll you're you've your yours yourself yourselves
""".split())


class OpStopWordsRemover(HostTransformer):
    """TextList → TextList minus stopwords (OpStopWordsRemover.scala)."""

    in_types = (T.TextList,)
    out_type = T.TextList

    def __init__(self, stop_words: Optional[Sequence[str]] = None,
                 case_sensitive: bool = False, uid: Optional[str] = None):
        super().__init__(uid=uid, case_sensitive=case_sensitive)
        self.stop_words = frozenset(stop_words) if stop_words is not None \
            else ENGLISH_STOP_WORDS
        self.case_sensitive = case_sensitive
        self.params["stop_words"] = sorted(self.stop_words)

    def transform(self, cols: Sequence[Column], ctx=None) -> Column:
        out = np.empty(len(cols[0].data), dtype=object)
        for i, toks in enumerate(cols[0].data):
            if toks is None:
                out[i] = None
                continue
            if self.case_sensitive:
                kept = [t for t in toks if t not in self.stop_words]
            else:
                kept = [t for t in toks if t.lower() not in self.stop_words]
            out[i] = kept or None
        return Column(T.TextList, out)


class OpNGram(HostTransformer):
    """TextList → TextList of space-joined n-grams (OpNGram.scala)."""

    in_types = (T.TextList,)
    out_type = T.TextList

    def __init__(self, n: int = 2, uid: Optional[str] = None):
        if n < 1:
            raise ValueError("n must be >= 1")
        super().__init__(uid=uid, n=int(n))
        self.n = int(n)

    def transform(self, cols: Sequence[Column], ctx=None) -> Column:
        out = np.empty(len(cols[0].data), dtype=object)
        for i, toks in enumerate(cols[0].data):
            if toks is None or len(toks) < self.n:
                out[i] = None
                continue
            out[i] = [" ".join(toks[j:j + self.n])
                      for j in range(len(toks) - self.n + 1)]
        return Column(T.TextList, out)


# --------------------------------------------------------------------------- #
# OpCountVectorizer                                                           #
# --------------------------------------------------------------------------- #

class CountVectorizerModel(Transformer):
    out_type = T.OPVector

    def __init__(self, vocab: Sequence[str], binary: bool = False,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.vocab = list(vocab)
        self.binary = binary
        self._lut = {w: i for i, w in enumerate(self.vocab)}

    def host_prepare(self, cols: Sequence[Optional[Column]]):
        c = cols[0]
        out = np.zeros((len(c.data), len(self.vocab)), dtype=np.float32)
        lut = self._lut
        for i, toks in enumerate(c.data):
            if toks is None:
                continue
            for t in toks:
                j = lut.get(t)
                if j is not None:
                    out[i, j] += 1.0
        if self.binary:
            np.minimum(out, 1.0, out=out)
        return out

    def device_apply(self, enc, dev):
        import jax.numpy as jnp
        return jnp.asarray(enc)

    def output_meta(self) -> VectorMetadata:
        f = self.input_features[0]
        cols = tuple(VectorColumnMetadata(
            parent_name=f.name, parent_type=f.ftype.__name__,
            descriptor_value=w) for w in self.vocab)
        return VectorMetadata(self.output_name(), cols).with_indices()

    def get_params(self):
        return {"vocab": self.vocab, "binary": self.binary}


class OpCountVectorizer(Estimator):
    """TextList → term-count OPVector over a fitted vocabulary
    (OpCountVectorizer.scala wrapping Spark CountVectorizer: vocab_size cap,
    min_df document-frequency floor)."""

    in_types = (T.TextList,)
    out_type = T.OPVector

    def __init__(self, vocab_size: int = 1 << 18, min_df: float = 1.0,
                 binary: bool = False, uid: Optional[str] = None):
        super().__init__(uid=uid, vocab_size=vocab_size, min_df=min_df,
                         binary=binary)
        self.vocab_size = int(vocab_size)
        self.min_df = min_df
        self.binary = binary

    def fit_model(self, cols: Sequence[Column], ctx: FitContext) -> Transformer:
        df: Counter = Counter()
        n_docs = 0
        for toks in cols[0].data:
            if toks is None:
                continue
            n_docs += 1
            df.update(set(toks))
        min_count = self.min_df if self.min_df >= 1.0 else \
            self.min_df * max(n_docs, 1)
        eligible = [(c, w) for w, c in df.items() if c >= min_count]
        eligible.sort(key=lambda t: (-t[0], t[1]))
        vocab = [w for _, w in eligible[: self.vocab_size]]
        return CountVectorizerModel(vocab, binary=self.binary)


# --------------------------------------------------------------------------- #
# OpWord2Vec — native skip-gram with negative sampling                        #
# --------------------------------------------------------------------------- #

class Word2VecModel(Transformer):
    """Transform = mean of token vectors (Spark Word2VecModel.transform)."""

    out_type = T.OPVector

    def __init__(self, vectors: Dict[str, np.ndarray], vector_size: int,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.vectors = {k: np.asarray(v, dtype=np.float32)
                        for k, v in vectors.items()}
        self.vector_size = int(vector_size)

    def host_prepare(self, cols: Sequence[Optional[Column]]):
        c = cols[0]
        out = np.zeros((len(c.data), self.vector_size), dtype=np.float32)
        for i, toks in enumerate(c.data):
            if not toks:
                continue
            vecs = [self.vectors[t] for t in toks if t in self.vectors]
            if vecs:
                out[i] = np.mean(vecs, axis=0)
        return out

    def device_apply(self, enc, dev):
        import jax.numpy as jnp
        return jnp.asarray(enc)

    def output_meta(self) -> VectorMetadata:
        f = self.input_features[0]
        cols = tuple(VectorColumnMetadata(
            parent_name=f.name, parent_type=f.ftype.__name__,
            descriptor_value=f"w2v_{j}") for j in range(self.vector_size))
        return VectorMetadata(self.output_name(), cols).with_indices()

    def get_params(self):
        return {"vectors": {k: v.tolist() for k, v in self.vectors.items()},
                "vector_size": self.vector_size}


class OpWord2Vec(Estimator):
    """TextList → OPVector via skip-gram negative sampling trained on the
    fit corpus (OpWord2Vec.scala:41 wrapping Spark Word2Vec; native numpy
    SGNS here — same params: vector_size, window, min_count, num_iter)."""

    in_types = (T.TextList,)
    out_type = T.OPVector

    def __init__(self, vector_size: int = 100, window: int = 5,
                 min_count: int = 5, num_iter: int = 1,
                 learning_rate: float = 0.025, negatives: int = 5,
                 seed: int = 42, uid: Optional[str] = None):
        super().__init__(uid=uid, vector_size=vector_size, window=window,
                         min_count=min_count, num_iter=num_iter,
                         learning_rate=learning_rate, negatives=negatives,
                         seed=seed)
        self.vector_size = int(vector_size)
        self.window = int(window)
        self.min_count = int(min_count)
        self.num_iter = int(num_iter)
        self.learning_rate = float(learning_rate)
        self.negatives = int(negatives)
        self.seed = int(seed)

    def fit_model(self, cols: Sequence[Column], ctx: FitContext) -> Transformer:
        counts: Counter = Counter()
        docs: List[List[int]] = []
        for toks in cols[0].data:
            if toks:
                counts.update(toks)
        vocab = sorted((w for w, c in counts.items() if c >= self.min_count),
                       key=lambda w: (-counts[w], w))
        lut = {w: i for i, w in enumerate(vocab)}
        for toks in cols[0].data:
            if toks:
                ids = [lut[t] for t in toks if t in lut]
                if len(ids) > 1:
                    docs.append(ids)
        V, D = len(vocab), self.vector_size
        rng = np.random.default_rng(self.seed)
        if V == 0 or not docs:
            return Word2VecModel({}, D)
        W_in = (rng.random((V, D), dtype=np.float32) - 0.5) / D
        W_out = np.zeros((V, D), dtype=np.float32)
        # unigram^(3/4) negative-sampling table
        freq = np.asarray([counts[w] for w in vocab], dtype=np.float64) ** 0.75
        neg_p = freq / freq.sum()
        lr = self.learning_rate
        # Flat skip-gram pair generation (r2 ran a python loop per token —
        # O(corpus) interpreter time; this is vectorized over ALL
        # positions): docs concatenate into one id stream with document
        # boundaries, per-position dynamic window spans draw like the
        # word2vec reference, and each offset o ∈ [1, window] contributes
        # the (center, center±o) pairs where o ≤ span and both sides stay
        # inside the document.
        flat = np.concatenate([np.asarray(d) for d in docs])
        doc_of = np.concatenate(
            [np.full(len(d), i) for i, d in enumerate(docs)])
        n_pos = len(flat)
        # Batch caps at 8·vocab pairs: `np.add.at` SUMS every in-batch
        # duplicate of a word as one stale-gradient step, so a tiny
        # vocabulary (near-categorical text columns) under a large batch
        # takes effective steps of ~(batch/V)·lr·‖v‖ — divergent even at
        # the default lr. Bounding duplicates-per-word at ~8 keeps the
        # batched update within a small factor of gensim/Spark's
        # sequential SGD (their batch is effectively 1); natural corpora
        # (V ≥ 1024) keep the full throughput batch.
        batch = int(min(8192, max(16, 8 * V)))
        for it in range(self.num_iter):
            spans = rng.integers(1, self.window + 1, size=n_pos)
            centers_l, contexts_l = [], []
            for o in range(1, self.window + 1):
                ok = (spans >= o)
                same_doc = doc_of[o:] == doc_of[:-o]
                # each side gates on the CENTER position's own span draw —
                # word2vec's per-center dynamic window (r3 advisor: gating
                # the right-side pair on the context's draw was equivalent
                # only in expectation)
                left = ok[o:] & same_doc    # center at idx, context idx-o
                idx_l = np.flatnonzero(left) + o
                centers_l.append(flat[idx_l])
                contexts_l.append(flat[idx_l - o])
                right = ok[:-o] & same_doc  # center at idx-o, context idx
                idx_r = np.flatnonzero(right) + o
                centers_l.append(flat[idx_r - o])
                contexts_l.append(flat[idx_r])
            centers = np.concatenate(centers_l)
            contexts = np.concatenate(contexts_l)
            order = rng.permutation(len(centers))
            centers, contexts = centers[order], contexts[order]
            # minibatched SGNS: per batch one gathered matmul-free update
            # (einsum over (B, k+1, D)); np.add.at applies the scatter
            for s in range(0, len(centers), batch):
                c = centers[s:s + batch]
                pos_t = contexts[s:s + batch]
                B = len(c)
                negs = rng.choice(V, size=(B, self.negatives), p=neg_p)
                targets = np.concatenate([pos_t[:, None], negs], axis=1)
                labels = np.zeros((B, 1 + self.negatives), np.float32)
                labels[:, 0] = 1.0
                vin = W_in[c]                          # (B, D)
                vout = W_out[targets]                  # (B, m, D)
                # numerically stable sigmoid: exp only ever sees -|x|, so
                # huge logits (adversarial corpora drive dot products past
                # ±700 where exp overflows to inf) stay finite
                logits = np.einsum("bmd,bd->bm", vout, vin)
                ez = np.exp(-np.abs(logits))
                scores = np.where(logits >= 0, 1.0 / (1.0 + ez),
                                  ez / (1.0 + ez))
                g = (labels - scores) * lr             # (B, m)
                # no-NaN guarantee: a raw update is ≤ lr·‖v‖ — growth
                # MULTIPLICATIVE in the weight scale, so a huge
                # user-supplied lr turns wrong-direction saturation into
                # an exponential run to ±inf (whose 0·inf / inf−inf
                # products are where NaNs are born). An absolute ±1e3
                # per-element update clip (far above any useful gradient;
                # trained embeddings live at ‖v‖ ≲ 10) caps growth at
                # linear, keeping every value finite forever while never
                # binding during sane training.
                gin = np.clip(np.einsum("bm,bmd->bd", g, vout), -1e3, 1e3)
                gout = np.clip((g[:, :, None] * vin[:, None, :]).reshape(
                    -1, D), -1e3, 1e3)
                np.add.at(W_in, c, gin)
                np.add.at(W_out, targets.reshape(-1), gout)
        return Word2VecModel({w: W_in[i] for i, w in enumerate(vocab)}, D)


# --------------------------------------------------------------------------- #
# OpLDA — native batch variational EM                                         #
# --------------------------------------------------------------------------- #

class LDAModel(Transformer):
    """OPVector (term counts) → topic distribution via folded-in E-steps."""

    out_type = T.OPVector

    def __init__(self, topics: np.ndarray, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.topics = np.asarray(topics, dtype=np.float64)  # (k, V) normalized

    def host_prepare(self, cols: Sequence[Optional[Column]]):
        X = np.asarray(cols[0].data, dtype=np.float64)
        k = self.topics.shape[0]
        theta = np.full((X.shape[0], k), 1.0 / k)
        B = self.topics + 1e-12
        for _ in range(20):  # fixed-point E-step per doc batch
            # responsibility-weighted counts: theta ∝ sum_w x_w * p(z|w)
            denom = theta @ B + 1e-12                 # (n, V)
            theta_new = theta * ((X / denom) @ B.T)
            s = theta_new.sum(axis=1, keepdims=True)
            theta = np.where(s > 0, theta_new / np.maximum(s, 1e-12),
                             1.0 / k)
        return theta.astype(np.float32)

    def device_apply(self, enc, dev):
        import jax.numpy as jnp
        return jnp.asarray(enc)

    def output_meta(self) -> VectorMetadata:
        f = self.input_features[0]
        cols = tuple(VectorColumnMetadata(
            parent_name=f.name, parent_type=f.ftype.__name__,
            descriptor_value=f"topic_{j}")
            for j in range(self.topics.shape[0]))
        return VectorMetadata(self.output_name(), cols).with_indices()

    def get_params(self):
        return {"topics": self.topics.tolist()}


class OpLDA(Estimator):
    """OPVector (term counts) → k-topic mixture (OpLDA.scala:41 wrapping
    Spark LDA; native EM here: multinomial mixture with Dirichlet
    smoothing, which is LDA's MAP point estimate)."""

    in_types = (T.OPVector,)
    out_type = T.OPVector

    def __init__(self, k: int = 10, max_iter: int = 20, seed: int = 42,
                 uid: Optional[str] = None):
        super().__init__(uid=uid, k=k, max_iter=max_iter, seed=seed)
        self.k = int(k)
        self.max_iter = int(max_iter)
        self.seed = int(seed)

    def fit_model(self, cols: Sequence[Column], ctx: FitContext) -> Transformer:
        X = np.asarray(cols[0].data, dtype=np.float64)  # (n, V)
        n, V = X.shape
        rng = np.random.default_rng(self.seed)
        B = rng.random((self.k, V)) + 0.1
        B /= B.sum(axis=1, keepdims=True)
        theta = np.full((n, self.k), 1.0 / self.k)
        for _ in range(self.max_iter):
            denom = theta @ B + 1e-12                  # (n, V)
            R = X / denom                              # (n, V)
            theta = theta * (R @ B.T)
            theta /= np.maximum(theta.sum(axis=1, keepdims=True), 1e-12)
            B = B * ((theta.T @ R))                    # (k, V)
            B += 1.0 / V                               # Dirichlet smoothing
            B /= B.sum(axis=1, keepdims=True)
        return LDAModel(B)
