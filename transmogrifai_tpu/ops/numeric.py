"""Numeric vectorizers: impute + null-indicator encoding.

Reference parity: `core/.../feature/RealVectorizer.scala` (mean impute),
`IntegralVectorizer.scala` (mode impute), `BinaryVectorizer.scala`,
`RealNNVectorizer.scala` — the per-type defaults applied by
`Transmogrifier.transmogrify` (`Transmogrifier.scala:116-344`).

TPU-first: each vectorizer is an N-ary sequence estimator whose fit is a
single masked reduction over the stacked (n, F) batch — shardable over the
data axis with a `psum` — and whose transform is a pure jnp map that XLA
fuses with everything downstream.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from transmogrifai_tpu import types as T
from transmogrifai_tpu.data.columns import Column
from transmogrifai_tpu.data.metadata import (
    NULL_INDICATOR, VectorColumnMetadata, VectorMetadata)
from transmogrifai_tpu.stages.base import Estimator, FitContext, Transformer


def stack_scalar_dev(dev: Sequence) -> tuple:
    """Stack N scalar device pytrees into (n, F) value / mask arrays."""
    value = jnp.stack([d["value"] for d in dev], axis=1)
    mask = jnp.stack([d["mask"] for d in dev], axis=1)
    return value, mask


def _interleave(cols_per_feature: Sequence[Sequence[jnp.ndarray]]) -> jnp.ndarray:
    """Concat per-feature column groups into one (n, sum(widths)) vector."""
    flat = [c for group in cols_per_feature for c in group]
    return jnp.stack(flat, axis=1) if flat else jnp.zeros((0, 0), jnp.float32)


class _NumericModelBase(Transformer):
    """Fitted numeric vectorizer: fill + optional null-indicator columns."""

    out_type = T.OPVector

    def __init__(self, fill_values: Sequence[float], track_nulls: bool = True,
                 descriptor: Optional[str] = None, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.fill_values = np.asarray(fill_values, dtype=np.float32)
        self.track_nulls = track_nulls
        self.descriptor = descriptor

    def device_apply(self, enc, dev):
        groups = []
        for i, d in enumerate(dev):
            v, m = d["value"], d["mask"]
            filled = v * m + self.fill_values[i] * (1.0 - m)
            cols = [filled]
            if self.track_nulls:
                cols.append(1.0 - m)
            groups.append(cols)
        return _interleave(groups)

    def output_meta(self) -> VectorMetadata:
        cols: List[VectorColumnMetadata] = []
        for f in self.input_features:
            cols.append(VectorColumnMetadata(
                parent_name=f.name, parent_type=f.ftype.__name__,
                descriptor_value=self.descriptor))
            if self.track_nulls:
                cols.append(VectorColumnMetadata(
                    parent_name=f.name, parent_type=f.ftype.__name__,
                    indicator_value=NULL_INDICATOR))
        return VectorMetadata(self.output_name(), tuple(cols)).with_indices()

    def get_params(self):
        return {"fill_values": self.fill_values.tolist(),
                "track_nulls": self.track_nulls, "descriptor": self.descriptor}


class RealVectorizerModel(_NumericModelBase):
    pass


class RealVectorizer(Estimator):
    """N Real features → [imputed value, null indicator] per feature.

    fill_value: "mean" (default, RealVectorizer.scala) | "median" | float.
    """

    in_types = (T.Real, Ellipsis)
    out_type = T.OPVector

    def __init__(self, fill_value="mean", track_nulls: bool = True,
                 uid: Optional[str] = None):
        super().__init__(uid=uid, fill_value=fill_value, track_nulls=track_nulls)
        self.fill_value = fill_value
        self.track_nulls = track_nulls

    def fit_model(self, cols: Sequence[Column], ctx: FitContext) -> Transformer:
        dev = [c.device_value() for c in cols]
        value, mask = stack_scalar_dev(dev)
        if self.fill_value == "mean":
            denom = jnp.maximum(mask.sum(axis=0), 1.0)
            fills = np.asarray((value * mask).sum(axis=0) / denom)
        elif self.fill_value == "median":
            fills = []
            for c in cols:
                v = np.asarray(c.data["value"], dtype=np.float64)
                m = np.asarray(c.data["mask"])
                fills.append(float(np.median(v[m])) if m.any() else 0.0)
            fills = np.asarray(fills)
        else:
            fills = np.full(len(cols), float(self.fill_value))
        return RealVectorizerModel(fills, self.track_nulls)


class IntegralVectorizerModel(_NumericModelBase):
    pass


class IntegralVectorizer(Estimator):
    """N Integral features → [mode-imputed value, null indicator] each
    (IntegralVectorizer.scala fill-with-mode)."""

    in_types = (T.Integral, Ellipsis)
    out_type = T.OPVector

    def __init__(self, fill_value="mode", track_nulls: bool = True,
                 uid: Optional[str] = None):
        super().__init__(uid=uid, fill_value=fill_value, track_nulls=track_nulls)
        self.fill_value = fill_value
        self.track_nulls = track_nulls

    def fit_model(self, cols: Sequence[Column], ctx: FitContext) -> Transformer:
        fills = []
        for c in cols:
            if self.fill_value == "mode":
                v = np.asarray(c.data["value"])[np.asarray(c.data["mask"])]
                if v.size == 0:
                    fills.append(0.0)
                else:
                    vals, counts = np.unique(v, return_counts=True)
                    # ties broken by smallest value (np.unique sorts ascending)
                    fills.append(float(vals[np.argmax(counts)]))
            else:
                fills.append(float(self.fill_value))
        return IntegralVectorizerModel(np.asarray(fills), self.track_nulls)


class BinaryVectorizerModel(_NumericModelBase):
    pass


class BinaryVectorizer(Estimator):
    """N Binary features → [value (null→fill), null indicator] each
    (BinaryVectorizer.scala, fillValue default false)."""

    in_types = (T.Binary, Ellipsis)
    out_type = T.OPVector

    def __init__(self, fill_value: bool = False, track_nulls: bool = True,
                 uid: Optional[str] = None):
        super().__init__(uid=uid, fill_value=fill_value, track_nulls=track_nulls)
        self.fill_value = fill_value
        self.track_nulls = track_nulls

    def fit_model(self, cols: Sequence[Column], ctx: FitContext) -> Transformer:
        fills = np.full(len(cols), 1.0 if self.fill_value else 0.0)
        return BinaryVectorizerModel(fills, self.track_nulls)


class RealNNVectorizer(Transformer):
    """N RealNN features → identity stack (RealNNVectorizer.scala) —
    stateless, no nulls possible."""

    in_types = (T.RealNN, Ellipsis)
    out_type = T.OPVector

    def device_apply(self, enc, dev):
        return jnp.stack([d["value"] for d in dev], axis=1)

    def output_meta(self) -> VectorMetadata:
        cols = tuple(
            VectorColumnMetadata(parent_name=f.name, parent_type=f.ftype.__name__)
            for f in self.input_features)
        return VectorMetadata(self.output_name(), cols).with_indices()
