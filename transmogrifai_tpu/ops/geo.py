"""Geolocation vectorization.

Reference parity: `core/.../feature/GeolocationVectorizer.scala` —
lat/lon/accuracy triple with mean imputation + null indicator.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from transmogrifai_tpu import types as T
from transmogrifai_tpu.data.columns import Column
from transmogrifai_tpu.data.metadata import (
    NULL_INDICATOR, VectorColumnMetadata, VectorMetadata)
from transmogrifai_tpu.stages.base import Estimator, FitContext, Transformer


def _geo_arrays(col: Column):
    n = len(col.data)
    vals = np.zeros((n, 3), dtype=np.float32)
    mask = np.zeros(n, dtype=np.float32)
    for i, v in enumerate(col.data):
        if v is not None:
            vals[i] = v
            mask[i] = 1.0
    return vals, mask


class GeolocationModel(Transformer):
    out_type = T.OPVector

    def __init__(self, fills: Sequence[Sequence[float]], track_nulls: bool = True,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.fills = np.asarray(fills, dtype=np.float32)  # (F, 3)
        self.track_nulls = track_nulls

    def host_prepare(self, cols: Sequence[Optional[Column]]):
        return [_geo_arrays(c) for c in cols]

    def device_apply(self, enc, dev):
        parts = []
        for i, (vals, mask) in enumerate(enc):
            v = jnp.asarray(vals)
            m = jnp.asarray(mask)[:, None]
            filled = v * m + self.fills[i][None, :] * (1.0 - m)
            parts.append(filled)
            if self.track_nulls:
                parts.append(1.0 - m)
        return jnp.concatenate(parts, axis=1)

    def output_meta(self) -> VectorMetadata:
        cols: List[VectorColumnMetadata] = []
        for f in self.input_features:
            for d in ("lat", "lon", "accuracy"):
                cols.append(VectorColumnMetadata(
                    parent_name=f.name, parent_type=f.ftype.__name__,
                    descriptor_value=d))
            if self.track_nulls:
                cols.append(VectorColumnMetadata(
                    parent_name=f.name, parent_type=f.ftype.__name__,
                    indicator_value=NULL_INDICATOR))
        return VectorMetadata(self.output_name(), tuple(cols)).with_indices()

    def get_params(self):
        return {"fills": self.fills.tolist(), "track_nulls": self.track_nulls}


class GeolocationVectorizer(Estimator):
    """N Geolocation features → [lat, lon, acc (mean-imputed), null] each."""

    in_types = (T.Geolocation, Ellipsis)
    out_type = T.OPVector

    def __init__(self, track_nulls: bool = True, uid: Optional[str] = None):
        super().__init__(uid=uid, track_nulls=track_nulls)
        self.track_nulls = track_nulls

    def fit_model(self, cols: Sequence[Column], ctx: FitContext) -> Transformer:
        fills = []
        for c in cols:
            vals, mask = _geo_arrays(c)
            denom = max(float(mask.sum()), 1.0)
            fills.append((vals * mask[:, None]).sum(axis=0) / denom)
        return GeolocationModel(np.asarray(fills), self.track_nulls)
