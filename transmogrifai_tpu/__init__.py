"""transmogrifai_tpu — a TPU-native AutoML framework for structured data.

A ground-up JAX/XLA re-design of the capabilities of TransmogrifAI
(Scala/Spark reference surveyed in SURVEY.md): typed features over records,
a lazy stage DAG, automated type-driven feature engineering, automated
feature validation, cross-validated model selection swept across a TPU
device mesh, model insights, and save/load plus batch/streaming/local
scoring — all compiling to fused XLA programs.

Quickstart (mirrors reference README.md:31-61):

    import transmogrifai_tpu as op

    ds = op.Dataset.from_csv("titanic.csv")
    features, label = op.FeatureBuilder.from_dataset(ds, response="survived")
    checked = op.transmogrify(features).sanity_check(label)
    pred = op.BinaryClassificationModelSelector.with_cross_validation() \\
             .set_input(label, checked).get_output()
    model = op.Workflow().set_result_features(pred).set_input_dataset(ds).train()
    scores = model.score(ds)
"""

from transmogrifai_tpu.utils.uid import UID
from transmogrifai_tpu.utils.fnser import extract_fn  # noqa: F401 — stable extract-fn names
from transmogrifai_tpu.aggregators import CutOffTime, Event
from transmogrifai_tpu.readers import DataReaders
from transmogrifai_tpu.types import *  # noqa: F401,F403 — the feature type lattice
from transmogrifai_tpu import dsl  # noqa: F401 — attaches rich methods to Feature

__version__ = "0.1.0"
