"""Isotonic regression calibrator (pool-adjacent-violators).

Reference parity: `core/.../impl/regression/IsotonicRegressionCalibrator.scala`
(Spark IsotonicRegression). PAV runs on host (inherently sequential);
the fitted model is a device-side piecewise-linear interpolation
(`jnp.interp`) that fuses into the scoring program.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from transmogrifai_tpu import types as T
from transmogrifai_tpu.data.columns import Column
from transmogrifai_tpu.stages.base import Estimator, FitContext, Transformer


def pav_fit(x: np.ndarray, y: np.ndarray, w: Optional[np.ndarray] = None,
            increasing: bool = True):
    """Pool-adjacent-violators → (boundaries, values) knots."""
    order = np.argsort(x, kind="mergesort")
    xs, ys = x[order], y[order].astype(np.float64)
    ws = (np.ones_like(ys) if w is None else w[order]).astype(np.float64)
    if not increasing:
        ys = -ys
    # blocks as (weighted mean, weight, start_idx)
    means: List[float] = []
    weights: List[float] = []
    starts: List[int] = []
    for i in range(len(ys)):
        means.append(ys[i])
        weights.append(ws[i])
        starts.append(i)
        while len(means) > 1 and means[-2] > means[-1]:
            m2, w2 = means.pop(), weights.pop()
            starts.pop()
            means[-1] = (means[-1] * weights[-1] + m2 * w2) / (weights[-1] + w2)
            weights[-1] += w2
        # starts[-1] stays at the merged block's first index
    bounds, values = [], []
    for bi, s in enumerate(starts):
        e = starts[bi + 1] - 1 if bi + 1 < len(starts) else len(xs) - 1
        v = means[bi] if increasing else -means[bi]
        bounds.extend([xs[s], xs[e]])
        values.extend([v, v])
    return np.asarray(bounds, dtype=np.float64), np.asarray(values, dtype=np.float64)


class IsotonicCalibratorModel(Transformer):
    out_type = T.RealNN

    def __init__(self, boundaries: Sequence[float], values: Sequence[float],
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.boundaries = np.asarray(boundaries, dtype=np.float32)
        self.values = np.asarray(values, dtype=np.float32)

    def device_apply(self, enc, dev):
        score = dev[-1]["value"]
        cal = jnp.interp(score, jnp.asarray(self.boundaries),
                         jnp.asarray(self.values))
        return {"value": cal, "mask": dev[-1]["mask"]}

    # parameter lifting: the PAV step table can reach 2·n_blocks entries
    # — per-tenant state, not program state (serving/fleet.py). No
    # narrow variant: `jnp.interp` needs strictly ordered boundaries and
    # f16 rounding could collapse adjacent steps.
    def device_constants(self):
        return {"boundaries": jnp.asarray(self.boundaries),
                "values": jnp.asarray(self.values)}

    def device_apply_with(self, consts, enc, dev):
        cal = jnp.interp(dev[-1]["value"], consts["boundaries"],
                         consts["values"])
        return {"value": cal, "mask": dev[-1]["mask"]}

    def signature_params(self):
        return {}

    def get_params(self):
        return {"boundaries": self.boundaries.tolist(),
                "values": self.values.tolist()}


class IsotonicRegressionCalibrator(Estimator):
    """BinaryEstimator(RealNN label, RealNN score) → calibrated RealNN."""

    in_types = (T.RealNN, T.RealNN)
    out_type = T.RealNN

    def __init__(self, increasing: bool = True, uid: Optional[str] = None):
        super().__init__(uid=uid, increasing=increasing)
        self.increasing = increasing

    def fit_model(self, cols: Sequence[Column], ctx: FitContext) -> Transformer:
        label, score = cols
        y = np.asarray(label.data["value"], dtype=np.float64)
        x = np.asarray(score.data["value"], dtype=np.float64)
        bounds, values = pav_fit(x, y, increasing=self.increasing)
        return IsotonicCalibratorModel(bounds, values)
