"""Multilayer perceptron classifier.

Reference parity: `core/.../impl/classification/OpMultilayerPerceptronClassifier.scala`
(Spark MLP: sigmoid hidden layers, softmax output, full-batch L-BFGS).

TPU-first: fixed-epoch full-batch Adam inside a `lax.scan` (static shapes,
vmappable over hyperparams/folds); every layer is an MXU matmul.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from transmogrifai_tpu.models.base import (
    PredictionModel, PredictorEstimator, infer_n_classes)
from transmogrifai_tpu.stages.base import FitContext


def _init_params(layers: Tuple[int, ...], key) -> List[Dict]:
    params = []
    for i in range(len(layers) - 1):
        key, sub = jax.random.split(key)
        fan_in = layers[i]
        params.append({
            "W": jax.random.normal(sub, (layers[i], layers[i + 1]),
                                   jnp.float32) / jnp.sqrt(fan_in),
            "b": jnp.zeros((layers[i + 1],), jnp.float32)})
    return params


def _forward(params: List[Dict], X: jnp.ndarray) -> jnp.ndarray:
    h = X
    for layer in params[:-1]:
        h = jax.nn.sigmoid(h @ layer["W"] + layer["b"])
    last = params[-1]
    return h @ last["W"] + last["b"]  # logits


@partial(jax.jit, static_argnames=("layers", "max_iter"))
def fit_mlp(X: jnp.ndarray, y: jnp.ndarray, w: jnp.ndarray,
            layers: Tuple[int, ...], max_iter: int = 200,
            learning_rate: float = 0.05, seed: int = 0) -> List[Dict]:
    k = layers[-1]
    oh = jax.nn.one_hot(y.astype(jnp.int32), k)
    params = _init_params(layers, jax.random.PRNGKey(seed))

    def loss_fn(p):
        logits = _forward(p, X)
        ll = optax.softmax_cross_entropy(logits, oh)
        return (ll * w).sum() / jnp.maximum(w.sum(), 1.0)

    opt = optax.adam(learning_rate)
    state = opt.init(params)

    def step(carry, _):
        p, s = carry
        v, g = jax.value_and_grad(loss_fn)(p)
        updates, s = opt.update(g, s)
        return (optax.apply_updates(p, updates), s), v

    (params, _), _ = jax.lax.scan(step, (params, state), None, length=max_iter)
    return params


def predict_mlp(params: List[Dict], X: jnp.ndarray) -> Dict[str, jnp.ndarray]:
    logits = _forward(params, X)
    return {"prediction": jnp.argmax(logits, -1).astype(jnp.float32),
            "rawPrediction": logits,
            "probability": jax.nn.softmax(logits, -1)}


class MLPModel(PredictionModel):
    def __init__(self, weights=None, uid: Optional[str] = None):
        super().__init__(uid=uid)
        # weights: list of {"W": 2d list, "b": 1d list}
        self.weights = [
            {"W": np.asarray(l["W"], dtype=np.float32),
             "b": np.asarray(l["b"], dtype=np.float32)} for l in weights]

    def predict_arrays(self, X):
        params = [{"W": jnp.asarray(l["W"]), "b": jnp.asarray(l["b"])}
                  for l in self.weights]
        return predict_mlp(params, X)

    # parameter lifting: see LinearRegressionModel — the layer count and
    # widths key the program via the consts structure digest
    def device_constants(self):
        return {"layers": [
            {"W": jnp.asarray(l["W"]), "b": jnp.asarray(l["b"])}
            for l in self.weights]}

    def device_apply_with(self, consts, enc, dev):
        return predict_mlp(consts["layers"], jnp.asarray(dev[-1]))

    def signature_params(self):
        return {}

    def narrow_device_constants(self, consts):
        return {"layers": [
            {"W": l["W"].astype(jnp.bfloat16), "b": l["b"]}
            for l in consts["layers"]]}

    def get_params(self):
        return {"weights": [
            {"W": l["W"].tolist(), "b": l["b"].tolist()} for l in self.weights]}


class OpMultilayerPerceptronClassifier(PredictorEstimator):
    """hidden_layers e.g. (10, 10); input/output sizes are inferred."""

    def __init__(self, hidden_layers: Sequence[int] = (10,),
                 max_iter: int = 200, learning_rate: float = 0.05,
                 n_classes: Optional[int] = None, uid: Optional[str] = None):
        super().__init__(uid=uid, hidden_layers=list(hidden_layers),
                         max_iter=max_iter, learning_rate=learning_rate,
                         n_classes=n_classes)
        self.hidden_layers = tuple(hidden_layers)
        self.max_iter = max_iter
        self.learning_rate = learning_rate
        self.n_classes = n_classes

    def fit_arrays(self, X, y, w, ctx: FitContext) -> MLPModel:
        k = self.n_classes or infer_n_classes(np.asarray(y))
        layers = (int(X.shape[1]),) + self.hidden_layers + (k,)
        params = fit_mlp(X, y, w, layers, self.max_iter,
                         self.learning_rate, ctx.seed)
        return MLPModel([{"W": np.asarray(l["W"]), "b": np.asarray(l["b"])}
                         for l in params])
