"""Multinomial logistic regression, L-BFGS-optimized, vmappable.

Reference parity: `core/.../impl/classification/OpLogisticRegression.scala`
(wrapping Spark MLlib LogisticRegression, itself L-BFGS/OWL-QN).

TPU-first: the fit is a fixed-length `lax.scan` of optax L-BFGS steps over
the full batch — static shapes, no data-dependent control flow — so the
sweep engine can `vmap` it over hyperparameters and fold masks and `pjit`
the batch dimension over the mesh. bfloat16 is deliberately NOT used for
the optimizer state (convergence); X enters as f32 and the dominant cost
(X @ W) hits the MXU.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from transmogrifai_tpu.models.base import (
    PredictionModel, PredictorEstimator, infer_n_classes)
from transmogrifai_tpu.stages.base import FitContext


def logreg_loss(params: Dict, X: jnp.ndarray, y_onehot: jnp.ndarray,
                w: jnp.ndarray, l2: jnp.ndarray) -> jnp.ndarray:
    logits = X @ params["W"] + params["b"]
    ll = optax.softmax_cross_entropy(logits, y_onehot)
    wsum = jnp.maximum(w.sum(), 1.0)
    return (ll * w).sum() / wsum + 0.5 * l2 * (params["W"] ** 2).sum()


@partial(jax.jit, static_argnames=("n_classes", "max_iter"))
def fit_logreg(X: jnp.ndarray, y: jnp.ndarray, w: jnp.ndarray,
               l2, n_classes: int, max_iter: int = 100) -> Dict:
    """Pure fit: (n,d), (n,), (n,), scalar l2 → {"W": (d,k), "b": (k,)}.

    vmap over `l2` and/or `w` to sweep grids × folds in one program.
    """
    d = X.shape[1]
    y_onehot = jax.nn.one_hot(y.astype(jnp.int32), n_classes, dtype=jnp.float32)
    params = {"W": jnp.zeros((d, n_classes), jnp.float32),
              "b": jnp.zeros((n_classes,), jnp.float32)}
    loss_fn = lambda p: logreg_loss(p, X, y_onehot, w, l2)  # noqa: E731
    opt = optax.lbfgs()
    state = opt.init(params)
    value_and_grad = optax.value_and_grad_from_state(loss_fn)

    def step(carry, _):
        p, s = carry
        value, grad = value_and_grad(p, state=s)
        updates, s = opt.update(grad, s, p, value=value, grad=grad,
                                value_fn=loss_fn)
        p = optax.apply_updates(p, updates)
        return (p, s), value

    (params, _), _ = jax.lax.scan(step, (params, state), None, length=max_iter)
    return params


def predict_logreg(params: Dict, X: jnp.ndarray) -> Dict[str, jnp.ndarray]:
    logits = X @ params["W"] + params["b"]
    prob = jax.nn.softmax(logits, axis=-1)
    return {
        "prediction": jnp.argmax(logits, axis=-1).astype(jnp.float32),
        "rawPrediction": logits,
        "probability": prob,
    }


class LogisticRegressionModel(PredictionModel):
    def __init__(self, W=None, b=None, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.W = np.asarray(W, dtype=np.float32)
        self.b = np.asarray(b, dtype=np.float32)

    def predict_arrays(self, X):
        return predict_logreg({"W": jnp.asarray(self.W), "b": jnp.asarray(self.b)}, X)

    def get_params(self):
        return {"W": self.W.tolist(), "b": self.b.tolist()}


class OpLogisticRegression(PredictorEstimator):
    """Grid-sweepable hyperparams: reg_param (L2), max_iter."""

    def __init__(self, reg_param: float = 0.0, max_iter: int = 100,
                 n_classes: Optional[int] = None, uid: Optional[str] = None):
        super().__init__(uid=uid, reg_param=reg_param, max_iter=max_iter,
                         n_classes=n_classes)
        self.reg_param = reg_param
        self.max_iter = max_iter
        self.n_classes = n_classes

    # pure fns exposed for the sweep engine
    fit_fn = staticmethod(fit_logreg)
    predict_fn = staticmethod(predict_logreg)

    def fit_arrays(self, X, y, w, ctx: FitContext) -> LogisticRegressionModel:
        k = self.n_classes or infer_n_classes(np.asarray(y))
        params = fit_logreg(X, y, w, jnp.float32(self.reg_param), k,
                            self.max_iter)
        return LogisticRegressionModel(np.asarray(params["W"]),
                                       np.asarray(params["b"]))
