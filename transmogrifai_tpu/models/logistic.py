"""Multinomial logistic regression, L-BFGS-optimized, vmappable.

Reference parity: `core/.../impl/classification/OpLogisticRegression.scala`
(wrapping Spark MLlib LogisticRegression, itself L-BFGS/OWL-QN).

TPU-first: the fit is a fixed-length `lax.scan` of optax L-BFGS steps over
the full batch — static shapes, no data-dependent control flow — so the
sweep engine can `vmap` it over hyperparameters and fold masks and `pjit`
the batch dimension over the mesh. bfloat16 is deliberately NOT used for
the optimizer state (convergence); X enters as f32 and the dominant cost
(X @ W) hits the MXU.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from transmogrifai_tpu.models.base import (
    PredictionModel, PredictorEstimator, infer_n_classes,
    resolve_init_params)
from transmogrifai_tpu.stages.base import FitContext


def logreg_loss(params: Dict, X: jnp.ndarray, y_onehot: jnp.ndarray,
                w: jnp.ndarray, l2: jnp.ndarray) -> jnp.ndarray:
    logits = X @ params["W"] + params["b"]
    ll = optax.softmax_cross_entropy(logits, y_onehot)
    wsum = jnp.maximum(w.sum(), 1.0)
    return (ll * w).sum() / wsum + 0.5 * l2 * (params["W"] ** 2).sum()


@partial(jax.jit, static_argnames=("n_classes", "max_iter"))
def fit_logreg(X: jnp.ndarray, y: jnp.ndarray, w: jnp.ndarray,
               l2, n_classes: int, max_iter: int = 100,
               init_params: Optional[Dict] = None) -> Dict:
    """Pure fit: (n,d), (n,), (n,), scalar l2 → {"W": (d,k), "b": (k,)}.

    vmap over `l2` and/or `w` to sweep grids × folds in one program.

    `init_params` ({"W", "b"}) warm-starts the optimizer from existing
    weights (the continual-refit path): on barely-shifted data L-BFGS
    starts inside the basin and converges in a fraction of the cold
    iteration budget. Passed as traced arrays, so repeated warm refits
    at fixed shapes reuse ONE compiled program (retrace-asserted in
    tests); the cold (None) form keeps its own cache entry.
    """
    d = X.shape[1]
    y_onehot = jax.nn.one_hot(y.astype(jnp.int32), n_classes, dtype=jnp.float32)
    if init_params is None:
        params = {"W": jnp.zeros((d, n_classes), jnp.float32),
                  "b": jnp.zeros((n_classes,), jnp.float32)}
    else:
        params = {"W": jnp.asarray(init_params["W"], jnp.float32),
                  "b": jnp.asarray(init_params["b"], jnp.float32)}
    loss_fn = lambda p: logreg_loss(p, X, y_onehot, w, l2)  # noqa: E731
    opt = optax.lbfgs()
    state = opt.init(params)
    value_and_grad = optax.value_and_grad_from_state(loss_fn)

    def step(carry, _):
        p, s = carry
        value, grad = value_and_grad(p, state=s)
        updates, s = opt.update(grad, s, p, value=value, grad=grad,
                                value_fn=loss_fn)
        p = optax.apply_updates(p, updates)
        return (p, s), value

    (params, _), _ = jax.lax.scan(step, (params, state), None, length=max_iter)
    return params


def _power_lipschitz(X: jnp.ndarray, w: jnp.ndarray, wsum: jnp.ndarray,
                     iters: int = 16) -> jnp.ndarray:
    """λmax(Xᵀ diag(w) X)/wsum via power iteration — two MXU matmuls per
    step, fully traceable (no eigendecomposition on device)."""
    d = X.shape[1]
    v = jnp.full((d,), 1.0 / jnp.sqrt(jnp.float32(d)), X.dtype)

    def step(v, _):
        u = X.T @ (w * (X @ v))
        nrm = jnp.linalg.norm(u)
        return u / jnp.maximum(nrm, 1e-12), nrm

    _, norms = jax.lax.scan(step, v, None, length=iters)
    return norms[-1] / wsum


@partial(jax.jit, static_argnames=("n_classes", "max_iter"))
def fit_logreg_enet(X: jnp.ndarray, y: jnp.ndarray, w: jnp.ndarray,
                    l1, l2, n_classes: int, max_iter: int = 200,
                    init_params: Optional[Dict] = None) -> Dict:
    """Elastic-net multinomial logistic regression via FISTA.

    Spark parity: MLlib LR's penalty is
    `regParam * (α·||W||₁ + (1−α)/2·||W||₂²)` solved with OWL-QN
    (`DefaultSelectorParams.scala:48` sweeps elasticNetParam {0.1, 0.5});
    callers pass `l1 = reg·α`, `l2 = reg·(1−α)`. OWL-QN's orthant
    bookkeeping maps poorly to fixed-shape XLA, so the TPU build uses
    accelerated proximal gradient (FISTA): the smooth part (weighted CE +
    L2) advances with a Lipschitz step from power iteration, and the L1
    prox is a soft-threshold — every op is dense, so the whole fit vmaps
    over (l1, l2) grid vectors and fold-weight rows like `fit_logreg`.
    Bias is unpenalized. l1 and l2 may be traced scalars.
    """
    y_onehot = jax.nn.one_hot(y.astype(jnp.int32), n_classes,
                              dtype=jnp.float32)
    d = X.shape[1]
    wsum = jnp.maximum(w.sum(), 1.0)
    # softmax-CE Hessian ≼ 0.5·XᵀWX/wsum (+ l2) — diag(p) − ppᵀ has
    # eigenvalues ≤ 1/2 (the binary-sigmoid bound 0.25 under-estimates L
    # for the multinomial loss and voids FISTA's 1/L step guarantee);
    # 1.05 head-room for the power-iteration tail
    L = 0.5 * 1.05 * _power_lipschitz(X, w, wsum) + l2 + 1e-8
    step = 1.0 / L

    def smooth_grads(W, b):
        p = jax.nn.softmax(X @ W + b)
        R = (p - y_onehot) * w[:, None]        # (n, k) weighted residual
        return X.T @ R / wsum + l2 * W, R.sum(0) / wsum

    def fista_step(carry, _):
        W, b, Wm, bm, t = carry
        gW, gb = smooth_grads(Wm, bm)
        W1 = Wm - step * gW
        W1 = jnp.sign(W1) * jnp.maximum(jnp.abs(W1) - step * l1, 0.0)
        b1 = bm - step * gb
        t1 = 0.5 * (1.0 + jnp.sqrt(1.0 + 4.0 * t * t))
        beta = (t - 1.0) / t1
        return (W1, b1, W1 + beta * (W1 - W), b1 + beta * (b1 - b), t1), None

    if init_params is None:
        W0 = jnp.zeros((d, n_classes), jnp.float32)
        b0 = jnp.zeros((n_classes,), jnp.float32)
    else:  # warm start: FISTA momentum restarts from the given weights
        W0 = jnp.asarray(init_params["W"], jnp.float32)
        b0 = jnp.asarray(init_params["b"], jnp.float32)
    (W, b, _, _, _), _ = jax.lax.scan(
        fista_step, (W0, b0, W0, b0, jnp.float32(1.0)), None, length=max_iter)
    return {"W": W, "b": b}


def predict_logreg(params: Dict, X: jnp.ndarray) -> Dict[str, jnp.ndarray]:
    logits = X @ params["W"] + params["b"]
    prob = jax.nn.softmax(logits, axis=-1)
    return {
        "prediction": jnp.argmax(logits, axis=-1).astype(jnp.float32),
        "rawPrediction": logits,
        "probability": prob,
    }


class LogisticRegressionModel(PredictionModel):
    def __init__(self, W=None, b=None, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.W = np.asarray(W, dtype=np.float32)
        self.b = np.asarray(b, dtype=np.float32)

    def predict_arrays(self, X):
        return predict_logreg({"W": jnp.asarray(self.W), "b": jnp.asarray(self.b)}, X)

    # parameter lifting: see LinearRegressionModel — weights are traced
    # jit arguments, so same-shaped LR tenants share one bucket program
    def device_constants(self):
        return {"W": jnp.asarray(self.W), "b": jnp.asarray(self.b)}

    def device_apply_with(self, consts, enc, dev):
        return predict_logreg(consts, jnp.asarray(dev[-1]))

    def signature_params(self):
        return {}

    def narrow_device_constants(self, consts):
        return {"W": consts["W"].astype(jnp.bfloat16), "b": consts["b"]}

    def get_params(self):
        return {"W": self.W.tolist(), "b": self.b.tolist()}


def enet_iters(max_iter: int) -> int:
    """FISTA iteration budget for an L-BFGS-equivalent `max_iter`: first-
    order prox steps need more iterations than quasi-Newton ones to reach
    the same region (O(1/k²) vs superlinear), so the elastic-net path runs
    4× the L-BFGS budget with a floor of 200."""
    return max(200, 4 * int(max_iter))


class OpLogisticRegression(PredictorEstimator):
    """Grid-sweepable hyperparams: reg_param, elastic_net_param, max_iter.

    Spark parity (`OpLogisticRegression.scala`, elasticNetParam): the
    penalty is `reg_param * (α·L1 + (1−α)/2·L2)`; α = 0 keeps the pure-L2
    L-BFGS path, α > 0 switches to the FISTA elastic-net fit."""

    def __init__(self, reg_param: float = 0.0, max_iter: int = 100,
                 elastic_net_param: float = 0.0,
                 n_classes: Optional[int] = None, uid: Optional[str] = None):
        super().__init__(uid=uid, reg_param=reg_param, max_iter=max_iter,
                         elastic_net_param=elastic_net_param,
                         n_classes=n_classes)
        self.reg_param = reg_param
        self.max_iter = max_iter
        self.elastic_net_param = elastic_net_param
        self.n_classes = n_classes

    # pure fns exposed for the sweep engine
    fit_fn = staticmethod(fit_logreg)
    predict_fn = staticmethod(predict_logreg)

    def fit_arrays(self, X, y, w, ctx: FitContext,
                   init_params: Optional[Dict] = None
                   ) -> LogisticRegressionModel:
        k = self.n_classes or infer_n_classes(np.asarray(y))
        warm = resolve_init_params(self, init_params,
                                   {"W": (X.shape[1], k), "b": (k,)})
        alpha = float(self.elastic_net_param)
        if alpha > 0.0:
            params = fit_logreg_enet(
                X, y, w, jnp.float32(self.reg_param * alpha),
                jnp.float32(self.reg_param * (1.0 - alpha)), k,
                enet_iters(self.max_iter), init_params=warm)
        else:
            params = fit_logreg(X, y, w, jnp.float32(self.reg_param), k,
                                self.max_iter, init_params=warm)
        return LogisticRegressionModel(np.asarray(params["W"]),
                                       np.asarray(params["b"]))
