from transmogrifai_tpu.models.base import PredictorEstimator, PredictionModel
from transmogrifai_tpu.models.logistic import OpLogisticRegression, LogisticRegressionModel
from transmogrifai_tpu.models.linear import OpLinearRegression, LinearRegressionModel

__all__ = [
    "PredictorEstimator", "PredictionModel",
    "OpLogisticRegression", "LogisticRegressionModel",
    "OpLinearRegression", "LinearRegressionModel",
]
