from transmogrifai_tpu.models.base import PredictorEstimator, PredictionModel
from transmogrifai_tpu.models.logistic import OpLogisticRegression, LogisticRegressionModel
from transmogrifai_tpu.models.linear import OpLinearRegression, LinearRegressionModel
from transmogrifai_tpu.models.naive_bayes import OpNaiveBayes, NaiveBayesModel
from transmogrifai_tpu.models.linear_svc import OpLinearSVC, LinearSVCModel
from transmogrifai_tpu.models.mlp import (
    OpMultilayerPerceptronClassifier, MLPModel)
from transmogrifai_tpu.models.glm import (
    OpGeneralizedLinearRegression, GLMModel)
from transmogrifai_tpu.models.isotonic import (
    IsotonicRegressionCalibrator, IsotonicCalibratorModel)
from transmogrifai_tpu.models.trees import (
    OpDecisionTreeClassifier, OpDecisionTreeRegressor,
    OpRandomForestClassifier, OpRandomForestRegressor,
    OpGBTClassifier, OpGBTRegressor,
    OpXGBoostClassifier, OpXGBoostRegressor)

__all__ = [
    "PredictorEstimator", "PredictionModel",
    "OpLogisticRegression", "LogisticRegressionModel",
    "OpLinearRegression", "LinearRegressionModel",
    "OpNaiveBayes", "NaiveBayesModel",
    "OpLinearSVC", "LinearSVCModel",
    "OpMultilayerPerceptronClassifier", "MLPModel",
    "OpGeneralizedLinearRegression", "GLMModel",
    "IsotonicRegressionCalibrator", "IsotonicCalibratorModel",
    "OpDecisionTreeClassifier", "OpDecisionTreeRegressor",
    "OpRandomForestClassifier", "OpRandomForestRegressor",
    "OpGBTClassifier", "OpGBTRegressor",
    "OpXGBoostClassifier", "OpXGBoostRegressor",
]
