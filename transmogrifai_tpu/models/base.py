"""Predictor base classes: Estimator2(RealNN, OPVector) → Prediction.

Reference parity: `core/.../sparkwrappers/specific/OpPredictorWrapper.scala:71-121`
and `OpPredictionModel` — but instead of wrapping Spark MLlib, every model
here is a pair of pure jnp functions:

    fit_fn(X, y, w, hyper)   -> params      (jit/vmap-able)
    predict_fn(params, X)    -> prediction pytree

`w` is a per-row weight vector — the single mechanism behind fold masking,
class balancing, and train/holdout splits in the sweep engine: k-fold CV
vmaps `fit_fn` over stacked weight masks so every fold×grid fit is one XLA
program on the mesh (SURVEY.md §3.3 north star).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from transmogrifai_tpu import types as T
from transmogrifai_tpu.data.columns import Column
from transmogrifai_tpu.stages.base import Estimator, FitContext, Transformer


class PredictionModel(Transformer):
    """Fitted predictor: device_apply returns the Prediction pytree."""

    out_type = T.Prediction
    response_aware = True  # inputs are (label, features)

    def predict_arrays(self, X: jnp.ndarray) -> Dict[str, jnp.ndarray]:
        raise NotImplementedError

    def device_apply(self, enc, dev):
        X = dev[-1]  # inputs are (label, features); label unused at transform
        return self.predict_arrays(jnp.asarray(X))


class PredictorEstimator(Estimator):
    """Base for model estimators. Subclasses implement `fit_arrays`.

    `init_params` (attribute, or the `init_params=` kwarg the iterative
    families' `fit_arrays` accept) warm-starts the optimizer from an
    existing model's weights — the continual-refit path: a refit on
    appended data continues from the serving model instead of from
    zeros. Families where it is meaningless (closed-form solves) ignore
    it; the sweep engine never sets it (grid fits stay cold and
    comparable)."""

    in_types = (T.RealNN, T.OPVector)
    out_type = T.Prediction
    response_aware = True  # slot 0 is the label
    init_params: Optional[Dict[str, Any]] = None

    def fit_arrays(self, X: jnp.ndarray, y: jnp.ndarray, w: jnp.ndarray,
                   ctx: FitContext) -> PredictionModel:
        raise NotImplementedError

    def fit_model(self, cols: Sequence[Column], ctx: FitContext) -> Transformer:
        label, vec = cols
        y = jnp.asarray(np.asarray(label.data["value"], dtype=np.float32))
        X = jnp.asarray(vec.device_value())
        w = jnp.ones_like(y)
        return self.fit_arrays(X, y, w, ctx)


def infer_n_classes(y: np.ndarray) -> int:
    """Label cardinality for classification (labels must be 0..k-1)."""
    k = int(np.asarray(y).max(initial=0)) + 1
    return max(k, 2)


def resolve_init_params(est: PredictorEstimator,
                        explicit: Optional[Dict[str, Any]],
                        expect_shapes: Dict[str, tuple]
                        ) -> Optional[Dict[str, jnp.ndarray]]:
    """Warm-start weights for a fit: the explicit `init_params=` kwarg
    wins over the estimator's `init_params` attribute. Shapes are
    validated HERE, on host, against the incoming data — a refit whose
    feature width changed (an upstream vectorizer re-fit differently)
    must fail with a clear message, not a mid-trace XLA shape error."""
    warm = explicit if explicit is not None else est.init_params
    if warm is None:
        return None
    out: Dict[str, jnp.ndarray] = {}
    for name, shape in expect_shapes.items():
        if name not in warm:
            raise ValueError(
                f"{type(est).__name__}: init_params missing {name!r} "
                f"(have {sorted(warm)})")
        arr = jnp.asarray(warm[name], jnp.float32)
        if tuple(arr.shape) != tuple(shape):
            raise ValueError(
                f"{type(est).__name__}: init_params[{name!r}] shape "
                f"{tuple(arr.shape)} does not match the data "
                f"({tuple(shape)}) — warm start requires an unchanged "
                f"feature/class layout; refit cold instead")
        out[name] = arr
    return out
