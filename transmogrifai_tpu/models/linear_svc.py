"""Linear SVC (binary, squared-hinge + L2, L-BFGS).

Reference parity: `core/.../impl/classification/OpLinearSVC.scala` (Spark
LinearSVC: hinge + OWLQN). Squared hinge keeps the objective smooth for
L-BFGS; decision behavior matches at the margin sign. No calibrated
probabilities in Spark's LinearSVC either — we expose sigmoid(margin) so
ranking metrics (AuROC/AuPR) still work.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from transmogrifai_tpu.models.base import PredictionModel, PredictorEstimator
from transmogrifai_tpu.stages.base import FitContext


@partial(jax.jit, static_argnames=("max_iter",))
def fit_linear_svc(X: jnp.ndarray, y: jnp.ndarray, w: jnp.ndarray, l2,
                   max_iter: int = 100) -> Dict:
    d = X.shape[1]
    ypm = 2.0 * y - 1.0  # {0,1} → {-1,+1}
    params = {"beta": jnp.zeros((d,), jnp.float32), "b": jnp.float32(0.0)}

    def loss_fn(p):
        margin = X @ p["beta"] + p["b"]
        hinge = jnp.maximum(0.0, 1.0 - ypm * margin) ** 2
        return (hinge * w).sum() / jnp.maximum(w.sum(), 1.0) \
            + 0.5 * l2 * (p["beta"] ** 2).sum()

    opt = optax.lbfgs()
    state = opt.init(params)
    vg = optax.value_and_grad_from_state(loss_fn)

    def step(carry, _):
        p, s = carry
        v, g = vg(p, state=s)
        updates, s = opt.update(g, s, p, value=v, grad=g, value_fn=loss_fn)
        return (optax.apply_updates(p, updates), s), v

    (params, _), _ = jax.lax.scan(step, (params, state), None, length=max_iter)
    return params


def predict_linear_svc(params: Dict, X: jnp.ndarray) -> Dict[str, jnp.ndarray]:
    margin = X @ params["beta"] + params["b"]
    raw = jnp.stack([-margin, margin], axis=1)
    p1 = jax.nn.sigmoid(margin)
    return {
        "prediction": (margin > 0).astype(jnp.float32),
        "rawPrediction": raw,
        "probability": jnp.stack([1.0 - p1, p1], axis=1),
    }


class LinearSVCModel(PredictionModel):
    def __init__(self, beta=None, b: float = 0.0, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.beta = np.asarray(beta, dtype=np.float32)
        self.b = float(b)

    def predict_arrays(self, X):
        return predict_linear_svc(
            {"beta": jnp.asarray(self.beta), "b": jnp.float32(self.b)}, X)

    # parameter lifting: see LinearRegressionModel
    def device_constants(self):
        return {"beta": jnp.asarray(self.beta), "b": jnp.float32(self.b)}

    def device_apply_with(self, consts, enc, dev):
        return predict_linear_svc(consts, jnp.asarray(dev[-1]))

    def signature_params(self):
        return {}

    def narrow_device_constants(self, consts):
        return {"beta": consts["beta"].astype(jnp.bfloat16),
                "b": consts["b"]}

    def get_params(self):
        return {"beta": self.beta.tolist(), "b": self.b}


class OpLinearSVC(PredictorEstimator):
    def __init__(self, reg_param: float = 0.0, max_iter: int = 100,
                 uid: Optional[str] = None):
        super().__init__(uid=uid, reg_param=reg_param, max_iter=max_iter)
        self.reg_param = reg_param
        self.max_iter = max_iter

    fit_fn = staticmethod(fit_linear_svc)
    predict_fn = staticmethod(predict_linear_svc)

    def fit_arrays(self, X, y, w, ctx: FitContext) -> LinearSVCModel:
        p = fit_linear_svc(X, y, w, jnp.float32(self.reg_param), self.max_iter)
        return LinearSVCModel(np.asarray(p["beta"]), float(p["b"]))
