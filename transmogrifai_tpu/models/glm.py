"""Generalized linear models (gaussian/binomial/poisson/gamma/tweedie).

Reference parity: `core/.../impl/regression/OpGeneralizedLinearRegression.scala`
(Spark GLR: family+link, IRLS). Here: penalized negative log-likelihood
minimized with L-BFGS in a fixed-length scan — same optimum, vmappable.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from transmogrifai_tpu.models.base import PredictionModel, PredictorEstimator
from transmogrifai_tpu.stages.base import FitContext

FAMILIES = ("gaussian", "binomial", "poisson", "gamma", "tweedie")
_EPS = 1e-8


def _neg_log_likelihood(family: str, mu, y, var_power: float = 1.5):
    if family == "gaussian":
        return 0.5 * (y - mu) ** 2
    if family == "binomial":
        mu = jnp.clip(mu, _EPS, 1 - _EPS)
        return -(y * jnp.log(mu) + (1 - y) * jnp.log(1 - mu))
    if family == "poisson":
        mu = jnp.maximum(mu, _EPS)
        return mu - y * jnp.log(mu)
    if family == "gamma":
        mu = jnp.maximum(mu, _EPS)
        return y / mu + jnp.log(mu)
    if family == "tweedie":
        mu = jnp.maximum(mu, _EPS)
        p = var_power
        return -(y * mu ** (1 - p) / (1 - p) - mu ** (2 - p) / (2 - p))
    raise ValueError(f"Unknown family {family!r}")


def _inverse_link(family: str, eta):
    if family == "gaussian":
        return eta  # identity
    if family == "binomial":
        return jax.nn.sigmoid(eta)  # logit link
    return jnp.exp(eta)  # log link (poisson/gamma/tweedie)


@partial(jax.jit, static_argnames=("family", "max_iter"))
def fit_glm(X: jnp.ndarray, y: jnp.ndarray, w: jnp.ndarray, l2,
            family: str = "gaussian", max_iter: int = 100,
            var_power: float = 1.5) -> Dict:
    d = X.shape[1]
    params = {"beta": jnp.zeros((d,), jnp.float32), "b": jnp.float32(0.0)}

    def loss_fn(p):
        eta = X @ p["beta"] + p["b"]
        mu = _inverse_link(family, eta)
        nll = _neg_log_likelihood(family, mu, y, var_power)
        return (nll * w).sum() / jnp.maximum(w.sum(), 1.0) \
            + 0.5 * l2 * (p["beta"] ** 2).sum()

    opt = optax.lbfgs()
    state = opt.init(params)
    vg = optax.value_and_grad_from_state(loss_fn)

    def step(carry, _):
        p, s = carry
        v, g = vg(p, state=s)
        updates, s = opt.update(g, s, p, value=v, grad=g, value_fn=loss_fn)
        return (optax.apply_updates(p, updates), s), v

    (params, _), _ = jax.lax.scan(step, (params, state), None, length=max_iter)
    return params


def predict_glm(params: Dict, X: jnp.ndarray, family: str) -> Dict:
    eta = X @ params["beta"] + params["b"]
    mu = _inverse_link(family, eta)
    return {"prediction": mu, "rawPrediction": eta[:, None],
            "probability": jnp.zeros((X.shape[0], 0), X.dtype)}


class GLMModel(PredictionModel):
    def __init__(self, beta=None, b: float = 0.0, family: str = "gaussian",
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.beta = np.asarray(beta, dtype=np.float32)
        self.b = float(b)
        self.family = family

    def predict_arrays(self, X):
        return predict_glm({"beta": jnp.asarray(self.beta),
                            "b": jnp.float32(self.b)}, X, self.family)

    def get_params(self):
        return {"beta": self.beta.tolist(), "b": self.b, "family": self.family}


class OpGeneralizedLinearRegression(PredictorEstimator):
    def __init__(self, family: str = "gaussian", reg_param: float = 0.0,
                 max_iter: int = 100, var_power: float = 1.5,
                 uid: Optional[str] = None):
        if family not in FAMILIES:
            raise ValueError(f"family must be one of {FAMILIES}")
        super().__init__(uid=uid, family=family, reg_param=reg_param,
                         max_iter=max_iter, var_power=var_power)
        self.family = family
        self.reg_param = reg_param
        self.max_iter = max_iter
        self.var_power = var_power

    def fit_arrays(self, X, y, w, ctx: FitContext) -> GLMModel:
        p = fit_glm(X, y, w, jnp.float32(self.reg_param), self.family,
                    self.max_iter, self.var_power)
        return GLMModel(np.asarray(p["beta"]), float(p["b"]), self.family)
