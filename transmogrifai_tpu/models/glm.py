"""Generalized linear models (gaussian/binomial/poisson/gamma/tweedie)
with the full Spark family × link surface.

Reference parity: `core/.../impl/regression/OpGeneralizedLinearRegression.scala`
(Spark GLR: family+link, IRLS; valid links per family listed in
`DefaultSelectorParams.scala:57-64`). Here: penalized negative
log-likelihood minimized with L-BFGS in a fixed-length scan — same
optimum, vmappable.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from transmogrifai_tpu.models.base import (
    PredictionModel, PredictorEstimator, resolve_init_params)
from transmogrifai_tpu.stages.base import FitContext

FAMILIES = ("gaussian", "binomial", "poisson", "gamma", "tweedie")
# Spark GLR's family → valid links table (first = canonical default,
# DefaultSelectorParams.scala:58-64); tweedie uses a power link derived
# from var_power instead of a named link
VALID_LINKS = {
    "gaussian": ("identity", "log", "inverse"),
    "binomial": ("logit", "probit", "cloglog"),
    "poisson": ("log", "identity", "sqrt"),
    "gamma": ("inverse", "identity", "log"),
    "tweedie": ("power",),
}
_EPS = 1e-8

# links the pre-link-param builds hard-coded per family: manifests saved
# without a "link" key were trained under THESE, so GLMModel must default
# to them (not the Spark-canonical table) to keep old models predicting
# identically
_LEGACY_LINKS = {"gaussian": "identity", "binomial": "logit",
                 "poisson": "log", "gamma": "log", "tweedie": "log"}


def _neg_log_likelihood(family: str, mu, y, var_power: float = 1.5):
    if family == "gaussian":
        return 0.5 * (y - mu) ** 2
    if family == "binomial":
        mu = jnp.clip(mu, _EPS, 1 - _EPS)
        return -(y * jnp.log(mu) + (1 - y) * jnp.log(1 - mu))
    if family == "poisson":
        mu = jnp.maximum(mu, _EPS)
        return mu - y * jnp.log(mu)
    if family == "gamma":
        mu = jnp.maximum(mu, _EPS)
        return y / mu + jnp.log(mu)
    if family == "tweedie":
        mu = jnp.maximum(mu, _EPS)
        p = var_power
        return -(y * mu ** (1 - p) / (1 - p) - mu ** (2 - p) / (2 - p))
    raise ValueError(f"Unknown family {family!r}")


def canonical_link(family: str) -> str:
    return VALID_LINKS[family][0]


def _inverse_link(family: str, eta, link: Optional[str] = None,
                  var_power: float = 1.5):
    """mu = g⁻¹(eta) for every Spark GLR link. Non-canonical links clamp
    eta into the link's domain instead of producing NaNs mid-optimization
    (Spark's IRLS guards equivalently)."""
    link = link or canonical_link(family)
    if link == "identity":
        return eta
    if link == "log":
        return jnp.exp(eta)
    if link == "inverse":
        return 1.0 / jnp.where(jnp.abs(eta) < _EPS,
                               jnp.where(eta < 0, -_EPS, _EPS), eta)
    if link == "logit":
        return jax.nn.sigmoid(eta)
    if link == "probit":
        return jnp.clip(jax.scipy.stats.norm.cdf(eta), _EPS, 1 - _EPS)
    if link == "cloglog":
        return jnp.clip(-jnp.expm1(-jnp.exp(eta)), _EPS, 1 - _EPS)
    if link == "sqrt":
        return eta ** 2
    if link == "power":  # tweedie: linkPower = 1 − var_power (Spark default)
        lp = 1.0 - var_power
        if abs(lp) < 1e-12:
            return jnp.exp(eta)
        return jnp.maximum(eta, _EPS) ** (1.0 / lp)
    raise ValueError(f"Unknown link {link!r}")


def _link_fwd(family: str, mu, link: Optional[str] = None,
              var_power: float = 1.5):
    """eta = g(mu) — used to initialize the intercept at g(mean(y)).
    Zero-initialization breaks non-log links whose inverse clamps around
    eta=0 (gamma's 1/eta, tweedie's power): the clamp's zero derivative
    kills the whole gradient, so L-BFGS never moves. Starting at the
    weighted mean (standard IRLS init) keeps eta in the link's domain."""
    link = link or canonical_link(family)
    if link == "identity":
        return mu
    if link == "log":
        return jnp.log(jnp.maximum(mu, _EPS))
    if link == "inverse":
        return 1.0 / jnp.maximum(mu, _EPS)
    if link == "logit":
        mu = jnp.clip(mu, _EPS, 1 - _EPS)
        return jnp.log(mu / (1 - mu))
    if link == "probit":
        from jax.scipy.special import ndtri
        return ndtri(jnp.clip(mu, _EPS, 1 - _EPS))
    if link == "cloglog":
        mu = jnp.clip(mu, _EPS, 1 - _EPS)
        return jnp.log(-jnp.log1p(-mu))
    if link == "sqrt":
        return jnp.sqrt(jnp.maximum(mu, 0.0))
    if link == "power":
        lp = 1.0 - var_power
        if abs(lp) < 1e-12:
            return jnp.log(jnp.maximum(mu, _EPS))
        return jnp.maximum(mu, _EPS) ** lp
    raise ValueError(f"Unknown link {link!r}")


# var_power is static: the power-link branch (`abs(1 − var_power)`) is
# python control flow, and sweep grids treat it as a static group key too
@partial(jax.jit, static_argnames=("family", "max_iter", "link", "var_power"))
def fit_glm(X: jnp.ndarray, y: jnp.ndarray, w: jnp.ndarray, l2,
            family: str = "gaussian", max_iter: int = 100,
            var_power: float = 1.5, link: Optional[str] = None,
            init_params: Optional[Dict] = None) -> Dict:
    d = X.shape[1]
    if init_params is None:
        mean_y = (y * w).sum() / jnp.maximum(w.sum(), 1.0)
        b0 = _link_fwd(family, mean_y, link, var_power).astype(jnp.float32)
        params = {"beta": jnp.zeros((d,), jnp.float32), "b": b0}
    else:
        # warm start (continual refit): the given weights already sit in
        # the link's domain, which is exactly what the mean-init exists
        # to guarantee for cold fits
        params = {"beta": jnp.asarray(init_params["beta"], jnp.float32),
                  "b": jnp.asarray(init_params["b"],
                                   jnp.float32).reshape(())}

    def loss_fn(p):
        eta = X @ p["beta"] + p["b"]
        mu = _inverse_link(family, eta, link, var_power)
        nll = _neg_log_likelihood(family, mu, y, var_power)
        return (nll * w).sum() / jnp.maximum(w.sum(), 1.0) \
            + 0.5 * l2 * (p["beta"] ** 2).sum()

    opt = optax.lbfgs()
    state = opt.init(params)
    vg = optax.value_and_grad_from_state(loss_fn)

    def step(carry, _):
        p, s = carry
        v, g = vg(p, state=s)
        updates, s = opt.update(g, s, p, value=v, grad=g, value_fn=loss_fn)
        return (optax.apply_updates(p, updates), s), v

    (params, _), _ = jax.lax.scan(step, (params, state), None, length=max_iter)
    return params


def predict_glm(params: Dict, X: jnp.ndarray, family: str,
                link: Optional[str] = None, var_power: float = 1.5) -> Dict:
    eta = X @ params["beta"] + params["b"]
    mu = _inverse_link(family, eta, link, var_power)
    return {"prediction": mu, "rawPrediction": eta[:, None],
            "probability": jnp.zeros((X.shape[0], 0), X.dtype)}


class GLMModel(PredictionModel):
    def __init__(self, beta=None, b: float = 0.0, family: str = "gaussian",
                 link: Optional[str] = None, var_power: float = 1.5,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.beta = np.asarray(beta, dtype=np.float32)
        self.b = float(b)
        self.family = family
        # no-link default = LEGACY hard-coded link, so pre-link-param
        # manifests (no "link" key) reload predicting exactly as saved;
        # new fits always pass the resolved link explicitly
        self.link = link or _LEGACY_LINKS[family]
        self.var_power = float(var_power)

    def predict_arrays(self, X):
        return predict_glm({"beta": jnp.asarray(self.beta),
                            "b": jnp.float32(self.b)}, X, self.family,
                           self.link, self.var_power)

    # parameter lifting: beta/b are traced jit arguments; family/link/
    # var_power stay in signature_params — they steer static control
    # flow in the trace (`_inverse_link`), so two GLMs share a program
    # only when their link functions agree
    def device_constants(self):
        return {"beta": jnp.asarray(self.beta), "b": jnp.float32(self.b)}

    def device_apply_with(self, consts, enc, dev):
        return predict_glm(consts, jnp.asarray(dev[-1]), self.family,
                           self.link, self.var_power)

    def signature_params(self):
        return {"family": self.family, "link": self.link,
                "var_power": self.var_power}

    def narrow_device_constants(self, consts):
        return {"beta": consts["beta"].astype(jnp.bfloat16),
                "b": consts["b"]}

    def get_params(self):
        return {"beta": self.beta.tolist(), "b": self.b,
                "family": self.family, "link": self.link,
                "var_power": self.var_power}


class OpGeneralizedLinearRegression(PredictorEstimator):
    """family × link as in Spark GLR (`OpGeneralizedLinearRegression.scala`);
    `link=None` means the family's canonical link. Invalid combinations
    raise at construction, mirroring Spark's parameter validation."""

    def __init__(self, family: str = "gaussian", reg_param: float = 0.0,
                 max_iter: int = 100, var_power: float = 1.5,
                 link: Optional[str] = None, uid: Optional[str] = None):
        if family not in FAMILIES:
            raise ValueError(f"family must be one of {FAMILIES}")
        if link is not None and link not in VALID_LINKS[family]:
            raise ValueError(
                f"link {link!r} invalid for family {family!r}; "
                f"valid: {VALID_LINKS[family]}")
        super().__init__(uid=uid, family=family, reg_param=reg_param,
                         max_iter=max_iter, var_power=var_power, link=link)
        self.family = family
        self.reg_param = reg_param
        self.max_iter = max_iter
        self.var_power = var_power
        self.link = link

    def fit_arrays(self, X, y, w, ctx: FitContext,
                   init_params: Optional[Dict] = None) -> GLMModel:
        link = self.link or canonical_link(self.family)
        warm = resolve_init_params(self, init_params,
                                   {"beta": (X.shape[1],), "b": ()})
        p = fit_glm(X, y, w, jnp.float32(self.reg_param), self.family,
                    self.max_iter, self.var_power, link, init_params=warm)
        return GLMModel(np.asarray(p["beta"]), float(p["b"]), self.family,
                        link, self.var_power)
