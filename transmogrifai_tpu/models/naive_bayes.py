"""Multinomial Naive Bayes.

Reference parity: `core/.../impl/classification/OpNaiveBayes.scala` (Spark
MLlib NaiveBayes, multinomial, smoothing=1.0, non-negative features
required — negative features raise, and the selector's fault tolerance
drops the family, matching Spark behavior).

TPU-first: fit is one one-hot-label matmul (class-conditional feature sums)
— a single MXU pass, shardable over rows with a psum.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from transmogrifai_tpu.models.base import (
    PredictionModel, PredictorEstimator, infer_n_classes)
from transmogrifai_tpu.stages.base import FitContext


def fit_naive_bayes(X: jnp.ndarray, y: jnp.ndarray, w: jnp.ndarray,
                    smoothing, n_classes: int) -> Dict:
    oh = jax.nn.one_hot(y.astype(jnp.int32), n_classes) * w[:, None]
    class_counts = oh.sum(0)                      # (k,)
    feat_sums = oh.T @ X                          # (k, d) — MXU
    log_prior = jnp.log(class_counts + 1e-12) - jnp.log(
        jnp.maximum(class_counts.sum(), 1e-12))
    num = feat_sums + smoothing
    log_theta = jnp.log(num) - jnp.log(num.sum(1, keepdims=True))
    return {"log_prior": log_prior, "log_theta": log_theta}


def predict_naive_bayes(params: Dict, X: jnp.ndarray) -> Dict[str, jnp.ndarray]:
    logits = X @ params["log_theta"].T + params["log_prior"]
    prob = jax.nn.softmax(logits, axis=-1)
    return {"prediction": jnp.argmax(logits, -1).astype(jnp.float32),
            "rawPrediction": logits, "probability": prob}


class NaiveBayesModel(PredictionModel):
    def __init__(self, log_prior=None, log_theta=None, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.log_prior = np.asarray(log_prior, dtype=np.float32)
        self.log_theta = np.asarray(log_theta, dtype=np.float32)

    def predict_arrays(self, X):
        return predict_naive_bayes(
            {"log_prior": jnp.asarray(self.log_prior),
             "log_theta": jnp.asarray(self.log_theta)}, X)

    # parameter lifting: see LinearRegressionModel
    def device_constants(self):
        return {"log_prior": jnp.asarray(self.log_prior),
                "log_theta": jnp.asarray(self.log_theta)}

    def device_apply_with(self, consts, enc, dev):
        return predict_naive_bayes(consts, jnp.asarray(dev[-1]))

    def signature_params(self):
        return {}

    def narrow_device_constants(self, consts):
        return {"log_prior": consts["log_prior"],
                "log_theta": consts["log_theta"].astype(jnp.bfloat16)}

    def get_params(self):
        return {"log_prior": self.log_prior.tolist(),
                "log_theta": self.log_theta.tolist()}


class OpNaiveBayes(PredictorEstimator):
    def __init__(self, smoothing: float = 1.0,
                 n_classes: Optional[int] = None, uid: Optional[str] = None):
        super().__init__(uid=uid, smoothing=smoothing, n_classes=n_classes)
        self.smoothing = smoothing
        self.n_classes = n_classes

    fit_fn = staticmethod(fit_naive_bayes)
    predict_fn = staticmethod(predict_naive_bayes)

    def fit_arrays(self, X, y, w, ctx: FitContext) -> NaiveBayesModel:
        if bool(jnp.any(X < 0)):
            raise ValueError(
                "NaiveBayes requires non-negative features (Spark parity)")
        k = self.n_classes or infer_n_classes(np.asarray(y))
        p = fit_naive_bayes(X, y, w, jnp.float32(self.smoothing), k)
        return NaiveBayesModel(np.asarray(p["log_prior"]),
                               np.asarray(p["log_theta"]))
