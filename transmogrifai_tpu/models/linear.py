"""Ridge / OLS linear regression via normal equations.

Reference parity: `core/.../impl/regression/OpLinearRegression.scala`
(Spark MLlib LinearRegression, "normal"/"l-bfgs" solvers).

TPU-first: closed-form (XᵀX + λI)β = Xᵀy with a Cholesky-backed solve —
XᵀX is one MXU matmul, shardable over the data axis with a `psum`, and the
whole fit vmaps over the λ grid and fold masks.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from transmogrifai_tpu.models.base import (
    PredictionModel, PredictorEstimator, resolve_init_params)
from transmogrifai_tpu.stages.base import FitContext


@jax.jit
def fit_linreg(X: jnp.ndarray, y: jnp.ndarray, w: jnp.ndarray, l2) -> Dict:
    """Weighted ridge: returns {"beta": (d,), "intercept": ()}."""
    wsum = jnp.maximum(w.sum(), 1.0)
    x_mean = (X * w[:, None]).sum(0) / wsum
    y_mean = (y * w).sum() / wsum
    Xc = (X - x_mean) * jnp.sqrt(w)[:, None]
    yc = (y - y_mean) * jnp.sqrt(w)
    d = X.shape[1]
    gram = Xc.T @ Xc / wsum
    # adaptive jitter keeps the solve well-posed when columns are constant
    # (e.g. an all-zero null-indicator) and l2 == 0
    eps = 1e-6 * (jnp.trace(gram) / d + 1.0)
    gram = gram + (l2 + eps) * jnp.eye(d, dtype=X.dtype)
    rhs = Xc.T @ yc / wsum
    beta = jax.scipy.linalg.solve(gram, rhs, assume_a="pos")
    intercept = y_mean - x_mean @ beta
    return {"beta": beta, "intercept": intercept}


@partial(jax.jit, static_argnames=("max_iter",))
def fit_linreg_enet(X: jnp.ndarray, y: jnp.ndarray, w: jnp.ndarray,
                    l1, l2, max_iter: int = 300,
                    init_params: Optional[Dict] = None) -> Dict:
    """Elastic-net weighted least squares via FISTA on centered data.

    Spark parity: MLlib LinearRegression with elasticNetParam > 0 (OWL-QN);
    callers pass `l1 = reg·α`, `l2 = reg·(1−α)`. Smooth part
    `0.5/wsum·Σ w(Xcβ − yc)² + 0.5·l2·||β||²` advances with a
    power-iteration Lipschitz step; the intercept comes from the centering
    identity (ȳ − x̄·β), exactly like `fit_linreg`. l1/l2 may be traced,
    so grids vmap."""
    from transmogrifai_tpu.models.logistic import _power_lipschitz
    wsum = jnp.maximum(w.sum(), 1.0)
    x_mean = (X * w[:, None]).sum(0) / wsum
    y_mean = (y * w).sum() / wsum
    Xc = X - x_mean
    yc = y - y_mean
    L = 1.05 * _power_lipschitz(Xc * jnp.sqrt(w)[:, None],
                                jnp.ones_like(w), wsum) + l2 + 1e-8
    step = 1.0 / L

    def fista_step(carry, _):
        b, bm, t = carry
        r = (Xc @ bm - yc) * w
        g = Xc.T @ r / wsum + l2 * bm
        b1 = bm - step * g
        b1 = jnp.sign(b1) * jnp.maximum(jnp.abs(b1) - step * l1, 0.0)
        t1 = 0.5 * (1.0 + jnp.sqrt(1.0 + 4.0 * t * t))
        return (b1, b1 + (t - 1.0) / t1 * (b1 - b), t1), None

    if init_params is None:
        b0 = jnp.zeros((X.shape[1],), jnp.float32)
    else:  # warm start from existing coefficients (continual refit)
        b0 = jnp.asarray(init_params["beta"], jnp.float32)
    (beta, _, _), _ = jax.lax.scan(
        fista_step, (b0, b0, jnp.float32(1.0)), None, length=max_iter)
    return {"beta": beta, "intercept": y_mean - x_mean @ beta}


def predict_linreg(params: Dict, X: jnp.ndarray) -> Dict[str, jnp.ndarray]:
    pred = X @ params["beta"] + params["intercept"]
    return {
        "prediction": pred,
        "rawPrediction": pred[:, None],
        "probability": jnp.zeros((X.shape[0], 0), X.dtype),
    }


class LinearRegressionModel(PredictionModel):
    def __init__(self, beta=None, intercept: float = 0.0,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.beta = np.asarray(beta, dtype=np.float32)
        self.intercept = float(intercept)

    def predict_arrays(self, X):
        return predict_linreg(
            {"beta": jnp.asarray(self.beta),
             "intercept": jnp.float32(self.intercept)}, X)

    # parameter lifting (serving/fleet.py): fitted weights flow into the
    # compiled scorer as traced jit ARGUMENTS, so every same-shaped
    # linear tenant in a fleet shares ONE compiled program and a
    # tenant's resident HBM cost is its parameters, not a program copy
    def device_constants(self):
        return {"beta": jnp.asarray(self.beta),
                "intercept": jnp.float32(self.intercept)}

    def device_apply_with(self, consts, enc, dev):
        return predict_linreg(consts, jnp.asarray(dev[-1]))

    def signature_params(self):
        return {}  # all fitted state is lifted; shapes key via consts

    def narrow_device_constants(self, consts):
        # memory-bound predict: bf16 weights halve the table read; the
        # matmul accumulates in f32 (~0.4% relative weight error, the
        # same documented tradeoff as the GBT bf16 histograms)
        return {"beta": consts["beta"].astype(jnp.bfloat16),
                "intercept": consts["intercept"]}

    def get_params(self):
        return {"beta": self.beta.tolist(), "intercept": self.intercept}


class OpLinearRegression(PredictorEstimator):
    """elastic_net_param > 0 blends L1 into the penalty
    (Spark `LinearRegression.elasticNetParam`) and switches the closed-form
    ridge solve for the FISTA elastic-net fit."""

    def __init__(self, reg_param: float = 0.0,
                 elastic_net_param: float = 0.0, uid: Optional[str] = None):
        super().__init__(uid=uid, reg_param=reg_param,
                         elastic_net_param=elastic_net_param)
        self.reg_param = reg_param
        self.elastic_net_param = elastic_net_param

    fit_fn = staticmethod(fit_linreg)
    predict_fn = staticmethod(predict_linreg)

    def fit_arrays(self, X, y, w, ctx: FitContext,
                   init_params: Optional[Dict] = None
                   ) -> LinearRegressionModel:
        alpha = float(self.elastic_net_param)
        if alpha > 0.0:
            warm = resolve_init_params(self, init_params,
                                       {"beta": (X.shape[1],)})
            p = fit_linreg_enet(X, y, w,
                                jnp.float32(self.reg_param * alpha),
                                jnp.float32(self.reg_param * (1.0 - alpha)),
                                init_params=warm)
        else:
            # closed-form ridge: the solve is exact, so a warm start has
            # nothing to continue from — init_params is accepted (the
            # continual refitter treats every family uniformly) and
            # harmlessly ignored
            p = fit_linreg(X, y, w, jnp.float32(self.reg_param))
        return LinearRegressionModel(np.asarray(p["beta"]),
                                     float(p["intercept"]))
