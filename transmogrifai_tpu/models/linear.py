"""Ridge / OLS linear regression via normal equations.

Reference parity: `core/.../impl/regression/OpLinearRegression.scala`
(Spark MLlib LinearRegression, "normal"/"l-bfgs" solvers).

TPU-first: closed-form (XᵀX + λI)β = Xᵀy with a Cholesky-backed solve —
XᵀX is one MXU matmul, shardable over the data axis with a `psum`, and the
whole fit vmaps over the λ grid and fold masks.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from transmogrifai_tpu.models.base import PredictionModel, PredictorEstimator
from transmogrifai_tpu.stages.base import FitContext


@jax.jit
def fit_linreg(X: jnp.ndarray, y: jnp.ndarray, w: jnp.ndarray, l2) -> Dict:
    """Weighted ridge: returns {"beta": (d,), "intercept": ()}."""
    wsum = jnp.maximum(w.sum(), 1.0)
    x_mean = (X * w[:, None]).sum(0) / wsum
    y_mean = (y * w).sum() / wsum
    Xc = (X - x_mean) * jnp.sqrt(w)[:, None]
    yc = (y - y_mean) * jnp.sqrt(w)
    d = X.shape[1]
    gram = Xc.T @ Xc / wsum
    # adaptive jitter keeps the solve well-posed when columns are constant
    # (e.g. an all-zero null-indicator) and l2 == 0
    eps = 1e-6 * (jnp.trace(gram) / d + 1.0)
    gram = gram + (l2 + eps) * jnp.eye(d, dtype=X.dtype)
    rhs = Xc.T @ yc / wsum
    beta = jax.scipy.linalg.solve(gram, rhs, assume_a="pos")
    intercept = y_mean - x_mean @ beta
    return {"beta": beta, "intercept": intercept}


def predict_linreg(params: Dict, X: jnp.ndarray) -> Dict[str, jnp.ndarray]:
    pred = X @ params["beta"] + params["intercept"]
    return {
        "prediction": pred,
        "rawPrediction": pred[:, None],
        "probability": jnp.zeros((X.shape[0], 0), X.dtype),
    }


class LinearRegressionModel(PredictionModel):
    def __init__(self, beta=None, intercept: float = 0.0,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.beta = np.asarray(beta, dtype=np.float32)
        self.intercept = float(intercept)

    def predict_arrays(self, X):
        return predict_linreg(
            {"beta": jnp.asarray(self.beta),
             "intercept": jnp.float32(self.intercept)}, X)

    def get_params(self):
        return {"beta": self.beta.tolist(), "intercept": self.intercept}


class OpLinearRegression(PredictorEstimator):
    def __init__(self, reg_param: float = 0.0, uid: Optional[str] = None):
        super().__init__(uid=uid, reg_param=reg_param)
        self.reg_param = reg_param

    fit_fn = staticmethod(fit_linreg)
    predict_fn = staticmethod(predict_linreg)

    def fit_arrays(self, X, y, w, ctx: FitContext) -> LinearRegressionModel:
        p = fit_linreg(X, y, w, jnp.float32(self.reg_param))
        return LinearRegressionModel(np.asarray(p["beta"]),
                                     float(p["intercept"]))
