"""Decision trees, random forests, and gradient boosting as XLA programs.

Reference parity: `core/.../impl/classification/OpDecisionTreeClassifier.scala`,
`OpRandomForestClassifier.scala`, `OpGBTClassifier.scala`,
`OpXGBoostClassifier.scala` and the regression counterparts — all JNI/JVM
(Spark MLlib trees, libxgboost+Rabit) in the reference (SURVEY.md §2.9).

TPU-first design (SURVEY.md §7 "Trees on TPU"):
- features are pre-binned to `max_bins` quantile buckets (host quantiles →
  static shapes); a tree never sees raw floats
- trees grow LEVEL-WISE with a fixed depth: every level builds
  (nodes × features × bins × outputs) gradient/weight histograms with one
  scatter-add over the batch — the data-parallel reduction (`psum` over a
  sharded batch axis), then picks argmax-gain splits — no data-dependent
  control flow, so the whole learner jits and vmaps
- a "tree" is three dense arrays (per-level split feature, split bin,
  leaf values); prediction is `depth` gathers — fusable into the scoring
  program
- RandomForest = vmap over per-tree bootstrap weights + feature masks;
  GBT/XGBoost = `lax.scan` over boosting rounds carrying the margin, using
  second-order (grad/hess) gains — the XGBoost formulation, with `psum`
  replacing Rabit allreduce when the batch axis is sharded

Unified learner: targets G (n, m) and weights H (n,); split gain =
Σ_m GL²/(HL+λ) + Σ_m GR²/(HR+λ) − Σ_m G²/(H+λ); leaf value = G/(H+λ).
With one-hot labels as G and counts as H this is exactly gini-style
variance reduction (RF/DT classification); with gradients/hessians it is
the XGBoost gain (GBT); with y and counts it is variance reduction (reg).
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import logging
import os

import jax
import jax.numpy as jnp
import numpy as np

from transmogrifai_tpu.models.base import (
    PredictionModel, PredictorEstimator, infer_n_classes)
from transmogrifai_tpu.stages.base import FitContext

log = logging.getLogger(__name__)

DEFAULT_MAX_BINS = 32


# --------------------------------------------------------------------------- #
# binning                                                                     #
# --------------------------------------------------------------------------- #

def quantile_bin_edges(X: np.ndarray, max_bins: int = DEFAULT_MAX_BINS) -> np.ndarray:
    """(d, max_bins-1) ascending bin edges per feature (host, fit-time)."""
    qs = np.linspace(0, 1, max_bins + 1)[1:-1]
    edges = np.quantile(np.asarray(X, dtype=np.float64), qs, axis=0).T
    return np.ascontiguousarray(edges, dtype=np.float32)


def bin_features(X: jnp.ndarray, edges: jnp.ndarray) -> jnp.ndarray:
    """(n, d) int8 bin ids in [0, max_bins) (int32 above 127 bins).

    Broadcast-compare + sum (== searchsorted side="right") instead of an
    actual per-column searchsorted: binary-search gathers serialize on TPU
    (~330ms at 100k×55) while the dense compare streams on the VPU and
    fuses with neighbours (~10ms). int8 storage quarters the HBM slab the
    predict walk re-reads every level (r5: the big-data path already
    staged int8; the in-core path now matches)."""
    b = (X[:, :, None] >= edges[None, :, :]).sum(-1, dtype=jnp.int32)
    if edges.shape[-1] + 1 <= 127:
        return b.astype(jnp.int8)
    return b


def _select_bin(Xb: jnp.ndarray, feat_idx: jnp.ndarray) -> jnp.ndarray:
    """Per-row feature selection Xb[r, feat_idx[r]] as a masked reduction.
    `take_along_axis` lowers to a serialized row gather on TPU; the one-hot
    compare fuses into a single VPU pass over (n, d). Accepts int8 or
    int32 bins; the selected value widens to int32 for the split compare."""
    d = Xb.shape[-1]
    onehot = jnp.arange(d, dtype=jnp.int32)[None, :] == feat_idx[:, None]
    return jnp.where(onehot, Xb, jnp.zeros((), Xb.dtype)).sum(
        axis=1, dtype=jnp.int32)


# --------------------------------------------------------------------------- #
# the level-wise learner                                                      #
# --------------------------------------------------------------------------- #

def bins_onehot(Xb: jnp.ndarray, n_bins: int) -> jnp.ndarray:
    """(n, d, bins) bf16 one-hot of the binned matrix — the histogram
    reduction operand, built ONCE per training matrix and reused across
    every level, tree, round, fold, and grid config. The 0/1 operand is
    exact in bf16; the OTHER matmul operand (gradient/hessian values in
    `_histograms`) is bf16-quantized to ~0.4% relative error — a
    deliberate precision/throughput tradeoff (full MXU rate, f32
    accumulation): near-tie split choices may differ from an f32
    scatter-add histogram, which changes individual trees but not metric
    quality (split ties are statistically arbitrary anyway)."""
    return jax.nn.one_hot(Xb, n_bins, dtype=jnp.bfloat16)


# Histogram precision (VERDICT r3 #8 — an explicit, documented choice):
#   "bf16" (default): G/H values quantize to bf16 before the histogram
#     matmul (~0.4% relative error; the one-hot operands are EXACT 0/1 in
#     bf16 and accumulation is f32). Near-tie splits can differ from an
#     exact f32 scatter-add histogram — individual trees change, metric
#     quality does not (ties are statistically arbitrary); in exchange the
#     matmul runs at full MXU bf16 rate.
#   "f32": exact single-precision histograms (Precision.HIGHEST forces
#     true f32 even where the platform runs plain f32 matmuls at bf16) —
#     the reference bar (MLlib/XGBoost exact f32/f64 scatter histograms)
#     at roughly 1/4-1/8 the MXU throughput.
# Process-level switch: TRANSMOGRIFAI_HIST_PRECISION=f32, read ONCE at
# import. jax.jit caches executables by shape/static-args only, so
# mutating this global (or the env var) after fit functions have traced
# silently keeps the OLD precision for already-compiled shapes — set the
# env var before importing this module and never mutate it mid-process
# (r4 advisor). test_models.py bounds the divergence of both modes
# against an f64 oracle on near-tie data.
HIST_PRECISION = os.environ.get("TRANSMOGRIFAI_HIST_PRECISION", "bf16")


def _histograms(B, node_idx, G, H, n_nodes: int):
    """hist_G: (m, nodes, d, bins); hist_H: (nodes, d, bins).

    One-hot MATMUL histograms: hist[node, f, b] = Σ_r A[r,node]·B[r,f,b]·v[r]
    computed as (nodes, n) @ (n, d·bins) on the MXU — where the FLOPs live
    on TPU. A scatter-add formulation is 20-50× slower here (TPU scatters
    serialize) and its (n, d, m) update tensor tile-pads the tiny class
    axis to 128 lanes (the r2 152 GB OOM). Contraction over the row axis
    also means a mesh-sharded batch reduces via an XLA-inserted psum —
    the Rabit-allreduce analogue (SURVEY.md §2.9).

    Per-value-column matmuls (B read m+1 times) measure FASTER here than
    stacking [G, H] into one ((m+1)·nodes, n) operand: at in-core shapes
    (d ≈ 55) the A-side (n, (m+1)·nodes) materialization costs more than
    the saved B reads — the OPPOSITE tradeoff from the out-of-core path
    (d=500, B per-chunk rebuilt), where `parallel/bigdata.py` stacks.

    Value precision is governed by HIST_PRECISION (see above)."""
    n, d, nb = B.shape
    m = G.shape[1]
    exact = HIST_PRECISION == "f32"
    A = jax.nn.one_hot(node_idx, n_nodes,
                       dtype=jnp.float32 if exact else jnp.bfloat16)
    Bf = B.reshape(n, d * nb)
    if exact:
        Bf = Bf.astype(jnp.float32)

    def red(vec):  # (n,) weights → (nodes, d, bins) f32
        if exact:
            Ag = A * vec[:, None].astype(jnp.float32)
            out = jnp.matmul(Ag.T, Bf,
                             precision=jax.lax.Precision.HIGHEST,
                             preferred_element_type=jnp.float32)
        else:
            Ag = A * vec[:, None].astype(jnp.bfloat16)
            out = jnp.matmul(Ag.T, Bf, preferred_element_type=jnp.float32)
        return out.reshape(n_nodes, d, nb)

    hh = red(H)
    hg = jnp.stack([red(G[:, c]) for c in range(m)])
    return hg, hh


def split_from_histograms(hg, hh, n_bins: int, reg_lambda,
                          min_child_weight, min_gain, min_gain_norm,
                          feature_mask, level: int, active_depth
                          ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-node best (feature, bin) from (m, nodes, d, bins) gradient and
    (nodes, d, bins) weight histograms — shared by the in-core level loop
    and the chunked big-data path (`parallel/bigdata.py`)."""
    n_nodes = hh.shape[0]
    cg = jnp.cumsum(hg, axis=-1)          # left sums at split-bin b
    ch = jnp.cumsum(hh, axis=-1)          # (nodes, d, bins)
    tg = cg[..., -1:]
    th = ch[..., -1:]
    score = lambda g, h: (g ** 2).sum(0) / (h + reg_lambda)  # noqa: E731
    gain = score(cg, ch) + score(tg - cg, th - ch) - score(tg, th)
    valid = (ch >= min_child_weight) & ((th - ch) >= min_child_weight)
    gain = jnp.where(valid, gain, -jnp.inf)
    if feature_mask is not None:
        gain = jnp.where(feature_mask[None, :, None], gain, -jnp.inf)
    flat = gain.reshape(n_nodes, -1)      # (nodes, d*bins)
    best = jnp.argmax(flat, axis=1)
    best_gain = jnp.take_along_axis(flat, best[:, None], 1)[:, 0]
    bf = (best // n_bins).astype(jnp.int32)
    bb = (best % n_bins).astype(jnp.int32)
    # a node with no usable gain "splits" at bin >= n_bins-1 → all left.
    # Two threshold scales coexist: `min_gain` compares raw (XGBoost
    # gamma), while `min_gain_norm` scales by the node's total weight —
    # with one-hot G / count H the unified score satisfies
    # (score_L + score_R − score_P)/h == Spark's gini/variance
    # impurity improvement, so the normalized threshold is EXACTLY
    # MLlib's minInfoGain scale ({0.001, 0.01, 0.1} in
    # DefaultSelectorParams.scala:39). Both may be traced grid values.
    splits = best_gain > jnp.maximum(min_gain, min_gain_norm * th[:, 0, 0])
    if active_depth is not None:
        splits = splits & (level < active_depth)
    bb = jnp.where(splits, bb, n_bins)
    return bf, bb


# Depth at which sibling subtraction starts paying (see grow_tree doc)
_SUBTRACT_MIN_DEPTH = 12


def grow_tree(Xb: jnp.ndarray, G: jnp.ndarray, H: jnp.ndarray,
              max_depth: int, n_bins: int, reg_lambda: float = 1.0,
              min_child_weight: float = 1.0, min_gain: float = 0.0,
              feature_mask: Optional[jnp.ndarray] = None,
              active_depth=None, alpha: float = 0.0,
              B: Optional[jnp.ndarray] = None,
              min_gain_norm=0.0) -> Dict:
    """Grow one fixed-depth tree. Returns dense arrays:

    {"feat": (depth, 2^depth) int32, "bin": (depth, 2^depth) int32,
     "leaf": (2^max_depth, m) float32}
    (per-level arrays are padded to 2^max_depth node slots)

    `active_depth`: optional TRACED effective depth ≤ max_depth. Levels at or
    beyond it never split (every sample routes left, partition unchanged), so
    the padded tree predicts exactly like a tree grown to that depth — this
    lets the sweep engine vmap a {max_depth: 3, 6, 12} grid in ONE compiled
    program padded to 12 instead of one compile per depth.

    Deep trees (max_depth ≥ `_SUBTRACT_MIN_DEPTH`) in EXACT-histogram
    mode (TRANSMOGRIFAI_HIST_PRECISION=f32) use HISTOGRAM SUBTRACTION —
    the standard XGBoost/LightGBM hist trick: per level, compute
    histograms only for rows routed RIGHT (grouped by parent) and derive
    the left child as parent − right. This halves the histogram-matmul
    A-side columns and FLOPs; r5 measured it only pays off once
    per-level matmuls span multiple MXU output tiles (90k×55: depth 12
    58→39 ms/tree, but depth ≤ 10 is bound by streaming the bin one-hot
    operand, where fewer output columns save nothing) — hence the depth
    gate. It is DISABLED in the default bf16 mode: a deep small node's
    subtracted histogram is a big-minus-big cancellation whose absolute
    error scales with the PARENT's magnitude, not the node's own — r5
    observed the depth-12-padded XGB sweep losing ~0.005 CV AuPR to it
    (enough to flip the bench's model selection), a genuine quality
    regression rather than the benign per-node bf16 tie noise of direct
    histograms. With f32 (HIGHEST) histograms the cancellation error
    sits at f32 rounding and the trick is sound — which is exactly why
    LightGBM subtracts in full precision.
    """
    n, d = Xb.shape
    m = G.shape[1]
    max_nodes = 2 ** max_depth
    node_idx = jnp.zeros(n, dtype=jnp.int32)
    feats = jnp.zeros((max_depth, max_nodes), jnp.int32)
    bins = jnp.full((max_depth, max_nodes), n_bins, jnp.int32)  # n_bins = "no split"
    if B is None:
        B = bins_onehot(Xb, n_bins)
    subtract = max_depth >= _SUBTRACT_MIN_DEPTH and HIST_PRECISION == "f32"
    if subtract:
        hg, hh = _histograms(B, node_idx, G, H, 1)

    for level in range(max_depth):
        n_nodes = 2 ** level
        if not subtract:
            hg, hh = _histograms(B, node_idx, G, H, n_nodes)
        bf, bb = split_from_histograms(
            hg, hh, n_bins, reg_lambda, min_child_weight, min_gain,
            min_gain_norm, feature_mask, level, active_depth)
        feats = feats.at[level, :n_nodes].set(bf)
        bins = bins.at[level, :n_nodes].set(bb)
        if n_nodes <= _ONEHOT_LOOKUP_MAX:
            sample_feat, split_bin = _table_lookup2(bf, bb, node_idx)
        else:
            sample_feat, split_bin = bf[node_idx], bb[node_idx]
        sample_bin = _select_bin(Xb, sample_feat)
        go_right = sample_bin > split_bin
        node_idx = node_idx * 2 + go_right.astype(jnp.int32)
        if subtract and level + 1 < max_depth:
            right = go_right.astype(jnp.float32)
            hg_r, hh_r = _histograms(B, node_idx >> 1, G * right[:, None],
                                     H * right, n_nodes)
            # interleave children: node k → (left 2k = parent − right,
            # right 2k+1)
            hg = jnp.stack([hg - hg_r, hg_r], axis=2).reshape(
                m, 2 * n_nodes, d, n_bins)
            hh = jnp.stack([hh - hh_r, hh_r], axis=1).reshape(
                2 * n_nodes, d, n_bins)

    leaf_g = jnp.zeros((max_nodes, m), G.dtype).at[node_idx].add(G)
    leaf_h = jnp.zeros((max_nodes,), H.dtype).at[node_idx].add(H)
    # L1 (alpha) soft-thresholds the leaf numerator (XGBoost leaf formula)
    leaf_g = jnp.sign(leaf_g) * jnp.maximum(jnp.abs(leaf_g) - alpha, 0.0)
    leaf = leaf_g / (leaf_h + reg_lambda)[:, None]
    return {"feat": feats, "bin": bins, "leaf": leaf}


# One-hot table lookups beat (n,)-indexed TPU gathers far beyond the 256
# entries r2 measured: r5 re-measured the depth-10 164-tree predict at
# 100k rows — each (100k,)-row gather costs ~1 ms (level-9 f/b tables +
# the leaf read were 490 ms of the 604 ms total), while the generated
# (n, w) compare+select fuses into one VPU pass (~0.3 ms at w=512).
# Above this width the linear (n·w) one-hot pass finally loses to the
# constant-time gather again.
_ONEHOT_LOOKUP_MAX = 2048


def _table_lookup2(ta: jnp.ndarray, tb: jnp.ndarray,
                   node: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(ta[node], tb[node]) for per-level tables: one fused one-hot
    pass instead of two serialized TPU gathers (the dominant cost of tree
    prediction at 100k rows was exactly these (n,)-indexed table reads)."""
    width = ta.shape[0]
    oh = jnp.arange(width, dtype=jnp.int32)[None, :] == node[:, None]
    return (jnp.where(oh, ta[None, :], 0).sum(1),
            jnp.where(oh, tb[None, :], 0).sum(1))


def _leaf_lookup(col: jnp.ndarray, node: jnp.ndarray) -> jnp.ndarray:
    """col[node] for one (width,) f32 leaf column as a fused one-hot
    masked sum — replacing the per-tree leaf gather (~1 ms per 100k rows
    on the tunnel) with a generated VPU pass. Adding exact 0.0s keeps the
    selected value bit-identical to the gather. A single leaf pass
    amortizes its compare over one select (the walk's `_table_lookup2`
    amortizes over two), so its crossover sits a factor higher than
    `_ONEHOT_LOOKUP_MAX`; beyond that the linear (n·width) pass loses to
    the constant-time gather (pad depth 14 → 16384-wide leaf tables)."""
    width = col.shape[0]
    if width > 2 * _ONEHOT_LOOKUP_MAX:
        return col[node]
    oh = jnp.arange(width, dtype=jnp.int32)[None, :] == node[:, None]
    return jnp.where(oh, col[None, :], 0.0).sum(1)


def _tree_walk(tree: Dict, Xb: jnp.ndarray, select_fn=None) -> jnp.ndarray:
    """(n,) leaf index for binned samples — the shared routing walk.
    Gather-free at every level up to `_ONEHOT_LOOKUP_MAX`-wide tables.
    `select_fn(Xb, feat_idx)` defaults to `_select_bin` (the big-data
    path passes its own fused variant)."""
    select_fn = select_fn or _select_bin
    n = Xb.shape[0]
    node = jnp.zeros(n, dtype=jnp.int32)
    depth = tree["feat"].shape[0]
    for level in range(depth):
        n_nodes = 2 ** level
        if n_nodes <= _ONEHOT_LOOKUP_MAX:
            f, b = _table_lookup2(tree["feat"][level][:n_nodes],
                                  tree["bin"][level][:n_nodes], node)
        else:
            f = tree["feat"][level][node]
            b = tree["bin"][level][node]
        sample_bin = select_fn(Xb, f)
        node = node * 2 + (sample_bin > b).astype(jnp.int32)
    return node


def predict_tree(tree: Dict, Xb: jnp.ndarray, select_fn=None) -> jnp.ndarray:
    """(n, m) leaf values for binned samples."""
    node = _tree_walk(tree, Xb, select_fn)
    m = tree["leaf"].shape[-1]
    # per-class masked sums instead of one (n, m) row gather: the gather
    # serializes AND its m-minor output tile-pads to 128 lanes; the class
    # count is small and static, so m fused (n, width) passes win
    return jnp.stack([_leaf_lookup(tree["leaf"][:, c], node)
                      for c in range(m)], axis=-1)


def predict_tree_dense(tree: Dict, Xb: jnp.ndarray) -> jnp.ndarray:
    """(n, m) leaf values — a TENSORIZED alternative formulation.

    ALL node decisions compute as ONE MXU matmul: selected-bin values
    `S = Xb @ onehot(feat)` for every node at once (bin ids ≤ 256 are
    exact in bf16, accumulation f32), then `D = S > bin` and a
    level-by-level 0/1 path product routes probability mass to leaves —
    no gathers anywhere. Bit-identical to `predict_tree` (same
    comparisons, exact 0/1 products; `P @ leaf` selects one leaf row).

    MEASURED (v5e, 160 depth-10 trees, 100k×55): 1.13 s vs 0.84 s for
    the level walk at predict chunk 64 — the (n, 2^level) routing slabs
    are HBM-bound and outweigh the gathers they remove, so the walk
    remains the default; this form is kept as the documented
    measured-alternative (it wins only where gathers are pathologically
    slow or depth ≪ 10 slabs fit cache)."""
    n, d = Xb.shape
    depth = tree["feat"].shape[0]
    max_nodes = tree["leaf"].shape[0]
    # level-major flattened internal nodes: offset(level) = 2^level - 1
    feats = jnp.concatenate(
        [tree["feat"][lv][:2 ** lv] for lv in range(depth)])
    bins = jnp.concatenate(
        [tree["bin"][lv][:2 ** lv] for lv in range(depth)])
    F = jax.nn.one_hot(feats, d, dtype=jnp.bfloat16)        # (nodes, d)
    S = jnp.matmul(Xb.astype(jnp.bfloat16), F.T,
                   preferred_element_type=jnp.float32)       # (n, nodes)
    D = (S > bins[None, :].astype(jnp.float32)).astype(jnp.bfloat16)
    P = jnp.ones((n, 1), jnp.bfloat16)
    off = 0
    for lv in range(depth):
        w = 2 ** lv
        Dlv = D[:, off:off + w]                              # (n, w)
        # children interleave: node k -> (left 2k, right 2k+1)
        P = jnp.stack([P * (1 - Dlv), P * Dlv], axis=-1).reshape(n, 2 * w)
        off += w
    # grow_tree always emits (depth, 2^depth) levels with 2^depth leaves
    assert P.shape[1] == max_nodes, (P.shape, max_nodes)
    # leaf values stay f32 and the tiny final matmul runs at HIGHEST
    # precision: exactly one nonzero 0/1 weight per row selects the leaf,
    # so the result is the untouched f32 leaf value
    return jnp.matmul(P.astype(jnp.float32), tree["leaf"],
                      precision=jax.lax.Precision.HIGHEST,
                      preferred_element_type=jnp.float32)


# --------------------------------------------------------------------------- #
# Random forest / decision tree                                               #
# --------------------------------------------------------------------------- #

_TREE_CHUNK_BUDGET = 1 << 26  # live per-tree working-set elements (bf16)


@partial(jax.jit, static_argnames=("n_trees", "max_depth", "n_bins",
                                   "n_outputs", "subsample_features",
                                   "bootstrap", "tree_budget_divisor"))
def fit_forest(Xb, Y, w, n_trees: int, max_depth: int, n_bins: int,
               n_outputs: int, seed, subsample_features: bool = True,
               min_child_weight: float = 1.0, active_depth=None,
               bootstrap: bool = True, tree_budget_divisor: int = 1,
               min_gain=0.0):
    n, d = Xb.shape
    keys = jax.random.split(jax.random.PRNGKey(seed), n_trees)
    n_sub = max(int(np.sqrt(d)), 1) if subsample_features else d
    B = bins_onehot(Xb, n_bins)  # shared across all trees

    def one_tree(key):
        k1, k2 = jax.random.split(key)
        if bootstrap:
            boot = jax.random.poisson(k1, 1.0, (n,)).astype(jnp.float32) * w
        else:  # deterministic single tree (OpDecisionTree* parity)
            boot = w
        if subsample_features:
            scores = jax.random.uniform(k2, (d,))
            thresh = jnp.sort(scores)[n_sub - 1]
            fmask = scores <= thresh
        else:
            fmask = jnp.ones((d,), bool)
        return grow_tree(Xb, Y * boot[:, None], boot, max_depth, n_bins,
                         reg_lambda=1e-6, min_child_weight=min_child_weight,
                         min_gain_norm=min_gain,
                         feature_mask=fmask, active_depth=active_depth, B=B)

    # Bound simultaneous per-tree working set: each live instance holds the
    # (n, nodes) one-hot routing matrix at the deepest level plus O(n·d)
    # gather state — cap the vmapped width and lax.map over chunks
    # (sequential, still one compile). Callers that add further batch axes
    # (the sweep's grid×fold vmaps) shrink the budget via
    # `tree_budget_divisor` so the product of live axes stays bounded.
    budget = _TREE_CHUNK_BUDGET // max(int(tree_budget_divisor), 1)
    per_instance = n * (d + 2 ** min(max_depth, 14))
    chunk = max(1, min(n_trees, budget // max(per_instance, 1)))
    if chunk == n_trees:
        return jax.vmap(one_tree)(keys)
    # pad the key array to a chunk multiple (extra trees are grown and
    # sliced off) rather than shrinking to a divisor — a prime n_trees
    # must not collapse to fully sequential growth
    n_chunks = -(-n_trees // chunk)
    pad = n_chunks * chunk - n_trees
    if pad:
        keys = jnp.concatenate([keys, keys[:pad]])
    chunked = keys.reshape(n_chunks, chunk, *keys.shape[1:])
    trees = jax.lax.map(jax.vmap(one_tree), chunked)
    return jax.tree.map(
        lambda a: a.reshape(n_chunks * chunk, *a.shape[2:])[:n_trees], trees)


_PREDICT_TREE_CHUNK = 8


def _scan_tree_chunks(trees: Dict, per_tree, acc0, chunk: int):
    """Σ_t per_tree(t) over `chunk`-tree vmapped scan steps: pads the
    tree axis to a chunk multiple with ZEROED trees (all-zero leaves
    contribute nothing), so live memory is one chunk's generated
    passes while per-tree parallelism stays."""
    n_trees = jax.tree_util.tree_leaves(trees)[0].shape[0]
    c = min(max(1, int(chunk)), n_trees)
    n_chunks = -(-n_trees // c)
    pad = n_chunks * c - n_trees
    if pad:
        trees = jax.tree.map(
            lambda a: jnp.concatenate(
                [a, jnp.zeros_like(a[:pad])]), trees)
    chunked = jax.tree.map(
        lambda a: a.reshape(n_chunks, c, *a.shape[1:]), trees)

    def body(acc, tc):
        return acc + jax.vmap(per_tree)(tc).sum(axis=0), None

    acc, _ = jax.lax.scan(body, acc0, chunked)
    return acc


def _predict_trees_sum(trees: Dict, Xb: jnp.ndarray,
                       chunk: int = _PREDICT_TREE_CHUNK) -> jnp.ndarray:
    """Σ_t predict_tree(t, Xb) as a scan of vmapped tree chunks.

    Per-tree scores accumulate CLASS-MAJOR (m, n): with the big row axis
    minor, nothing tile-pads the tiny class axis to 128 lanes (a plain
    vmap-then-sum of (c, n, m) slabs padded m→128 was the r4 RF family
    drop: 8 pairs × 50 trees × 90k rows × pad-128 f32 = 18.4 GB). The
    single (m, n) → (n, m) transpose at the end materializes one
    lane-padded (n, m→128) output — the shape every caller consumes
    anyway."""
    m = trees["leaf"].shape[-1]

    def per_tree(t):  # (m, n) class-major leaf values
        node = _tree_walk(t, Xb)
        return jnp.stack([_leaf_lookup(t["leaf"][:, cl], node)
                          for cl in range(m)], axis=0)

    return _scan_tree_chunks(
        trees, per_tree, jnp.zeros((m, Xb.shape[0]), jnp.float32), chunk).T


def _predict_trees_margin(trees: Dict, Xb: jnp.ndarray,
                          chunk: int = 64) -> jnp.ndarray:
    """Σ_t leaf value of tree t, single-output specialization: the (n,)
    accumulator + gather-free walk is the streaming-scorer hot path
    (r5: 604 → ~123 ms for the 164-tree depth-10 winner at 100k rows —
    the removed (100k,) row gathers cost ~1 ms EACH on the tunnel)."""
    def per_tree(t):
        return _leaf_lookup(t["leaf"][:, 0], _tree_walk(t, Xb))

    return _scan_tree_chunks(
        trees, per_tree, jnp.zeros((Xb.shape[0],), jnp.float32), chunk)


@partial(jax.jit, static_argnames=("chunk",))
def predict_forest(trees: Dict, Xb: jnp.ndarray,
                   chunk: int = 64) -> jnp.ndarray:
    """Mean per-tree prediction (memory-bounded; `_predict_trees_sum`).

    `chunk` trades live memory (chunk × n × 128-padded f32) for per-tree
    parallelism: model scoring uses the big default; sweep dispatches —
    where a width-8 pair vmap multiplies the slab — pass a small one."""
    n_trees = jax.tree_util.tree_leaves(trees)[0].shape[0]
    return _predict_trees_sum(trees, Xb, chunk) / jnp.float32(n_trees)


# --------------------------------------------------------------------------- #
# Gradient boosting (XGBoost-style second order)                              #
# --------------------------------------------------------------------------- #

def _gbt_val_loss(margin, y, val_w, objective: str,
                  eval_metric: str = "logloss"):
    """Per-round early-stopping metric on the held-out rows, MINIMIZED.

    "logloss": weighted logloss (binary) / MSE (squared) — cheap and
    strictly proper. "aupr" (binary only): NEGATED sort-free binned AuPR
    over 512 sigmoid buckets via one one-hot matmul — the reference's
    default XGBoost eval is maximized aucpr
    (`DefaultSelectorParams.scala:71` BinaryClassXGBEvaluationMetric), so
    the stopping round matches reference semantics; an exact sorted AuPR
    would serialize on TPU every round, the binned histogram stays on
    the MXU (90k × 512 bf16 ≈ 0.1 GFLOP/round)."""
    vs = jnp.maximum(val_w.sum(), 1.0)
    if objective == "logistic" and eval_metric == "aupr":
        nb = 512
        p = jax.nn.sigmoid(margin)
        b = jnp.minimum((p * nb).astype(jnp.int32), nb - 1)
        B = jax.nn.one_hot(b, nb, dtype=jnp.bfloat16)
        h = jnp.matmul(jnp.stack([(val_w * y).astype(jnp.bfloat16),
                                  val_w.astype(jnp.bfloat16)]), B,
                       preferred_element_type=jnp.float32)  # (2, nb)
        tp = jnp.cumsum(h[0, ::-1])
        n_at = jnp.cumsum(h[1, ::-1])
        n_pos = jnp.maximum(tp[-1], 1e-9)
        prec = jnp.where(n_at > 0, tp / jnp.maximum(n_at, 1e-30), 1.0)
        rec = tp / n_pos
        r = jnp.concatenate([jnp.zeros(1), rec])
        pr = jnp.concatenate([jnp.ones(1), prec])
        aupr = ((r[1:] - r[:-1]) * (pr[1:] + pr[:-1]) * 0.5).sum()
        return -aupr  # maximize aucpr == minimize its negation
    if objective == "logistic":
        ll = jax.nn.softplus(margin) - y * margin  # -log p(y|margin)
        return (ll * val_w).sum() / vs
    return (((margin - y) ** 2) * val_w).sum() / vs


def _gbt_scan(Xb, y, w, val_w, margin0, best0, since0, keys,
              max_depth: int, n_bins: int, learning_rate, reg_lambda,
              objective: str, min_child_weight, active_depth, gamma, alpha,
              subsample, colsample, early_stopping_rounds: int,
              min_gain_norm=0.0, eval_metric: str = "logloss"):
    """Shared traced boosting loop. Carry = (margin, best_val, since);
    with `early_stopping_rounds` > 0, a round whose start state has
    `since >= early_stopping_rounds` grows a ZEROED tree (leaf *= 0), so
    the margin freezes and the trailing trees are exact no-ops — the model
    the scan returns is the early-stopped model even though the scan's
    length is static (XGBoost semantics: stop adding trees once the eval
    metric hasn't improved for N rounds,
    `XGBoostParams.scala numEarlyStoppingRounds`)."""
    n, d = Xb.shape
    B = bins_onehot(Xb, n_bins)  # shared across all boosting rounds
    esr = int(early_stopping_rounds)

    def grads(margin):
        if objective == "logistic":
            p = jax.nn.sigmoid(margin)
            return (p - y) * w, jnp.maximum(p * (1 - p), 1e-6) * w
        return (margin - y) * w, w  # squared error

    def round_(carry, key):
        margin, best, since = carry
        k1, k2 = jax.random.split(key)
        # uniform draws in [0,1): rate 1.0 keeps everything (no-op default)
        rows = (jax.random.uniform(k1, (n,)) < subsample).astype(jnp.float32)
        fmask = jax.random.uniform(k2, (d,)) < colsample
        g, h = grads(margin)
        tree = grow_tree(Xb, (-g * rows)[:, None], h * rows, max_depth,
                         n_bins, reg_lambda=reg_lambda,
                         min_child_weight=min_child_weight,
                         min_gain=gamma, min_gain_norm=min_gain_norm,
                         feature_mask=fmask,
                         active_depth=active_depth, alpha=alpha, B=B)
        if esr > 0:
            live = (since < esr).astype(jnp.float32)
            tree["leaf"] = tree["leaf"] * live
        margin = margin + learning_rate * _leaf_lookup(
            tree["leaf"][:, 0], _tree_walk(tree, Xb))
        if esr > 0:
            m = _gbt_val_loss(margin, y, val_w, objective, eval_metric)
            improved = m < best - 1e-7
            since = jnp.where(since >= esr, since,
                              jnp.where(improved, 0, since + 1))
            best = jnp.minimum(best, m)
        return (margin, best, since), tree

    return jax.lax.scan(round_, (margin0, best0, since0), keys)


@partial(jax.jit, static_argnames=("n_estimators", "max_depth", "n_bins",
                                   "objective", "early_stopping_rounds",
                                   "eval_metric"))
def fit_gbt(Xb, y, w, n_estimators: int, max_depth: int, n_bins: int,
            learning_rate, reg_lambda, objective: str = "logistic",
            min_child_weight: float = 1.0, active_depth=None,
            gamma=0.0, alpha=0.0, subsample=1.0, colsample=1.0, seed=0,
            val_w=None, early_stopping_rounds: int = 0, min_gain_norm=0.0,
            eval_metric: str = "logloss"):
    """Returns (trees, final_margin): the scan carry already holds the full
    training-matrix margin, so sweep callers need not re-walk the forest.

    XGBoost param surface (OpXGBoostClassifier.scala / XGBoostParams.scala):
    `gamma` = min split gain, `alpha` = leaf L1, `subsample` = per-round
    row sampling, `colsample` = per-tree feature sampling; `val_w` +
    `early_stopping_rounds` = numEarlyStoppingRounds over a held-out row
    mask (trailing rounds after the stop are zeroed trees)."""
    n = Xb.shape[0]
    if val_w is None:
        val_w = jnp.zeros(n, jnp.float32)
        early_stopping_rounds = 0
    keys = jax.random.split(jax.random.PRNGKey(seed), n_estimators)
    (margin, _, _), trees = _gbt_scan(
        Xb, y, w, val_w, jnp.zeros(n, jnp.float32), jnp.float32(jnp.inf),
        jnp.int32(0), keys, max_depth, n_bins, learning_rate, reg_lambda,
        objective, min_child_weight, active_depth, gamma, alpha, subsample,
        colsample, early_stopping_rounds, min_gain_norm, eval_metric)
    return trees, margin


@partial(jax.jit, static_argnames=("n_rounds", "max_depth", "n_bins",
                                   "objective", "early_stopping_rounds",
                                   "eval_metric"))
def fit_gbt_chunk(Xb, y, w, val_w, margin, best, since, keys,
                  n_rounds: int, max_depth: int, n_bins: int,
                  learning_rate, reg_lambda, objective: str,
                  min_child_weight, active_depth, gamma, alpha,
                  subsample, colsample, early_stopping_rounds: int,
                  min_gain_norm=0.0, eval_metric: str = "logloss"):
    """One host-dispatched chunk of boosting rounds carrying the
    early-stopping state. A 200-round depth-10 fit at 100k rows exceeds
    the ~60s single-execution serving ceiling as ONE program; the sweep
    engine instead calls this per `rounds_per_dispatch` slice of the key
    array, keeping each execution seconds-long, and stops dispatching
    entirely once every vmapped pair reports `since >= early_stopping_
    rounds` — real compute savings on top of the in-scan masking.
    Returns ((margin, best, since), trees_chunk)."""
    return _gbt_scan(Xb, y, w, val_w, margin, best, since, keys,
                     max_depth, n_bins, learning_rate, reg_lambda, objective,
                     min_child_weight, active_depth, gamma, alpha,
                     subsample, colsample, early_stopping_rounds,
                     min_gain_norm, eval_metric)


def _pick_rounds_per_dispatch(n_estimators: int, ideal: int) -> int:
    """Largest divisor of `n_estimators` ≤ `ideal` — equal-size chunks mean
    ONE compiled chunk shape. A pathological divisor structure (prime
    round counts) falls back to `ideal` with a separately-compiled tail."""
    ideal = max(1, min(ideal, n_estimators))
    best = max(d for d in range(1, ideal + 1) if n_estimators % d == 0)
    return best if best * 2 >= ideal else ideal


def _default_rounds_per_dispatch(n: int, d: int, n_estimators: int,
                                 max_depth: int, n_bins: int) -> int:
    """~0.2s/round at the r2-measured 1.1e-12 s/unit on 90k×55×32×2^10;
    target a handful of seconds per dispatch (the axon serving layer
    kills single executions past ~60s)."""
    unit = n * (2 ** min(max_depth, 14)) * d * n_bins
    return _pick_rounds_per_dispatch(
        n_estimators, max(1, int(2.5e13 // max(unit, 1))))


def fit_gbt_hosted(Xb, y, w, n_estimators: int, max_depth: int, n_bins: int,
                   learning_rate, reg_lambda, objective: str = "logistic",
                   min_child_weight: float = 1.0, gamma=0.0, alpha=0.0,
                   subsample=1.0, colsample=1.0, seed=0, val_w=None,
                   early_stopping_rounds: int = 0,
                   rounds_per_dispatch: Optional[int] = None,
                   min_gain_norm=0.0, eval_metric: str = "logloss"):
    """Host-chunked boosting: bitwise-identical trees/margin to `fit_gbt`
    (same key stream, same scan body) but dispatched `rounds_per_dispatch`
    rounds at a time so no single XLA execution can hit the ~60s serving
    kill, and early stopping SKIPS the remaining dispatches instead of
    masking them. Used for refits whose full scan would be tens of
    seconds (200-round depth-10 at 100k rows)."""
    n, d = Xb.shape
    esr = int(early_stopping_rounds) if val_w is not None else 0
    if val_w is None:
        val_w = jnp.zeros(n, jnp.float32)
    if rounds_per_dispatch is None:
        rounds_per_dispatch = _default_rounds_per_dispatch(
            n, d, n_estimators, max_depth, n_bins)
    keys = jax.random.split(jax.random.PRNGKey(seed), n_estimators)
    margin = jnp.zeros(n, jnp.float32)
    best = jnp.float32(jnp.inf)
    since = jnp.int32(0)
    chunks = []
    done = 0
    while done < n_estimators:
        ks = keys[done:done + rounds_per_dispatch]
        (margin, best, since), trees = fit_gbt_chunk(
            Xb, y, w, val_w, margin, best, since, ks, int(ks.shape[0]),
            max_depth, n_bins, learning_rate, reg_lambda, objective,
            min_child_weight, None, gamma, alpha, subsample, colsample, esr,
            min_gain_norm, eval_metric)
        chunks.append(trees)
        done += int(ks.shape[0])
        if esr and int(since) >= esr:
            break  # remaining rounds would all be zeroed no-op trees
    trees = jax.tree.map(lambda *a: jnp.concatenate(a, 0), *chunks)
    return trees, margin


@partial(jax.jit, static_argnames=("n_estimators", "max_depth", "n_bins",
                                   "n_classes"))
def fit_gbt_multiclass(Xb, y, w, n_estimators: int, max_depth: int,
                       n_bins: int, n_classes: int, learning_rate,
                       reg_lambda, min_child_weight: float = 1.0,
                       active_depth=None, gamma=0.0, alpha=0.0,
                       subsample=1.0, colsample=1.0, seed=0,
                       min_gain_norm=0.0):
    """Softmax boosting: K one-vs-rest trees per round grown from the
    multinomial gradients (the reference's XGBoost multi:softprob —
    OpXGBoostClassifier.scala:47 supports multiclass; the r1 facade was
    binary-only). Returns (trees with (T, K, ...) leaves, (n, K) margin)."""
    n, d = Xb.shape
    Y = jax.nn.one_hot(y.astype(jnp.int32), n_classes)
    B = bins_onehot(Xb, n_bins)  # shared across rounds and classes

    def round_(margin, key):
        k1, k2 = jax.random.split(key)
        rows = (jax.random.uniform(k1, (n,)) < subsample).astype(jnp.float32)
        fmask = jax.random.uniform(k2, (d,)) < colsample
        p = jax.nn.softmax(margin, axis=1)
        G = (p - Y) * w[:, None]
        Hs = jnp.maximum(p * (1.0 - p), 1e-6) * w[:, None]

        def per_class(g, h):
            return grow_tree(Xb, (-g * rows)[:, None], h * rows, max_depth,
                             n_bins, reg_lambda=reg_lambda,
                             min_child_weight=min_child_weight,
                             min_gain=gamma, min_gain_norm=min_gain_norm,
                             feature_mask=fmask,
                             active_depth=active_depth, alpha=alpha, B=B)

        trees_k = jax.vmap(per_class, in_axes=(1, 1))(G, Hs)  # (K, ...)
        upd = jax.vmap(lambda t: _leaf_lookup(
            t["leaf"][:, 0], _tree_walk(t, Xb)))(trees_k)  # (K, n)
        return margin + learning_rate * upd.T, trees_k

    keys = jax.random.split(jax.random.PRNGKey(seed), n_estimators)
    base = jnp.zeros((n, n_classes), jnp.float32)
    margin, trees = jax.lax.scan(round_, base, keys)
    return trees, margin


@partial(jax.jit, static_argnames=())
def predict_gbt_multiclass_margin(trees: Dict, Xb: jnp.ndarray,
                                  learning_rate) -> jnp.ndarray:
    """(n, K) margin from (T, K, ...) stacked round trees."""
    per_round = jax.vmap(         # over rounds
        jax.vmap(lambda t: _leaf_lookup(
            t["leaf"][:, 0], _tree_walk(t, Xb))))(trees)  # (T, K, n)
    return learning_rate * per_round.sum(axis=0).T


def gbt_multiclass_pred_from_margin(margin: jnp.ndarray) -> Dict:
    probs = jax.nn.softmax(margin, axis=1)
    return {"prediction": jnp.argmax(probs, 1).astype(jnp.float32),
            "rawPrediction": margin, "probability": probs}


@partial(jax.jit, static_argnames=("chunk",))
def predict_gbt_margin(trees: Dict, Xb: jnp.ndarray, learning_rate,
                       chunk: int = 64) -> jnp.ndarray:
    return learning_rate * _predict_trees_margin(trees, Xb, chunk)


# --------------------------------------------------------------------------- #
# shared prediction assembly (model classes AND the sweep engine use these,   #
# so sweep metrics always describe exactly what the refit model predicts)     #
# --------------------------------------------------------------------------- #

def forest_classification_pred(trees: Dict, Xb: jnp.ndarray,
                               chunk: int = 64) -> Dict:
    probs = predict_forest(trees, Xb, chunk)
    probs = probs / jnp.maximum(probs.sum(-1, keepdims=True), 1e-9)
    return {"prediction": jnp.argmax(probs, -1).astype(jnp.float32),
            "rawPrediction": probs, "probability": probs}


def forest_regression_pred(trees: Dict, Xb: jnp.ndarray,
                           chunk: int = 64) -> Dict:
    pred = predict_forest(trees, Xb, chunk)[:, 0]
    return {"prediction": pred, "rawPrediction": pred[:, None],
            "probability": jnp.zeros((Xb.shape[0], 0), jnp.float32)}


def gbt_pred_from_margin(margin: jnp.ndarray, objective: str) -> Dict:
    if objective == "logistic":
        p1 = jax.nn.sigmoid(margin)
        return {"prediction": (margin > 0).astype(jnp.float32),
                "rawPrediction": jnp.stack([-margin, margin], 1),
                "probability": jnp.stack([1 - p1, p1], axis=1)}
    return {"prediction": margin, "rawPrediction": margin[:, None],
            "probability": jnp.zeros((margin.shape[0], 0), jnp.float32)}


# --------------------------------------------------------------------------- #
# Warm-start refits (continual training)                                      #
# --------------------------------------------------------------------------- #

def warm_tree_compatible(warm: Dict, X,
                         n_classes: Optional[int] = None,
                         max_bins: Optional[int] = None) -> bool:
    """Host-side validation of a tree warm-start payload against the
    incoming data — the `resolve_init_params` analogue for forests/GBT.
    The resident bin edges must match the feature width, the
    estimator's `max_bins` histogram must cover every resident bin id
    (rows binned past it would one-hot to all zeros and silently vanish
    from split decisions), and for classification the resident leaf
    width must cover every observed class: `one_hot` of an unseen class
    under the old width is all zeros, so a mismatched warm refit would
    silently mistrain instead of erroring. Returns False → the caller
    fits cold."""
    edges = np.asarray(warm["edges"])
    d = int(np.shape(X)[1])
    if int(edges.shape[0]) != d:
        log.info("tree warm refit: feature width changed (%d -> %d); "
                 "fitting cold", int(edges.shape[0]), d)
        return False
    if max_bins is not None and int(edges.shape[1]) + 1 > int(max_bins):
        log.info("tree warm refit: resident edges bin to %d buckets but "
                 "the estimator's max_bins is %d; fitting cold",
                 int(edges.shape[1]) + 1, int(max_bins))
        return False
    if n_classes is not None:
        leaf = np.asarray(warm["trees"]["leaf"])
        if int(leaf.shape[-1]) < int(n_classes):
            log.info("tree warm refit: resident leaves are %d-class but "
                     "the data has %d classes; fitting cold",
                     int(leaf.shape[-1]), int(n_classes))
            return False
    return True


def warm_refit_forest(est, warm: Dict, X, y, w, ctx,
                      classification: bool) -> Dict:
    """Forest warm refit: grow replacement trees on the DELTA rows and
    swap them in for the OLDEST trees of the resident ensemble, keeping
    the ensemble size (and therefore every compiled predict shape)
    fixed. `warm` is a fitted tree model's params ({"edges", "trees"})
    plus an optional "delta_rows" count of trailing new rows; without
    it the replacements grow on the full matrix.

    The resident bin edges are reused — re-binning under new quantiles
    would silently shift every surviving tree's split semantics.
    Returns the combined {"feat", "bin", "leaf"} pytree (host arrays)."""
    edges = jnp.asarray(np.asarray(warm["edges"], np.float32))
    old = {k: jnp.asarray(v) for k, v in warm["trees"].items()}
    n_trees = int(old["feat"].shape[0])
    delta = int(warm.get("delta_rows") or 0)
    if not (0 < delta <= X.shape[0]):
        delta = X.shape[0]
    n_new = int(warm.get("n_new") or 0)
    if n_new <= 0:
        # replacement count scales with how much of the data is new,
        # floored at one tree so a refit always learns something
        n_new = max(1, round(n_trees * delta / max(X.shape[0], 1)))
    n_new = min(n_new, n_trees)
    Xd = jnp.asarray(X)[-delta:]
    yd = jnp.asarray(y)[-delta:]
    wd = jnp.asarray(w)[-delta:]
    Xb = bin_features(Xd, edges)
    if classification:
        k = int(old["leaf"].shape[-1])
        Y = jax.nn.one_hot(yd.astype(jnp.int32), k)
    else:
        Y = yd[:, None]
    seed = (ctx.seed if ctx is not None else 0) + n_trees  # fresh draws
    new = fit_forest(Xb, Y, wd, n_new, est.max_depth, est.max_bins,
                     Y.shape[1], seed, est.subsample_features,
                     est._effective_mcw(),
                     min_gain=jnp.float32(est.min_info_gain))
    combined = jax.tree.map(
        lambda o, nw: jnp.concatenate([o[n_new:], nw], axis=0), old, new)
    return {k2: np.asarray(v) for k2, v in combined.items()}


def warm_refit_gbt(est, warm: Dict, X, y, w, ctx,
                   objective: str) -> Dict:
    """GBT warm refit: CONTINUE boosting from the resident ensemble's
    margin instead of restarting from zero — the new rounds fit the
    residual the old trees leave on the refreshed data (appended rows
    included), and the grown trees append to the ensemble. Binary /
    regression objectives only (the multiclass stacked-round layout
    falls back to a cold fit at the call site)."""
    edges = jnp.asarray(np.asarray(warm["edges"], np.float32))
    old = {k: jnp.asarray(v) for k, v in warm["trees"].items()}
    n_old = int(old["feat"].shape[0])
    lr = jnp.float32(warm.get("learning_rate", est.learning_rate))
    Xb = bin_features(jnp.asarray(X), edges)
    n = Xb.shape[0]
    margin0 = predict_gbt_margin(old, Xb, lr)
    n_extra = int(warm.get("n_new") or 0)
    if n_extra <= 0:
        n_extra = max(1, est.n_estimators // 4)
    # growth cap: an always-on loop must not boost the ensemble (and
    # every compiled predict shape, and HBM) without bound — the call
    # site falls back to a cold fit once the 2x ceiling is reached
    n_extra = min(n_extra,
                  max(1, 2 * int(est.n_estimators) - n_old))
    # key stream folded past the resident rounds: warm rounds draw fresh
    # subsample/colsample randomness, deterministically per (seed, round)
    seed = ctx.seed if ctx is not None else 0
    keys = jax.random.split(
        jax.random.fold_in(jax.random.PRNGKey(seed), n_old), n_extra)
    (_, _, _), new = fit_gbt_chunk(
        Xb, jnp.asarray(y), jnp.asarray(w), jnp.zeros(n, jnp.float32),
        margin0, jnp.float32(jnp.inf), jnp.int32(0), keys, n_extra,
        est.max_depth, est.max_bins, lr, jnp.float32(est.reg_lambda),
        objective, est._effective_mcw(), None, jnp.float32(est.gamma),
        jnp.float32(est.alpha), jnp.float32(est.subsample),
        jnp.float32(est.colsample_bytree), 0,
        jnp.float32(est.min_info_gain), est.eval_metric)
    combined = jax.tree.map(
        lambda o, nw: jnp.concatenate([o, nw], axis=0), old, new)
    return {k2: np.asarray(v) for k2, v in combined.items()}


# --------------------------------------------------------------------------- #
# Stage classes                                                               #
# --------------------------------------------------------------------------- #

class _TreeModelBase(PredictionModel):
    def __init__(self, edges=None, trees=None, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.edges = np.asarray(edges, dtype=np.float32)
        self.trees = {k: np.asarray(v) for k, v in trees.items()}

    def get_params(self):
        # ndarrays straight through: serialization offloads them to npz —
        # .tolist() would round-trip megabytes of leaves as PyObjects
        return {"edges": self.edges, "trees": dict(self.trees)}

    def _binned(self, X):
        return bin_features(jnp.asarray(X), jnp.asarray(self.edges))

    def _tree_pytree(self):
        return {"feat": jnp.asarray(self.trees["feat"], jnp.int32),
                "bin": jnp.asarray(self.trees["bin"], jnp.int32),
                "leaf": jnp.asarray(self.trees["leaf"], jnp.float32)}

    # megabyte-scale fitted arrays (a depth-12 forest is ~8MB) flow into
    # the compiled scorer as jit arguments, not closure constants
    def device_constants(self):
        return {"edges": jnp.asarray(self.edges),
                "trees": self._tree_pytree()}

    def narrow_device_constants(self, consts):
        """Quantized-inference dtypes for the tables the predict walk
        re-reads every level. Gates are SHAPE facts only (so every model
        sharing a scoring signature narrows to identical traced dtypes):
        split-feature ids fit int16 when d < 2^15, split-bin thresholds
        fit uint8 when there are at most 255 edges (bin ids <= n_edges),
        both lossless; threshold EDGES drop to f16 — lossy at f16 eps,
        inside the quantized mode's stated wire tolerance. Leaves stay
        f32 (tiny, and they carry the output precision)."""
        edges = consts["edges"]
        trees = dict(consts["trees"])
        d, n_edges = int(edges.shape[0]), int(edges.shape[1])
        if d < (1 << 15):
            trees["feat"] = trees["feat"].astype(jnp.int16)
        if n_edges <= 255:
            trees["bin"] = trees["bin"].astype(jnp.uint8)
        return {"edges": edges.astype(jnp.float16), "trees": trees}

    def device_apply_with(self, consts, enc, dev):
        return self._apply_arrays(consts["trees"],
                                  bin_features(jnp.asarray(dev[-1]),
                                               consts["edges"]))

    def predict_arrays(self, X):
        return self._apply_arrays(self._tree_pytree(), self._binned(X))

    def _apply_arrays(self, trees, Xb):
        raise NotImplementedError(type(self).__name__)


class ForestClassificationModel(_TreeModelBase):
    def _apply_arrays(self, trees, Xb):
        return forest_classification_pred(trees, Xb)


class ForestRegressionModel(_TreeModelBase):
    def _apply_arrays(self, trees, Xb):
        return forest_regression_pred(trees, Xb)


class GBTClassificationModel(_TreeModelBase):
    def __init__(self, edges=None, trees=None, learning_rate: float = 0.1,
                 uid: Optional[str] = None):
        super().__init__(edges=edges, trees=trees, uid=uid)
        self.learning_rate = learning_rate

    def get_params(self):
        d = super().get_params()
        d["learning_rate"] = self.learning_rate
        return d

    def _apply_arrays(self, trees, Xb):
        margin = predict_gbt_margin(trees, Xb,
                                    jnp.float32(self.learning_rate))
        return gbt_pred_from_margin(margin, "logistic")


class GBTRegressionModel(GBTClassificationModel):
    def _apply_arrays(self, trees, Xb):
        margin = predict_gbt_margin(trees, Xb,
                                    jnp.float32(self.learning_rate))
        return gbt_pred_from_margin(margin, "squared")


class GBTMulticlassModel(GBTClassificationModel):
    """Softmax forest: trees stacked (rounds, classes, ...)."""

    def _apply_arrays(self, trees, Xb):
        margin = predict_gbt_multiclass_margin(
            trees, Xb, jnp.float32(self.learning_rate))
        return gbt_multiclass_pred_from_margin(margin)


class _TreeEstimatorBase(PredictorEstimator):
    # Optional shared binning cache (max_bins → (edges, Xb)) used by the
    # sweep engine's HOST-loop fallback (`parallel/sweep.py:_sweep_generic`)
    # so repeated grid×fold fits bin the training matrix once. The batched
    # sweep path keeps its own per-family cache (`parallel/sweep.py:_binned`).
    _bin_cache: Optional[Dict] = None

    def _edges_binned(self, X, ctx):
        cache = self._bin_cache
        if cache is not None and self.max_bins in cache:
            return cache[self.max_bins]
        edges = quantile_bin_edges(np.asarray(X), self.max_bins)
        Xb = bin_features(jnp.asarray(X), jnp.asarray(edges))
        if cache is not None:
            cache[self.max_bins] = (edges, Xb)
        return edges, Xb


class OpRandomForestClassifier(_TreeEstimatorBase):
    """Spark RandomForestClassifier param surface: `min_info_gain`
    (minInfoGain — gini-improvement threshold, normalized gain scale) and
    `min_instances_per_node` (minInstancesPerNode — with count weights this
    is the child-weight bound) are grid axes in the reference defaults
    (`DefaultSelectorParams.scala:38-39`)."""

    def __init__(self, n_trees: int = 20, max_depth: int = 5,
                 max_bins: int = DEFAULT_MAX_BINS, min_child_weight: float = 1.0,
                 subsample_features: bool = True, min_info_gain: float = 0.0,
                 min_instances_per_node: float = 1.0,
                 n_classes: Optional[int] = None, uid: Optional[str] = None):
        super().__init__(uid=uid, n_trees=n_trees, max_depth=max_depth,
                         max_bins=max_bins, min_child_weight=min_child_weight,
                         subsample_features=subsample_features,
                         min_info_gain=min_info_gain,
                         min_instances_per_node=min_instances_per_node,
                         n_classes=n_classes)
        self.n_trees = n_trees
        self.max_depth = max_depth
        self.max_bins = max_bins
        self.min_child_weight = min_child_weight
        self.subsample_features = subsample_features
        self.min_info_gain = min_info_gain
        self.min_instances_per_node = min_instances_per_node
        self.n_classes = n_classes

    def _effective_mcw(self) -> float:
        return max(float(self.min_child_weight),
                   float(self.min_instances_per_node))

    def fit_arrays(self, X, y, w, ctx: FitContext):
        k = self.n_classes or infer_n_classes(np.asarray(y))
        warm = self.init_params
        if warm is not None and "trees" in warm and \
                warm_tree_compatible(warm, X, n_classes=k,
                                     max_bins=self.max_bins):
            trees = warm_refit_forest(self, warm, X, y, w, ctx,
                                      classification=True)
            return ForestClassificationModel(
                np.asarray(warm["edges"], np.float32), trees)
        edges, Xb = self._edges_binned(X, ctx)
        Y = jax.nn.one_hot(y.astype(jnp.int32), k)
        trees = fit_forest(Xb, Y, w, self.n_trees, self.max_depth,
                           self.max_bins, k, ctx.seed,
                           self.subsample_features, self._effective_mcw(),
                           min_gain=jnp.float32(self.min_info_gain))
        return ForestClassificationModel(edges, {k2: np.asarray(v)
                                                 for k2, v in trees.items()})


class OpRandomForestRegressor(OpRandomForestClassifier):
    def fit_arrays(self, X, y, w, ctx: FitContext):
        warm = self.init_params
        if warm is not None and "trees" in warm and \
                warm_tree_compatible(warm, X, max_bins=self.max_bins):
            trees = warm_refit_forest(self, warm, X, y, w, ctx,
                                      classification=False)
            return ForestRegressionModel(
                np.asarray(warm["edges"], np.float32), trees)
        edges, Xb = self._edges_binned(X, ctx)
        trees = fit_forest(Xb, y[:, None], w, self.n_trees, self.max_depth,
                           self.max_bins, 1, ctx.seed,
                           self.subsample_features, self._effective_mcw(),
                           min_gain=jnp.float32(self.min_info_gain))
        return ForestRegressionModel(edges, {k: np.asarray(v)
                                             for k, v in trees.items()})


class OpDecisionTreeClassifier(OpRandomForestClassifier):
    """Single deterministic tree (no bootstrap, all features)."""

    def __init__(self, max_depth: int = 5, max_bins: int = DEFAULT_MAX_BINS,
                 min_child_weight: float = 1.0, min_info_gain: float = 0.0,
                 min_instances_per_node: float = 1.0,
                 n_classes: Optional[int] = None,
                 uid: Optional[str] = None):
        super().__init__(n_trees=1, max_depth=max_depth, max_bins=max_bins,
                         min_child_weight=min_child_weight,
                         min_info_gain=min_info_gain,
                         min_instances_per_node=min_instances_per_node,
                         subsample_features=False, n_classes=n_classes, uid=uid)
        self.params = {"max_depth": max_depth, "max_bins": max_bins,
                       "min_child_weight": min_child_weight,
                       "min_info_gain": min_info_gain,
                       "min_instances_per_node": min_instances_per_node,
                       "n_classes": n_classes}

    def fit_arrays(self, X, y, w, ctx: FitContext):
        k = self.n_classes or infer_n_classes(np.asarray(y))
        edges, Xb = self._edges_binned(X, ctx)
        Y = jax.nn.one_hot(y.astype(jnp.int32), k)
        tree = grow_tree(Xb, Y * w[:, None], w, self.max_depth, self.max_bins,
                         reg_lambda=1e-6,
                         min_child_weight=self._effective_mcw(),
                         min_gain_norm=jnp.float32(self.min_info_gain))
        trees = jax.tree.map(lambda a: a[None], tree)  # (1, ...) forest shape
        return ForestClassificationModel(edges, {k2: np.asarray(v)
                                                 for k2, v in trees.items()})


class OpDecisionTreeRegressor(OpRandomForestRegressor):
    def __init__(self, max_depth: int = 5, max_bins: int = DEFAULT_MAX_BINS,
                 min_child_weight: float = 1.0, min_info_gain: float = 0.0,
                 min_instances_per_node: float = 1.0,
                 uid: Optional[str] = None):
        super().__init__(n_trees=1, max_depth=max_depth, max_bins=max_bins,
                         min_child_weight=min_child_weight,
                         min_info_gain=min_info_gain,
                         min_instances_per_node=min_instances_per_node,
                         subsample_features=False, uid=uid)
        self.params = {"max_depth": max_depth, "max_bins": max_bins,
                       "min_child_weight": min_child_weight,
                       "min_info_gain": min_info_gain,
                       "min_instances_per_node": min_instances_per_node}

    def fit_arrays(self, X, y, w, ctx: FitContext):
        edges, Xb = self._edges_binned(X, ctx)
        tree = grow_tree(Xb, (y * w)[:, None], w, self.max_depth, self.max_bins,
                         reg_lambda=1e-6,
                         min_child_weight=self._effective_mcw(),
                         min_gain_norm=jnp.float32(self.min_info_gain))
        trees = jax.tree.map(lambda a: a[None], tree)
        return ForestRegressionModel(edges, {k: np.asarray(v)
                                             for k, v in trees.items()})


class OpGBTClassifier(_TreeEstimatorBase):
    """Gradient-boosted classifier, XGBoost-style 2nd order: binary via
    sigmoid margin, multiclass via softmax boosting (K trees/round)."""

    def __init__(self, n_estimators: int = 20, max_depth: int = 3,
                 learning_rate: float = 0.1, reg_lambda: float = 1.0,
                 max_bins: int = DEFAULT_MAX_BINS, min_child_weight: float = 1.0,
                 gamma: float = 0.0, alpha: float = 0.0,
                 subsample: float = 1.0, colsample_bytree: float = 1.0,
                 early_stopping_rounds: int = 0, min_info_gain: float = 0.0,
                 min_instances_per_node: float = 1.0,
                 eval_metric: str = "logloss",
                 n_classes: Optional[int] = None, uid: Optional[str] = None):
        super().__init__(uid=uid, n_estimators=n_estimators, max_depth=max_depth,
                         learning_rate=learning_rate, reg_lambda=reg_lambda,
                         max_bins=max_bins, min_child_weight=min_child_weight,
                         gamma=gamma, alpha=alpha, subsample=subsample,
                         colsample_bytree=colsample_bytree,
                         early_stopping_rounds=early_stopping_rounds,
                         min_info_gain=min_info_gain,
                         min_instances_per_node=min_instances_per_node,
                         eval_metric=eval_metric,
                         n_classes=n_classes)
        self.n_estimators = n_estimators
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.reg_lambda = reg_lambda
        self.max_bins = max_bins
        self.min_child_weight = min_child_weight
        self.gamma = gamma
        self.alpha = alpha
        self.subsample = subsample
        self.colsample_bytree = colsample_bytree
        self.early_stopping_rounds = early_stopping_rounds
        # Spark GBTClassifier/Regressor parity knobs (the regression
        # default grid sweeps them, DefaultSelectorParams.scala:38-39);
        # min_info_gain uses the NORMALIZED gain scale, XGBoost's `gamma`
        # stays raw
        self.min_info_gain = min_info_gain
        self.min_instances_per_node = min_instances_per_node
        # early-stopping eval: "logloss" (Spark-ish strictly-proper
        # default) or "aupr" (the reference's maximized XGBoost aucpr,
        # DefaultSelectorParams.scala:71 — OpXGBoostClassifier's default)
        self.eval_metric = eval_metric
        self.n_classes = n_classes

    def _effective_mcw(self) -> float:
        return max(float(self.min_child_weight),
                   float(self.min_instances_per_node))

    _objective = "logistic"
    _model_cls = GBTClassificationModel

    # refit early-stopping eval fraction: like the XGBoost sklearn
    # `eval_set` idiom, a seeded 20% of the training rows is held out of
    # the boosting gradients and drives numEarlyStoppingRounds when no
    # explicit eval split exists (the reference CV sweep evals on the
    # fold's validation rows; the refit has no fold)
    _ES_EVAL_FRACTION = 0.2

    def fit_arrays(self, X, y, w, ctx: FitContext):
        if self._objective == "logistic":
            k = self.n_classes or infer_n_classes(np.asarray(y))
        else:
            k = 2
        warm = self.init_params
        if warm is not None and "trees" in warm:
            n_resident = int(np.asarray(warm["trees"]["feat"]).shape[0])
            if self._objective == "logistic" and k > 2:
                log.info("GBT warm refit: multiclass stacked-round layout "
                         "has no margin-continuation path; fitting cold")
            elif n_resident >= 2 * self.n_estimators:
                log.info("GBT warm refit: resident ensemble at the 2x "
                         "growth cap (%d rounds vs n_estimators=%d); "
                         "fitting cold to reset the ensemble size",
                         n_resident, self.n_estimators)
            elif not warm_tree_compatible(warm, X,
                                          max_bins=self.max_bins):
                pass  # logged: shape drift falls back to a cold fit
            else:
                trees = warm_refit_gbt(self, warm, X, y, w, ctx,
                                       self._objective)
                return self._model_cls(
                    np.asarray(warm["edges"], np.float32), trees,
                    float(warm.get("learning_rate", self.learning_rate)))
        edges, Xb = self._edges_binned(X, ctx)
        seed = ctx.seed if ctx is not None else 0
        if self._objective == "logistic" and k > 2:
            trees, _ = fit_gbt_multiclass(
                Xb, y, w, self.n_estimators, self.max_depth, self.max_bins,
                k, jnp.float32(self.learning_rate),
                jnp.float32(self.reg_lambda), self._effective_mcw(),
                gamma=jnp.float32(self.gamma), alpha=jnp.float32(self.alpha),
                subsample=jnp.float32(self.subsample),
                colsample=jnp.float32(self.colsample_bytree), seed=seed,
                min_gain_norm=jnp.float32(self.min_info_gain))
            return GBTMulticlassModel(
                edges, {k2: np.asarray(v) for k2, v in trees.items()},
                self.learning_rate)
        esr = int(self.early_stopping_rounds or 0)
        n_rounds = self.n_estimators
        if esr > 0:
            # Pass 1 — round-count search: hold a seeded 20% of rows out
            # of the boosting gradients and let numEarlyStoppingRounds
            # pick the effective round count. The probe model is thrown
            # away: the reference's xgboost4j-spark refit trains on ALL
            # rows (trainTestRatio default 1.0), so shipping the
            # 80%-trained model silently changed default behavior
            # (r3 advisor, medium).
            rng = np.random.default_rng(seed)
            hold = jnp.asarray(
                rng.uniform(size=Xb.shape[0]) < self._ES_EVAL_FRACTION,
                dtype=jnp.float32)
            probe, _ = fit_gbt_hosted(
                Xb, y, (1.0 - hold) * w, self.n_estimators, self.max_depth,
                self.max_bins, jnp.float32(self.learning_rate),
                jnp.float32(self.reg_lambda), self._objective,
                self._effective_mcw(), gamma=jnp.float32(self.gamma),
                alpha=jnp.float32(self.alpha),
                subsample=jnp.float32(self.subsample),
                colsample=jnp.float32(self.colsample_bytree),
                seed=seed, val_w=hold * w, early_stopping_rounds=esr,
                min_gain_norm=jnp.float32(self.min_info_gain),
                eval_metric=self.eval_metric)
            # stopped rounds grow ZEROED trees, so the probe's stopping
            # round is the LAST live tree's index + 1 — counting live
            # trees instead would undercount when a mid-sequence tree is
            # fully pruned by gamma/min_info_gain (all-zero leaves while
            # boosting continued; r4 advisor)
            leaf = np.asarray(probe["leaf"])
            live = np.any(leaf != 0, axis=tuple(range(1, leaf.ndim)))
            n_live = int(np.flatnonzero(live).max()) + 1 if live.any() else 1
            # quantize UP to a multiple of the probe's dispatch chunk so
            # the refit reuses the already-compiled chunk program (a
            # fresh XLA shape costs 15-50s through the remote-AOT
            # service); the ≤R-1 extra rounds match XGBoost's default of
            # predicting with post-best-iteration trees included
            rpd = _default_rounds_per_dispatch(
                Xb.shape[0], Xb.shape[1], self.n_estimators,
                self.max_depth, self.max_bins)
            n_rounds = min(-(-n_live // rpd) * rpd, self.n_estimators)
            rpd_refit = rpd
        else:
            rpd_refit = None
        # Pass 2 (or the only pass) — the shipped model: full weights,
        # fixed round count, no holdout.
        trees, _ = fit_gbt_hosted(
            Xb, y, w, n_rounds, self.max_depth,
            self.max_bins, jnp.float32(self.learning_rate),
            jnp.float32(self.reg_lambda), self._objective,
            self._effective_mcw(),
            gamma=jnp.float32(self.gamma),
            alpha=jnp.float32(self.alpha),
            subsample=jnp.float32(self.subsample),
            colsample=jnp.float32(self.colsample_bytree),
            seed=seed, rounds_per_dispatch=rpd_refit,
            min_gain_norm=jnp.float32(self.min_info_gain))
        return self._model_cls(edges, {k2: np.asarray(v) for k2, v in trees.items()},
                               self.learning_rate)


class OpGBTRegressor(OpGBTClassifier):
    _objective = "squared"
    _model_cls = GBTRegressionModel


class OpXGBoostClassifier(OpGBTClassifier):
    """XGBoost parameter surface (OpXGBoostClassifier.scala:47,
    XGBoostParams.scala:55-69): eta / gamma / alpha / lambda / subsample /
    colsample_bytree / min_child_weight, binary AND multiclass objectives.
    The in-tree GBT implements the XGBoost histogram + second-order
    algorithm natively; Rabit allreduce becomes a psum over the sharded
    batch axis."""

    def __init__(self, n_estimators: int = 50, max_depth: int = 6,
                 eta: float = 0.3, reg_lambda: float = 1.0,
                 max_bins: int = DEFAULT_MAX_BINS,
                 min_child_weight: float = 1.0, gamma: float = 0.0,
                 alpha: float = 0.0, subsample: float = 1.0,
                 colsample_bytree: float = 1.0,
                 early_stopping_rounds: int = 0, min_info_gain: float = 0.0,
                 min_instances_per_node: float = 1.0,
                 eval_metric: str = "aupr",
                 n_classes: Optional[int] = None, uid: Optional[str] = None):
        super().__init__(n_estimators=n_estimators, max_depth=max_depth,
                         learning_rate=eta, reg_lambda=reg_lambda,
                         max_bins=max_bins, min_child_weight=min_child_weight,
                         gamma=gamma, alpha=alpha, subsample=subsample,
                         colsample_bytree=colsample_bytree,
                         early_stopping_rounds=early_stopping_rounds,
                         min_info_gain=min_info_gain,
                         min_instances_per_node=min_instances_per_node,
                         eval_metric=eval_metric,
                         n_classes=n_classes, uid=uid)
        self.params["eta"] = eta
        self.params.pop("learning_rate", None)


class OpXGBoostRegressor(OpXGBoostClassifier):
    _objective = "squared"
    _model_cls = GBTRegressionModel
