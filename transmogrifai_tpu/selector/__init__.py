from transmogrifai_tpu.selector.splitters import (
    DataSplitter, DataBalancer, DataCutter, SplitterSummary)
from transmogrifai_tpu.selector.validators import (
    OpCrossValidation, OpTrainValidationSplit)
from transmogrifai_tpu.selector.grids import ParamGridBuilder, RandomParamBuilder
from transmogrifai_tpu.selector.model_selector import (
    ModelSelector, ModelSelectorSummary,
    BinaryClassificationModelSelector, MultiClassificationModelSelector,
    RegressionModelSelector)
from transmogrifai_tpu.selector.combiner import (
    SelectedCombinerModel, SelectedModelCombiner)

__all__ = [
    "SelectedModelCombiner", "SelectedCombinerModel",
    "DataSplitter", "DataBalancer", "DataCutter", "SplitterSummary",
    "OpCrossValidation", "OpTrainValidationSplit",
    "ParamGridBuilder", "RandomParamBuilder",
    "ModelSelector", "ModelSelectorSummary",
    "BinaryClassificationModelSelector", "MultiClassificationModelSelector",
    "RegressionModelSelector",
]
