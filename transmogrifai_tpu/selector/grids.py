"""Hyperparameter grid builders.

Reference parity: Spark's ParamGridBuilder usage in
`BinaryClassificationModelSelector.scala:71-137` and
`core/.../selector/RandomParamBuilder.scala:52-150`.
"""

from __future__ import annotations

from itertools import product
from typing import Any, Dict, List, Sequence

import numpy as np


class ParamGridBuilder:
    """Cartesian grid: `ParamGridBuilder().add("reg_param", [0.01, 0.1]).build()`."""

    def __init__(self):
        self._grids: Dict[str, Sequence[Any]] = {}

    def add(self, param: str, values: Sequence[Any]) -> "ParamGridBuilder":
        self._grids[param] = list(values)
        return self

    def build(self) -> List[Dict[str, Any]]:
        if not self._grids:
            return [{}]
        keys = list(self._grids)
        return [dict(zip(keys, combo))
                for combo in product(*(self._grids[k] for k in keys))]


class RandomParamBuilder:
    """Random search: uniform / exponential / subset draws per param."""

    def __init__(self, seed: int = 42):
        self._rng = np.random.default_rng(seed)
        self._specs: List = []

    def uniform(self, param: str, lo: float, hi: float) -> "RandomParamBuilder":
        self._specs.append((param, lambda: float(self._rng.uniform(lo, hi))))
        return self

    def exponential(self, param: str, lo: float, hi: float) -> "RandomParamBuilder":
        if lo <= 0 or hi <= 0:
            raise ValueError("exponential bounds must be positive")
        llo, lhi = np.log(lo), np.log(hi)
        self._specs.append(
            (param, lambda: float(np.exp(self._rng.uniform(llo, lhi)))))
        return self

    def uniform_int(self, param: str, lo: int, hi: int) -> "RandomParamBuilder":
        """Inclusive integer draw (RandomParamBuilder.scala uniform on
        IntParam)."""
        if hi < lo:
            raise ValueError("uniform_int: hi < lo")
        self._specs.append(
            (param, lambda: int(self._rng.integers(lo, hi + 1))))
        return self

    def subset(self, param: str, values: Sequence[Any]) -> "RandomParamBuilder":
        vals = list(values)
        if not vals:
            raise ValueError("subset: empty choices")
        self._specs.append(
            (param, lambda: vals[int(self._rng.integers(len(vals)))]))
        return self

    def build(self, n: int) -> List[Dict[str, Any]]:
        return [{p: draw() for p, draw in self._specs} for _ in range(n)]
