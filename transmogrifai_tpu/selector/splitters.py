"""Data splitters: holdout reservation + label-balancing preparation.

Reference parity: `core/.../tuning/Splitter.scala:47-84` (reserve test
fraction), `DataSplitter.scala:65-128`, `DataBalancer.scala:73-393` (binary
up/down-sampling), `DataCutter.scala:78-308` (multiclass label pruning).

Host-side index computation (deterministic per seed); the device only ever
sees the resulting index arrays / weight masks.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np


@dataclass
class SplitterSummary:
    splitter: str
    n_rows: int
    n_train: int
    n_test: int
    details: Dict = field(default_factory=dict)

    def to_json(self) -> Dict:
        return {"splitter": self.splitter, "n_rows": self.n_rows,
                "n_train": self.n_train, "n_test": self.n_test,
                "details": self.details}


class DataSplitter:
    """Random holdout reservation (DataSplitter.scala)."""

    def __init__(self, reserve_test_fraction: float = 0.1, seed: int = 42):
        self.reserve_test_fraction = reserve_test_fraction
        self.seed = seed

    def split(self, y: np.ndarray) -> Tuple[np.ndarray, np.ndarray, SplitterSummary]:
        n = len(y)
        rng = np.random.default_rng(self.seed)
        perm = rng.permutation(n)
        n_test = int(round(n * self.reserve_test_fraction))
        test, train = perm[:n_test], perm[n_test:]
        return np.sort(train), np.sort(test), SplitterSummary(
            splitter=type(self).__name__, n_rows=n,
            n_train=len(train), n_test=len(test))

    def prepare(self, y: np.ndarray, train_idx: np.ndarray
                ) -> Tuple[np.ndarray, Dict]:
        """Post-split training-set preparation (identity here)."""
        return train_idx, {}


class DataBalancer(DataSplitter):
    """Binary-label balancing: down-sample the majority class until the
    minority fraction reaches `sample_fraction` (DataBalancer.scala)."""

    def __init__(self, sample_fraction: float = 0.1,
                 max_training_sample: int = 1_000_000,
                 reserve_test_fraction: float = 0.1, seed: int = 42):
        super().__init__(reserve_test_fraction, seed)
        self.sample_fraction = sample_fraction
        self.max_training_sample = max_training_sample

    def prepare(self, y: np.ndarray, train_idx: np.ndarray
                ) -> Tuple[np.ndarray, Dict]:
        rng = np.random.default_rng(self.seed + 1)
        yt = y[train_idx]
        pos = train_idx[yt > 0.5]
        neg = train_idx[yt <= 0.5]
        n_pos, n_neg = len(pos), len(neg)
        details: Dict = {"n_pos": n_pos, "n_neg": n_neg, "balanced": False}
        if n_pos == 0 or n_neg == 0:
            return train_idx, details
        small, big = (pos, neg) if n_pos <= n_neg else (neg, pos)
        frac = len(small) / (len(small) + len(big))
        if frac < self.sample_fraction:
            # shrink the majority so the minority hits sample_fraction
            target_big = int(len(small) * (1 - self.sample_fraction)
                             / self.sample_fraction)
            big = rng.choice(big, size=min(target_big, len(big)), replace=False)
            details["balanced"] = True
        out = np.sort(np.concatenate([small, big]))
        if len(out) > self.max_training_sample:
            out = np.sort(rng.choice(out, self.max_training_sample, replace=False))
            details["downsampled_to_max"] = True
        details["n_after"] = int(len(out))
        return out, details


class DataCutter(DataSplitter):
    """Multiclass label pruning: keep the most frequent labels
    (DataCutter.scala: maxLabelCategories / minLabelFraction)."""

    def __init__(self, max_label_categories: int = 100,
                 min_label_fraction: float = 0.0,
                 reserve_test_fraction: float = 0.1, seed: int = 42):
        super().__init__(reserve_test_fraction, seed)
        self.max_label_categories = max_label_categories
        self.min_label_fraction = min_label_fraction

    def prepare(self, y: np.ndarray, train_idx: np.ndarray
                ) -> Tuple[np.ndarray, Dict]:
        yt = y[train_idx]
        labels, counts = np.unique(yt, return_counts=True)
        order = np.argsort(-counts)
        keep = []
        for i in order[: self.max_label_categories]:
            if counts[i] / len(yt) >= self.min_label_fraction:
                keep.append(labels[i])
        keep_set = np.isin(yt, np.asarray(keep))
        details = {"labels_kept": [float(v) for v in keep],
                   "labels_dropped": [float(v) for v in labels
                                      if v not in set(keep)]}
        return train_idx[keep_set], details
