"""Validation strategies: k-fold CV and train/validation split.

Reference parity: `core/.../tuning/OpCrossValidation.scala:42-202`
(stratified option, per-fold fits), `OpTrainValidationSplit.scala`,
`OpValidator.scala:62-380`.

TPU-first: a "fold" is a pair of row-weight masks over the fixed (n, d)
training matrix — never a reshuffled copy. The sweep engine vmaps the model
fit over the stacked fold masks, so folds × grids become one batched XLA
program instead of the reference's thread-pool of Spark jobs.
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np


class OpCrossValidation:
    """k-fold splits as (train_mask, val_mask) float32 vectors."""

    def __init__(self, n_folds: int = 3, seed: int = 42, stratify: bool = False):
        if n_folds < 2:
            raise ValueError("n_folds must be >= 2")
        self.n_folds = n_folds
        self.seed = seed
        self.stratify = stratify

    def splits(self, y: np.ndarray) -> List[Tuple[np.ndarray, np.ndarray]]:
        n = len(y)
        rng = np.random.default_rng(self.seed)
        fold_of = np.empty(n, dtype=np.int64)
        if self.stratify:
            # per-class round-robin assignment after a shuffle
            # (stratifyKFolds, OpCrossValidation.scala:184)
            for lvl in np.unique(np.round(y).astype(np.int64)):
                idx = np.nonzero(np.round(y).astype(np.int64) == lvl)[0]
                idx = rng.permutation(idx)
                fold_of[idx] = np.arange(len(idx)) % self.n_folds
        else:
            fold_of = rng.permutation(n) % self.n_folds
        out = []
        for k in range(self.n_folds):
            val = (fold_of == k)
            out.append(((~val).astype(np.float32), val.astype(np.float32)))
        return out


class OpTrainValidationSplit:
    """Single split (OpTrainValidationSplit.scala), same mask contract."""

    def __init__(self, train_ratio: float = 0.75, seed: int = 42):
        self.train_ratio = train_ratio
        self.seed = seed

    def splits(self, y: np.ndarray) -> List[Tuple[np.ndarray, np.ndarray]]:
        n = len(y)
        rng = np.random.default_rng(self.seed)
        train = rng.uniform(size=n) < self.train_ratio
        return [(train.astype(np.float32), (~train).astype(np.float32))]
