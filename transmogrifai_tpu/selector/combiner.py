"""SelectedModelCombiner: merge two ModelSelector Prediction outputs.

Reference parity: `core/.../selector/SelectedModelCombiner.scala:72-180`
(strategies Best / Weighted / Equal from `CombinationStrategy.scala`):
weights come from each selector's validation metric; `best` passes the
winner through, `weighted` mixes probabilities by relative metric, `equal`
averages. The fitted combiner is a pure device blend — one fused op in the
compiled scorer.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from transmogrifai_tpu import types as T
from transmogrifai_tpu.data.columns import Column
from transmogrifai_tpu.stages.base import Estimator, FitContext, Transformer

BEST, WEIGHTED, EQUAL = "best", "weighted", "equal"


class SelectedCombinerModel(Transformer):
    """Fitted combiner: prediction = argmax of the blended probabilities
    (or the weighted mean for regression raw predictions)."""

    out_type = T.Prediction
    response_aware = True  # inputs are (label, pred, pred)

    def __init__(self, weight1: float = 0.5, weight2: float = 0.5,
                 strategy: str = BEST, metric_name: str = "",
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.weight1 = float(weight1)
        self.weight2 = float(weight2)
        self.strategy = strategy
        self.metric_name = metric_name
        self.summary = None

    def device_apply(self, enc, dev):
        _, p1, p2 = dev
        w1, w2 = self.weight1, self.weight2
        prob = w1 * p1["probability"] + w2 * p2["probability"]
        raw = w1 * p1["rawPrediction"] + w2 * p2["rawPrediction"]
        if prob.shape[1] > 0:
            pred = jnp.argmax(prob, axis=1).astype(jnp.float32)
        else:  # regression predictions blend directly
            pred = w1 * p1["prediction"] + w2 * p2["prediction"]
        return {"prediction": pred, "probability": prob,
                "rawPrediction": raw}

    def get_params(self) -> Dict[str, Any]:
        return {"weight1": self.weight1, "weight2": self.weight2,
                "strategy": self.strategy, "metric_name": self.metric_name}


class SelectedModelCombiner(Estimator):
    """Estimator3(RealNN, Prediction, Prediction) → Prediction. Both
    prediction inputs must come from ModelSelectors (their summaries carry
    the validation metric used for weighting)."""

    in_types = (T.RealNN, T.Prediction, T.Prediction)
    out_type = T.Prediction
    response_aware = True  # slot 0 is the label

    def __init__(self, strategy: str = BEST, uid: Optional[str] = None):
        if strategy not in (BEST, WEIGHTED, EQUAL):
            raise ValueError(
                f"strategy must be best/weighted/equal, got {strategy!r}")
        super().__init__(uid=uid, strategy=strategy)
        self.strategy = strategy

    def _selector_metric(self, feature) -> tuple:
        stage = feature.origin_stage
        summary = getattr(stage, "summary", None)
        if summary is None:
            est = getattr(stage, "_estimator", None)
            summary = getattr(est, "summary", None)
        if summary is None:
            raise ValueError(
                "SelectedModelCombiner inputs must be ModelSelector outputs "
                f"(no summary on {feature.name!r})")
        metric = summary.holdout_metrics.get(summary.metric_name)
        if metric is None:  # a real 0.0 must NOT fall through
            metric = summary.train_metrics.get(summary.metric_name)
        if metric is None:
            sign = 1.0 if getattr(summary, "larger_is_better", True) else -1.0
            best = max(summary.validation_results,
                       key=lambda r: sign * r.mean_metric)
            metric = best.mean_metric
        return float(metric), summary

    def fit_model(self, cols: Sequence[Column], ctx: FitContext) -> Transformer:
        f1, f2 = self.input_features[1], self.input_features[2]
        m1, s1 = self._selector_metric(f1)
        m2, s2 = self._selector_metric(f2)
        larger_better = getattr(s1, "larger_is_better", True)
        if self.strategy == BEST:
            first_wins = (m1 > m2) == larger_better or m1 == m2
            w1, w2 = (1.0, 0.0) if first_wins else (0.0, 1.0)
        elif self.strategy == WEIGHTED:
            total = m1 + m2
            if not total:
                w1, w2 = 0.5, 0.5
            elif larger_better:
                w1, w2 = m1 / total, m2 / total
            else:  # smaller-is-better (RMSE): invert so the better model
                w1, w2 = m2 / total, m1 / total  # gets the larger weight
        else:
            w1, w2 = 0.5, 0.5
        model = SelectedCombinerModel(
            w1, w2, self.strategy, metric_name=s1.metric_name)
        model.summary = s1 if w1 >= w2 else s2
        return model
