"""ModelSelector: cross-validated model + hyperparameter selection.

Reference parity: `core/.../selector/ModelSelector.scala:72-211` (prep data
→ findBestEstimator → refit best on full prepared train → evaluate → wrap
SelectedModel + ModelSelectorSummary), factories
`BinaryClassificationModelSelector.scala:49-224`,
`MultiClassificationModelSelector`, `RegressionModelSelector.scala`,
defaults `DefaultSelectorParams.scala:35-90`.

The sweep (folds × models × grids) runs through
`transmogrifai_tpu.parallel.sweep.run_sweep` — vmapped/batched XLA programs
instead of the reference's Future-per-fit thread pool.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from transmogrifai_tpu import types as T
from transmogrifai_tpu.data.columns import Column
from transmogrifai_tpu.evaluators import (
    BinaryClassificationEvaluator, MultiClassificationEvaluator,
    RegressionEvaluator)
from transmogrifai_tpu.models import OpLinearRegression, OpLogisticRegression
from transmogrifai_tpu.parallel.sweep import run_sweep
from transmogrifai_tpu.selector.splitters import DataBalancer, DataCutter, DataSplitter
from transmogrifai_tpu.selector.validators import OpCrossValidation
from transmogrifai_tpu.stages.base import Estimator, FitContext, Transformer

log = logging.getLogger(__name__)


@dataclass
class ValidationResult:
    model: str
    grid: Dict[str, Any]
    fold_metrics: List[float]
    model_index: int = 0  # index into ModelSelector.models (class names can repeat)

    @property
    def mean_metric(self) -> float:
        return float(np.mean(self.fold_metrics)) if self.fold_metrics else float("nan")

    def to_json(self) -> Dict:
        return {"model": self.model, "model_index": self.model_index,
                "grid": self.grid, "fold_metrics": self.fold_metrics,
                "mean": self.mean_metric}


@dataclass
class ModelSelectorSummary:
    """ModelSelectorSummary.scala analogue, persisted on the fitted model."""

    problem_type: str
    metric_name: str
    validation_results: List[ValidationResult] = field(default_factory=list)
    best_model: str = ""
    best_grid: Dict[str, Any] = field(default_factory=dict)
    train_metrics: Dict[str, Any] = field(default_factory=dict)
    holdout_metrics: Dict[str, Any] = field(default_factory=dict)
    splitter_summary: Dict[str, Any] = field(default_factory=dict)
    larger_is_better: bool = True

    def to_json(self) -> Dict:
        return {
            "problem_type": self.problem_type, "metric": self.metric_name,
            "validation_results": [r.to_json() for r in self.validation_results],
            "best_model": self.best_model, "best_grid": self.best_grid,
            "train_metrics": self.train_metrics,
            "holdout_metrics": self.holdout_metrics,
            "splitter": self.splitter_summary,
        }

    def pretty(self) -> str:
        sign = -1.0 if self.larger_is_better else 1.0  # best first
        lines = [f"Evaluated {len(self.validation_results)} model configs "
                 f"({self.metric_name}):"]
        for r in sorted(self.validation_results, key=lambda r: sign * r.mean_metric):
            lines.append(f"  {r.model} {r.grid} -> {r.mean_metric:.4f}")
        lines.append(f"Best: {self.best_model} {self.best_grid}")
        return "\n".join(lines)


class ModelSelector(Estimator):
    """Estimator2(RealNN, OPVector) → Prediction. Fits the sweep, refits the
    winner on the full prepared training data, evaluates train + holdout."""

    in_types = (T.RealNN, T.OPVector)
    out_type = T.Prediction
    response_aware = True  # slot 0 is the label

    def __init__(self, models: Sequence[Tuple[Estimator, List[Dict]]],
                 validator=None, splitter=None, evaluator=None,
                 problem_type: str = "binary", uid: Optional[str] = None,
                 checkpoint_dir: Optional[str] = None):
        super().__init__(uid=uid)
        self.models = list(models)
        self.validator = validator or OpCrossValidation()
        self.splitter = splitter
        self.evaluator = evaluator or BinaryClassificationEvaluator()
        self.problem_type = problem_type
        # sweep checkpointing (SURVEY.md §5.4 — the reference has no
        # mid-sweep resume; long TPU sweeps need one): per-family metric
        # matrices persist as JSON after each family completes, and a
        # per-block SweepJournal (runtime/journal.py) persists each grid
        # config's fold metrics AS THE SWEEP RUNS — both keyed by a
        # signature of the family + grids + data content + folds + seed,
        # so a killed sweep resumes at the first un-journaled block
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_fsync = True  # journal durability (tests may relax)

    def fit_model(self, cols: Sequence[Column], ctx: FitContext) -> Transformer:
        label_col, vec_col = cols
        y_np = np.asarray(label_col.data["value"], dtype=np.float64)
        X_full = jnp.asarray(vec_col.device_value())

        # -- data preparation (Splitter.split + preValidationPrepare) ---- #
        split_summary: Dict[str, Any] = {}
        if self.splitter is not None:
            train_idx, test_idx, ssum = self.splitter.split(y_np)
            train_idx, prep_details = self.splitter.prepare(y_np, train_idx)
            split_summary = ssum.to_json()
            split_summary["details"].update(prep_details)
        else:
            train_idx = np.arange(len(y_np))
            test_idx = np.array([], dtype=np.int64)

        X = X_full[jnp.asarray(train_idx)]
        y_train = y_np[train_idx]
        y_dev = jnp.asarray(y_train.astype(np.float32))
        folds = self.validator.splits(y_train)

        # -- the sweep --------------------------------------------------- #
        sharding = None
        use_scheduler = False
        if ctx.mesh is not None:
            import os as _os
            from transmogrifai_tpu.parallel.mesh import (
                SWEEP_AXIS, sweep_sharding)
            # a >1-wide sweep axis runs the distributed work-stealing
            # scheduler (parallel/scheduler.py): grid blocks partition
            # across the mesh's sweep rows, per-worker journal shards
            # form the shared completion log, and each worker's blocks
            # execute the exact single-device programs (bit-identical
            # winner). TRANSMOGRIFAI_DISTRIBUTED_SWEEP=0 falls back to
            # the grid-axis vmap sharding path.
            use_scheduler = (
                ctx.cv_refit is None
                and dict(ctx.mesh.shape).get(SWEEP_AXIS, 1) > 1
                and _os.environ.get(
                    "TRANSMOGRIFAI_DISTRIBUTED_SWEEP", "1") != "0")
            if not use_scheduler:  # spread the grid axis across the mesh
                sharding = sweep_sharding(ctx.mesh)
        results: List[ValidationResult] = []
        failures = 0
        if ctx.cv_refit is None:
            data_digest = (self._data_digest(X, y_dev)
                           if self.checkpoint_dir is not None else None)

            # family jobs run on pool threads with no inherited span
            # context: parent each family span explicitly so sweep-block
            # spans nest under the caller's run/stage span
            from transmogrifai_tpu.obs.trace import TRACER as _TRACER
            _sweep_parent = _TRACER.current()

            def run_family(mi_est_grids):
                mi, (est, grids) = mi_est_grids
                with _TRACER.span(f"sweep:family:{type(est).__name__}",
                                  category="sweep_family",
                                  parent=_sweep_parent, grids=len(grids)):
                    sig, ckpt, cached = self._checkpoint_lookup(
                        mi, est, grids, X, data_digest, folds, ctx)
                    if cached is not None:
                        return cached
                    # block-granular journal: completed grid blocks
                    # persist as the sweep runs, so a kill ANYWHERE
                    # inside the family resumes at the first
                    # un-journaled block instead of re-running the
                    # family from scratch
                    journal = self._journal_for(mi, est, sig)
                    grid_fold = self._run_sweep_with_retry(
                        est, grids, X, y_dev, folds, ctx, sharding,
                        journal=journal)
                    self._save_checkpoint(ckpt, grid_fold)
                    return grid_fold

            # Families run on a thread pool (the reference's Parallelism=8
            # Future-per-fit pool, OpValidator.scala:374): device
            # executions serialize on the chip anyway, but one family's
            # remote-AOT compiles overlap another's compiles AND
            # executions — the dominant cold-process cost (VERDICT r3 #2).
            # Threads only help a fresh process; a warm compile cache
            # degrades gracefully to interleaved execution.
            import os as _os
            from concurrent.futures import ThreadPoolExecutor
            par = min(len(self.models), int(_os.environ.get(
                "TRANSMOGRIFAI_SWEEP_PARALLELISM", "8")))
            if use_scheduler:
                outcomes = self._sweep_scheduled(
                    ctx, X, y_dev, folds, data_digest)
            elif par > 1 and sharding is None and len(self.models) > 1:
                with ThreadPoolExecutor(max_workers=par) as pool:
                    futs = [pool.submit(run_family, (mi, mg))
                            for mi, mg in enumerate(self.models)]
                    outcomes = []
                    for f in futs:
                        try:
                            outcomes.append(f.result())
                        except Exception as e:
                            outcomes.append(e)
            else:
                outcomes = []
                for mi, mg in enumerate(self.models):
                    try:
                        outcomes.append(run_family((mi, mg)))
                    except Exception as e:
                        outcomes.append(e)
            for mi, ((est, grids), out) in enumerate(
                    zip(self.models, outcomes)):
                if isinstance(out, Exception):
                    # drop failing family (OpValidator.scala:344-347)
                    failures += 1
                    log.error("Model family %s failed; dropping from sweep",
                              type(est).__name__, exc_info=out)
                    continue
                for grid, fm in zip(grids, out):
                    results.append(ValidationResult(
                        model=type(est).__name__, grid=grid,
                        fold_metrics=[float(m) for m in fm], model_index=mi))
        else:
            results, failures = self._sweep_with_workflow_cv(
                ctx, folds, train_idx, y_dev, sharding)
        if not results:
            raise RuntimeError(
                f"All {failures} model families failed during validation")

        sign = 1.0 if self.evaluator.is_larger_better else -1.0
        finite = [r for r in results if np.isfinite(r.mean_metric)]
        return self._finish(ctx, results, finite, sign, X, X_full, y_np,
                            y_dev, train_idx, test_idx, split_summary)

    def _sweep_scheduled(self, ctx, X, y_dev, folds, data_digest):
        """Distributed sweep: ALL families' grid blocks go into ONE
        work-stealing schedule over the mesh (parallel/scheduler.py) —
        one queue packs the mesh better than per-family fan-out, and a
        straggling tree family's blocks spread over lanes that finished
        their linear families. Per-family checkpoints still short-
        circuit whole families; per-worker journal shards
        (``<family>.journal-w<k>.jsonl``) are the shared completion log
        for steal/resume decisions. Returns one outcome per family
        (metric matrix, or the Exception that failed it)."""
        from transmogrifai_tpu.parallel.scheduler import (
            GridScheduler, SweepJob)

        outcomes: List[Any] = [None] * len(self.models)
        jobs, meta = [], []
        for mi, (est, grids) in enumerate(self.models):
            sig, ckpt, cached = self._checkpoint_lookup(
                mi, est, grids, X, data_digest, folds, ctx)
            if cached is not None:
                outcomes[mi] = cached
                continue
            jobs.append(SweepJob(
                index=mi, est=est, grids=grids,
                journal=self._journal_for(mi, est, sig, sharded=True),
                name=type(est).__name__,
                # per-block transient-RPC retry: distribution must not be
                # LESS fault-tolerant than the single-device family path
                run=self._block_runner(type(est).__name__)))
            meta.append((mi, ckpt))
        if jobs:
            import os as _os
            pod_store = _os.environ.get("TRANSMOGRIFAI_POD_STORE")
            if pod_store:
                # pod tier (parallel/pod.py): this process is ONE HOST of
                # a multi-host sweep — every host env-points at the same
                # store dir + sweep id and races block claims through the
                # shared lease table. Requires journals (checkpoint_dir),
                # which double as the cross-host completion log.
                from transmogrifai_tpu.parallel.scheduler import (
                    HostScheduler)
                workers = _os.environ.get("TRANSMOGRIFAI_POD_WORKERS")
                sched = HostScheduler(
                    pod_store,
                    _os.environ.get("TRANSMOGRIFAI_POD_HOST",
                                    f"h{_os.getpid()}"),
                    sweep_id=_os.environ.get(
                        "TRANSMOGRIFAI_POD_SWEEP", "pod"),
                    mesh=ctx.mesh,
                    n_workers=int(workers) if workers else None,
                    lease_ttl_s=float(_os.environ.get(
                        "TRANSMOGRIFAI_POD_TTL_S", "30") or 30))
            else:
                sched = GridScheduler(mesh=ctx.mesh)
            for (mi, ckpt), out in zip(meta, sched.run(
                    jobs, X, y_dev, folds, self.evaluator, ctx)):
                outcomes[mi] = out
                if not isinstance(out, Exception):
                    self._save_checkpoint(ckpt, out)
        return outcomes

    def _block_runner(self, family: str):
        """run_sweep wrapped in the transient-RPC RetryPolicy, one policy
        per family job (attempt budgets must not pool across blocks of
        different families). Used as `SweepJob.run` by the scheduler;
        completed grids inside a retried block skip via the journal."""
        policy = self._sweep_retry_policy()

        def run_block(*args, **kwargs):
            return policy.call(run_sweep, *args,
                               label=f"sweep.{family}", **kwargs)
        return run_block

    @staticmethod
    def _sweep_retry_policy(retries: int = 2):
        """The serving tunnel's remote-compile RPC occasionally drops a
        response mid-read (transient INTERNAL error, r3 bench); dropping
        a whole model family for that throws away real work. Shared by
        the single-device family path AND the distributed scheduler's
        per-block runner — the persistent compile cache plus the block
        journal make a retry cheap (journaled blocks are skipped)."""
        from transmogrifai_tpu.runtime.retry import RetryPolicy

        def classify(e):
            if "remote_compile" in str(e) or \
                    type(e).__name__ == "JaxRuntimeError":
                return True
            return None  # fall through to the error's own `transient` attr

        return RetryPolicy(max_attempts=retries + 1, base_delay_s=3.0,
                           max_delay_s=10.0, backoff=1.5,
                           transient_types=(), classify=classify)

    def _run_sweep_with_retry(self, est, grids, X, y_dev, folds, ctx,
                              sharding, retries: int = 2, journal=None):
        """Family sweep behind the transient-RPC RetryPolicy; only after
        exhaustion does the family-drop fault tolerance
        (OpValidator.scala:344-347 parity) take over."""
        return self._sweep_retry_policy(retries).call(
            run_sweep, est, grids, X, y_dev, folds, self.evaluator, ctx,
            sharding=sharding, journal=journal,
            label=f"sweep.{type(est).__name__}")

    # -- sweep checkpointing ------------------------------------------- #

    def _checkpoint_lookup(self, mi, est, grids, X, data_digest, folds, ctx):
        """(sig, ckpt_path, cached matrix-or-None) for one family — the
        ONE source of checkpoint-hit semantics for both the
        single-device family path and the distributed scheduler."""
        sig = self._sweep_signature(
            mi, est, grids, X, data_digest, folds, ctx)
        ckpt = self._checkpoint_path(mi, est, sig)
        cached = self._load_checkpoint(ckpt)
        if cached is not None:
            log.info("sweep checkpoint hit: %s (%d grids)",
                     type(est).__name__, len(cached))
        return sig, ckpt, cached

    @staticmethod
    def _data_digest(X, y) -> Optional[str]:
        """sha256 of the training data bytes, computed ONCE per fit (the
        device→host materialization is shared by every family's key)."""
        import hashlib
        try:
            hasher = hashlib.sha256()
            hasher.update(np.ascontiguousarray(np.asarray(X)).tobytes())
            hasher.update(np.ascontiguousarray(np.asarray(y)).tobytes())
            return hasher.hexdigest()
        except Exception:
            return None

    def _sweep_signature(self, mi, est, grids, X, data_digest, folds,
                         ctx) -> Optional[str]:
        """Hash of everything that determines the metric matrix: family +
        params + grids, the TRAINING DATA CONTENT (the digest of X and y
        bytes — same-shaped different data must miss), the fold
        structure, the evaluator class + metric, and the fit seed. Keys
        both the per-family checkpoint file and the per-block journal.
        Never raises: checkpointing is an optimization, so any failure
        degrades to 'no checkpoint'."""
        if self.checkpoint_dir is None or data_digest is None:
            return None
        import hashlib
        import json as _json
        try:
            val = self.validator
            sig = _json.dumps({
                "family": type(est).__name__, "index": mi,
                "params": {k: repr(v) for k, v in sorted(est.params.items())
                           if k != "uid"},
                "grids": grids, "shape": list(map(int, X.shape)),
                "data": data_digest,
                "folds": len(folds),
                "validator": [type(val).__name__,
                              getattr(val, "n_folds", None),
                              getattr(val, "train_ratio", None),
                              getattr(val, "seed", None)],
                "seed": getattr(ctx, "seed", None),
                "evaluator": [type(self.evaluator).__name__,
                              getattr(self.evaluator, "metric", None)],
            }, sort_keys=True, default=repr)
            return hashlib.sha256(sig.encode()).hexdigest()[:16]
        except Exception:
            log.warning("sweep checkpointing disabled for this fit "
                        "(signature failed)", exc_info=True)
            return None

    def _checkpoint_path(self, mi, est, sig) -> Optional[str]:
        if self.checkpoint_dir is None or sig is None:
            return None
        import os
        try:
            os.makedirs(self.checkpoint_dir, exist_ok=True)
        except OSError:
            log.warning("sweep checkpointing disabled for this fit "
                        "(checkpoint_dir unusable)", exc_info=True)
            return None
        return os.path.join(self.checkpoint_dir,
                            f"sweep_{mi}_{type(est).__name__}_{sig}.json")

    def _journal_for(self, mi, est, sig, sharded: bool = False):
        """Open (or resume) the family's block journal beside the family
        checkpoint. `sharded=True` (the distributed scheduler) returns a
        `ShardedSweepJournal`: per-worker ``-w<k>.jsonl`` shard files
        merged on read, so concurrent workers never share an append fd
        (and a pre-existing single-file journal at the base path still
        merges in read-only). Never raises — an unusable journal
        degrades to family-level resume granularity."""
        if self.checkpoint_dir is None or sig is None:
            return None
        import os

        from transmogrifai_tpu.runtime.journal import (
            ShardedSweepJournal, SweepJournal)
        try:
            os.makedirs(self.checkpoint_dir, exist_ok=True)
            path = os.path.join(
                self.checkpoint_dir,
                f"sweep_{mi}_{type(est).__name__}_{sig}.journal")
            # resume symmetry: a single-device resume of a MESH-journaled
            # sweep must read the shard files too, or every block the
            # mesh completed re-runs (appends then go to shard 0)
            cls = (ShardedSweepJournal
                   if sharded or ShardedSweepJournal.has_shards(path)
                   else SweepJournal)
            return cls(path, meta={"sig": sig},
                       fsync=getattr(self, "checkpoint_fsync", True))
        except Exception:
            log.warning("sweep journal unusable; family-level resume only",
                        exc_info=True)
            return None

    @staticmethod
    def _load_checkpoint(path: Optional[str]):
        import json as _json
        import os
        if path is None or not os.path.exists(path):
            return None
        try:
            with open(path) as f:
                return _json.load(f)["grid_fold"]
        except Exception:
            log.warning("unreadable sweep checkpoint %s; re-running", path)
            return None

    @staticmethod
    def _save_checkpoint(path: Optional[str], grid_fold) -> None:
        if path is None:
            return
        import json as _json
        import os
        try:
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                _json.dump({"grid_fold": [[float(m) for m in row]
                                          for row in grid_fold]}, f)
            os.replace(tmp, path)  # atomic: a killed write never half-loads
        except OSError:
            log.warning("could not write sweep checkpoint %s", path,
                        exc_info=True)

    def _sweep_with_workflow_cv(self, ctx, folds, train_idx, y_dev, sharding):
        """Workflow-level CV (OpWorkflowCore.withWorkflowCV → cutDAG,
        FitStagesUtil.scala:302-367; in-fold applyDAG OpValidator.scala:250):
        re-fit the pre-selector feature-engineering DAG on each fold's
        training rows via `ctx.cv_refit`, then sweep each family fold by
        fold on the fold-specific matrix — fold-global statistics cannot
        leak into validation metrics."""
        import jax.numpy as jnp

        per_family: Dict[int, List[List[float]]] = {}
        dead: set = set()
        # fold-outer so all families in one fold share the sweep data cache;
        # the fold matrix is built AND consumed inside the loop — only one
        # fold's refit matrix is alive at a time (bounds device memory to
        # the plain sweep's footprint)
        for fi, (tr, va) in enumerate(folds):
            fold_rows = train_idx[np.asarray(tr) > 0.5]
            X_fold = jnp.asarray(
                np.asarray(ctx.cv_refit(fold_rows))[train_idx])
            for mi, (est, grids) in enumerate(self.models):
                if mi in dead:
                    continue
                try:
                    gm = run_sweep(est, grids, X_fold, y_dev, [(tr, va)],
                                   self.evaluator, ctx, sharding=sharding)
                except Exception:
                    dead.add(mi)
                    per_family.pop(mi, None)
                    log.exception(
                        "Model family %s failed in fold %d; dropping",
                        type(est).__name__, fi)
                    continue
                rows = per_family.setdefault(
                    mi, [[] for _ in range(len(grids))])
                for gi, row in enumerate(gm):
                    rows[gi].append(float(row[0]))
        results: List[ValidationResult] = []
        for mi, (est, grids) in enumerate(self.models):
            if mi in per_family:
                for grid, fm in zip(grids, per_family[mi]):
                    results.append(ValidationResult(
                        model=type(est).__name__, grid=grid,
                        fold_metrics=fm, model_index=mi))
        return results, len(dead)

    def _finish(self, ctx, results, finite, sign, X, X_full, y_np, y_dev,
                train_idx, test_idx, split_summary):
        if not finite:
            raise RuntimeError(
                "Every validated config produced a non-finite metric")
        best = max(finite, key=lambda r: sign * r.mean_metric)

        # -- refit winner on full prepared train ------------------------- #
        best_est_proto = self.models[best.model_index][0]
        kwargs = {k: v for k, v in best_est_proto.params.items() if k != "uid"}
        kwargs.update(best.grid)
        best_est = type(best_est_proto)(**kwargs)
        model = best_est.fit_arrays(
            X, y_dev, jnp.ones_like(y_dev), ctx)

        # -- evaluate train + holdout ------------------------------------ #
        def _eval(idx: np.ndarray) -> Dict[str, Any]:
            if len(idx) == 0:
                return {}
            pred = model.predict_arrays(X_full[jnp.asarray(idx)])
            pcol = Column(T.Prediction, {k: np.asarray(v) for k, v in pred.items()})
            lcol = Column(T.RealNN, {
                "value": y_np[idx], "mask": np.ones(len(idx), dtype=bool)})
            m = self.evaluator.evaluate(lcol, pcol).to_json()
            return {k: v for k, v in m.items() if not isinstance(v, list)}

        summary = ModelSelectorSummary(
            problem_type=self.problem_type,
            metric_name=self.evaluator.default_metric,
            validation_results=results, best_model=best.model,
            best_grid=best.grid, train_metrics=_eval(train_idx),
            holdout_metrics=_eval(test_idx), splitter_summary=split_summary,
            larger_is_better=self.evaluator.is_larger_better)
        model.summary = summary
        return model


# --------------------------------------------------------------------------- #
# Factories (ModelSelectorFactory + per-problem selectors)                    #
# --------------------------------------------------------------------------- #

# the reference's shared grid axes (DefaultSelectorParams.scala:35-76)
_REGULARIZATION = (0.001, 0.01, 0.1, 0.2)
_ELASTIC_NET = (0.1, 0.5)
_MAX_DEPTH = (3, 6, 12)
_MIN_INFO_GAIN = (0.001, 0.01, 0.1)
_MIN_INSTANCES = (10.0, 100.0)


def _lr_grid() -> List[Dict]:
    """LR/linear: ElasticNet {0.1, 0.5} × Regularization {0.001..0.2} = 8."""
    return [{"reg_param": r, "elastic_net_param": a}
            for a in _ELASTIC_NET for r in _REGULARIZATION]


def _rf_grid() -> List[Dict]:
    """RF/DT: MaxDepth × MinInfoGain × MinInstancesPerNode = 18."""
    return [{"max_depth": d, "min_info_gain": g, "min_instances_per_node": m}
            for d in _MAX_DEPTH for g in _MIN_INFO_GAIN
            for m in _MIN_INSTANCES]


def _default_binary_models() -> List[Tuple[Estimator, List[Dict]]]:
    """Reference defaults: LR + RF + XGB
    (BinaryClassificationModelSelector.scala:62-64, grids :70-137): LR 8
    elastic-net configs at maxIter 50, RF 18 tree-shape configs at
    numTrees 50, XGB numRound 200 / eta 0.02 / depth 10 / gamma 0.8 /
    early stopping 20 × minChildWeight {1, 10} — 28 configs total."""
    from transmogrifai_tpu.models import (
        OpRandomForestClassifier, OpXGBoostClassifier)
    xgb_grid = [{"min_child_weight": m} for m in (1.0, 10.0)]
    return [(OpLogisticRegression(max_iter=50), _lr_grid()),
            (OpRandomForestClassifier(n_trees=50), _rf_grid()),
            (OpXGBoostClassifier(n_estimators=200, eta=0.02, max_depth=10,
                                 gamma=0.8, early_stopping_rounds=20),
             xgb_grid)]


def _default_multiclass_models() -> List[Tuple[Estimator, List[Dict]]]:
    """LR + RF (MultiClassificationModelSelector.scala:61-88) — 26 configs."""
    from transmogrifai_tpu.models import OpRandomForestClassifier
    return [(OpLogisticRegression(max_iter=50), _lr_grid()),
            (OpRandomForestClassifier(n_trees=50), _rf_grid())]


def _default_regression_models() -> List[Tuple[Estimator, List[Dict]]]:
    """Linear + RF + GBT (RegressionModelSelector.scala:61-99): linear 8
    elastic-net configs, RF 18, Spark-GBT 18 at maxIter 20 / stepSize 0.1
    — 44 configs total."""
    from transmogrifai_tpu.models import (
        OpGBTRegressor, OpRandomForestRegressor)
    return [(OpLinearRegression(), _lr_grid()),
            (OpRandomForestRegressor(n_trees=50), _rf_grid()),
            (OpGBTRegressor(n_estimators=20, learning_rate=0.1), _rf_grid())]


class BinaryClassificationModelSelector:
    """`BinaryClassificationModelSelector.with_cross_validation()` factory
    (BinaryClassificationModelSelector.scala:170)."""

    @staticmethod
    def with_cross_validation(
            models: Optional[Sequence[Tuple[Estimator, List[Dict]]]] = None,
            n_folds: int = 3, validation_metric: str = "AuPR",
            splitter=None, seed: int = 42,
            checkpoint_dir: Optional[str] = None) -> ModelSelector:
        return ModelSelector(
            models=models or _default_binary_models(),
            validator=OpCrossValidation(n_folds=n_folds, seed=seed),
            splitter=splitter if splitter is not None else DataBalancer(seed=seed),
            evaluator=BinaryClassificationEvaluator(metric=validation_metric),
            problem_type="binary", checkpoint_dir=checkpoint_dir)

    @staticmethod
    def with_train_validation_split(
            models: Optional[Sequence[Tuple[Estimator, List[Dict]]]] = None,
            train_ratio: float = 0.75, validation_metric: str = "AuPR",
            splitter=None, seed: int = 42,
            checkpoint_dir: Optional[str] = None) -> ModelSelector:
        from transmogrifai_tpu.selector.validators import OpTrainValidationSplit
        return ModelSelector(
            models=models or _default_binary_models(),
            validator=OpTrainValidationSplit(train_ratio=train_ratio, seed=seed),
            splitter=splitter if splitter is not None else DataBalancer(seed=seed),
            evaluator=BinaryClassificationEvaluator(metric=validation_metric),
            problem_type="binary", checkpoint_dir=checkpoint_dir)


class MultiClassificationModelSelector:
    @staticmethod
    def with_cross_validation(
            models: Optional[Sequence[Tuple[Estimator, List[Dict]]]] = None,
            n_folds: int = 3, validation_metric: str = "F1",
            splitter=None, seed: int = 42,
            checkpoint_dir: Optional[str] = None) -> ModelSelector:
        return ModelSelector(
            models=models or _default_multiclass_models(),
            validator=OpCrossValidation(n_folds=n_folds, seed=seed),
            splitter=splitter if splitter is not None else DataCutter(seed=seed),
            evaluator=MultiClassificationEvaluator(metric=validation_metric),
            problem_type="multiclass", checkpoint_dir=checkpoint_dir)

    @staticmethod
    def with_train_validation_split(
            models: Optional[Sequence[Tuple[Estimator, List[Dict]]]] = None,
            train_ratio: float = 0.75, validation_metric: str = "F1",
            splitter=None, seed: int = 42,
            checkpoint_dir: Optional[str] = None) -> ModelSelector:
        from transmogrifai_tpu.selector.validators import OpTrainValidationSplit
        return ModelSelector(
            models=models or _default_multiclass_models(),
            validator=OpTrainValidationSplit(train_ratio=train_ratio, seed=seed),
            splitter=splitter if splitter is not None else DataCutter(seed=seed),
            evaluator=MultiClassificationEvaluator(metric=validation_metric),
            problem_type="multiclass", checkpoint_dir=checkpoint_dir)


class RegressionModelSelector:
    @staticmethod
    def with_cross_validation(
            models: Optional[Sequence[Tuple[Estimator, List[Dict]]]] = None,
            n_folds: int = 3, validation_metric: str = "RMSE",
            splitter=None, seed: int = 42,
            checkpoint_dir: Optional[str] = None) -> ModelSelector:
        return ModelSelector(
            models=models or _default_regression_models(),
            validator=OpCrossValidation(n_folds=n_folds, seed=seed),
            splitter=splitter if splitter is not None else DataSplitter(seed=seed),
            evaluator=RegressionEvaluator(metric=validation_metric),
            problem_type="regression", checkpoint_dir=checkpoint_dir)

    @staticmethod
    def with_train_validation_split(
            models: Optional[Sequence[Tuple[Estimator, List[Dict]]]] = None,
            train_ratio: float = 0.75, validation_metric: str = "RMSE",
            splitter=None, seed: int = 42,
            checkpoint_dir: Optional[str] = None) -> ModelSelector:
        from transmogrifai_tpu.selector.validators import OpTrainValidationSplit
        return ModelSelector(
            models=models or _default_regression_models(),
            validator=OpTrainValidationSplit(train_ratio=train_ratio, seed=seed),
            splitter=splitter if splitter is not None else DataSplitter(seed=seed),
            evaluator=RegressionEvaluator(metric=validation_metric),
            problem_type="regression", checkpoint_dir=checkpoint_dir)
