"""Host-data-plane smoke (`make parse-smoke`): the PR-15 contracts.

1. CODEC PARITY — `data/rowcodec.encode_rows` returns a Dataset
   bit-identical (values, dtypes, schema, column order) to the
   reference `Dataset.from_rows_reference` on a hostile schema:
   NaN/None cells, keys missing from the first row (and from later
   rows), FeatureType-wrapped cells, exact big ints past 2^53, text/
   list/map object columns, numeric strings, and inference-typed
   extras.

2. STAGED-BUFFER REUSE — a warm ScoringService assembles every device
   batch by WRITING into the resident per-bucket staging block: after
   warmup, sustained traffic performs ZERO fresh batch-buffer
   allocations (staging allocation counter flat while the assembled
   counter climbs), and a hot-swap bumps the staging generation (the
   fence) and re-allocates exactly once per (bucket, layout).

3. CALIBRATED QUANT BIT-STABILITY — with `quantize="int8-calibrated"`
   the same rows scored inside two different batch compositions are
   bit-identical (fit-time fleet-wide ranges), while batch-relative
   "int8" drifts within its stated tolerance and stays the fallback
   for models without calibration.

Run: ``python -m transmogrifai_tpu.serving.parse_smoke`` (exit 0 = OK).
"""

from __future__ import annotations

import sys

import numpy as np


def _assert_dataset_equal(a, b, ctx: str) -> None:
    assert list(a.columns) == list(b.columns), \
        f"{ctx}: column order {list(a.columns)} vs {list(b.columns)}"
    assert a.schema == b.schema, f"{ctx}: schema mismatch"
    for k in a.columns:
        ca, cb = a.columns[k], b.columns[k]
        assert ca.dtype == cb.dtype, (ctx, k, ca.dtype, cb.dtype)
        if ca.dtype == object:
            assert len(ca) == len(cb) and all(
                (x is None and y is None) or x == y
                for x, y in zip(ca, cb)), (ctx, k)
        else:
            np.testing.assert_array_equal(ca, cb, err_msg=f"{ctx}:{k}")


def _check_codec_parity() -> None:
    from transmogrifai_tpu import types as T
    from transmogrifai_tpu.data.dataset import Dataset
    from transmogrifai_tpu.data.rowcodec import encode_rows

    hostile_schema = {
        "r": T.Real, "i": T.Integral, "b": T.Binary, "t": T.Text,
        "lst": T.TextList, "m": T.TextMap, "unused": T.Real,
    }
    hostile_rows = [
        {"r": 1.5, "i": 3, "b": True, "t": "x", "lst": ["a"],
         "m": {"k": "v"}},
        # ragged FIRST row regression: "extra" appears only later,
        # "r" goes missing here
        {"i": None, "b": False, "t": None, "lst": None, "m": None,
         "extra": 9.0},
        {"r": float("nan"), "i": (1 << 55) + 1, "b": None, "t": "z",
         "lst": ["b", "c"], "m": {}, "extra": None},
        {"r": "2.25", "i": "7", "b": False, "t": T.Text("wrapped"),
         "lst": ["d"], "m": {"a": "b"}},
    ]
    for schema in (hostile_schema, None):
        ref = Dataset.from_rows_reference(hostile_rows, schema=schema)
        fast = encode_rows(hostile_rows, schema=schema)
        _assert_dataset_equal(ref, fast, "hostile")
    # big-int column keeps exact object storage on both paths
    big = [{"id": (1 << 60) + 7}, {"id": 12}]
    ref = Dataset.from_rows_reference(big, schema={"id": T.Integral})
    fast = encode_rows(big, schema={"id": T.Integral})
    assert ref.columns["id"].dtype == object
    _assert_dataset_equal(ref, fast, "bigint")
    print("parse-smoke: codec parity OK (hostile schema, ragged first "
          "row, big ints, FeatureType cells)")


def _mk_model(n_rows: int = 600, seed: int = 5):
    import transmogrifai_tpu.types as t
    from transmogrifai_tpu.data import Dataset
    from transmogrifai_tpu.features import FeatureBuilder
    from transmogrifai_tpu.models import OpLogisticRegression
    from transmogrifai_tpu.ops.numeric import RealVectorizer
    from transmogrifai_tpu.workflow import Workflow

    rng = np.random.default_rng(seed)
    cols = {f"x{j}": rng.normal(loc=5.0 * j, scale=1.0 + j,
                                size=n_rows)
            for j in range(5)}
    y = (cols["x0"] - 5.0 * 0 + 0.5 * (cols["x1"] - 5.0)
         + rng.normal(0, 0.5, n_rows) > 0).astype(np.float64)
    schema = {k: t.Real for k in cols}
    cols["y"] = y
    schema["y"] = t.Integral
    ds = Dataset(dict(cols), schema)
    preds, label = FeatureBuilder.from_dataset(ds, response="y")
    vec = RealVectorizer(track_nulls=False).set_input(
        *preds).get_output()
    pred = OpLogisticRegression(max_iter=25).set_input(
        label, vec).get_output()
    model = Workflow().set_result_features(pred, label) \
        .set_input_dataset(ds).train()
    return model, pred, ds


def _check_staging_reuse() -> None:
    from transmogrifai_tpu.serving.service import (
        ScoringService, ServingConfig)

    model, pred, ds = _mk_model()
    rows = ds.to_rows()
    svc = ScoringService(model=model, config=ServingConfig(
        max_batch=16, batch_wait_ms=0.5, tracing={"enabled": False}))
    svc.start()
    try:
        for i in range(8):  # warm every layout/bucket this traffic uses
            svc.score([rows[i % len(rows)]], deadline_ms=10_000)
        pool = svc._staging
        warm_allocs = pool.allocations
        warm_gen = pool.generation
        before = pool.assembled
        for i in range(64):
            svc.score([rows[(3 * i) % len(rows)]], deadline_ms=10_000)
        assert pool.assembled > before, "staging pool was bypassed"
        assert pool.allocations == warm_allocs, (
            f"staging reallocated under steady traffic: "
            f"{warm_allocs} -> {pool.allocations}")
        assert pool.generation == warm_gen
        assert pool.fallbacks == 0, pool.fallbacks
        # generation fence: a rollback-equivalent swap invalidates
        svc._staging.invalidate()
        assert pool.generation == warm_gen + 1
        svc.score([rows[0]], deadline_ms=10_000)
        assert pool.allocations == warm_allocs + 1  # exactly one realloc
    finally:
        svc.stop()
    print("parse-smoke: staged-buffer reuse OK (zero fresh batch "
          "allocations across 64 warm batches; generation fence "
          "re-allocates once)")


def _check_calibrated_quant() -> None:
    from transmogrifai_tpu.data.dataset import Dataset

    model, pred, ds = _mk_model(seed=11)
    assert model.quant_calibration, "fit-time calibration not captured"
    rows = ds.to_rows()
    base, fill_a, fill_b = rows[:4], rows[10:14], rows[200:204]

    def padded(quant, batch):
        sub = Dataset.from_rows(batch, schema=ds.schema)
        out = model._ensure_compiled(quant=quant).score_padded(sub, 8)
        return np.asarray(out[pred.name]["probability"])[:4]

    cal_a = padded("int8-calibrated", base + fill_a)
    cal_b = padded("int8-calibrated", base + fill_b)
    assert (cal_a == cal_b).all(), (
        "calibrated quant is not bit-stable across batch compositions")
    rel_a = padded("int8", base + fill_a)
    rel_b = padded("int8", base + fill_b)
    drift = float(np.abs(rel_a - rel_b).max())
    # batch-relative fallback: same rows may drift across compositions
    # (that is the gap calibration closes) but stays within a loose
    # tolerance sanity bound
    assert drift < 0.1, drift
    f32 = padded(None, base + fill_a)
    assert float(np.abs(cal_a - f32).max()) < 0.1
    print(f"parse-smoke: calibrated quant bit-stable across "
          f"compositions OK (batch-relative drift {drift:.2e} "
          f"closed to 0)")


def main() -> int:
    _check_codec_parity()
    _check_staging_reuse()
    _check_calibrated_quant()
    print("parse-smoke OK: codec parity, staged-buffer reuse, "
          "calibrated-quant bit-stability")
    return 0


if __name__ == "__main__":
    sys.exit(main())
