"""ScoringService: the compiled scorer as a servable, observable endpoint.

This is the productization layer the reference stops short of (SURVEY
§2.13 ends at cluster-free batch scoring): an in-process service that

- admits row-dict requests through a BOUNDED queue (load-shedding with
  structured errors at capacity),
- coalesces concurrent requests into one device batch padded to a
  power-of-two shape bucket (``serving/batcher.py``) so the jit cache
  stays warm — the retrace counters prove zero recompiles after warmup,
- AOT-warms every bucket at model load (one compile per bucket, per
  segment, before the first request arrives),
- hot-swaps model versions under traffic: load a new serialization dir,
  warm it OFF the serving path, then atomically swap; the previous
  version is retained for one-call rollback,
- quarantines per-request errors: a failing batch is re-scored request
  by request so one bad record fails one request, not its batchmates,
- exports latency/throughput/queue/shed/compile metrics through a
  ``MetricsRegistry`` (JSON + Prometheus text).

Threading model: callers (any thread) do host-side row→Dataset parsing
and block on a per-request future; ONE scoring thread owns batch
assembly and every device dispatch, so jit caches are touched without
cross-thread interleaving. Model swap flips one attribute under a lock.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from transmogrifai_tpu import types as T
from transmogrifai_tpu.data.dataset import Dataset
from transmogrifai_tpu.data.rowcodec import columns_dataset, encode_rows
from transmogrifai_tpu.obs.metrics import (
    MICRO_LATENCY_BUCKETS, MetricsRegistry)
from transmogrifai_tpu.obs.trace import (
    TRACER, RequestTrace, TailSampler, TraceContext, TracingParams, now_s)
from transmogrifai_tpu.runtime.faults import (
    SITE_BATCH_ASSEMBLE, SITE_DEVICE_DISPATCH, SITE_RELOAD_LOAD,
    fault_point)
from transmogrifai_tpu.serving.batcher import (
    MicroBatcher, Request, ScoreError, bucket_for, bucket_ladder,
    derive_ladder)
from transmogrifai_tpu.serving.resilience import (
    QUARANTINED, MemberHealth, ResilienceParams, Watchdog)
from transmogrifai_tpu.serving.staging import StagingPool
from transmogrifai_tpu.workflow.compiled import slice_result_tree

log = logging.getLogger(__name__)


@dataclass
class ServingConfig:
    """Knobs for the scoring service (see also ServingParams in
    workflow/params.py, its JSON-loadable mirror)."""

    max_batch: int = 64            # top bucket = largest device batch
    min_bucket: int = 1            # bottom rung of the ladder
    buckets: Optional[Sequence[int]] = None  # explicit ladder override
    max_queue: int = 256           # bounded admission queue
    batch_wait_ms: float = 2.0     # linger to coalesce concurrent requests
    default_deadline_ms: float = 2000.0  # per-request deadline
    warm_on_load: bool = True      # AOT-compile every bucket at load
    keep_versions: int = 2         # live + rollback
    # derive the ladder from observed request sizes + the cost model's
    # predicted per-bucket latency once enough traffic has been seen
    # (serving/batcher.derive_ladder; a cold model keeps the power-of-
    # two ladder exactly). Ignored when `buckets` is explicit.
    auto_ladder: bool = False
    # optional data/feature_cache.py policy (a FeatureCacheParams JSON
    # dict) installed as the process default at service construction:
    # any store-backed scoring this process runs through the
    # parallel/bigdata.py builders then reuses cached — and, with
    # resident=True, HBM-resident — device matrices across model
    # hot-swaps instead of re-uploading after every /reload (the row
    # /score path itself builds no device matrices)
    feature_cache: Optional[Dict[str, Any]] = None
    # persistent XLA compilation cache (utils/compile_cache.py) enabled
    # at service construction with a 0s persistence threshold (a bucket
    # ladder is MANY small programs; a replica's cold start is their
    # compile-time sum). None = read TRANSMOGRIFAI_SERVING_COMPILE_CACHE
    # (off when unset — tests and embedded callers stay hermetic);
    # `cli serve` defaults it ON.
    compile_cache: Optional[bool] = None
    compile_cache_dir: Optional[str] = None
    # write/read the AOT warmup manifest beside each model artifact
    # (workflow/serialization.save_warmup_manifest): a cold warmup
    # records its wall seconds + ladder; a later replica (or same-shaped
    # swap) that matches the manifest reports the recovered compile
    # seconds as `serving_compile_cache_saved_s`
    warmup_manifest: bool = True
    # serving resilience knobs (serving/resilience.ResilienceParams as a
    # JSON dict): per-member health state machine, circuit breaker +
    # degraded fallback, hang watchdog. None = defaults (enabled);
    # {"enabled": false} turns the layer off
    resilience: Optional[Dict[str, Any]] = None
    # quantized inference ("int8"/"int4", workflow.compiled.ScoringQuant):
    # the request matrix ships on an affine narrow wire and fitted
    # tables compute in narrowed dtypes inside the fused bucket
    # programs. Stated per-feature tolerance scale/2 =
    # (hi − lo)/(2·(2^bits − 1)); "-calibrated" variants quantize
    # against fit-time fleet-wide ranges persisted with the model
    # (repeat scores bit-stable across batch compositions, quantization
    # is a constant-scale vectorized pass during batch staging), bare
    # modes against each batch's own range; None = exact f32 scoring.
    # Folded into the fleet's program-sharing signature, so quantized
    # and f32 members never adopt each other's programs (calibrated and
    # batch-relative builds of one mode DO share — scale/lo are traced
    # arguments).
    quantize: Optional[str] = None
    # request-scoped tracing + tail sampling (obs/trace.TracingParams
    # JSON): every /score request gets a span tree (W3C traceparent
    # honored + echoed, parse/queue-wait/pad/dispatch/demux phase
    # children, serving_phase_seconds histograms with trace-id
    # exemplars); the tail sampler keeps errors + the slow tail and
    # head-samples the healthy majority. None = defaults (ON);
    # {"enabled": false} turns request tracing off.
    tracing: Optional[Dict[str, Any]] = None
    # SLO burn-rate engine (obs/slo.SLOParams JSON): declarative
    # availability/latency/staleness objectives evaluated over this
    # service's registry with multi-window multi-burn-rate alerting,
    # surfaced on /slo + slo_* gauges + slo_alert events. None = off
    # (opt-in: an SLO without an operator reading it is noise).
    slo: Optional[Dict[str, Any]] = None
    # crash flight recorder (obs/flight.py): {"enabled": bool, "dir":
    # str, "capacity": int, "min_interval_s": float}. None = enabled
    # with defaults — serving processes should always have a black box.
    flight: Optional[Dict[str, Any]] = None

    def ladder(self) -> Tuple[int, ...]:
        if self.buckets:
            ladder = tuple(sorted(set(int(b) for b in self.buckets)))
            if ladder[0] < 1:
                raise ValueError(f"bucket sizes must be >= 1: {ladder}")
            return ladder
        return bucket_ladder(self.max_batch, self.min_bucket)


def raw_schema(model) -> Dict[str, type]:
    """Raw input column name -> feature type, from the model's own graph
    (the reader-schema derivation the runner uses, DataReader.scala:221)."""
    schema: Dict[str, type] = {}
    for rf in model.result_features:
        for f in rf.raw_features():
            schema[f.name] = f.ftype
    return schema


def _synthetic_rows(schema: Dict[str, type], n: int,
                    response_names: Sequence[str] = ()) -> List[Dict[str, Any]]:
    """Type-appropriate warmup rows: numerics 0, text-kinds None (the
    missing-value path every fitted stage already handles). Only SHAPES
    matter for warmup — the scores are discarded."""
    row: Dict[str, Any] = {}
    for name, ftype in schema.items():
        if name in response_names:
            continue
        if issubclass(ftype, T.Binary):
            row[name] = False
        elif issubclass(ftype, T.OPNumeric):
            row[name] = 0.0
        else:
            row[name] = None
    return [dict(row) for _ in range(n)]


class ModelVersion:
    """One loaded + warmed model: the unit of hot-swap."""

    def __init__(self, model, version_id: str,
                 path: Optional[str] = None, quant: Optional[str] = None):
        self.model = model
        self.version_id = version_id
        self.path = path or getattr(model, "loaded_from", None)
        self.loaded_at = time.time()
        self.scorer = model._ensure_compiled(quant=quant)
        self.compile_counts: Dict[int, int] = {}  # bucket -> traces seen
        self.warm_s: float = 0.0                  # measured warmup wall
        self.cache_saved_s: Optional[float] = None  # vs manifest cold warm

    def warm(self, ladder: Tuple[int, ...],
             warm_rows: Optional[List[Dict[str, Any]]] = None) -> None:
        """AOT-compile every bucket shape BEFORE serving traffic from this
        version. Warm data is synthesized from the model's raw schema
        (or caller-provided rows); per-bucket trace deltas are kept so
        the metrics surface can report compile counts per bucket."""
        from transmogrifai_tpu.analysis.retrace import MONITOR
        schema = raw_schema(self.model)
        responses = [f.name for rf in self.model.result_features
                     for f in rf.raw_features() if f.is_response]
        rows = warm_rows or _synthetic_rows(schema, 1, responses)
        base = Dataset.from_rows(
            rows, schema={k: v for k, v in schema.items()
                          if k in rows[0]})
        for bucket in ladder:
            before = MONITOR.snapshot()
            # score_padded only pads UP: truncate warm data for buckets
            # smaller than the provided warm rows
            sample = base if len(base) <= bucket \
                else base.take(np.arange(bucket))
            self.scorer.score_padded(sample, bucket)
            new = sum(MONITOR.delta(before).values())
            self.compile_counts[bucket] = \
                self.compile_counts.get(bucket, 0) + new

    def info(self) -> Dict[str, Any]:
        out = {"version": self.version_id, "path": self.path,
               "loaded_at": self.loaded_at,
               "warm_s": round(self.warm_s, 6),
               "compile_counts": {str(k): v
                                  for k, v in self.compile_counts.items()}}
        if self.cache_saved_s is not None:
            out["compile_cache_saved_s"] = round(self.cache_saved_s, 6)
        return out


@dataclass
class ScoreResult:
    """Per-request outcome: result feature name -> host arrays (sliced to
    this request's rows) + the serving version that produced it."""

    outputs: Dict[str, Any]
    model_version: str
    n_rows: int = 0
    latency_s: float = 0.0
    # request-scoped trace correlation (set when tracing is on): the
    # trace id this request's spans carry and the W3C traceparent echo
    # the HTTP layer returns as a response header
    trace_id: Optional[str] = None
    traceparent: Optional[str] = None

    def rows(self) -> List[Dict[str, Any]]:
        """Row-dict view of the outputs (the `/score` JSON shape),
        matching `score_function`'s per-row conversion."""
        out: List[Dict[str, Any]] = []
        for i in range(self.n_rows):
            row: Dict[str, Any] = {}
            for name, v in self.outputs.items():
                if isinstance(v, dict) and "prediction" in v:
                    m: Dict[str, float] = {"prediction": float(
                        np.asarray(v["prediction"])[i])}
                    prob = np.asarray(v["probability"])[i]
                    for j, x in enumerate(np.ravel(prob)):
                        m[f"probability_{j}"] = float(x)
                    row[name] = m
                elif isinstance(v, dict) and "value" in v:
                    present = bool(np.asarray(v["mask"])[i])
                    row[name] = (float(np.asarray(v["value"])[i])
                                 if present else None)
                else:
                    arr = np.asarray(v)
                    first = arr[i]
                    if arr.dtype == object:
                        row[name] = first
                    else:
                        row[name] = (first.tolist() if arr.ndim > 1
                                     else first.item())
            out.append(row)
        return out


class ScoringService:
    """Online scoring over a loaded WorkflowModel. See module docstring.

    Usage::

        svc = ScoringService.from_path("model_dir")
        svc.start()
        result = svc.score([{"age": 31.0, "sex": "male", ...}])
        svc.reload("model_dir_v2")   # warm, then atomic swap
        svc.rollback()               # back to the prior version
        svc.stop()
    """

    def __init__(self, model=None, version_id: Optional[str] = None,
                 config: Optional[ServingConfig] = None,
                 registry: Optional[MetricsRegistry] = None,
                 warm_rows: Optional[List[Dict[str, Any]]] = None):
        self.config = config or ServingConfig()
        self.ladder = self.config.ladder()
        self.registry = registry or MetricsRegistry()
        self.warm_rows = warm_rows
        self._swap_lock = threading.Lock()
        self._versions: List[ModelVersion] = []   # newest-last history
        self._active: Optional[ModelVersion] = None
        self._batcher = MicroBatcher(
            self.config.max_queue, self.ladder[-1],
            batch_wait_s=self.config.batch_wait_ms / 1000.0)
        # resident per-bucket batch staging (serving/staging.py): the
        # scoring thread writes each batch into preallocated buffers —
        # coalesce + pad are writes, not fresh concat/pad allocations.
        # Hot-swaps/rollbacks/rebuckets invalidate (generation fence).
        self._staging = StagingPool()
        # (rows, seconds) of batch-run row decodes, drained into the
        # perf corpus by the scoring thread AFTER each pad wall closes
        self._parse_notes: List[Tuple[int, float]] = []
        self._thread: Optional[threading.Thread] = None
        self._running = False
        # resilience layer: health state machine + breaker + watchdog
        # bookkeeping (serving/resilience.py). `_generation` fences the
        # scoring thread: a watchdog restart bumps it, and a stale
        # (formerly wedged) loop that wakes later sees the mismatch and
        # exits without touching shared state.
        self.resilience = ResilienceParams.from_json(
            self.config.resilience)
        self.fault_scope: Optional[str] = None  # fleet member name
        self._health: Optional[MemberHealth] = None
        if self.resilience.enabled:
            self._health = MemberHealth(self.resilience, registry=self.registry)
        self._generation = 0
        self._inflight_lock = threading.Lock()
        self._inflight: List[Request] = []
        self._busy_since: Optional[float] = None
        self._watchdog: Optional[Watchdog] = None
        self._own_watchdog = True   # fleet members are fleet-supervised
        self._m_fallback = None     # created lazily with member label
        self.started_at = time.time()          # epoch timestamp (display)
        self._started_mono = time.monotonic()  # uptime arithmetic (L009)
        self._trace_parent = None  # span the batcher thread nests under
        self._schema: Dict[str, type] = {}
        # request-scoped tracing: per-request span trees + tail sampling
        # (obs/trace.py). ON by default — the cost is a few Span objects
        # per request and the sampler keeps the process ring bounded.
        self.tracing = TracingParams.from_json(self.config.tracing)
        self.sampler: Optional[TailSampler] = (
            TailSampler(self.tracing, registry=self.registry)
            if self.tracing.enabled else None)
        # crash flight recorder: ring always armed for serving processes
        # (the serving plane is exactly where a post-mortem matters);
        # {"enabled": false} opts out, dir/capacity/debounce overridable
        flight_cfg = dict(self.config.flight or {})
        if flight_cfg.get("enabled", True):
            from transmogrifai_tpu.obs import flight
            flight.enable(
                dump_dir=flight_cfg.get("dir"),
                capacity=flight_cfg.get("capacity"),
                min_interval_s=flight_cfg.get("min_interval_s"))
        # SLO burn-rate engine (opt-in via config.slo)
        self.slo_engine = None
        if self.config.slo and dict(self.config.slo).get("enabled", True):
            self._build_slo_engine()
        # observed request-size distribution (rows per request): the
        # sample `derive_ladder` shapes the bucket ladder from
        self._sizes: deque = deque(maxlen=4096)
        self._auto_done = False   # an auto rebucket landed
        self._auto_seen = 0       # batches processed (auto trigger)
        self._auto_next = 256     # next attempt threshold
        # serializes ladder derivation+warm+swap: a slow warm must not
        # overlap a second derivation computed from the stale ladder
        self._rebucket_lock = threading.Lock()
        # persistent XLA compile cache: resolved BEFORE the first model
        # install so its warmup compiles land in (or hit) the cache
        cc = self.config.compile_cache
        if cc is None:
            cc = os.environ.get("TRANSMOGRIFAI_SERVING_COMPILE_CACHE",
                                "").lower() in ("1", "on", "true")
        self._compile_cache_path: Optional[str] = None
        if cc:
            from transmogrifai_tpu.utils.compile_cache import (
                enable_compile_cache)
            self._compile_cache_path = enable_compile_cache(
                self.config.compile_cache_dir, min_compile_s=0.0)
        self._init_metrics()
        if self.config.feature_cache:
            # device-matrix cache policy for this serving process: warm
            # scoring over a ColumnarStore replays the wire artifact,
            # and resident=True keeps the built matrices in HBM across
            # hot-swaps (a /reload swaps the MODEL, not the data)
            from transmogrifai_tpu.data.feature_cache import (
                FeatureCacheParams, set_default_cache_params)
            set_default_cache_params(
                FeatureCacheParams.from_json(dict(self.config.feature_cache)))
        if model is not None:
            self._install(model, version_id or "v0")

    # -- construction ------------------------------------------------------ #

    @classmethod
    def from_path(cls, model_location: str, **kwargs) -> "ScoringService":
        from transmogrifai_tpu.workflow.serialization import (
            load_model, model_fingerprint)
        model = load_model(model_location)
        return cls(model=model,
                   version_id=model_fingerprint(model_location), **kwargs)

    def _init_metrics(self) -> None:
        r = self.registry
        self._m_requests = r.counter(
            "serving_requests_total", "scoring requests admitted")
        self._m_rows = r.counter(
            "serving_rows_total", "rows scored (valid rows, not padding)")
        self._m_pad_rows = r.counter(
            "serving_padded_rows_total", "pad rows added for shape buckets")
        self._m_batches = r.counter(
            "serving_batches_total", "device batches dispatched")
        self._m_swaps = r.counter(
            "serving_model_swaps_total", "successful model hot-swaps")
        self._m_errors = r.counter(
            "serving_errors_total", "requests failed with internal errors")
        self._m_queue = r.gauge(
            "serving_queue_depth", "requests waiting in the bounded queue")
        self._m_latency = r.histogram(
            "serving_request_latency_seconds",
            "enqueue-to-resolve latency per request")
        self._m_batch_lat = r.histogram(
            "serving_batch_latency_seconds",
            "device batch execution latency")
        # µs-resolution buckets: host phases (parse, pad, demux) run in
        # tens of µs — on the default 100µs-floor ladder they all land
        # in the first bucket and the interpolated p50 is meaningless
        self._phase_hists = {
            phase: r.histogram(
                "serving_phase_seconds",
                "per-request time spent in each serving phase",
                bounds=MICRO_LATENCY_BUCKETS, phase=phase)
            for phase in self._PHASES}
        self._m_staging_alloc = r.counter(
            "serving_staging_allocations_total",
            "resident batch staging buffer sets (re)allocated")
        self._m_staging_fallback = r.counter(
            "serving_staging_fallback_total",
            "batches the staging pool refused (legacy concat path)")
        self._m_staging_gen = r.gauge(
            "serving_staging_generation",
            "staging-pool generation (bumps on hot-swap/rebucket)")

    def _shed(self, reason: str):
        return self.registry.counter(
            "serving_shed_total", "requests shed under overload",
            reason=reason)

    def _build_slo_engine(self) -> None:
        """Wire the declarative SLOs (obs/slo.py) onto this service's
        own registry: availability from the request/error/shed
        counters, latency from the request-latency histogram,
        staleness from the continual loop's freshness gauge on the
        process registry."""
        from transmogrifai_tpu.obs.metrics import get_registry
        from transmogrifai_tpu.obs.slo import (
            SLOEngine, SLOParams, availability_source, latency_source,
            staleness_source)
        params = SLOParams.from_json(self.config.slo)
        engine = SLOEngine(params, registry=self.registry)
        for slo in engine.slos():
            if slo.kind == "availability":
                engine.set_source(slo.name, availability_source(
                    self.registry, "serving_requests_total",
                    error_families=("serving_errors_total",),
                    shed_families=("serving_shed_total",)))
            elif slo.kind == "latency":
                engine.set_source(slo.name, latency_source(
                    self.registry, "serving_request_latency_seconds",
                    slo.threshold_s))
            elif slo.kind == "staleness":
                engine.set_source(slo.name, staleness_source(
                    get_registry(), "continual_staleness_current_seconds",
                    slo.threshold_s))
        from transmogrifai_tpu.obs.slo import maybe_attach_fleet
        maybe_attach_fleet(engine)
        self.slo_engine = engine

    # the closed phase-label set (span names are `serving:<phase>`);
    # request-derived values never become labels
    _PHASES = ("parse", "assemble", "queue_wait", "pad",
               "device_dispatch", "demux", "admission")

    def _phase_hist(self, phase: str):
        """The labeled per-phase latency family (`serving_phase_seconds
        {phase=...}`). The fixed set is pre-bound at init (the
        `_init_metrics` convention) so the per-request finish path
        never takes the registry lock; an unexpected phase still
        resolves through the registry rather than dropping data."""
        hist = self._phase_hists.get(phase)
        if hist is None:
            hist = self.registry.histogram(
                "serving_phase_seconds",
                "per-request time spent in each serving phase",
                bounds=MICRO_LATENCY_BUCKETS, phase=phase)
            self._phase_hists[phase] = hist
        return hist

    def _finish_request_trace(self, rt: Optional[RequestTrace],
                              latency_s: float,
                              error: Optional[str] = None) -> None:
        """Request-trace epilogue on EVERY exit path (success, shed,
        deadline, scoring error): end the root, run the tail-sampling
        decision, and on keep record the phase histograms with this
        trace's id pinned as the bucket exemplar (exemplars must point
        at traces that EXIST — a dropped trace id would 404)."""
        if rt is None:
            if error is None:
                self._m_latency.observe(latency_s)
            return
        rt.finish(error)
        kept = False
        if self.sampler is not None:
            kept = self.sampler.observe(rt, latency_s,
                                        error=error is not None)
        exemplar = rt.trace_id if kept else None
        for phase, dur in rt.phase_durations().items():
            self._phase_hist(phase).observe(dur, exemplar=exemplar)
        if error is None:
            # the request-latency family has always counted SUCCESSFUL
            # resolves only; the kept trace's id rides along as the
            # exemplar on whichever bucket this latency landed in
            self._m_latency.observe(latency_s, exemplar=exemplar)

    def _install(self, model, version_id: str,
                 path: Optional[str] = None) -> ModelVersion:
        """Load-side half of a swap: compile + warm OFF the serving path,
        then atomically flip `_active`."""
        version = ModelVersion(model, version_id, path=path,
                               quant=self.config.quantize)
        path = version.path  # falls back to the model's loaded_from
        if self.config.warm_on_load:
            manifest = None
            if path and self.config.warmup_manifest:
                from transmogrifai_tpu.workflow.serialization import (
                    load_warmup_manifest)
                manifest = load_warmup_manifest(path)
                if manifest is not None and (
                        manifest.get("fingerprint") != version_id
                        or manifest.get("ladder") != list(self.ladder)):
                    manifest = None  # stale sidecar: treat as cold
            t0 = time.perf_counter()
            version.warm(self.ladder, self.warm_rows)
            version.warm_s = time.perf_counter() - t0
            # bucket label only (no version label): label cardinality must
            # stay bounded by the ladder width, not grow per reload — the
            # per-version breakdown lives in health()['versions'] instead
            for bucket, n in version.compile_counts.items():
                self.registry.counter(
                    "serving_bucket_compiles_total",
                    "XLA traces attributed to each shape bucket at warmup",
                    bucket=bucket).inc(n)
            self._note_warmup(version, manifest)
        with self._swap_lock:
            self._versions.append(version)
            keep = max(2, self.config.keep_versions)
            del self._versions[:-keep]
            self._active = version
            self._schema = raw_schema(model)
        # the new model may stage a different column layout: fence the
        # resident batch buffers (scoring thread reallocates lazily)
        self._staging.invalidate()
        self.registry.gauge(
            "serving_model_versions", "versions held (active + rollback)"
        ).set(len(self._versions))
        return version

    def _note_warmup(self, version: ModelVersion,
                     manifest: Optional[Dict[str, Any]]) -> None:
        """Cold-start accounting around one warmup: with a matching
        manifest AND the persistent compile cache enabled, the delta to
        the manifest's recorded cold warmup is the measured recovery
        (`serving_compile_cache_saved_s` + a `compile_cache_saved`
        goodput event); a warmup that actually compiled programs with
        no prior manifest IS the cold baseline and writes one. A warmup
        absorbed by shared programs (zero traces, no manifest claim)
        records neither — its near-zero wall must not become a 'cold'
        baseline that poisons future savings."""
        n_compiles = sum(version.compile_counts.values())
        if manifest is not None and self._compile_cache_path \
                and n_compiles > 0:
            # n_compiles gate: a warmup absorbed by the fleet's SHARED
            # programs traces nothing — its near-zero wall against the
            # manifest's cold baseline is program-sharing's win, not the
            # compile cache's, and must not be booked here
            saved = max(0.0, float(manifest.get("warm_s") or 0.0)
                        - version.warm_s)
            version.cache_saved_s = saved
            self.registry.counter(
                "serving_compile_cache_saved_s",
                "warmup seconds recovered by the persistent compile "
                "cache vs the recorded cold warmup").inc(saved)
            try:
                from transmogrifai_tpu.obs.export import record_event
                record_event("compile_cache_saved",
                             saved_s=round(saved, 6),
                             warm_s=round(version.warm_s, 6),
                             model_version=version.version_id)
            except Exception:
                log.debug("compile_cache_saved event failed",
                          exc_info=True)
        elif (version.path and self.config.warmup_manifest
                and manifest is None and n_compiles > 0):
            from transmogrifai_tpu.workflow.serialization import (
                save_warmup_manifest)
            save_warmup_manifest(version.path, {
                "fingerprint": version.version_id,
                "ladder": list(self.ladder),
                "warm_s": round(version.warm_s, 6),
                "compiles": n_compiles,
                "compile_counts": {str(k): v for k, v
                                   in version.compile_counts.items()},
                "signature": getattr(version.scorer,
                                     "program_signature", None),
                "compile_cache": bool(self._compile_cache_path),
                "warmed_at": time.time(),
            })

    # -- lifecycle --------------------------------------------------------- #

    def start(self) -> "ScoringService":
        if self._active is None:
            raise RuntimeError("no model installed — pass one or reload()")
        if self._running:
            return self
        if self._batcher.closed:  # restart after stop(): fresh admissions
            self._batcher = MicroBatcher(
                self.config.max_queue, self.ladder[-1],
                batch_wait_s=self.config.batch_wait_ms / 1000.0)
        # the scoring thread does not inherit this context: capture the
        # caller's current span so batch spans nest under the run that
        # started the service (e.g. the runner's serve phase)
        self._trace_parent = TRACER.current()
        self._running = True
        self._start_scoring_thread()
        if self._health is not None and self._own_watchdog:
            # single-service mode supervises itself; fleet members are
            # covered by the FleetService-level watchdog instead
            self._watchdog = Watchdog(
                lambda: {"service": self},
                period_s=self.resilience.watchdog_period_s)
            self._watchdog.start()
        if self.slo_engine is not None:
            # alert events attach to the span that started the service
            # (the engine thread has no ambient span of its own)
            self.slo_engine.span = self._trace_parent
            self.slo_engine.start()
        return self

    def _start_scoring_thread(self) -> None:
        gen = self._generation
        self._thread = threading.Thread(
            target=self._serve_loop, args=(gen,),
            name=f"scoring-batcher-{gen}", daemon=True)
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._running = False
        if self.slo_engine is not None:
            self.slo_engine.stop()
        if self._watchdog is not None:
            self._watchdog.stop()
            self._watchdog = None
        for req in self._batcher.close():
            req.fail(ScoreError("shutdown", "service stopped"))
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            if self._thread is not None and self._thread.is_alive():
                # the scoring thread is wedged (e.g. a hung dispatch):
                # its in-flight batch must still be ANSWERED, not left
                # blocking clients forever on a dead service
                self._fail_inflight(ScoreError(
                    "shutdown",
                    "service stopped with the batch still in flight"))
            self._thread = None

    # -- resilience: liveness + recovery ------------------------------------ #

    def _fault_site(self, base: str) -> str:
        """Fleet members scope injection sites by member name
        (`serving.device_dispatch#a`) so a chaos plan can storm ONE
        member while its peers run clean."""
        return f"{base}#{self.fault_scope}" if self.fault_scope else base

    def _has_fallback(self) -> bool:
        with self._swap_lock:
            return len(self._versions) >= 2

    def _fail_inflight(self, error: ScoreError) -> List[Request]:
        """Quarantine the in-flight batch per-request: every client
        blocked on it gets a structured error NOW (never a hang)."""
        with self._inflight_lock:
            batch, self._inflight = self._inflight, []
            self._busy_since = None
        for req in batch:
            if not req._event.is_set():
                self._m_errors.inc()
                if self._health is not None:
                    self._health.note_request(False)
                req.fail(error)
        return batch

    def check_liveness(self) -> Optional[str]:
        """Watchdog probe: ``"dead"`` when the scoring thread exited
        (killed by a BaseException), ``"stalled"`` when its current
        batch has been in flight past ``watchdog_stall_s`` (a wedged
        jit dispatch), else None."""
        if not self._running:
            return None
        th = self._thread
        if th is None:
            return None
        if not th.is_alive():
            return "dead"
        busy = self._busy_since
        if busy is not None and (
                time.monotonic() - busy) > self.resilience.watchdog_stall_s:
            return "stalled"
        return None

    def recover_scoring_thread(self, reason: str) -> None:
        """Watchdog recovery: fence off the wedged/dead loop (generation
        bump), answer its in-flight batch with structured errors, and
        start a fresh scoring thread over the SAME batcher (queued
        requests keep their place). Recorded as
        `serving_watchdog_restarts_total` + a ``watchdog_restart``
        event; the health machine quarantines until recovery is
        re-proven (or the window washes clean)."""
        with self._inflight_lock:
            stalled_since = self._busy_since
        self._generation += 1
        # a stale (formerly wedged) loop that wakes mid-batch may still
        # WRITE the staging buffers it fetched; orphan them so the
        # restarted loop allocates a fresh set it alone owns
        self._staging.invalidate()
        # the recovery gets its own span under the service's trace so
        # the watchdog_restart + health_transition events it emits land
        # in the goodput rollup (the watchdog thread has no ambient span)
        with TRACER.span("serving:watchdog_restart", category="serving",
                         parent=self._trace_parent, reason=reason,
                         member=self.fault_scope or "service"):
            if self._health is not None:
                self._health.note_stall(since=stalled_since)
            self._fail_inflight(ScoreError(
                "watchdog_restart",
                f"scoring loop {reason}; thread restarted — retry",
                retry_after_s=self.resilience.watchdog_period_s))
            self.registry.counter(
                "serving_watchdog_restarts_total",
                "scoring threads restarted by the hang watchdog",
                reason=reason).inc()
            try:
                from transmogrifai_tpu.obs.export import record_event
                record_event("watchdog_restart", reason=reason,
                             member=self.fault_scope or "service")
            except Exception:
                log.debug("watchdog_restart event failed", exc_info=True)
            # black box: the ring holds the batches that led up to the
            # wedge/death — dump it before the evidence scrolls away
            try:
                from transmogrifai_tpu.obs import flight
                flight.request_dump(f"watchdog_{reason}")
            except Exception:
                log.debug("flight dump on watchdog restart failed",
                          exc_info=True)
            log.warning("serving%s: scoring loop %s; restarting thread "
                        "(generation %d)",
                        f"[{self.fault_scope}]" if self.fault_scope
                        else "", reason, self._generation)
            if self._running:
                self._start_scoring_thread()
            if self._health is not None:
                self._health.clear_stall()

    def __enter__(self) -> "ScoringService":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- client API -------------------------------------------------------- #

    def _begin_request_trace(self, trace: Any,
                             n_rows: int) -> Optional[RequestTrace]:
        """The request's span buffer: an incoming `RequestTrace` (the
        fleet router already opened it around admission) passes
        through; a `TraceContext` (W3C wire context or an in-process
        parent span, e.g. a continual cycle) roots the request under
        the caller's trace; None mints a fresh trace id."""
        if isinstance(trace, RequestTrace):
            return trace
        if self.sampler is None:
            return None
        ctx = trace if isinstance(trace, TraceContext) else None
        return RequestTrace(ctx=ctx, rows=n_rows,
                            member=self.fault_scope or "service")

    def score(self, rows: List[Dict[str, Any]],
              deadline_ms: Optional[float] = None,
              timeout_s: Optional[float] = None,
              trace: Any = None) -> ScoreResult:
        """Score `rows` (list of raw-column dicts). Blocks until the
        micro-batcher resolves this request or its deadline passes.
        Raises ScoreError with a structured code on shed/expiry/bad
        input — the service keeps serving others regardless.

        `trace` carries request-scoped trace context (an
        `obs.trace.TraceContext` from a ``traceparent`` header or an
        in-process parent span, or a pre-opened `RequestTrace`); every
        exit path — success, shed, deadline, error — finishes the
        request's span tree and runs it through the tail sampler."""
        return self._traced_score(
            trace, len(rows or ()),
            lambda rt: self._score_inner(rows, deadline_ms, timeout_s,
                                         rt))

    def _traced_score(self, trace: Any, n_rows: int,
                      inner) -> ScoreResult:
        """The request-trace envelope shared by BOTH wires: open the
        span buffer, run `inner(rt)`, and on every exit path — success,
        shed, deadline, error — finish the trace, run tail sampling,
        and stamp the trace id onto the result or the raised
        ScoreError (a failed request must be as correlatable as a slow
        one)."""
        rt = self._begin_request_trace(trace, n_rows)
        t0 = time.monotonic()
        try:
            result = inner(rt)
        except ScoreError as e:
            self._finish_request_trace(rt, time.monotonic() - t0,
                                       error=e.code)
            if rt is not None:
                # error traces are the ones tail sampling ALWAYS keeps:
                # the failed response must carry the id a client needs
                # to find them (HTTP echoes it as headers + body field)
                e.trace_id = rt.trace_id
                e.traceparent = rt.traceparent()
            raise
        except BaseException as e:
            self._finish_request_trace(rt, time.monotonic() - t0,
                                       error=type(e).__name__)
            raise
        self._finish_request_trace(rt, result.latency_s)
        if rt is not None:
            result.trace_id = rt.trace_id
            result.traceparent = rt.traceparent()
        return result

    def _admit(self) -> None:
        """Shared admission preamble for BOTH wires: reject when the
        service is down, and FAST-FAIL a quarantined member with no
        resident fallback (structured error + retry-after) instead of
        queueing into a dead (or known-broken) batcher."""
        if not self._running:
            raise ScoreError("shutdown", "service is not running")
        if self._health is not None:
            retry_after = self._health.admit(self._has_fallback())
            if retry_after is not None:
                self._shed("circuit_open").inc()
                raise ScoreError(
                    "circuit_open",
                    f"member quarantined (breaker open / scoring loop "
                    f"down); retry in {retry_after:.2f}s",
                    retry_after_s=retry_after)

    def _score_inner(self, rows: List[Dict[str, Any]],
                     deadline_ms: Optional[float],
                     timeout_s: Optional[float],
                     rt: Optional[RequestTrace]) -> ScoreResult:
        self._admit()
        if not rows:
            raise ScoreError("bad_request", "empty rows")
        # the row PIVOT is deferred: admission validates shape only
        # (per-request wire checks, bucket fit) and the scoring thread
        # encodes the whole batch's rows through ONE compiled-codec
        # pass during staging (data/rowcodec.py; amortized host work
        # replacing the per-request Dataset.from_rows loop ROADMAP
        # called out as the serving p50 dominator). The parse child
        # therefore times request-side wire validation; the amortized
        # batch encode lands in the `pad` (staging) phase.
        if rt is not None:
            with rt.child("serving:assemble") as asm:
                with rt.child("serving:parse", parent=asm,
                              rows=len(rows)):
                    self._validate_rows(rows)
                bucket_for(len(rows), self.ladder)  # must fit a bucket
        else:
            self._validate_rows(rows)
            bucket_for(len(rows), self.ladder)  # admission: must fit
        return self._enqueue(None, deadline_ms, timeout_s, rt,
                             rows=rows)

    def _validate_rows(self, rows: List[Dict[str, Any]]) -> None:
        for r in rows:
            if not isinstance(r, dict):
                raise ScoreError(
                    "bad_request",
                    f"rows must be objects, got {type(r).__name__}")

    def _enqueue(self, ds: Optional[Dataset],
                 deadline_ms: Optional[float],
                 timeout_s: Optional[float],
                 rt: Optional[RequestTrace],
                 rows: Optional[List[Dict[str, Any]]] = None
                 ) -> ScoreResult:
        """Shared post-parse half of row and columnar scoring: deadline
        resolution, admission into the micro-batcher, and the blocking
        wait on the request future. Both wires land here, so mixed
        row/columnar traffic coalesces into the same batches and shares
        one bucket ladder."""
        if deadline_ms is None:
            ddl_ms = self.config.default_deadline_ms
        else:
            try:
                ddl_ms = float(deadline_ms)
            except (TypeError, ValueError):
                raise ScoreError(
                    "bad_request",
                    f"deadline_ms must be a number, got {deadline_ms!r}")
        deadline = (time.monotonic() + ddl_ms / 1000.0) if ddl_ms > 0 \
            else None
        n_rows = len(ds) if ds is not None else len(rows)
        self._sizes.append(n_rows)
        req = Request(ds, deadline, trace=rt, rows=rows,
                      schema=self._schema)
        if rt is not None:
            rt.enqueued_s = now_s()  # queue-wait span starts here
        try:
            self._batcher.put(req)
        except ScoreError as e:
            self._shed(e.code).inc()
            if e.code == "queue_full" and e.retry_after_s is None:
                # proportional backoff: predicted time to drain the
                # backlog through top-rung batches. None while the
                # model is cold — the HTTP layer then answers its
                # constant default, exactly the pre-model behavior.
                e.retry_after_s = self.predicted_drain_s()
            raise
        self._m_requests.inc()
        self._m_queue.set(self._batcher.depth())
        wait_s = timeout_s if timeout_s is not None else (
            ddl_ms / 1000.0 + 30.0 if ddl_ms > 0 else None)
        outputs, version = req.wait(wait_s)
        latency = time.monotonic() - req.enqueued_at
        return ScoreResult(outputs=outputs, model_version=version,
                           n_rows=req.n_rows, latency_s=latency)

    def _parse_rows(self, rows: List[Dict[str, Any]]) -> Dataset:
        """Row wire → Dataset through the compiled codec cache (kept
        for embedded callers and tests — the serving path itself now
        defers the pivot to batch staging). The FULL raw schema is
        passed (not a rows[0]-filtered subset): a column absent from
        the first row but present in a later one must still be
        schema-typed, never value-inferred — the old filter produced
        dtype-inconsistent batches on ragged first rows."""
        try:
            return encode_rows(rows, self._schema)
        except Exception as e:
            raise ScoreError("bad_request", f"unparseable rows: {e}")

    def _note_parse(self, n_rows: int, seconds: float) -> None:
        """Sampled host-parse cost into the perf corpus
        (`serving_parse` target): the ladder derivation and any other
        host-cost consumer can then PREDICT parse seconds per request
        size instead of assuming host work is free. Never raises."""
        try:
            from transmogrifai_tpu import perf
            perf.note_parse(n_rows, len(self._schema), seconds)
        except Exception:
            log.debug("perf parse recording failed", exc_info=True)

    def score_columns(self, columns: Dict[str, List[Any]],
                      deadline_ms: Optional[float] = None,
                      timeout_s: Optional[float] = None,
                      trace: Any = None) -> ScoreResult:
        """Columnar request wire: score ``{name: [values...]}`` with NO
        row pivot — callers that already hold columns (feature stores,
        batch scorers, the HTTP ``{"columns": ...}`` body) skip the
        per-row parse entirely; outputs are bit-identical to the row
        wire for the same data. Ragged lengths, unknown columns, and
        undeclarable cell types are structured ``bad_request``s.
        Columnar and row traffic coalesce into the same device batches
        (one bucket ladder)."""
        if not isinstance(columns, dict) or not columns:
            raise ScoreError("bad_request",
                             'expected {"columns": {name: [values...]}}')
        n_rows = 0
        for v in columns.values():
            n_rows = len(v) if hasattr(v, "__len__") else 0
            break
        return self._traced_score(
            trace, n_rows,
            lambda rt: self._score_columns_inner(columns, deadline_ms,
                                                 timeout_s, rt))

    def _score_columns_inner(self, columns: Dict[str, List[Any]],
                             deadline_ms: Optional[float],
                             timeout_s: Optional[float],
                             rt: Optional[RequestTrace]) -> ScoreResult:
        self._admit()
        t0 = time.perf_counter()
        if rt is not None:
            with rt.child("serving:assemble") as asm:
                with rt.child("serving:parse", parent=asm,
                              columnar=True):
                    ds = self._parse_columns(columns)
                bucket_for(len(ds), self.ladder)
        else:
            ds = self._parse_columns(columns)
            bucket_for(len(ds), self.ladder)
        # perf-corpus note AFTER the span: corpus appends are sampled
        # file IO and must never pollute the parse timing they record
        self._note_parse(len(ds), time.perf_counter() - t0)
        return self._enqueue(ds, deadline_ms, timeout_s, rt)

    def _parse_columns(self, columns: Dict[str, List[Any]]) -> Dataset:
        try:
            ds = columns_dataset(columns, self._schema,
                                 strict_schema=True)
        except ValueError as e:
            raise ScoreError("bad_request", f"bad columnar payload: {e}")
        except Exception as e:
            raise ScoreError("bad_request",
                             f"unparseable columnar payload: {e}")
        if len(ds) == 0:
            raise ScoreError("bad_request", "empty columns")
        return ds

    def score_row(self, row: Dict[str, Any], **kw) -> Dict[str, Any]:
        """Single-row convenience: returns the one result row dict."""
        return self.score([row], **kw).rows()[0]

    # -- hot swap ---------------------------------------------------------- #

    def reload(self, model_location: str) -> Dict[str, Any]:
        """Load + warm a new serialized model, then atomically swap it
        under traffic. The displaced version stays resident for
        `rollback()`. In-flight batches finish on the version they were
        dispatched with — no request is ever mis-versioned.

        The candidate dir is integrity-verified BEFORE anything is
        loaded: a torn/corrupt artifact is rejected with a structured
        error (and a `serving_reload_rejected_total` tick) while the
        resident version keeps serving untouched."""
        from transmogrifai_tpu.workflow.serialization import (
            ModelIntegrityError, load_model, model_fingerprint,
            verify_model_dir)
        try:
            verify_model_dir(model_location)
            vid = model_fingerprint(model_location)
        except (ModelIntegrityError, OSError) as e:
            self.registry.counter(
                "serving_reload_rejected_total",
                "reloads rejected by artifact integrity verification").inc()
            log.warning("serving: reload of %s rejected (%s); resident "
                        "version keeps serving", model_location, e)
            raise ScoreError(
                "bad_request",
                f"reload rejected by integrity check, resident version "
                f"keeps serving: {e}")
        active = self._active
        if active is not None and active.version_id == vid:
            return {"status": "unchanged", "version": vid}
        # injectable load failure (chaos: serving.reload_load) — an
        # error here propagates to the caller while the resident
        # version keeps serving untouched
        fault_point(self._fault_site(SITE_RELOAD_LOAD))
        model = load_model(model_location, verify=False)  # verified above
        version = self._install(model, vid, path=model_location)
        self._m_swaps.inc()
        log.info("serving: swapped to model %s from %s", vid,
                 model_location)
        return {"status": "swapped", "version": version.version_id,
                "previous": active.version_id if active else None}

    def rollback(self) -> Dict[str, Any]:
        """Re-activate the previous resident version (already warm —
        rollback is instant, no compile)."""
        with self._swap_lock:
            if len(self._versions) < 2:
                raise ScoreError("bad_request",
                                 "no previous version to roll back to")
            demoted = self._versions.pop()
            restored = self._versions[-1]
            self._active = restored
            self._schema = raw_schema(restored.model)
            n_versions = len(self._versions)
        self._staging.invalidate()  # restored model's layout may differ
        self.registry.gauge(
            "serving_model_versions", "versions held (active + rollback)"
        ).set(n_versions)
        self._m_swaps.inc()
        self.registry.counter(
            "serving_rollbacks_total",
            "model versions rolled back (manual + automatic)").inc()
        log.info("serving: rolled back %s -> %s", demoted.version_id,
                 restored.version_id)
        return {"status": "rolled_back", "version": restored.version_id,
                "previous": demoted.version_id}

    # -- learned bucket ladder ---------------------------------------------- #

    def suggest_ladder(self) -> Tuple[int, ...]:
        """The ladder the cost model + observed request sizes would
        pick right now (`serving/batcher.derive_ladder`). With an
        explicit `buckets` config, a cold model, or no traffic yet,
        this is the current ladder unchanged."""
        if self.config.buckets:
            return self.ladder
        try:
            from transmogrifai_tpu import perf
            model = perf.get_model()
        except Exception:
            model = None
        return derive_ladder(self.config.max_batch, self.config.min_bucket,
                             list(self._sizes), model,
                             n_cols=len(self._schema))

    def rebucket(self) -> Dict[str, Any]:
        """Re-derive the bucket ladder from observed traffic + predicted
        per-bucket latency and swap it in under traffic: new rungs are
        AOT-warmed on the active version OFF the serving path first, so
        the scoring thread never compiles mid-request. The top rung
        (max_batch) never changes, so admission capacity is stable.
        Serialized: concurrent rebuckets (auto + manual) would each
        derive from the same stale ladder and double-swap."""
        with self._rebucket_lock:
            return self._rebucket_locked()

    def _rebucket_locked(self) -> Dict[str, Any]:
        new = tuple(self.suggest_ladder())
        if new == tuple(self.ladder):
            return {"status": "unchanged", "ladder": list(self.ladder)}
        fresh = tuple(b for b in new if b not in self.ladder)
        if self.config.warm_on_load and fresh:
            with self._swap_lock:
                versions = list(self._versions)
            for version in versions:
                # EVERY resident version, not just the active one: a
                # post-rebucket rollback() must stay 'already warm — no
                # compile', so the demoted version needs the new rungs
                # compiled too
                version.warm(fresh, self.warm_rows)
        old = self.ladder
        with self._swap_lock:
            self.ladder = new
        self._staging.invalidate()  # per-bucket buffers keyed off rungs
        self.registry.counter(
            "serving_rebuckets_total",
            "bucket-ladder re-derivations applied").inc()
        log.info("serving: bucket ladder rebucketed %s -> %s",
                 list(old), list(new))
        try:
            from transmogrifai_tpu.obs.export import record_event
            record_event("ladder_rebucket", previous=list(old),
                         ladder=list(new))
        except Exception:
            log.debug("rebucket event emission failed", exc_info=True)
        return {"status": "rebucketed", "ladder": list(new),
                "previous": list(old)}

    def _auto_rebucket(self) -> None:
        if not self._rebucket_lock.acquire(blocking=False):
            return  # a previous attempt is still deriving/warming
        try:
            # refit from the corpus first: the serving_bucket rows this
            # process has been recording are younger than the cached
            # model's refit cadence, and a stale fit derives the cold
            # (unchanged) ladder
            from transmogrifai_tpu import perf
            perf.refresh()
            if self._rebucket_locked()["status"] == "rebucketed":
                self._auto_done = True
        except Exception:
            log.warning("serving: auto rebucket failed; ladder unchanged",
                        exc_info=True)
        finally:
            self._rebucket_lock.release()

    def rearm_auto_rebucket(self) -> bool:
        """Re-arm the auto-rebucket trigger after its one shot landed.
        The shot stays one-shot ORGANICALLY (a derived ladder should not
        churn under stable traffic); a controller that watched the
        traffic mix shift (SLO burn) re-arms it under its own cooldown.
        The next scored batch re-derives from the freshest size sample.
        Returns False when there was nothing to re-arm (still armed, or
        the auto path is off for this config)."""
        if not self.config.auto_ladder or self.config.buckets:
            return False
        if not self._auto_done:
            return False
        self._auto_done = False
        self._auto_next = self._auto_seen + 1
        return True

    def predicted_drain_s(self) -> Optional[float]:
        """Predicted seconds to drain the CURRENT queue backlog through
        top-rung batches (perf.predict_drain_seconds), clamped to
        [0.1, 30] so a runaway fit can never tell clients to go away
        for an hour. None while the cost model is cold."""
        try:
            from transmogrifai_tpu import perf
            depth = self._batcher.depth()
            top = max(self.ladder) if self.ladder else \
                self.config.max_batch
            pred = perf.predict_drain_seconds(max(1, depth), top)
            if pred is None:
                return None
            return round(max(0.1, min(30.0, pred.value)), 3)
        except Exception:
            log.debug("drain-time prediction failed", exc_info=True)
            return None

    # -- introspection ----------------------------------------------------- #

    def health(self) -> Dict[str, Any]:
        active = self._active
        if not (self._running and active):
            status = "down"
        elif self._health is not None and \
                self._health.state == QUARANTINED:
            # still "serving" when a fallback version exists, but the
            # primary path is dark — /healthz reports it as unhealthy
            # (503 + Retry-After) so balancers drain this member
            status = "quarantined"
        else:
            status = "ok"
        out = {
            "status": status,
            "model_version": active.version_id if active else None,
            "uptime_s": round(time.monotonic() - self._started_mono, 3),
            "queue_depth": self._batcher.depth(),
            "buckets": list(self.ladder),
            "compile_cache": self._compile_cache_path,
            "versions": [v.info() for v in self._versions],
            "staging": {
                "generation": self._staging.generation,
                "allocations": self._staging.allocations,
                "assembled": self._staging.assembled,
                "fallbacks": self._staging.fallbacks,
            },
        }
        if self._health is not None:
            out["health"] = self._health.snapshot()
            if status == "quarantined":
                out["retry_after_s"] = round(
                    max(self._health.retry_after_s(),
                        self.resilience.watchdog_period_s), 3)
        return out

    # -- scoring thread ---------------------------------------------------- #

    def _serve_loop(self, gen: int = 0) -> None:
        while self._running and self._generation == gen:
            batch, expired = self._batcher.next_batch()
            if self._generation != gen:
                # fenced off by a watchdog restart while we were blocked:
                # hand anything we popped back to the live loop's clients
                # as structured errors (they were already answered if
                # they were in flight when the restart fired)
                for req in [*batch, *expired]:
                    if not req._event.is_set():
                        req.fail(ScoreError(
                            "watchdog_restart",
                            "scoring loop restarted; retry"))
                return
            self._m_queue.set(self._batcher.depth())
            for req in expired:
                self._shed("deadline_exceeded").inc()
                req.fail(ScoreError(
                    "deadline_exceeded",
                    "request deadline passed while queued"))
            if not batch:
                continue
            self._auto_seen += 1
            if (self.config.auto_ladder and not self._auto_done
                    and not self.config.buckets
                    and self._auto_seen >= self._auto_next):
                # deferred rebucket once the size sample is dense enough;
                # off-thread — warming new rungs must not stall the
                # scoring loop. RETRIED every ~512 batches until one
                # lands: at the first attempt the cached model is often
                # still cold on the serving target (its fit predates the
                # bucket rows this very traffic recorded), and a one-shot
                # flag would silently disable the feature forever.
                self._auto_next = self._auto_seen + 512
                threading.Thread(target=self._auto_rebucket,
                                 name="serving-rebucket",
                                 daemon=True).start()
            with self._inflight_lock:
                if self._generation != gen:
                    continue  # fenced: top of loop exits
                self._inflight = list(batch)
                self._busy_since = time.monotonic()
            # NO `finally` around the in-flight clear: a BaseException
            # (InjectedKill / fatal runtime error) must leave the batch
            # REGISTERED as in flight while it kills this thread, so the
            # watchdog's recovery can answer those clients — a finally
            # would wipe the list on the way out and orphan them
            try:
                self._process(batch, gen)
            except Exception as e:  # the scoring thread must NEVER die
                log.exception("serving: unexpected batch failure")
                for req in batch:
                    if not req._event.is_set():
                        req.fail(ScoreError(
                            "internal",
                            f"unexpected serving failure: "
                            f"{type(e).__name__}: {e}"))
            with self._inflight_lock:
                if self._generation == gen:
                    self._inflight = []
                    self._busy_since = None

    def _dispatch_plan(self) -> Tuple[ModelVersion, str]:
        """(version, mode) for this batch. Modes:

        - ``primary``: the active version, breaker closed (normal path);
        - ``probe``: breaker open, half-open slot claimed — dispatch the
          active version to test recovery;
        - ``fallback``: breaker open, resident previous version exists —
          degraded mode, serve known-good answers instead of going dark;
        - ``reject``: breaker open, no fallback, probe not due — fail
          the batch fast with ``circuit_open``."""
        version = self._active
        h = self._health
        if h is None or not h.breaker_open:
            return version, "primary"
        if h.probe_due():
            return version, "probe"
        prev = None
        with self._swap_lock:
            if len(self._versions) >= 2:
                prev = self._versions[-2]
        if prev is not None:
            return prev, "fallback"
        return version, "reject"

    def _live(self, gen: Optional[int]) -> bool:
        """True while `gen` is still the current scoring generation. A
        stale (watchdog-fenced) thread that wakes mid-batch may still
        RESOLVE its requests (harmless — they were already answered)
        but must not note health/breaker state or account metrics for
        a generation it no longer belongs to."""
        return gen is None or self._generation == gen

    def _queue_wait_spans(self, batch: List[Request],
                          t_end: float) -> List[Request]:
        """Backdate one ``serving:queue_wait`` child per traced request
        (enqueue tick → batch pickup) and return the traced subset."""
        traced = [r for r in batch if r.trace is not None]
        for r in traced:
            if r.trace.enqueued_s is not None:
                r.trace.child_at("serving:queue_wait",
                                 r.trace.enqueued_s, t_end)
        return traced

    def _process(self, batch: List[Request],
                 gen: Optional[int] = None) -> None:
        version, mode = self._dispatch_plan()
        assert version is not None
        t_pickup = now_s()
        traced = self._queue_wait_spans(batch, t_pickup)
        if mode == "reject":
            retry_after = self._health.retry_after_s() if self._health \
                else None
            for req in batch:
                self._m_errors.inc()
                req.fail(ScoreError(
                    "circuit_open",
                    "breaker open and no resident fallback version",
                    retry_after_s=retry_after))
            return
        t0 = time.monotonic()
        with TRACER.span("serving:batch", category="serving",
                         parent=self._trace_parent,
                         requests=len(batch), mode=mode,
                         version=version.version_id) as sp:
            try:
                # batch ASSEMBLY quarantines too: two requests with
                # mismatched column sets/ftypes fail staging AND the
                # concat fallback, and that must degrade to per-request
                # scoring, not kill the batch — and it is NOT a device
                # failure, so it feeds the health window but never the
                # breaker
                fault_point(self._fault_site(SITE_BATCH_ASSEMBLE))
                t_pad0 = now_s()
                ds, n_valid, bucket = self._assemble_batch(batch)
                t_pad1 = now_s()
                sp.set(bucket=bucket, rows=n_valid)
            except Exception as e:
                log.warning("serving: batch assembly of %d requests "
                            "failed (%s); quarantining per-request",
                            len(batch), e)
                for req in batch:
                    self._score_single(req, version, mode, gen)
                return
            for r in traced:
                r.trace.child_at("serving:pad", t_pad0, t_pad1,
                                 bucket=bucket, batch_rows=n_valid)
            if self._parse_notes:
                # pad wall is closed: the sampled corpus appends can
                # no longer pollute the timing they record
                for n_rows, secs in self._parse_notes:
                    self._note_parse(n_rows, secs)
                self._parse_notes = []
            t_d0 = now_s()
            try:
                if mode != "fallback":
                    # degraded fallback skips the site: the injected
                    # fault models a broken ACTIVE version, and the
                    # resident previous version is the recovery path
                    fault_point(self._fault_site(SITE_DEVICE_DISPATCH))
                out = version.scorer.score_padded(ds, bucket)
            except Exception as e:
                t_d1 = now_s()
                for r in traced:
                    # the failing dispatch is part of this request's
                    # story (and the flight recorder's): the quarantine
                    # re-score appends its own dispatch span after it
                    r.trace.child_at(
                        "serving:device_dispatch", t_d0, t_d1,
                        error=f"{type(e).__name__}: {e}"[:200],
                        bucket=bucket, mode=mode,
                        version=version.version_id)
                if self._live(gen):
                    self._note_dispatch(False, mode)
                # error quarantine: one bad record must fail ONE
                # request. Re-score each request alone so its
                # batchmates still get answers; only the offender sees
                # the error.
                log.warning("serving: batch of %d requests failed (%s); "
                            "quarantining per-request", len(batch), e)
                for req in batch:
                    self._score_single(req, version, mode, gen)
                return
            t_d1 = now_s()
            for r in traced:
                r.trace.child_at("serving:device_dispatch", t_d0, t_d1,
                                 bucket=bucket, mode=mode,
                                 version=version.version_id)
            # success-path health notes stay INSIDE the batch span:
            # their events (breaker_close on a probe win, degraded_
            # fallback, health_transition) attach to this trace —
            # outside the span they would vanish from the goodput rollup
            latency = time.monotonic() - t0
            live = self._live(gen)
            if live:
                self._note_dispatch(True, mode)
                if mode == "fallback":
                    self._note_fallback(len(batch), version)
                if self._health is not None:
                    for _ in batch:
                        self._health.note_request(True, latency)
        if live:
            self._account_batch(len(batch), n_valid, bucket, latency)
        off = 0
        for req in batch:
            t_x0 = now_s()
            sliced = {name: slice_result_tree(v, off, off + req.n_rows)
                      for name, v in out.items()}
            if req.trace is not None:
                req.trace.child_at("serving:demux", t_x0, now_s())
            req.resolve(sliced, version.version_id)
            off += req.n_rows

    def _assemble_batch(self, batch: List[Request]
                        ) -> Tuple[Dataset, int, int]:
        """Coalesce + pad through the resident staging pool: the
        batch's ROW-WIRE requests are decoded by ONE compiled-codec
        pass per aligned run (amortized host parse — the scoring
        thread pays one pivot per batch, not the callers one per
        request), every part's columns are WRITTEN into slices of the
        per-bucket staging block, and the pad tail repeats the last
        valid row — zero fresh staging allocations in steady state
        (the parse-smoke assert). The staged dataset is already
        bucket-sized, so `score_padded`'s own concat+pad path no-ops
        and the device write reads straight off the staging buffers.
        Batches the pool refuses (mixed column layouts, exact-int
        object columns) take the legacy concat path — correctness
        never depends on staging."""
        pool = self._staging
        n_valid = sum(r.n_rows for r in batch)
        bucket = bucket_for(n_valid, self.ladder)
        parts = self._encode_parts(batch)
        alloc0, fb0 = pool.allocations, pool.fallbacks
        staged = pool.assemble(parts, n_valid, bucket)
        self._m_staging_alloc.inc(pool.allocations - alloc0)
        self._m_staging_gen.set(pool.generation)
        if staged is None:
            self._m_staging_fallback.inc(pool.fallbacks - fb0)
            ds = Dataset.concat(parts) if len(parts) > 1 else parts[0]
            return ds, n_valid, bucket
        return staged, n_valid, bucket

    def _encode_parts(self, batch: List[Request]) -> List[Dataset]:
        """Order-preserving Dataset parts for one batch: already-
        columnar requests pass through; consecutive row-wire requests
        whose rows all share one key order decode through a SINGLE
        `RowCodec.encode_aligned` call (one pivot + one bulk cast for
        the whole run). A run with mixed key orders degrades to
        per-request encodes (each request keeps its own column-union
        semantics — two requests with different column sets must fail
        assembly exactly like the eager path did). Runs group by each
        request's ENQUEUE-TIME schema object, never the live
        `self._schema`: a hot-swap between enqueue and assembly must
        not re-type queued requests against the new model."""
        from transmogrifai_tpu.data.rowcodec import codec_for
        parts: List[Dataset] = []
        run: List[Request] = []
        run_schema: Optional[Dict[str, type]] = None

        def flush() -> None:
            if not run:
                return
            t0 = time.perf_counter()
            k0 = None
            vals: List[Any] = []
            aligned = True
            for req in run:
                for r in req.rows:
                    kt = tuple(r)
                    if k0 is None:
                        k0 = kt
                    elif kt != k0:
                        aligned = False
                        break
                    vals.append(r.values())
                if not aligned:
                    break
            if aligned:
                parts.append(codec_for(k0, run_schema)
                             .encode_aligned(vals, len(vals)))
            else:
                parts.extend(req.dataset for req in run)
            # deferred to _process AFTER the pad wall closes: the
            # sampled corpus append is file IO and must not ride the
            # pad-phase timing it helps explain
            self._parse_notes.append(
                (sum(req.n_rows for req in run),
                 time.perf_counter() - t0))
            run.clear()

        for req in batch:
            if req._dataset is not None:
                flush()
                parts.append(req._dataset)
            else:
                if run and req._schema is not run_schema:
                    flush()
                run_schema = req._schema
                run.append(req)
        flush()
        return parts

    def _note_dispatch(self, ok: bool, mode: str) -> None:
        """Primary-path dispatch outcomes feed the breaker; fallback
        dispatches prove nothing about the broken primary and stay out."""
        if self._health is not None and mode in ("primary", "probe"):
            self._health.note_dispatch(ok, probe=(mode == "probe"))

    def _note_fallback(self, n_requests: int, version: ModelVersion) -> None:
        if self._m_fallback is None:
            self._m_fallback = self.registry.counter(
                "serving_degraded_fallback_total",
                "requests served by the resident previous version while "
                "the breaker was open")
        self._m_fallback.inc(n_requests)
        try:
            from transmogrifai_tpu.obs.export import record_event
            record_event("degraded_fallback", requests=n_requests,
                         member=self.fault_scope or "service",
                         version=version.version_id)
        except Exception:
            log.debug("degraded_fallback event failed", exc_info=True)

    def _score_single(self, req: Request, version: ModelVersion,
                      mode: str = "primary",
                      gen: Optional[int] = None) -> None:
        t0 = time.monotonic()
        # materialize the (possibly deferred) row decode BEFORE the
        # dispatch site: a client-malformed payload is a bad_request —
        # an INPUT problem, never a member outcome — so it must feed
        # neither the circuit breaker nor the health error-rate window
        # (either would let sustained malformed traffic from one client
        # quarantine a healthy member for every tenant)
        try:
            ds = req.dataset
        except Exception as e:
            if self._live(gen):
                self._m_errors.inc()
            req.fail(ScoreError(
                "bad_request",
                f"unparseable rows: {type(e).__name__}: {e}"))
            return
        t_d0 = now_s()
        try:
            bucket = bucket_for(req.n_rows, self.ladder)
            if mode != "fallback":
                fault_point(self._fault_site(SITE_DEVICE_DISPATCH))
            out = version.scorer.score_padded(ds, bucket)
            if req.trace is not None:
                req.trace.child_at("serving:device_dispatch", t_d0,
                                   now_s(), bucket=bucket, mode=mode,
                                   quarantined=True,
                                   version=version.version_id)
            latency = time.monotonic() - t0
            if self._live(gen):
                self._note_dispatch(True, mode)
                self._account_batch(1, req.n_rows, bucket, latency)
                if mode == "fallback":
                    self._note_fallback(1, version)
                if self._health is not None:
                    self._health.note_request(True, latency)
            req.resolve(out, version.version_id)
        except ScoreError as e:
            # admission-shaped failure (oversized request): not a
            # dispatch failure — never feeds the breaker
            if self._live(gen):
                self._m_errors.inc()
                if self._health is not None:
                    self._health.note_request(False,
                                              time.monotonic() - t0)
            req.fail(e)
        except Exception as e:
            if req.trace is not None:
                req.trace.child_at(
                    "serving:device_dispatch", t_d0, now_s(),
                    error=f"{type(e).__name__}: {e}"[:200], mode=mode,
                    quarantined=True, version=version.version_id)
            if self._live(gen):
                self._note_dispatch(False, mode)
                self._m_errors.inc()
                if self._health is not None:
                    self._health.note_request(False,
                                              time.monotonic() - t0)
            req.fail(ScoreError(
                "record_error",
                f"request failed scoring in isolation: "
                f"{type(e).__name__}: {e}"))

    def _account_batch(self, n_requests: int, n_valid: int, bucket: int,
                       latency_s: float) -> None:
        # cost-model corpus row (sampled) + predicted-vs-measured
        # residual for this bucket's compiled shape; never raises
        try:
            from transmogrifai_tpu import perf
            perf.note_serving(bucket, latency_s)
        except Exception:
            log.debug("perf serving recording failed", exc_info=True)
        self._m_batches.inc()
        self._m_rows.inc(n_valid)
        self._m_pad_rows.inc(bucket - n_valid)
        self._m_batch_lat.observe(latency_s)
        self.registry.counter(
            "serving_bucket_batches_total",
            "device batches dispatched per shape bucket",
            bucket=bucket).inc()
        self.registry.counter(
            "serving_bucket_requests_total",
            "requests coalesced per shape bucket",
            bucket=bucket).inc(n_requests)
