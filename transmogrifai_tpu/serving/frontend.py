"""Warmth-aware L7 router over K fleet replicas.

The fleet can now share its cold-start artifacts through the store
(`store/`); this is the tier that makes K replicas LOOK like one
service. A `Frontend` holds N `FleetService` replicas (in-process
tier-0; the replica surface it consumes — `health()`, `score*()` — is
exactly what a remote replica exposes over HTTP, so a URL-backed
replica handle can slot in later), learns each replica's WARMTH from
its health/warmup reports — which models it hosts, which bucket-ladder
programs are compiled, whether resident staging buffers are live — and
routes every request to the warmest replica for its (model, bucket),
breaking ties power-of-two-choices on queue depth so one warm replica
doesn't melt while an equally-warm peer idles.

Admission stays in each replica's `Router`; with `FleetConfig.
shared_quota` the replicas meter against the CAS-guarded shared balance
(store/state.py), so the over-quota tenant gets its 429 from EITHER
replica and the K-replica sum stays inside one tenant's rate — the
frontend never needs a per-request quota round trip of its own.

Speaks both request wires: the JSON row/columnar body and the binary
columnar framing (serving/binwire.py) — decoded ONCE here at the edge,
then handed to the replica as columns (no JSON re-encode on the hop).

`/metrics` on the frontend HTTP server is the fleet-wide view:
`MetricsRegistry.merge()` over every replica registry (counters sum,
gauges keep a `replica` label, histograms merge buckets). With a
``store_dir`` the frontend also federates: ``/metrics/fleet`` serves
the replicas' PUBLISHED snapshots (works across processes, where
in-process registry merging can't reach), and a sampled request's
frontend leg is appended to the store's ``frontend`` trace shard so
`obs.federate.merge_fleet_trace` stitches frontend → replica into one
Perfetto timeline.

Remote replicas are first-class: `HTTPReplica` wraps a replica fleet's
base URL behind the same `health()`/`score*()` surface the in-process
`FleetService` exposes — the replica hop forwards the W3C
``traceparent`` (frontend request root as the parent), which is what
makes the cross-process stitch possible.
"""

from __future__ import annotations

import json
import logging
import random
import threading
import time
from http.server import ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

from transmogrifai_tpu.obs.metrics import MetricsRegistry
from transmogrifai_tpu.obs.trace import (RequestTrace, TRACER, TraceContext,
                                         format_traceparent, span_id_hex)
from transmogrifai_tpu.serving.batcher import ScoreError, bucket_for
from transmogrifai_tpu.serving.http import (
    _columnar_payload, _JSONHandler, _row_payload)

log = logging.getLogger(__name__)

__all__ = ["Frontend", "FrontendHTTPServer", "HTTPReplica",
           "serve_frontend"]


def _record_event(name: str, **attrs: Any) -> None:
    try:
        from transmogrifai_tpu.obs.export import record_event
        record_event(name, **attrs)
    except Exception:
        log.debug("%s event emission failed", name, exc_info=True)


class Frontend:
    """Route requests across replicas by warmth. See module docstring."""

    def __init__(self, replicas: Dict[str, Any],
                 registry: Optional[MetricsRegistry] = None,
                 refresh_s: float = 2.0, seed: int = 0,
                 store_dir: Optional[str] = None):
        if not replicas:
            raise ValueError("frontend needs at least one replica")
        self.replicas = dict(replicas)
        self.registry = registry or MetricsRegistry()
        self.refresh_s = float(refresh_s)
        self.store_dir = store_dir
        self.shard_writer = None
        if store_dir:
            # publish the frontend leg of sampled traces to the shared
            # store so merge_fleet_trace can stitch across processes
            from transmogrifai_tpu.obs.federate import TraceShardWriter
            self.shard_writer = TraceShardWriter(store_dir, "frontend")
            self.shard_writer.install()
        self._lock = threading.Lock()
        self._warmth: Dict[str, Dict[str, Any]] = {}  # guarded-by: self._lock
        self._refreshed = 0.0  # guarded-by: self._lock
        self._rng = random.Random(seed)  # guarded-by: self._lock
        # fidelity route flips (autopilot-owned): requests for a key
        # model route AND score as the mapped resident sibling (e.g.
        # the int8-calibrated build) until the flip is cleared. Plain
        # table write — no compile, no drop; the caller must emit the
        # actuation event that justified it (lint L022).
        self._route_overrides: Dict[str, str] = {}  # guarded-by: self._lock
        self._m_requests = {}  # pre-bound per (replica, wire) lazily
        self._m_latency = self.registry.histogram(
            "router_request_latency_seconds",
            "frontend route + replica scoring latency")
        self._m_warm = self.registry.counter(
            "router_warm_hits_total",
            "requests routed to a replica with warm bucket programs")
        self._m_cold = self.registry.counter(
            "router_cold_routes_total",
            "requests routed with no warm replica available")
        self._m_frame_err = self.registry.counter(
            "router_frame_errors_total",
            "binary frames rejected as bad_request")
        self.refresh()

    # -- warmth ------------------------------------------------------------ #

    def refresh(self) -> Dict[str, Dict[str, Any]]:
        """Pull each replica's health report and distill the routing
        facts: hosted models, their ladders, whether the ladder is
        compiled (warm), staging residency, queue depth."""
        reports: Dict[str, Dict[str, Any]] = {}
        for name, fleet in self.replicas.items():
            try:
                health = fleet.health()
            except Exception as e:
                log.warning("frontend: replica %s health failed: %s",
                            name, e)
                reports[name] = {"status": "down", "models": {}}
                continue
            models: Dict[str, Dict[str, Any]] = {}
            for mname, m in (health.get("models") or {}).items():
                versions = m.get("versions") or []
                active = versions[0] if versions else {}
                staging = m.get("staging") or {}
                models[mname] = {
                    "status": m.get("status"),
                    "buckets": list(m.get("buckets") or ()),
                    "queue_depth": int(m.get("queue_depth") or 0),
                    # warm = the active version finished its warmup
                    # ladder (compile counts reported) — the fact the
                    # warmup manifest records for replay
                    "warm": bool(active.get("compile_counts")
                                 or active.get("warmed")),
                    "staging": bool(staging.get("allocations")),
                }
            reports[name] = {"status": health.get("status"),
                             "models": models}
        with self._lock:
            self._warmth = reports
            self._refreshed = time.monotonic()
        return reports

    def _maybe_refresh(self) -> None:
        with self._lock:
            stale = (time.monotonic() - self._refreshed) > self.refresh_s
        if stale:
            self.refresh()

    @staticmethod
    def _score_warmth(entry: Optional[Dict[str, Any]],
                      n_rows: int) -> int:
        """0 = can't serve, 1 = hosts the model cold, 2 = warm
        programs, 3 = warm + resident staging for this bucket."""
        if not entry or entry.get("status") not in ("ok", "degraded"):
            return 0
        score = 1
        if entry.get("warm"):
            score += 1
            if entry.get("staging"):
                buckets = entry.get("buckets") or ()
                try:
                    bucket_for(max(1, n_rows), tuple(buckets))
                    score += 1
                except (ScoreError, ValueError):
                    # rows overflow the replica's bucket ladder: its
                    # resident staging cannot host this request, so no
                    # staging point — warm-programs score stands
                    log.debug("warmth: %d rows overflow ladder %r",
                              n_rows, buckets)
        return score

    def set_route_override(self, model: str,
                           target: Optional[str] = None) -> Optional[str]:
        """Install (or, with target=None, clear) a fidelity route flip
        for `model`. Returns the previous target (None if none)."""
        with self._lock:
            if target is None:
                return self._route_overrides.pop(model, None)
            prev = self._route_overrides.get(model)
            self._route_overrides[model] = str(target)
            return prev

    def resolve_route(self, model: str) -> str:
        """The model name requests for `model` actually score as."""
        with self._lock:
            return self._route_overrides.get(model, model)

    def route(self, model: str, n_rows: int) -> Tuple[str, Any, bool]:
        """(replica_name, fleet, warm?) for one request. Warmest wins;
        ties break power-of-two-choices on queue depth."""
        self._maybe_refresh()
        with self._lock:
            warmth = {name: dict((self._warmth.get(name) or {})
                                 .get("models", {}).get(model) or {})
                      for name in self.replicas}
            scored = [(self._score_warmth(entry or None, n_rows), name)
                      for name, entry in warmth.items()]
            best = max(s for s, _ in scored)
            candidates = [name for s, name in scored if s == best]
            if best == 0:
                # nobody reports the model (all cold or health lag):
                # spread p2c over everyone and let the replica 404
                candidates = list(self.replicas)
            if len(candidates) > 2:
                candidates = self._rng.sample(candidates, 2)
            elif len(candidates) == 2 and self._rng.random() < 0.5:
                candidates.reverse()
        name = min(candidates,
                   key=lambda n: (warmth.get(n) or {}).get(
                       "queue_depth", 0))
        return name, self.replicas[name], best >= 2

    # -- scoring ----------------------------------------------------------- #

    def _count(self, replica: str, wire: str) -> None:
        key = (replica, wire)
        m = self._m_requests.get(key)
        if m is None:
            m = self.registry.counter(
                "router_requests_total",
                "requests routed per replica and wire",
                replica=replica, wire=wire)
            # conc-ok: C001 (idempotent memo — racing writers store the
            # same registry-deduped Counter object)
            self._m_requests[key] = m
        m.inc()

    def _route_and_score(self, model: str, n_rows: int, wire: str,
                         call, trace: Optional[TraceContext] = None
                         ) -> Any:
        t0 = time.monotonic()
        rt = None
        downstream = trace
        if trace is not None and (trace.sampled
                                  or trace.parent is not None):
            # sampled cross-hop request: the frontend leg gets its own
            # request root in the caller's trace, and the replica hop
            # is re-parented under it (same trace id, root as parent)
            # so merge_fleet_trace stitches frontend → replica
            rt = RequestTrace(name="router:request", ctx=trace,
                              rows=n_rows, model=model, wire=wire)
            downstream = TraceContext(
                trace_id=rt.trace_id,
                parent_hex=span_id_hex(rt.root.span_id),
                parent=rt.root, sampled=True)
        try:
            if rt is not None:
                route_span = rt.child("router:route", model=model,
                                      wire=wire)
            else:
                route_span = TRACER.span("router:route",
                                         category="router",
                                         model=model, wire=wire)
            with route_span:
                name, fleet, warm = self.route(model, n_rows)
            (self._m_warm if warm else self._m_cold).inc()
            self._count(name, wire)
        except Exception:
            if rt is not None:
                rt.finish("internal")
                TRACER.collect(rt.spans)
            raise
        try:
            result = call(fleet, downstream)
        except ScoreError as e:
            if rt is not None:
                rt.finish(e.code)
                TRACER.collect(rt.spans)
            _record_event("router_route", replica=name, model=model,
                          wire=wire, warm=warm, rows=n_rows,
                          outcome=e.code)
            raise
        if rt is not None:
            rt.finish()
            TRACER.collect(rt.spans)
        self._m_latency.observe(time.monotonic() - t0)
        _record_event("router_route", replica=name, model=model,
                      wire=wire, warm=warm, rows=n_rows, outcome="ok")
        return result

    def score(self, model: str, rows: List[Dict[str, Any]],
              tenant: Optional[str] = None,
              deadline_ms: Optional[float] = None,
              trace: Optional[TraceContext] = None):
        model = self.resolve_route(model)
        return self._route_and_score(
            model, len(rows or ()), "json",
            lambda fleet, tr: fleet.score(model, rows, tenant=tenant,
                                          deadline_ms=deadline_ms,
                                          trace=tr),
            trace=trace)

    def score_columns(self, model: str, columns: Dict[str, Any],
                      tenant: Optional[str] = None,
                      deadline_ms: Optional[float] = None,
                      trace: Optional[TraceContext] = None,
                      wire: str = "json"):
        model = self.resolve_route(model)
        n_rows = 0
        for v in (columns or {}).values():
            n_rows = len(v) if hasattr(v, "__len__") else 0
            break
        return self._route_and_score(
            model, n_rows, wire,
            lambda fleet, tr: fleet.score_columns(
                model, columns, tenant=tenant,
                deadline_ms=deadline_ms, trace=tr),
            trace=trace)

    def score_frame(self, frame: bytes,
                    trace: Optional[TraceContext] = None):
        """Binary wire entry: decode once at the edge, route on the
        header, hand the replica decoded columns. Malformed frames are
        bad_request and never reach (or get charged to) a replica."""
        from transmogrifai_tpu.serving.binwire import decode_frame
        try:
            columns, meta = decode_frame(frame)
        except ScoreError:
            self._m_frame_err.inc()
            raise
        model = meta.get("model")
        if not isinstance(model, str) or not model:
            self._m_frame_err.inc()
            raise ScoreError("bad_request",
                             "binary frame: missing model name")
        return self.score_columns(
            model, columns, tenant=meta.get("tenant"),
            deadline_ms=meta.get("deadline_ms"), trace=trace,
            wire="binary")

    # -- introspection ------------------------------------------------------ #

    def health(self) -> Dict[str, Any]:
        reports = self.refresh()
        statuses = [r.get("status") for r in reports.values()]
        if any(s == "ok" for s in statuses):
            status = ("ok" if all(s == "ok" for s in statuses)
                      else "degraded")
        else:
            status = "down"
        return {"status": status, "replicas": reports}

    def warmth(self) -> Dict[str, Any]:
        self._maybe_refresh()
        with self._lock:
            return {name: dict(report)
                    for name, report in self._warmth.items()}

    def merged_registry(self) -> MetricsRegistry:
        """Fleet-wide metrics: the frontend's own router_* series plus
        every replica registry merged (counters sum, gauges labeled
        per replica, histogram buckets folded)."""
        merged = MetricsRegistry()
        merged.merge(self.registry, replica="frontend")
        for name, fleet in self.replicas.items():
            merged.merge(fleet.registry, replica=name)
        return merged

    def fleet_metrics_json(self) -> Dict[str, Any]:
        """Federated metrics: fold every replica's PUBLISHED snapshot
        from the shared store (obs.federate) with the frontend's own
        router_* series. Unlike merged_registry() this reaches replicas
        in OTHER processes — HTTPReplica handles carry an empty local
        registry, their real series arrive through the store."""
        if not self.store_dir:
            raise ScoreError(
                "not_found",
                "frontend has no store_dir: metrics federation is off")
        from transmogrifai_tpu.obs.federate import aggregate_fleet_metrics
        merged, info = aggregate_fleet_metrics(self.store_dir)
        merged.merge(self.registry, replica="frontend")
        return {"replicas": info, "fleet": merged.to_json()}

    def close(self) -> None:
        """Tear down the frontend's trace-shard sink (no-op without a
        store_dir)."""
        if self.shard_writer is not None:
            self.shard_writer.close()
            self.shard_writer = None


class _RemoteResult:
    """Scoring result decoded from a replica's HTTP response — the
    slice of the in-process result surface the frontend handler reads
    (`rows()`, `model_version`, `latency_s`, trace echo)."""

    def __init__(self, payload: Dict[str, Any],
                 headers: Dict[str, str]):
        self._scores = payload.get("scores")
        self.model_version = payload.get("model_version")
        self.latency_s = float(payload.get("latency_ms") or 0.0) / 1000.0
        self.traceparent = headers.get("traceparent")
        self.trace_id = (payload.get("trace_id")
                         if self.traceparent else None)

    def rows(self) -> Any:
        return self._scores


class HTTPReplica:
    """URL-backed replica handle: the `health()`/`score*()` surface a
    `Frontend` consumes, served by a remote fleet's HTTP endpoint
    (serving/http.py `serve_fleet`). Forwards the downstream
    `TraceContext` as a W3C ``traceparent`` header so the replica's leg
    of a sampled request lands in ITS trace shard under the frontend's
    trace id — `obs.federate.merge_fleet_trace` does the stitching.

    Carries an empty local `registry` (satisfies `merged_registry()`);
    the replica's real series federate through the store
    (`/metrics/fleet`), not through this handle."""

    def __init__(self, base_url: str, timeout_s: float = 30.0):
        self.base_url = base_url.rstrip("/")
        self.timeout_s = float(timeout_s)
        self.registry = MetricsRegistry()

    @staticmethod
    def _trace_header(trace: Optional[TraceContext]
                      ) -> Optional[str]:
        if trace is None or not trace.trace_id:
            return None
        if trace.parent is not None:
            return format_traceparent(trace.trace_id,
                                      trace.parent.span_id,
                                      sampled=trace.sampled)
        if trace.parent_hex:
            return format_traceparent(trace.trace_id, trace.parent_hex,
                                      sampled=trace.sampled)
        return None

    def _request(self, method: str, path: str,
                 body: Optional[bytes] = None,
                 headers: Optional[Dict[str, str]] = None
                 ) -> Tuple[int, Dict[str, Any], Dict[str, str]]:
        import urllib.error
        import urllib.request
        req = urllib.request.Request(self.base_url + path, data=body,
                                     headers=dict(headers or {}),
                                     method=method)
        try:
            with urllib.request.urlopen(
                    req, timeout=self.timeout_s) as resp:
                payload = json.loads(resp.read().decode("utf-8"))
                return resp.status, payload, dict(resp.headers)
        except urllib.error.HTTPError as e:
            try:
                payload = json.loads(e.read().decode("utf-8"))
            except Exception:
                payload = {"error": "internal",
                           "message": f"replica HTTP {e.code}"}
            return e.code, payload, dict(e.headers or {})
        except (urllib.error.URLError, OSError, ValueError) as e:
            raise ScoreError("internal",
                             f"replica {self.base_url}{path}: {e}")

    def _score_request(self, payload: Dict[str, Any],
                       trace: Optional[TraceContext]) -> _RemoteResult:
        headers = {"Content-Type": "application/json"}
        tp = self._trace_header(trace)
        if tp:
            headers["traceparent"] = tp
        status, body, resp_headers = self._request(
            "POST", "/score", json.dumps(payload).encode("utf-8"),
            headers)
        if status != 200:
            retry = resp_headers.get("Retry-After")
            raise ScoreError(
                str(body.get("error") or "internal"),
                str(body.get("message") or f"replica HTTP {status}"),
                retry_after_s=float(retry) if retry else None)
        return _RemoteResult(body, resp_headers)

    def health(self) -> Dict[str, Any]:
        # both 200 (ok/degraded) and 503 (down) carry the health body
        _, body, _ = self._request("GET", "/healthz")
        return body

    def score(self, model: str, rows: List[Dict[str, Any]],
              tenant: Optional[str] = None,
              deadline_ms: Optional[float] = None,
              trace: Optional[TraceContext] = None) -> _RemoteResult:
        payload: Dict[str, Any] = {"model": model, "rows": rows}
        if tenant:
            payload["tenant"] = tenant
        if deadline_ms is not None:
            payload["deadline_ms"] = deadline_ms
        return self._score_request(payload, trace)

    def score_columns(self, model: str, columns: Dict[str, Any],
                      tenant: Optional[str] = None,
                      deadline_ms: Optional[float] = None,
                      trace: Optional[TraceContext] = None
                      ) -> _RemoteResult:
        cols = {k: (list(v) if hasattr(v, "__len__")
                    and not isinstance(v, list) else v)
                for k, v in (columns or {}).items()}
        payload: Dict[str, Any] = {"model": model, "columns": cols}
        if tenant:
            payload["tenant"] = tenant
        if deadline_ms is not None:
            payload["deadline_ms"] = deadline_ms
        return self._score_request(payload, trace)


class FrontendHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer carrying the Frontend reference."""

    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, address: Tuple[str, int], frontend: Frontend):
        super().__init__(address, _FrontendHandler)
        self.frontend = frontend

    @property
    def port(self) -> int:
        return self.server_address[1]


class _FrontendHandler(_JSONHandler):
    """Router routes:

    - ``POST /score``  JSON row/columnar body (same shape as the fleet
      endpoint) or a binary columnar frame under the
      ``application/x-transmogrifai-columnar`` content type;
    - ``GET /healthz`` aggregated replica health (200 while ANY replica
      serves);
    - ``GET /warmth``  the routing table the frontend decides with;
    - ``GET /metrics`` fleet-wide merged exposition (?format=json);
    - ``GET /metrics/fleet`` federated exposition from the replicas'
      store-published snapshots (cross-process; 404 without a store).
    """

    @property
    def frontend(self) -> Frontend:
        return self.server.frontend  # type: ignore[attr-defined]

    def do_GET(self) -> None:  # noqa: N802
        path, _, query = self.path.partition("?")
        if path == "/healthz":
            self._send_health(self.frontend.health())
        elif path == "/warmth":
            self._send_json(200, {"replicas": self.frontend.warmth()})
        elif path == "/metrics/fleet":
            try:
                self._send_json(200, self.frontend.fleet_metrics_json())
            except ScoreError as e:
                self._send_error(e)
        elif path == "/metrics":
            merged = self.frontend.merged_registry()
            if "format=json" in query:
                self._send_json(200, merged.to_json())
            else:
                self._send(200, merged.to_prometheus().encode(),
                           content_type="text/plain; version=0.0.4")
        else:
            self._send_json(404, {"error": "not_found",
                                  "message": f"no route {path}"})

    def do_POST(self) -> None:  # noqa: N802
        path = self.path.partition("?")[0]
        try:
            if path != "/score":
                self._send_json(404, {"error": "not_found",
                                      "message": f"no route {path}"})
                return
            ctype = (self.headers.get("Content-Type") or "")
            ctype = ctype.partition(";")[0].strip().lower()
            from transmogrifai_tpu.serving.binwire import CONTENT_TYPE
            if ctype == CONTENT_TYPE:
                result = self.frontend.score_frame(
                    self._read_bytes(), trace=self._trace_ctx())
                model = None
            else:
                body = self._read_json()
                model = body.get("model")
                if not model:
                    raise ScoreError(
                        "bad_request",
                        'expected {"model": "name", "rows": [...]}')
                tenant = (body.get("tenant")
                          or self.headers.get("X-Tenant"))
                cols = _columnar_payload(body)
                if cols is not None:
                    result = self.frontend.score_columns(
                        str(model), cols, tenant=tenant,
                        deadline_ms=body.get("deadline_ms"),
                        trace=self._trace_ctx())
                else:
                    result = self.frontend.score(
                        str(model), _row_payload(body), tenant=tenant,
                        deadline_ms=body.get("deadline_ms"),
                        trace=self._trace_ctx())
            self._send_json(200, {
                "scores": result.rows(),
                "model": model,
                "model_version": result.model_version,
                "latency_ms": round(result.latency_s * 1000.0, 3),
                "trace_id": result.trace_id,
            }, headers=self._trace_headers(result))
        except ScoreError as e:
            self._send_error(e)
        except Exception as e:  # keep the server alive on handler bugs
            log.exception("http: unhandled frontend error on %s", path)
            self._send_json(500, {"error": "internal",
                                  "message": f"{type(e).__name__}: {e}"})


def serve_frontend(frontend: Frontend, host: str = "127.0.0.1",
                   port: int = 0, block: bool = True
                   ) -> Tuple[FrontendHTTPServer,
                              Optional[threading.Thread]]:
    """Boot the router HTTP frontend — same contract as `serve` /
    `serve_fleet` (port=0 binds a free port; block=False runs on a
    daemon thread)."""
    server = FrontendHTTPServer((host, port), frontend)
    if block:
        try:
            server.serve_forever(poll_interval=0.2)
        finally:
            server.server_close()
        return server, None
    thread = threading.Thread(target=server.serve_forever,
                              kwargs={"poll_interval": 0.2},
                              name="router-http", daemon=True)
    thread.start()
    return server, thread
