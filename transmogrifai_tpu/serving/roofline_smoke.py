"""Roofline-scoring smoke: fused dispatch, quantized parity, lifted
parameters — end to end, in one process.

`make roofline-smoke` runs this module. Under a minute on CPU it must
prove the acceptance surface of the roofline scoring work
(`workflow/compiled.py` + the lifted model families + `serving/`):

1. whole-pipeline fusion: a warm `ScoringService` executes exactly ONE
   device dispatch per bucket per score call
   (`analysis.retrace.DISPATCHES`-asserted per rung);
2. quantized inference: int8 scoring agrees with the f32 path within
   the stated per-feature wire tolerance (the linear-path error bound
   sum(|w_d|·scale_d/2) computed from the model's own weights), and
   the quantized build's signature never adopts the f32 programs;
3. parameter lifting: TWO different same-shaped linear fits in one
   fleet share ONE compiled program set — the second member warms with
   ZERO new traces and scores bit-identically to a solo load;
4. honest accounting: `scoring_hbm_frac` is present and nonzero in the
   smoke payload (achieved bytes/s from XLA's program bytes over the
   measured warm device execution, against peak HBM bandwidth).

Run: ``JAX_PLATFORMS=cpu python -m transmogrifai_tpu.serving.roofline_smoke``
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time


def _train_models(tmp: str):
    """a + b: logistic pipelines over IDENTICAL features with different
    labels — identical scoring signatures (weights are LIFTED jit
    arguments), different fitted coefficients."""
    import numpy as np

    import transmogrifai_tpu.types as t
    from transmogrifai_tpu.data import Dataset
    from transmogrifai_tpu.features import FeatureBuilder
    from transmogrifai_tpu.models import OpLogisticRegression
    from transmogrifai_tpu.ops.numeric import RealVectorizer
    from transmogrifai_tpu.workflow import Workflow

    rng = np.random.default_rng(7)
    n = 160
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)

    def fit(name: str, y) -> None:
        ds = Dataset({"x1": x1, "x2": x2, "y": y},
                     {"x1": t.Real, "x2": t.Real, "y": t.Integral})
        preds, label = FeatureBuilder.from_dataset(ds, response="y")
        vec = RealVectorizer(track_nulls=False) \
            .set_input(*preds).get_output()
        pred = OpLogisticRegression(max_iter=30) \
            .set_input(label, vec).get_output()
        Workflow().set_result_features(pred, label) \
            .set_input_dataset(ds).train().save(os.path.join(tmp, name))

    y_a = ((x1 + 0.5 * x2 + rng.normal(0, 0.3, n)) > 0).astype(np.float64)
    y_b = ((x1 - 0.5 * x2 + rng.normal(0, 0.3, n)) > 0).astype(np.float64)
    fit("a", y_a)
    fit("b", y_b)


def main() -> int:
    t_start = time.perf_counter()
    import numpy as np

    from transmogrifai_tpu.analysis.retrace import DISPATCHES, MONITOR
    from transmogrifai_tpu.serving.fleet import (
        FleetConfig, FleetService, scoring_signature)
    from transmogrifai_tpu.serving.service import (
        ScoringService, ServingConfig)
    from transmogrifai_tpu.workflow.serialization import load_model

    payload = {"smoke": "roofline"}
    rows = [{"x1": 0.3, "x2": -1.2}, {"x1": -0.5, "x2": 0.8},
            {"x1": 2.0, "x2": 0.1}, {"x1": -1.4, "x2": -0.9}]

    with tempfile.TemporaryDirectory(prefix="roofline-smoke-") as tmp:
        _train_models(tmp)
        dir_a, dir_b = os.path.join(tmp, "a"), os.path.join(tmp, "b")

        # -- 1. one device dispatch per bucket per score call ---------- #
        svc = ScoringService.from_path(dir_a, config=ServingConfig(
            max_batch=8, batch_wait_ms=0.5))
        svc.start()
        dispatches = {}
        for k in (1, 2, 3, 4):  # buckets 1, 2, 4, 4
            svc.score(rows[:k])  # warm the request path
        for k in (1, 2, 4):
            before = DISPATCHES.snapshot()
            svc.score(rows[:k])
            dispatches[k] = sum(DISPATCHES.delta(before).values())
        payload["dispatches_per_call"] = dispatches
        assert all(v == 1 for v in dispatches.values()), \
            f"fused plan must dispatch ONE program per score call: " \
            f"{dispatches}"

        # f32 reference scores for the parity checks below
        f32_probs = np.asarray([r[next(k for k in r if "Logistic" in k)]
                                ["probability_1"]
                                for r in (svc.score(rows).rows())])
        svc.stop()

        # -- 2. quantized parity within the stated wire tolerance ------ #
        model_a = load_model(dir_a)
        qsvc = ScoringService(model=model_a, version_id="q0",
                              config=ServingConfig(max_batch=8,
                                                   batch_wait_ms=0.5,
                                                   quantize="int8"))
        qsvc.start()
        q_probs = np.asarray([r[next(k for k in r if "Logistic" in k)]
                              ["probability_1"]
                              for r in (qsvc.score(rows).rows())])
        qsvc.stop()
        # linear-path error bound: |Δlogit| <= sum_d |W_d|·scale_d/2
        # with scale_d = (hi_d − lo_d)/255 over this batch's own range,
        # plus the bf16 weight-table rounding (2^-8 relative);
        # sigmoid is 1-Lipschitz·1/4 so the prob tolerance follows
        pred_stage = [s for s in model_a.fitted.values()
                      if type(s).__name__ == "LogisticRegressionModel"][0]
        W = np.abs(np.asarray(pred_stage.W)).sum()
        X = np.asarray([[r["x1"], r["x2"]] for r in rows], np.float32)
        span = (X.max(0) - X.min(0)).max()
        tol_logit = float(W * (span / 255.0) / 2.0 + W * 2.0 ** -8 * 4.0)
        tol_prob = max(0.25 * tol_logit, 1e-4)
        q_err = float(np.abs(q_probs - f32_probs).max())
        payload["quant_prob_err"] = round(q_err, 6)
        payload["quant_prob_tol"] = round(tol_prob, 6)
        assert q_err <= tol_prob, \
            f"int8 parity {q_err} exceeds stated tolerance {tol_prob}"

        # quantized and f32 builds must NEVER share programs
        assert scoring_signature(model_a) != \
            scoring_signature(model_a, quant="int8"), \
            "quant config must fold into the compile-group key"

        # -- 3. two same-shaped linear tenants share ONE program ------- #
        solo = ScoringService.from_path(dir_b, config=ServingConfig(
            max_batch=8, batch_wait_ms=0.5))
        solo.start()
        solo_rows = solo.score(rows).rows()
        solo.stop()

        fleet = FleetService(FleetConfig(
            models={"a": dir_a},
            serving={"max_batch": 8, "batch_wait_ms": 0.5}))
        before = MONITOR.snapshot()
        fleet.add_model("b", dir_b)
        new_traces = MONITOR.delta(before)
        shared = fleet.pool.report()
        payload["shared_signatures"] = len(shared)
        payload["second_tenant_traces"] = sum(new_traces.values())
        assert len(shared) == 1 and \
            sorted(len(e["members"]) for e in shared.values()) == [2], \
            f"same-shaped linear tenants must share one program set: " \
            f"{shared}"
        assert not new_traces, \
            f"second linear tenant must warm with ZERO traces: {new_traces}"
        fleet.start()
        fleet_rows = fleet.score("b", rows).rows()
        fleet.stop()
        for s_row, f_row in zip(solo_rows, fleet_rows):
            for key in s_row:
                sv, fv = s_row[key], f_row[key]
                if isinstance(sv, dict):
                    for kk in sv:
                        assert sv[kk] == fv[kk], \
                            f"adopted tenant must score bit-identically " \
                            f"({key}.{kk}: {sv[kk]} != {fv[kk]})"

        # -- 4. scoring_hbm_frac present and nonzero ------------------- #
        import bench
        from transmogrifai_tpu.data.dataset import Dataset
        import transmogrifai_tpu.types as t
        big = Dataset({"x1": np.random.default_rng(1).normal(size=4096),
                       "x2": np.random.default_rng(2).normal(size=4096)},
                      {"x1": t.Real, "x2": t.Real})
        roof = bench.score_roofline(load_model(dir_a), big)
        payload["scoring_hbm_frac"] = roof.get("scoring_hbm_frac")
        payload["scoring_bytes_per_sec"] = roof.get("scoring_bytes_per_sec")
        assert payload["scoring_hbm_frac"] and \
            payload["scoring_hbm_frac"] > 0, \
            f"scoring_hbm_frac must be present and nonzero: {roof}"

    payload["wall_s"] = round(time.perf_counter() - t_start, 2)
    print(json.dumps(payload))
    print("ROOFLINE SMOKE OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
