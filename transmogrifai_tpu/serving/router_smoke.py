"""Fleet-router smoke: two replicas over ONE shared store, end to end.

`make router-smoke` runs this module. Under a minute on CPU it must
prove the acceptance surface of the shared state plane + router tier
(`store/` + `serving/frontend.py`):

1. replica-1 boots COLD over a fresh store/compile-cache and publishes
   its warmup manifest into the artifact store; a second service over
   the same local artifacts measures the WARM restart;
2. replica-2 boots from a model directory that has NO local warmup
   sidecar — its cold start is ARTIFACT REPLAY (store-keyed manifest by
   model fingerprint + shared persistent compile cache) and its
   cold-start-to-first-score lands within 1.5x the warm replica;
3. with `shared_quota` both replicas meter the same CAS-guarded
   fleet-wide balance: after one replica drains a tenant's burst, the
   over-quota tenant gets its 429 from EITHER replica (and over the
   frontend);
4. under concurrent mixed-wire load through the frontend HTTP server,
   binary-framed requests score BIT-IDENTICALLY to the JSON columnar
   wire;
5. split overload across replicas: each replica's burn stays under the
   multi-window threshold locally (errors never sit in both of its
   windows at once), but the fleet-folded burn crosses in BOTH windows
   — the fleet alert fires, exactly once, via the CAS latch.

Run: ``JAX_PLATFORMS=cpu python -m transmogrifai_tpu.serving.router_smoke``
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

COLS = {f"x{j}": [0.3 * j, -0.5, 2.0 - j, 0.25] for j in range(6)}


def _train_model(path: str) -> None:
    import numpy as np

    import transmogrifai_tpu.types as t
    from transmogrifai_tpu.data import Dataset
    from transmogrifai_tpu.features import FeatureBuilder
    from transmogrifai_tpu.models import OpLogisticRegression
    from transmogrifai_tpu.ops.numeric import RealVectorizer
    from transmogrifai_tpu.workflow import Workflow

    rng = np.random.default_rng(13)
    n = 160
    feats = {f"x{j}": rng.normal(size=n) for j in range(6)}
    x = np.column_stack(list(feats.values()))
    y = ((x @ rng.normal(size=6)) > 0).astype(np.float64)
    ds = Dataset({**feats, "y": y},
                 {**{k: t.Real for k in feats}, "y": t.Integral})
    preds, label = FeatureBuilder.from_dataset(ds, response="y")
    vec = RealVectorizer(track_nulls=False).set_input(*preds).get_output()
    pred = OpLogisticRegression(max_iter=40).set_input(
        label, vec).get_output()
    Workflow().set_result_features(pred, label) \
        .set_input_dataset(ds).train().save(path)


def _post(url: str, data: bytes, content_type: str) -> dict:
    req = urllib.request.Request(
        url, data=data, headers={"Content-Type": content_type},
        method="POST")
    with urllib.request.urlopen(req, timeout=30) as resp:
        return json.loads(resp.read())


def main() -> int:  # noqa: C901 (one linear acceptance script)
    os.environ.setdefault("TRANSMOGRIFAI_PERF_MODEL", "0")
    from transmogrifai_tpu.serving.binwire import (
        CONTENT_TYPE, encode_frame)
    from transmogrifai_tpu.serving.fleet import FleetConfig, FleetService
    from transmogrifai_tpu.serving.frontend import (
        Frontend, serve_frontend)
    from transmogrifai_tpu.workflow.serialization import (
        WARMUP, load_warmup_manifest)

    with tempfile.TemporaryDirectory(prefix="router-smoke-") as tmp:
        store_dir = f"{tmp}/store"
        # the ONE resolution point: consumers (warmup publish, caches)
        # follow the store root without their own env knobs
        os.environ["TRANSMOGRIFAI_STORE_DIR"] = store_dir
        os.environ.setdefault("TRANSMOGRIFAI_PERF_CORPUS_DIR",
                              f"{tmp}/perf-corpus")
        _train_model(f"{tmp}/model-a")

        def config(name: str, model_dir: str) -> FleetConfig:
            return FleetConfig(
                models={"m": model_dir},
                tenants={"gold": {"rate": 1e6, "priority": 1},
                         "meter": {"rate": 0.001, "burst": 30,
                                   "priority": 0}},
                serving={"max_batch": 8, "batch_wait_ms": 1.0,
                         "max_queue": 256},
                compile_cache=True, compile_cache_dir=f"{tmp}/xla-cache",
                store_dir=store_dir, replica=name, shared_quota=True)

        def first_score_s(name: str, model_dir: str):
            t0 = time.perf_counter()
            fleet = FleetService(config(name, model_dir))
            fleet.start()
            fleet.score_columns("m", {k: list(v) for k, v in COLS.items()},
                                tenant="gold")
            return time.perf_counter() - t0, fleet

        # -- 1: cold boot populates the shared artifacts ---------------- #
        cold_s, boot = first_score_s("r0", f"{tmp}/model-a")
        boot.stop()
        assert os.path.exists(f"{tmp}/model-a/{WARMUP}"), \
            "cold warmup never wrote its local manifest"
        warm_s, r1 = first_score_s("r1", f"{tmp}/model-a")

        # -- 2: replica-2 cold start == artifact replay ----------------- #
        # same model, different host checkout: NO local warmup sidecar,
        # so the manifest must come back out of the shared store (keyed
        # by model fingerprint) and the XLA programs out of the shared
        # persistent compile cache
        shutil.copytree(f"{tmp}/model-a", f"{tmp}/model-b")
        os.remove(f"{tmp}/model-b/{WARMUP}")
        assert load_warmup_manifest(f"{tmp}/model-b"), \
            "store-backed warmup manifest fallback found nothing"
        r2_s, r2 = first_score_s("r2", f"{tmp}/model-b")
        try:
            ratio = r2_s / max(warm_s, 1e-9)
            # the acceptance bar (+0.25s absorbing scheduler noise on a
            # sub-second measurement)
            assert r2_s <= 1.5 * warm_s + 0.25, \
                (f"replica-2 cold start {r2_s:.2f}s vs warm replica "
                 f"{warm_s:.2f}s ({ratio:.2f}x > 1.5x): artifact replay "
                 f"did not carry")
            assert r2_s < cold_s, (r2_s, cold_s)

            # -- 3: over-quota tenant 429s from EITHER replica ---------- #
            meter_cols = {k: list(v) for k, v in COLS.items()}
            admitted = 0
            denied = {"r1": 0, "r2": 0}
            for _ in range(30):  # 4-row requests drain the 30-row burst
                try:
                    r1.score_columns("m", meter_cols, tenant="meter")
                    admitted += 4
                except Exception:
                    denied["r1"] += 1
                    break
            assert admitted <= 32, \
                f"replica-1 alone admitted {admitted} rows past burst=30"
            for name, rep in (("r2", r2), ("r1", r1)):
                try:
                    rep.score_columns("m", meter_cols, tenant="meter")
                    raise AssertionError(
                        f"replica {name} admitted an over-quota tenant "
                        "(shared balance not consulted)")
                except Exception as e:
                    code = getattr(e, "code", None)
                    assert code == "quota_exceeded", (name, e)
                    denied[name] += 1
            assert denied["r1"] >= 1 and denied["r2"] >= 1, denied

            # -- 4: frontend — 429 over HTTP + wire bit-parity ---------- #
            fe = Frontend({"r1": r1, "r2": r2})
            server, _ = serve_frontend(fe, port=0, block=False)
            base = f"http://127.0.0.1:{server.port}"
            try:
                body = json.dumps({"model": "m", "columns": meter_cols,
                                   "tenant": "meter"}).encode()
                try:
                    _post(f"{base}/score", body, "application/json")
                    raise AssertionError(
                        "frontend admitted the over-quota tenant")
                except urllib.error.HTTPError as e:
                    assert e.code == 429, e.code

                frame = encode_frame(meter_cols, model="m",
                                     tenant="gold")
                jbody = json.dumps({"model": "m", "columns": meter_cols,
                                    "tenant": "gold"}).encode()
                results = {"json": [], "binary": []}
                errors = []
                lock = threading.Lock()

                def client(wire: str, n: int) -> None:
                    for _ in range(n):
                        try:
                            if wire == "binary":
                                out = _post(f"{base}/score", frame,
                                            CONTENT_TYPE)
                            else:
                                out = _post(f"{base}/score", jbody,
                                            "application/json")
                            with lock:
                                results[wire].append(out["scores"])
                        except Exception as e:
                            with lock:
                                errors.append(f"{wire}: {e}")

                threads = [threading.Thread(
                    target=client, args=(wire, 10),
                    name=f"router-smoke-{wire}-{i}")
                    for i in range(2) for wire in ("json", "binary")]
                for th in threads:
                    th.start()
                for th in threads:
                    th.join()
                assert not errors, errors[:3]
                assert len(results["json"]) == 20 and \
                    len(results["binary"]) == 20, {
                        k: len(v) for k, v in results.items()}
                ref = results["json"][0]
                for wire, outs in results.items():
                    for out in outs:
                        assert out == ref, \
                            (f"{wire} wire diverged from the JSON "
                             f"reference under concurrent load")
                health = json.loads(urllib.request.urlopen(
                    f"{base}/healthz", timeout=30).read())
                assert health["status"] == "ok", health
            finally:
                server.shutdown()
                server.server_close()
        finally:
            r1.stop()
            r2.stop()

        # -- 5: split overload — fleet burn fires ONCE, locals never -- #
        # rA's errors all land early: while they sit in its short
        # window its long window is diluted by warm-up traffic, and by
        # the time the long window slides past the warm-up the short
        # window has drained — never both at once. rB errors late and
        # little: its short window spikes but its long window stays
        # diluted. The fleet fold SUMS the counters: once the clean
        # warm-up slides out of the long window, fleet burn crosses the
        # threshold in BOTH windows and exactly one replica's engine
        # wins the CAS latch.
        from transmogrifai_tpu.obs.federate import FleetAlertLatch
        from transmogrifai_tpu.obs.slo import SLOEngine, SLOParams

        slo_store = f"{tmp}/slo-store"
        params = SLOParams.from_json({
            "slos": [{"name": "fleet-avail", "kind": "availability",
                      "objective": 0.9}],
            "windows": [[8.0, 2.0, 2.0, "page"]],
            "eval_period_s": 0.25})
        counters = {"rA": [0.0, 0.0], "rB": [0.0, 0.0]}  # [good, total]

        def source(nm: str):
            return lambda: tuple(counters[nm])

        engines = {}
        for nm in ("rA", "rB"):
            eng = SLOEngine(params)
            eng.set_source("fleet-avail", source(nm))
            eng.attach_fleet(slo_store, nm, name="router-split")
            engines[nm] = eng

        def add(nm: str, good: int, bad: int) -> None:
            counters[nm][0] += good
            counters[nm][1] += good + bad

        local_fired = []
        dt = 0.25
        for k in range(1, 43):  # t = 0.25 .. 10.5
            t = k * dt
            if t <= 2.0:        # warm-up: both clean
                add("rA", 25, 0)
                add("rB", 25, 0)
            elif t <= 4.0:      # rA's overload burst
                add("rA", 0, 5)
                add("rB", 10, 0)
            elif t <= 8.0:      # quiet middle: rA trickles, rB serves
                add("rA", 1, 0)
                add("rB", 10, 0)
            elif t <= 10.0:     # rB's (small) overload burst
                add("rA", 1, 0)
                add("rB", 0, 5)
            # else: two settle ticks, no traffic, so BOTH engines see
            # the final counters after the other's last publish
            for nm, eng in engines.items():
                st = eng.evaluate(now=t)["slos"]["fleet-avail"]
                if st["state"] == "firing":
                    local_fired.append((nm, t))

        assert not local_fired, \
            (f"local burn crossed the multi-window threshold on "
             f"{local_fired[:4]} — the split overload should only be "
             f"visible fleet-wide")
        for nm, eng in engines.items():
            st = eng.evaluate(now=10.5)["slos"]["fleet-avail"]
            assert st["alerts"] == 0, (nm, st["alerts"])
            fleet_view = st.get("fleet") or {}
            assert fleet_view.get("state") == "firing", (nm, fleet_view)
            assert fleet_view.get("replicas") == 2, (nm, fleet_view)
        latch_counts = FleetAlertLatch(
            slo_store, name="router-split").counts()
        row = latch_counts.get("fleet-avail") or {}
        assert row.get("state") == "firing" and row.get("fired") == 1, \
            (f"fleet alert must fire exactly once across both "
             f"replicas: {latch_counts}")

    print(f"router-smoke OK: replica-2 artifact replay "
          f"{r2_s:.2f}s vs warm {warm_s:.2f}s ({ratio:.2f}x, bar 1.5x; "
          f"cold was {cold_s:.2f}s); over-quota tenant denied by BOTH "
          f"replicas ({denied}) and 429'd by the frontend; 40 "
          f"concurrent mixed-wire requests bit-identical across "
          f"binary/JSON; split overload fired the FLEET alert exactly "
          f"once (owner={row.get('owner')}) while both local engines "
          f"stayed quiet")
    return 0


if __name__ == "__main__":
    sys.exit(main())
