"""Admission + routing for fleet serving: per-tenant quotas, priorities.

The single-model serving layer already degrades gracefully under load
(bounded queue, deadlines — `serving/batcher.py`); what a MULTI-tenant
process needs on top is fairness and isolation, decided at admission,
the cheapest point:

- **token-bucket quotas** per tenant, metered in ROWS per second (a
  64-row batch spends 64 tokens — requests are not equal work), with a
  configurable burst so bursty-but-within-rate tenants are not
  penalized. An over-quota tenant is shed with a structured
  ``quota_exceeded`` error (HTTP 429) while every other tenant's
  traffic is untouched;
- **priority classes**: under queue pressure on the TARGET model, the
  lowest-priority classes are shed first (``shed_low_priority``, also
  429) and the highest-priority class is never priority-shed — it still
  ends at the bounded queue's own ``queue_full`` backstop. Pressure is
  graded: as the queue fills past ``shed_watermark`` toward capacity,
  progressively higher classes are shed, top class excepted;
- **per-tenant metrics**: labeled ``fleet_*`` series (requests, rows,
  sheds by reason, latency histogram) on the fleet registry, plus a
  plain-dict ``snapshot()``/``delta()`` used by the rolling-swap
  goodput accounting (`FleetService.reload_model`).
"""

from __future__ import annotations

import logging
import math
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

from transmogrifai_tpu.obs.metrics import MetricsRegistry
from transmogrifai_tpu.serving.batcher import ScoreError

log = logging.getLogger(__name__)

__all__ = ["TenantPolicy", "TokenBucket", "Router"]

DEFAULT_TENANT = "default"


@dataclass
class TenantPolicy:
    """One tenant's admission contract: sustained rate (rows/second;
    inf = unmetered), burst capacity (rows; defaults to 2s of rate),
    and priority class (higher survives pressure longer)."""

    rate: float = math.inf
    burst: Optional[float] = None
    priority: int = 0

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "TenantPolicy":
        return TenantPolicy(
            rate=float(d.get("rate", math.inf)),
            burst=(float(d["burst"]) if d.get("burst") is not None
                   else None),
            priority=int(d.get("priority", 0)))

    def effective_burst(self) -> float:
        if self.burst is not None:
            return max(1.0, self.burst)
        if math.isinf(self.rate):
            return math.inf
        return max(1.0, 2.0 * self.rate)


class TokenBucket:
    """Classic token bucket over a monotonic clock; thread-safe."""

    def __init__(self, rate: float, burst: float):
        self.rate = float(rate)
        self.burst = float(burst)
        self._tokens = self.burst
        self._last = time.monotonic()
        self._lock = threading.Lock()

    def try_take(self, n: float) -> bool:
        if math.isinf(self.rate):
            return True
        with self._lock:
            now = time.monotonic()
            self._tokens = min(self.burst,
                               self._tokens + (now - self._last) * self.rate)
            self._last = now
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False

    def refill_eta_s(self, n: float) -> float:
        """Seconds until `n` tokens would accumulate at the sustained
        rate — the ``Retry-After`` for a shed request (0 when unmetered
        or already affordable). For n > burst this is optimistic (the
        bucket can never hold n; the client should split the request),
        but still a sane backoff rather than 0. Capped at an hour: a
        zero-rate (blocked) tenant or a huge deficit must yield a
        finite, JSON-safe hint, never inf (which would overflow the
        HTTP Retry-After integer)."""
        if math.isinf(self.rate):
            return 0.0
        with self._lock:
            now = time.monotonic()
            tokens = min(self.burst,
                         self._tokens + (now - self._last) * self.rate)
            need = float(n) - tokens
            eta = need / self.rate if self.rate > 0 else math.inf
            return max(0.0, min(eta, 3600.0))


class _TenantState:
    __slots__ = ("policy", "bucket", "requests", "rows", "shed", "errors")

    def __init__(self, policy: TenantPolicy):
        self.policy = policy
        self.bucket = TokenBucket(policy.rate, policy.effective_burst())
        self.requests = 0
        self.rows = 0
        self.shed = 0
        self.errors = 0


class Router:
    """Tenant admission + accounting. See module docstring."""

    def __init__(self, tenants: Optional[Dict[str, TenantPolicy]] = None,
                 default: Optional[TenantPolicy] = None,
                 shed_watermark: float = 0.5,
                 registry: Optional[MetricsRegistry] = None,
                 max_tenants: int = 1024,
                 shared=None):
        if not (0.0 < shed_watermark <= 1.0):
            raise ValueError(
                f"shed_watermark must be in (0, 1]: {shed_watermark}")
        self.registry = registry or MetricsRegistry()
        self.shed_watermark = float(shed_watermark)
        # when a store-backed SharedQuota is attached, metered tenants
        # spend against the FLEET-WIDE balance (local lease, CAS-synced
        # cell) instead of this replica's private bucket — the invariant
        # that K replicas together stay within one tenant's rate
        self.shared = shared
        # unknown tenant names come straight off the wire (X-Tenant):
        # cap how many may mint per-tenant state + labeled metric series,
        # or a client cycling random names grows memory and Prometheus
        # label cardinality without bound; past the cap they share the
        # DEFAULT_TENANT bucket
        self.max_tenants = int(max_tenants)
        self._lock = threading.Lock()
        self._tenants: Dict[str, _TenantState] = {
            name: _TenantState(p) for name, p in (tenants or {}).items()}
        # predictive admission pressure per model (autopilot-written):
        # a synthetic queue fraction merged with the OBSERVED fraction
        # at admit time, so predicted saturation sheds low classes
        # through the exact same graded ladder before the bounded
        # queue ever backs up. Empty map = observed-only (bit-identical
        # to the pre-autopilot behavior).
        self._pressure: Dict[str, float] = {}  # guarded-by: self._lock
        # anonymous/unknown tenants: unmetered but LOWEST priority by
        # default, so configured tenants outrank them under pressure
        self._default = default or TenantPolicy(
            rate=math.inf, priority=min(
                [s.policy.priority for s in self._tenants.values()] + [0]))
        # priority ladder for graded shedding (top class is exempt)
        self._levels = sorted({s.policy.priority
                               for s in self._tenants.values()}
                              | {self._default.priority})

    # -- admission --------------------------------------------------------- #

    def _state(self, tenant: Optional[str]) -> Tuple[str, "_TenantState"]:
        name = tenant or DEFAULT_TENANT
        with self._lock:
            state = self._tenants.get(name)
            if state is None:
                if len(self._tenants) >= self.max_tenants:
                    # cardinality cap: overflow tenants share the default
                    # bucket (state AND metric labels) instead of minting
                    # fresh series per wire-supplied name
                    name = DEFAULT_TENANT
                    state = self._tenants.get(DEFAULT_TENANT)
                    if state is None:
                        state = _TenantState(self._default)
                        self._tenants[DEFAULT_TENANT] = state
                else:
                    state = _TenantState(self._default)
                    self._tenants[name] = state
        return name, state

    def _shed_floor(self, queue_frac: float) -> Optional[int]:
        """Minimum priority admitted at this queue pressure, or None
        when below the watermark. Pressure grades linearly from the
        watermark to full: just past the watermark only the lowest
        class sheds; approaching capacity everything below the TOP
        class sheds (the top class is left to the bounded queue's own
        queue_full backstop — priorities order tenants, they never
        starve the whole process)."""
        if len(self._levels) < 2 or queue_frac < self.shed_watermark:
            return None
        span = max(1e-9, 1.0 - self.shed_watermark)
        frac = min(1.0, (queue_frac - self.shed_watermark) / span)
        k = min(len(self._levels) - 1,
                1 + int(frac * (len(self._levels) - 1)))
        return self._levels[k]

    def set_pressure(self, model: str, frac: float) -> None:
        """Write the predictive admission pressure for `model` (0 or
        negative clears it). Autopilot-owned: every write must be
        paired with a flight-recorder actuation event naming the burn
        window + prediction that justified it (lint L022)."""
        with self._lock:
            if frac <= 0.0:
                self._pressure.pop(model, None)
            else:
                self._pressure[model] = min(1.0, float(frac))

    def pressure(self, model: str = "") -> float:
        """Current predictive pressure for `model` (0.0 when none)."""
        with self._lock:
            return self._pressure.get(model, 0.0)

    def admit(self, tenant: Optional[str], n_rows: int,
              queue_frac: float, model: str = "",
              drain_s: Optional[float] = None) -> str:
        """Admission gate: returns the resolved tenant name or raises a
        structured ScoreError (quota_exceeded / shed_low_priority).
        `queue_frac` is the observed queue fill; any predictive
        pressure set for `model` merges in as max(). `drain_s` (the
        perf model's predicted queue-drain seconds, when warm) turns
        the shed backoff hint proportional instead of constant."""
        name, state = self._state(tenant)
        pressure = self.pressure(model)
        eff_frac = max(queue_frac, pressure)
        floor = self._shed_floor(eff_frac)
        if floor is not None and state.policy.priority < floor:
            self._shed(name, state, model,
                       "shed_predictive" if pressure > queue_frac
                       else "shed_low_priority")
            # backoff hint: predicted drain time when the model is
            # warm; otherwise scaled by how deep past the watermark
            # the queue is (pressure at the watermark suggests a short
            # retry, pressure at capacity a full second)
            if drain_s is not None:
                hint = round(max(0.1, min(30.0, float(drain_s))), 3)
            else:
                hint = round(max(0.1, min(1.0, eff_frac)), 3)
            raise ScoreError(
                "shed_low_priority",
                f"tenant {name!r} (priority {state.policy.priority}) shed "
                f"under queue pressure ({eff_frac:.0%} of capacity"
                + (", predicted" if pressure > queue_frac else "")
                + "); retry with backoff",
                retry_after_s=hint)
        n_take = max(1, int(n_rows))
        if self.shared is not None and not math.isinf(state.policy.rate):
            if not self.shared.try_spend(name, n_take, state.policy.rate,
                                         state.policy.effective_burst()):
                self._shed(name, state, model, "quota_exceeded")
                raise ScoreError(
                    "quota_exceeded",
                    f"tenant {name!r} over its fleet-wide row quota "
                    f"({state.policy.rate:g} rows/s across all "
                    "replicas); retry after backoff",
                    retry_after_s=round(self.shared.refill_eta_s(
                        name, n_take, state.policy.rate), 3))
        elif not state.bucket.try_take(n_take):
            self._shed(name, state, model, "quota_exceeded")
            raise ScoreError(
                "quota_exceeded",
                f"tenant {name!r} over its row quota "
                f"({state.policy.rate:g} rows/s, burst "
                f"{state.bucket.burst:g}); retry after backoff",
                retry_after_s=round(state.bucket.refill_eta_s(n_take), 3))
        return name

    def _shed(self, name: str, state: "_TenantState", model: str,
              reason: str) -> None:
        with self._lock:
            state.shed += 1
        self.registry.counter(
            "fleet_shed_total", "requests shed at fleet admission",
            tenant=name, reason=reason).inc()
        try:
            from transmogrifai_tpu.obs.export import record_event
            record_event("tenant_shed", tenant=name, model=model,
                         reason=reason)
        except Exception:
            log.debug("tenant_shed event emission failed", exc_info=True)

    # -- accounting -------------------------------------------------------- #

    def note_success(self, tenant: str, model: str, n_rows: int,
                     latency_s: float) -> None:
        _, state = self._state(tenant)
        with self._lock:
            state.requests += 1
            state.rows += int(n_rows)
        self.registry.counter(
            "fleet_requests_total", "requests served per tenant/model",
            tenant=tenant, model=model).inc()
        self.registry.counter(
            "fleet_rows_total", "rows scored per tenant",
            tenant=tenant).inc(int(n_rows))
        self.registry.histogram(
            "fleet_request_latency_seconds",
            "fleet routing + scoring latency per tenant",
            tenant=tenant).observe(latency_s)

    def note_error(self, tenant: str, model: str, code: str) -> None:
        _, state = self._state(tenant)
        with self._lock:
            state.errors += 1
        self.registry.counter(
            "fleet_errors_total", "scoring errors per tenant",
            tenant=tenant, code=code).inc()

    # -- snapshots (rolling-swap goodput) ----------------------------------- #

    def snapshot(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            return {name: {"requests": s.requests, "rows": s.rows,
                           "shed": s.shed, "errors": s.errors,
                           "priority": s.policy.priority}
                    for name, s in self._tenants.items()}

    def delta(self, before: Dict[str, Dict[str, int]]
              ) -> Dict[str, Dict[str, int]]:
        """Per-tenant traffic since `before` (a `snapshot()`); tenants
        with no movement are omitted."""
        now = self.snapshot()
        out: Dict[str, Dict[str, int]] = {}
        for name, cur in now.items():
            prev = before.get(name, {})
            d = {k: cur[k] - prev.get(k, 0)
                 for k in ("requests", "rows", "shed", "errors")}
            if any(d.values()):
                out[name] = d
        return out
