"""Stdlib-HTTP frontend for the scoring service.

No web framework (the container constraint is also the right call for a
latency path): ``http.server.ThreadingHTTPServer`` + JSON bodies. Each
connection gets an OS thread that does only cheap work — row parsing and
blocking on the request future; all device work stays on the service's
single scoring thread.

Endpoints:

- ``POST /score``   ``{"rows": [{...}], "deadline_ms": 500}`` →
  ``{"scores": [...], "model_version": "...", "latency_ms": ...}``;
  a single ``{"row": {...}}`` is accepted as shorthand. Structured
  errors map to status codes: 429 queue_full, 504 deadline_exceeded,
  400 bad_request, 422 record_error, 503 shutdown/circuit_open/
  watchdog_restart, 500 internal. Every 429/503 carries a
  ``Retry-After`` header derived from the token-bucket refill or
  breaker half-open deadline, so well-behaved clients back off
  instead of hammering a tripped member.

  A W3C ``traceparent`` request header joins the caller's distributed
  trace (sampled=01 contexts are force-kept past tail sampling); with
  no header a fresh trace id is minted. Either way the response echoes
  ``traceparent`` (+ ``X-Trace-Id``) naming the request's own root
  span, so "this exact slow response" is greppable in the exported
  trace and in the histogram exemplars.
- ``GET /healthz``  liveness + active version + queue depth + the
  member's resilience health state; a quarantined/down service answers
  503 with ``Retry-After``.
- ``GET /metrics``  Prometheus text (default) or JSON with
  ``?format=json``.
- ``GET /slo``      the SLO engine's burn-rate/alert status (404 when
  no SLOs are configured).
- ``POST /reload``  ``{"model_location": "dir"}`` hot-swap, or
  ``{"rollback": true}`` to restore the previous version.
- ``POST /debug/dump``  on-demand crash-flight-recorder dump; returns
  the committed artifact path.

The FLEET frontend (``serve_fleet`` / `_FleetHandler`) serves the
multi-model process (`serving/fleet.py`): ``/score`` takes a ``model``
name and optional ``tenant`` (body field or ``X-Tenant`` header),
``/reload`` swaps one named member while the others keep serving, and
``/healthz`` adds per-model versions, tenant counters, and the
shared-bucket-program report.
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple

from transmogrifai_tpu.obs.trace import TraceContext
from transmogrifai_tpu.serving.batcher import ScoreError
from transmogrifai_tpu.serving.service import ScoringService

log = logging.getLogger(__name__)

_ERROR_STATUS = {
    "queue_full": 429,
    "quota_exceeded": 429,
    "shed_low_priority": 429,
    "deadline_exceeded": 504,
    "bad_request": 400,
    "not_found": 404,
    "record_error": 422,
    "shutdown": 503,
    "circuit_open": 503,
    "watchdog_restart": 503,
    "internal": 500,
}


def _retry_after_header(retry_after_s: Optional[float],
                        default_s: float = 1.0) -> str:
    """HTTP Retry-After delta-seconds: at least 1 (a 0 would tell
    clients to hammer right back — the opposite of the point), at most
    an hour (a non-finite or runaway hint must never overflow the
    integer header or tell clients to go away for a day)."""
    import math
    v = default_s if retry_after_s is None else float(retry_after_s)
    if not math.isfinite(v):
        v = 3600.0
    return str(max(1, int(math.ceil(min(v, 3600.0)))))


def metrics_text(service: ScoringService) -> str:
    """Prometheus exposition for `/metrics`: the service's own registry
    PLUS the process-global `obs.metrics` registry, so train/ingest/
    runtime counters registered anywhere in the process land on the
    same scrape surface as the serving series. Family names are
    namespaced by convention (serving_* vs ingest_*/train_*/runtime_*),
    so the concatenation stays collision-free."""
    from transmogrifai_tpu.obs.metrics import get_registry
    return service.registry.to_prometheus() + get_registry().to_prometheus()


def metrics_json(service: ScoringService) -> Dict[str, Any]:
    """JSON form of `/metrics?format=json`: process-global families
    merged under the service's (the service wins a name collision — its
    series are the ones this endpoint has always reported)."""
    from transmogrifai_tpu.obs.metrics import get_registry
    return {**get_registry().to_json(), **service.registry.to_json()}


class ServingHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer carrying the ScoringService reference."""

    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, address: Tuple[str, int], service: ScoringService):
        super().__init__(address, _Handler)
        self.service = service

    @property
    def port(self) -> int:
        return self.server_address[1]


class _JSONHandler(BaseHTTPRequestHandler):
    """Shared JSON plumbing for the single-model and fleet handlers."""

    protocol_version = "HTTP/1.1"

    def log_message(self, fmt: str, *args: Any) -> None:
        log.debug("http: " + fmt, *args)

    def _send(self, status: int, body: bytes,
              content_type: str = "application/json",
              headers: Optional[Dict[str, str]] = None) -> None:
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        for name, value in (headers or {}).items():
            self.send_header(name, value)
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, status: int, payload: Dict[str, Any],
                   headers: Optional[Dict[str, str]] = None) -> None:
        self._send(status, json.dumps(payload, default=_jsonable).encode(),
                   headers=headers)

    def _send_error(self, e: ScoreError) -> None:
        """Structured-error response. 429/503 answers carry a
        ``Retry-After`` header (delta-seconds, ceil'd so a sub-second
        hint still tells a well-behaved client to wait ~1s) derived
        from the token-bucket refill or breaker half-open deadline.
        Errors that left a kept trace behind (tail sampling always
        keeps them) echo its ``traceparent``/``X-Trace-Id`` too — a
        failed request must be as correlatable as a slow one."""
        status = _ERROR_STATUS.get(e.code, 500)
        headers: Dict[str, str] = {}
        if status in (429, 503):
            headers["Retry-After"] = _retry_after_header(
                getattr(e, "retry_after_s", None))
        if getattr(e, "traceparent", None):
            headers["traceparent"] = e.traceparent
            headers["X-Trace-Id"] = e.trace_id
        self._send_json(status, e.to_json(), headers=headers or None)

    def _send_health(self, health: Dict[str, Any]) -> None:
        """/healthz: 200 only when fully healthy; degraded fleets stay
        200 (they serve), quarantined/down members 503 with a
        Retry-After derived from the breaker half-open deadline /
        watchdog cadence."""
        if health["status"] in ("ok", "degraded"):
            self._send_json(200, health)
            return
        self._send_json(503, health, headers={
            "Retry-After": _retry_after_header(
                health.get("retry_after_s"))})

    def _read_json(self) -> Dict[str, Any]:
        length = int(self.headers.get("Content-Length") or 0)
        if length <= 0:
            return {}
        raw = self.rfile.read(length)
        try:
            body = json.loads(raw)
        except json.JSONDecodeError as e:
            raise ScoreError("bad_request", f"invalid JSON body: {e}")
        if not isinstance(body, dict):
            raise ScoreError("bad_request", "body must be a JSON object")
        return body

    def _read_bytes(self, max_bytes: int = 256 << 20) -> bytes:
        """Raw body for the binary columnar wire. Size problems are
        bad_request like any other malformed frame — never a breaker
        signal."""
        length = int(self.headers.get("Content-Length") or 0)
        if length <= 0:
            raise ScoreError("bad_request",
                             "binary frame requires Content-Length")
        if length > max_bytes:
            raise ScoreError("bad_request",
                             f"binary frame too large ({length} bytes)")
        return self.rfile.read(length)

    def _trace_ctx(self) -> Optional[TraceContext]:
        """The caller's W3C trace context, when a valid ``traceparent``
        header came in (malformed headers are ignored per spec, not
        400'd)."""
        return TraceContext.from_traceparent(
            self.headers.get("traceparent"))

    @staticmethod
    def _trace_headers(result) -> Optional[Dict[str, str]]:
        """Response-side trace echo: the request's trace id (as both
        the raw id and a spec-shaped traceparent naming the request's
        root span) — None when tracing is off."""
        tid = getattr(result, "trace_id", None)
        if not tid:
            return None
        return {"traceparent": result.traceparent, "X-Trace-Id": tid}

    def _send_slo(self, engine) -> None:
        if engine is None:
            self._send_json(404, {
                "error": "not_found",
                "message": "no SLOs configured (serving config `slo`)"})
            return
        self._send_json(200, engine.status())

    def _debug_dump(self) -> None:
        from transmogrifai_tpu.obs import flight
        path = flight.request_dump("debug", force=True)
        if path is None:
            self._send_json(500, {"error": "internal",
                                  "message": "flight dump failed"})
            return
        self._send_json(200, {"status": "dumped", "path": path})


class _Handler(_JSONHandler):

    # -- helpers ----------------------------------------------------------- #

    @property
    def service(self) -> ScoringService:
        return self.server.service  # type: ignore[attr-defined]

    # -- routes ------------------------------------------------------------ #

    def do_GET(self) -> None:  # noqa: N802 (stdlib handler casing)
        path, _, query = self.path.partition("?")
        if path == "/healthz":
            self._send_health(self.service.health())
        elif path == "/slo":
            self._send_slo(self.service.slo_engine)
        elif path == "/metrics":
            if "format=json" in query:
                self._send_json(200, metrics_json(self.service))
            else:
                self._send(
                    200, metrics_text(self.service).encode(),
                    content_type="text/plain; version=0.0.4")
        else:
            self._send_json(404, {"error": "not_found",
                                  "message": f"no route {path}"})

    def do_POST(self) -> None:  # noqa: N802
        path = self.path.partition("?")[0]
        try:
            body = self._read_json()
            if path == "/score":
                self._score(body)
            elif path == "/reload":
                self._reload(body)
            elif path == "/debug/dump":
                self._debug_dump()
            else:
                self._send_json(404, {"error": "not_found",
                                      "message": f"no route {path}"})
        except ScoreError as e:
            self._send_error(e)
        except Exception as e:  # keep the server alive on handler bugs
            log.exception("http: unhandled error on %s", path)
            self._send_json(500, {"error": "internal",
                                  "message": f"{type(e).__name__}: {e}"})

    def _score(self, body: Dict[str, Any]) -> None:
        cols = _columnar_payload(body)
        if cols is not None:
            result = self.service.score_columns(
                cols, deadline_ms=body.get("deadline_ms"),
                trace=self._trace_ctx())
        else:
            rows = _row_payload(body)
            result = self.service.score(
                rows, deadline_ms=body.get("deadline_ms"),
                trace=self._trace_ctx())
        self._send_json(200, {
            "scores": result.rows(),
            "model_version": result.model_version,
            "latency_ms": round(result.latency_s * 1000.0, 3),
            "trace_id": result.trace_id,
        }, headers=self._trace_headers(result))

    def _reload(self, body: Dict[str, Any]) -> None:
        if body.get("rollback"):
            self._send_json(200, self.service.rollback())
            return
        loc = body.get("model_location")
        if not loc:
            raise ScoreError(
                "bad_request",
                'expected {"model_location": "dir"} or {"rollback": true}')
        try:
            self._send_json(200, self.service.reload(loc))
        except ScoreError:
            raise
        except Exception as e:
            # a bad reload must leave the ACTIVE version serving
            raise ScoreError("bad_request",
                             f"reload failed, keeping current version: "
                             f"{type(e).__name__}: {e}")


def _row_payload(body: Dict[str, Any]) -> list:
    """The row-wire payload: ``{"rows": [{...}, ...]}`` (or the
    ``{"row": {...}}`` single-row shorthand), validated."""
    rows = body.get("rows")
    if rows is None and "row" in body:
        rows = [body["row"]]
    if not isinstance(rows, list) or not rows or \
            not all(isinstance(r, dict) for r in rows):
        raise ScoreError(
            "bad_request",
            'expected {"rows": [{...}, ...]} or {"columns": {...}}')
    return rows


def _columnar_payload(body: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """The columnar-wire payload when the body carries one:
    ``{"columns": {name: [values...], ...}}`` — callers that already
    hold columns skip the row pivot entirely (the host-data-plane fast
    wire). Returns None when the body is row-shaped; supplying BOTH
    forms is ambiguous and rejected."""
    cols = body.get("columns")
    if cols is None:
        return None
    if "rows" in body or "row" in body:
        raise ScoreError("bad_request",
                         'pass either "rows" or "columns", not both')
    if not isinstance(cols, dict) or not cols or \
            not all(isinstance(v, list) for v in cols.values()):
        raise ScoreError(
            "bad_request",
            'expected {"columns": {name: [values...], ...}} with one '
            'list per column')
    return cols


def _jsonable(v: Any) -> Any:
    import numpy as np
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, np.ndarray):
        return v.tolist()
    return str(v)


# --------------------------------------------------------------------------- #
# Fleet frontend                                                              #
# --------------------------------------------------------------------------- #

def fleet_metrics_text(fleet) -> str:
    """Prometheus exposition for the fleet `/metrics`: the fleet
    registry (tenant/model-LABELED series — one family, many labeled
    series, so N models never collide) plus the process-global
    registry. Per-model un-labeled serving_* registries are exposed as
    JSON under `/metrics?format=json` instead — concatenating N copies
    of the same un-labeled family would be invalid exposition."""
    from transmogrifai_tpu.obs.metrics import get_registry
    return fleet.registry.to_prometheus() + get_registry().to_prometheus()


def fleet_metrics_json(fleet) -> Dict[str, Any]:
    from transmogrifai_tpu.obs.metrics import get_registry
    out = fleet.metrics_json()
    out["process"] = get_registry().to_json()
    return out


class FleetHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer carrying the FleetService reference."""

    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, address: Tuple[str, int], fleet):
        super().__init__(address, _FleetHandler)
        self.fleet = fleet

    @property
    def port(self) -> int:
        return self.server_address[1]


class _FleetHandler(_JSONHandler):
    """Fleet routes:

    - ``POST /score``   ``{"model": "name", "rows": [...],
      "tenant": "acme", "deadline_ms": 500}`` (tenant also accepted via
      the ``X-Tenant`` header; ``{"row": {...}}`` shorthand works).
      Adds 429 ``quota_exceeded`` / ``shed_low_priority`` and 404
      ``not_found`` to the single-model status mapping.
    - ``GET /healthz``  fleet + per-model health, tenant counters, and
      the shared-program report (signature -> members).
    - ``GET /models``   model listing only.
    - ``GET /metrics``  fleet+process Prometheus text; ``?format=json``
      nests per-model registries under their names.
    - ``GET /metrics/fleet``  the FEDERATED view: every replica's
      published `MetricsRegistry` snapshot merged (counters summed,
      histograms bucket-merged, gauges replica-labeled) — 404 without
      a configured store_dir.
    - ``POST /reload``  ``{"model": "name", "model_location": "dir"}``
      rolling swap of ONE member, or ``{"model": ..., "rollback":
      true}``.
    """

    @property
    def fleet(self) -> "FleetService":
        return self.server.fleet  # type: ignore[attr-defined]

    def do_GET(self) -> None:  # noqa: N802
        path, _, query = self.path.partition("?")
        if path == "/healthz":
            self._send_health(self.fleet.health())
        elif path == "/slo":
            self._send_slo(self.fleet.slo_engine)
        elif path == "/models":
            self._send_json(200, {"models": self.fleet.models()})
        elif path == "/metrics/fleet":
            # the federated view: every replica's published snapshot
            # merged (404 without a shared store)
            try:
                self._send_json(200, self.fleet.fleet_metrics_json())
            except ScoreError as e:
                self._send_error(e)
        elif path == "/metrics":
            if "format=json" in query:
                self._send_json(200, fleet_metrics_json(self.fleet))
            else:
                self._send(200, fleet_metrics_text(self.fleet).encode(),
                           content_type="text/plain; version=0.0.4")
        else:
            self._send_json(404, {"error": "not_found",
                                  "message": f"no route {path}"})

    def do_POST(self) -> None:  # noqa: N802
        path = self.path.partition("?")[0]
        try:
            ctype = (self.headers.get("Content-Type") or "")
            ctype = ctype.partition(";")[0].strip().lower()
            from transmogrifai_tpu.serving.binwire import CONTENT_TYPE
            if path == "/score" and ctype == CONTENT_TYPE:
                self._score_frame(self._read_bytes())
                return
            body = self._read_json()
            if path == "/score":
                self._score(body)
            elif path == "/reload":
                self._reload(body)
            elif path == "/debug/dump":
                self._debug_dump()
            else:
                self._send_json(404, {"error": "not_found",
                                      "message": f"no route {path}"})
        except ScoreError as e:
            self._send_error(e)
        except Exception as e:  # keep the server alive on handler bugs
            log.exception("http: unhandled fleet error on %s", path)
            self._send_json(500, {"error": "internal",
                                  "message": f"{type(e).__name__}: {e}"})

    def _score_frame(self, frame: bytes) -> None:
        """Binary columnar wire: the frame header carries model/tenant/
        deadline, the buffers feed the columnar scoring path with no
        JSON decode. The response stays JSON (scores are tiny; the win
        is on the request side, where the columns live)."""
        from transmogrifai_tpu.serving.binwire import decode_frame
        columns, meta = decode_frame(frame)
        model = meta.get("model")
        if not isinstance(model, str) or not model:
            raise ScoreError("bad_request",
                             "binary frame: missing model name")
        tenant = meta.get("tenant") or self.headers.get("X-Tenant")
        result = self.fleet.score_columns(
            model, columns, tenant=tenant,
            deadline_ms=meta.get("deadline_ms"),
            trace=self._trace_ctx())
        self._send_json(200, {
            "scores": result.rows(),
            "model": model,
            "model_version": result.model_version,
            "latency_ms": round(result.latency_s * 1000.0, 3),
            "trace_id": result.trace_id,
        }, headers=self._trace_headers(result))

    def _score(self, body: Dict[str, Any]) -> None:
        model = body.get("model")
        if not model:
            raise ScoreError("bad_request",
                             'expected {"model": "name", "rows": [...]}')
        tenant = body.get("tenant") or self.headers.get("X-Tenant")
        cols = _columnar_payload(body)
        if cols is not None:
            result = self.fleet.score_columns(
                str(model), cols, tenant=tenant,
                deadline_ms=body.get("deadline_ms"),
                trace=self._trace_ctx())
        else:
            rows = _row_payload(body)
            result = self.fleet.score(str(model), rows, tenant=tenant,
                                      deadline_ms=body.get("deadline_ms"),
                                      trace=self._trace_ctx())
        self._send_json(200, {
            "scores": result.rows(),
            "model": model,
            "model_version": result.model_version,
            "latency_ms": round(result.latency_s * 1000.0, 3),
            "trace_id": result.trace_id,
        }, headers=self._trace_headers(result))

    def _reload(self, body: Dict[str, Any]) -> None:
        model = body.get("model")
        if not model:
            raise ScoreError(
                "bad_request",
                'expected {"model": "name", "model_location": "dir"} '
                'or {"model": "name", "rollback": true}')
        if body.get("rollback"):
            self._send_json(200, self.fleet.rollback_model(str(model)))
            return
        loc = body.get("model_location")
        if not loc:
            raise ScoreError(
                "bad_request",
                'expected {"model_location": "dir"} or {"rollback": true}')
        try:
            self._send_json(200, self.fleet.reload_model(str(model), loc))
        except ScoreError:
            raise
        except Exception as e:
            # a bad reload must leave the resident member serving
            raise ScoreError("bad_request",
                             f"reload failed, keeping current version: "
                             f"{type(e).__name__}: {e}")


def serve_fleet(fleet, host: str = "127.0.0.1", port: int = 0,
                block: bool = True
                ) -> Tuple[FleetHTTPServer, Optional[threading.Thread]]:
    """Boot the fleet HTTP frontend over a (started) FleetService —
    same contract as `serve` (port=0 binds a free port; block=False
    runs on a daemon thread)."""
    server = FleetHTTPServer((host, port), fleet)
    if block:
        try:
            server.serve_forever(poll_interval=0.2)
        finally:
            server.server_close()
        return server, None
    thread = threading.Thread(target=server.serve_forever,
                              kwargs={"poll_interval": 0.2},
                              name="fleet-http", daemon=True)
    thread.start()
    return server, thread


def serve(service: ScoringService, host: str = "127.0.0.1",
          port: int = 0, block: bool = True
          ) -> Tuple[ServingHTTPServer, Optional[threading.Thread]]:
    """Boot the HTTP frontend over a (started) ScoringService.

    ``port=0`` binds an OS-assigned free port (read it back from
    ``server.port``). ``block=False`` runs serve_forever on a daemon
    thread and returns immediately — the smoke test / embedded mode."""
    server = ServingHTTPServer((host, port), service)
    if block:
        try:
            server.serve_forever(poll_interval=0.2)
        finally:
            server.server_close()
        return server, None
    thread = threading.Thread(target=server.serve_forever,
                              kwargs={"poll_interval": 0.2},
                              name="serving-http", daemon=True)
    thread.start()
    return server, thread
